"""Link transports under the r8 `Channel` (ISSUE 11): the plain
socket path, plus a `ShapedTransport` that injects bandwidth, RTT and
jitter so the process-separated parties run over a link with
wide-area realism instead of an infinitely fast loopback — and, since
ISSUE 14, the hostile-network-grade `TcpTransport`: listener + dialer
wrapped in stdlib-`ssl` mutual TLS (per-party certs from
`tools/certs.py`, CA pinning, both-ways name check), carrying
sequence-numbered acked frames that survive a dropped connection
(`drivers/session.ReliableChannel` owns the redial policy; this layer
owns the wire state that makes replay after reconnect exactly-once).

The session layer stays the owner of framing, deadlines and fault
injection; a transport only decides HOW a fully framed byte string
reaches the socket.  `ShapedTransport` models the link on the send
side (both ends shape their own sends, so a bidirectional exchange
pays the shape in both directions):

    delay(frame) = rtt/2 + U(0, jitter) + len(frame)/bandwidth

with the jitter drawn from a SEEDED generator per transport — a
shaped run is replayable, exactly like the fault harness whose clock
(`time.sleep`) it borrows.  The `net_send` fault checkpoint fires per
frame before any pacing, so the whole drop/delay/truncate/corrupt/
hang matrix composes with shaping at the same seam.

`MASTIC_NET_SHAPE` arms it process-wide (every process of a session
parses the lever itself, like `MASTIC_FAULTS`):

    MASTIC_NET_SHAPE="bw=1m:rtt=20ms:jitter=2ms[:seed=N]"

bw is BYTES/second with optional k/m/g multiplier (0 = unlimited);
rtt/jitter accept a trailing "ms" or "s" (plain numbers are seconds).
BASELINE.md's communication-only numbers extend through this into the
measured communication-vs-computation crossover (`bench.py
--parties-wan`; PERF.md §13).
"""

import contextlib
import os
import random
import socket
import ssl
import struct
import time
from dataclasses import dataclass
from typing import Callable, Optional


@dataclass
class LinkShape:
    """One direction of a shaped link (each end applies it to its own
    sends)."""

    bandwidth: float = 0.0   # bytes/second; 0 = unlimited
    rtt: float = 0.0         # full round-trip seconds (rtt/2 a send)
    jitter: float = 0.0      # max extra seconds, uniform, seeded
    seed: int = 0

    def __post_init__(self):
        if self.bandwidth < 0 or self.rtt < 0 or self.jitter < 0:
            raise ValueError("link shape values must be >= 0")


_BW_UNITS = {"k": 1e3, "m": 1e6, "g": 1e9}


def _parse_seconds(val: str, field: str) -> float:
    val = val.strip().lower()
    scale = 1.0
    if val.endswith("ms"):
        (val, scale) = (val[:-2], 1e-3)
    elif val.endswith("s"):
        val = val[:-1]
    try:
        return float(val) * scale
    except ValueError:
        raise ValueError(f"link shape {field} must be seconds or "
                         f"'<n>ms', got {val!r}")


def parse_shape(text: Optional[str]) -> Optional[LinkShape]:
    """Parse a MASTIC_NET_SHAPE spec; None/empty means unshaped.
    Unknown keys are errors — a typo'd shape that silently runs at
    loopback speed would make every WAN number vacuous (the
    parse_faults stance)."""
    if text is None or not text.strip():
        return None
    kwargs: dict = {}
    for chunk in text.split(":"):
        chunk = chunk.strip()
        if not chunk:
            continue
        if "=" not in chunk:
            raise ValueError(f"malformed link-shape field {chunk!r} "
                             f"(want key=value)")
        (key, val) = chunk.split("=", 1)
        key = key.strip()
        val = val.strip().lower()
        if key == "bw":
            scale = 1.0
            if val and val[-1] in _BW_UNITS:
                scale = _BW_UNITS[val[-1]]
                val = val[:-1]
            try:
                kwargs["bandwidth"] = float(val) * scale
            except ValueError:
                raise ValueError(f"link shape bw must be bytes/s "
                                 f"with optional k/m/g, got {val!r}")
        elif key in ("rtt", "jitter"):
            kwargs[key] = _parse_seconds(val, key)
        elif key == "seed":
            kwargs["seed"] = int(val)
        else:
            raise ValueError(f"unknown link-shape key {key!r} (must "
                             f"be bw, rtt, jitter or seed)")
    return LinkShape(**kwargs)


def shape_from_env() -> Optional[LinkShape]:
    return parse_shape(os.environ.get("MASTIC_NET_SHAPE"))


class Transport:
    """The plain path: frames go straight to the socket.  Counts
    bytes so callers (bench, tests) can attribute wire traffic."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.bytes_sent = 0
        self.frames_sent = 0

    def send(self, frame: bytes) -> None:
        self.sock.sendall(frame)
        self.bytes_sent += len(frame)
        self.frames_sent += 1


class ShapedTransport(Transport):
    """Bandwidth/RTT/jitter pacing ahead of every frame, plus the
    `net_send` fault checkpoint — the link-layer twin of the
    checkpoints the party main loops fire between protocol steps."""

    def __init__(self, sock: socket.socket, shape: LinkShape,
                 injector=None):
        super().__init__(sock)
        self.shape = shape
        self.injector = injector
        self._rng = random.Random(shape.seed)
        self.slept_s = 0.0

    def send(self, frame: bytes) -> None:
        if self.injector is not None:
            self.injector.checkpoint("net_send")
        shape = self.shape
        delay = shape.rtt / 2.0
        if shape.jitter > 0:
            delay += self._rng.uniform(0.0, shape.jitter)
        if shape.bandwidth > 0:
            delay += len(frame) / shape.bandwidth
        if delay > 0:
            time.sleep(delay)
            self.slept_s += delay
        super().send(frame)


def for_socket(sock: socket.socket,
               shape: Optional[LinkShape] = None,
               injector=None) -> Optional[Transport]:
    """The transport for a just-built channel socket: None when
    unshaped (the Channel's inline sendall is the plain path — no
    wrapper object per frame on the fast path), a ShapedTransport
    when a shape is armed."""
    if shape is None:
        return None
    return ShapedTransport(sock, shape, injector)


# ---------------------------------------------------------------------
# Mutual TLS (ISSUE 14): per-party certs (tools/certs.py), CA pinning,
# name check on BOTH ends, every refusal reason-coded.
# ---------------------------------------------------------------------

# Reason codes a refused handshake carries in its SessionError detail
# (prefix form "reason: ..."); the negative-path matrix in
# tests/test_net.py asserts each one.
TLS_WRONG_CA = "tls-wrong-ca"
TLS_EXPIRED = "tls-expired-cert"
TLS_NAME_MISMATCH = "tls-hostname-mismatch"
TLS_PLAINTEXT = "tls-plaintext"
TLS_TRUNCATED = "tls-truncated-handshake"
TLS_PEER_REFUSED = "tls-peer-refused"
TLS_FAILED = "tls-handshake-failed"

# OpenSSL X509 verify codes -> reason (ssl.SSLCertVerificationError
# .verify_code; the numeric codes are stable across OpenSSL 1.1/3.x).
_VERIFY_CODE_REASONS = {
    10: TLS_EXPIRED,            # certificate has expired
    62: TLS_NAME_MISMATCH,      # hostname mismatch
    18: TLS_WRONG_CA,           # self-signed certificate
    19: TLS_WRONG_CA,           # self-signed in chain
    20: TLS_WRONG_CA,           # unable to get local issuer cert
    21: TLS_WRONG_CA,           # unable to verify leaf signature
}


def tls_reason(exc: BaseException) -> str:
    """Map a handshake exception to its refusal reason code."""
    if isinstance(exc, ssl.SSLCertVerificationError):
        reason = _VERIFY_CODE_REASONS.get(
            getattr(exc, "verify_code", None))
        if reason is not None:
            return reason
        msg = (getattr(exc, "verify_message", "") or str(exc)).lower()
        if "expired" in msg:
            return TLS_EXPIRED
        if "hostname" in msg:
            return TLS_NAME_MISMATCH
        return TLS_WRONG_CA
    if isinstance(exc, ssl.SSLEOFError):
        return TLS_TRUNCATED
    if isinstance(exc, ssl.SSLError):
        text = str(exc).upper()
        if "WRONG_VERSION_NUMBER" in text \
                or "UNKNOWN_PROTOCOL" in text \
                or "HTTP_REQUEST" in text or "HTTPS_PROXY" in text:
            return TLS_PLAINTEXT
        if "ALERT" in text:
            # The peer's verifier refused OUR credential (its own
            # reason code lands on its side); locally this is the
            # alert it sent back.
            return TLS_PEER_REFUSED
        if "EOF" in text:
            return TLS_TRUNCATED
        return TLS_FAILED
    if isinstance(exc, (ConnectionError, EOFError)):
        return TLS_TRUNCATED
    return TLS_FAILED


@dataclass
class TlsConfig:
    """One endpoint's mutual-TLS identity: its own cert/key pair, the
    pinned CA bundle every peer must chain to, and the peer NAME it
    expects on the other end of each link (the cert's CN/SAN as
    minted by tools/certs.py — "leader", "helper", "collector").

    Env form (`MASTIC_NET_TLS_CERT` / `_KEY` / `_CA`, optional
    `MASTIC_NET_TLS_NAME` override for the expected peer): unset cert
    means TLS is unarmed and `from_env` returns None — a PARTIAL set
    is an error, because a session that silently ran plaintext when
    the operator thought it armed TLS would be the worst outcome
    (the parse_faults stance)."""

    cert_file: str
    key_file: str
    ca_file: str
    peer_name: Optional[str] = None

    @classmethod
    def from_env(cls) -> Optional["TlsConfig"]:
        cert = os.environ.get("MASTIC_NET_TLS_CERT", "").strip()
        key = os.environ.get("MASTIC_NET_TLS_KEY", "").strip()
        ca = os.environ.get("MASTIC_NET_TLS_CA", "").strip()
        if not (cert or key or ca):
            return None
        if not (cert and key and ca):
            raise ValueError(
                "partial MASTIC_NET_TLS_* set: cert, key and ca must "
                "all be present (or none, for plaintext)")
        name = os.environ.get("MASTIC_NET_TLS_NAME", "").strip()
        return cls(cert, key, ca, peer_name=name or None)

    def expecting(self, peer_name: str) -> "TlsConfig":
        """This identity, pinned to expect `peer_name` on the link
        being built (one TlsConfig serves links to several peers)."""
        return TlsConfig(self.cert_file, self.key_file, self.ca_file,
                         peer_name=peer_name)

    def _load(self, ctx: ssl.SSLContext) -> None:
        ctx.load_cert_chain(self.cert_file, self.key_file)
        ctx.load_verify_locations(self.ca_file)
        ctx.verify_mode = ssl.CERT_REQUIRED

    def server_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        self._load(ctx)
        return ctx

    def client_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        self._load(ctx)
        ctx.check_hostname = True
        return ctx


def _cert_names(cert: dict) -> list:
    """CN + DNS SANs of a (verified) peer cert dict."""
    names = [v for (k, v) in cert.get("subjectAltName", ())
             if k == "DNS"]
    for rdn in cert.get("subject", ()):
        for (k, v) in rdn:
            if k == "commonName":
                names.append(v)
    return names


def _session_error(remote: str, step: str, kind: str, detail: str):
    from ..drivers import session as session_mod

    return session_mod.SessionError(remote, step, kind, detail)


def _refusal(remote: str, exc: BaseException, side: str):
    """A handshake failure as a reason-coded SessionError (kind
    `tls`, terminal — a bad credential does not heal on retry)."""
    from ..drivers import session as session_mod

    reason = tls_reason(exc)
    err = _session_error(remote, "tls_handshake",
                         session_mod.KIND_TLS,
                         f"{reason}: {side} handshake refused "
                         f"({type(exc).__name__}: {str(exc)[:160]})")
    err.reason = reason
    return err


def _count_refusal(reason: str, side: str) -> None:
    from ..obs.registry import get_registry

    get_registry().counter("mastic_tls_refusals_total",
                           reason=reason, side=side).inc()


class TcpListener:
    """A bound TCP listener whose accept path optionally terminates
    mutual TLS: handshake + client-cert CA pinning + peer-name check
    happen before any frame is read, every refusal reason-coded (and
    counted in `mastic_tls_refusals_total`) — a plaintext, wrong-CA,
    expired or misnamed dialer never gets a byte of session state."""

    def __init__(self, host: str, port: int,
                 tls: Optional[TlsConfig] = None, injector=None):
        self.sock = socket.create_server((host, port))
        self.tls = tls
        self.injector = injector
        self._ctx = tls.server_context() if tls is not None else None
        self.refusals: dict = {}   # reason -> count (tests read this)

    @property
    def port(self) -> int:
        return self.sock.getsockname()[1]

    def close(self) -> None:
        with contextlib.suppress(OSError):   # idempotent teardown
            self.sock.close()

    def _note_refusal(self, reason: str) -> None:
        self.refusals[reason] = self.refusals.get(reason, 0) + 1
        _count_refusal(reason, "server")

    def accept(self, remote: str, timeout: float,
               handshake_timeout: float = 10.0) -> socket.socket:
        """One authenticated connection (raw when TLS is unarmed).
        Raises the reason-coded refusal instead of returning a
        half-trusted socket; the listener itself stays usable (the
        caller decides whether to keep accepting)."""
        from ..drivers import session as session_mod

        if self.injector is not None:
            self.injector.checkpoint("tls_handshake")
        self.sock.settimeout(timeout)
        try:
            (sock, _addr) = self.sock.accept()
        except socket.timeout:
            raise _session_error(remote, "accept",
                                 session_mod.KIND_TIMEOUT,
                                 f"no connection within {timeout:.1f}s")
        except OSError as exc:
            raise _session_error(remote, "accept",
                                 session_mod.KIND_CLOSED,
                                 f"accept failed: {exc}")
        if self._ctx is None:
            return sock
        try:
            sock.settimeout(handshake_timeout)
            tls_sock = self._ctx.wrap_socket(sock, server_side=True)
        except (ssl.SSLError, OSError, EOFError) as exc:
            sock.close()
            err = _refusal(remote, exc, "server")
            self._note_refusal(err.reason)
            raise err
        except BaseException:
            # Anything outside the reason-coded tuple (an injector
            # fault, KeyboardInterrupt mid-handshake) must not strand
            # the accepted fd on the floor (RL001).
            sock.close()
            raise
        try:
            names = _cert_names(tls_sock.getpeercert() or {})
            expected = self.tls.peer_name
            if expected is not None and expected not in names:
                err = _session_error(
                    remote, "tls_handshake", session_mod.KIND_TLS,
                    f"{TLS_NAME_MISMATCH}: peer cert names {names} do "
                    f"not include expected {expected!r}")
                err.reason = TLS_NAME_MISMATCH
                self._note_refusal(TLS_NAME_MISMATCH)
                raise err
        except BaseException:
            # The refusal (or any surprise past the handshake) closes
            # the wrapped socket — wrap_socket owns `sock` from here.
            tls_sock.close()
            raise
        return tls_sock


def tcp_dial(host: str, port: int, remote: str, timeout: float,
             tls: Optional[TlsConfig] = None,
             injector=None) -> socket.socket:
    """Deadline-bounded dial, TLS-wrapped when armed: CA pinning +
    server-name check (`ssl` SNI/hostname machinery over the party
    name the cert was minted for), refusals reason-coded."""
    from ..drivers import session as session_mod

    if injector is not None:
        injector.checkpoint("tls_handshake")
    try:
        sock = socket.create_connection((host, port), timeout=timeout)
    except socket.timeout:
        raise _session_error(remote, "connect",
                             session_mod.KIND_TIMEOUT,
                             f"no connection to {host}:{port} within "
                             f"{timeout:.1f}s")
    except OSError as exc:
        raise _session_error(remote, "connect",
                             session_mod.KIND_CLOSED,
                             f"connect to {host}:{port} failed: {exc}")
    if tls is None:
        return sock
    server_name = tls.peer_name or remote
    try:
        return tls.client_context().wrap_socket(
            sock, server_hostname=server_name)
    except (ssl.SSLError, OSError, EOFError) as exc:
        sock.close()
        err = _refusal(remote, exc, "client")
        _count_refusal(err.reason, "client")
        raise err


# ---------------------------------------------------------------------
# Sequence-numbered acked framing (ISSUE 14): the reliable link state
# that makes reconnect-and-replay exactly-once.
# ---------------------------------------------------------------------

# Frame types.  Every (re)connection opens with one RESUME in each
# direction; DATA frames carry (gen, seq, payload); ACK carries the
# receiver's cumulative next-expected seq (everything below it is
# delivered and may leave the replay buffer).
FRAME_RESUME = 0x01
FRAME_DATA = 0x02
FRAME_ACK = 0x03

_RESUME_FMT = "<B8sIQ"           # type, session id, gen, recv_next
_DATA_HDR_FMT = "<BIQI"          # type, gen, seq, payload length
_ACK_FMT = "<BIQ"                # type, gen, recv_next

# Replay-buffer sanity bound: the alternating session protocol keeps
# a handful of frames in flight; hitting this means a protocol bug,
# not load — fail loudly instead of growing.
MAX_UNACKED = 1024

# A dropped link redials up to this many times (exponential backoff,
# clamped to the round deadline) before the failure propagates; more
# generous than the protocol-retry budget because a partition is
# expected to HEAL, while a protocol error is not.
RECONNECT_ATTEMPTS = 8


class SessionRestart(Exception):
    """An accept-side resume handshake met a NEW session id: the peer
    abandoned the old session (collector respawn) and is opening a
    fresh one.  Carries the live, already-authenticated socket and
    the peer's RESUME fields so the server loop can adopt it without
    losing the connection."""

    def __init__(self, sock: socket.socket, session_id: bytes,
                 gen: int, recv_next: int):
        super().__init__("peer opened a new session")
        self.sock = sock
        self.session_id = session_id
        self.gen = gen
        self.recv_next = recv_next


def pack_resume(session_id: bytes, gen: int, seq: int) -> bytes:
    return struct.pack(_RESUME_FMT, FRAME_RESUME, session_id, gen,
                       seq)


def pack_data(gen: int, seq: int, payload: bytes) -> bytes:
    return struct.pack(_DATA_HDR_FMT, FRAME_DATA, gen, seq,
                       len(payload)) + payload


def pack_ack(gen: int, recv_next: int) -> bytes:
    return struct.pack(_ACK_FMT, FRAME_ACK, gen, recv_next)


class TcpTransport:
    """One end of a reliable, reconnecting party link.

    Owns: the live socket, the send-side sequence counter and replay
    buffer (unacked DATA frames), the receive-side `recv_next` cursor
    that makes redelivery after a reconnect exactly-once, and the
    (re)connect handshake.  `connect` is the one policy hook — a
    callable returning a fresh CONNECTED (and TLS-authenticated)
    socket: the dialing end redials, the accepting end re-accepts on
    its retained listener; this class cannot tell and does not care.

    The session layer (`drivers/session.ReliableChannel`) supplies
    attribution (remote/step), deadlines and the redial/backoff
    policy; fault injection reaches this layer through the
    `on_net` seam (conn_drop / partition / slow_loris) plus the
    `tls_handshake` checkpoint inside the connect callables.
    """

    def __init__(self, connect: Callable, remote: str,
                 injector=None, shape: Optional[LinkShape] = None,
                 session_id: Optional[bytes] = None,
                 accept_side: bool = False,
                 adopt: Optional[tuple] = None):
        self.connect = connect
        self.remote = remote
        self.injector = injector
        self.shape = shape
        self._shape_rng = (random.Random(shape.seed)
                          if shape is not None else None)
        # The dialer names the session (8 random bytes); the accept
        # side starts with None and adopts the dialer's id from its
        # first RESUME.  `adopt` seeds the first establish() with an
        # already-accepted socket whose RESUME was consumed (the
        # SessionRestart handoff): (sock, session, gen, recv_next).
        self.session_id = session_id
        self.accept_side = accept_side
        self._adopted = adopt
        self.sock: Optional[socket.socket] = None
        self.gen = 0
        self.send_seq = 0            # last assigned outbound seq
        self.recv_next = 1           # next inbound seq expected
        self.peer_acked = 1          # peer's cumulative next-expected
        self.unacked: dict = {}      # seq -> payload bytes
        self._inbound: list = []     # DATA payloads read while
        #                              draining acks out of order
        self.reconnects = 0
        self.replayed_frames = 0
        self.bytes_sent = 0
        self.bytes_received = 0
        self.partition_until = 0.0   # injected partition healing time
        self._loris_delay = 0.0      # injected stalled-writer delay

    # -- low-level I/O ---------------------------------------------

    def _write_raw(self, data: bytes) -> None:
        if self.shape is not None:
            delay = self.shape.rtt / 2.0
            if self.shape.jitter > 0:
                delay += self._shape_rng.uniform(
                    0.0, self.shape.jitter)
            if self.shape.bandwidth > 0:
                delay += len(data) / self.shape.bandwidth
            if delay > 0:
                time.sleep(delay)
        if self._loris_delay > 0:
            # Injected slow-loris: the writer stalls mid-frame, so
            # the reader sits on a half-delivered frame for the
            # stall — exactly the shape a wedged peer produces.
            stall = self._loris_delay
            self._loris_delay = 0.0
            self.sock.sendall(data[:1])
            time.sleep(stall)
            data = data[1:]
        self.sock.sendall(data)
        self.bytes_sent += len(data)

    def _read_exact(self, n: int, timeout: float) -> bytes:
        """n bytes or an exception; '' mid-read is a dropped link."""
        buf = bytearray()
        while len(buf) < n:
            self.sock.settimeout(timeout)
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionResetError(
                    f"link closed mid-frame ({len(buf)}/{n})")
            buf += chunk
            self.bytes_received += len(chunk)
        return bytes(buf)

    def _read_frame(self, timeout: float) -> tuple:
        """-> (frame type, fields...).  Raises OSError flavors on a
        dead link, socket.timeout on an idle one."""
        head = self._read_exact(1, timeout)
        kind = head[0]
        if kind == FRAME_DATA:
            rest = self._read_exact(
                struct.calcsize(_DATA_HDR_FMT) - 1, timeout)
            (gen, seq, length) = struct.unpack("<IQI", rest)
            payload = self._read_exact(length, timeout) if length \
                else b""
            return (FRAME_DATA, gen, seq, payload)
        if kind == FRAME_ACK:
            rest = self._read_exact(
                struct.calcsize(_ACK_FMT) - 1, timeout)
            (gen, recv_next) = struct.unpack("<IQ", rest)
            return (FRAME_ACK, gen, recv_next)
        if kind == FRAME_RESUME:
            rest = self._read_exact(
                struct.calcsize(_RESUME_FMT) - 1, timeout)
            (session_id, gen, recv_next) = struct.unpack("<8sIQ",
                                                         rest)
            return (FRAME_RESUME, session_id, gen, recv_next)
        raise ConnectionResetError(f"unknown frame type {kind:#x}")

    # -- connection lifecycle --------------------------------------

    def connected(self) -> bool:
        return self.sock is not None

    def kill_socket(self) -> None:
        """Drop the link NOW (fault injection and teardown): the next
        send/recv sees a dead socket and runs the resume path."""
        if self.sock is not None:
            # Idempotent kill of a possibly-dead socket; the
            # reconnect machinery is the recorded outcome.
            with contextlib.suppress(OSError):
                self.sock.close()
            self.sock = None

    def establish(self, handshake_timeout: float) -> int:
        """Connect (or re-accept) + RESUME handshake + replay.
        Returns the number of frames replayed.

        The dialer speaks first (send RESUME, read the reply); the
        accept side reads first, so it can tell a RESUMING peer from
        one opening a NEW session BEFORE committing a reply — the
        latter raises SessionRestart carrying the live socket and
        the consumed RESUME for the server loop to adopt."""
        if time.monotonic() < self.partition_until:
            raise _session_error(
                self.remote, "reconnect", _kind_closed(),
                f"link partitioned for another "
                f"{self.partition_until - time.monotonic():.2f}s")
        old = self.sock
        self.sock = None
        if old is not None:
            with contextlib.suppress(OSError):
                old.close()   # superseded socket
        if self._adopted is not None:
            (sock, peer_session, _peer_gen, peer_next) = \
                self._adopted
            self._adopted = None
            frame_read = True
        else:
            sock = self.connect()
            frame_read = False
        try:
            sock.settimeout(handshake_timeout)
            self.sock = sock   # _read_frame/_write_raw target
            if not self.accept_side:
                sock.sendall(pack_resume(self.session_id,
                                         self.gen + 1,
                                         self.recv_next))
            if not frame_read:
                frame = self._read_frame(handshake_timeout)
                if frame[0] != FRAME_RESUME:
                    self.sock = None
                    sock.close()
                    raise _session_error(
                        self.remote, "reconnect", _kind_protocol(),
                        f"peer opened with frame type "
                        f"{frame[0]:#x}, not RESUME")
                (_kind, peer_session, _peer_gen, peer_next) = frame
            if self.accept_side:
                if self.session_id is None:
                    self.session_id = peer_session
                elif peer_session != self.session_id:
                    self.sock = None
                    raise SessionRestart(sock, peer_session,
                                         _peer_gen, peer_next)
                sock.sendall(pack_resume(self.session_id,
                                         self.gen + 1,
                                         self.recv_next))
            elif peer_session != self.session_id:
                self.sock = None
                sock.close()
                raise _session_error(
                    self.remote, "reconnect", _kind_protocol(),
                    "peer answered with a different session id")
        except ssl.SSLError as exc:
            # TLS 1.3 lets the dialer "finish" before the listener's
            # verdict: a refused credential surfaces as an alert on
            # the first post-handshake read/write.  Classify it as
            # the terminal TLS refusal it is — redialing with the
            # same bad credential would only hammer the listener.
            self.sock = None
            sock.close()
            raise _refusal(self.remote, exc, "client")
        except (OSError, socket.timeout) as exc:
            self.sock = None
            sock.close()
            raise _session_error(
                self.remote, "reconnect", _kind_closed(),
                f"resume handshake failed: {exc}")
        self.gen += 1
        # Everything the peer already holds leaves the replay buffer;
        # the rest replays in order — the peer's recv_next cursor
        # discards any duplicate, so redelivery is exactly-once.
        self.peer_acked = max(self.peer_acked, peer_next)
        for seq in sorted(self.unacked):
            if seq < peer_next:
                del self.unacked[seq]
        replayed = 0
        try:
            for seq in sorted(self.unacked):
                self._write_raw(pack_data(self.gen, seq,
                                          self.unacked[seq]))
                replayed += 1
        except (OSError, socket.timeout) as exc:
            self.kill_socket()
            raise _session_error(
                self.remote, "reconnect", _kind_closed(),
                f"replay failed after resume: {exc}")
        self.replayed_frames += replayed
        return replayed

    # -- fault seam ------------------------------------------------

    def apply_net_fault(self, step: str) -> None:
        """Fire the per-send network fault seam (faults.on_net):
        conn_drop kills the link, partition kills it and refuses
        redial for `delay` seconds (both directions die with the
        socket), slow_loris stalls the next write mid-frame."""
        if self.injector is None:
            return
        rule = self.injector.on_net(step)
        if rule is None:
            return
        if rule.action == "conn_drop":
            self.kill_socket()
        elif rule.action == "partition":
            self.kill_socket()
            self.partition_until = time.monotonic() + rule.delay
        elif rule.action == "slow_loris":
            self._loris_delay = rule.delay

    # -- the reliable send/recv the channel builds on --------------

    def buffer_payload(self, payload: bytes) -> int:
        """Assign the next seq and enter the payload into the replay
        buffer; the caller then pushes it (and owns reconnects)."""
        if len(self.unacked) >= MAX_UNACKED:
            raise _session_error(
                self.remote, "send", _kind_protocol(),
                f"replay buffer exceeded {MAX_UNACKED} frames — "
                f"the peer is not acking")
        self.send_seq += 1
        self.unacked[self.send_seq] = payload
        return self.send_seq

    def push(self, seq: int, timeout: float) -> None:
        """Write one buffered frame (raises on a dead link; the
        caller reconnects and the frame replays from the buffer)."""
        self.sock.settimeout(timeout)
        self._write_raw(pack_data(self.gen, seq, self.unacked[seq]))

    def pull(self, timeout: float) -> Optional[bytes]:
        """One in-order DATA payload (acking it), or None when only
        bookkeeping frames arrived within this read (caller loops).
        Duplicates from a replay are acked and discarded."""
        if self._inbound:
            return self._inbound.pop(0)
        frame = self._read_frame(timeout)
        if frame[0] == FRAME_ACK:
            (_kind, _gen, peer_next) = frame
            self.peer_acked = max(self.peer_acked, peer_next)
            for seq in sorted(self.unacked):
                if seq < peer_next:
                    del self.unacked[seq]
            return None
        if frame[0] == FRAME_DATA:
            (_kind, _gen, seq, payload) = frame
            if seq < self.recv_next:         # replayed duplicate
                self._send_ack(timeout)
                return None
            if seq != self.recv_next:
                raise _session_error(
                    self.remote, "recv", _kind_protocol(),
                    f"sequence gap: got {seq}, expected "
                    f"{self.recv_next} (frames lost inside a "
                    f"connection)")
            self.recv_next += 1
            self._send_ack(timeout)
            return payload
        raise _session_error(
            self.remote, "recv", _kind_protocol(),
            "RESUME frame mid-connection")

    def _send_ack(self, timeout: float) -> None:
        try:
            self.sock.settimeout(timeout)
            self._write_raw(pack_ack(self.gen, self.recv_next))
        except (OSError, socket.timeout):
            # The payload is already delivered locally; a failed ack
            # only means the peer replays it after reconnect and the
            # recv_next cursor discards the duplicate.
            self.kill_socket()

    def close(self) -> None:
        self.kill_socket()


def _kind_closed() -> str:
    from ..drivers import session as session_mod

    return session_mod.KIND_CLOSED


def _kind_protocol() -> str:
    from ..drivers import session as session_mod

    return session_mod.KIND_PROTOCOL
