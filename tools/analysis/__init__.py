"""Static analyzer for the trace-safety / dtype / secret-flow / Pallas
invariants that make this reproduction's bit-exact crypto survive
jit + Pallas (run via `make analyze`; part of `make ci`).

Four passes, each with stable rule IDs, each scoped to the layer whose
contract it checks:

  tracesafe   TS001-TS004   mastic_tpu/ops/, backend/, flp/flp_jax.py
  dtypes      DT001-DT003   mastic_tpu/ops/ (field/AES/Keccak kernels)
  secretflow  SF001-SF002   vidpf.py, mastic.py, aes.py, xof.py
  pallasck    PL001-PL004   any file calling pallas_call
  robustness  RB001-RB005   mastic_tpu/drivers/ + tools/serve.py
                            (session layer + collector service)
  observability OB001       mastic_tpu/ library code (prints must
                            route through the telemetry layer)

plus the suppression meta-rules AL001 (mastic-allow without a written
justification) and AL002 (mastic-allow that silences nothing), and
XX000 (file does not parse).

Findings are suppressed inline with `# mastic-allow: <ID>[, <ID>] —
reason`, on the flagged line or as a comment line directly above the
flagged statement.  There are no file-level exclusions: every accepted
risk is written down where the code is.

See USAGE.md ("Static analysis") for the rule table and workflow.
"""

import json
import pathlib

from . import (dtypes, observability, pallasck, robustness,
               secretflow, tracesafe)
from .core import REPO, Finding, load_file

PASSES = (tracesafe, dtypes, secretflow, pallasck, robustness,
          observability)

DEFAULT_ROOTS = ("mastic_tpu", "tools", "bench.py")

_RULE_TABLE = {}
for _p in PASSES:
    _RULE_TABLE.update(_p.RULES)
_RULE_TABLE.update({
    "AL001": "mastic-allow without a written justification",
    "AL002": "mastic-allow that suppresses nothing",
    "XX000": "file does not parse",
})


def default_files() -> list:
    files = [REPO / "bench.py"]
    for root in ("mastic_tpu", "tools"):
        files += sorted((REPO / root).rglob("*.py"))
    return [f for f in files if f.exists()]


def _pass_applies(mod, rel: str, tree) -> bool:
    if mod is pallasck:
        return mod.in_scope(rel, tree)
    return mod.in_scope(rel)


def analyze_paths(paths, only_passes=None, force_scope=False):
    """Run the passes over `paths`.

    only_passes: iterable of pass names (e.g. {"tracesafe"}) to run a
    subset; force_scope: apply the passes regardless of each pass's
    path scope (how the fixture self-tests drive files that live under
    tests/fixtures/).  Returns (findings, suppressed) where both are
    lists of Finding — `findings` is what gates CI, `suppressed` is
    what inline allows silenced.
    """
    selected = [p for p in PASSES
                if only_passes is None or p.PASS_NAME in only_passes]
    findings: list = []
    suppressed: list = []
    for path in paths:
        path = pathlib.Path(path)
        info = load_file(path)
        if isinstance(info, Finding):
            findings.append(info)
            continue
        raw: list = []
        for mod in selected:
            if force_scope or _pass_applies(mod, info.rel, info.tree):
                raw += mod.check(info)
        for f in raw:
            sup = info.suppression_for(f)
            if sup is None:
                findings.append(f)
            else:
                sup.used = True
                suppressed.append(f)
        # Suppression hygiene: every allow must carry a reason and
        # actually silence something.
        for sup in info.suppressions:
            if not sup.reason:
                findings.append(Finding(
                    "AL001", info.rel, sup.line,
                    "mastic-allow without a written justification "
                    "(add '— why this is fine')"))
            elif not sup.used and (only_passes is None
                                   or _covered(sup, selected)):
                findings.append(Finding(
                    "AL002", info.rel, sup.line,
                    f"mastic-allow for {', '.join(sup.ids)} suppresses "
                    "nothing — stale; remove it"))
    findings.sort(key=Finding.key)
    suppressed.sort(key=Finding.key)
    return (findings, suppressed)


def _covered(sup, selected) -> bool:
    """Only report a stale allow when the selected passes could have
    produced its rules (partial runs must not flag other passes')."""
    owned = set()
    for mod in selected:
        owned |= set(mod.RULES)
    return any(rid in owned for rid in sup.ids)


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="tools.analysis",
        description="trace-safety / dtype / secret-flow / pallas "
                    "static analyzer (rules in USAGE.md)")
    parser.add_argument("paths", nargs="*",
                        help="files to analyze (default: mastic_tpu/, "
                             "tools/, bench.py)")
    parser.add_argument("--json", action="store_true",
                        help="emit findings as one JSON object")
    parser.add_argument("--pass", dest="only", action="append",
                        choices=[p.PASS_NAME for p in PASSES],
                        help="run only this pass (repeatable)")
    parser.add_argument("--force-scope", action="store_true",
                        help="apply passes regardless of path scope "
                             "(fixture testing)")
    args = parser.parse_args(argv)

    files = ([pathlib.Path(p).resolve() for p in args.paths]
             if args.paths else default_files())
    (findings, suppressed_list) = analyze_paths(
        files, only_passes=set(args.only) if args.only else None,
        force_scope=args.force_scope)
    if args.json:
        print(json.dumps({
            "findings": [f.as_json() for f in findings],
            "suppressed": [f.as_json() for f in suppressed_list],
            "files": len(files),
        }, indent=2))
    else:
        for f in findings:
            print(f.text())
        print(f"analyze: {len(files)} files, {len(findings)} "
              f"finding(s), {len(suppressed_list)} suppressed")
    return 1 if findings else 0
