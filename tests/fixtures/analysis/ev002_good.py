"""EV002 clean: the loop waits for writability before each send."""


def flush(sel, sock, payload):
    sock.setblocking(False)
    while payload:
        sel.select(0)
        sent = sock.send(payload)
        payload = payload[sent:]
