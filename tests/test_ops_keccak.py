"""Differential tests: batched Keccak/TurboSHAKE128 vs scalar reference."""

import numpy as np

from mastic_tpu.keccak import turbo_shake128
from mastic_tpu.ops.keccak_jax import turbo_shake128 as ts_jax


def test_turbo_shake128_matches_scalar():
    rng = np.random.default_rng(0)
    # Lengths straddling the 168-byte rate boundary, both domains used
    # by the VDAF XOFs, single- and multi-block squeezes.
    cases = [
        (0, 1, 16), (1, 2, 32), (42, 1, 32), (167, 1, 168),
        (168, 2, 169), (169, 1, 16), (336, 2, 32), (901, 1, 345),
    ]
    for (msg_len, domain, out_len) in cases:
        batch = rng.integers(0, 256, size=(3, msg_len), dtype=np.uint8)
        got = np.asarray(ts_jax(batch, domain, out_len))
        for b in range(batch.shape[0]):
            want = turbo_shake128(bytes(batch[b]), domain, out_len)
            assert bytes(got[b]) == want, (msg_len, domain, out_len, b)


def test_turbo_shake128_nd_batch():
    rng = np.random.default_rng(1)
    batch = rng.integers(0, 256, size=(2, 3, 50), dtype=np.uint8)
    got = np.asarray(ts_jax(batch, 1, 32))
    assert got.shape == (2, 3, 32)
    for i in range(2):
        for j in range(3):
            assert bytes(got[i, j]) == turbo_shake128(bytes(batch[i, j]), 1, 32)
