"""The abstract VDAF interface and an in-process protocol runner
(draft-irtf-cfrg-vdaf-13 §5; replaces `vdaf_poc.vdaf` and the
`run_vdaf` harness used by the reference test suite).

Multi-party execution without a cluster is just function composition:
every party is a pure function over bytes, so the runner calls each
party's functions in protocol order (cf. reference examples.py:49-71).
"""

from typing import Any, Generic, TypeVar

from .common import gen_rand

Measurement = TypeVar("Measurement")
AggParam = TypeVar("AggParam")
PublicShare = TypeVar("PublicShare")
InputShare = TypeVar("InputShare")
OutShare = TypeVar("OutShare")
AggShare = TypeVar("AggShare")
AggResult = TypeVar("AggResult")
PrepState = TypeVar("PrepState")
PrepShare = TypeVar("PrepShare")
PrepMessage = TypeVar("PrepMessage")


class Vdaf(Generic[Measurement, AggParam, PublicShare, InputShare, OutShare,
                   AggShare, AggResult, PrepState, PrepShare, PrepMessage]):
    """A Verifiable Distributed Aggregation Function."""

    ID: int
    VERIFY_KEY_SIZE: int
    RAND_SIZE: int
    NONCE_SIZE: int
    SHARES: int
    ROUNDS: int

    # Client.
    def shard(self, ctx: bytes, measurement: Measurement, nonce: bytes,
              rand: bytes) -> tuple[PublicShare, list[InputShare]]:
        raise NotImplementedError()

    # Aggregator.
    def is_valid(self, agg_param: AggParam,
                 previous_agg_params: list[AggParam]) -> bool:
        raise NotImplementedError()

    def prep_init(self, verify_key: bytes, ctx: bytes, agg_id: int,
                  agg_param: AggParam, nonce: bytes,
                  public_share: PublicShare, input_share: InputShare) \
            -> tuple[PrepState, PrepShare]:
        raise NotImplementedError()

    def prep_shares_to_prep(self, ctx: bytes, agg_param: AggParam,
                            prep_shares: list[PrepShare]) -> PrepMessage:
        raise NotImplementedError()

    def prep_next(self, ctx: bytes, prep_state: PrepState,
                  prep_msg: PrepMessage) -> OutShare:
        raise NotImplementedError()

    def agg_init(self, agg_param: AggParam) -> AggShare:
        raise NotImplementedError()

    def agg_update(self, agg_param: AggParam, agg_share: AggShare,
                   out_share: OutShare) -> AggShare:
        raise NotImplementedError()

    def merge(self, agg_param: AggParam,
              agg_shares: list[AggShare]) -> AggShare:
        raise NotImplementedError()

    # Collector.
    def unshard(self, agg_param: AggParam, agg_shares: list[AggShare],
                num_measurements: int) -> AggResult:
        raise NotImplementedError()


def run_vdaf(vdaf: Vdaf[Measurement, AggParam, Any, Any, Any, Any,
                        AggResult, Any, Any, Any],
             verify_key: bytes,
             agg_param: AggParam,
             ctx: bytes,
             nonces: list[bytes],
             measurements: list[Measurement]) -> AggResult:
    """Run the full one-round VDAF protocol in-process."""
    assert len(nonces) == len(measurements)
    agg_shares = [vdaf.agg_init(agg_param) for _ in range(vdaf.SHARES)]
    for (nonce, measurement) in zip(nonces, measurements):
        rand = gen_rand(vdaf.RAND_SIZE)
        (public_share, input_shares) = \
            vdaf.shard(ctx, measurement, nonce, rand)

        prep_states = []
        outbound_prep_shares = []
        for agg_id in range(vdaf.SHARES):
            (state, share) = vdaf.prep_init(verify_key, ctx, agg_id,
                                            agg_param, nonce, public_share,
                                            input_shares[agg_id])
            prep_states.append(state)
            outbound_prep_shares.append(share)

        prep_msg = vdaf.prep_shares_to_prep(ctx, agg_param,
                                            outbound_prep_shares)
        for agg_id in range(vdaf.SHARES):
            out_share = vdaf.prep_next(ctx, prep_states[agg_id], prep_msg)
            agg_shares[agg_id] = vdaf.agg_update(agg_param,
                                                 agg_shares[agg_id],
                                                 out_share)
    return vdaf.unshard(agg_param, agg_shares, len(measurements))
