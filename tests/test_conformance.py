"""Conformance: replay every JSON vector in
/root/reference/test_vec/mastic/ and compare every hex field byte for
byte (shard, prep shares, prep messages, out shares, agg shares, agg
result).  These vectors are the cross-implementation ground truth
(consumed also by libprio-rs; reference README.md:47-51).
"""

import json
import os

import pytest

from mastic_tpu import testvec_codec as codec
from mastic_tpu.mastic import (Mastic, MasticCount, MasticHistogram,
                               MasticMultihotCountVec, MasticSum,
                               MasticSumVec)

TEST_VEC_DIR = os.environ.get(
    "MASTIC_TEST_VEC", "/root/reference/test_vec/mastic")


def _instance_for(test_vec: dict) -> Mastic:
    bits = test_vec["vidpf_bits"]
    name = test_vec["_name"]
    if name.startswith("MasticCount"):
        return MasticCount(bits)
    if name.startswith("MasticSumVec"):
        return MasticSumVec(bits, test_vec["length"], test_vec["bits"],
                            test_vec["chunk_length"])
    if name.startswith("MasticSum"):
        return MasticSum(bits, test_vec["max_measurement"])
    if name.startswith("MasticHistogram"):
        return MasticHistogram(bits, test_vec["length"],
                               test_vec["chunk_length"])
    if name.startswith("MasticMultihotCountVec"):
        return MasticMultihotCountVec(bits, test_vec["length"],
                                      test_vec["max_weight"],
                                      test_vec["chunk_length"])
    raise ValueError(f"unknown vector {name}")


def _parse_measurement(mastic: Mastic, raw) -> tuple:
    (alpha_raw, weight_raw) = raw
    alpha = tuple(bool(b) for b in alpha_raw)
    return (alpha, weight_raw)


def _vector_files() -> list[str]:
    if not os.path.isdir(TEST_VEC_DIR):
        return []
    return sorted(f for f in os.listdir(TEST_VEC_DIR)
                  if f.endswith(".json"))


@pytest.mark.parametrize("filename", _vector_files())
def test_vector(filename: str) -> None:
    with open(os.path.join(TEST_VEC_DIR, filename)) as f:
        test_vec = json.load(f)
    test_vec["_name"] = filename
    mastic = _instance_for(test_vec)

    ctx = bytes.fromhex(test_vec["ctx"])
    verify_key = bytes.fromhex(test_vec["verify_key"])
    assert len(verify_key) == mastic.VERIFY_KEY_SIZE
    agg_param = mastic.decode_agg_param(
        bytes.fromhex(test_vec["agg_param"]))
    assert mastic.encode_agg_param(agg_param).hex() == \
        test_vec["agg_param"]

    agg_shares = [mastic.agg_init(agg_param) for _ in range(2)]
    for prep in test_vec["prep"]:
        nonce = bytes.fromhex(prep["nonce"])
        rand = bytes.fromhex(prep["rand"])
        assert len(rand) == mastic.RAND_SIZE, \
            f"RAND_SIZE {mastic.RAND_SIZE} != {len(rand)}"
        measurement = _parse_measurement(mastic, prep["measurement"])

        # Client.
        (public_share, input_shares) = \
            mastic.shard(ctx, measurement, nonce, rand)
        assert codec.encode_public_share(mastic, public_share).hex() == \
            prep["public_share"]
        for (agg_id, input_share) in enumerate(input_shares):
            assert codec.encode_input_share(mastic, input_share).hex() \
                == prep["input_shares"][agg_id], f"input share {agg_id}"

        # Aggregators: prep.
        prep_states = []
        prep_shares = []
        for agg_id in range(2):
            (state, share) = mastic.prep_init(
                verify_key, ctx, agg_id, agg_param, nonce, public_share,
                input_shares[agg_id])
            assert codec.encode_prep_share(mastic, share).hex() == \
                prep["prep_shares"][0][agg_id], f"prep share {agg_id}"
            prep_states.append(state)
            prep_shares.append(share)

        prep_msg = mastic.prep_shares_to_prep(ctx, agg_param, prep_shares)
        assert codec.encode_prep_msg(mastic, prep_msg).hex() == \
            prep["prep_messages"][0]

        for agg_id in range(2):
            out_share = mastic.prep_next(ctx, prep_states[agg_id], prep_msg)
            expected = [bytes.fromhex(h) for h in
                        prep["out_shares"][agg_id]]
            got = [mastic.field.encode_vec([x]) for x in out_share]
            assert got == expected, f"out share {agg_id}"
            agg_shares[agg_id] = mastic.agg_update(
                agg_param, agg_shares[agg_id], out_share)

    for agg_id in range(2):
        assert codec.encode_agg_share(mastic, agg_shares[agg_id]).hex() \
            == test_vec["agg_shares"][agg_id], f"agg share {agg_id}"

    agg_result = mastic.unshard(agg_param, agg_shares,
                                len(test_vec["prep"]))
    assert agg_result == test_vec["agg_result"]
