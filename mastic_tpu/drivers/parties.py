"""Process-separated aggregators: leader and helper as OS processes
exchanging the real wire encodings over sockets.

The reference PoC simulates all parties in one process
(/root/reference/poc/examples.py:51-59); its wire *formats* are fully
specified, though, and this module runs them over an actual transport:

    collector ──spawn──> leader (agg 0)     helper (agg 1)
        │ upload: nonce‖public share‖input share   (per party view)
        │ round:  encoded agg param ‖ quarantine mask
        │                  ▲
        │   helper ──prep share blob──> leader
        │   leader ──accept bitmap + prep msgs──> helper
        │ agg share bytes ──> collector (leader adds the bitmap)

Each party drives the *batched* backend for prep (one device program
over its whole report batch) and the scalar layer for the per-report
cross-party logic (prep_shares_to_prep / joint-rand confirmation),
exactly the split a real deployment would have.  Lanes where XOF
rejection sampling fires are recomputed through the party's own
scalar path before the exchange, so the fallback never crosses a
trust boundary.

Fault tolerance (ISSUE 3; the session layer in drivers/session.py):

* every blocking call carries a deadline (per-exchange timeout plus a
  session-level round budget), so a dead or hung peer fails the round
  in bounded time with a `SessionError` naming the party and step;
* a party that hits a protocol error NAKs the collector with a
  structured error frame before exiting, so attribution does not have
  to wait out the deadline;
* a malformed report blob is *quarantined* (that report is excluded
  from the batch with a reason code, both parties agree via the
  collector's union mask) instead of aborting the upload;
* the idempotent exchanges (upload, agg-param dispatch, agg-share
  fetch) retry with bounded backoff; prep shares are recomputable
  from the marshaled arrays, so `AggregationSession` restarts a whole
  round after respawning a crashed party and the rerun is
  bit-identical;
* every outcome (timeouts, retries, quarantines, respawns) lands in
  `RoundMetrics` counters.

The DAP-style topology: the helper only talks to the leader for prep;
the collector only sees aggregate shares (plus the leader's accept
count) — reference README's deployment sketch and SURVEY.md §2.3's
communication-backend plan.
"""

import json
import os
import socket
import subprocess
import sys
import time
from typing import Optional

import numpy as np

from .. import mastic as mastic_mod
from ..mastic import Mastic
from .. import wire
from ..metrics import RoundMetrics, count_round_bytes
from ..obs import trace as obs_trace
from . import faults as faults_mod
from . import session as session_mod
from .session import (Channel, Deadline, SessionConfig, SessionError,
                      with_retries)

# Collector -> party command bytes.
CMD_UPLOAD = b"\x01"
CMD_ROUND = b"\x02"
CMD_SHUTDOWN = b"\x03"
# Party -> collector reply framing: ACK prefix + payload, or NAK
# prefix + a JSON-encoded structured error (party/step/kind/detail).
REPLY_ACK = b"\x06"
REPLY_NAK = b"\x15"

# Quarantine reason codes (the per-report rejection taxonomy the
# upload ack reports; names in REASON_NAMES for metrics/debugging).
REASON_MALFORMED = 1      # decode raised: bad length / framing
REASON_RANGE = 2          # decoded but out of range (field element)
REASON_NAMES = {REASON_MALFORMED: "malformed", REASON_RANGE: "range"}


def instantiate(spec: dict) -> Mastic:
    """{"class": "MasticCount", "args": [2]} -> instance."""
    cls = getattr(mastic_mod, spec["class"])
    return cls(*spec["args"])


class AggregatorParty:
    """One aggregator's protocol engine (transport-agnostic)."""

    def __init__(self, mastic: Mastic, agg_id: int, verify_key: bytes,
                 ctx: bytes):
        from ..backend.mastic_jax import BatchedMastic

        self.m = mastic
        self.agg_id = agg_id
        self.verify_key = verify_key
        self.ctx = ctx
        self.bm = BatchedMastic(mastic)
        self.reports: list = []
        self.quarantined: list = []   # [(index, reason code, detail)]
        self.arrays: Optional[dict] = None
        self._prep = None
        self._resolve_fns: dict = {}

    # -- upload channel --------------------------------------------

    def load_reports(self, blobs: list[bytes]) -> list:
        """Decode the upload blobs; a malformed blob quarantines that
        report (returned as (index, reason, detail)) instead of
        aborting the batch — the lane is padded with a copy of the
        first good report and masked out of every later stage.
        Raises ValueError when no report decodes (there is no batch
        to pad)."""
        decoded: list = []
        quarantined: list = []
        for (i, blob) in enumerate(blobs):
            try:
                decoded.append(wire.decode_report(self.m, self.agg_id,
                                                  blob))
            except (ValueError, EOFError) as exc:
                reason = (REASON_RANGE
                          if "out of range" in str(exc)
                          else REASON_MALFORMED)
                quarantined.append((i, reason, str(exc)))
                decoded.append(None)
        good = next((r for r in decoded if r is not None), None)
        if good is None:
            raise ValueError(
                f"all {len(blobs)} uploaded reports are malformed — "
                f"no batch to aggregate")
        self.reports = [r if r is not None else good for r in decoded]
        self.quarantined = quarantined
        self.arrays = self.bm.marshal_party_reports(self.agg_id,
                                                    self.reports)
        return quarantined

    # -- prep ------------------------------------------------------

    def prep_blob(self, agg_param) -> bytes:
        """Run the batched prep and encode this party's prep shares:
        R fixed-size rows (eval proof ‖ [jr part] ‖ [verifier])."""
        import jax

        if self.arrays is None:
            raise SessionError(
                "leader" if self.agg_id == 0 else "helper",
                "agg_param", session_mod.KIND_PROTOCOL,
                "round requested before any report upload")
        a = self.arrays
        bm = self.bm
        fn = jax.jit(lambda n, c, k, p, s, j: bm.prep(
            self.agg_id, self.verify_key, self.ctx, agg_param,
            n, c, k, proof_shares=p, seeds=s, peer_jr_parts=j))
        p = fn(a["nonces"], a["cws"], a["keys"], a["proof_shares"],
               a["seeds"], a["peer_jr_parts"])
        self._prep = self._scalar_fallback(agg_param, p)
        return self._encode_prep(agg_param, self._prep)

    def _scalar_fallback(self, agg_param, p):
        """Recompute lanes where XOF rejection sampling fired through
        this party's scalar layer (vdaf-13 §6.2 rejection loop) and
        splice the exact rows in."""
        ok = np.asarray(p.ok)
        if ok.all():
            return p
        spec = self.bm.spec
        out_share = np.asarray(p.out_share).copy()
        eval_proof = np.asarray(p.eval_proof).copy()
        verifier = (None if p.verifier is None
                    else np.asarray(p.verifier).copy())
        jr_part = (None if p.joint_rand_part is None
                   else np.asarray(p.joint_rand_part).copy())
        jr_seed = (None if p.joint_rand_seed is None
                   else np.asarray(p.joint_rand_seed).copy())
        for r in np.flatnonzero(~ok):
            (nonce, public_share, input_share) = self.reports[r]
            (state, share) = self.m.prep_init(
                self.verify_key, self.ctx, self.agg_id, agg_param,
                nonce, public_share, input_share)
            (out, seed) = state
            (proof, ver, part) = share
            out_share[r] = [spec.int_to_limbs(x.int()) for x in out]
            eval_proof[r] = np.frombuffer(proof, np.uint8)
            if verifier is not None and ver is not None:
                verifier[r] = [spec.int_to_limbs(x.int()) for x in ver]
            if jr_part is not None and part is not None:
                jr_part[r] = np.frombuffer(part, np.uint8)
            if jr_seed is not None and seed is not None:
                jr_seed[r] = np.frombuffer(seed, np.uint8)
        return p._replace(
            out_share=out_share, eval_proof=eval_proof,
            verifier=verifier, joint_rand_part=jr_part,
            joint_rand_seed=jr_seed)

    def _encode_prep(self, agg_param, p) -> bytes:
        (_level, _prefixes, do_weight_check) = agg_param
        num = np.asarray(p.eval_proof).shape[0]
        parts = [np.asarray(p.eval_proof)]
        if do_weight_check:
            if self.m.flp.JOINT_RAND_LEN > 0:
                parts.append(np.asarray(p.joint_rand_part))
            ver = np.asarray(self.bm.spec.plain_to_le_bytes(
                p.verifier)).reshape(num, -1)
            parts.append(ver)
        return np.concatenate(parts, axis=-1).tobytes()

    # -- leader: the prep-share exchange ---------------------------

    def resolve(self, agg_param, peer_blob: bytes,
                exclude: Optional[np.ndarray] = None) -> tuple:
        """Leader side of prep_shares_to_prep over the report batch:
        returns (accept bitmap bytes, prep-msg blob).

        Vectorized over the report axis (scalar semantics:
        mastic.py prep_shares_to_prep + the leader's own joint-rand
        confirmation): eval-proof equality, the FLP decide over the
        summed verifier shares (the batched decide kernel), and the
        joint-rand seed derivation all run as single batched ops.  A
        verifier element outside the field (possible only from a
        misbehaving helper) rejects that report instead of aborting
        the batch.  `exclude` masks quarantined lanes (the
        collector's union mask) out of acceptance before the bitmap
        is built."""
        import jax.numpy as jnp

        (_level, _prefixes, do_wc) = agg_param
        size = wire.prep_share_size(self.m, agg_param)
        num = len(self.reports)
        p = self._prep
        if len(peer_blob) != num * size:
            # A protocol-level refusal, not a numpy reshape traceback:
            # a truncated or oversized exchange from a misbehaving
            # peer aborts the round loudly and attributably.
            raise ValueError(
                f"malformed prep-share exchange from peer: got "
                f"{len(peer_blob)} bytes, expected {num} x {size}")
        peer = np.frombuffer(peer_blob, np.uint8).reshape(num, size)
        use_jr = (self.m.flp.JOINT_RAND_LEN > 0 and do_wc)
        fn = self._resolve_fn(do_wc, use_jr, num, size)
        if do_wc:
            (accept, prep_msgs) = fn(
                jnp.asarray(peer), p.eval_proof, p.verifier,
                p.joint_rand_part, p.joint_rand_seed)
        else:
            (accept, prep_msgs) = fn(jnp.asarray(peer), p.eval_proof)
        accept = np.asarray(accept).copy()
        prep_msgs = (np.asarray(prep_msgs) if prep_msgs is not None
                     else None)
        if exclude is not None:
            accept &= ~np.asarray(exclude, bool)

        bitmap = np.packbits(accept, bitorder="little").tobytes()
        blob = b"".join(
            wire.frame(prep_msgs[r].tobytes()
                       if accept[r] and prep_msgs is not None else b"")
            for r in range(num))
        return (accept, bitmap + blob)

    def _resolve_fn(self, do_wc: bool, use_jr: bool, num: int,
                    size: int):
        """One jitted program for the whole batched exchange (eager
        dispatch of the Keccak/NTT kernels at 10k reports costs more
        than the math).  Cached per round *kind* only — jax.jit
        already specializes per (num, size) shape."""
        import jax
        import jax.numpy as jnp

        del num, size  # shape specialization is jit's job
        key = (do_wc, use_jr)
        fn = self._resolve_fns.get(key)
        if fn is not None:
            return fn
        (bm, ctx, elem) = (self.bm, self.ctx, self.m.field.ENCODED_SIZE)

        if not do_wc:
            def fn(peer, eval_proof):
                return (jnp.all(eval_proof == peer[:, :32], axis=-1),
                        None)
        else:
            def fn(peer, eval_proof, verifier_own, jr_part_own,
                   jr_seed_own):
                accept = jnp.all(eval_proof == peer[:, :32], axis=-1)
                off = 32
                if use_jr:
                    part1 = peer[:, off:off + 32]
                    off += 32
                ver_bytes = peer[:, off:]
                vlen = ver_bytes.shape[1] // elem
                (ver1, in_range) = bm.spec.limbs_from_le_bytes(
                    ver_bytes.reshape(ver_bytes.shape[0], vlen, elem))
                verifier = bm.spec.add(verifier_own, ver1)
                accept &= bm.bflp.decide(verifier)
                accept &= jnp.all(in_range, axis=-1)
                prep_msgs = None
                if use_jr:
                    # prep msg = joint-rand seed from [leader, helper]
                    # parts; the leader's confirmation compares it to
                    # its own predicted seed (prep_next semantics —
                    # the helper runs the same check in confirm()).
                    prep_msgs = bm.joint_rand_seed(ctx, jr_part_own,
                                                   part1)
                    accept &= jnp.all(prep_msgs == jr_seed_own,
                                      axis=-1)
                return (accept, prep_msgs)

        fn = jax.jit(fn)
        self._resolve_fns[key] = fn
        return fn

    def confirm(self, agg_param, resolution: bytes) -> np.ndarray:
        """Helper side: parse the leader's bitmap + prep msgs, run the
        joint-rand confirmation (prep_next semantics) per report."""
        num = len(self.reports)
        nbytes = (num + 7) // 8
        if len(resolution) < nbytes:
            # Same protocol-level refusal as the leader's resolve():
            # a truncating peer aborts loudly, not via numpy/struct
            # tracebacks mid-parse.
            raise ValueError(
                f"malformed resolution from leader: got "
                f"{len(resolution)} bytes, accept bitmap alone needs "
                f"{nbytes}")
        accept = np.unpackbits(
            np.frombuffer(resolution[:nbytes], np.uint8),
            bitorder="little")[:num].astype(bool)
        rest = resolution[nbytes:]
        use_jr = (self.m.flp.JOINT_RAND_LEN > 0 and agg_param[2])
        jr_seed = (None if self._prep.joint_rand_seed is None
                   else np.asarray(self._prep.joint_rand_seed))
        for r in range(num):
            try:
                (msg, rest) = wire.unframe(rest)
            except Exception as exc:
                raise ValueError(
                    f"malformed resolution from leader: prep msg "
                    f"{r} of {num} truncated") from exc
            if not accept[r]:
                continue
            if use_jr:
                if jr_seed is None:
                    raise ValueError(
                        "malformed resolution from leader: prep msg "
                        "present but this round has no joint rand")
                if msg != jr_seed[r].tobytes():
                    accept[r] = False  # joint-rand confirmation failed
            elif msg != b"":
                accept[r] = False
        if rest:
            # Strict length symmetry with resolve(): trailing bytes
            # are a malformed exchange, not ignorable padding.
            raise ValueError(
                f"malformed resolution from leader: {len(rest)} "
                f"trailing bytes after the last prep msg")
        return accept

    # -- aggregation -----------------------------------------------

    def agg_share(self, agg_param, accept: np.ndarray) -> bytes:
        import jax.numpy as jnp

        agg = self.bm.aggregate(jnp.asarray(self._prep.out_share),
                                jnp.asarray(accept))
        return np.asarray(
            self.bm.spec.plain_to_le_bytes(agg)).tobytes()


# -- quarantine ack / round-command codecs ----------------------------

def encode_quarantine(entries: list) -> bytes:
    """(index, reason, detail) list -> compact ack payload (details
    stay party-local; the wire carries index + reason code)."""
    out = [np.uint32(len(entries)).tobytes()]
    for (idx, reason, _detail) in entries:
        out.append(np.uint32(idx).tobytes() + bytes([reason]))
    return b"".join(out)


def decode_quarantine(payload: bytes) -> list:
    if len(payload) < 4:
        raise ValueError("malformed upload ack: truncated count")
    (num,) = np.frombuffer(payload[:4], np.uint32)
    body = payload[4:]
    if len(body) != int(num) * 5:
        raise ValueError(
            f"malformed upload ack: {len(body)} bytes for "
            f"{int(num)} quarantine entries")
    entries = []
    for i in range(int(num)):
        (idx,) = np.frombuffer(body[i * 5:i * 5 + 4], np.uint32)
        entries.append((int(idx), body[i * 5 + 4]))
    return entries


def encode_round_cmd(encoded_param: bytes, mask: np.ndarray) -> bytes:
    """CMD_ROUND ‖ u32 param length ‖ param ‖ quarantine mask bits."""
    mask_bytes = np.packbits(np.asarray(mask, bool),
                             bitorder="little").tobytes()
    return (CMD_ROUND + np.uint32(len(encoded_param)).tobytes()
            + encoded_param + mask_bytes)


def decode_round_cmd(msg: bytes, num_reports: int) -> tuple:
    """-> (encoded agg param, quarantine mask over num_reports)."""
    if len(msg) < 5:
        raise ValueError("malformed round command: truncated header")
    (plen,) = np.frombuffer(msg[1:5], np.uint32)
    plen = int(plen)
    if len(msg) < 5 + plen:
        raise ValueError(
            f"malformed round command: param needs {plen} bytes, "
            f"{len(msg) - 5} present")
    encoded_param = msg[5:5 + plen]
    mask_bytes = msg[5 + plen:]
    need = (num_reports + 7) // 8
    if len(mask_bytes) != need:
        raise ValueError(
            f"malformed round command: quarantine mask is "
            f"{len(mask_bytes)} bytes, want {need}")
    mask = np.unpackbits(
        np.frombuffer(mask_bytes, np.uint8),
        bitorder="little")[:num_reports].astype(bool)
    return (encoded_param, mask)


# -- the party process main loop -------------------------------------

def party_main(argv: list[str]) -> None:
    # The ambient sitecustomize force-overrides jax's platform config
    # to the remote TPU backend; make the caller's JAX_PLATFORMS
    # authoritative again (the test fabric runs parties on CPU, and a
    # down TPU tunnel must not be able to hang a CPU party).
    import jax

    requested = os.environ.get("JAX_PLATFORMS", "").strip()
    if requested and "axon" not in requested.split(","):
        jax.config.update("jax_platforms", requested)
    # Share the persistent compile cache with the parent fabric.
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                     "/tmp/mastic_tpu_jax_cache"))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    debug = os.environ.get("MASTIC_PARTY_DEBUG") == "1"

    # Config arrives on stdin (the collector's private-pipe handoff —
    # key material must not ride argv, which is world-readable in
    # /proc/<pid>/cmdline).  An explicit argv blob still wins for
    # by-hand debugging of a single party.
    cfg = json.loads(argv[0] if argv else sys.stdin.readline())
    agg_id = cfg["agg_id"]
    me = "leader" if agg_id == 0 else "helper"
    config = SessionConfig.from_env()
    injector = faults_mod.injector_from_env(me)

    def trace(what: str) -> None:
        # Every step lands as a span event (the party's JSONL trace,
        # MASTIC_TRACE_FILE, interleaves with the collector's); the
        # stderr echo stays behind the MASTIC_PARTY_DEBUG lever for
        # watching a live two-process session by eye.
        obs_trace.event("party_step", party=me, step=what)
        if debug:
            # mastic-allow: OB001 — interactive debug lever: the
            # whole point of MASTIC_PARTY_DEBUG is a human watching
            # stderr of a live subprocess; the span event above is
            # the scrapeable record
            print(f"[party {agg_id}] {what}", file=sys.stderr,
                  flush=True)

    def checkpoint(step: str) -> None:
        if injector is not None:
            injector.checkpoint(step)

    checkpoint("spawn")
    mastic = instantiate(cfg["mastic"])
    party = AggregatorParty(mastic, agg_id,
                            bytes.fromhex(cfg["verify_key"]),
                            bytes.fromhex(cfg["ctx"]))
    # Network-separated deployment realism (ISSUE 11): every link
    # this party sends on is paced by MASTIC_NET_SHAPE (bandwidth /
    # RTT / jitter) — each process parses the lever itself, exactly
    # like MASTIC_FAULTS, so one env var shapes the whole session.
    from ..net.transport import shape_from_env

    shaper = shape_from_env()
    trace("engine up, connecting"
          + (" (shaped link)" if shaper is not None else ""))

    coll = session_mod.connect(
        "127.0.0.1", cfg["collector_port"], "collector",
        config.connect_timeout, config.exchange_timeout, injector,
        shaper=shaper)
    try:
        _party_loop(party, coll, config, injector, trace, checkpoint,
                    shaper=shaper)
    except SessionError as err:
        trace(f"session error: {err}")
        nak = json.dumps({"party": err.party, "step": err.step,
                          "kind": err.kind,
                          "detail": err.detail}).encode()
        try:
            coll.send_msg(REPLY_NAK + nak, "nak")
        except SessionError:
            trace("collector unreachable for the error report")
        sys.exit(1)


def _party_loop(party: AggregatorParty, coll: Channel,
                config: SessionConfig, injector, trace,
                checkpoint, shaper=None) -> None:
    agg_id = party.agg_id
    coll.send_msg(bytes([agg_id]), "hello")

    peer = None
    try:
        if agg_id == 0:
            lst = socket.create_server(("127.0.0.1", 0))
            try:
                coll.send_msg(
                    lst.getsockname()[1].to_bytes(2, "little"),
                    "leader_port")
                trace("listening for helper")
                peer = session_mod.accept(lst, "helper",
                                          config.connect_timeout,
                                          config.exchange_timeout,
                                          injector, shaper=shaper)
            finally:
                lst.close()
        else:
            port_msg = coll.recv_msg("leader_port")
            if port_msg is None or len(port_msg) != 2:
                raise SessionError("collector", "leader_port",
                                   session_mod.KIND_CLOSED,
                                   "no leader port from collector")
            peer = session_mod.connect(
                "127.0.0.1", int.from_bytes(port_msg, "little"),
                "leader", config.connect_timeout,
                config.exchange_timeout, injector, shaper=shaper)
        trace("peer channel up")
        _command_loop(party, coll, peer, config, injector, trace,
                      checkpoint)
    finally:
        if peer is not None:
            peer.close()


def _command_loop(party: AggregatorParty, coll, peer,
                  config: SessionConfig, injector, trace,
                  checkpoint) -> None:
    """The command-driven protocol engine shared by the loopback
    spawn path (`_party_loop`) and the standalone network party
    (`tools/party.py`): upload / round / shutdown over whatever
    channel pair the caller built (plain or reliable, plaintext or
    mTLS)."""
    del injector  # faults reach this loop via `checkpoint` + channels
    agg_id = party.agg_id
    mastic = party.m
    while True:
        # Idle wait for the next command: bounded by the round
        # deadline, not the (shorter) exchange timeout — a collector
        # pacing rounds or retrying an upload is normal; a collector
        # that DIED closes the socket and lands here as None at once.
        msg = coll.recv_msg("command", timeout=config.round_deadline)
        if msg is None or msg[:1] == CMD_SHUTDOWN:
            trace("shutdown")
            break
        if msg[:1] == CMD_UPLOAD:  # upload
            if len(msg) < 2:
                raise SessionError("collector", "upload",
                                   session_mod.KIND_MALFORMED,
                                   "upload without a generation byte")
            gen = msg[1:2]   # echoed in the ack so a retried upload
            #                  cannot be satisfied by a stale ack
            body = msg[2:]
            try:
                blobs = _parse_upload_body(body)
                quarantined = party.load_reports(blobs)
            except (ValueError, EOFError) as exc:
                raise SessionError("collector", "upload",
                                   session_mod.KIND_MALFORMED,
                                   str(exc))
            checkpoint("reports_loaded")
            trace(f"loaded {len(party.reports)} reports "
                  f"({len(quarantined)} quarantined)")
            coll.send_msg(
                REPLY_ACK + gen + encode_quarantine(quarantined),
                "upload_ack")
        elif msg[:1] == CMD_ROUND:  # one aggregation round
            try:
                (encoded_param, mask) = decode_round_cmd(
                    msg, len(party.reports))
                agg_param = mastic.decode_agg_param(encoded_param)
            except (ValueError, EOFError) as exc:
                raise SessionError("collector", "agg_param",
                                   session_mod.KIND_MALFORMED,
                                   str(exc))
            checkpoint("round_start")
            trace(f"round level={agg_param[0]} compiling prep")
            blob = party.prep_blob(agg_param)
            checkpoint("prep_done")
            trace("prep done, exchanging")
            if agg_id == 1:
                peer.send_msg(blob, "prep_share")
                resolution = peer.recv_msg("resolution")
                if resolution is None:
                    raise SessionError("leader", "resolution",
                                       session_mod.KIND_CLOSED,
                                       "leader closed before the "
                                       "resolution")
                try:
                    accept = party.confirm(agg_param, resolution)
                except ValueError as exc:
                    raise SessionError("leader", "resolution",
                                       session_mod.KIND_MALFORMED,
                                       str(exc))
                accept &= ~mask
                checkpoint("confirm_done")
                # mastic-allow: SF004 — the aggregate share IS this
                # step's protocol message (the collector decodes it
                # with wire.decode_agg_share, the codec twin); only
                # the share bytes the draft specifies cross here
                coll.send_msg(
                    REPLY_ACK + party.agg_share(agg_param, accept),
                    "agg_share")
            else:
                peer_blob = peer.recv_msg("prep_share")
                if peer_blob is None:
                    raise SessionError("helper", "prep_share",
                                       session_mod.KIND_CLOSED,
                                       "helper closed before its "
                                       "prep share")
                try:
                    (accept, resolution) = party.resolve(
                        agg_param, peer_blob, exclude=mask)
                except ValueError as exc:
                    raise SessionError("helper", "prep_share",
                                       session_mod.KIND_MALFORMED,
                                       str(exc))
                checkpoint("resolve_done")
                peer.send_msg(resolution, "resolution")
                bitmap = np.packbits(accept,
                                     bitorder="little").tobytes()
                # mastic-allow: SF004 — accept bitmap + aggregate
                # share are this step's protocol message
                # (wire.decode_agg_share is the codec twin); nothing
                # beyond the draft's payload crosses here
                coll.send_msg(
                    REPLY_ACK + bitmap
                    + party.agg_share(agg_param, accept),
                    "agg_share")
            trace("round done")
        else:
            raise SessionError("collector", "command",
                               session_mod.KIND_PROTOCOL,
                               f"unknown command byte "
                               f"{msg[:1].hex()}")


def _parse_upload_body(body: bytes) -> list:
    if len(body) < 4:
        raise ValueError("malformed upload: truncated report count")
    (num,) = np.frombuffer(body[:4], np.uint32)
    rest = body[4:]
    blobs = []
    for i in range(int(num)):
        try:
            (blob, rest) = wire.unframe(rest)
        except ValueError as exc:
            raise ValueError(
                f"malformed upload: report frame {i} of {int(num)}: "
                f"{exc}")
        blobs.append(blob)
    if rest:
        raise ValueError(
            f"malformed upload: {len(rest)} trailing bytes after "
            f"the last report frame")
    return blobs


# -- collector side --------------------------------------------------

class ProcessCollector:
    """Spawns the two aggregator processes and drives rounds against
    them; the in-process analog is drivers/heavy_hitters.run_round.

    One spawn generation: a transport fault surfaces as a
    `SessionError` attributed to a party and step.  `respawn()` tears
    the pair down and rebuilds it (replaying the stored upload), which
    is how `AggregationSession` survives a crashed party.
    """

    def __init__(self, mastic: Mastic, mastic_spec: dict, ctx: bytes,
                 verify_key: bytes,
                 config: Optional[SessionConfig] = None,
                 faults_spec: Optional[str] = None,
                 connect: Optional[dict] = None, tls=None):
        self.m = mastic
        self.spec = mastic_spec
        self.ctx = ctx
        self.verify_key = verify_key
        self.config = config or SessionConfig.from_env()
        self.faults_spec = faults_spec
        # ISSUE 14 connect mode: parties are standalone network
        # processes (`tools/party.py serve`) instead of spawned
        # children — `connect` maps {"leader"/"helper"/"leader_peer"
        # -> (host, port)}, `tls` is a net.transport.TlsConfig (this
        # end's cert; peer names pinned per link).  Channels are
        # reliable (sequence-numbered acked frames, reconnect-and-
        # replay), and the verify-key-bearing party config crosses
        # the mTLS channel instead of a local stdin pipe.
        self.connect = connect
        self.tls = tls
        self.injector = (
            faults_mod.FaultInjector(
                faults_mod.parse_faults(faults_spec), "collector")
            if faults_spec is not None
            else faults_mod.injector_from_env("collector"))
        self.counters = {"timeouts": 0, "retries": 0, "respawns": 0,
                         "quarantined": 0, "reconnects": 0,
                         "replayed_frames": 0}
        self.quarantine: dict = {}       # report index -> reason code
        self.num_reports = 0
        self._upload_bodies: Optional[list] = None
        self._upload_gen = 0
        # Injected party faults are one-generation: a respawned pair
        # comes up clean (otherwise a kill-at-step fault would kill
        # every respawn and recovery could never be tested or used).
        self._arm_child_faults = True
        # The collector's own sends ride the same shaped link the
        # parties arm from MASTIC_NET_SHAPE (upload bodies are the
        # largest payloads of a session — the crossover bench needs
        # them paced too).
        from ..net.transport import shape_from_env
        self.shaper = shape_from_env()
        self.procs: list = []
        self.server: Optional[socket.socket] = None
        self.leader: Optional[Channel] = None
        self.helper: Optional[Channel] = None
        try:
            self._spawn()
        except SessionError:
            # A failed handshake must not leak the surviving party
            # process or the server port.
            self._teardown(kill=True)
            raise

    # -- spawn / teardown / respawn --------------------------------

    def _spawn(self) -> None:
        if self.connect is not None:
            self._connect_parties()
            return
        cfg = self.config
        self.server = socket.create_server(("127.0.0.1", 0))
        port = self.server.getsockname()[1]
        env_cfg = {"mastic": self.spec, "ctx": self.ctx.hex(),
                   "verify_key": self.verify_key.hex(),
                   "collector_port": port}
        env = {**os.environ, **self.config.child_env()}
        if self.faults_spec is not None and self._arm_child_faults:
            env["MASTIC_FAULTS"] = self.faults_spec
        else:
            env.pop("MASTIC_FAULTS", None)
        # The party config (which binds the VERIFY KEY) crosses on
        # the child's private stdin pipe, NOT argv: every local user
        # can read /proc/<pid>/cmdline, so key material in argv was a
        # real leak (the whole-program SF004 rule found it; this is
        # the fix).
        self.procs = [
            subprocess.Popen(
                [sys.executable, "-m", "mastic_tpu.drivers.parties"],
                cwd=_repo_root(), env=env, stdin=subprocess.PIPE,
                stdout=sys.stderr, stderr=sys.stderr)
            for agg_id in range(2)
        ]
        for (agg_id, proc) in enumerate(self.procs):
            blob = (json.dumps({**env_cfg, "agg_id": agg_id})
                    + "\n").encode()
            try:
                # mastic-allow: SF004 — the key-bearing config leaves
                # the process over the child's PRIVATE stdin pipe
                # (mode 0600, no /proc exposure) — this IS the
                # sanctioned replacement for the old argv handoff
                proc.stdin.write(blob)
                proc.stdin.flush()
                proc.stdin.close()
            except OSError as exc:
                # A party dead before reading its config: attribute
                # now instead of waiting out the handshake accept.
                raise SessionError(
                    "leader" if agg_id == 0 else "helper", "spawn",
                    session_mod.KIND_CRASHED,
                    f"config handoff failed: {exc}")
        chans: dict = {}
        for _ in range(2):
            try:
                chan = session_mod.accept(
                    self.server, "party", cfg.connect_timeout,
                    cfg.exchange_timeout, self.injector,
                    shaper=self.shaper)
            except SessionError as err:
                raise self._attributed(err)
            # The accepted channel closes on every raise out of the
            # hello exchange (RL001) — a malformed peer must not
            # strand its fd on the runner.
            try:
                try:
                    hello = chan.recv_msg("hello")
                except SessionError as err:
                    raise self._attributed(err)
                if hello is None or len(hello) != 1 \
                        or hello[0] not in (0, 1):
                    raise SessionError(
                        "party", "hello", session_mod.KIND_MALFORMED,
                        f"bad hello {hello!r}")
                if hello[0] in chans:
                    raise SessionError(
                        "leader" if hello[0] == 0 else "helper",
                        "hello", session_mod.KIND_PROTOCOL,
                        "duplicate hello")
                chan.remote = "leader" if hello[0] == 0 else "helper"
                chans[hello[0]] = chan
            except BaseException:
                chan.close()
                raise
        (self.leader, self.helper) = (chans[0], chans[1])
        try:
            leader_port = self.leader.recv_msg("leader_port")
        except SessionError as err:
            raise self._attributed(err)
        if leader_port is None:
            raise SessionError("leader", "leader_port",
                               session_mod.KIND_CLOSED,
                               "leader closed before sending its "
                               "peer port")
        self.helper.send_msg(leader_port, "leader_port")

    def _connect_parties(self) -> None:
        """The ISSUE 14 deployment shape: dial each standalone party
        over the reliable (mTLS) transport and hand it its session
        config as the first framed message — hello comes back on the
        same authenticated channel."""
        from .session import reliable_connect

        cfg = self.config
        base = {"mastic": self.spec, "ctx": self.ctx.hex(),
                "verify_key": self.verify_key.hex()}
        if self.faults_spec is not None and self._arm_child_faults:
            base["faults"] = self.faults_spec
        chans: dict = {}
        try:
            for (agg_id, name) in ((0, "leader"), (1, "helper")):
                (host, port) = self.connect[name]
                chan = reliable_connect(
                    host, int(port), name, cfg, tls=self.tls,
                    injector=self.injector, shaper=self.shaper,
                    deadline=Deadline(cfg.round_deadline))
                chans[agg_id] = chan
                party_cfg = dict(base, agg_id=agg_id)
                if agg_id == 1:
                    (ph, pp) = self.connect["leader_peer"]
                    party_cfg["peer"] = [ph, int(pp)]
                # mastic-allow: SF004 — the key-bearing config
                # crosses the mutually-authenticated (mTLS, CA-
                # pinned, name-checked) session channel — the
                # sanctioned network replacement for the local
                # stdin-pipe handoff the spawn path uses
                chan.send_msg(json.dumps(party_cfg).encode(),
                              "config")
                hello = chan.recv_msg(
                    "hello", timeout=cfg.connect_timeout)
                if hello != bytes([agg_id]):
                    raise SessionError(
                        name, "hello", session_mod.KIND_PROTOCOL,
                        f"bad hello {hello!r} from {host}:{port}")
        except SessionError:
            for chan in chans.values():
                chan.close()
            raise
        (self.leader, self.helper) = (chans[0], chans[1])

    def _fold_reliability(self) -> None:
        """Fold the live channels' recovery counters into the
        session-cumulative ledger before the channels are dropped
        (teardown/respawn), so attribution survives the channels."""
        for chan in (self.leader, self.helper):
            if chan is not None:
                self.counters["reconnects"] += \
                    getattr(chan, "reconnects", 0)
                self.counters["replayed_frames"] += \
                    getattr(chan, "replayed_frames", 0)

    def _teardown(self, kill: bool = False) -> None:
        self._fold_reliability()
        for chan in (self.leader, self.helper):
            if chan is not None:
                chan.close()
        (self.leader, self.helper) = (None, None)
        for proc in self.procs:
            if proc.poll() is None:
                if kill:
                    proc.kill()
                else:
                    proc.terminate()
                try:
                    proc.wait(timeout=self.config.shutdown_timeout)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
        self.procs = []
        if self.server is not None:
            self.server.close()
            self.server = None

    def respawn(self) -> None:
        """Kill and rebuild the party pair, replaying the stored
        upload — the crash-recovery path.  Prep state is recomputed
        from the replayed reports, so a rerun round is bit-identical
        to an unfaulted one."""
        self.counters["respawns"] += 1
        self._teardown(kill=True)
        self._arm_child_faults = False
        try:
            self._spawn()
        except SessionError:
            self._teardown(kill=True)
            raise
        if self._upload_bodies is not None:
            self._send_upload()

    def reliability_counters(self) -> dict:
        """Session-cumulative transport recovery attribution: folded
        counts from torn-down channels plus the live channels'."""
        out = {"reconnects": self.counters["reconnects"],
               "replayed_frames": self.counters["replayed_frames"]}
        for chan in (self.leader, self.helper):
            if chan is not None:
                out["reconnects"] += getattr(chan, "reconnects", 0)
                out["replayed_frames"] += \
                    getattr(chan, "replayed_frames", 0)
        return out

    def wire_bytes(self) -> dict:
        """Measured collector-side wire traffic (the Channel
        counters).  Party<->party prep-exchange bytes are invisible
        from here; `metrics.count_round_bytes`' model covers those —
        the crossover bench stamps both."""
        out = {"sent": 0, "received": 0}
        for chan in (self.leader, self.helper):
            if chan is not None:
                out["sent"] += chan.sent_bytes
                out["received"] += chan.recv_bytes
        return out

    def _party_status(self) -> str:
        out = []
        for (name, proc) in zip(("leader", "helper"), self.procs):
            rc = proc.poll()
            out.append(f"{name}: "
                       + ("running" if rc is None
                          else f"exited rc={rc}"))
        return "; ".join(out) if out else "no processes"

    def _attributed(self, err: SessionError) -> SessionError:
        """Sharpen a transport error with process liveness: a timeout
        whose party is dead becomes a crash, attributed to the dead
        party.  A party that exited rc=1 NAKed a structured error of
        its own first — a harder death (kill, signal, injected exit)
        is the better root cause when both are down."""
        if err.kind in (session_mod.KIND_CLOSED,
                        session_mod.KIND_TIMEOUT):
            # A dying party closes its socket an instant before the
            # kernel reaps it — give poll() a short grace window so
            # the crash is attributed as a crash, not a closed chan.
            grace = Deadline(0.5)
            while not grace.expired() \
                    and all(p.poll() is None for p in self.procs):
                time.sleep(0.02)
        dead = [(name, rc)
                for (name, proc) in zip(("leader", "helper"),
                                        self.procs)
                for rc in [proc.poll()]
                if rc is not None and rc != 0
                and err.party in (name, "party")]
        if dead:
            hard = [d for d in dead if d[1] != 1]
            (name, rc) = hard[0] if hard else dead[0]
            return SessionError(
                name, err.step, session_mod.KIND_CRASHED,
                f"party process exited rc={rc} ({err.detail})")
        if err.kind == session_mod.KIND_TIMEOUT:
            self.counters["timeouts"] += 1
        return SessionError(err.party, err.step, err.kind,
                            f"{err.detail} [{self._party_status()}]")

    # -- upload ----------------------------------------------------

    def upload(self, reports: list) -> None:
        """reports: [(nonce, public_share, input_shares)] with BOTH
        input shares (the collector here doubles as the upload relay —
        clients talk to aggregators directly in a real deployment).
        Malformed report blobs are quarantined per report (reason
        codes in `self.quarantine`), not fatal; the upload exchange
        retries with backoff (it is idempotent: parties reload the
        batch wholesale)."""
        self.num_reports = len(reports)
        bodies = []
        for agg_id in range(2):
            blobs = []
            for (nonce, ps, shares) in reports:
                blob = wire.encode_report(self.m, agg_id, nonce, ps,
                                          shares[agg_id])
                if self.injector is not None:
                    blob = self.injector.split_report_blob(
                        "upload_report", blob)
                blobs.append(blob)
            bodies.append(np.uint32(len(blobs)).tobytes()
                          + b"".join(wire.frame(b) for b in blobs))
        self._upload_bodies = bodies
        self._send_upload()

    def upload_encoded(self, bodies: list, num_reports: int) -> None:
        """Replay path (AggregationSession resume): upload
        pre-encoded per-party bodies verbatim."""
        self.num_reports = num_reports
        self._upload_bodies = list(bodies)
        self._send_upload()

    def _send_upload(self) -> None:
        cfg = self.config
        # The whole retry ladder shares one round-deadline budget:
        # with_retries clamps each backoff sleep to what remains and
        # fails fast (attributed) once it is gone, so a retried
        # upload cannot overrun the round budget by the backoff.
        deadline = Deadline(cfg.round_deadline)

        def attempt():
            self.quarantine = {}
            self._upload_gen = (self._upload_gen + 1) % 256
            gen = bytes([self._upload_gen])
            try:
                for (chan, body) in ((self.leader,
                                      self._upload_bodies[0]),
                                     (self.helper,
                                      self._upload_bodies[1])):
                    chan.send_msg(CMD_UPLOAD + gen + body, "upload")
                for chan in (self.leader, self.helper):
                    ack = self._recv_ack(chan, gen)
                    for (idx, reason) in decode_quarantine(ack):
                        self.quarantine[idx] = reason
            except SessionError as err:
                raise self._attributed(err)

        with_retries(attempt, cfg.retries, cfg.backoff,
                     on_retry=self._on_retry, deadline=deadline)
        self.counters["quarantined"] = len(self.quarantine)
        if len(self.quarantine) >= self.num_reports \
                and self.num_reports > 0:
            reasons = {k: REASON_NAMES.get(v, v)
                       for (k, v) in sorted(self.quarantine.items())}
            raise SessionError(
                "collector", "upload", session_mod.KIND_PROTOCOL,
                f"all {self.num_reports} reports quarantined "
                f"(reasons: {reasons})")

    def _on_retry(self, err: SessionError, attempt: int) -> None:
        self.counters["retries"] += 1

    def quarantine_mask(self) -> np.ndarray:
        mask = np.zeros(self.num_reports, bool)
        for idx in self.quarantine:
            if idx < self.num_reports:
                mask[idx] = True
        return mask

    def _recv_ack(self, chan: Channel, gen: bytes) -> bytes:
        """One upload ack matching this attempt's generation byte; a
        stale ack from a timed-out earlier attempt is discarded (the
        resend is idempotent, but its ack must not be double-read)."""
        deadline = Deadline(self.config.ack_timeout)
        while True:
            ack = self._recv_reply(chan, "upload_ack", deadline,
                                   timeout=self.config.ack_timeout)
            if len(ack) < 1:
                raise SessionError(chan.remote, "upload_ack",
                                   session_mod.KIND_MALFORMED,
                                   "empty upload ack")
            if ack[:1] == gen:
                return ack[1:]
            # stale generation: drop and keep the window open

    def _recv_reply(self, chan: Channel, step: str,
                    deadline: Optional[Deadline] = None,
                    timeout: Optional[float] = None) -> bytes:
        """One ACK payload; a NAK raises the party's own structured
        error (attribution without waiting out the deadline)."""
        msg = chan.recv_msg(step, deadline, timeout)
        if msg is None:
            raise SessionError(chan.remote, step,
                               session_mod.KIND_CLOSED,
                               "party closed the channel")
        if msg[:1] == REPLY_NAK:
            try:
                err = json.loads(msg[1:])
            except ValueError:
                raise SessionError(chan.remote, step,
                                   session_mod.KIND_MALFORMED,
                                   "unparsable NAK")
            raise SessionError(
                err.get("party", chan.remote), err.get("step", step),
                err.get("kind", session_mod.KIND_PROTOCOL),
                f"(reported by {chan.remote}) {err.get('detail', '')}")
        if msg[:1] != REPLY_ACK:
            raise SessionError(chan.remote, step,
                               session_mod.KIND_MALFORMED,
                               f"bad reply prefix {msg[:1].hex()}")
        return msg[1:]

    # -- rounds ----------------------------------------------------

    def round(self, agg_param,
              metrics_out: Optional[list] = None) -> tuple:
        """Run one aggregation round under the session deadline;
        returns (agg_result, accept, (leader share, helper share)).
        Timeout/retry/quarantine/respawn counters land in a
        RoundMetrics appended to `metrics_out`."""
        cfg = self.config
        deadline = Deadline(cfg.round_deadline)
        encoded = encode_round_cmd(self.m.encode_agg_param(agg_param),
                                   self.quarantine_mask())
        try:
            self.leader.send_msg(encoded, "agg_param", deadline)
            self.helper.send_msg(encoded, "agg_param", deadline)
            # Round replies are governed by the round deadline alone:
            # a party legitimately spends minutes in prep compile, and
            # a party-side fault reaches us earlier as a NAK anyway.
            leader_msg = self._recv_reply(
                self.leader, "agg_share", deadline,
                timeout=cfg.round_deadline)
            helper_msg = self._recv_reply(
                self.helper, "agg_share", deadline,
                timeout=cfg.round_deadline)
        except SessionError as err:
            raise self._attributed(err)
        # leader payload: accept bitmap + agg share
        share_size = wire.agg_share_size(self.m, agg_param)
        nbytes = len(leader_msg) - share_size
        if nbytes != (self.num_reports + 7) // 8 \
                or len(helper_msg) != share_size:
            raise SessionError(
                "leader" if nbytes != (self.num_reports + 7) // 8
                else "helper",
                "agg_share", session_mod.KIND_MALFORMED,
                f"malformed round payload: leader sent "
                f"{len(leader_msg)} bytes (want bitmap "
                f"{(self.num_reports + 7) // 8} + share {share_size}),"
                f" helper sent {len(helper_msg)} (want {share_size})")
        accept = np.unpackbits(
            np.frombuffer(leader_msg[:nbytes], np.uint8),
            bitorder="little")[:self.num_reports].astype(bool)
        accept &= ~self.quarantine_mask()
        agg0 = wire.decode_agg_share(self.m, agg_param,
                                     leader_msg[nbytes:])
        agg1 = wire.decode_agg_share(self.m, agg_param, helper_msg)
        num = int(accept.sum())
        result = self.m.unshard(agg_param, [agg0, agg1], num)
        if metrics_out is not None:
            metrics_out.append(self.round_metrics(agg_param, accept))
        return (result, accept, (leader_msg[nbytes:], helper_msg))

    def round_metrics(self, agg_param,
                      accept: np.ndarray) -> RoundMetrics:
        """Session-cumulative fault counters + this round's verdict
        and channel bytes (the process-separated driver cannot
        attribute rejections to a specific check — the leader only
        ships the final bitmap)."""
        (level, prefixes, _wc) = agg_param
        metrics = RoundMetrics(level=level,
                               frontier_width=len(prefixes),
                               padded_width=len(prefixes),
                               reports_total=self.num_reports)
        metrics.accepted = int(np.asarray(accept, bool).sum())
        metrics.timeouts = self.counters["timeouts"]
        metrics.retries = self.counters["retries"]
        metrics.respawns = self.counters["respawns"]
        metrics.quarantined = self.counters["quarantined"]
        rel = self.reliability_counters()
        metrics.reconnects = rel["reconnects"]
        metrics.replayed_frames = rel["replayed_frames"]
        count_round_bytes(metrics, self.m, agg_param,
                          self.num_reports)
        metrics.extra["process_separated"] = True
        metrics.extra["quarantine"] = {
            str(idx): REASON_NAMES.get(code, code)
            for (idx, code) in sorted(self.quarantine.items())}
        return metrics

    # -- teardown --------------------------------------------------

    def close(self) -> None:
        """Graceful shutdown hardened against hung parties: a party
        that ignores CMD_SHUTDOWN is terminated, then killed; the
        server socket closes in a finally so a wedged party can
        neither leak the port nor hang teardown."""
        try:
            for chan in (self.leader, self.helper):
                if chan is None:
                    continue
                try:
                    chan.send_msg(CMD_SHUTDOWN, "shutdown")
                except SessionError:
                    # A party that died earlier cannot ack shutdown;
                    # count it so teardown stays observable.
                    self.counters["shutdown_errors"] = \
                        self.counters.get("shutdown_errors", 0) + 1
            for proc in self.procs:
                try:
                    proc.wait(timeout=self.config.shutdown_timeout)
                except subprocess.TimeoutExpired:
                    proc.terminate()
                    try:
                        proc.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait()
        finally:
            for chan in (self.leader, self.helper):
                if chan is not None:
                    chan.close()
            if self.server is not None:
                self.server.close()
                self.server = None


# -- supervised sessions: retry, respawn, snapshot, resume ------------

_SNAPSHOT_VERSION = 1


class AggregationSession:
    """A supervised collection session over a ProcessCollector.

    Adds the fault-tolerance policy on top of the mechanics: a failed
    round (timeout, crash, malformed exchange) respawns the party
    pair, replays the upload, and reruns the round — prep shares are
    pure functions of the replayed reports, so the rerun aggregate is
    bit-identical to an unfaulted run.  Completed rounds snapshot at
    round boundaries (`to_bytes`), and `from_bytes` resumes a session
    after a collector crash: it respawns parties, replays the stored
    upload bodies, and replays completed rounds from the snapshot
    instead of re-running them.
    """

    def __init__(self, mastic: Mastic, mastic_spec: dict, ctx: bytes,
                 verify_key: bytes,
                 config: Optional[SessionConfig] = None,
                 faults_spec: Optional[str] = None,
                 connect: Optional[dict] = None, tls=None):
        self.m = mastic
        self.spec = mastic_spec
        self.ctx = ctx
        self.verify_key = verify_key
        self.config = config or SessionConfig.from_env()
        self.coll = ProcessCollector(mastic, mastic_spec, ctx,
                                     verify_key, self.config,
                                     faults_spec, connect=connect,
                                     tls=tls)
        # [(encoded agg param, result, accept, (share0, share1))]
        self.completed: list = []
        self._replay_index = 0

    @property
    def counters(self) -> dict:
        return self.coll.counters

    def upload(self, reports: list) -> None:
        self.coll.upload(reports)

    def round(self, agg_param,
              metrics_out: Optional[list] = None) -> tuple:
        """One round with bounded retry: a retryable SessionError
        respawns the pair (replaying the upload) and reruns the
        round.  A snapshot-resumed session replays completed rounds
        from the snapshot (same agg params, in order) without
        touching the parties."""
        encoded = self.m.encode_agg_param(agg_param)
        if self._replay_index < len(self.completed):
            (saved_param, result, accept, shares) = \
                self.completed[self._replay_index]
            if saved_param != encoded:
                raise SessionError(
                    "collector", "agg_param",
                    session_mod.KIND_PROTOCOL,
                    "resumed session replayed a different agg param "
                    "than the snapshot recorded")
            self._replay_index += 1
            if metrics_out is not None:
                metrics_out.append(
                    self.coll.round_metrics(agg_param, accept))
            return (result, accept, shares)

        attempt = 0
        while True:
            try:
                (result, accept, shares) = self.coll.round(
                    agg_param, metrics_out=metrics_out)
                break
            except SessionError as err:
                if not err.retryable() \
                        or attempt >= self.config.retries:
                    raise
                self.coll.counters["retries"] += 1
                attempt += 1
                self.coll.respawn()
        self.completed.append((encoded, result, accept, shares))
        self._replay_index = len(self.completed)
        return (result, accept, shares)

    def close(self) -> None:
        self.coll.close()

    # -- snapshot / resume (northstar.py checkpoint header pattern:
    #    length-prefixed JSON binding header + npz payload) ---------

    def to_bytes(self) -> bytes:
        import io

        header = json.dumps({
            "version": _SNAPSHOT_VERSION,
            "spec": self.spec,
            "ctx": self.ctx.hex(),
            "verify_key": self.verify_key.hex(),
        }, sort_keys=True).encode()
        data: dict = {
            "meta": np.array([_SNAPSHOT_VERSION,
                              self.coll.num_reports,
                              len(self.completed)], np.int64),
        }
        bodies = self.coll._upload_bodies or [b"", b""]
        for (i, body) in enumerate(bodies):
            data[f"upload_{i}"] = np.frombuffer(body, np.uint8)
        for (i, (param, result, accept, shares)) in \
                enumerate(self.completed):
            data[f"r{i}_param"] = np.frombuffer(param, np.uint8)
            data[f"r{i}_result"] = np.frombuffer(
                json.dumps(result).encode(), np.uint8)
            data[f"r{i}_accept"] = np.asarray(accept, bool)
            data[f"r{i}_share0"] = np.frombuffer(shares[0], np.uint8)
            data[f"r{i}_share1"] = np.frombuffer(shares[1], np.uint8)
        buf = io.BytesIO()
        np.savez(buf, **data)
        return (len(header).to_bytes(4, "little") + header
                + buf.getvalue())

    @classmethod
    def from_bytes(cls, data: bytes,
                   config: Optional[SessionConfig] = None,
                   faults_spec: Optional[str] = None
                   ) -> "AggregationSession":
        import io

        hlen = int.from_bytes(data[:4], "little")
        try:
            header = json.loads(data[4:4 + hlen])
        except ValueError:
            raise ValueError(
                "session snapshot has no JSON binding header — not a "
                "snapshot written by AggregationSession.to_bytes")
        if header.get("version") != _SNAPSHOT_VERSION:
            raise ValueError(
                f"unknown session snapshot version "
                f"{header.get('version')}")
        arrays = np.load(io.BytesIO(data[4 + hlen:]),
                         allow_pickle=False)
        (_version, num_reports, num_rounds) = \
            [int(x) for x in arrays["meta"]]
        mastic = instantiate(header["spec"])
        sess = cls(mastic, header["spec"],
                   bytes.fromhex(header["ctx"]),
                   bytes.fromhex(header["verify_key"]),
                   config=config, faults_spec=faults_spec)
        bodies = [arrays["upload_0"].tobytes(),
                  arrays["upload_1"].tobytes()]
        if num_reports:
            sess.coll.upload_encoded(bodies, num_reports)
        for i in range(num_rounds):
            sess.completed.append((
                arrays[f"r{i}_param"].tobytes(),
                json.loads(arrays[f"r{i}_result"].tobytes()),
                np.asarray(arrays[f"r{i}_accept"], bool),
                (arrays[f"r{i}_share0"].tobytes(),
                 arrays[f"r{i}_share1"].tobytes()),
            ))
        sess._replay_index = 0
        return sess


def _repo_root() -> str:
    import pathlib
    return str(pathlib.Path(__file__).resolve().parents[2])


if __name__ == "__main__":
    party_main(sys.argv[1:])
