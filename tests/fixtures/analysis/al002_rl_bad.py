"""Known-bad: stale suppression on the CFG-era rules (AL002) — the
leak this allow once excused was fixed, but the allow stayed behind."""


def fine(make):
    sock = make()
    try:
        sock.settimeout(5)
        return sock
    except BaseException:
        # mastic-allow: RL001, EV001 — historical leak, since fixed
        sock.close()
        raise
