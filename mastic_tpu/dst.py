"""Domain-separation tags for every XOF usage (reference poc/dst.py).

Kept in one module so distinctness is auditable at a glance.
"""

from .common import byte, to_be_bytes

# Version of the Mastic document; 0 until adoption.
VERSION: int = 0

# Mastic usages.
USAGE_PROVE_RAND: int = 0
USAGE_PROOF_SHARE: int = 1
USAGE_QUERY_RAND: int = 2
USAGE_JOINT_RAND_SEED: int = 3
USAGE_JOINT_RAND_PART: int = 4
USAGE_JOINT_RAND: int = 5
USAGE_ONEHOT_CHECK: int = 6
USAGE_PAYLOAD_CHECK: int = 7
USAGE_EVAL_PROOF: int = 8

# VIDPF usages.
USAGE_NODE_PROOF: int = 9
USAGE_EXTEND: int = 10
USAGE_CONVERT: int = 11


def dst(ctx: bytes, usage: int) -> bytes:
    assert usage in range(12)
    return b"mastic" + byte(VERSION) + byte(usage) + ctx


def dst_alg(ctx: bytes, usage: int, algorithm_id: int) -> bytes:
    assert usage in range(12)
    assert algorithm_id in range(2 ** 32)
    return b"mastic" + byte(VERSION) + byte(usage) \
        + to_be_bytes(algorithm_id, 4) + ctx
