"""Known-bad (ISSUE 14, TLS flavor): an ssl handshake driven with no
armed deadline (RB001) — a dialer that connects and then goes silent
mid-handshake wedges this thread exactly like a bare recv (the
`tls_handshake` chaos checkpoint models precisely this stall)."""


class Listener:
    def accept_tls(self, ctx):
        (conn, _addr) = self.sock.accept()
        tls = ctx.wrap_socket(conn, server_side=True,
                              do_handshake_on_connect=False)
        tls.do_handshake()
        return tls
