"""Fully Linear Proof system of [BBCGGI19], as profiled by
draft-irtf-cfrg-vdaf-13 §7.3 (`FlpBBCGGI19`).

Replaces `vdaf_poc.flp_bbcggi19` as consumed by the Mastic composition
(/root/reference/poc/mastic.py:9-10, :125, :250, :349).  The prover
evaluates the validity circuit while recording every gadget's wire
inputs; each wire becomes a polynomial interpolated over a power-of-two
NTT domain, and the proof carries the wire seeds plus the composed
gadget polynomial's coefficients.  The verifier re-evaluates the
circuit using the gadget polynomial in place of the gadget and spot
checks wire/gadget consistency at a random point.

Parameters are pinned by the measured constants of SURVEY.md §2.4
(e.g. Count: PROOF_LEN 5, verifier 4; Sum(max=7): PROOF_LEN 16,
verifier 3) and byte-locked by the conformance vectors.
"""

from typing import Generic, TypeVar

from ..common import front, next_power_of_2
from ..field import F, poly_add, poly_eval, poly_interp, poly_mul

W = TypeVar("W")  # measurement type
R = TypeVar("R")  # aggregate result type


class Gadget(Generic[F]):
    """A non-linear subcircuit: low arity and degree, called many times."""

    ARITY: int
    DEGREE: int

    def eval(self, field: type[F], inp: list[F]) -> F:
        raise NotImplementedError()

    def eval_poly(self, field: type[F], inp_poly: list[list[F]]) \
            -> list[F]:
        """Evaluate over polynomial inputs (coefficient vectors)."""
        raise NotImplementedError()


class Mul(Gadget[F]):
    ARITY = 2
    DEGREE = 2

    def eval(self, field: type[F], inp: list[F]) -> F:
        return inp[0] * inp[1]

    def eval_poly(self, field: type[F], inp_poly: list[list[F]]) -> list[F]:
        return poly_mul(field, inp_poly[0], inp_poly[1])


class PolyEval(Gadget[F]):
    """Gadget evaluating a fixed univariate polynomial `p` (list of int
    coefficients, low-to-high)."""

    ARITY = 1

    def __init__(self, p: list[int]):
        assert len(p) >= 2
        self.p = p
        self.DEGREE = len(p) - 1

    def eval(self, field: type[F], inp: list[F]) -> F:
        return poly_eval(field, [field(c % field.MODULUS) for c in self.p],
                         inp[0])

    def eval_poly(self, field: type[F], inp_poly: list[list[F]]) -> list[F]:
        out = [field(self.p[-1] % field.MODULUS)]
        for coeff in reversed(self.p[:-1]):
            out = poly_mul(field, out, inp_poly[0])
            if not out:
                out = [field(0)]
            out[0] += field(coeff % field.MODULUS)
        return out


class ParallelSum(Gadget[F]):
    """Sum of `count` invocations of a subgadget on disjoint inputs."""

    def __init__(self, subcircuit: Gadget[F], count: int):
        self.subcircuit = subcircuit
        self.count = count
        self.ARITY = subcircuit.ARITY * count
        self.DEGREE = subcircuit.DEGREE

    def eval(self, field: type[F], inp: list[F]) -> F:
        out = field(0)
        for i in range(self.count):
            start = i * self.subcircuit.ARITY
            out += self.subcircuit.eval(
                field, inp[start:start + self.subcircuit.ARITY])
        return out

    def eval_poly(self, field: type[F], inp_poly: list[list[F]]) -> list[F]:
        out: list[F] = []
        for i in range(self.count):
            start = i * self.subcircuit.ARITY
            term = self.subcircuit.eval_poly(
                field, inp_poly[start:start + self.subcircuit.ARITY])
            out = poly_add(field, out, term)
        return out


class Valid(Generic[W, R, F]):
    """A validity circuit: an arithmetic circuit over gadgets plus the
    measurement encoding/truncation/decoding maps."""

    field: type[F]
    MEAS_LEN: int
    OUTPUT_LEN: int
    JOINT_RAND_LEN: int
    EVAL_OUTPUT_LEN: int
    GADGETS: list[Gadget[F]]
    GADGET_CALLS: list[int]

    def encode(self, measurement: W) -> list[F]:
        raise NotImplementedError()

    def truncate(self, meas: list[F]) -> list[F]:
        raise NotImplementedError()

    def decode(self, output: list[F], num_measurements: int) -> R:
        raise NotImplementedError()

    def eval(self, meas: list[F], joint_rand: list[F],
             num_shares: int) -> list[F]:
        """Evaluate the circuit; gadget calls go through self.GADGETS
        (which prove/query wrap to record or replace wire values)."""
        raise NotImplementedError()

    def check_valid_eval(self, meas: list[F], joint_rand: list[F]) -> None:
        assert len(meas) == self.MEAS_LEN
        assert len(joint_rand) == self.JOINT_RAND_LEN

    def test_vec_set_type_param(self, test_vec: dict) -> list[str]:
        return []


class _ProveGadget(Gadget[F]):
    """Wraps a gadget during proof generation: seeds each wire with a
    prove_rand element at domain point alpha^0 and records the inputs of
    call k at alpha^(k+1)."""

    def __init__(self, field: type[F], wire_seeds: list[F],
                 inner: Gadget[F], calls: int):
        self.inner = inner
        self.ARITY = inner.ARITY
        self.DEGREE = inner.DEGREE
        p = next_power_of_2(calls + 1)
        self.wires = [[field(0)] * p for _ in range(inner.ARITY)]
        for (j, seed) in enumerate(wire_seeds):
            self.wires[j][0] = seed
        self.k = 0

    def eval(self, field: type[F], inp: list[F]) -> F:
        self.k += 1
        for j in range(self.ARITY):
            self.wires[j][self.k] = inp[j]
        return self.inner.eval(field, inp)


class _QueryGadget(Gadget[F]):
    """Wraps a gadget during query: records wire inputs and returns the
    (prover-supplied) gadget polynomial evaluated at alpha^(k+1)."""

    def __init__(self, field: type[F], wire_seeds: list[F],
                 gadget_poly: list[F], inner: Gadget[F], calls: int):
        self.ARITY = inner.ARITY
        self.DEGREE = inner.DEGREE
        p = next_power_of_2(calls + 1)
        self.wires = [[field(0)] * p for _ in range(inner.ARITY)]
        for (j, seed) in enumerate(wire_seeds):
            self.wires[j][0] = seed
        # The gadget polynomial has degree DEGREE*(p-1) (larger than the
        # size-p wire domain), so it is evaluated pointwise at the call
        # points alpha^(k+1), lazily as calls arrive.
        self.gadget_poly = gadget_poly
        self.alpha = field.gen() ** (field.GEN_ORDER // p)
        self.k = 0

    def eval(self, field: type[F], inp: list[F]) -> F:
        self.k += 1
        for j in range(self.ARITY):
            self.wires[j][self.k] = inp[j]
        return poly_eval(field, self.gadget_poly, self.alpha ** self.k)


class FlpBBCGGI19(Generic[W, R, F]):
    """The [BBCGGI19] FLP for a given validity circuit."""

    def __init__(self, valid: Valid[W, R, F]):
        self.valid = valid
        self.field: type[F] = valid.field
        self.MEAS_LEN = valid.MEAS_LEN
        self.OUTPUT_LEN = valid.OUTPUT_LEN
        self.JOINT_RAND_LEN = valid.JOINT_RAND_LEN
        self.PROVE_RAND_LEN = sum(g.ARITY for g in valid.GADGETS)
        # One independent reduction weight per circuit output (when
        # there is more than one), plus one spot-check point per gadget.
        self.QUERY_RAND_LEN = len(valid.GADGETS)
        if valid.EVAL_OUTPUT_LEN > 1:
            self.QUERY_RAND_LEN += valid.EVAL_OUTPUT_LEN
        self.PROOF_LEN = 0
        for (g, calls) in zip(valid.GADGETS, valid.GADGET_CALLS):
            p = next_power_of_2(calls + 1)
            self.PROOF_LEN += g.ARITY + g.DEGREE * (p - 1) + 1
        self.VERIFIER_LEN = 1 + sum(g.ARITY + 1 for g in valid.GADGETS)

    # -- prover ----------------------------------------------------

    def prove(self, meas: list[F], prove_rand: list[F],
              joint_rand: list[F]) -> list[F]:
        if len(prove_rand) != self.PROVE_RAND_LEN:
            raise ValueError("incorrect prove randomness length")
        field = self.field

        # Wrap each gadget so the circuit evaluation records wire inputs.
        wrapped: list[_ProveGadget[F]] = []
        rest = prove_rand
        for (g, calls) in zip(self.valid.GADGETS, self.valid.GADGET_CALLS):
            (seeds, rest) = front(g.ARITY, rest)
            wrapped.append(_ProveGadget(field, list(seeds), g, calls))
        saved = self.valid.GADGETS
        self.valid.GADGETS = wrapped  # type: ignore[assignment]
        try:
            self.valid.eval(meas, joint_rand, 1)
        finally:
            self.valid.GADGETS = saved

        # Assemble the proof: per gadget, the wire seeds followed by the
        # coefficients of the composed gadget polynomial.
        proof: list[F] = []
        for (wg, inner, calls) in zip(wrapped, saved,
                                      self.valid.GADGET_CALLS):
            p = next_power_of_2(calls + 1)
            wire_polys = [poly_interp(field, wire) for wire in wg.wires]
            gadget_poly = inner.eval_poly(field, wire_polys)
            coeff_len = inner.DEGREE * (p - 1) + 1
            coeffs = list(gadget_poly) + \
                [field(0)] * (coeff_len - len(gadget_poly))
            proof += [wire[0] for wire in wg.wires]
            proof += coeffs[:coeff_len]
        return proof

    # -- verifier --------------------------------------------------

    def query(self, meas: list[F], proof: list[F], query_rand: list[F],
              joint_rand: list[F], num_shares: int) -> list[F]:
        if len(proof) != self.PROOF_LEN:
            raise ValueError("incorrect proof length")
        if len(query_rand) != self.QUERY_RAND_LEN:
            raise ValueError("incorrect query randomness length")
        field = self.field

        # Unpack the proof and wrap gadgets with the prover's claimed
        # gadget polynomials.
        wrapped: list[_QueryGadget[F]] = []
        rest = proof
        for (g, calls) in zip(self.valid.GADGETS, self.valid.GADGET_CALLS):
            p = next_power_of_2(calls + 1)
            (seeds, rest) = front(g.ARITY, rest)
            (coeffs, rest) = front(g.DEGREE * (p - 1) + 1, rest)
            wrapped.append(_QueryGadget(field, list(seeds), list(coeffs),
                                        g, calls))
        saved = self.valid.GADGETS
        self.valid.GADGETS = wrapped  # type: ignore[assignment]
        try:
            out = self.valid.eval(meas, joint_rand, num_shares)
        finally:
            self.valid.GADGETS = saved

        # Reduce the circuit outputs to a single element via a random
        # linear combination with independent weights.
        if self.valid.EVAL_OUTPUT_LEN > 1:
            (weights, query_rand) = front(self.valid.EVAL_OUTPUT_LEN,
                                          query_rand)
            v = field(0)
            for (weight, out_elem) in zip(weights, out):
                v += weight * out_elem
        else:
            v = out[0]

        # Spot-check each gadget's wires against its gadget polynomial
        # at a random point t outside the call domain.
        verifier = [v]
        for (wg, t) in zip(wrapped, query_rand):
            p = len(wg.wires[0])
            if t ** p == field(1):
                raise ValueError("query randomness hit the NTT domain")
            for wire in wg.wires:
                wire_poly = poly_interp(field, wire)
                verifier.append(poly_eval(field, wire_poly, t))
            verifier.append(poly_eval(field, wg.gadget_poly, t))
        return verifier

    def decide(self, verifier: list[F]) -> bool:
        if len(verifier) != self.VERIFIER_LEN:
            raise ValueError("incorrect verifier length")
        field = self.field
        ([v], rest) = front(1, verifier)
        if v != field(0):
            return False
        for g in self.valid.GADGETS:
            (x, rest) = front(g.ARITY, rest)
            ([y], rest) = front(1, rest)
            if g.eval(field, list(x)) != y:
                return False
        return True

    # -- passthroughs ----------------------------------------------

    def encode(self, measurement: W) -> list[F]:
        return self.valid.encode(measurement)

    def truncate(self, meas: list[F]) -> list[F]:
        return self.valid.truncate(meas)

    def decode(self, output: list[F], num_measurements: int) -> R:
        return self.valid.decode(output, num_measurements)

    def test_vec_set_type_param(self, test_vec: dict) -> list[str]:
        return self.valid.test_vec_set_type_param(test_vec)
