"""RL005 clean: `with Popen(...)` settles the child on every path
(the context manager waits on exit)."""
import subprocess


def spawn(cmd):
    with subprocess.Popen(cmd) as proc:
        proc.communicate()
