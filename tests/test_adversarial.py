"""Adversarial / malformed-report tests: every verifiability check
observed rejecting, on both the scalar and batched paths.

Port of the reference's malformed-input matrix
(/root/reference/poc/tests/test_vidpf.py:193-341 and
tests/test_mastic.py:71-175) to this codebase's level-synchronous
execution model: tamper a VIDPF key, a correction word's
seed/ctrl/proof, or a payload (counter and weight, including the
level-0 payload-check-has-no-parent edge case), or the FLP proof /
joint-rand part — and require prep to reject from the malformed level
onward while still accepting below it.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from mastic_tpu import MasticCount, MasticHistogram
from mastic_tpu.backend.mastic_jax import BatchedMastic

BITS = 5
CTX = b"adversarial test"


def _make_report(mastic, seed=0):
    rng = np.random.default_rng(seed)
    nonce = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
    rand = rng.integers(0, 256, mastic.RAND_SIZE,
                        dtype=np.uint8).tobytes()
    alpha = (True,) * mastic.vidpf.BITS
    meas = (alpha, 1) if isinstance(mastic, MasticCount) else (alpha, 0)
    (public_share, input_shares) = mastic.shard(CTX, meas, nonce, rand)
    return (nonce, public_share, input_shares)


def _scalar_accepts(mastic, nonce, public_share, input_shares,
                    agg_param, verify_key=bytes(range(32))):
    """Run both preps + the exchange; True iff the report survives
    every check (incl. joint-rand confirmation)."""
    states = []
    shares = []
    for agg_id in range(2):
        (state, share) = mastic.prep_init(
            verify_key, CTX, agg_id, agg_param, nonce, public_share,
            input_shares[agg_id])
        states.append(state)
        shares.append(share)
    try:
        prep_msg = mastic.prep_shares_to_prep(CTX, agg_param, shares)
        for agg_id in range(2):
            mastic.prep_next(CTX, states[agg_id], prep_msg)
    except Exception:
        return False
    return True


def _batched_accepts(mastic, nonce, public_share, input_shares,
                     agg_param, verify_key=bytes(range(32))):
    import jax

    bm = BatchedMastic(mastic)
    batch = bm.marshal_reports([(nonce, public_share, input_shares)])
    (_agg0, _agg1, accept, ok) = jax.jit(
        lambda b: bm.round_device(verify_key, CTX, agg_param, b))(batch)
    assert bool(np.asarray(ok).all())
    return bool(np.asarray(accept)[0])


def _full_level_param(mastic, level, weight_check=False):
    return (level, mastic.vidpf.prefixes_for_level(level), weight_check)


def _tamper_cw(public_share, level, what):
    """Copy of the public share with one field of the level's
    correction word tweaked."""
    cws = list(public_share)
    (seed, ctrl, w, proof) = cws[level]
    if what == "seed":
        seed = bytes([seed[0] ^ 1]) + seed[1:]
    elif what == "ctrl":
        ctrl = [not ctrl[0], ctrl[1]]
    elif what == "proof":
        proof = bytes([proof[0] ^ 1]) + proof[1:]
    elif what == "counter":
        w = [w[0] + type(w[0])(1)] + list(w[1:])
    elif what == "weight":
        w = [w[0]] + [w[1] + type(w[1])(1)] + list(w[2:])
    else:
        raise ValueError(what)
    cws[level] = (seed, ctrl, w, proof)
    return cws


def test_malformed_key():
    """A tweaked VIDPF key fails verification at every level, on both
    paths (reference test_vidpf.py:193-221)."""
    mastic = MasticCount(BITS)
    (nonce, public_share, input_shares) = _make_report(mastic)
    (key, proofs, seed, part) = input_shares[0]
    bad_key = bytes([key[0] ^ 1]) + key[1:]
    bad_shares = [(bad_key, proofs, seed, part), input_shares[1]]
    for level in range(BITS):
        param = _full_level_param(mastic, level)
        assert not _scalar_accepts(mastic, nonce, public_share,
                                   bad_shares, param), level
    assert not _batched_accepts(mastic, nonce, public_share, bad_shares,
                                _full_level_param(mastic, 2))


@pytest.mark.parametrize("what", ["seed", "ctrl", "proof"])
@pytest.mark.parametrize("malformed_level", [0, 2, BITS - 1])
def test_malformed_correction_word(what, malformed_level):
    """A tweaked correction-word seed/ctrl/proof is undetectable below
    the malformed level and rejected from it onward (reference
    test_vidpf.py:223-341; the on-path prefix is always in the full
    level set, so the proof tweak is always caught)."""
    mastic = MasticCount(BITS)
    (nonce, public_share, input_shares) = _make_report(mastic)
    bad = _tamper_cw(public_share, malformed_level, what)
    for level in range(BITS):
        param = _full_level_param(mastic, level)
        accepted = _scalar_accepts(mastic, nonce, bad, input_shares,
                                   param)
        assert accepted == (level < malformed_level), (what, level)
    # Batched spot checks: one level below (accept), one at/above
    # (reject).
    if malformed_level > 0:
        assert _batched_accepts(
            mastic, nonce, bad, input_shares,
            _full_level_param(mastic, malformed_level - 1))
    assert not _batched_accepts(
        mastic, nonce, bad, input_shares,
        _full_level_param(mastic, malformed_level))


@pytest.mark.parametrize("malformed_level", [0, 1, 3])
def test_malformed_payload_counter(malformed_level):
    """Tweaking a payload counter trips the counter check (level 0) or
    the payload check (deeper) from the malformed level onward
    (reference test_mastic.py:125-144)."""
    mastic = MasticCount(BITS)
    (nonce, public_share, input_shares) = _make_report(mastic)
    bad = _tamper_cw(public_share, malformed_level, "counter")
    for level in range(BITS):
        param = _full_level_param(mastic, level)
        accepted = _scalar_accepts(mastic, nonce, bad, input_shares,
                                   param)
        assert accepted == (level < malformed_level), level
    assert not _batched_accepts(
        mastic, nonce, bad, input_shares,
        _full_level_param(mastic, malformed_level))


@pytest.mark.parametrize("malformed_level", [0, 1, 3])
def test_malformed_payload_weight(malformed_level):
    """Tweaking a payload weight trips the payload check — except at
    level 0, where the payload check has no parent and detection is
    deferred to level 1 (reference test_mastic.py:146-175)."""
    mastic = MasticCount(BITS)
    (nonce, public_share, input_shares) = _make_report(mastic)
    bad = _tamper_cw(public_share, malformed_level, "weight")
    start = max(malformed_level, 1)
    for level in range(BITS):
        param = _full_level_param(mastic, level)
        accepted = _scalar_accepts(mastic, nonce, bad, input_shares,
                                   param)
        assert accepted == (level < start), level
    # The level-0 edge case on the batched path too: accepted at 0,
    # rejected at 1.
    if malformed_level == 0:
        assert _batched_accepts(mastic, nonce, bad, input_shares,
                                _full_level_param(mastic, 0))
    assert not _batched_accepts(mastic, nonce, bad, input_shares,
                                _full_level_param(mastic, start))


def test_malformed_flp_proof():
    """A tweaked leader FLP proof share fails the weight check — and
    only the weight check (non-weight-check rounds don't read it)."""
    mastic = MasticCount(BITS)
    (nonce, public_share, input_shares) = _make_report(mastic)
    (key, proofs, seed, part) = input_shares[0]
    bad_proofs = [proofs[0] + mastic.field(1)] + list(proofs[1:])
    bad_shares = [(key, bad_proofs, seed, part), input_shares[1]]

    wc_param = _full_level_param(mastic, 0, weight_check=True)
    assert not _scalar_accepts(mastic, nonce, public_share, bad_shares,
                               wc_param)
    assert not _batched_accepts(mastic, nonce, public_share, bad_shares,
                                wc_param)
    # Unread on non-weight-check rounds.
    param = _full_level_param(mastic, 0)
    assert _scalar_accepts(mastic, nonce, public_share, bad_shares,
                           param)
    assert _batched_accepts(mastic, nonce, public_share, bad_shares,
                            param)


def test_malformed_weight_rejected_by_flp():
    """A counter/weight inconsistent with the circuit (weight > 1 for
    Count) is rejected by the FLP on the weight-check round.  Built by
    tampering beta via the level-0 payload correction word on *both*
    counter and weight so the VIDPF checks still pass at level 0."""
    mastic = MasticCount(BITS)
    (nonce, public_share, input_shares) = _make_report(mastic)
    cws = list(public_share)
    (seed, ctrl, w, proof) = cws[0]
    # beta becomes [1, 2]: counter still valid, weight fails x^2-x=0.
    cws[0] = (seed, ctrl, [w[0], w[1] + mastic.field(1)], proof)
    wc_param = _full_level_param(mastic, 0, weight_check=True)
    assert not _scalar_accepts(mastic, nonce, cws, input_shares,
                               wc_param)
    assert not _batched_accepts(mastic, nonce, cws, input_shares,
                                wc_param)


def test_malformed_joint_rand_part():
    """A tweaked peer joint-rand part breaks the joint-rand
    confirmation (prep_next seed equality) for a joint-rand circuit."""
    mastic = MasticHistogram(2, 4, 2)
    rng = np.random.default_rng(3)
    nonce = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
    rand = rng.integers(0, 256, mastic.RAND_SIZE,
                        dtype=np.uint8).tobytes()
    (public_share, input_shares) = mastic.shard(
        CTX, ((True, False), 2), nonce, rand)
    (key, proofs, seed, part) = input_shares[0]
    bad_part = bytes([part[0] ^ 1]) + part[1:]
    bad_shares = [(key, proofs, seed, bad_part), input_shares[1]]
    wc_param = (0, ((False,), (True,)), True)
    assert _scalar_accepts(mastic, nonce, public_share, input_shares,
                           wc_param)
    assert not _scalar_accepts(mastic, nonce, public_share, bad_shares,
                               wc_param)
    assert not _batched_accepts(mastic, nonce, public_share, bad_shares,
                                wc_param)
