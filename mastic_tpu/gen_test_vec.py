"""Deterministic test-vector generator.

Re-emits the nine conformance vectors of
/root/reference/test_vec/mastic/ byte-for-byte (JSON formatting
included), proving full wire fidelity of shard / prep / aggregate /
unshard, and enabling new-vector interop with other implementations
(the reference generator: /root/reference/poc/gen_test_vec.py:12-20 on
top of vdaf_poc.test_utils.gen_test_vec_for_vdaf).

Randomness is the counting-byte pattern (00 01 02 ...) for every
nonce, shard rand and the verify key — the deterministic convention
visible in every shipped vector's "rand" field.

Run as a module to (re)write the files:
    python -m mastic_tpu.gen_test_vec [output_dir]
"""

import json
import os
import sys

from . import testvec_codec as codec
from .mastic import (Mastic, MasticCount, MasticHistogram,
                     MasticMultihotCountVec, MasticSum, MasticSumVec)


def deterministic_bytes(length: int) -> bytes:
    """The counting-byte test pattern used for all test-vector
    randomness."""
    return bytes(i & 0xFF for i in range(length))


def _jsonify_measurement(measurement) -> list:
    (alpha, weight) = measurement
    return [list(alpha), weight]


def gen_test_vec(mastic: Mastic, agg_param, ctx: bytes,
                 measurements: list) -> dict:
    """Run the whole protocol deterministically and capture every wire
    message, in the reference vector schema."""
    verify_key = deterministic_bytes(mastic.VERIFY_KEY_SIZE)
    nonce = deterministic_bytes(mastic.NONCE_SIZE)
    rand = deterministic_bytes(mastic.RAND_SIZE)

    test_vec: dict = {
        "agg_param": mastic.encode_agg_param(agg_param).hex(),
        "ctx": ctx.hex(),
        "prep": [],
        "shares": 2,
        "verify_key": verify_key.hex(),
    }
    codec.set_type_param(mastic, test_vec)

    agg_shares = [mastic.agg_init(agg_param) for _ in range(2)]
    for measurement in measurements:
        (public_share, input_shares) = mastic.shard(
            ctx, measurement, nonce, rand)

        prep_states = []
        prep_shares = []
        for agg_id in range(2):
            (state, share) = mastic.prep_init(
                verify_key, ctx, agg_id, agg_param, nonce, public_share,
                input_shares[agg_id])
            prep_states.append(state)
            prep_shares.append(share)
        prep_msg = mastic.prep_shares_to_prep(ctx, agg_param,
                                              prep_shares)

        out_shares = []
        for agg_id in range(2):
            out_share = mastic.prep_next(ctx, prep_states[agg_id],
                                         prep_msg)
            out_shares.append(out_share)
            agg_shares[agg_id] = mastic.agg_update(
                agg_param, agg_shares[agg_id], out_share)

        test_vec["prep"].append({
            "input_shares": [
                codec.encode_input_share(mastic, share).hex()
                for share in input_shares
            ],
            "measurement": _jsonify_measurement(measurement),
            "nonce": nonce.hex(),
            "out_shares": [
                [mastic.field.encode_vec([x]).hex() for x in out_share]
                for out_share in out_shares
            ],
            "prep_messages": [
                codec.encode_prep_msg(mastic, prep_msg).hex()],
            "prep_shares": [[
                codec.encode_prep_share(mastic, share).hex()
                for share in prep_shares
            ]],
            "public_share":
                codec.encode_public_share(mastic, public_share).hex(),
            "rand": rand.hex(),
        })

    test_vec["agg_shares"] = [
        codec.encode_agg_share(mastic, share).hex()
        for share in agg_shares
    ]
    test_vec["agg_result"] = mastic.unshard(agg_param, agg_shares,
                                            len(measurements))
    return test_vec


def render_test_vec(test_vec: dict) -> str:
    """The exact on-disk representation of the reference files."""
    return json.dumps(test_vec, indent=4, sort_keys=True) + "\n"


def _idx(mastic: Mastic, value: int, length: int) -> tuple:
    return mastic.vidpf.test_index_from_int(value, length)


def all_test_vecs() -> list[tuple[str, Mastic, tuple, list]]:
    """The nine (filename, instance, agg_param, measurements) configs
    of the reference generator (gen_test_vec.py:26-242)."""
    ctx_configs = []
    count2 = MasticCount(2)
    ctx_configs.append((
        "MasticCount_0.json", count2,
        (0, (_idx(count2, 0b0, 1), _idx(count2, 0b1, 1)), True),
        [(_idx(count2, 0b10, 2), True)]))
    ctx_configs.append((
        "MasticCount_1.json", count2,
        (1, (_idx(count2, 0b00, 2), _idx(count2, 0b01, 2)), True),
        [(_idx(count2, 0b10, 2), True)]))
    # A candidate-prefix set stressing the BFS traversal order of the
    # evaluation-proof computation.
    count5 = MasticCount(5)
    bfs_prefixes = (
        (False, False, False, False, False),
        (False, False, True, True, False),
        (False, False, True, True, True),
        (False, True, True, False, False),
        (False, True, True, True, True),
        (True, False, False, False, False),
        (True, True, True, True, True),
    )
    bfs_measurements = [
        ((False, False, False, False, False), True),
        ((False, False, False, False, False), True),
        ((False, False, True, True, True), True),
        ((False, False, True, True, False), True),
        ((False, True, True, True, True), True),
        ((False, True, True, False, False), True),
        ((False, True, True, False, False), True),
        ((False, True, True, False, False), True),
    ]
    ctx_configs.append(("MasticCount_2.json", count5,
                        (4, bfs_prefixes, True), bfs_measurements))
    # The same round without the weight check.
    ctx_configs.append(("MasticCount_3.json", count5,
                        (4, bfs_prefixes, False), bfs_measurements))

    sum3 = MasticSum(2, 2 ** 3 - 1)
    ctx_configs.append((
        "MasticSum_0.json", sum3,
        (0, (_idx(sum3, 0b0, 1), _idx(sum3, 0b1, 1)), True),
        [(_idx(sum3, 0b10, 2), 1), (_idx(sum3, 0b00, 2), 6),
         (_idx(sum3, 0b11, 2), 7), (_idx(sum3, 0b01, 2), 5),
         (_idx(sum3, 0b11, 2), 2)]))
    sum2 = MasticSum(2, 2 ** 2 - 1)
    ctx_configs.append((
        "MasticSum_1.json", sum2,
        (1, (_idx(sum2, 0b00, 2), _idx(sum2, 0b01, 2)), True),
        [(_idx(sum2, 0b10, 2), 3), (_idx(sum2, 0b00, 2), 2),
         (_idx(sum2, 0b11, 2), 0), (_idx(sum2, 0b01, 2), 1),
         (_idx(sum2, 0b01, 2), 2)]))

    sumvec = MasticSumVec(16, 3, 1, 1)
    ctx_configs.append((
        "MasticSumVec_0.json", sumvec,
        (14, (_idx(sumvec, 0b111100001111000, 15),), True),
        [(_idx(sumvec, 0b1111000011110000, 16), [0, 0, 1]),
         (_idx(sumvec, 0b1111000011110001, 16), [0, 1, 0])]))

    histogram = MasticHistogram(2, 4, 2)
    ctx_configs.append((
        "MasticHistogram_0.json", histogram,
        (1, (_idx(histogram, 0b00, 2), _idx(histogram, 0b01, 2)), True),
        [(_idx(histogram, 0b10, 2), 1), (_idx(histogram, 0b01, 2), 2),
         (_idx(histogram, 0b00, 2), 3)]))

    multihot = MasticMultihotCountVec(2, 4, 2, 2)
    ctx_configs.append((
        "MasticMultihotCountVec_0.json", multihot,
        (1, (_idx(multihot, 0b00, 2), _idx(multihot, 0b01, 2)), True),
        [(_idx(multihot, 0b10, 2), [False, True, True, False]),
         (_idx(multihot, 0b01, 2), [False, True, True, False])]))
    return ctx_configs


def main(out_dir: str) -> None:
    ctx = b"some application"
    os.makedirs(out_dir, exist_ok=True)
    for (filename, mastic, agg_param, measurements) in all_test_vecs():
        rendered = render_test_vec(
            gen_test_vec(mastic, agg_param, ctx, measurements))
        with open(os.path.join(out_dir, filename), "w") as f:
            f.write(rendered)
        print(f"wrote {filename}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "test_vec/mastic")
