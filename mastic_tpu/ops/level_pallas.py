"""Pallas fused level-step megakernel: the whole VIDPF node-eval
pipeline — extend (1 fixed-key AES block per child) -> correct/select
-> convert (`convert_blocks` AES blocks) -> node proof
(Keccak-p[1600,12]) — resident in VMEM for a (report x frontier) tile.

PERF.md §3: the headline `eval_step` is HBM-bandwidth-bound (8.29 GB
logical bytes per step, 15.8 KB per node eval, 84-92% of a v5e chip's
HBM at the measured rate), and per-stage kernels (Keccak r4, AES r5)
only tie the XLA scan because each stage's VMEM residency is repaid by
its own HBM carries.  This kernel is the lever PERF.md names: the
~16 KB of per-eval intermediates (expanded seeds, bitsliced AES
planes, Keccak state planes) never leave VMEM — only the level's
input carries (parent seed planes, ctrl words, correction words, round
keys) and its output rows (next seeds, ctrl, payload limbs, proofs)
cross the HBM boundary, ~100 B per eval against the scan path's
15.8 KB.

Round math is shared by import with the hardware-validated per-stage
kernels: the tower-field bitsliced S-box (ops/sbox_tower), ShiftRows /
MixColumns plane helpers (ops/aes_pallas) and the lane-major 12-round
permutation body (ops/keccak_jax._keccak_round), so the megakernel
cannot drift from the paths the chip already ran.

Layouts keep the r5 tiling lessons: every ref block is 2-D+, uint32,
the lane axis is 128-wide (packed words W for the AES phase, dense
reports R = 32*W for the Keccak phase), and every second-to-last block
dim is a multiple of 8 or equals the array dim.  The child/column axes
are tiled by `_block_parents` so the per-grid-step working set stays a
few MB of VMEM.

Two call forms, one stage table:

* fused (`chain=False`): ONE pallas_call running all stages with the
  intermediates in VMEM scratch — the hardware form.  Its interpret
  compile is the known >1 h wall, so it is never traced on the CPU
  fabric.
* chained (`chain=True`, the default whenever `interpret=True`): one
  pallas_call per stage with the intermediate state in explicit
  buffers — the r5 technique that pins every AES round key, every
  Keccak round constant and the final AES round's missing MixColumns
  bit-exactly on CPU without the interpret compile of the fused form
  (tests/test_ops_level_pallas.py).

Gated by MASTIC_LEVEL_PALLAS=1 (read in backend/vidpf_jax at import):
bit-exact by the chained interpret suite; the fused form is unmeasured
on hardware until the next tunnel window (tools/chip_session.sh runs
`bench.py --level-pallas` automatically when it returns).
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..keccak import ROUND_CONSTANTS
from .aes_pallas import _mix_list, _shift_rows

_U32 = jnp.uint32
_ONES32 = np.uint32(0xFFFFFFFF)
_LANE = 128     # TPU vector lane width: packed words per lane tile
_RATE = 168     # TurboSHAKE128 rate (bytes); proof messages are one
                # absorb block (the wrapper refuses longer binders)
_PROOF_WORDS = 8   # 32-byte node proof = 8 uint32 lane halves

# Stage table (half-open ranges; NUM_STAGES total):
#   0            extend sigma build (seed ^ le128(i), Davies-Meyer in)
#   1..11        extend AES stages 0..10 (whiten, 9 rounds, final)
#   12           extend finish + correct/select + convert sigma build
#   13..23       convert AES stages 0..10
#   24           convert finish: next-seed bit-transpose, payload
#                sample + field correction, ct unpack, ok mask
#   25           node-proof message build + absorb (single block)
#   26..37       Keccak-p rounds 12..23
#   38           squeeze + proof correction
NUM_STAGES = 39

_CONSTS = ("ekp", "ckp", "pseed", "pctrl", "cwsd", "cwct", "wcw",
           "pcw", "bnd")
_OUTS = ("seedb", "ctd", "wlb", "okd", "prf")
_SCRATCH = ("planes", "sigma", "ctp", "klo", "khi")
_STATE = _OUTS + _SCRATCH


def _block_parents(m: int) -> int:
    """Parents per grid step: the smallest count whose convert column
    block (2 children x m blocks each) is a multiple of the 8-sublane
    tile — the r5 Mosaic rule that failed the first AES kernel."""
    return 2 if m % 2 == 0 else 4


def _sigma_rows(x: jax.Array) -> jax.Array:
    """sigma(lo||hi) = hi || hi^lo on a (128, ...) plane-row stack
    (row = 16*bit + byte): pure row shuffling + XOR, the plane-index
    form of xof_jax.fixed_key_blocks_planes' byte moves."""
    rows = []
    for b in range(8):
        lo = x[b * 16:b * 16 + 8]
        hi = x[b * 16 + 8:b * 16 + 16]
        rows.append(hi)
        rows.append(hi ^ lo)
    return jnp.concatenate(rows, axis=0)


def _flip_index_bits(x: jax.Array, i: int) -> jax.Array:
    """XOR le128(i) into a plane-row stack: block indices are < 256,
    so only byte 0's bit planes (rows 16*b) flip — scalar XORs, no
    captured constant arrays (pallas rejects those)."""
    out = x
    for b in range(8):
        if (i >> b) & 1:
            out = jnp.concatenate(
                [out[:b * 16], out[b * 16:b * 16 + 1] ^ _ONES32,
                 out[b * 16 + 1:]], axis=0)
    return out


def _aes_stage(planes: list, key: list, stage: int) -> list:
    """One AES stage on 8 plane arrays of shape (16, cols, lanes):
    stage 0 = whitening, 1..9 = full rounds, 10 = final round (no
    MixColumns) — identical math to ops/aes_pallas._make_kernel."""
    from .sbox_tower import sbox_planes_tower

    if stage == 0:
        return [planes[b] ^ key[b] for b in range(8)]
    planes = sbox_planes_tower(planes, _ONES32)
    planes = [_shift_rows(p) for p in planes]
    if stage < 10:
        planes = _mix_list(planes)
    return [planes[b] ^ key[b] for b in range(8)]


def _unpack_words(words: jax.Array) -> jax.Array:
    """(rows, W) packed words -> (rows, 32*W) dense bits (report
    r = 32*w + j, the bitslice_pack convention), values 0/1."""
    iota = jax.lax.broadcasted_iota(_U32, (1, 1, 32), 2)
    bits = (words[:, :, None] >> iota) & _U32(1)
    return bits.reshape(words.shape[0], words.shape[1] * 32)


class _Meta:
    """Static kernel parameters (hashable cache key via `key`)."""

    def __init__(self, m, n_limbs, value_len, enc_size, p_limbs,
                 prefix, blen, num_parents_pad, w_pad, lane):
        self.m = m                      # convert blocks per child
        self.n = n_limbs                # 16-bit limbs per element
        self.vl = value_len
        self.enc = enc_size
        self.p = tuple(int(v) for v in p_limbs)
        self.prefix = bytes(prefix)     # static TurboSHAKE prefix
        self.blen = blen                # binder bytes per child
        self.msg_len = len(prefix) + 16 + blen
        self.bn = _block_parents(m)     # parents per grid step
        self.np_ = num_parents_pad      # padded parent count
        self.w = w_pad                  # padded packed-word count
        self.lane = lane                # words per lane tile
        self.tnb = 2 * self.bn          # children per grid step
        self.cb = self.tnb * m          # convert columns per step
        self.tn = 2 * num_parents_pad
        self.c = self.tn * m
        self.r = 32 * w_pad             # dense report lanes
        self.rl = 32 * lane             # dense reports per lane tile

    def key(self):
        return (self.m, self.n, self.vl, self.enc, self.p, self.prefix,
                self.blen, self.np_, self.w, self.lane)


# -- in-kernel field arithmetic (plain 16-bit limbs in uint32) --------

def _limb_lt(a: list, b: list):
    """Borrow out of a - b over matched limb lists (the
    field_jax._sub_limbs borrow chain with static constants)."""
    borrow = None
    for (ai, bi) in zip(a, b):
        need = bi + borrow if borrow is not None else bi
        bor = (ai < need).astype(_U32)
        borrow = bor
    return borrow


def _field_add(a: list, b: list, p: tuple) -> list:
    """(a + b) mod p on limb lists — byte-exact twin of FieldSpec.add
    (propagate to n+1 limbs, one conditional subtract of p)."""
    n = len(p)
    s = []
    carry = None
    for i in range(n):
        v = a[i] + b[i]
        if carry is not None:
            v = v + carry
        s.append(v & _U32(0xFFFF))
        carry = v >> 16
    s.append(carry)
    p_ext = tuple(p) + (0,)
    d = []
    borrow = None
    for i in range(n + 1):
        need = _U32(p_ext[i])
        if borrow is not None:
            need = need + borrow
        bor = (s[i] < need).astype(_U32)
        d.append((s[i] + (bor << 16) - need) & _U32(0xFFFF))
        borrow = bor
    keep = _U32(0) - borrow     # all-ones where a + b < p
    return [(s[i] & keep) | (d[i] & ~keep) for i in range(n)]


# -- the stage bodies -------------------------------------------------

def _run_stages(meta: _Meta, refs: dict, start: int, end: int) -> None:
    mt = meta
    for stage in range(start, end):
        if stage == 0:
            _stage_extend_sigma(mt, refs)
        elif stage <= 11:
            _stage_aes(mt, refs, "ekp", stage - 1, 2 * mt.bn)
        elif stage == 12:
            _stage_correct(mt, refs)
        elif stage <= 23:
            _stage_aes(mt, refs, "ckp", stage - 13, mt.cb)
        elif stage == 24:
            _stage_convert_finish(mt, refs)
        elif stage == 25:
            _stage_absorb(mt, refs)
        elif stage <= 37:
            _stage_keccak(mt, refs, stage - 26 + 12)
        else:
            _stage_proof(mt, refs)


def _stage_extend_sigma(mt: _Meta, refs) -> None:
    ps = jnp.moveaxis(refs["pseed"][...], 0, 1)   # (128, BN, L)
    sigs = [_sigma_rows(_flip_index_bits(ps, i)) for i in (0, 1)]
    # Column = 2*parent + block: left/right extend blocks interleaved.
    s = jnp.stack(sigs, axis=2).reshape(128, mt.tnb, mt.lane)
    refs["planes"][:, :mt.tnb, :] = s
    refs["sigma"][:, :mt.tnb, :] = s


def _stage_aes(mt: _Meta, refs, kp_name: str, aes_stage: int,
               cols: int) -> None:
    st = refs["planes"][:, :cols, :]
    planes = [st[b * 16:(b + 1) * 16] for b in range(8)]
    kp = refs[kp_name]
    key = [kp[(aes_stage * 8 + b) * 16:(aes_stage * 8 + b + 1) * 16]
           for b in range(8)]
    planes = _aes_stage(planes, key, aes_stage)
    refs["planes"][:, :cols, :] = jnp.concatenate(planes, axis=0)


def _stage_correct(mt: _Meta, refs) -> None:
    """Extend finish (Davies-Meyer), ctrl-bit extraction, seed/ctrl
    corrections (mask ANDs on packed words — vidpf_jax.
    _level_core_planes' constant-time discipline), then the convert
    sigma build for all m blocks of every child."""
    enc = refs["planes"][:, :mt.tnb, :] ^ refs["sigma"][:, :mt.tnb, :]
    t = enc[0:1]                       # plane (bit 0, byte 0): ctrl
    seeds = jnp.concatenate(
        [jnp.zeros_like(enc[0:1]), enc[1:]], axis=0)

    # Parent ctrl replicated per child (col = 2*parent + side).
    pc = jnp.moveaxis(refs["pctrl"][...], 0, 1)     # (1, BN, L)
    pcc = jnp.broadcast_to(pc[:, :, None, :],
                           (1, mt.bn, 2, mt.lane)).reshape(
                               1, mt.tnb, mt.lane)
    seeds = seeds ^ (refs["cwsd"][...] & pcc)
    ccw = jnp.moveaxis(refs["cwct"][...], 0, 1)     # (1, 2, L)
    ilv = jnp.broadcast_to(ccw[:, None, :, :],
                           (1, mt.bn, 2, mt.lane)).reshape(
                               1, mt.tnb, mt.lane)
    t = t ^ (pcc & ilv)
    refs["ctp"][...] = jnp.moveaxis(t, 1, 0)        # (2BN, 1, L)

    sigs = [_sigma_rows(_flip_index_bits(seeds, j))
            for j in range(mt.m)]
    s = jnp.stack(sigs, axis=2).reshape(128, mt.cb, mt.lane)
    refs["planes"][...] = s
    refs["sigma"][...] = s


def _stage_convert_finish(mt: _Meta, refs) -> None:
    """Davies-Meyer finish on the convert stream, then the in-VMEM
    plane->byte bit-transpose: next-seed bytes (block 0) feed the
    node-proof message, payload bytes (blocks 1..m-1) become field
    limbs with the in-range mask and the w correction word applied."""
    enc = refs["planes"][...] ^ refs["sigma"][...]
    st = enc.reshape(128, mt.tnb, mt.m, mt.lane)

    def dense_byte(j: int, k: int) -> jax.Array:
        """Byte k of stream block j per (child, report): unpack the 8
        bit planes of one byte position to report-dense values."""
        acc = None
        for b in range(8):
            bits = _unpack_words(st[b * 16 + k, :, j, :]) << b
            acc = bits if acc is None else acc | bits
        return acc                       # (2BN, RL) values 0..255

    for k in range(16):
        refs["seedb"][:, k, :] = dense_byte(0, k)

    ctd = _unpack_words(refs["ctp"][:, 0, :])
    refs["ctd"][:, 0, :] = ctd
    mask = _U32(0) - ctd                 # select mask per (child, r)

    byte_cache: dict = {}

    def payload_byte(pos: int) -> jax.Array:
        if pos not in byte_cache:
            byte_cache[pos] = dense_byte(pos // 16 + 1, pos % 16)
        return byte_cache[pos]

    ok_all = None
    for e in range(mt.vl):
        limbs = []
        for li in range(mt.n):
            p0 = e * mt.enc + 2 * li
            limbs.append(payload_byte(p0)
                         | (payload_byte(p0 + 1) << 8))
        # In-range: value < p (the XOF rejection predicate).
        ok_e = _limb_lt(limbs, [_U32(v) for v in mt.p])
        ok_all = ok_e if ok_all is None else ok_all & ok_e
        # w correction: w + w_cw mod p where the child holds ctrl.
        cw = [refs["wcw"][e * mt.n + li:e * mt.n + li + 1, 0, :]
              for li in range(mt.n)]
        corrected = _field_add(limbs, cw, mt.p)
        for li in range(mt.n):
            sel = (limbs[li] & ~mask) | (corrected[li] & mask)
            refs["wlb"][:, e * mt.n + li, :] = sel
    refs["okd"][:, 0, :] = ok_all


def _stage_absorb(mt: _Meta, refs) -> None:
    """Build the padded TurboSHAKE128 message lanes (prefix | next
    seed | binder, domain 1, pad10*1) and absorb into the zero state:
    message fits one rate block by the wrapper's gate."""
    bnd = refs["bnd"][...]               # (2BN, 1, B_pad) byte values

    def msg_byte(p: int):
        """Static message byte p: scalar, (2BN, RL) seed byte, or
        (2BN, 1) binder column (broadcast over reports)."""
        lp = len(mt.prefix)
        val = 0
        if p < lp:
            val = mt.prefix[p]
        elif p < lp + 16:
            return refs["seedb"][:, p - lp, :]
        elif p < mt.msg_len:
            return bnd[:, 0, p - lp - 16:p - lp - 15]
        if p == mt.msg_len:
            val ^= 0x01                  # domain byte
        if p == _RATE - 1:
            val ^= 0x80                  # pad10*1 final bit
        return val

    for i in range(25):
        for (half, ref) in ((0, refs["klo"]), (1, refs["khi"])):
            if i >= 21:                  # capacity lanes stay zero
                ref[:, i, :] = jnp.zeros((mt.tnb, mt.rl), _U32)
                continue
            base = 8 * i + 4 * half
            scalar = 0
            arr = None
            for t in range(4):
                b = msg_byte(base + t)
                if isinstance(b, int):
                    scalar |= b << (8 * t)
                else:
                    part = (b if b.ndim == 2 and b.shape[1] == mt.rl
                            else jnp.broadcast_to(b, (mt.tnb, 1)))
                    part = part.astype(_U32) << (8 * t)
                    arr = part if arr is None else arr | part
            word = jnp.full((mt.tnb, mt.rl), scalar, _U32)
            if arr is not None:
                word = word | arr        # byte fields are disjoint
            ref[:, i, :] = word


def _stage_keccak(mt: _Meta, refs, r: int) -> None:
    from .keccak_jax import _keccak_round

    a = [(refs["klo"][:, i, :], refs["khi"][:, i, :])
         for i in range(25)]
    rc = ROUND_CONSTANTS[r]
    a = _keccak_round(a, _U32(rc & 0xFFFFFFFF), _U32(rc >> 32))
    for i in range(25):
        refs["klo"][:, i, :] = a[i][0]
        refs["khi"][:, i, :] = a[i][1]


def _stage_proof(mt: _Meta, refs) -> None:
    """Squeeze the 32 proof bytes (lanes 0..3) and fold in proof_cw
    where the child holds the ctrl bit, at uint32-word granularity."""
    mask = _U32(0) - refs["ctd"][:, 0, :]
    for t in range(_PROOF_WORDS):
        src = refs["klo"] if t % 2 == 0 else refs["khi"]
        cw = refs["pcw"][t:t + 1, 0, :]
        refs["prf"][:, t, :] = src[:, t // 2, :] ^ (cw & mask)


# -- pallas_call assembly ---------------------------------------------

def _shapes(mt: _Meta) -> dict:
    """Full-array shape per buffer (blocks in _specs slice these)."""
    return {
        "ekp": (11 * 128, 1, mt.w), "ckp": (11 * 128, 1, mt.w),
        "pseed": (mt.np_, 128, mt.w), "pctrl": (mt.np_, 1, mt.w),
        "cwsd": (128, 1, mt.w), "cwct": (2, 1, mt.w),
        "wcw": (mt.vl * mt.n, 1, mt.r), "pcw": (_PROOF_WORDS, 1, mt.r),
        "bnd": (mt.tn, 1, _LANE),
        "planes": (128, mt.c, mt.w), "sigma": (128, mt.c, mt.w),
        "ctp": (mt.tn, 1, mt.w),
        "seedb": (mt.tn, 16, mt.r), "ctd": (mt.tn, 1, mt.r),
        "wlb": (mt.tn, mt.vl * mt.n, mt.r), "okd": (mt.tn, 1, mt.r),
        "prf": (mt.tn, _PROOF_WORDS, mt.r),
        "klo": (mt.tn, 25, mt.r), "khi": (mt.tn, 25, mt.r),
    }


def _specs(mt: _Meta) -> dict:
    """BlockSpec per buffer over the (lane-tile j, parent-tile i)
    grid.  Node-major leading axes keep every second-to-last block dim
    either a multiple of 8 or equal to the array dim (the r5 Mosaic
    tiling rule); lane axes are `lane` packed words or 32*lane dense
    reports."""
    from jax.experimental import pallas as pl

    (bn, tnb, cb, l, rl) = (mt.bn, mt.tnb, mt.cb, mt.lane, mt.rl)
    # mastic-allow: PL004 — the klo/khi 25-row blocks equal the full
    # Keccak lane-axis dim (25 lanes, never tiled), the case Mosaic
    # accepts for a non-multiple-of-8 sublane dim
    return {
        "ekp": pl.BlockSpec((11 * 128, 1, l), lambda j, i: (0, 0, j)),
        "ckp": pl.BlockSpec((11 * 128, 1, l), lambda j, i: (0, 0, j)),
        "pseed": pl.BlockSpec((bn, 128, l), lambda j, i: (i, 0, j)),
        "pctrl": pl.BlockSpec((bn, 1, l), lambda j, i: (i, 0, j)),
        "cwsd": pl.BlockSpec((128, 1, l), lambda j, i: (0, 0, j)),
        "cwct": pl.BlockSpec((2, 1, l), lambda j, i: (0, 0, j)),
        "wcw": pl.BlockSpec((mt.vl * mt.n, 1, rl),
                            lambda j, i: (0, 0, j)),
        "pcw": pl.BlockSpec((_PROOF_WORDS, 1, rl),
                            lambda j, i: (0, 0, j)),
        "bnd": pl.BlockSpec((tnb, 1, _LANE), lambda j, i: (i, 0, 0)),
        "planes": pl.BlockSpec((128, cb, l), lambda j, i: (0, i, j)),
        "sigma": pl.BlockSpec((128, cb, l), lambda j, i: (0, i, j)),
        "ctp": pl.BlockSpec((tnb, 1, l), lambda j, i: (i, 0, j)),
        "seedb": pl.BlockSpec((tnb, 16, rl), lambda j, i: (i, 0, j)),
        "ctd": pl.BlockSpec((tnb, 1, rl), lambda j, i: (i, 0, j)),
        "wlb": pl.BlockSpec((tnb, mt.vl * mt.n, rl),
                            lambda j, i: (i, 0, j)),
        "okd": pl.BlockSpec((tnb, 1, rl), lambda j, i: (i, 0, j)),
        "prf": pl.BlockSpec((tnb, _PROOF_WORDS, rl),
                            lambda j, i: (i, 0, j)),
        "klo": pl.BlockSpec((tnb, 25, rl), lambda j, i: (i, 0, j)),
        "khi": pl.BlockSpec((tnb, 25, rl), lambda j, i: (i, 0, j)),
    }


_CALL_CACHE: dict = {}


def _chained_call(mt: _Meta, start: int, end: int, interpret: bool):
    """One pallas_call covering stages [start, end) with the full
    intermediate state in explicit HBM buffers (in AND out), so stages
    chain across calls — the r5 per-stage validation technique."""
    from jax.experimental import pallas as pl

    cache_key = ("chain", mt.key(), start, end, interpret)
    call = _CALL_CACHE.get(cache_key)
    if call is not None:
        return call
    shapes = _shapes(mt)
    specs = _specs(mt)

    def kernel(*refs):
        named = dict(zip(_CONSTS + tuple("in_" + s for s in _STATE)
                         + _STATE, refs))
        for s in _STATE:   # carry untouched state through this stage
            named[s][...] = named["in_" + s][...]
        _run_stages(mt, named, start, end)

    grid = (mt.w // mt.lane, mt.np_ // mt.bn)
    call = pl.pallas_call(
        kernel,
        out_shape=tuple(jax.ShapeDtypeStruct(shapes[s], jnp.uint32)
                        for s in _STATE),
        grid=grid,
        in_specs=[specs[s] for s in _CONSTS]
        + [specs[s] for s in _STATE],
        out_specs=tuple(specs[s] for s in _STATE),
        interpret=interpret,
    )
    _CALL_CACHE[cache_key] = call
    return call


def _fused_call(mt: _Meta, interpret: bool):
    """The production form: ONE pallas_call, all stages, intermediates
    in VMEM scratch — nothing but the level's inputs and outputs
    crosses HBM.  Never traced in interpret mode by the wrapper (the
    unrolled pipeline is the known >1 h interpret compile)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    cache_key = ("fused", mt.key(), interpret)
    call = _CALL_CACHE.get(cache_key)
    if call is not None:
        return call
    shapes = _shapes(mt)
    specs = _specs(mt)
    scratch = {
        "planes": (128, mt.cb, mt.lane), "sigma": (128, mt.cb, mt.lane),
        "ctp": (mt.tnb, 1, mt.lane),
        "klo": (mt.tnb, 25, mt.rl), "khi": (mt.tnb, 25, mt.rl),
    }

    def kernel(*refs):
        named = dict(zip(_CONSTS + _OUTS + _SCRATCH, refs))
        _run_stages(mt, named, 0, NUM_STAGES)

    grid = (mt.w // mt.lane, mt.np_ // mt.bn)
    call = pl.pallas_call(
        kernel,
        out_shape=tuple(jax.ShapeDtypeStruct(shapes[s], jnp.uint32)
                        for s in _OUTS),
        grid=grid,
        in_specs=[specs[s] for s in _CONSTS],
        out_specs=tuple(specs[s] for s in _OUTS),
        scratch_shapes=[pltpu.VMEM(scratch[s], jnp.uint32)
                        for s in _SCRATCH],
        interpret=interpret,
    )
    _CALL_CACHE[cache_key] = call
    return call


# -- host-facing wrapper ----------------------------------------------

def _pad_axis(x: jax.Array, axis: int, size: int) -> jax.Array:
    pad = size - x.shape[axis]
    if pad <= 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def supports(convert_blocks: int, prefix_len: int,
             binder_bytes: int) -> bool:
    """Shapes the megakernel serves; callers fall back to the scan
    path otherwise.  The message must fit one absorb block and the
    convert column block must stay a small multiple of the VMEM tile
    (huge-payload instantiations like SumVec(1024) stream hundreds of
    blocks and belong on the scan path)."""
    return (convert_blocks <= 8
            and prefix_len + 16 + binder_bytes <= _RATE - 1)


def level_step_pallas(spec, convert_blocks: int, ext_rk: jax.Array,
                      conv_rk: jax.Array, parent_seed: jax.Array,
                      parent_ctrl: jax.Array, cw_slice,
                      prefix: bytes, node_binder,
                      interpret: bool = False, chain=None):
    """Run one full VIDPF level in the megakernel.

    spec: ops/field_jax.FieldSpec; ext_rk/conv_rk (R, 11, 16) uint8;
    parent_seed (R, N, 16) uint8; parent_ctrl (R, N) bool; cw_slice =
    (seed_cw (R,16), ctrl_cw (R,2), w_cw (R,VL,n), proof_cw (R,32));
    prefix = the static TurboSHAKE node-proof message prefix;
    node_binder (2N, blen) uint8 (static or traced — same for every
    report).  Returns (next_seed (R,2N,16) u8, ct (R,2N) bool, w
    (R,2N,VL,n) u32 plain limbs, ok (R,2N) bool, proof (R,2N,32) u8),
    byte-exact vs vidpf_jax's scan-path eval_step.

    `chain` selects per-stage kernel calls — one pallas_call per
    pipeline stage with the intermediate state in explicit buffers,
    which is what pins each AES round key, each Keccak round constant
    and the final AES round's missing MixColumns individually.  The
    default follows `interpret`, keeping the CPU fabric off the fused
    form's interpret-compile wall.
    """
    from ..ops.aes_jax import bitslice_keys, bitslice_pack, pack_mask

    (seed_cw, ctrl_cw, w_cw, proof_cw) = cw_slice
    (num_reports, num_parents) = parent_ctrl.shape
    binder = jnp.asarray(node_binder)
    blen = int(binder.shape[-1])
    assert supports(convert_blocks, len(prefix), blen), \
        "shape outside the megakernel envelope (caller must gate)"
    if chain is None:
        chain = interpret

    # Pad reports to the packed-word lane tile and parents to the
    # grid block; dead lanes carry zeros and are sliced off below.
    # The chained (CPU validation) form shrinks the lane tile to the
    # batch so small differential shapes stay small; the fused
    # (hardware) form always uses the full 128-lane tile.
    r32 = -(-num_reports // 32) * 32
    w_words = r32 // 32
    lane = (min(_LANE, 1 << (w_words - 1).bit_length()) if chain
            else _LANE)
    w_pad = -(-w_words // lane) * lane
    bn = _block_parents(convert_blocks)
    np_pad = max(bn, -(-num_parents // bn) * bn)
    mt = _Meta(convert_blocks, spec.num_limbs, w_cw.shape[-2],
               spec.encoded_size, spec.P, prefix, blen, np_pad,
               w_pad, lane)

    def planes_in(x, mid):
        """uint8 (R, ..., 16) -> padded plane rows (mid, 128, w_pad)
        node-major (mid = middle-axis size after padding)."""
        p = bitslice_pack(_pad_axis(x, 0, 32 * w_pad))
        p = p.reshape((128,) + p.shape[2:])
        if p.ndim == 2:
            p = p[:, None, :]
        p = _pad_axis(p, 1, mid)
        return jnp.moveaxis(p, 1, 0)

    pseed = planes_in(parent_seed, np_pad)
    cwsd = jnp.moveaxis(planes_in(seed_cw, 1), 0, 1)   # (128, 1, W)
    pctrl = _pad_axis(
        pack_mask(_pad_axis(parent_ctrl, 0, 32 * w_pad)),
        0, np_pad)[:, None, :]
    cwct = pack_mask(_pad_axis(ctrl_cw, 0, 32 * w_pad))[:, None, :]
    ekp = bitslice_keys(
        _pad_axis(ext_rk, 0, 32 * w_pad)).reshape(11 * 128, 1, w_pad)
    ckp = bitslice_keys(
        _pad_axis(conv_rk, 0, 32 * w_pad)).reshape(11 * 128, 1, w_pad)
    wcw = jnp.moveaxis(
        _pad_axis(w_cw, 0, mt.r).reshape(mt.r, -1).astype(_U32),
        0, 1)[:, None, :]
    shifts = (jnp.arange(4, dtype=_U32) * 8)[None, None, :]
    pcw = jnp.sum(
        _pad_axis(proof_cw, 0, mt.r).reshape(mt.r, 8, 4).astype(_U32)
        << shifts, axis=-1, dtype=_U32)
    pcw = jnp.moveaxis(pcw, 0, 1)[:, None, :]
    bnd = _pad_axis(_pad_axis(binder.astype(_U32), 0, mt.tn),
                    1, _LANE)[:, None, :]

    consts = (ekp, ckp, pseed, pctrl, cwsd, cwct, wcw, pcw, bnd)
    if chain:
        shapes = _shapes(mt)
        state = tuple(jnp.zeros(shapes[s], _U32) for s in _STATE)
        for stage in range(NUM_STAGES):
            state = _chained_call(mt, stage, stage + 1,
                                  interpret)(*consts, *state)
        outs = state[:len(_OUTS)]
    else:
        outs = _fused_call(mt, interpret)(*consts)
    (seedb, ctd, wlb, okd, prf) = outs

    tn = 2 * num_parents
    next_seed = jnp.moveaxis(
        seedb[:tn, :, :num_reports], 2, 0).astype(jnp.uint8)
    ct = jnp.moveaxis(ctd[:tn, 0, :num_reports], 1, 0).astype(bool)
    w = jnp.moveaxis(
        wlb[:tn, :, :num_reports].reshape(
            tn, mt.vl, mt.n, num_reports), 3, 0)
    ok = jnp.moveaxis(okd[:tn, 0, :num_reports], 1, 0).astype(bool)
    byte_sh = (jnp.arange(4, dtype=_U32) * 8)[None, None, :, None]
    prf_bytes = ((prf[:tn, :, None, :num_reports] >> byte_sh)
                 & _U32(0xFF)).reshape(tn, 32, num_reports)
    proof = jnp.moveaxis(prf_bytes, 2, 0).astype(jnp.uint8)
    return (next_seed, ct, w, ok, proof)
