"""Batched Mastic aggregator: prep over a whole report batch at once.

Device twin of the scalar Mastic.prep_init / prep_shares_to_prep /
agg_update (mastic_tpu/mastic.py, itself byte-exact vs the reference
/root/reference/poc/mastic.py:205-397).  The whole round — VIDPF tree
eval, the three verifiability checks, the FLP query/decide on
weight-check rounds (reference mastic.py:250-256, :348-350), masked
aggregation — runs on device; only the wire boundaries are host-side.

Binder assembly order: the payload/onehot check binders concatenate
per-depth node data in lexicographic order, which equals the
reference's BFS materialization order (see backend/schedule.py).
"""

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..common import to_le_bytes
from ..dst import (USAGE_EVAL_PROOF, USAGE_JOINT_RAND,
                   USAGE_JOINT_RAND_PART, USAGE_JOINT_RAND_SEED,
                   USAGE_ONEHOT_CHECK, USAGE_PAYLOAD_CHECK,
                   USAGE_PROOF_SHARE, USAGE_PROVE_RAND,
                   USAGE_QUERY_RAND, dst_alg)
from ..flp.flp_jax import BatchedFlp
from ..mastic import Mastic
from ..ops.field_jax import field_sum, spec_for
from ..vidpf import PROOF_SIZE
from .schedule import LevelSchedule
from .vidpf_jax import BatchedCorrectionWords, BatchedVidpf, EvalState
from .xof_jax import sample_vec, turboshake_xof

SEED_SIZE = 32  # XofTurboShake128.SEED_SIZE


class BatchedPrep(NamedTuple):
    """Per-report device results of one aggregator's prep.

    out_share    (R, P*(1+OUTPUT_LEN), n) plain limbs
    eval_proof   (R, 32) uint8
    verifier     (R, VERIFIER_LEN, n) plain limbs (weight-check rounds)
                 — the FLP verifier share this aggregator broadcasts
    joint_rand_part / joint_rand_seed  (R, 32) uint8 or None
    ok           (R,) bool — False where rejection sampling fired and
                 the scalar fallback must recompute this report
    """
    out_share: jax.Array
    eval_proof: jax.Array
    verifier: Optional[jax.Array]
    joint_rand_part: Optional[jax.Array]
    joint_rand_seed: Optional[jax.Array]
    ok: jax.Array


class ReportBatch(NamedTuple):
    """A report batch marshalled to device arrays (host boundary of
    the upload channel; wire formats in mastic_tpu.mastic)."""
    nonces: jax.Array              # (R, 16) uint8
    cws: BatchedCorrectionWords
    keys: jax.Array                # (R, 2, 16) uint8
    leader_proofs: jax.Array       # (R, PROOF_LEN, n) plain limbs
    helper_seeds: jax.Array        # (R, 32) uint8
    leader_seeds: Optional[jax.Array]   # (R, 32) or None
    peer_parts: tuple              # per agg: (R, 32) or None


class BatchedMastic:
    """Batched execution engine for one Mastic instantiation; wraps the
    scalar instance for parameters and the host fallback paths."""

    def __init__(self, mastic: Mastic):
        self.m = mastic
        self.spec = spec_for(mastic.field)
        self.vidpf = BatchedVidpf(mastic.field, mastic.vidpf.BITS,
                                  mastic.vidpf.VALUE_LEN)
        self.bflp = BatchedFlp(mastic.flp)
        self._trunc = self._truncate_map()

    # -- truncation as a static linear map -------------------------

    def _truncate_map(self):
        """All five circuits' truncate() maps are linear (identity,
        projection, or bit-recomposition — flp/circuits.py); express
        them as a gather or a constant Montgomery matrix so truncation
        runs on device."""
        flp = self.m.flp
        field = self.m.field
        cols = []
        for j in range(flp.MEAS_LEN):
            e = field.zeros(flp.MEAS_LEN)
            e[j] = field(1)
            cols.append([x.int() for x in flp.truncate(e)])
        # matrix[out][in]
        matrix = np.array([[cols[j][o] for j in range(flp.MEAS_LEN)]
                           for o in range(flp.OUTPUT_LEN)], object)
        gather = np.full(flp.OUTPUT_LEN, -1, np.int64)
        for o in range(flp.OUTPUT_LEN):
            nonzero = [j for j in range(flp.MEAS_LEN) if matrix[o][j] != 0]
            if len(nonzero) == 1 and matrix[o][nonzero[0]] == 1:
                gather[o] = nonzero[0]
            else:
                gather[0] = -1
                break
        if (gather >= 0).all():
            return ("gather", gather)
        mont = np.zeros((flp.OUTPUT_LEN, flp.MEAS_LEN,
                         self.spec.num_limbs), np.uint32)
        for o in range(flp.OUTPUT_LEN):
            for j in range(flp.MEAS_LEN):
                mont[o, j] = self.spec.to_mont_host(int(matrix[o][j]))
        return ("matrix", mont)

    def truncate(self, w: jax.Array) -> jax.Array:
        """Apply flp.truncate to plain-limb payloads (..., MEAS, n)."""
        (kind, data) = self._trunc
        if kind == "gather":
            return w[..., data, :]
        prods = self.spec.mul(w[..., None, :, :], jnp.asarray(data))
        return field_sum(self.spec, prods, axis=-2)

    # -- batched XOF derivations (scalar: mastic.py:393-423) -------

    def _expand_vec(self, seed, usage: int, ctx: bytes, binder_parts,
                    length: int, batch_shape):
        dst = dst_alg(ctx, usage, self.m.ID)
        stream = turboshake_xof(dst, seed, binder_parts,
                                length * self.spec.encoded_size,
                                batch_shape)
        return sample_vec(self.spec, stream, length)

    def helper_proof_share(self, ctx: bytes, seeds: jax.Array):
        return self._expand_vec(seeds, USAGE_PROOF_SHARE, ctx, (),
                                self.m.flp.PROOF_LEN, seeds.shape[:-1])

    def prove_rand(self, ctx: bytes, seeds: jax.Array):
        return self._expand_vec(seeds, USAGE_PROVE_RAND, ctx, (),
                                self.m.flp.PROVE_RAND_LEN,
                                seeds.shape[:-1])

    def query_rand(self, verify_key: bytes, ctx: bytes,
                   nonces: jax.Array, level: int):
        return self._expand_vec(
            verify_key, USAGE_QUERY_RAND, ctx,
            (nonces, to_le_bytes(level, 2)),
            self.m.flp.QUERY_RAND_LEN, nonces.shape[:-1])

    def joint_rand_part(self, ctx: bytes, seeds: jax.Array,
                        weight_share: jax.Array, nonces: jax.Array):
        binder = jnp.concatenate(
            [nonces, self.spec.plain_to_le_bytes(weight_share).reshape(
                weight_share.shape[:-2] + (-1,))], axis=-1)
        return turboshake_xof(
            dst_alg(ctx, USAGE_JOINT_RAND_PART, self.m.ID), seeds,
            (binder,), SEED_SIZE, seeds.shape[:-1])

    def joint_rand_seed(self, ctx: bytes, part0: jax.Array,
                        part1: jax.Array):
        return turboshake_xof(
            dst_alg(ctx, USAGE_JOINT_RAND_SEED, self.m.ID), b"",
            (part0, part1), SEED_SIZE, part0.shape[:-1])

    def joint_rand(self, ctx: bytes, seeds: jax.Array):
        return self._expand_vec(seeds, USAGE_JOINT_RAND, ctx, (),
                                self.m.flp.JOINT_RAND_LEN,
                                seeds.shape[:-1])

    # -- batched client shard (scalar: mastic.py:100-152) ----------

    def shard_device(self, ctx: bytes, alphas: jax.Array,
                     betas: jax.Array, nonces: jax.Array,
                     rand: jax.Array) -> tuple:
        """Batched client sharding: the whole client fleet's report
        generation in one program (scalar twin: Mastic.shard — itself
        the unified path over reference mastic.py:103-185).

        alphas (R, BITS) bool; betas (R, VALUE_LEN, n) plain limbs
        with the counter 1 prepended (beta = [1] || encode(weight));
        nonces (R, 16); rand (R, RAND_SIZE) uint8 split exactly as the
        scalar layer splits it, so identical bytes produce identical
        reports (tests/test_chunked.py locks this bit-exactly).

        Returns (ReportBatch, ok): lanes where XOF rejection sampling
        fired carry garbage and must be re-sharded via the scalar
        layer (same fallback contract as the aggregator side).
        """
        use_jr = self.m.flp.JOINT_RAND_LEN > 0
        vs = self.m.vidpf.RAND_SIZE
        vidpf_rand = rand[:, :vs]
        prove_seed = rand[:, vs:vs + SEED_SIZE]
        helper_seed = rand[:, vs + SEED_SIZE:vs + 2 * SEED_SIZE]
        leader_seed = (rand[:, vs + 2 * SEED_SIZE:vs + 3 * SEED_SIZE]
                       if use_jr else None)

        (cws, keys, ok) = self.vidpf.gen(alphas, betas, ctx, nonces,
                                         vidpf_rand)

        joint_rand = None
        peer_parts: tuple = (None, None)
        if use_jr:
            parts = []
            for (agg_id, seed) in ((0, leader_seed), (1, helper_seed)):
                (bs, bok) = self.vidpf.get_beta_share(
                    agg_id, cws, keys[:, agg_id], ctx, nonces)
                ok = ok & bok
                parts.append(self.joint_rand_part(
                    ctx, seed, bs[..., 1:, :], nonces))
            jr_seed = self.joint_rand_seed(ctx, parts[0], parts[1])
            (joint_rand, jok) = self.joint_rand(ctx, jr_seed)
            ok = ok & jok
            # Each party's input share carries the PEER's part.
            peer_parts = (parts[1], parts[0])

        (prove_rand, pok) = self.prove_rand(ctx, prove_seed)
        ok = ok & pok
        proof = self.bflp.prove(betas[..., 1:, :], prove_rand,
                                joint_rand)
        (helper_share, hok) = self.helper_proof_share(ctx, helper_seed)
        ok = ok & hok
        leader_proofs = self.spec.sub(proof, helper_share)

        batch = ReportBatch(
            nonces=nonces, cws=cws, keys=keys,
            leader_proofs=leader_proofs, helper_seeds=helper_seed,
            leader_seeds=leader_seed, peer_parts=peer_parts)
        return (batch, ok)

    def encode_measurements(self, measurements: list) -> tuple:
        """Host-side encoding of [(alpha path, weight)] into the
        shard_device inputs (alphas bool array, betas plain limbs)."""
        flp = self.m.flp
        num = len(measurements)
        bits = self.m.vidpf.BITS
        alphas = np.zeros((num, bits), bool)
        betas = np.zeros((num, self.m.vidpf.VALUE_LEN,
                          self.spec.num_limbs), np.uint32)
        for (r, (alpha, weight)) in enumerate(measurements):
            alphas[r] = alpha
            beta = [self.m.field(1)] + flp.encode(weight)
            for (j, el) in enumerate(beta):
                betas[r, j] = self.spec.int_to_limbs(el.int())
        return (alphas, betas)

    # -- the checks (scalar: mastic.py:219-247) --------------------

    def check_binders(self, levels: list[EvalState],
                      sched: LevelSchedule):
        """Per-report payload / onehot binder byte arrays, in the BFS
        order of the reference (mastic.py:258-287)."""
        num_reports = levels[0].ctrl.shape[0]
        payload_parts = []
        for d in range(sched.level):
            idx = sched.internal_index[d]
            parent_w = levels[d].w[:, idx]
            child_w = levels[d + 1].w
            left = child_w[:, 0::2]
            right = child_w[:, 1::2]
            diff = self.spec.sub(parent_w,
                                 self.spec.add(left, right))
            payload_parts.append(
                self.spec.plain_to_le_bytes(diff).reshape(
                    num_reports, -1))
        payload_binder = (
            jnp.concatenate(payload_parts, axis=-1) if payload_parts
            else jnp.zeros((num_reports, 0), jnp.uint8))
        onehot_binder = jnp.concatenate(
            [lvl.proof.reshape(num_reports, -1) for lvl in levels],
            axis=-1)
        return (payload_binder, onehot_binder)

    def eval_proof(self, verify_key: bytes, ctx: bytes,
                   levels: list[EvalState], sched: LevelSchedule,
                   agg_id: int) -> jax.Array:
        (payload_binder, onehot_binder) = self.check_binders(levels,
                                                             sched)
        batch = (payload_binder.shape[0],)
        payload_check = turboshake_xof(
            dst_alg(ctx, USAGE_PAYLOAD_CHECK, self.m.ID), b"",
            (payload_binder,), PROOF_SIZE, batch)
        onehot_check = turboshake_xof(
            dst_alg(ctx, USAGE_ONEHOT_CHECK, self.m.ID), b"",
            (onehot_binder,), PROOF_SIZE, batch)
        # Counter check: the root children's unnegated share of beta[0],
        # plus agg_id so both parties agree iff the counter is 1
        # (mastic.py:234-240).
        counter = self.spec.add(levels[0].w[:, 0, 0],
                                levels[0].w[:, 1, 0])
        if agg_id == 1:
            one = np.zeros(self.spec.num_limbs, np.uint32)
            one[0] = 1
            counter = self.spec.add(counter, jnp.asarray(one))
        counter_check = self.spec.plain_to_le_bytes(counter)
        return turboshake_xof(
            dst_alg(ctx, USAGE_EVAL_PROOF, self.m.ID), verify_key,
            (onehot_check, counter_check, payload_check), PROOF_SIZE,
            batch)

    # -- prep (scalar: mastic.py:179-257) --------------------------

    def prep(self, agg_id: int, verify_key: bytes, ctx: bytes,
             agg_param, nonces: jax.Array, cws: BatchedCorrectionWords,
             keys: jax.Array, proof_shares: Optional[jax.Array] = None,
             seeds: Optional[jax.Array] = None,
             peer_jr_parts: Optional[jax.Array] = None) -> BatchedPrep:
        """One aggregator's prep over the report batch.

        proof_shares: leader's FLP proof shares (R, PROOF_LEN, n) plain
        limbs (agg 0, weight-check rounds); seeds: the helper's 32-byte
        FLP seeds (agg 1); peer_jr_parts: the other party's joint-rand
        parts (joint-rand circuits only).
        """
        (level, prefixes, do_weight_check) = agg_param
        sched = LevelSchedule(prefixes, level, self.m.vidpf.BITS)

        (levels, out_w, ok) = self.vidpf.eval_full(
            agg_id, cws, keys, sched, ctx, nonces)

        eval_proof = self.eval_proof(verify_key, ctx, levels, sched,
                                     agg_id)

        # Truncated out share: per prefix [counter] + truncate(weight).
        counter = out_w[..., :1, :]
        trunc = self.truncate(out_w[..., 1:, :])
        out_share = jnp.concatenate([counter, trunc], axis=-2)
        out_share = out_share.reshape(out_share.shape[0], -1,
                                      self.spec.num_limbs)

        verifier = None
        jr_part = None
        jr_seed = None
        if do_weight_check:
            beta_share = self.spec.add(levels[0].w[:, 0],
                                       levels[0].w[:, 1])
            if agg_id == 1:
                beta_share = self.spec.neg(beta_share)
            (verifier, jr_part, jr_seed, wok) = self._weight_check(
                agg_id, verify_key, ctx, level, nonces, beta_share,
                proof_shares, seeds, peer_jr_parts)
            ok = ok & wok

        return BatchedPrep(
            out_share=out_share, eval_proof=eval_proof,
            verifier=verifier, joint_rand_part=jr_part,
            joint_rand_seed=jr_seed, ok=ok)

    def _weight_check(self, agg_id: int, verify_key: bytes, ctx: bytes,
                      level: int, nonces: jax.Array,
                      beta_share: jax.Array,
                      proof_shares: Optional[jax.Array],
                      seeds: Optional[jax.Array],
                      peer_jr_parts: Optional[jax.Array]):
        """One aggregator's FLP weight check over an (unnegated-sum
        derived) beta share (scalar: mastic.py:234-256).  Returns
        (verifier, joint_rand_part, joint_rand_seed, ok)."""
        (query_rand, ok) = self.query_rand(verify_key, ctx, nonces,
                                           level)
        expanded_proof = proof_shares
        if agg_id == 1:
            assert seeds is not None
            (expanded_proof, pok) = self.helper_proof_share(ctx, seeds)
            ok = ok & pok
        joint_rand = None
        jr_part = None
        jr_seed = None
        if self.m.flp.JOINT_RAND_LEN > 0:
            assert seeds is not None
            assert peer_jr_parts is not None
            jr_part = self.joint_rand_part(
                ctx, seeds, beta_share[..., 1:, :], nonces)
            if agg_id == 0:
                jr_seed = self.joint_rand_seed(ctx, jr_part,
                                               peer_jr_parts)
            else:
                jr_seed = self.joint_rand_seed(ctx, peer_jr_parts,
                                               jr_part)
            (joint_rand, jok) = self.joint_rand(ctx, jr_seed)
            ok = ok & jok
        # Device FLP query (scalar: mastic.py:250-256).
        (verifier, vok) = self.bflp.query(
            beta_share[..., 1:, :], expanded_proof, query_rand,
            joint_rand, 2)
        return (verifier, jr_part, jr_seed, ok & vok)

    def weight_check_device(self, verify_key: bytes, ctx: bytes,
                            level: int, batch: "ReportBatch",
                            w0_pair: jax.Array, w1_pair: jax.Array):
        """Both aggregators' FLP weight check from the two depth-0
        payload shares each already holds (the incremental round-0
        path: the tree program computed those rows, so no second
        from-root eval is needed — contrast the reference, whose
        prep re-derives them via get_beta_share, mastic.py:234-236).

        w{a}_pair: aggregator a's unnegated depth-0 child payloads
        (R, 2, VALUE_LEN, n) plain limbs.  Returns (checks, ok (R,))
        where checks has per-verdict masks "weight_check" [+
        "joint_rand"] — the eval-proof check belongs to the tree
        round."""
        results = []
        ok = None
        for (agg_id, w_pair) in ((0, w0_pair), (1, w1_pair)):
            beta_share = self.spec.add(w_pair[:, 0], w_pair[:, 1])
            if agg_id == 1:
                beta_share = self.spec.neg(beta_share)
            (verifier, _part, jr_seed, aok) = self._weight_check(
                agg_id, verify_key, ctx, level, batch.nonces,
                beta_share,
                batch.leader_proofs if agg_id == 0 else None,
                batch.leader_seeds if agg_id == 0
                else batch.helper_seeds,
                batch.peer_parts[agg_id])
            results.append((verifier, jr_seed))
            ok = aok if ok is None else ok & aok
        verifier = self.spec.add(results[0][0], results[1][0])
        checks = {"weight_check": self.bflp.decide(verifier)}
        if results[0][1] is not None:
            checks["joint_rand"] = jnp.all(
                results[0][1] == results[1][1], axis=-1)
        return (checks, ok)

    # -- round finish (scalar: mastic.py:284-331) ------------------

    def accept_checks(self, prep0: BatchedPrep, prep1: BatchedPrep,
                      do_weight_check: bool) -> dict:
        """Per-check verdict masks: eval proofs equal, FLP decide over
        the summed verifier shares (weight-check rounds), joint-rand
        seed confirmation (prep_next semantics).  Keys present only
        for checks this round runs.  Fully on device, jittable."""
        checks = {"eval_proof": jnp.all(
            prep0.eval_proof == prep1.eval_proof, axis=-1)}
        if do_weight_check:
            assert prep0.verifier is not None
            verifier = self.spec.add(prep0.verifier, prep1.verifier)
            checks["weight_check"] = self.bflp.decide(verifier)
        if prep0.joint_rand_seed is not None:
            checks["joint_rand"] = jnp.all(
                prep0.joint_rand_seed == prep1.joint_rand_seed, axis=-1)
        return checks

    def accept_mask(self, prep0: BatchedPrep, prep1: BatchedPrep,
                    do_weight_check: bool) -> jax.Array:
        """AND of accept_checks (the round's accept verdict)."""
        checks = self.accept_checks(prep0, prep1, do_weight_check)
        accept = checks["eval_proof"]
        for (name, mask) in checks.items():
            if name != "eval_proof":
                accept = accept & mask
        return accept

    def aggregate(self, out_share: jax.Array,
                  accept: jax.Array) -> jax.Array:
        """Sum accepted reports' out shares: (R, L, n) -> (L, n)."""
        masked = jnp.where(accept[:, None, None], out_share,
                           jnp.zeros_like(out_share))
        return field_sum(self.spec, masked, axis=0)

    # -- host boundary ---------------------------------------------

    def agg_share_to_host(self, agg_share: jax.Array) -> list:
        # mastic-allow: TS003 — host-boundary converter: runs on
        # concrete device arrays outside any jit trace, where
        # np.asarray is the device-to-host transfer
        arr = np.asarray(agg_share)
        return [self.m.field(self.spec.limbs_to_int(arr[i]))
                for i in range(arr.shape[0])]

    def marshal_reports(self, reports: list) -> ReportBatch:
        """Scalar-layer reports [(nonce, public_share, input_shares)]
        -> device arrays (the aggregator's upload ingestion path)."""
        nonces = np.stack([np.frombuffer(n, np.uint8)
                           for (n, _, _) in reports])
        cws = self.vidpf.cws_from_host([ps for (_, ps, _) in reports])
        keys = np.stack([
            np.stack([np.frombuffer(sh[a][0], np.uint8)
                      for a in range(2)])
            for (_, _, sh) in reports
        ])
        leader_proofs = np.stack([
            np.stack([self.spec.int_to_limbs(x.int())
                      for x in sh[0][1]])
            for (_, _, sh) in reports
        ])
        helper_seeds = np.stack([np.frombuffer(sh[1][2], np.uint8)
                                 for (_, _, sh) in reports])
        if self.m.flp.JOINT_RAND_LEN > 0:
            leader_seeds = jnp.asarray(np.stack(
                [np.frombuffer(sh[0][2], np.uint8)
                 for (_, _, sh) in reports]))
            peer_parts = tuple(
                jnp.asarray(np.stack(
                    [np.frombuffer(sh[a][3], np.uint8)
                     for (_, _, sh) in reports]))
                for a in range(2))
        else:
            leader_seeds = None
            peer_parts = (None, None)
        return ReportBatch(
            nonces=jnp.asarray(nonces), cws=cws,
            keys=jnp.asarray(keys),
            leader_proofs=jnp.asarray(leader_proofs),
            helper_seeds=jnp.asarray(helper_seeds),
            leader_seeds=leader_seeds, peer_parts=peer_parts)

    def marshal_party_reports(self, agg_id: int, reports: list) -> dict:
        """One party's view of the upload channel: reports
        [(nonce, public_share, input_share)] where input_share is THIS
        aggregator's MasticInputShare only (the process-separated
        parties never see the peer's share).  Returns the keyword
        arguments for `prep` plus the nonce/cw arrays."""
        nonces = np.stack([np.frombuffer(n, np.uint8)
                           for (n, _, _) in reports])
        cws = self.vidpf.cws_from_host([ps for (_, ps, _) in reports])
        keys = jnp.asarray(np.stack(
            [np.frombuffer(sh[0], np.uint8) for (_, _, sh) in reports]))
        out = {"nonces": jnp.asarray(nonces), "cws": cws, "keys": keys,
               "proof_shares": None, "seeds": None,
               "peer_jr_parts": None}
        if agg_id == 0:
            out["proof_shares"] = jnp.asarray(np.stack([
                np.stack([self.spec.int_to_limbs(x.int())
                          for x in sh[1]])
                for (_, _, sh) in reports]))
        if any(sh[2] is not None for (_, _, sh) in reports):
            out["seeds"] = jnp.asarray(np.stack(
                [np.frombuffer(sh[2], np.uint8)
                 for (_, _, sh) in reports]))
        if self.m.flp.JOINT_RAND_LEN > 0:
            out["peer_jr_parts"] = jnp.asarray(np.stack(
                [np.frombuffer(sh[3], np.uint8)
                 for (_, _, sh) in reports]))
        return out

    def prep_both(self, verify_key: bytes, ctx: bytes, agg_param,
                  batch: ReportBatch) -> tuple:
        """Run both aggregators' prep on a marshalled batch (the
        in-process protocol simulation, reference examples.py:51-59)."""
        p0 = self.prep(0, verify_key, ctx, agg_param, batch.nonces,
                       batch.cws, batch.keys[:, 0],
                       proof_shares=batch.leader_proofs,
                       seeds=batch.leader_seeds,
                       peer_jr_parts=batch.peer_parts[0])
        p1 = self.prep(1, verify_key, ctx, agg_param, batch.nonces,
                       batch.cws, batch.keys[:, 1],
                       seeds=batch.helper_seeds,
                       peer_jr_parts=batch.peer_parts[1])
        return (p0, p1)

    def round_device(self, verify_key: bytes, ctx: bytes, agg_param,
                     batch: ReportBatch) -> tuple:
        """One full simulated aggregation round on device: both preps,
        all checks (incl. the FLP verifier exchange), masked
        aggregation.  Returns (agg_share0, agg_share1, accept, ok) —
        jittable; weight-check rounds included.

        Lanes where XOF rejection sampling fired (ok=False) hold
        garbage and are excluded from the aggregates; the driver
        recomputes those reports through the scalar path and splices
        their contributions in (drivers/heavy_hitters.py:
        splice_rejected).
        """
        return self.round_device_checks(verify_key, ctx, agg_param,
                                        batch)[:4]

    def round_device_checks(self, verify_key: bytes, ctx: bytes,
                            agg_param, batch: ReportBatch) -> tuple:
        """round_device plus the per-check verdict masks (for the
        metrics layer): (agg0, agg1, accept, ok, checks)."""
        (_level, _prefixes, do_weight_check) = agg_param
        (p0, p1) = self.prep_both(verify_key, ctx, agg_param, batch)
        checks = self.accept_checks(p0, p1, do_weight_check)
        accept = checks["eval_proof"]
        for (name, mask) in checks.items():
            if name != "eval_proof":
                accept = accept & mask
        ok = p0.ok & p1.ok
        agg0 = self.aggregate(p0.out_share, accept & ok)
        agg1 = self.aggregate(p1.out_share, accept & ok)
        return (agg0, agg1, accept, ok, checks)
