"""Known-good: table indexed by public loop position (SF002)."""

TABLE = tuple(range(256))


def lookup(position: int) -> int:
    return TABLE[position % 256]
