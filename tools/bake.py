"""Offline AOT artifact baker (`drivers/artifacts.py`, ROADMAP item
4): enumerate the round-program family for a collection config —
pow2 buckets × growth path × mesh shape — trace + compile each
program once, and seal the executables into a digest-sealed store a
collector process loads in seconds instead of re-paying the ~100 s
trace+XLA bill (`BENCH_LAST_GOOD.json`'s `compile_seconds`).

    # bake the family for a 32-bit Count collection streamed in
    # 256-report chunks, hitters up to 4, into ./artifacts/aot:
    python tools/bake.py --out artifacts/aot --bits 32 --rows 256 \
        --ctx "my collection" --hitters 1,2,3,4

    # the serving process then starts trace-free:
    python tools/serve.py --artifact-dir artifacts/aot ...
    # (or MASTIC_ARTIFACT_DIR=artifacts/aot for any driver)

The trajectory model: a heavy-hitters run's program shapes are a
pure function of the per-level frontier, which the planted-path
model (`artifacts.planted_paths` / `artifacts.trajectory`) makes
deterministic — `--hitters k` bakes the steady-k frontier family,
`--grow-frontier N` adds the threshold-prunes-nothing growth phase
(incl. the padded-width growth programs the runtime predictor
deliberately compiles inline).  A frontier the bake did not cover
simply compiles inline at runtime, attributed in
`extra["artifacts"]` — never wrong, only slower.

``--smoke`` is the `make artifacts-smoke` gate: bake a tiny config,
run the collection in-process against the freshly-traced programs
(the inline reference), then re-run it in a FRESH subprocess that
may only use the baked store — asserting zero inline compiles, a
zero compile field in every round timeline, and bit-identical
hitters + per-round counters.  That last comparison is the PERF.md
§7 soundness criterion: a deserialized executable must reproduce the
traced program's outputs exactly, and the per-artifact probe round
gates every load the same way.
"""

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Shared by --smoke and bench.py --cold-start: one tiny deterministic
# planted-path config both sides can reproduce exactly.
SMOKE_CONFIG = {"bits": 4, "reports": 16, "chunk": 8, "hitters": 2,
                "ctx": "artifact smoke"}


def bake(args) -> dict:
    import jax  # noqa: F401  (device init before any lowering)

    from mastic_tpu.backend.mastic_jax import BatchedMastic
    from mastic_tpu.drivers import artifacts
    from mastic_tpu.drivers.parties import instantiate

    if args.mesh:
        from mastic_tpu.parallel import make_mesh
        mesh = make_mesh(args.mesh, nodes_axis=1)
    else:
        mesh = None

    spec = (json.loads(args.spec) if args.spec
            else {"class": "MasticCount", "args": [args.bits]})
    m = instantiate(spec)
    bm = BatchedMastic(m)
    ctx = args.ctx.encode()
    store = artifacts.default_store(args.out)
    bits = m.vidpf.BITS

    totals = {"compiled": 0, "skipped": 0, "seconds": 0.0}
    t0 = time.time()
    for rows in args.rows:
        for k in args.hitters:
            baker = artifacts.make_baker(bm, ctx, width=args.width,
                                         mesh=mesh)
            stats = artifacts.bake_trajectory(
                baker, store, rows,
                artifacts.trajectory(
                    bits, artifacts.planted_paths(bits, k)),
                with_stablehlo=not args.no_stablehlo)
            for (key, v) in stats.items():
                totals[key] += v
            print(f"[bake] rows={rows} hitters={k}: {stats}",
                  file=sys.stderr, flush=True)
        if args.grow_frontier:
            baker = artifacts.make_baker(bm, ctx, width=args.width,
                                         mesh=mesh)
            stats = artifacts.bake_trajectory(
                baker, store, rows,
                artifacts.growth_trajectory(bits, args.grow_frontier),
                with_stablehlo=not args.no_stablehlo)
            for (key, v) in stats.items():
                totals[key] += v
            print(f"[bake] rows={rows} grow<={args.grow_frontier}: "
                  f"{stats}", file=sys.stderr, flush=True)
        if args.attributes:
            # The attribute-metrics round program (ISSUE 10: the
            # from-root round now rides the artifact tier too) —
            # baked per (attribute set, rows, mesh shape), preloaded
            # by the service at tenant admission like every other
            # family member.
            baker = artifacts.make_baker(bm, ctx, width=args.width,
                                         mesh=mesh)
            stats = artifacts.bake_attribute_round(
                baker, store, rows, args.attributes,
                with_stablehlo=not args.no_stablehlo)
            for (key, v) in stats.items():
                totals[key] += v
            print(f"[bake] rows={rows} attributes="
                  f"{','.join(args.attributes)}: {stats}",
                  file=sys.stderr, flush=True)
    return {
        "mode": "bake",
        "out": store.path,
        "runtime": artifacts.runtime_tag(),
        "instance": spec,
        "ctx": args.ctx,
        "rows": args.rows,
        "hitters": args.hitters,
        "attributes": args.attributes,
        "mesh_devices": args.mesh or 1,
        "entries": store.entry_count(),
        "store_bytes": store.store_bytes(),
        "compiled": totals["compiled"],
        "skipped": totals["skipped"],
        "compile_seconds": round(totals["seconds"], 1),
        "wall_seconds": round(time.time() - t0, 1),
    }


def _smoke_child(store_dir: str, expect_store: bool) -> dict:
    """Run the smoke collection in a fresh subprocess (bench.py
    --cold-start-child), with or without the baked store armed."""
    cfg = SMOKE_CONFIG
    env = dict(os.environ)
    env.pop("MASTIC_ARTIFACT_DIR", None)
    if expect_store:
        env["MASTIC_ARTIFACT_DIR"] = store_dir
    env["JAX_PLATFORMS"] = "cpu"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "bench.py"),
         "--cold-start-child", "--cpu", "--bits", str(cfg["bits"]),
         "--chunked-reports", str(cfg["reports"]),
         "--cold-start-chunk", str(cfg["chunk"]),
         "--cold-start-hitters", str(cfg["hitters"]),
         "--cold-start-ctx", cfg["ctx"]],
        capture_output=True, text=True, timeout=1800, env=env)
    if proc.returncode != 0:
        raise SystemExit(
            f"bake --smoke: child (store={expect_store}) failed "
            f"rc={proc.returncode}:\n{proc.stderr[-4000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def smoke(args) -> dict:
    """The artifacts-smoke gate (acceptance criteria of ISSUE 9)."""
    import tempfile

    t0 = time.time()
    cfg = SMOKE_CONFIG
    tmp = tempfile.mkdtemp(prefix="mastic_aot_smoke_")
    bake_args = argparse.Namespace(
        out=tmp, spec=None, bits=cfg["bits"], ctx=cfg["ctx"],
        rows=[cfg["chunk"]], hitters=[cfg["hitters"]],
        grow_frontier=0, attributes=[], width=8, mesh=0,
        no_stablehlo=False)
    rec = bake(bake_args)
    print(f"[smoke] baked {rec['entries']} entries in "
          f"{rec['wall_seconds']}s", file=sys.stderr, flush=True)

    # The inline-traced reference: a fresh process with NO store.
    ref = _smoke_child(tmp, expect_store=False)
    if ref["inline_compiles"] == 0:
        raise SystemExit("smoke: reference child compiled nothing — "
                         "the comparison would be vacuous")
    # The warm-store run: a fresh process that may only load.
    warm = _smoke_child(tmp, expect_store=True)

    problems = []
    if warm["inline_compiles"] != 0:
        problems.append(f"warm child paid "
                        f"{warm['inline_compiles']} inline compiles")
    if warm["artifact_hits"] == 0:
        problems.append("warm child loaded no artifacts")
    if any(ms > 0.0 for ms in warm["round_compile_ms"]):
        problems.append(f"warm child's timeline compile field is "
                        f"nonzero: {warm['round_compile_ms']}")
    if warm["results"] != ref["results"]:
        problems.append(f"results diverge: {warm['results']} != "
                        f"{ref['results']}")
    if warm["counters"] != ref["counters"]:
        problems.append(f"per-round counters diverge: "
                        f"{warm['counters']} != {ref['counters']}")
    if problems:
        for p in problems:
            print(f"smoke: FAIL: {p}", file=sys.stderr, flush=True)
        sys.exit(1)
    return {
        "mode": "smoke", "ok": True,
        "store": tmp,
        "entries": rec["entries"],
        "bake_seconds": rec["wall_seconds"],
        "traced_first_round_s": ref["time_to_first_round_s"],
        "warm_first_round_s": warm["time_to_first_round_s"],
        "warm_artifact_hits": warm["artifact_hits"],
        "results": warm["results"],
        "wall_seconds": round(time.time() - t0, 1),
    }


def main() -> None:
    parser = argparse.ArgumentParser(
        description="bake AOT round-program artifacts "
                    "(USAGE.md 'AOT artifacts')")
    parser.add_argument("--out", type=str, default="artifacts/aot",
                        help="store directory (MASTIC_ARTIFACT_DIR / "
                             "--artifact-dir at serve time)")
    parser.add_argument("--spec", type=str, default=None,
                        help="instantiation record, e.g. "
                             '\'{"class": "MasticHistogram", '
                             '"args": [64, 16, 4]}\'')
    parser.add_argument("--bits", type=int, default=32,
                        help="MasticCount tree depth when --spec is "
                             "not given")
    parser.add_argument("--ctx", type=str, default="bench",
                        help="collection context (baked into the "
                             "programs' domain-separation tags — must "
                             "match the serving config)")
    parser.add_argument("--rows", type=str, default="256",
                        help="comma-separated device row counts "
                             "(chunk sizes) to bake for")
    parser.add_argument("--hitters", type=str, default="1,2,3,4",
                        help="comma-separated planted-hitter counts: "
                             "each bakes that steady frontier "
                             "trajectory")
    parser.add_argument("--grow-frontier", type=int, default=0,
                        help="also bake the all-survive growth "
                             "trajectory up to this frontier width "
                             "(covers padded-width growth programs)")
    parser.add_argument("--attributes", type=str, default="",
                        help="comma-separated attribute list: also "
                             "bake the attribute-metrics round "
                             "program for it (must match the serving "
                             "config's list exactly — the hashed "
                             "prefixes are baked into the program)")
    parser.add_argument("--width", type=int, default=8,
                        help="initial padded node width (grown on "
                             "demand, as at runtime)")
    parser.add_argument("--mesh", type=int, default=0,
                        help="bake mesh-sharded programs for this "
                             "many report-axis devices (0 = single "
                             "device; on CPU forces virtual devices)")
    parser.add_argument("--no-stablehlo", action="store_true",
                        help="skip the portable jax.export StableHLO "
                             "form (native executables only)")
    parser.add_argument("--smoke", action="store_true",
                        help="the `make artifacts-smoke` gate: bake "
                             "a tiny config, then prove a fresh "
                             "subprocess runs it trace-free and "
                             "bit-identical to the inline path")
    args = parser.parse_args()
    args.rows = [int(x) for x in str(args.rows).split(",") if x]
    args.hitters = [int(x) for x in str(args.hitters).split(",") if x]
    args.attributes = [x for x in str(args.attributes).split(",")
                       if x]

    if args.mesh:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.mesh}").strip()

    import jax

    requested = os.environ.get("JAX_PLATFORMS", "").strip()
    if requested and "axon" not in requested.split(","):
        jax.config.update("jax_platforms", requested)

    out = smoke(args) if args.smoke else bake(args)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
