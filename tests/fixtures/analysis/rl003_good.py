"""RL003 clean: the use happens strictly before the close (and the
close is guaranteed by the finally)."""
import socket


def reuse(host, port):
    sock = socket.create_connection((host, port))
    try:
        return sock.recv(16)
    finally:
        sock.close()
