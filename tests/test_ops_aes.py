"""Differential tests: batched bitsliced AES vs scalar reference."""

import numpy as np

from mastic_tpu.aes import Aes128
from mastic_tpu.ops.aes_jax import aes128_encrypt, aes128_key_schedule


def test_fips197_known_answer():
    key = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
    pt = bytes.fromhex("00112233445566778899aabbccddeeff")
    rk = aes128_key_schedule(np.frombuffer(key, np.uint8))
    ct = aes128_encrypt(rk, np.frombuffer(pt, np.uint8))
    assert bytes(np.asarray(ct)) == \
        bytes.fromhex("69c4e0d86a7b0430d8cdb78070b4c55a")


def test_batched_matches_scalar():
    rng = np.random.default_rng(2)
    keys = rng.integers(0, 256, size=(4, 16), dtype=np.uint8)
    blocks = rng.integers(0, 256, size=(4, 3, 16), dtype=np.uint8)
    rk = aes128_key_schedule(keys)           # (4, 11, 16)
    got = np.asarray(aes128_encrypt(rk[:, None], blocks))
    for b in range(4):
        cipher = Aes128(bytes(keys[b]))
        for n in range(3):
            assert bytes(got[b, n]) == cipher.encrypt_block(bytes(blocks[b, n]))


def test_key_schedule_matches_scalar():
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 256, size=(2, 16), dtype=np.uint8)
    rk = np.asarray(aes128_key_schedule(keys))
    for b in range(2):
        want = Aes128(bytes(keys[b])).round_keys
        for r in range(11):
            assert bytes(rk[b, r]) == want[r]
