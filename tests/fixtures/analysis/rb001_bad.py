"""Known-bad: blocking socket reads with no deadline (RB001)."""

import socket


def serve(server: socket.socket) -> bytes:
    (conn, _addr) = server.accept()
    return conn.recv(4)


def dial(port: int):
    sock = socket.create_connection(("127.0.0.1", port))
    return sock.makefile("rwb")
