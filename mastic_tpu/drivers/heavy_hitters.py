"""Weighted heavy hitters: the multi-round collector loop.

Functionally equivalent to the reference driver
(/root/reference/poc/examples.py:13-91) — per level, aggregate over the
candidate-prefix frontier, threshold-prune, expand survivors — but the
per-report prep loop is replaced by one batched device round per level
(both aggregators' prep + accept + aggregation on device; the FLP
verifier exchange on the weight-check round crosses the host boundary,
as it does between real aggregators).

Thresholds: a dict mapping prefix tuples to ints with a "default" key;
the threshold for a prefix is that of its *longest strict ancestor*
present in the dict, else the default (reference examples.py:26-34,
spec draft-mouris-cfrg-mastic.md:1535-1572).
"""

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common import gen_rand, vec_add
from ..mastic import Mastic
from ..backend.mastic_jax import BatchedMastic, ReportBatch


def get_reports_from_measurements(mastic: Mastic, ctx: bytes,
                                  measurements: Sequence) -> list:
    """Client side: shard each measurement with fresh randomness."""
    reports = []
    for measurement in measurements:
        nonce = gen_rand(mastic.NONCE_SIZE)
        rand = gen_rand(mastic.RAND_SIZE)
        (public_share, input_shares) = mastic.shard(
            ctx, measurement, nonce, rand)
        reports.append((nonce, public_share, input_shares))
    return reports


def get_threshold(thresholds: dict, prefix: tuple) -> int:
    """Longest-strict-ancestor threshold lookup."""
    for level in reversed(range(len(prefix) - 1)):
        if prefix[:level + 1] in thresholds:
            return thresholds[prefix[:level + 1]]
    return thresholds["default"]


def _round_fn(bm: BatchedMastic, verify_key: bytes, ctx: bytes,
              agg_param):
    """The jitted full-round function, cached on the BatchedMastic so
    repeated rounds with the same aggregation parameter (or repeated
    aggregate_by_attribute calls) reuse the compiled program."""
    cache = getattr(bm, "_round_cache", None)
    if cache is None:
        cache = {}
        bm._round_cache = cache
    key = (verify_key, ctx, agg_param)
    fn = cache.get(key)
    if fn is None:
        fn = jax.jit(lambda b: bm.round_device(verify_key, ctx,
                                               agg_param, b))
        cache[key] = fn
    return fn


def run_round(bm: BatchedMastic, verify_key: bytes, ctx: bytes,
              agg_param, batch: ReportBatch,
              accept_out: Optional[list] = None) -> list:
    """One aggregation round on the batched backend: both preps, all
    checks (incl. the device FLP on weight-check rounds), masked
    aggregation, unshard.  Returns the per-prefix aggregate result;
    appends the accept mask to `accept_out`."""
    (agg0, agg1, accept, ok) = _round_fn(bm, verify_key, ctx,
                                         agg_param)(batch)
    _require_ok(ok)
    accept = np.asarray(accept)
    if accept_out is not None:
        accept_out.append(accept)
    agg_shares = [bm.agg_share_to_host(a) for a in (agg0, agg1)]
    num = int(accept.sum())
    return bm.m.unshard(agg_param, agg_shares, num)


def _require_ok(ok) -> None:
    """Rejection sampling fired (~2^-32/element): the scalar fallback
    for affected reports is not wired up yet, so fail loudly rather
    than silently diverge."""
    if not bool(np.all(np.asarray(ok))):
        raise NotImplementedError(
            "XOF rejection-sampling fallback not yet implemented for "
            "this batch")


def compute_heavy_hitters(mastic: Mastic, ctx: bytes, thresholds: dict,
                          reports: list,
                          verify_key: Optional[bytes] = None,
                          incremental: bool = True) -> list:
    """The full collector loop (reference examples.py:37-91).

    With `incremental` (the default), each aggregator carries its
    prefix-tree state across rounds and only evaluates the new level's
    frontier — O(BITS * frontier) node evaluations for the whole run
    instead of O(BITS^2 * frontier) — using one compiled round program
    per padded frontier width (backend/incremental.py).  The
    `incremental=False` path re-evaluates from the root each round
    (one compile per level) and serves as the differential reference.
    """
    if verify_key is None:
        verify_key = gen_rand(mastic.VERIFY_KEY_SIZE)
    bm = BatchedMastic(mastic)
    batch = bm.marshal_reports(reports)
    runner = (_IncrementalRunner(bm, verify_key, ctx, batch)
              if incremental else None)

    prefixes: list = [(False,), (True,)]
    prev_agg_params: list = []
    heavy_hitters: list = []
    for level in range(mastic.vidpf.BITS):
        if not prefixes:
            break
        agg_param = (level, tuple(prefixes), level == 0)
        assert mastic.is_valid(agg_param, prev_agg_params)
        if runner is not None:
            agg_result = runner.round(agg_param)
        else:
            agg_result = run_round(bm, verify_key, ctx, agg_param,
                                   batch)
        prev_agg_params.append(agg_param)

        survivors = [
            prefix for (prefix, count) in zip(prefixes, agg_result)
            if count >= get_threshold(thresholds, prefix)
        ]
        if level < mastic.vidpf.BITS - 1:
            prefixes = [p + (bit,) for p in survivors
                        for bit in (False, True)]
        else:
            heavy_hitters = survivors
    return heavy_hitters


class _IncrementalRunner:
    """Drives backend/incremental.py across the collector loop: keeps
    both aggregators' carries, grows the padded width on demand
    (recompiling at most log2(max_width) times), and folds the
    weight-check FLP verdict of the level-0 round in via the fused
    round program."""

    def __init__(self, bm: BatchedMastic, verify_key: bytes, ctx: bytes,
                 batch: ReportBatch, width: int = 8):
        from ..backend.incremental import IncrementalMastic

        self.bm = bm
        self.verify_key = verify_key
        self.ctx = ctx
        self.batch = batch
        self.num_reports = int(batch.nonces.shape[0])
        self.width = max(4, width)
        self.engine = IncrementalMastic(bm, self.width)
        (self.ext_rk, self.conv_rk) = jax.jit(
            lambda n: bm.vidpf.roundkeys(ctx, n))(batch.nonces)
        self.carries = [
            self.engine.init_carry(self.num_reports,
                                   batch.keys[:, a], a)
            for a in range(2)
        ]
        self.carried_paths: list = []
        self.prev_paths = None
        self._eval_fn = None
        self._agg_fn = None

    def _grow(self, width: int) -> None:
        from ..backend.incremental import Carry, IncrementalMastic

        pad_nodes = width - self.width
        self.carries = [
            Carry(
                w=jnp.pad(c.w, ((0, 0), (0, 0), (0, pad_nodes),
                                (0, 0), (0, 0))),
                proof=jnp.pad(c.proof,
                              ((0, 0), (0, 0), (0, pad_nodes), (0, 0))),
                seed=jnp.pad(c.seed, ((0, 0), (0, pad_nodes), (0, 0))),
                ctrl=jnp.pad(c.ctrl, ((0, 0), (0, pad_nodes))),
            )
            for c in self.carries
        ]
        self.width = width
        self.engine = IncrementalMastic(self.bm, width)
        self._eval_fn = None
        self._agg_fn = None

    def _plan(self, prefixes, level):
        from ..backend.incremental import RoundPlan

        while True:
            try:
                return RoundPlan(prefixes, level,
                                 self.bm.m.vidpf.BITS, self.width,
                                 self.prev_paths, self.carried_paths)
            except ValueError as err:
                if "exceeds padded width" not in str(err):
                    raise
                self._grow(self.width * 2)

    def _fns(self):
        if self._eval_fn is None:
            engine = self.engine
            (vk, ctx) = (self.verify_key, self.ctx)

            def both(c0, c1, rnd, ext_rk, conv_rk, cws):
                (c0, proof0, out0, ok0) = engine.agg_round(
                    0, vk, ctx, c0, rnd, ext_rk, conv_rk, cws)
                (c1, proof1, out1, ok1) = engine.agg_round(
                    1, vk, ctx, c1, rnd, ext_rk, conv_rk, cws)
                accept = jnp.all(proof0 == proof1, axis=-1)
                return (c0, c1, out0, out1, accept, ok0 & ok1)

            def agg(out0, out1, accept):
                return (self.bm.aggregate(out0, accept),
                        self.bm.aggregate(out1, accept))

            self._eval_fn = jax.jit(both)
            self._agg_fn = jax.jit(agg)
        return (self._eval_fn, self._agg_fn)

    def round(self, agg_param) -> list:
        from ..backend.incremental import round_inputs

        (level, prefixes, do_weight_check) = agg_param
        plan = self._plan(prefixes, level)
        (eval_fn, agg_fn) = self._fns()
        (c0, c1, out0, out1, accept, ok) = eval_fn(
            self.carries[0], self.carries[1], round_inputs(plan),
            self.ext_rk, self.conv_rk, self.batch.cws)
        _require_ok(ok)
        self.carries = [c0, c1]
        self.carried_paths = plan.needed
        self.prev_paths = plan.needed[level]

        if do_weight_check:
            # The FLP weight check runs through the fused from-root
            # round program, re-evaluating level 0 (2 nodes wide —
            # negligible next to the deep levels) to reuse its
            # query/decide pipeline; its accept is authoritative.
            (_agg0, _agg1, wc_accept, wc_ok) = _round_fn(
                self.bm, self.verify_key, self.ctx, agg_param)(
                self.batch)
            _require_ok(wc_ok)
            accept = jnp.asarray(accept) & jnp.asarray(wc_accept)

        (agg0, agg1) = agg_fn(out0, out1, jnp.asarray(accept))
        rows = len(prefixes) * (1 + self.bm.m.flp.OUTPUT_LEN)
        agg_shares = [
            self.bm.agg_share_to_host(a[:rows]) for a in (agg0, agg1)
        ]
        num = int(np.asarray(accept).sum())
        return self.bm.m.unshard(agg_param, agg_shares, num)
