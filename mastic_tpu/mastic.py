"""The Mastic VDAF: a two-party, one-round VDAF for weighted heavy
hitters and attribute-based metrics, composing a VIDPF (input/prefix
side) with an FLP (weight-validity side).

Functionally equivalent to the reference (/root/reference/poc/mastic.py)
and byte-exact against /root/reference/test_vec/mastic/*.json, but the
aggregator hot path is organized around the level-synchronous prefix
tree of mastic_tpu.vidpf so the batched TPU backend
(mastic_tpu/backend/) can share the exact same schedule.
"""

from typing import Generic, Optional, TypeAlias, TypeVar

from .common import (concat, front, pack_bits, to_be_bytes, to_le_bytes,
                     unpack_bits, vec_add, vec_neg, vec_sub)
from .dst import (USAGE_EVAL_PROOF, USAGE_JOINT_RAND, USAGE_JOINT_RAND_PART,
                  USAGE_JOINT_RAND_SEED, USAGE_ONEHOT_CHECK,
                  USAGE_PAYLOAD_CHECK, USAGE_PROOF_SHARE, USAGE_PROVE_RAND,
                  USAGE_QUERY_RAND, dst_alg)
from .field import F, Field64, Field128
from .flp import (Count, FlpBBCGGI19, Histogram, MultihotCountVec, Sum,
                  SumVec, Valid)
from .vdaf import Vdaf
from .vidpf import PROOF_SIZE, CorrectionWord, Path, PrefixTree, Vidpf
from .xof import XofTurboShake128

W = TypeVar("W")
R = TypeVar("R")

MasticAggParam: TypeAlias = tuple[
    int,                  # level
    tuple[Path, ...],     # candidate prefixes
    bool,                 # whether to do the weight check
]

MasticInputShare: TypeAlias = tuple[
    bytes,              # VIDPF key
    Optional[list],     # FLP leader proof share
    Optional[bytes],    # FLP seed
    Optional[bytes],    # FLP peer joint rand part
]

MasticPrepState: TypeAlias = tuple[
    list,               # truncated output share
    Optional[bytes],    # predicted FLP joint rand seed
]

MasticPrepShare: TypeAlias = tuple[
    bytes,              # VIDPF eval proof
    Optional[list],     # FLP verifier share
    Optional[bytes],    # FLP joint randomness part
]

MasticPrepMessage: TypeAlias = Optional[bytes]  # FLP joint rand seed


class ReportRejected(Exception):
    """A report failed one of the protocol's validity checks (VIDPF
    eval proof, FLP decide, or joint-rand confirmation).  Distinct
    from programming/infrastructure errors so callers that treat
    rejection as a per-report verdict (e.g. the XOF rejection-sampling
    fallback) don't swallow real bugs."""


class Mastic(
        Generic[W, R, F],
        Vdaf[
            tuple[Path, W],          # Measurement
            MasticAggParam,
            list[CorrectionWord],    # PublicShare
            MasticInputShare,
            list,                    # OutShare
            list,                    # AggShare
            list,                    # AggResult
            MasticPrepState,
            MasticPrepShare,
            MasticPrepMessage,
        ]):

    xof = XofTurboShake128

    ID: int = 0xFFFFFFFF
    VERIFY_KEY_SIZE = XofTurboShake128.SEED_SIZE
    NONCE_SIZE = 16
    SHARES = 2
    ROUNDS = 1

    test_vec_name = "Mastic"

    def __init__(self, bits: int, valid: Valid[W, R, F]):
        self.field: type[F] = valid.field
        self.flp = FlpBBCGGI19(valid)
        self.vidpf = Vidpf(valid.field, bits, 1 + valid.MEAS_LEN)
        self.RAND_SIZE = self.vidpf.RAND_SIZE + 2 * self.xof.SEED_SIZE
        if self.flp.JOINT_RAND_LEN > 0:  # FLP leader seed
            self.RAND_SIZE += self.xof.SEED_SIZE

    # -- client (reference mastic.py:91-185) -----------------------

    def shard(self, ctx: bytes, measurement: "tuple[Path, W]",
          nonce: bytes, rand: bytes
          ) -> tuple[list[CorrectionWord], list[MasticInputShare]]:
        """Produce the public share (VIDPF correction words) and the
        two input shares.  One code path serves both FLP families: for
        joint-rand circuits the client additionally derives both
        parties' joint-rand parts itself (it knows both beta shares)
        and attaches the peer's part to each input share.
        """
        use_jr = self.flp.JOINT_RAND_LEN > 0
        seeds_needed = 3 if use_jr else 2
        (vidpf_rand, rest) = front(self.vidpf.RAND_SIZE, rand)
        seeds = []
        for _ in range(seeds_needed):
            (seed, rest) = front(self.xof.SEED_SIZE, rest)
            seeds.append(bytes(seed))
        assert len(rest) == 0
        (prove_rand_seed, helper_seed) = seeds[:2]
        leader_seed = seeds[2] if use_jr else None

        # beta = counter || encoded weight.
        (alpha, weight) = measurement
        beta = [self.field(1)] + self.flp.encode(weight)

        (correction_words, keys) = \
            self.vidpf.gen(alpha, beta, ctx, nonce, vidpf_rand)

        joint_rand: list[F] = []
        parts = None
        if use_jr:
            # Each party contributes a part bound to its beta share;
            # the client evaluates both shares to compute both parts.
            parts = []
            for (agg_id, seed) in ((0, leader_seed), (1, helper_seed)):
                beta_share = self.vidpf.get_beta_share(
                    agg_id, correction_words, keys[agg_id], ctx, nonce)
                parts.append(self.joint_rand_part(
                    ctx, seed, beta_share[1:], nonce))
            joint_rand = self.joint_rand(
                ctx, self.joint_rand_seed(ctx, parts))

        proof = self.flp.prove(beta[1:],
                               self.prove_rand(ctx, prove_rand_seed),
                               joint_rand)
        leader_proof_share = vec_sub(
            proof, self.helper_proof_share(ctx, helper_seed))

        input_shares: list[MasticInputShare] = [
            (keys[0], leader_proof_share, leader_seed,
             parts[1] if parts else None),
            (keys[1], None, helper_seed, parts[0] if parts else None),
        ]
        return (correction_words, input_shares)

    # -- aggregation-parameter policy (reference mastic.py:187-203) -

    def is_valid(self, agg_param: MasticAggParam,
             previous_agg_params: list[MasticAggParam]) -> bool:
        (level, _prefixes, do_weight_check) = agg_param

        # The weight check happens exactly once, on the first round.
        weight_checked = \
            (do_weight_check and len(previous_agg_params) == 0) or \
            (not do_weight_check and
                any(prev[2] for prev in previous_agg_params))

        # The level is strictly increasing between rounds.
        level_increased = len(previous_agg_params) == 0 or \
            level > previous_agg_params[-1][0]

        return weight_checked and level_increased

    # -- aggregator (reference mastic.py:205-318) ------------------

    def prep_init(self, verify_key: bytes, ctx: bytes, agg_id: int,
                  agg_param: MasticAggParam, nonce: bytes,
                  correction_words: list[CorrectionWord],
                  input_share: MasticInputShare
                  ) -> tuple[MasticPrepState, MasticPrepShare]:
        (level, prefixes, do_weight_check) = agg_param
        (key, proof_share, seed, peer_joint_rand_part) = \
            self.expand_input_share(ctx, agg_id, input_share)

        # Evaluate the VIDPF over the level-synchronous node grid.
        (out_share, tree) = self.vidpf.eval_level_synchronous(
            agg_id, correction_words, key, level, prefixes, ctx, nonce)

        # Weight check: query the FLP against this party's beta share.
        joint_rand_part = None
        joint_rand_seed = None
        verifier_share = None
        if do_weight_check:
            # This party's beta share is the sum of the two depth-1
            # payloads, both already present in the evaluated tree.
            beta_share = vec_add(tree.levels[0][(False,)].w,
                                 tree.levels[0][(True,)].w)
            if agg_id == 1:
                beta_share = vec_neg(beta_share)
            query_rand = self.query_rand(verify_key, ctx, nonce, level)
            joint_rand: list[F] = []
            if self.flp.JOINT_RAND_LEN > 0:
                assert seed is not None
                assert peer_joint_rand_part is not None
                joint_rand_part = self.joint_rand_part(
                    ctx, seed, beta_share[1:], nonce)
                if agg_id == 0:
                    joint_rand_parts = [joint_rand_part,
                                        peer_joint_rand_part]
                else:
                    joint_rand_parts = [peer_joint_rand_part,
                                        joint_rand_part]
                joint_rand_seed = self.joint_rand_seed(
                    ctx, joint_rand_parts)
                joint_rand = self.joint_rand(ctx, joint_rand_seed)
            verifier_share = self.flp.query(
                beta_share[1:], proof_share, query_rand, joint_rand, 2)

        (payload_check_binder, onehot_check_binder) = \
            self.check_binders(tree)

        payload_check = self.xof(
            b"",
            dst_alg(ctx, USAGE_PAYLOAD_CHECK, self.ID),
            payload_check_binder,
        ).next(PROOF_SIZE)

        onehot_check = self.xof(
            b"",
            dst_alg(ctx, USAGE_ONEHOT_CHECK, self.ID),
            onehot_check_binder,
        ).next(PROOF_SIZE)

        # Counter check: beta[0] must equal 1.  Aggregator 1 adds 1 to
        # its (negated) share so both parties derive the same bytes iff
        # the counter is correct.
        w0 = tree.levels[0][(False,)].w
        w1 = tree.levels[0][(True,)].w
        counter_check = self.field.encode_vec(
            [w0[0] + w1[0] + self.field(agg_id)])

        # A single proof binding all three checks.
        eval_proof = self.xof(
            verify_key,
            dst_alg(ctx, USAGE_EVAL_PROOF, self.ID),
            onehot_check + counter_check + payload_check,
        ).next(PROOF_SIZE)

        # Truncate each per-prefix payload to its aggregatable part.
        truncated_out_share: list[F] = []
        for val_share in out_share:
            truncated_out_share += [val_share[0]] + \
                self.flp.truncate(val_share[1:])

        prep_state = (truncated_out_share, joint_rand_seed)
        prep_share = (eval_proof, verifier_share, joint_rand_part)
        return (prep_state, prep_share)

    def check_binders(self, tree: PrefixTree[F]) -> tuple[bytes, bytes]:
        """Assemble the payload- and onehot-check binders.

        The reference walks its lazily built tree breadth-first
        (mastic.py:258-287); the equivalent order here is: per depth,
        nodes in lexicographic path order (see vidpf.tree_schedule).
        Every materialized node contributes its proof to the onehot
        binder; every *internal* node (one with both children, i.e. a
        path node) contributes `w - w_left - w_right` to the payload
        binder.
        """
        payload_check_binder = b""
        onehot_check_binder = b""
        for (depth, nodes) in enumerate(tree.levels):
            next_nodes = tree.levels[depth + 1] \
                if depth + 1 < len(tree.levels) else {}
            for (path, node) in nodes.items():
                left = next_nodes.get(path + (False,))
                right = next_nodes.get(path + (True,))
                if left is not None and right is not None:
                    payload_check_binder += self.field.encode_vec(
                        vec_sub(node.w, vec_add(left.w, right.w)))
                onehot_check_binder += node.proof
        return (payload_check_binder, onehot_check_binder)

    def prep_shares_to_prep(self, ctx: bytes,
                        agg_param: MasticAggParam,
                        prep_shares: list[MasticPrepShare]
                        ) -> MasticPrepMessage:
        (_level, _prefixes, do_weight_check) = agg_param

        if len(prep_shares) != 2:
            raise ValueError("unexpected number of prep shares")

        (eval_proof_0, verifier_share_0, joint_rand_part_0) = prep_shares[0]
        (eval_proof_1, verifier_share_1, joint_rand_part_1) = prep_shares[1]

        # VIDPF validity: both parties must derive identical proofs.
        if eval_proof_0 != eval_proof_1:
            raise ReportRejected("VIDPF verification failed")

        if not do_weight_check:
            return None
        if verifier_share_0 is None or verifier_share_1 is None:
            raise ValueError("expected FLP verifier shares")

        # FLP validity.
        verifier = vec_add(verifier_share_0, verifier_share_1)
        if not self.flp.decide(verifier):
            raise ReportRejected("FLP verification failed")

        if self.flp.JOINT_RAND_LEN == 0:
            return None
        if joint_rand_part_0 is None or joint_rand_part_1 is None:
            raise ValueError("expected FLP joint randomness parts")

        return self.joint_rand_seed(ctx, [joint_rand_part_0,
                                          joint_rand_part_1])

    def prep_next(self, _ctx: bytes, prep_state: MasticPrepState,
              prep_msg: MasticPrepMessage) -> list:
        (truncated_out_share, joint_rand_seed) = prep_state
        if joint_rand_seed is not None:
            if prep_msg is None:
                raise ValueError("expected joint rand confirmation")
            if prep_msg != joint_rand_seed:
                raise ReportRejected("joint rand confirmation failed")
        return truncated_out_share

    # -- aggregation & collection (reference mastic.py:379-411) ----

    def agg_init(self, agg_param: MasticAggParam) -> list:
        (_level, prefixes, _do_weight_check) = agg_param
        return self.field.zeros(len(prefixes) * (1 + self.flp.OUTPUT_LEN))

    def agg_update(self, agg_param: MasticAggParam, agg_share: list,
               out_share: list) -> list:
        return vec_add(agg_share, out_share)

    def merge(self, agg_param: MasticAggParam,
          agg_shares: list) -> list:
        agg = self.agg_init(agg_param)
        for agg_share in agg_shares:
            agg = vec_add(agg, agg_share)
        return agg

    def unshard(self, agg_param: MasticAggParam, agg_shares: list,
            _num_measurements: int) -> list:
        agg = self.merge(agg_param, agg_shares)
        agg_result = []
        while len(agg) > 0:
            (chunk, agg) = front(1 + self.flp.OUTPUT_LEN, agg)
            meas_count = chunk[0].int()
            agg_result.append(self.flp.decode(chunk[1:], meas_count))
        return agg_result

    # -- wire encodings (reference mastic.py:413-435, :512-559) ----

    def encode_agg_param(self, agg_param: MasticAggParam) -> bytes:
        (level, prefixes, do_weight_check) = agg_param
        if level not in range(2 ** 16):
            raise ValueError("level out of range")
        if len(prefixes) not in range(2 ** 32):
            raise ValueError("number of prefixes out of range")
        encoded = bytes()
        encoded += to_be_bytes(level, 2)
        encoded += to_be_bytes(len(prefixes), 4)
        for prefix in prefixes:
            encoded += pack_bits(list(prefix))
        encoded += to_be_bytes(int(do_weight_check), 1)
        return encoded

    def decode_agg_param(self, encoded: bytes) -> MasticAggParam:
        if len(encoded) < 7:
            raise ValueError("malformed agg param")
        level = int.from_bytes(encoded[:2], "big")
        num_prefixes = int.from_bytes(encoded[2:6], "big")
        prefix_bytes = ((level + 1) + 7) // 8
        if len(encoded) != 6 + num_prefixes * prefix_bytes + 1:
            raise ValueError("malformed agg param")
        off = 6
        prefixes = []
        for _ in range(num_prefixes):
            chunk = encoded[off:off + prefix_bytes]
            prefixes.append(tuple(unpack_bits(chunk, level + 1)))
            off += prefix_bytes
        do_weight_check = bool(encoded[off])
        return (level, tuple(prefixes), do_weight_check)

    def expand_input_share(
            self, ctx: bytes, agg_id: int,
            input_share: MasticInputShare
    ) -> tuple[bytes, list, Optional[bytes], Optional[bytes]]:
        if agg_id == 0:
            (key, proof_share, seed, peer_joint_rand_part) = input_share
            assert proof_share is not None
        else:
            (key, _leader_share, seed, peer_joint_rand_part) = input_share
            assert seed is not None
            proof_share = self.helper_proof_share(ctx, seed)
        return (key, proof_share, seed, peer_joint_rand_part)

    # -- XOF derivations (reference mastic.py:452-510) -------------
    #
    # Every per-protocol random vector is one row of this table: the
    # XOF usage plus which FLP length it expands to.  The seed and
    # binder vary per row and are supplied by the caller.

    _VEC_DERIVATIONS = {
        "prove_rand": (USAGE_PROVE_RAND, "PROVE_RAND_LEN"),
        "proof_share": (USAGE_PROOF_SHARE, "PROOF_LEN"),
        "joint_rand": (USAGE_JOINT_RAND, "JOINT_RAND_LEN"),
        "query_rand": (USAGE_QUERY_RAND, "QUERY_RAND_LEN"),
    }

    def derive_vec(self, what: str, ctx: bytes, seed: bytes,
                   binder: bytes = b"") -> list[F]:
        (usage, length_attr) = self._VEC_DERIVATIONS[what]
        return self.xof.expand_into_vec(
            self.field, seed, dst_alg(ctx, usage, self.ID), binder,
            getattr(self.flp, length_attr))

    def prove_rand(self, ctx: bytes, seed: bytes) -> list[F]:
        return self.derive_vec("prove_rand", ctx, seed)

    def helper_proof_share(self, ctx: bytes, seed: bytes) -> list[F]:
        return self.derive_vec("proof_share", ctx, seed)

    def joint_rand(self, ctx: bytes, seed: bytes) -> list[F]:
        return self.derive_vec("joint_rand", ctx, seed)

    def query_rand(self, verify_key: bytes, ctx: bytes, nonce: bytes,
                   level: int) -> list[F]:
        return self.derive_vec("query_rand", ctx, verify_key,
                               nonce + to_le_bytes(level, 2))

    def joint_rand_part(self, ctx: bytes, seed: bytes,
                        weight_share: list[F], nonce: bytes) -> bytes:
        return self.xof.derive_seed(
            seed, dst_alg(ctx, USAGE_JOINT_RAND_PART, self.ID),
            nonce + self.field.encode_vec(weight_share))

    def joint_rand_seed(self, ctx: bytes, parts: list[bytes]) -> bytes:
        return self.xof.derive_seed(
            b"", dst_alg(ctx, USAGE_JOINT_RAND_SEED, self.ID),
            concat(parts))


##
# INSTANTIATIONS (reference mastic.py:567-614; IANA codepoints from
# draft-mouris-cfrg-mastic.md:1359-1366)
#


class MasticCount(Mastic[int, int, Field64]):
    ID = 0xFFFF0001
    test_vec_name = "MasticCount"

    def __init__(self, bits: int):
        super().__init__(bits, Count(Field64))


class MasticSum(Mastic[int, int, Field64]):
    ID = 0xFFFF0002
    test_vec_name = "MasticSum"

    def __init__(self, bits: int, max_measurement: int):
        super().__init__(bits, Sum(Field64, max_measurement))


class MasticSumVec(Mastic[list[int], list[int], Field128]):
    ID = 0xFFFF0003
    test_vec_name = "MasticSumVec"

    def __init__(self, bits: int, length: int, sum_vec_bits: int,
                 chunk_length: int):
        super().__init__(
            bits, SumVec(Field128, length, sum_vec_bits, chunk_length))


class MasticHistogram(Mastic[int, list[int], Field128]):
    ID = 0xFFFF0004
    test_vec_name = "MasticHistogram"

    def __init__(self, bits: int, length: int, chunk_length: int):
        super().__init__(bits, Histogram(Field128, length, chunk_length))


class MasticMultihotCountVec(Mastic[list[bool], list[int], Field128]):
    ID = 0xFFFF0005
    test_vec_name = "MasticMultihotCountVec"

    def __init__(self, bits: int, length: int, max_weight: int,
                 chunk_length: int):
        super().__init__(
            bits, MultihotCountVec(Field128, length, max_weight,
                                   chunk_length))
