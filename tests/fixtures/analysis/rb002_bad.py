"""Known-bad: except blocks that swallow the error (RB002)."""


def swallow(path: str) -> int:
    total = 0
    try:
        with open(path) as f:
            total = len(f.read())
    except OSError:
        pass
    for line in range(3):
        try:
            total += int(line)
        except ValueError:
            continue
    return total
