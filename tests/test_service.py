"""Continuous-ingest collector service (ISSUE 6): admission control,
backpressure, paged buffers, supervised multi-tenant epochs, and
crash-resume through the service snapshot.

Fast tier (run via `make serve-smoke`, wired into `make ci`; also in
the plain fast suite): the host-side admission/backpressure/paging
machinery (no device rounds), the upload-path fault checkpoints
(hang during admission, corrupt page flush, kill during admission in
a subprocess that dies before any compile), the with_retries
deadline-clamp fix, and ONE end-to-end epoch proving the scheduler
path bit-identical to the offline batch path including a mid-epoch
snapshot/discard/resume.  Slow tier: the subprocess kill-9 +
`--resume` pair through `tools/serve.py`, the two-tenant
interleaving proof, the epoch-deadline degradation, and the
mesh-sharded bit-identity.
"""

import json
import os
import pathlib
import subprocess
import sys
import time

import pytest

from mastic_tpu.common import gen_rand
from mastic_tpu.drivers import faults
from mastic_tpu.drivers.heavy_hitters import (
    HeavyHittersRun, get_reports_from_measurements)
from mastic_tpu.drivers.service import (ADMITTED, QUARANTINED, SHED,
                                        CollectionRun,
                                        CollectorService,
                                        ServiceConfig, TenantSpec,
                                        decode_upload, encode_upload,
                                        thresholds_from_json,
                                        thresholds_to_json)
from mastic_tpu.drivers.session import (Deadline, SessionError,
                                        with_retries)
from mastic_tpu.mastic import MasticCount

CTX = b"service test"
COUNT2 = {"class": "MasticCount", "args": [2]}

REPO = pathlib.Path(__file__).parent.parent


def _reports(m, values, bits=2):
    meas = [(m.vidpf.test_index_from_int(v, bits), True)
            for v in values]
    return get_reports_from_measurements(m, CTX, meas)


def _spec(name="count", vk=None, m=None, **over):
    m = m or MasticCount(2)
    over.setdefault("thresholds", {"default": 2})
    return TenantSpec(name=name, spec=COUNT2, ctx=CTX,
                      verify_key=vk or gen_rand(m.VERIFY_KEY_SIZE),
                      **over)


def _cfg(**over):
    base = dict(page_size=2, max_buffered=64, max_pending_epochs=4,
                shed_policy="reject-newest", quarantine_limit=16,
                epoch_deadline=600.0)
    base.update(over)
    return ServiceConfig(**base)


def _admit(svc, tenant, m, reports):
    return [svc.submit(tenant, encode_upload(m, r)) for r in reports]


# -- with_retries deadline clamp (the r8 backoff bugfix) -------------

def test_with_retries_clamps_sleep_to_deadline():
    """A retry ladder whose backoff exceeds the remaining Deadline
    budget must fail fast with attribution, not sleep through it
    (previously it slept the FULL backoff regardless)."""
    calls = []

    def failing():
        calls.append(1)
        raise SessionError("helper", "upload", "timeout", "nope")

    t0 = time.monotonic()
    with pytest.raises(SessionError) as ei:
        with_retries(failing, attempts=5, backoff=10.0,
                     deadline=Deadline(0.3))
    elapsed = time.monotonic() - t0
    assert elapsed < 2.0, f"slept {elapsed:.1f}s past a 0.3s budget"
    assert ei.value.kind == "timeout"
    assert ei.value.party == "helper" and ei.value.step == "upload"
    assert "retry budget exhausted" in ei.value.detail
    assert len(calls) >= 2   # at least one clamped retry ran


def test_with_retries_unbounded_keeps_old_behavior():
    attempts = []

    def failing():
        attempts.append(1)
        raise SessionError("helper", "upload", "timeout", "nope")

    with pytest.raises(SessionError) as ei:
        with_retries(failing, attempts=2, backoff=0.01)
    assert len(attempts) == 3
    assert ei.value.detail == "nope"   # the original error surfaces


# -- upload codec + admission ----------------------------------------

def test_upload_codec_roundtrip():
    m = MasticCount(2)
    report = _reports(m, [1])[0]
    blob = encode_upload(m, report)
    (nonce, _ps, shares) = decode_upload(m, blob)
    assert nonce == report[0]
    assert len(shares) == 2
    with pytest.raises(ValueError):
        decode_upload(m, blob + b"x")
    with pytest.raises(ValueError):
        decode_upload(m, blob[:-1])


def test_malformed_uploads_quarantined_then_suspended():
    m = MasticCount(2)
    svc = CollectorService([_spec(quarantine_limit=3)],
                           config=_cfg())
    for (i, blob) in enumerate((b"", b"\x07garbage", b"\xff" * 40)):
        (status, reason) = svc.submit("count", blob)
        assert status == QUARANTINED
        assert reason == "malformed"
    # the limit hit: the tenant is suspended, later uploads shed
    (status, reason) = svc.submit(
        "count", encode_upload(m, _reports(m, [0])[0]))
    assert (status, reason) == (SHED, "tenant-quarantined")
    c = svc.metrics()["tenants"]["count"]
    assert c["suspended"]
    assert c["counters"]["quarantined"] == 3
    assert c["counters"]["quarantine_reasons"] == {"malformed": 3}
    assert c["counters"]["shed_reasons"] == {"tenant-quarantined": 1}


def test_quota_reject_newest_and_page_seal():
    m = MasticCount(2)
    svc = CollectorService([_spec(max_buffered=3)],
                           config=_cfg(page_size=2))
    outcomes = _admit(svc, "count", m, _reports(m, [0] * 5))
    assert [o[0] for o in outcomes] == \
        [ADMITTED, ADMITTED, ADMITTED, SHED, SHED]
    t = svc.metrics()["tenants"]["count"]
    assert t["buffered_reports"] == 3      # bounded, not 5
    assert t["sealed_pages"] == 1 and t["open_page"] == 1
    assert t["counters"]["pages_sealed"] == 1
    assert t["counters"]["shed_reasons"] == {"reject-newest": 2}


def test_shed_oldest_epoch_first_makes_room():
    m = MasticCount(2)
    svc = CollectorService(
        [_spec(max_buffered=4)],
        config=_cfg(shed_policy="oldest-epoch-first"))
    _admit(svc, "count", m, _reports(m, [0] * 4))
    assert svc.begin_epoch("count") == 0
    outcomes = _admit(svc, "count", m, _reports(m, [1] * 2))
    assert [o[0] for o in outcomes] == [ADMITTED, ADMITTED]
    t = svc.metrics()["tenants"]["count"]
    assert t["pending_epochs"] == 0        # epoch 0 was dropped
    assert t["counters"]["shed"] == 4
    assert t["counters"]["shed_reasons"] == {"oldest-epoch-first": 4}


def test_epoch_queue_bound_refuses_cut():
    m = MasticCount(2)
    svc = CollectorService([_spec()],
                           config=_cfg(max_pending_epochs=1))
    _admit(svc, "count", m, _reports(m, [0] * 2))
    assert svc.begin_epoch("count") == 0
    _admit(svc, "count", m, _reports(m, [1] * 2))
    assert svc.begin_epoch("count") is None   # queue full, counted
    t = svc.metrics()["tenants"]["count"]
    assert t["counters"]["epochs_refused"] == 1
    assert t["pending_epochs"] == 1
    assert t["buffered_reports"] == 4         # pages stay buffered


def test_empty_epoch_cut_is_none():
    svc = CollectorService([_spec()], config=_cfg())
    assert svc.begin_epoch("count") is None
    assert svc.drained()


# -- upload-path fault checkpoints (MASTIC_FAULTS extensions) --------

def test_hang_during_admission_checkpoint_fires():
    """`delay:party=collector:step=admit` stalls exactly one
    admission — the in-process probe that the admit checkpoint is
    wired (the kill flavor runs as a subprocess below)."""
    m = MasticCount(2)
    inj = faults.FaultInjector(
        faults.parse_faults(
            "delay:party=collector:step=admit:nth=2:delay=0.3"),
        "collector")
    svc = CollectorService([_spec()], config=_cfg(), injector=inj)
    reports = _reports(m, [0, 1])
    t0 = time.monotonic()
    svc.submit("count", encode_upload(m, reports[0]))
    fast = time.monotonic() - t0
    t0 = time.monotonic()
    svc.submit("count", encode_upload(m, reports[1]))
    stalled = time.monotonic() - t0
    assert stalled >= 0.3 > fast


def test_kill_during_admission_subprocess():
    """`kill:party=collector:step=admit` dies with the injector's
    exit code before any compile — the service crashes attributably
    at the ingest door, and a fresh boot is clean (uploads since the
    last snapshot are the client's to retry, as in any ingest
    service)."""
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "MASTIC_FAULTS": "kill:party=collector:step=admit:nth=3"}
    proc = subprocess.run(
        [sys.executable, "tools/serve.py", "--reports", "4",
         "--epochs", "1"],
        cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=240)
    assert proc.returncode == faults.KILL_EXIT_CODE, proc.stderr[-800:]


def test_corrupt_page_flush_detected_and_degraded():
    """`corrupt:party=collector:step=page_flush` mutates a sealed
    page's stored bytes after its digest — the epoch must detect the
    mismatch, drop the page with reason `page-corrupt`, and degrade
    (here: every page corrupt, so the epoch finalizes empty) instead
    of aggregating garbage."""
    m = MasticCount(2)
    inj = faults.FaultInjector(
        faults.parse_faults(
            "corrupt:party=collector:step=page_flush:offset=9"),
        "collector")
    svc = CollectorService([_spec()], config=_cfg(page_size=2),
                           injector=inj)
    _admit(svc, "count", m, _reports(m, [0, 3]))
    assert svc.begin_epoch("count") == 0
    assert not svc.step()                   # degraded, drained
    t = svc.metrics()["tenants"]["count"]
    assert t["counters"]["pages_corrupt"] == 1
    assert t["counters"]["quarantine_reasons"] == {"page-corrupt": 2}
    rec = t["epochs"][0]
    assert rec["truncated"] and rec["result"] == []
    assert rec["levels_completed"] == 0


# -- supervision: a raising round must not take the service down ----

class _StubRun:
    """Duck-typed CollectionRun: completes in 2 steps, or raises on
    every step when flaky (a rebuilt replacement is healthy)."""

    def __init__(self, flaky):
        self.flaky = flaky
        self.metrics = []
        self.done = False
        self._n = 0

    def step(self):
        if self.flaky:
            raise RuntimeError("injected round failure")
        self._n += 1
        self.done = self._n >= 2
        return not self.done

    def result(self):
        return ["ok"]

    def frontier(self):
        return []

    def rounds_completed(self):
        return self._n

    def to_bytes(self):
        return b"{}"


def test_supervised_retry_rebuilds_run():
    """The first round raising marks a failure and REBUILDS the run
    from the epoch's reports (a half-executed round may have left
    device state inconsistent); the rebuilt run completes and the
    epoch record is clean."""
    m = MasticCount(2)
    svc = CollectorService([_spec()], config=_cfg(epoch_retries=1))
    builds = []

    def fake_build(t, reports):
        run = _StubRun(flaky=not builds)   # only the first is flaky
        builds.append(run)
        return run

    svc._build_run = fake_build
    _admit(svc, "count", m, _reports(m, [0, 3]))
    svc.begin_epoch("count")
    assert svc.run_until_drained(deadline=Deadline(30.0))
    assert len(builds) == 2
    rec = svc.metrics()["tenants"]["count"]["epochs"][0]
    assert "error" not in rec and not rec["truncated"]
    assert rec["result"] == ["ok"]
    c = svc.metrics()["tenants"]["count"]["counters"]
    assert c["epochs_completed"] == 1 and c["epochs_failed"] == 0


def test_run_construction_refusal_fails_epoch_not_service():
    """A tenant whose run cannot even be built (e.g. the memory
    envelope refuses its chunk config) fails its epoch attributably;
    the service keeps going."""
    m = MasticCount(2)
    svc = CollectorService([_spec()], config=_cfg())

    def refuse(t, reports):
        raise ValueError("envelope refused")

    svc._build_run = refuse
    _admit(svc, "count", m, _reports(m, [0, 3]))
    svc.begin_epoch("count")
    assert not svc.step()
    rec = svc.metrics()["tenants"]["count"]["epochs"][0]
    assert rec["truncated"] and "envelope refused" in rec["error"]
    c = svc.metrics()["tenants"]["count"]["counters"]
    assert c["epochs_failed"] == 1
    # admission still works afterwards
    assert svc.submit(
        "count", encode_upload(m, _reports(m, [1])[0]))[0] == ADMITTED


def test_supervised_epoch_fails_after_retries_exhausted():
    m = MasticCount(2)
    svc = CollectorService([_spec()], config=_cfg(epoch_retries=1))
    svc._build_run = lambda t, reports: _StubRun(flaky=True)
    _admit(svc, "count", m, _reports(m, [0, 3]))
    svc.begin_epoch("count")
    assert svc.run_until_drained(deadline=Deadline(30.0))
    rec = svc.metrics()["tenants"]["count"]["epochs"][0]
    assert rec["truncated"] and "injected round failure" in rec["error"]
    c = svc.metrics()["tenants"]["count"]["counters"]
    assert c["epochs_failed"] == 1 and c["epochs_completed"] == 0


# -- snapshot plumbing (no rounds) -----------------------------------

def test_snapshot_refuses_garbage():
    with pytest.raises(ValueError):
        CollectorService.from_bytes(b"\xff" * 64)


def test_snapshot_roundtrip_preserves_buffers_and_counters():
    m = MasticCount(2)
    svc = CollectorService([_spec()], config=_cfg(page_size=2))
    svc.submit("count", b"junk")                      # quarantine
    _admit(svc, "count", m, _reports(m, [0, 3, 1]))   # page + open
    assert svc.begin_epoch("count") == 0              # seals the tail
    _admit(svc, "count", m, _reports(m, [2]))         # new open page
    blob = svc.to_bytes()
    svc2 = CollectorService.from_bytes(blob, config=_cfg(page_size=2))
    (t1, t2) = (svc.metrics()["tenants"]["count"],
                svc2.metrics()["tenants"]["count"])
    assert t2["buffered_reports"] == t1["buffered_reports"] == 4
    assert t2["pending_epochs"] == 1 and t2["open_page"] == 1
    assert t2["counters"]["quarantined"] == 1
    assert t2["counters"]["resumes"] == 1
    # the restored open page keeps accepting uploads
    assert svc2.submit(
        "count", encode_upload(m, _reports(m, [1])[0]))[0] == ADMITTED


def test_thresholds_json_roundtrip():
    thr = {"default": 5, (False, True): 2, (True,): 9}
    assert thresholds_from_json(thresholds_to_json(thr)) == thr
    enc = json.dumps(thresholds_to_json(thr))   # must be JSON-safe
    assert thresholds_from_json(json.loads(enc)) == thr


def test_collection_run_interface_registration():
    from mastic_tpu.drivers.attribute_metrics import AttributeMetricsRun

    assert issubclass(HeavyHittersRun, CollectionRun)
    assert issubclass(AttributeMetricsRun, CollectionRun)


def test_heavy_hitters_frontier_semantics():
    """frontier() is the truncated-but-correct contract: [] before
    any completed level, the unique parents of the expanded candidate
    set mid-run, the final hitters when done."""
    m = MasticCount(3)
    run = HeavyHittersRun(m, CTX, {"default": 2},
                          _reports(m, [0, 7], bits=3),
                          incremental=False)
    assert run.frontier() == []
    # mid-run state as step() leaves it after level 0: survivors
    # (False,), (True,) expanded into their children.
    run.level = 1
    run.prefixes = [(False, False), (False, True),
                    (True, False), (True, True)]
    assert run.frontier() == [(False,), (True,)]
    run.done = True
    run.heavy_hitters = [(False, False, False)]
    assert run.frontier() == [(False, False, False)]


# -- the end-to-end acceptance: scheduler == offline batch, resume ---

@pytest.mark.slow
def test_epoch_bit_identical_to_offline_with_mid_epoch_resume():
    """One tenant, two epochs over the same values: (a) the scheduler
    path's hitters and per-level accept counters equal the offline
    batch run's bit for bit; (b) an epoch snapshotted mid-run,
    abandoned (the kill-9 state model: only the snapshot survives),
    and resumed in a fresh service finishes with the identical
    result.

    Slow-marked to keep the plain fast tier inside its budget, but
    `make serve-smoke` runs it explicitly by node id — it IS the
    gate's acceptance test."""
    m = MasticCount(2)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    values = [0, 0, 0, 3, 3]
    reports = _reports(m, values)

    offline = HeavyHittersRun(m, CTX, {"default": 2}, reports,
                              verify_key=vk)
    while offline.step():
        pass

    svc = CollectorService([_spec(vk=vk)], config=_cfg(page_size=3))
    _admit(svc, "count", m, reports)
    assert svc.begin_epoch("count") == 0
    assert svc.run_until_drained(deadline=Deadline(600.0))
    rec = svc.metrics()["tenants"]["count"]["epochs"][0]
    assert not rec["truncated"]
    assert rec["result"] == [[bool(b) for b in p]
                             for p in offline.result()]
    assert rec["levels_completed"] == len(offline.metrics)

    # (b) second epoch: same uploads, snapshot after one round,
    # abandon the live service, resume, finish — bit-identical.
    _admit(svc, "count", m, reports)
    assert svc.begin_epoch("count") == 1
    assert svc.step()            # one scheduler quantum = one round
    active = next(iter(svc.tenants.values())).active
    assert active is not None and len(active.run.metrics) == 1
    mx0 = active.run.metrics[0]
    assert mx0.extra["service"]["tenant"] == "count"
    assert mx0.extra["service"]["epoch"] == 1
    assert mx0.accepted == offline.metrics[0].accepted
    blob = svc.to_bytes()
    del svc                      # kill -9 state model
    svc2 = CollectorService.from_bytes(blob, config=_cfg(page_size=3))
    assert svc2.run_until_drained(deadline=Deadline(600.0))
    rec2 = svc2.metrics()["tenants"]["count"]["epochs"][1]
    assert not rec2["truncated"]
    assert rec2["result"] == rec["result"]
    assert rec2["levels_completed"] == rec["levels_completed"]
    c = svc2.metrics()["tenants"]["count"]["counters"]
    assert c["resumes"] == 1 and c["epochs_completed"] == 2


# -- slow tier: subprocess kill-9, interleaving, deadline, mesh ------

def _run_serve(extra_args, fault_spec=None, timeout=900):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("MASTIC_FAULTS", None)
    if fault_spec is not None:
        env["MASTIC_FAULTS"] = fault_spec
    return subprocess.run(
        [sys.executable, "tools/serve.py"] + extra_args,
        cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=timeout)


@pytest.mark.slow
def test_serve_kill9_mid_epoch_resume_bit_identical(tmp_path):
    """The full acceptance drill through tools/serve.py: a clean run,
    a run killed (hard process exit) mid-epoch by the injector at the
    scheduler's epoch_round checkpoint, and a --resume run from the
    killed run's snapshot — results bit-identical to the clean run."""
    snap_a = str(tmp_path / "clean.snap")
    clean = _run_serve(["--reports", "5", "--snapshot", snap_a])
    assert clean.returncode == 0, clean.stderr[-2000:]
    clean_out = json.loads(clean.stdout.strip().splitlines()[-1])

    snap_b = str(tmp_path / "killed.snap")
    killed = _run_serve(
        ["--reports", "5", "--snapshot", snap_b],
        fault_spec="kill:party=collector:step=epoch_round:nth=2")
    assert killed.returncode == faults.KILL_EXIT_CODE, \
        killed.stderr[-2000:]
    assert os.path.exists(snap_b)

    resumed = _run_serve(["--reports", "5", "--snapshot", snap_b,
                          "--resume"])
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    resumed_out = json.loads(resumed.stdout.strip().splitlines()[-1])
    assert resumed_out["results"] == clean_out["results"]


@pytest.mark.slow
def test_two_tenants_interleave_and_match_offline():
    """Two tenants (multi-round heavy hitters + single-round
    attribute metrics) multiplex through one scheduler round-robin;
    each tenant's output equals its offline driver's."""
    from mastic_tpu.drivers.attribute_metrics import (
        aggregate_by_attribute, hash_attribute)

    m = MasticCount(2)
    m_attr = MasticCount(8)
    (vk, vk_attr) = (gen_rand(m.VERIFY_KEY_SIZE),
                     gen_rand(m_attr.VERIFY_KEY_SIZE))
    hh_reports = _reports(m, [0, 0, 3, 3, 1])
    alpha = hash_attribute(m_attr, "checkout.html")
    attr_val = int("".join("1" if b else "0" for b in alpha), 2)
    attr_meas = [(m_attr.vidpf.test_index_from_int(v, 8), True)
                 for v in (attr_val, attr_val, 0)]
    attr_reports = get_reports_from_measurements(m_attr, CTX,
                                                 attr_meas)
    attrs = ["checkout.html", "landing.html"]

    offline_hh = HeavyHittersRun(m, CTX, {"default": 2}, hh_reports,
                                 verify_key=vk)
    while offline_hh.step():
        pass
    offline_attr = aggregate_by_attribute(m_attr, CTX, attrs,
                                          attr_reports,
                                          verify_key=vk_attr)

    svc = CollectorService(
        [_spec(vk=vk),
         TenantSpec(name="attrs",
                    spec={"class": "MasticCount", "args": [8]},
                    ctx=CTX, verify_key=vk_attr,
                    mode="attribute_metrics", attributes=attrs)],
        config=_cfg())
    _admit(svc, "count", m, hh_reports)
    _admit(svc, "attrs", m_attr, attr_reports)
    svc.begin_epoch("count")
    svc.begin_epoch("attrs")
    # Per-quantum tenant sequence, recovered from the rounds-counter
    # deltas: round-robin must interleave the attrs round between the
    # count epoch's levels, not serialize whole epochs.
    seq = []
    prev = {"count": 0, "attrs": 0}
    while True:
        more = svc.step()
        for (name, t) in svc.tenants.items():
            rounds = t.counters.rounds
            if rounds != prev[name]:
                seq.append(name)
                prev[name] = rounds
        if not more:
            break
    assert svc.drained()
    assert seq == ["count", "attrs", "count"]
    mx = svc.metrics()["tenants"]
    assert mx["count"]["epochs"][0]["result"] == \
        [[bool(b) for b in p] for p in offline_hh.result()]
    assert mx["attrs"]["epochs"][0]["result"] == \
        [[a, v] for (a, v) in offline_attr]
    # both tenants were scheduled (round-robin interleave): the
    # attrs round ran before the count epoch finished
    assert mx["attrs"]["counters"]["rounds"] == 1
    assert mx["count"]["counters"]["rounds"] == \
        mx["count"]["epochs"][0]["levels_completed"]


@pytest.mark.slow
def test_epoch_deadline_truncates_to_completed_frontier():
    """An epoch that blows its deadline finishes at the last
    completed level: the record is marked truncated and carries the
    survivors of the rounds that DID run (here level 0's), nothing
    deeper."""
    m = MasticCount(2)
    svc = CollectorService(
        # budget covers the (compile-heavy) level-0 round but expires
        # well before level 1's check — the cold compile on this
        # fabric takes tens of seconds, the margin is wide
        [_spec(epoch_deadline=3.0)],
        config=_cfg())
    _admit(svc, "count", m, _reports(m, [0, 0, 3, 3, 1]))
    svc.begin_epoch("count")
    assert svc.step()            # level 0 runs (slow: compile)
    assert not svc.step()        # deadline gone: truncate, drain
    t = svc.metrics()["tenants"]["count"]
    rec = t["epochs"][0]
    assert rec["truncated"] and rec["levels_completed"] == 1
    # both 1-bit prefixes pass threshold 2 (counts 3 and 2)
    assert sorted(rec["result"]) == [[False], [True]]
    assert t["counters"]["deadline_misses"] == 1
    assert t["counters"]["epochs_truncated"] == 1


@pytest.mark.slow
def test_service_mesh_bit_identical(tmp_path):
    """The scheduler path under report-axis mesh sharding produces
    the same epoch record as the single-device service (the r10
    bit-identity contract composed with the service layer)."""
    import jax

    from mastic_tpu.parallel import make_mesh

    if jax.device_count() < 2:
        pytest.skip("needs >= 2 (virtual) devices")
    m = MasticCount(2)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    reports = _reports(m, [0, 0, 3, 3, 1])

    def run_service(mesh):
        svc = CollectorService(
            [_spec(vk=vk, chunk_size=3)],
            config=_cfg(page_size=3), mesh=mesh)
        _admit(svc, "count", m, reports)
        svc.begin_epoch("count")
        assert svc.run_until_drained(deadline=Deadline(900.0))
        rec = svc.metrics()["tenants"]["count"]["epochs"][0]
        for key in ("wall_s", "compile_ms", "inline_compiles"):
            rec.pop(key, None)
        return rec

    plain = run_service(None)
    meshed = run_service(make_mesh(2, nodes_axis=1))
    assert meshed == plain
