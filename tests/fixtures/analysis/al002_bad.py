"""Known-bad: stale suppression that silences nothing (AL002)."""


def quiet(count: int) -> int:
    # mastic-allow: SF001 — there is no secret branch left here
    return count + 1
