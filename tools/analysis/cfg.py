"""Intraprocedural control-flow graphs for the path-sensitive passes
(ISSUE 17).

The AST passes see statements; the resource-lifetime (RL) and
event-loop-readiness (EV) rules need *paths*: "is there an execution
on which this socket reaches function exit unclosed?" is a question
about edges, not nodes.  `build(func)` lowers one function body (from
the same single-parse FileInfos every other pass shares) to basic
blocks with explicit edges for if/while/for/try/except/finally/with/
return/raise/break/continue, and `solve()` is the gen/kill dataflow
driver the passes run to fixpoint over it.

Design decisions that matter to the consumers:

* **one element per block** — every block carries at most one
  "element": a simple ast.stmt, a branch/loop test (bare ast.expr),
  or a tagged tuple ``("for", node)`` / ``("with", item, node)``.
  Transfer functions therefore never reason about intra-block order.

* **raise edges out of every call** — any element containing a Call
  (plus assert/raise) gets an EXC edge to the innermost active
  handler (or the virtual `raise_exit`).  The driver feeds EXC edges
  from the *exc_out* facts the transfer computes for the element —
  the convention the RL pass uses is "kills commit, gens do not": a
  failing acquisition acquired nothing, a failing cleanup still
  counts as cleanup.

* **finally duplication** — each abnormal exit (return/break/continue
  crossing a try/finally) inlines its own copy of the finalbody, so a
  close() in a finally kills the fact on the return path without
  conflating it with the fall-through path.  The exception channel of
  one try shares a single finalbody copy (per-raise duplication would
  explode); handler bodies raise into that same copy.

* **None-guard pruning** — a branch test of the shape ``x``,
  ``not x``, ``x is None`` / ``x is not None`` kills the facts for
  ``x`` on the edge where it is known None/falsy, so the ubiquitous
  ``finally: if sock is not None: sock.close()`` pattern does not
  report the None path as a leak.

Known blind spots (documented in USAGE.md): exception *types* are not
matched — a raise may reach any handler of the enclosing try (plus
the outer context when no handler is catch-all); `with` __exit__
suppression is not modeled; comprehensions are treated as opaque
expressions; `while True` without break simply never reaches the
normal exit (sound for leak detection — no path, no report).
"""

import ast
from collections import deque

FLOW = "flow"
TRUE = "true"
FALSE = "false"
EXC = "exc"

_CATCH_ALL = {"Exception", "BaseException"}


class Block:
    __slots__ = ("idx", "elem", "succ")

    def __init__(self, idx: int):
        self.idx = idx
        self.elem = None     # ast.stmt | ast.expr | tagged tuple | None
        self.succ = []       # [(Block, kind)]

    def __repr__(self):   # debugging aid only
        kind = type(self.elem).__name__ if self.elem is not None else "-"
        return f"<B{self.idx} {kind} ->{[s.idx for (s, _k) in self.succ]}>"


class CFG:
    __slots__ = ("func", "blocks", "entry", "exit", "raise_exit")

    def __init__(self, func, blocks, entry, exit_b, raise_exit):
        self.func = func
        self.blocks = blocks
        self.entry = entry
        self.exit = exit_b            # normal exit (return / fall-off)
        self.raise_exit = raise_exit  # uncaught-exception exit


class _Ctx:
    """Where control transfers OUT of the current statement list go:
    `handler` is the innermost active exception target, `finallies`
    the stack of pending (finalbody, ctx-to-run-it-under) pairs an
    abnormal exit must inline, `loops` the (head, after, fin-depth)
    stack for continue/break."""

    __slots__ = ("handler", "finallies", "loops")

    def __init__(self, handler, finallies=(), loops=()):
        self.handler = handler
        self.finallies = finallies
        self.loops = loops

    def push_finally(self, finalbody, outer):
        return _Ctx(self.handler, self.finallies + ((finalbody, outer),),
                    self.loops)

    def with_handler(self, handler):
        return _Ctx(handler, self.finallies, self.loops)

    def push_loop(self, head, after):
        return _Ctx(self.handler, self.finallies,
                    self.loops + ((head, after, len(self.finallies)),))


def _contains_call(node) -> bool:
    return any(isinstance(n, ast.Call) for n in ast.walk(node))


def _stmt_can_raise(st) -> bool:
    if isinstance(st, (ast.Raise, ast.Assert)):
        return True
    return _contains_call(st)


def _const_truth(expr):
    """True/False for a constant test, None when the test is dynamic."""
    if isinstance(expr, ast.Constant):
        return bool(expr.value)
    return None


class _Builder:
    def __init__(self, func):
        self.func = func
        self.blocks = []
        self.exit = self._new()
        self.raise_exit = self._new()

    def _new(self) -> Block:
        b = Block(len(self.blocks))
        self.blocks.append(b)
        return b

    def _edge(self, a, b, kind=FLOW) -> None:
        if a is not None and b is not None:
            a.succ.append((b, kind))

    def build(self) -> CFG:
        entry = self._new()
        ctx = _Ctx(handler=self.raise_exit)
        end = self.seq(self.func.body, entry, ctx)
        self._edge(end, self.exit)    # fall off the end: implicit return
        return CFG(self.func, self.blocks, entry, self.exit,
                   self.raise_exit)

    # -- statement lowering ------------------------------------------

    def seq(self, stmts, cur, ctx):
        for st in stmts:
            if cur is None:
                break               # unreachable tail
            cur = self.stmt(st, cur, ctx)
        return cur

    def _elem(self, cur, elem, raises, ctx, exc_to=None):
        """Append one element block after `cur`; returns the new empty
        continuation block."""
        b = self._new()
        self._edge(cur, b)
        b.elem = elem
        if raises:
            self._edge(b, exc_to if exc_to is not None else ctx.handler,
                       EXC)
        nxt = self._new()
        self._edge(b, nxt)
        return (b, nxt)

    def _unwind(self, cur, ctx, target, depth=0):
        """Inline the pending finallies (innermost first) down to stack
        depth `depth`, then edge to `target`."""
        for (finalbody, fctx) in reversed(ctx.finallies[depth:]):
            entry = self._new()
            self._edge(cur, entry)
            cur = self.seq(finalbody, entry, fctx)
            if cur is None:
                return              # the finally itself never completes
        self._edge(cur, target)

    def stmt(self, st, cur, ctx):
        if isinstance(st, ast.If):
            return self._if(st, cur, ctx)
        if isinstance(st, ast.While):
            return self._while(st, cur, ctx)
        if isinstance(st, (ast.For, ast.AsyncFor)):
            return self._for(st, cur, ctx)
        if isinstance(st, ast.Try):
            return self._try(st, cur, ctx)
        if isinstance(st, (ast.With, ast.AsyncWith)):
            return self._with(st, cur, ctx)
        if isinstance(st, ast.Return):
            (b, _nxt) = self._elem(cur, st, _stmt_can_raise(st), ctx)
            self._unwind(b, ctx, self.exit)
            return None
        if isinstance(st, ast.Raise):
            b = self._new()
            self._edge(cur, b)
            b.elem = st
            self._edge(b, ctx.handler, EXC)
            return None
        if isinstance(st, ast.Break):
            if not ctx.loops:
                return cur          # malformed; tolerate
            (_head, after, depth) = ctx.loops[-1]
            b = self._new()
            self._edge(cur, b)
            self._unwind(b, ctx, after, depth)
            return None
        if isinstance(st, ast.Continue):
            if not ctx.loops:
                return cur
            (head, _after, depth) = ctx.loops[-1]
            b = self._new()
            self._edge(cur, b)
            self._unwind(b, ctx, head, depth)
            return None
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return cur              # nested scopes are their own CFGs
        # Simple statement.
        (_b, nxt) = self._elem(cur, st, _stmt_can_raise(st), ctx)
        return nxt

    def _if(self, st, cur, ctx):
        (tb, _nxt) = self._elem(cur, st.test, _contains_call(st.test),
                                ctx)
        tb.succ = [s for s in tb.succ if s[1] != FLOW]
        join = self._new()
        truth = _const_truth(st.test)
        if truth is not False:
            then_entry = self._new()
            self._edge(tb, then_entry, TRUE)
            then_end = self.seq(st.body, then_entry, ctx)
            self._edge(then_end, join)
        if truth is not True:
            if st.orelse:
                else_entry = self._new()
                self._edge(tb, else_entry, FALSE)
                else_end = self.seq(st.orelse, else_entry, ctx)
                self._edge(else_end, join)
            else:
                self._edge(tb, join, FALSE)
        return join

    def _while(self, st, cur, ctx):
        head_join = self._new()          # back-edge target
        self._edge(cur, head_join)
        (tb, _nxt) = self._elem(head_join, st.test,
                                _contains_call(st.test), ctx)
        tb.succ = [s for s in tb.succ if s[1] != FLOW]
        after = self._new()
        truth = _const_truth(st.test)
        loop_ctx = ctx.push_loop(head_join, after)
        if truth is not False:
            body_entry = self._new()
            self._edge(tb, body_entry, TRUE)
            body_end = self.seq(st.body, body_entry, loop_ctx)
            self._edge(body_end, head_join)
        if truth is not True:
            if st.orelse:
                else_entry = self._new()
                self._edge(tb, else_entry, FALSE)
                else_end = self.seq(st.orelse, else_entry, ctx)
                self._edge(else_end, after)
            else:
                self._edge(tb, after, FALSE)
        return after

    def _for(self, st, cur, ctx):
        head_join = self._new()
        self._edge(cur, head_join)
        (hb, _nxt) = self._elem(head_join, ("for", st),
                                _contains_call(st.iter), ctx)
        hb.succ = [s for s in hb.succ if s[1] != FLOW]
        after = self._new()
        loop_ctx = ctx.push_loop(head_join, after)
        body_entry = self._new()
        self._edge(hb, body_entry, TRUE)      # iterator yielded
        body_end = self.seq(st.body, body_entry, loop_ctx)
        self._edge(body_end, head_join)
        if st.orelse:
            else_entry = self._new()
            self._edge(hb, else_entry, FALSE)
            else_end = self.seq(st.orelse, else_entry, ctx)
            self._edge(else_end, after)
        else:
            self._edge(hb, after, FALSE)      # iterator exhausted
        return after

    def _with(self, st, cur, ctx):
        for item in st.items:
            (_b, cur) = self._elem(
                cur, ("with", item, st),
                _contains_call(item.context_expr), ctx)
        body_end = self.seq(st.body, cur, ctx)
        after = self._new()
        self._edge(body_end, after)
        return after

    def _try(self, st, cur, ctx):
        outer = ctx
        # The exception channel's single finalbody copy: everything
        # raised inside this try (uncaught by its handlers) runs it,
        # then proceeds to the outer handler.
        if st.finalbody:
            fin_exc_entry = self._new()
            fin_exc_end = self.seq(st.finalbody, fin_exc_entry, outer)
            self._edge(fin_exc_end, outer.handler)
            exc_escape = fin_exc_entry
        else:
            exc_escape = outer.handler

        if st.handlers:
            dispatch = self._new()
            body_exc_target = dispatch
        else:
            body_exc_target = exc_escape

        body_ctx = outer.with_handler(body_exc_target)
        if st.finalbody:
            body_ctx = body_ctx.push_finally(st.finalbody, outer)
        body_entry = self._new()
        self._edge(cur, body_entry)
        body_end = self.seq(st.body, body_entry, body_ctx)

        handler_ctx = outer.with_handler(exc_escape)
        if st.finalbody:
            handler_ctx = handler_ctx.push_finally(st.finalbody, outer)

        if st.orelse and body_end is not None:
            body_end = self.seq(st.orelse, body_end, handler_ctx)

        normal_ends = [body_end]
        catch_all = False
        if st.handlers:
            for h in st.handlers:
                if h.type is None:
                    catch_all = True
                else:
                    names = [h.type] if not isinstance(h.type, ast.Tuple) \
                        else list(h.type.elts)
                    for t in names:
                        tail = _dotted_tail(t)
                        if tail in _CATCH_ALL:
                            catch_all = True
                h_entry = self._new()
                self._edge(dispatch, h_entry)
                h_end = self.seq(h.body, h_entry, handler_ctx)
                normal_ends.append(h_end)
            if not catch_all:
                # A raise may match no handler and escape this try.
                self._edge(dispatch, exc_escape)

        after = self._new()
        if st.finalbody:
            # The normal-completion finalbody copy (separate from the
            # exception channel's so the paths stay distinguishable).
            fin_entry = self._new()
            fin_end = self.seq(st.finalbody, fin_entry, outer)
            self._edge(fin_end, after)
            for end in normal_ends:
                self._edge(end, fin_entry)
        else:
            for end in normal_ends:
                self._edge(end, after)
        return after


def _dotted_tail(node) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def build(func) -> CFG:
    """CFG for one ast.FunctionDef / AsyncFunctionDef."""
    return _Builder(func).build()


# -- the gen/kill driver ----------------------------------------------

def solve(cfg: CFG, transfer, entry_facts=frozenset()):
    """Forward may-analysis to fixpoint.  `transfer(block, facts)`
    returns a dict of edge-kind -> fact set; missing kinds default to
    the FLOW entry (which itself defaults to the input unchanged).
    EXC entries model "the element raised mid-way".  Returns the list
    of per-block input fact sets, indexed by block idx."""
    n = len(cfg.blocks)
    preds = [[] for _ in range(n)]
    for b in cfg.blocks:
        for (s, kind) in b.succ:
            preds[s.idx].append((b, kind))
    ins = [frozenset()] * n
    outs = [None] * n                 # block idx -> kind -> facts
    ins[cfg.entry.idx] = frozenset(entry_facts)

    def out_for(b, kind):
        table = outs[b.idx]
        if table is None:
            return frozenset()
        return table.get(kind, table.get(FLOW, frozenset()))

    work = deque(cfg.blocks)
    queued = {b.idx for b in cfg.blocks}
    rounds = 0
    limit = 64 * n + 64               # termination backstop
    while work and rounds < limit:
        rounds += 1
        b = work.popleft()
        queued.discard(b.idx)
        acc = set(ins[b.idx]) if b is cfg.entry else set()
        for (p, kind) in preds[b.idx]:
            acc |= out_for(p, kind)
        acc = frozenset(acc)
        if outs[b.idx] is not None and acc == ins[b.idx]:
            continue
        ins[b.idx] = acc
        table = transfer(b, acc)
        if FLOW not in table:
            table = dict(table)
            table[FLOW] = acc
        if table != outs[b.idx]:
            outs[b.idx] = table
            for (s, _kind) in b.succ:
                if s.idx not in queued:
                    queued.add(s.idx)
                    work.append(s)
    return ins
