"""EV001: bare recv in a non-blocking context — nothing proved the
fd readable, so the call either blocks the loop or raises
BlockingIOError."""


def pump(sock):
    sock.setblocking(False)
    return sock.recv(4096)
