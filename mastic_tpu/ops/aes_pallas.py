"""Pallas fused bitsliced-AES kernel: all 10 rounds resident in VMEM.

The XLA path (ops/aes_jax.aes128_encrypt_bitsliced) runs the middle
rounds under lax.scan — correct and portable, but the 128 plane arrays
round-trip through HBM between rounds unless XLA fuses aggressively.
This kernel keeps the whole bitsliced state in VMEM for the full
whiten -> 9 full rounds -> final round pipeline: one HBM read of the
state planes, ~3k gate-ops of pure VPU work per 128 packed blocks, one
HBM write.  Same boolean circuit (ops/sbox_tower shared by import), so
constant-time discipline is preserved.

Layout: the (8, 16, M, W) plane stack flattens to (128, M, W) — plane
rows on the sublane axis, the packed-word axis W riding the 128-wide
vector lanes, the block axis M gridded.  Round-key planes (11, 8, 16,
W) flatten to (1408, 1, W) and broadcast over M inside the kernel.

Gated by MASTIC_AES_PALLAS=1 (read in ops/aes_jax at import):
untested on real hardware until the tunnel returns; the chained
interpret-mode suite (tests/test_ops_aes.py) locks every stage
bit-exact against the scan path on CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np

_U32 = jnp.uint32
_ONES32 = np.uint32(0xFFFFFFFF)
_LANE = 128    # TPU vector lane width
# Blocks-per-grid-step.  Mosaic requires the second-to-last block dim
# to be a multiple of 8 (sublane tile) unless it equals the array dim,
# so the block axis is padded up to a multiple of 8 below; VMEM stays
# ~6 MB of live planes per grid step.
_BLOCK_M = 8

# ShiftRows permutation over the 16-byte axis (ops/aes_jax._SHIFT_ROWS).
from .aes_jax import _SHIFT_ROWS


def _shift_rows(p: jax.Array) -> jax.Array:
    """Static-slice permutation of the 16-byte axis — a fancy-index
    gather would capture its index array as a pallas kernel constant,
    which pallas_call rejects."""
    return jnp.concatenate([p[i:i + 1] for i in _SHIFT_ROWS], axis=0)


def _xtime_list(planes: list) -> list:
    """xtime on a list of 8 plane arrays (aes_jax._xtime_planes on a
    stack): planes shift up one, the top plane folds into the 0x1B
    taps (bits 1, 3, 4) and becomes bit 0."""
    hi = planes[7]
    out = [hi] + list(planes[:7])
    out[1] = out[1] ^ hi
    out[3] = out[3] ^ hi
    out[4] = out[4] ^ hi
    return out


def _mix_list(planes: list) -> list:
    """MixColumns on 8 x (16, ...) plane arrays (byte index = 4*col +
    row, so axis 1 of the (4, 4, ...) reshape is the row axis —
    aes_jax._mix_columns_planes with the plane axis as a list)."""
    c = [p.reshape((4, 4) + p.shape[1:]) for p in planes]
    r1 = [jnp.roll(x, -1, axis=1) for x in c]
    r2 = [jnp.roll(x, -2, axis=1) for x in c]
    r3 = [jnp.roll(x, -3, axis=1) for x in c]
    xt_c = _xtime_list(c)
    xt_r1 = _xtime_list(r1)
    out = [xt_c[i] ^ xt_r1[i] ^ r1[i] ^ r2[i] ^ r3[i]
           for i in range(8)]
    return [o.reshape((16,) + o.shape[2:]) for o in out]


def _make_kernel(start: int, end: int):
    """Stages 0..10: stage 0 = key whitening, 1..9 = full rounds,
    10 = final round (no MixColumns).  [start, end) is half-open."""

    def kernel(kp_ref, state_ref, out_ref):
        from .sbox_tower import sbox_planes_tower

        planes = [state_ref[b * 16:(b + 1) * 16] for b in range(8)]

        def key(r: int) -> list:
            return [kp_ref[(r * 8 + b) * 16:(r * 8 + b + 1) * 16]
                    for b in range(8)]

        for stage in range(start, end):  # unrolled: state stays in VMEM
            if stage == 0:
                k = key(0)
                planes = [planes[b] ^ k[b] for b in range(8)]
                continue
            planes = sbox_planes_tower(planes, _ONES32)
            planes = [_shift_rows(p) for p in planes]
            if stage < 10:
                planes = _mix_list(planes)
            k = key(stage)
            planes = [planes[b] ^ k[b] for b in range(8)]
        for b in range(8):
            out_ref[b * 16:(b + 1) * 16] = planes[b]

    return kernel


_CALL_CACHE: dict = {}


def aes128_encrypt_bitsliced_pallas(key_planes: jax.Array,
                                    planes: jax.Array,
                                    interpret: bool = False,
                                    stage_range: tuple = None):
    """Drop-in twin of ops/aes_jax.aes128_encrypt_bitsliced:
    key_planes (11, 8, 16, W), planes (8, 16, N..., W) -> encrypted
    planes, middle dims broadcasting against the keys.

    `stage_range` overrides the full [0, 11) pipeline with an explicit
    half-open stage window — the chained equivalence test applies the
    11 stages one kernel at a time, pinning each round key and the
    final round's missing MixColumns without the interpret compile of
    the fully unrolled kernel."""
    from jax.experimental import pallas as pl

    (rounds, eight, sixteen, w) = key_planes.shape
    assert (rounds, eight, sixteen) == (11, 8, 16), key_planes.shape
    mid_shape = planes.shape[2:-1]
    m = int(np.prod(mid_shape)) if mid_shape else 1
    state = planes.reshape(8 * 16, m, planes.shape[-1])
    kp = key_planes.reshape(11 * 8 * 16, 1, w)

    # Pad the lane axis to the 128-wide tile and the block axis to the
    # grid block (dead lanes/blocks are sliced back off).
    w_pad = -(-w // _LANE) * _LANE - w
    m_block = _BLOCK_M  # never narrower: Mosaic's 8-sublane tile rule
    m_pad = -(-m // m_block) * m_block - m
    if w_pad:
        state = jnp.pad(state, ((0, 0), (0, 0), (0, w_pad)))
        kp = jnp.pad(kp, ((0, 0), (0, 0), (0, w_pad)))
    if m_pad:
        state = jnp.pad(state, ((0, 0), (0, m_pad), (0, 0)))
    (stages, wp) = (stage_range or (0, 11), w + w_pad)
    mp = m + m_pad

    key = (stages, mp, m_block, wp, interpret)
    call = _CALL_CACHE.get(key)
    if call is None:
        # Grid over BOTH the block axis and the lane axis: packed
        # lanes are independent (round keys included), and an
        # un-gridded W would scale the VMEM-resident key block
        # linearly with the report count (~18 MB at 100k reports).
        call = pl.pallas_call(
            _make_kernel(*stages),
            out_shape=jax.ShapeDtypeStruct((128, mp, wp), jnp.uint32),
            grid=(mp // m_block, wp // _LANE),
            in_specs=[
                pl.BlockSpec((11 * 128, 1, _LANE),
                             lambda i, j: (0, 0, j)),
                pl.BlockSpec((128, m_block, _LANE),
                             lambda i, j: (0, i, j)),
            ],
            out_specs=pl.BlockSpec((128, m_block, _LANE),
                                   lambda i, j: (0, i, j)),
            interpret=interpret,
        )
        _CALL_CACHE[key] = call
    out = call(kp, state)
    out = out[:, :m, :w]
    return out.reshape(planes.shape[:2] + mid_shape
                       + planes.shape[-1:])
