"""AOT artifact store (ISSUE 9, `drivers/artifacts.py`): the three
load gates (digest / runtime / probe), the ProgramCache artifact
tier, the runtime-skew refusal, and — slow tier — full-round
bit-identity of reloaded executables vs freshly traced programs
(incl. mesh={1,2} and width growth) plus kill-9 resume over a warm
store.

Fast-tier tests use trivial jitted programs (sub-second compiles);
the real round-program family is exercised by `make artifacts-smoke`
(tools/bake.py --smoke: bake -> fresh-subprocess load -> probe ->
bit-identity) and the slow tests here.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mastic_tpu.drivers import artifacts
from mastic_tpu.drivers.pipeline import ProgramCache
from mastic_tpu.obs.registry import configure as configure_registry


@pytest.fixture
def store(tmp_path):
    return artifacts.ArtifactStore(str(tmp_path / "store"))


def _trivial(tag: int = 1):
    """A compiled trivial program plus its call args."""
    fn = jax.jit(lambda a, b: (a + b * tag, (a * b).sum()))
    args = (jnp.arange(4, dtype=jnp.uint32),
            jnp.full((4,), 2, jnp.uint32))
    return (fn, args, fn.lower(*args).compile())


def _key(fam="famA", rows=4):
    return ("eval", rows, 0, 8, 2, 1, 2, artifacts.runtime_tag(), fam)


def _manifest(store):
    with open(os.path.join(store.path, "manifest.json")) as fh:
        return json.load(fh)


def _write_manifest(store, man):
    with open(os.path.join(store.path, "manifest.json"), "w") as fh:
        json.dump(man, fh)


# -- store mechanics --------------------------------------------------


def test_save_load_round_trip_bit_identical(store):
    (fn, args, compiled) = _trivial()
    entry = store.save(_key(), compiled,
                       stablehlo=artifacts.export_stablehlo(fn, args))
    assert entry["bytes"] > 0
    assert os.path.exists(os.path.join(store.path, entry["blob"]))
    assert os.path.exists(os.path.join(store.path, entry["stablehlo"]))
    # A fresh store object (no in-memory memo) pays the real disk
    # load + probe; outputs must be bit-identical to the traced
    # program's.
    fresh = artifacts.ArtifactStore(store.path)
    loaded = fresh.load(_key())
    assert loaded is not None
    for (a, b) in zip(jax.tree_util.tree_leaves(compiled(*args)),
                      jax.tree_util.tree_leaves(loaded(*args))):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_miss_and_memoization(store):
    assert store.load(("absent", 1)) is None
    (_fn, _args, compiled) = _trivial()
    store.save(_key(), compiled)
    # The saving store serves the traced object from memory — the
    # bake process never runs a reload of its own programs.
    assert store.load(_key()) is compiled


def test_corrupt_blob_detected_before_unpickle(store):
    (_fn, _args, compiled) = _trivial()
    entry = store.save(_key(), compiled)
    blob = os.path.join(store.path, entry["blob"])
    data = bytearray(open(blob, "rb").read())
    data[len(data) // 2] ^= 0xFF
    with open(blob, "wb") as fh:
        fh.write(bytes(data))
    fresh = artifacts.ArtifactStore(store.path)
    assert fresh.load(_key()) is None
    assert artifacts.CORRUPT in fresh._failed.values()


def test_version_skew_refused(store):
    (_fn, _args, compiled) = _trivial()
    store.save(_key(), compiled)
    man = _manifest(store)
    man["runtime"] = "jax-9.9.9-neverland"
    _write_manifest(store, man)
    fresh = artifacts.ArtifactStore(store.path)
    assert fresh.load(_key()) is None
    assert artifacts.VERSION_SKEW in fresh._failed.values()


def test_probe_failure_detected(store):
    """The PERF.md §7 failure mode: a reload that produces different
    outputs must be refused.  Simulated by doctoring the recorded
    probe digest — the load-side probe run then mismatches."""
    (_fn, _args, compiled) = _trivial()
    store.save(_key(), compiled)
    man = _manifest(store)
    name = artifacts.key_name(_key())
    man["entries"][name]["probe_digest"] = "0" * 64
    _write_manifest(store, man)
    fresh = artifacts.ArtifactStore(store.path)
    assert fresh.load(_key()) is None
    assert fresh._failed[name] == artifacts.PROBE_FAIL


def test_load_outcomes_land_in_registry(store, tmp_path):
    reg = configure_registry()
    (_fn, _args, compiled) = _trivial()
    entry = store.save(_key(), compiled)
    fresh = artifacts.ArtifactStore(store.path)
    fresh.load(_key())           # hit
    fresh.load(("absent", 1))    # miss
    blob = os.path.join(store.path, entry["blob"])
    with open(blob, "wb") as fh:
        fh.write(b"garbage")
    fresh2 = artifacts.ArtifactStore(store.path)
    fresh2.load(_key())          # corrupt
    get = lambda outcome: reg.counter(  # noqa: E731
        "mastic_artifact_loads_total", outcome=outcome).value()
    assert get("hit") == 1.0
    assert get("miss") == 1.0
    assert get("corrupt") == 1.0
    configure_registry()


# -- ProgramCache artifact tier ---------------------------------------


def test_cache_artifact_tier_skips_compile(store):
    (_fn, _args, compiled) = _trivial()
    store.save(_key(), compiled)
    cache = ProgramCache(store=artifacts.ArtifactStore(store.path))

    def must_not_build():
        raise AssertionError("store hit must not compile")

    (prog, wait) = cache.get(_key(), must_not_build)
    assert prog is not None and wait > 0.0
    assert cache.stats == {**cache.stats, "artifact_hits": 1,
                           "inline_compiles": 0}
    # Second get: in-process tier, zero wait.
    (prog2, wait2) = cache.get(_key(), must_not_build)
    assert prog2 is prog and wait2 == 0.0


def test_cache_warm_prefetches_from_store(store):
    (_fn, _args, compiled) = _trivial()
    store.save(_key(), compiled)
    cache = ProgramCache(store=artifacts.ArtifactStore(store.path))
    spent = cache.warm(_key(), lambda: pytest.fail("must prefetch"))
    assert spent > 0.0
    assert cache.stats["artifact_hits"] == 1
    assert cache.stats["warm_compiles"] == 0
    assert cache.contains(_key())


def test_cache_preload_filters_by_family(store):
    (_fn, _args, c1) = _trivial(1)
    (_fn2, _args2, c2) = _trivial(2)
    store.save(_key("famA"), c1)
    store.save(_key("famB"), c2)
    cache = ProgramCache(store=artifacts.ArtifactStore(store.path))
    n = cache.preload(lambda key: key[-1] == "famA")
    assert n == 1
    assert cache.contains(_key("famA"))
    assert not cache.contains(_key("famB"))


def test_cache_refuses_foreign_runtime_key():
    """Satellite regression: an in-process cache can never serve (or
    store) a program keyed for a different runtime — the refusal is
    loud, not a silent miss."""
    cache = ProgramCache()
    skewed = ("eval", 4, 0, 8, "jax-0.0.1-elsewhere", "fam")
    with pytest.raises(RuntimeError, match="refusing to serve"):
        cache.get(skewed, lambda: None)
    with pytest.raises(RuntimeError, match="refusing to serve"):
        cache.warm(skewed, lambda: None)
    # The matching runtime passes through to the build path.
    ok_key = ("k", artifacts.runtime_tag())
    (prog, _wait) = cache.get(
        ok_key, lambda: jax.jit(lambda: jnp.zeros(1)).lower())
    assert prog is not None


def test_store_from_env_lever(monkeypatch, tmp_path):
    monkeypatch.delenv("MASTIC_ARTIFACT_DIR", raising=False)
    assert artifacts.store_from_env() is None
    monkeypatch.setenv("MASTIC_ARTIFACT_DIR", str(tmp_path / "s"))
    store = artifacts.store_from_env()
    assert store is not None
    # Singleton per path: the in-memory memo is process-wide.
    assert artifacts.store_from_env() is store


# -- schema + key plumbing --------------------------------------------


def test_artifacts_extra_block_schema():
    from mastic_tpu.obs import schema

    good = {"artifacts": {"store": None, "hits": 0,
                          "inline_compiles": 2}}
    assert schema.validate_extra(good) == []
    assert schema.validate_extra(
        {"artifacts": {"store": "/s", "hits": 1,
                       "inline_compiles": 0}}) == []
    bad = schema.validate_extra({"artifacts": {"hits": 1}})
    assert any("missing" in p for p in bad)
    bad = schema.validate_extra(
        {"artifacts": {"store": 7, "hits": 0, "inline_compiles": 0}})
    assert any("artifacts.store" in p for p in bad)


def test_planted_trajectory_is_deterministic():
    paths = artifacts.planted_paths(4, 2)
    assert paths == artifacts.planted_paths(4, 2)
    levels = list(artifacts.trajectory(4, paths))
    assert [lvl for (lvl, _p) in levels] == [0, 1, 2, 3]
    # Steady-2: every frontier after level 0 is the 2 ancestors'
    # children (width 4).
    assert all(len(p) == 4 for (lvl, p) in levels[1:])
    grow = list(artifacts.growth_trajectory(4, 8))
    assert [len(p) for (_lvl, p) in grow] == [2, 4, 8]


def test_runner_keys_carry_runtime_and_family():
    """Every program key a runner builds ends with (runtime tag,
    family id) — the store namespace AND the in-process refusal
    hook."""
    from mastic_tpu.backend.mastic_jax import BatchedMastic
    from mastic_tpu.mastic import MasticCount

    m = MasticCount(4)
    bm = BatchedMastic(m)
    baker = artifacts.make_baker(bm, b"ctx A")
    plan = baker._plan(((False,), (True,)), 0)
    tag = artifacts.runtime_tag()
    fam = artifacts.family_id(bm, b"ctx A")
    for key in (baker._eval_key(8, plan), baker._agg_key(8, 4),
                baker._wc_key(8, 0), baker._rk_key(8)):
        assert key[-2:] == (tag, fam)
    # A different ctx is a different family: its programs can never
    # be served to this collection.
    assert artifacts.family_id(bm, b"ctx B") != fam
    assert artifacts.family_id(
        BatchedMastic(MasticCount(8)), b"ctx A") != fam


def test_struct_signatures_match_concrete_args():
    """The bake-side abstract signatures must mirror the runners'
    concrete arrays exactly — a drifted struct would bake programs
    the runtime cache can never hit."""
    from mastic_tpu.backend.mastic_jax import BatchedMastic
    from mastic_tpu.mastic import MasticCount

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import _synth_batch

    m = MasticCount(4)
    bm = BatchedMastic(m)
    baker = artifacts.make_baker(bm, b"sig")
    rows = 8
    batch = _synth_batch(bm, rows, np.random.default_rng(0))
    structs = baker._batch_structs(rows)
    concrete = jax.tree_util.tree_map(
        lambda x: (x.shape, str(x.dtype)), batch)
    abstract = jax.tree_util.tree_map(
        lambda s: (s.shape, str(s.dtype)), structs)
    assert concrete == abstract
    plan = baker._plan(((False,), (True,)), 0)
    ev = baker._eval_structs(rows, plan)
    assert ev[1].w.shape == (rows, 4, baker.width,
                             m.vidpf.VALUE_LEN, bm.spec.num_limbs)
    (erk, crk) = jax.eval_shape(
        lambda nn: bm.vidpf.roundkeys(b"sig", nn),
        jax.ShapeDtypeStruct((rows, 16), jnp.uint8))
    assert ev[4].shape == erk.shape and ev[5].shape == crk.shape


# -- slow tier: the real round programs -------------------------------


def _planted_run(m, ctx, chunk_size, mesh=None, reports=None):
    from mastic_tpu.drivers.heavy_hitters import (
        HeavyHittersRun, get_reports_from_measurements)

    bits = m.vidpf.BITS
    paths = artifacts.planted_paths(bits, 2)
    if reports is None:
        meas = [(tuple(paths[i % 2]), True) for i in range(10)]
        reports = get_reports_from_measurements(m, ctx, meas)
    run = HeavyHittersRun(m, ctx, {"default": 1}, reports,
                          verify_key=bytes(range(m.VERIFY_KEY_SIZE)),
                          chunk_size=chunk_size, mesh=mesh)
    while run.step():
        pass
    return (run, reports)


def _assert_identical(a, b):
    assert a.result() == b.result()
    assert len(a.metrics) == len(b.metrics)
    for (ma, mb) in zip(a.metrics, b.metrics):
        assert (ma.accepted, ma.rejected_eval_proof,
                ma.rejected_weight_check, ma.rejected_joint_rand,
                ma.xof_fallbacks) == \
            (mb.accepted, mb.rejected_eval_proof,
             mb.rejected_weight_check, mb.rejected_joint_rand,
             mb.xof_fallbacks)


@pytest.mark.slow
@pytest.mark.parametrize("mesh_n", [0, 2])
def test_round_trip_bit_identity_full_rounds(tmp_path, monkeypatch,
                                             mesh_n):
    """Traced reference run vs the same collection served purely from
    a baked store (fresh store objects, so every load comes from
    disk through all three gates): identical hitters and per-round
    counters, single-device and mesh=2.  (The fresh-SUBPROCESS
    variant is `make artifacts-smoke`.)"""
    from mastic_tpu.backend.mastic_jax import BatchedMastic
    from mastic_tpu.mastic import MasticCount

    monkeypatch.delenv("MASTIC_ARTIFACT_DIR", raising=False)
    mesh = None
    if mesh_n:
        from mastic_tpu.parallel import make_mesh
        mesh = make_mesh(mesh_n, nodes_axis=1)
    m = MasticCount(3)
    ctx = b"artifact rt"
    (ref, reports) = _planted_run(m, ctx, 4, mesh=mesh)
    assert ref.runner.programs.stats["inline_compiles"] > 0

    store = artifacts.default_store(str(tmp_path / f"s{mesh_n}"))
    baker = artifacts.make_baker(BatchedMastic(m), ctx, mesh=mesh)
    rows = ref.runner._device_rows()
    stats = artifacts.bake_trajectory(
        baker, store, rows,
        artifacts.trajectory(3, artifacts.planted_paths(3, 2)),
        with_stablehlo=False)
    assert stats["compiled"] > 0
    # Drop the in-memory memo so loads come from disk, then run the
    # same collection against the store only.
    artifacts._stores.pop(store.path, None)
    monkeypatch.setenv("MASTIC_ARTIFACT_DIR", store.path)
    (warm, _r) = _planted_run(m, ctx, 4, mesh=mesh, reports=reports)
    warm_stats = warm.runner.programs.stats
    assert warm_stats["inline_compiles"] == 0, warm_stats
    assert warm_stats["artifact_hits"] > 0
    _assert_identical(ref, warm)
    for mx in warm.metrics:
        assert mx.extra["artifacts"]["inline_compiles"] == 0
        assert mx.extra["artifacts"]["store"] == store.path


@pytest.mark.slow
def test_attribute_round_rides_artifact_tier(tmp_path, monkeypatch):
    """ISSUE 10 satellite: the attribute-metrics round program (a
    bare per-(ctx, agg_param) jit before r15) rides the AOT tier —
    baked via artifacts.bake_attribute_round, loaded through all
    three gates, zero inline compiles and a bit-identical aggregate
    on the warm path."""
    from mastic_tpu.backend.mastic_jax import BatchedMastic
    from mastic_tpu.drivers.attribute_metrics import \
        aggregate_by_attribute
    from mastic_tpu.drivers.heavy_hitters import \
        get_reports_from_measurements
    from mastic_tpu.mastic import MasticCount

    monkeypatch.delenv("MASTIC_ARTIFACT_DIR", raising=False)
    m = MasticCount(4)   # small tree keeps the from-root compile cheap
    ctx = b"attr artifact"
    attrs = ["checkout.html", "landing.html"]  # distinct 4-bit hashes
    from mastic_tpu.drivers.attribute_metrics import hash_attribute

    alpha = hash_attribute(m, attrs[0])
    val = int("".join("1" if b else "0" for b in alpha), 2)
    meas = [(m.vidpf.test_index_from_int(v, 4), True)
            for v in (val, val, 0)]
    reports = get_reports_from_measurements(m, ctx, meas)
    vk = bytes(range(m.VERIFY_KEY_SIZE))
    mx_ref: list = []
    ref = aggregate_by_attribute(m, ctx, attrs, reports,
                                 verify_key=vk, metrics_out=mx_ref)
    assert mx_ref[0].extra["artifacts"]["inline_compiles"] > 0

    store = artifacts.default_store(str(tmp_path / "attr"))
    baker = artifacts.make_baker(BatchedMastic(m), ctx)
    stats = artifacts.bake_attribute_round(
        baker, store, len(reports), attrs, with_stablehlo=False)
    assert stats["compiled"] == 1
    # Re-baking is a skip, not a recompile.
    assert artifacts.bake_attribute_round(
        baker, store, len(reports), attrs,
        with_stablehlo=False)["skipped"] == 1
    # Drop the in-memory memo so the load comes from disk through
    # the digest/runtime/probe gates.
    artifacts._stores.pop(store.path, None)
    monkeypatch.setenv("MASTIC_ARTIFACT_DIR", store.path)
    mx_warm: list = []
    warm = aggregate_by_attribute(m, ctx, attrs, reports,
                                  verify_key=vk, metrics_out=mx_warm)
    assert warm == ref
    art = mx_warm[0].extra["artifacts"]
    assert art["inline_compiles"] == 0, art
    assert art["hits"] >= 1
    assert art["store"] == store.path


def test_save_refuses_donating_executable():
    """The memory-safety guard behind the donation-free bake rule: a
    deserialized executable with input-output aliasing double-frees
    its donated buffers on this fabric (found by the artifacts-smoke
    gate), so sealing one is refused outright."""
    f = jax.jit(lambda a, b: a + b, donate_argnums=(0,))
    compiled = f.lower(jnp.ones(4), jnp.ones(4)).compile()
    import tempfile

    store = artifacts.ArtifactStore(tempfile.mkdtemp())
    with pytest.raises(ValueError, match="donated"):
        store.save(("k", artifacts.runtime_tag()), compiled)


@pytest.mark.slow
def test_bake_trajectory_covers_growth(tmp_path, monkeypatch):
    """A store baked over the growth trajectory serves a run whose
    width actually grows — the grow rounds load instead of paying the
    inline compile the runtime predictor deliberately skips."""
    from mastic_tpu.backend.mastic_jax import BatchedMastic
    from mastic_tpu.mastic import MasticCount

    monkeypatch.delenv("MASTIC_ARTIFACT_DIR", raising=False)
    m = MasticCount(4)
    ctx = b"grow bake"
    bm = BatchedMastic(m)
    store = artifacts.default_store(str(tmp_path / "grow"))
    baker = artifacts.make_baker(bm, ctx)
    stats = artifacts.bake_trajectory(
        baker, store, 4, artifacts.growth_trajectory(4, 16),
        with_stablehlo=False)
    assert stats["compiled"] > 0
    assert baker.width == 16  # the walk grew the padded width
    widths = {k[3] for k in store.keys() if k[0] == "eval"}
    assert widths >= {8, 16}

    # An all-survive run (threshold 0 keeps everything) over the
    # same family: the width-growth round — which the runtime
    # predictor deliberately never warms — loads from the store
    # instead of compiling inline.
    from mastic_tpu.drivers.heavy_hitters import (
        HeavyHittersRun, get_reports_from_measurements)

    artifacts._stores.pop(store.path, None)
    monkeypatch.setenv("MASTIC_ARTIFACT_DIR", store.path)
    meas = [(m.vidpf.test_index_from_int(v, 4), True)
            for v in range(8)]
    reports = get_reports_from_measurements(m, ctx, meas)
    run = HeavyHittersRun(m, ctx, {"default": 0}, reports,
                          verify_key=bytes(range(m.VERIFY_KEY_SIZE)),
                          chunk_size=4)
    while run.step():
        pass
    stats = run.runner.programs.stats
    assert run.runner.width == 16
    assert stats["inline_compiles"] == 0, stats
    assert sorted(len(r) for r in run.result()) == [4] * 16


@pytest.mark.slow
def test_kill9_resume_with_warm_store(tmp_path):
    """Crash-resume composes with the artifact store: a serve.py
    process killed mid-run resumes from its snapshot with
    --artifact-dir armed and finishes bit-identically to an unfaulted
    run — the restart path is exactly the cold start the store
    exists to kill."""
    import signal
    import time as _time

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("MASTIC_ARTIFACT_DIR", None)
    snap = str(tmp_path / "svc.snap")
    store = str(tmp_path / "store")

    def serve(extra, timeout=900, check=True, **kw):
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "tools", "serve.py"),
             "--bits", "2", "--reports", "6", "--page-size", "3",
             "--seed", "7", "--snapshot", snap] + extra,
            capture_output=True, text=True, timeout=timeout, env=env,
            **kw)
        if check:
            assert proc.returncode == 0, proc.stderr[-3000:]
        return proc

    # Reference: unfaulted run (also the trajectory the bake needs —
    # bake the store from a bake.py family walk for the same config).
    ref = serve([])
    ref_line = json.loads(ref.stdout.strip().splitlines()[-1])

    bake = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "bake.py"),
         "--out", store, "--bits", "2", "--rows", "6",
         "--hitters", "1,2,3", "--ctx", "serve count",
         "--no-stablehlo"],
        capture_output=True, text=True, timeout=900, env=env)
    assert bake.returncode == 0, bake.stderr[-3000:]

    # Kill -9 a fresh run mid-flight, then resume WITH the store.
    os.unlink(snap)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(root, "tools", "serve.py"),
         "--bits", "2", "--reports", "6", "--page-size", "3",
         "--seed", "7", "--snapshot", snap,
         "--artifact-dir", store],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        cwd=root, env=env)
    deadline = _time.time() + 600
    while not os.path.exists(snap) and _time.time() < deadline:
        _time.sleep(0.25)
    assert os.path.exists(snap), "no snapshot before the kill"
    proc.send_signal(signal.SIGKILL)
    proc.wait()

    resumed = serve(["--resume", "--artifact-dir", store])
    res_line = json.loads(resumed.stdout.strip().splitlines()[-1])
    assert res_line["ok"]
    # The count tenant's epoch results match the unfaulted run's.
    assert res_line["results"]["count"] == \
        ref_line["results"]["count"]
