"""Link transports under the r8 `Channel` (ISSUE 11): the plain
socket path, plus a `ShapedTransport` that injects bandwidth, RTT and
jitter so the process-separated parties run over a link with
wide-area realism instead of an infinitely fast loopback.

The session layer stays the owner of framing, deadlines and fault
injection; a transport only decides HOW a fully framed byte string
reaches the socket.  `ShapedTransport` models the link on the send
side (both ends shape their own sends, so a bidirectional exchange
pays the shape in both directions):

    delay(frame) = rtt/2 + U(0, jitter) + len(frame)/bandwidth

with the jitter drawn from a SEEDED generator per transport — a
shaped run is replayable, exactly like the fault harness whose clock
(`time.sleep`) it borrows.  The `net_send` fault checkpoint fires per
frame before any pacing, so the whole drop/delay/truncate/corrupt/
hang matrix composes with shaping at the same seam.

`MASTIC_NET_SHAPE` arms it process-wide (every process of a session
parses the lever itself, like `MASTIC_FAULTS`):

    MASTIC_NET_SHAPE="bw=1m:rtt=20ms:jitter=2ms[:seed=N]"

bw is BYTES/second with optional k/m/g multiplier (0 = unlimited);
rtt/jitter accept a trailing "ms" or "s" (plain numbers are seconds).
BASELINE.md's communication-only numbers extend through this into the
measured communication-vs-computation crossover (`bench.py
--parties-wan`; PERF.md §13).
"""

import random
import socket
import time
from dataclasses import dataclass
from typing import Optional


@dataclass
class LinkShape:
    """One direction of a shaped link (each end applies it to its own
    sends)."""

    bandwidth: float = 0.0   # bytes/second; 0 = unlimited
    rtt: float = 0.0         # full round-trip seconds (rtt/2 a send)
    jitter: float = 0.0      # max extra seconds, uniform, seeded
    seed: int = 0

    def __post_init__(self):
        if self.bandwidth < 0 or self.rtt < 0 or self.jitter < 0:
            raise ValueError("link shape values must be >= 0")


_BW_UNITS = {"k": 1e3, "m": 1e6, "g": 1e9}


def _parse_seconds(val: str, field: str) -> float:
    val = val.strip().lower()
    scale = 1.0
    if val.endswith("ms"):
        (val, scale) = (val[:-2], 1e-3)
    elif val.endswith("s"):
        val = val[:-1]
    try:
        return float(val) * scale
    except ValueError:
        raise ValueError(f"link shape {field} must be seconds or "
                         f"'<n>ms', got {val!r}")


def parse_shape(text: Optional[str]) -> Optional[LinkShape]:
    """Parse a MASTIC_NET_SHAPE spec; None/empty means unshaped.
    Unknown keys are errors — a typo'd shape that silently runs at
    loopback speed would make every WAN number vacuous (the
    parse_faults stance)."""
    if text is None or not text.strip():
        return None
    kwargs: dict = {}
    for chunk in text.split(":"):
        chunk = chunk.strip()
        if not chunk:
            continue
        if "=" not in chunk:
            raise ValueError(f"malformed link-shape field {chunk!r} "
                             f"(want key=value)")
        (key, val) = chunk.split("=", 1)
        key = key.strip()
        val = val.strip().lower()
        if key == "bw":
            scale = 1.0
            if val and val[-1] in _BW_UNITS:
                scale = _BW_UNITS[val[-1]]
                val = val[:-1]
            try:
                kwargs["bandwidth"] = float(val) * scale
            except ValueError:
                raise ValueError(f"link shape bw must be bytes/s "
                                 f"with optional k/m/g, got {val!r}")
        elif key in ("rtt", "jitter"):
            kwargs[key] = _parse_seconds(val, key)
        elif key == "seed":
            kwargs["seed"] = int(val)
        else:
            raise ValueError(f"unknown link-shape key {key!r} (must "
                             f"be bw, rtt, jitter or seed)")
    return LinkShape(**kwargs)


def shape_from_env() -> Optional[LinkShape]:
    import os

    return parse_shape(os.environ.get("MASTIC_NET_SHAPE"))


class Transport:
    """The plain path: frames go straight to the socket.  Counts
    bytes so callers (bench, tests) can attribute wire traffic."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.bytes_sent = 0
        self.frames_sent = 0

    def send(self, frame: bytes) -> None:
        self.sock.sendall(frame)
        self.bytes_sent += len(frame)
        self.frames_sent += 1


class ShapedTransport(Transport):
    """Bandwidth/RTT/jitter pacing ahead of every frame, plus the
    `net_send` fault checkpoint — the link-layer twin of the
    checkpoints the party main loops fire between protocol steps."""

    def __init__(self, sock: socket.socket, shape: LinkShape,
                 injector=None):
        super().__init__(sock)
        self.shape = shape
        self.injector = injector
        self._rng = random.Random(shape.seed)
        self.slept_s = 0.0

    def send(self, frame: bytes) -> None:
        if self.injector is not None:
            self.injector.checkpoint("net_send")
        shape = self.shape
        delay = shape.rtt / 2.0
        if shape.jitter > 0:
            delay += self._rng.uniform(0.0, shape.jitter)
        if shape.bandwidth > 0:
            delay += len(frame) / shape.bandwidth
        if delay > 0:
            time.sleep(delay)
            self.slept_s += delay
        super().send(frame)


def for_socket(sock: socket.socket,
               shape: Optional[LinkShape] = None,
               injector=None) -> Optional[Transport]:
    """The transport for a just-built channel socket: None when
    unshaped (the Channel's inline sendall is the plain path — no
    wrapper object per frame on the fast path), a ShapedTransport
    when a shape is armed."""
    if shape is None:
        return None
    return ShapedTransport(sock, shape, injector)
