"""Aggregation-parameter policy: the `is_valid` matrix.

Same cases as the reference policy suite
(/root/reference/poc/tests/test_mastic.py:11-68): the weight check
happens exactly once and on the first round, and the level strictly
increases between rounds (reference mastic.py:187-203; spec
draft-mouris-cfrg-mastic.md:1175-1207).
"""

import pytest

from mastic_tpu import MasticCount

MASTIC = MasticCount(4)

CASES = [
    # (expected, agg_param, previous_agg_params)
    # Weight check on the first round, at any level.
    (True, (0, ((False,),), True), []),
    (True, (2, ((True, False, False),), True), []),
    # Invalid: the weight check never happens.
    (False, (0, ((False,),), False), []),
    # Later round without a weight check, after a checked first round.
    (True, (1, ((False, True),), False),
     [(0, ((False,),), True)]),
    # Invalid: the weight check happens twice.
    (False, (1, ((False, True),), True),
     [(0, ((False,),), True)]),
    # Invalid: the weight check happens, but not on the first round.
    (False, (1, ((False, True),), True),
     [(0, ((False,),), False)]),
    # Invalid: the weight check never happens (two rounds in).
    (False, (1, ((True, False),), False),
     [(0, ((False,),), False)]),
    # Invalid: the level decreases.
    (False, (1, ((True, False),), False),
     [(2, ((True, False, False),), True)]),
    # Invalid: the level repeats.
    (False, (1, ((True, False),), False),
     [(1, ((False, True),), True)]),
]


@pytest.mark.parametrize(("expected", "agg_param", "previous"), CASES)
def test_is_valid_matrix(expected, agg_param, previous):
    assert MASTIC.is_valid(agg_param, previous) is expected
