"""Known-bad (ISSUE 11, network-front flavor): a per-client rate
table that grows one entry per address forever (RB004) — a hostile
address stream converts the admission layer itself into the OOM."""
import collections
import queue


def make_front_state():
    buckets = queue.Queue()            # no maxsize: unbounded
    pending_bodies = collections.deque()   # no maxlen: unbounded
    return (buckets, pending_bodies)


def accept_loop(listener, pending_bodies):
    while True:
        pending_bodies.append(listener.take())
