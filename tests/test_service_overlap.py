"""Overlapped epoch execution + concurrent ingest front (ISSUE 10).

Fast tier: the overlapped scheduler's interleaving discipline,
deadline/supervision/snapshot semantics (split-capable stub runs — no
device work), and the concurrent-submit stress matrix against the
ingest front (zero lost/duplicated uploads, exact shed accounting
under both policies, bounded queue backpressure, deterministic
worker-stall shed).  Slow tier: overlap-vs-serial bit-identity with
REAL rounds — 2 and 3 tenants, mixed heavy-hitters +
attribute-metrics, mesh={1,2} — plus chunked (atomic-quantum) runs
under overlap.  The kill-9 + --resume drill under overlap lives in
`tools/serve.py --overlap-drill` (`make serve-smoke`).
"""

import threading
import time

import pytest

from mastic_tpu.common import gen_rand
from mastic_tpu.drivers import faults
from mastic_tpu.drivers.heavy_hitters import \
    get_reports_from_measurements
from mastic_tpu.drivers.service import (ADMITTED, QUEUED, SHED,
                                        CollectorService,
                                        ServiceConfig, TenantSpec,
                                        encode_upload)
from mastic_tpu.drivers.session import Deadline
from mastic_tpu.mastic import MasticCount

CTX = b"overlap test"
COUNT2 = {"class": "MasticCount", "args": [2]}


def _reports(m, values, bits=2, ctx=CTX):
    meas = [(m.vidpf.test_index_from_int(v, bits), True)
            for v in values]
    return get_reports_from_measurements(m, ctx, meas)


def _spec(name="count", vk=None, m=None, **over):
    m = m or MasticCount(2)
    over.setdefault("thresholds", {"default": 2})
    return TenantSpec(name=name, spec=COUNT2, ctx=CTX,
                      verify_key=vk or gen_rand(m.VERIFY_KEY_SIZE),
                      **over)


def _cfg(**over):
    base = dict(page_size=4, max_buffered=256, max_pending_epochs=8,
                shed_policy="reject-newest", quarantine_limit=64,
                epoch_deadline=600.0)
    base.update(over)
    return ServiceConfig(**base)


def _admit(svc, tenant, m, reports):
    return [svc.submit(tenant, encode_upload(m, r)) for r in reports]


# -- split-capable stub runs (scheduler semantics, no device) --------

class _SplitStub:
    """Duck-typed CollectionRun with the split-phase protocol: each
    round is a begin/finish pair logged into a shared trace, so tests
    assert the INTERLEAVING the overlapped scheduler promises."""

    def __init__(self, rounds=2, log=None, name="",
                 fail_finish_round=None):
        self.rounds = rounds
        self.metrics: list = []
        self.done = False
        self.log = log if log is not None else []
        self.name = name
        self.fail_finish_round = fail_finish_round
        self._n = 0

    def step_begin(self):
        if self.done:
            return None
        self.log.append(("begin", self.name, self._n))
        return {"atomic": False, "round": self._n}

    def step_finish(self, handle):
        if self.fail_finish_round == handle["round"]:
            self.log.append(("fail", self.name, handle["round"]))
            raise RuntimeError("injected finish failure")
        self.log.append(("finish", self.name, handle["round"]))
        self._n += 1
        self.done = self._n >= self.rounds
        return not self.done

    def step(self):
        handle = self.step_begin()
        if handle is None:
            return False
        return self.step_finish(handle)

    def result(self):
        return [f"done-{self.name}"]

    def frontier(self):
        return []

    def rounds_completed(self):
        return self._n

    def to_bytes(self):
        return b"{}"


class _LegacyStub(_SplitStub):
    """No split seam: the scheduler must run it atomically."""

    step_begin = None
    step_finish = None

    def step(self):
        if self.done:
            return False
        self.log.append(("atomic", self.name, self._n))
        self._n += 1
        self.done = self._n >= self.rounds
        return not self.done


def _stub_service(stubs: dict, log, config=None):
    """A service whose runs are the given stubs (by tenant name);
    admission stays real (host-only)."""
    m = MasticCount(2)
    svc = CollectorService(
        [_spec(name=n) for n in stubs], config or _cfg(overlap=2))

    def fake_build(t, reports):
        stub = stubs[t.spec.name]
        if callable(stub):
            return stub()
        return stub

    svc._build_run = fake_build
    for name in stubs:
        _admit(svc, name, m, _reports(m, [0, 3]))
        svc.begin_epoch(name)
    return svc


def test_overlap_interleaves_two_tenants():
    """K=2, two 2-round tenants: tenant b stages while tenant a's
    round is in flight — the exact begin/finish order is asserted, so
    real overlap (not serialized begin+finish pairs) is structural,
    not statistical."""
    log: list = []
    svc = _stub_service({"a": _SplitStub(2, log, "a"),
                         "b": _SplitStub(2, log, "b")}, log)
    while svc.step():
        pass
    assert svc.drained()
    assert log == [
        ("begin", "a", 0), ("begin", "b", 0), ("finish", "a", 0),
        ("begin", "a", 1), ("finish", "b", 0),
        ("begin", "b", 1), ("finish", "a", 1), ("finish", "b", 1),
    ]
    mx = svc.metrics()["tenants"]
    for name in ("a", "b"):
        rec = mx[name]["epochs"][0]
        assert not rec["truncated"]
        assert rec["result"] == [f"done-{name}"]
        assert mx[name]["counters"]["rounds"] == 2


def test_overlap_occupancy_capped_at_k():
    """Three tenants, K=2: never more than 2 rounds in flight, and
    every tenant still completes (round-robin rotation reaches the
    third tenant as slots free up)."""
    log: list = []
    svc = _stub_service({"a": _SplitStub(2, log, "a"),
                         "b": _SplitStub(2, log, "b"),
                         "c": _SplitStub(2, log, "c")}, log,
                        config=_cfg(overlap=2))
    peak = 0
    while svc.step():
        peak = max(peak, svc.inflight_rounds())
    assert peak <= 2
    open_rounds = set()
    for entry in log:
        (kind, name, rnd) = entry
        if kind == "begin":
            open_rounds.add((name, rnd))
            assert len(open_rounds) <= 2, log
        elif kind == "finish":
            open_rounds.remove((name, rnd))
    mx = svc.metrics()["tenants"]
    assert all(mx[n]["epochs"][0]["result"] == [f"done-{n}"]
               for n in ("a", "b", "c"))


def test_overlap_runs_legacy_runs_atomically():
    """A run kind without the split protocol executes whole inside
    its stage slot; a split-capable tenant still overlaps around
    it."""
    log: list = []
    svc = _stub_service({"a": _LegacyStub(2, log, "a"),
                         "b": _SplitStub(2, log, "b")}, log)
    while svc.step():
        pass
    assert svc.drained()
    assert ("atomic", "a", 0) in log and ("atomic", "a", 1) in log
    mx = svc.metrics()["tenants"]
    assert mx["a"]["epochs"][0]["result"] == ["done-a"]
    assert mx["b"]["epochs"][0]["result"] == ["done-b"]


def test_overlap_deadline_truncates_before_stage():
    log: list = []
    m = MasticCount(2)
    svc = CollectorService(
        [_spec(name="slow", epoch_deadline=0.0)], _cfg(overlap=2))
    svc._build_run = lambda t, reports: _SplitStub(2, log, "slow")
    _admit(svc, "slow", m, _reports(m, [0, 3]))
    svc.begin_epoch("slow")
    while svc.step():
        pass
    rec = svc.metrics()["tenants"]["slow"]["epochs"][0]
    assert rec["truncated"] and rec["levels_completed"] == 0
    assert svc.metrics()["tenants"]["slow"]["counters"][
        "deadline_misses"] == 1
    # the deadline fired before any round staged
    assert log == []


def test_overlap_supervision_rebuilds_on_finish_failure():
    """A collect-side failure mid-overlap rebuilds the run (device
    state after a half-collected round is suspect) and the epoch
    completes on the retry."""
    log: list = []
    builds: list = []

    def build():
        stub = _SplitStub(2, log, f"try{len(builds)}",
                          fail_finish_round=(0 if not builds
                                             else None))
        builds.append(stub)
        return stub

    svc = _stub_service({"a": build}, log,
                        config=_cfg(overlap=2, epoch_retries=1))
    while svc.step():
        pass
    assert len(builds) == 2
    rec = svc.metrics()["tenants"]["a"]["epochs"][0]
    assert not rec["truncated"] and "error" not in rec
    c = svc.metrics()["tenants"]["a"]["counters"]
    assert c["epochs_completed"] == 1 and c["epochs_failed"] == 0


def test_snapshot_drains_inflight_rounds():
    """to_bytes() is a quiescent point: staged rounds collect first,
    so the snapshot never serializes a half-staged round."""
    log: list = []
    svc = _stub_service({"a": _SplitStub(3, log, "a"),
                         "b": _SplitStub(3, log, "b")}, log)
    svc.step()
    assert svc.inflight_rounds() == 1   # b staged, a collected
    svc.to_bytes()
    assert svc.inflight_rounds() == 0
    finishes = [e for e in log if e[0] == "finish"]
    begins = [e for e in log if e[0] == "begin"]
    assert len(finishes) == len(begins)   # everything staged retired
    while svc.step():
        pass
    assert svc.drained()


# -- concurrent ingest front -----------------------------------------

def _burst(svc, items, threads=4):
    """Submit (tenant, blob) items from `threads` concurrent client
    threads; returns the flat outcome list."""
    outcomes: list = []
    mu = threading.Lock()
    shards = [items[i::threads] for i in range(threads)]

    def feed(mine):
        got = [svc.submit(tn, blob) for (tn, blob) in mine]
        with mu:
            outcomes.extend(got)

    ths = [threading.Thread(target=feed, args=(s,)) for s in shards]
    for th in ths:
        th.start()
    for th in ths:
        th.join()
    return outcomes


def _page_blobs(t) -> list:
    """Every upload blob currently buffered by the tenant (open page
    + sealed pages), decoded from the stored bytes."""
    out = list(t.open_page.decode_blobs())
    for page in t.sealed:
        assert page.verify()
        out += page.decode_blobs()
    return out


def test_ingest_concurrent_stress_reject_newest():
    """4 client threads, unique uploads, malformed mixed in: every
    submission accounted exactly once; the buffered pages hold
    exactly the admitted blobs (no loss, no duplication); quarantine
    counts the malformed ones precisely."""
    m = MasticCount(2)
    svc = CollectorService(
        [_spec(name="a", max_buffered=24),
         _spec(name="b", max_buffered=24)],
        config=_cfg(ingest_threads=3, ingest_queue=256,
                    quarantine_limit=1000))
    good = [encode_upload(m, r)
            for r in _reports(m, [i % 4 for i in range(60)])]
    junk = [bytes([7]) * (9 + i) for i in range(8)]
    items = [(("a" if i % 2 else "b"), blob)
             for (i, blob) in enumerate(good + junk)]
    outcomes = _burst(svc, items)
    assert all(o[0] == QUEUED for o in outcomes)
    svc.flush_ingest()
    svc.stop_ingest()
    mx = svc.metrics()["tenants"]
    total = {"admitted": 0, "quarantined": 0, "shed": 0}
    submitted = {"a": [b for (tn, b) in items if tn == "a"],
                 "b": [b for (tn, b) in items if tn == "b"]}
    for name in ("a", "b"):
        c = mx[name]["counters"]
        for key in total:
            total[key] += c[key]
        t = svc.tenants[name]
        buffered = _page_blobs(t)
        # no loss, no duplication: buffered == admitted exactly, every
        # buffered blob was submitted, none twice
        assert len(buffered) == c["admitted"]
        assert len(set(buffered)) == len(buffered)
        assert set(buffered) <= set(submitted[name])
        assert c["shed_reasons"].get("reject-newest", 0) \
            == c["shed"]
        assert c["quarantine_reasons"].get("malformed", 0) \
            == c["quarantined"]
    assert total["quarantined"] == len(junk)
    assert total["admitted"] + total["shed"] == len(good)
    assert total["admitted"] == 2 * 24   # both quotas filled exactly


def test_ingest_concurrent_stress_oldest_epoch_first():
    """Under oldest-epoch-first, concurrent over-quota admission
    drops the queued epoch (counted per report) instead of the
    incoming uploads — and the accounting still balances exactly."""
    m = MasticCount(2)
    svc = CollectorService(
        [_spec(name="a", max_buffered=8)],
        config=_cfg(shed_policy="oldest-epoch-first",
                    ingest_threads=2, ingest_queue=256))
    first = [encode_upload(m, r) for r in _reports(m, [0] * 8)]
    for blob in first:
        svc.submit("a", blob)
    svc.flush_ingest()
    assert svc.begin_epoch("a") == 0
    fresh = [encode_upload(m, r) for r in _reports(m, [3] * 8)]
    outcomes = _burst(svc, [("a", b) for b in fresh], threads=2)
    assert all(o[0] == QUEUED for o in outcomes)
    svc.flush_ingest()
    svc.stop_ingest()
    c = svc.metrics()["tenants"]["a"]["counters"]
    # the queued epoch's 8 reports shed to make room; the 8 fresh
    # uploads all admitted
    assert c["shed_reasons"] == {"oldest-epoch-first": 8}
    assert c["admitted"] == 16
    assert svc.metrics()["tenants"]["a"]["pending_epochs"] == 0
    buffered = _page_blobs(svc.tenants["a"])
    assert sorted(buffered) == sorted(fresh)


def test_ingest_queue_full_sheds_attributed():
    """A stalled worker (deterministic `delay` fault at the admit
    checkpoint) backs the bounded queue up: the caller-side sheds
    carry reason ingest-queue-full and the counters agree with the
    callers exactly."""
    m = MasticCount(2)
    inj = faults.FaultInjector(
        faults.parse_faults(
            "delay:party=collector:step=admit:nth=1:delay=0.8"),
        "collector")
    svc = CollectorService(
        [_spec(name="a")],
        config=_cfg(ingest_threads=1, ingest_queue=1), injector=inj)
    blobs = [encode_upload(m, r) for r in _reports(m, [0, 1, 2, 3])]
    assert svc.submit("a", blobs[0])[0] == QUEUED
    # let the single worker pick it up and stall in the fault
    time.sleep(0.3)
    outcomes = [svc.submit("a", b) for b in blobs[1:]]
    assert outcomes[0][0] == QUEUED          # fills the 1-deep queue
    assert outcomes[1] == (SHED, "ingest-queue-full")
    assert outcomes[2] == (SHED, "ingest-queue-full")
    svc.flush_ingest()
    svc.stop_ingest()
    c = svc.metrics()["tenants"]["a"]["counters"]
    assert c["admitted"] == 2
    assert c["shed_reasons"] == {"ingest-queue-full": 2}


def test_stop_ingest_restores_inprocess_submit():
    m = MasticCount(2)
    svc = CollectorService([_spec(name="a")],
                           config=_cfg(ingest_threads=1))
    blob = encode_upload(m, _reports(m, [0])[0])
    assert svc.submit("a", blob)[0] == QUEUED
    svc.stop_ingest()
    assert svc.submit("a", blob)[0] == ADMITTED
    assert svc.metrics()["tenants"]["a"]["counters"]["admitted"] == 2


def test_begin_epoch_flushes_ingest_queue():
    """An epoch cut must include every upload submitted before it —
    nothing may be lost in the queue."""
    m = MasticCount(2)
    svc = CollectorService([_spec(name="a")],
                           config=_cfg(ingest_threads=2))
    _burst(svc, [("a", encode_upload(m, r))
                 for r in _reports(m, [0] * 12)], threads=3)
    assert svc.begin_epoch("a") == 0
    svc.stop_ingest()
    t = svc.metrics()["tenants"]["a"]
    assert t["counters"]["admitted"] == 12
    assert t["buffered_reports"] == 12
    assert sum(p.count
               for p in svc.tenants["a"].pending[0].pages) == 12


# -- slow tier: real rounds, overlap vs serial bit-identity ----------

def _strip(rec: dict) -> dict:
    return {k: v for (k, v) in rec.items()
            if k not in ("wall_s", "compile_ms", "inline_compiles")}


def _run_service(specs, admissions, config, mesh=None) -> dict:
    svc = CollectorService([TenantSpec(**s) for s in specs],
                           config=config, mesh=mesh)
    for (name, m, reports) in admissions:
        _admit(svc, name, m, reports)
        svc.begin_epoch(name)
    assert svc.run_until_drained(deadline=Deadline(1800.0))
    svc.stop_ingest()
    return {name: [_strip(rec) for rec in t["epochs"]]
            for (name, t) in svc.metrics()["tenants"].items()}


def _mixed_workload(n_hh: int):
    """n_hh heavy-hitters tenants + one attribute-metrics tenant,
    with deterministic keys/reports shared across scheduler modes."""
    from mastic_tpu.drivers.attribute_metrics import hash_attribute

    m = MasticCount(2)
    m8 = MasticCount(8)
    vk = bytes(range(m.VERIFY_KEY_SIZE))
    specs = []
    admissions = []
    for i in range(n_hh):
        name = f"hh{i}"
        specs.append(dict(name=name, spec=COUNT2, ctx=CTX,
                          verify_key=vk,
                          thresholds={"default": 2}))
        admissions.append((name, m, _reports(m, [0, 0, 3, 3, 1])))
    alpha = hash_attribute(m8, "checkout.html")
    attr_val = int("".join("1" if b else "0" for b in alpha), 2)
    specs.append(dict(name="attrs",
                      spec={"class": "MasticCount", "args": [8]},
                      ctx=CTX, verify_key=bytes(range(32)),
                      mode="attribute_metrics",
                      attributes=["checkout.html", "landing.html"]))
    admissions.append(
        ("attrs", m8,
         _reports(m8, [attr_val, attr_val, 0], bits=8)))
    return (specs, admissions)


@pytest.mark.slow
@pytest.mark.parametrize("n_hh", [1, 2])
def test_overlap_bit_identical_mixed_tenants(n_hh):
    """The acceptance matrix core: 2 and 3 tenants (heavy hitters +
    attribute metrics), serial round-robin vs overlapped executor
    with the ingest front armed — every per-tenant epoch record
    (results, counters-relevant fields) equal bit for bit."""
    (specs, admissions) = _mixed_workload(n_hh)
    serial = _run_service(specs, admissions, _cfg())
    overlapped = _run_service(
        specs, admissions,
        _cfg(overlap=2, ingest_threads=2))
    assert overlapped == serial
    # sanity: the runs actually computed (no silent empty epochs)
    assert serial[f"hh0"][0]["result"], serial


@pytest.mark.slow
def test_overlap_bit_identical_chunked_and_mesh():
    """Chunked runs execute atomically under overlap (no split seam)
    and stay bit-identical; with 2 virtual devices the mesh-sharded
    service under overlap equals the serial single-device run."""
    import jax

    m = MasticCount(2)
    vk = bytes(range(m.VERIFY_KEY_SIZE))
    specs = [dict(name="chunked", spec=COUNT2, ctx=CTX,
                  verify_key=vk, thresholds={"default": 2},
                  chunk_size=3),
             dict(name="resident", spec=COUNT2, ctx=CTX,
                  verify_key=vk, thresholds={"default": 2})]
    # 6 reports: the resident runner shards evenly over mesh=2 (its
    # divisibility requirement predates this PR) and the chunked
    # tenant still gets an uneven 3+3 split across two chunks.
    reports = _reports(m, [0, 0, 3, 3, 1, 1])
    admissions = [("chunked", m, reports), ("resident", m, reports)]
    serial = _run_service(specs, admissions, _cfg())
    overlapped = _run_service(specs, admissions,
                              _cfg(overlap=2, ingest_threads=2))
    assert overlapped == serial
    if jax.device_count() >= 2:
        from mastic_tpu.parallel import make_mesh

        meshed = _run_service(specs, admissions,
                              _cfg(overlap=2),
                              mesh=make_mesh(2, nodes_axis=1))
        assert meshed == serial
