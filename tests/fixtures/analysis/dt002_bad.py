"""Known-bad: narrowing astype over an unmasked shift (DT002)."""

import jax.numpy as jnp


def truncating(v):
    return (v << 4).astype(jnp.uint8)
