"""Known-bad: does not parse (XX000)."""


def broken(:
    return 0
