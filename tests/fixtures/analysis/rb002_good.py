"""Known-good: except blocks re-raise or record the outcome (RB002)."""


def report(path: str, counters: dict) -> int:
    try:
        with open(path) as f:
            return len(f.read())
    except OSError as exc:
        counters["read_errors"] = counters.get("read_errors", 0) + 1
        raise ValueError(f"unreadable {path}") from exc


def count(path: str, counters: dict) -> int:
    try:
        with open(path) as f:
            return len(f.read())
    except OSError:
        counters["read_errors"] = counters.get("read_errors", 0) + 1
        return 0
