"""Pass 6 — observability hygiene (ISSUE 7).

Scope: mastic_tpu/ library code.  tools/ and tests/ are exempt (CLIs
print their JSON lines; tests print diagnostics), and so is
`mastic_tpu/gen_test_vec.py` (a file-generator CLI that happens to
live inside the package).

  OB001  a bare `print(` in library code.  The library's output
         channels are the telemetry layer (`mastic_tpu/obs/`): spans
         and span events for anything timed or attributed, registry
         counters for anything counted, `RoundMetrics.extra` for
         per-round structure.  A print — stdout OR stderr — is
         invisible to every one of them: it cannot be scraped,
         asserted on, attributed to a tenant, or found after the
         process died.  (The lint gate's check 4 only bans *stdout*
         prints; this rule closes the stderr loophole the r8 party
         debug logging used.)  Genuinely interactive diagnostics
         carry an allow naming why the tracer cannot serve them.

Intentional exceptions are suppressed inline with a justified
`# mastic-allow: OB00x — reason`, same as every other pass.
"""

import ast

from .core import Finding

PASS_NAME = "observability"

RULES = {
    "OB001": "bare print() in library code — route through the "
             "tracer/registry (mastic_tpu/obs/)",
}

SCOPE_PREFIX = "mastic_tpu/"

# CLI-shaped files inside the package: their stdout IS the interface.
EXEMPT_FILES = ("mastic_tpu/gen_test_vec.py",)


def in_scope(rel: str) -> bool:
    return rel.startswith(SCOPE_PREFIX) and rel not in EXEMPT_FILES


def check(info) -> list:
    findings: list = []
    for node in ast.walk(info.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Name) \
                and node.func.id == "print":
            findings.append(Finding(
                "OB001", info.rel, node.lineno,
                "bare print() in library code — a printed diagnostic "
                "cannot be scraped, asserted on, or tenant-attributed;"
                " record a span event (obs.trace.event) or a registry "
                "counter instead, or allow with the reason the tracer "
                "cannot serve it"))
    return findings
