"""Tower-field AES S-box circuit: GF(2^8) inversion via
GF(2^2) -> GF(2^4) -> GF(2^8), ~4x fewer gates than the x^254
addition chain.

The isomorphism between the AES polynomial representation
(mod x^8+x^4+x^3+x+1) and the tower representation is DERIVED here at
import time — phi is fixed by sending the AES generator X=0x02 to a
root of the AES modulus inside the tower field, and the S-box affine
map is fused into the output basis-change matrix.  The circuit
functions are representation-agnostic (only ^ and & between planes);
`ops/aes_jax.py` locks the whole construction against the generated
S-box table on numpy at import, so a derivation bug cannot ship.

Gate count per S-box: 2 basis changes (~60 XOR) + tower inversion
(~150 gates: 9 AND-heavy GF(2^2) multiplies inside 3 GF(2^4)
multiplies + one GF(2^4) inversion) vs ~830 for the addition chain.

Tower layout (bit i of a tower byte):
  GF(2^2) element  = b0 + b1*W,            W^2 = W + 1
  GF(2^4) element  = lo2 + hi2*x,          x^2 = x + N,  N = W
  GF(2^8) element  = lo4 + hi4*y,          y^2 = y + NU (derived)
  bits: [b0..b3] = lo4 (b0,b1 its lo2; b2,b3 its hi2), [b4..b7] = hi4
"""

import numpy as np

# -- host-side tower arithmetic on ints (for deriving matrices) ------


def _mul2i(a: int, b: int) -> int:
    (a0, a1) = (a & 1, a >> 1)
    (b0, b1) = (b & 1, b >> 1)
    q = (a0 ^ a1) & (b0 ^ b1)
    p = a0 & b0
    t = a1 & b1
    return (p ^ t) | ((q ^ p) << 1)


def _mulw_i(a: int) -> int:
    (a0, a1) = (a & 1, a >> 1)
    return a1 | ((a0 ^ a1) << 1)


def _mul4i(a: int, b: int) -> int:
    (al, ah) = (a & 3, a >> 2)
    (bl, bh) = (b & 3, b >> 2)
    hh = _mul2i(ah, bh)
    ll = _mul2i(al, bl)
    m = _mul2i(ah ^ al, bh ^ bl)
    return (ll ^ _mulw_i(hh)) | ((m ^ ll) << 2)


def _mul8i(a: int, b: int, nu: int) -> int:
    (al, ah) = (a & 15, a >> 4)
    (bl, bh) = (b & 15, b >> 4)
    hh = _mul4i(ah, bh)
    ll = _mul4i(al, bl)
    m = _mul4i(ah ^ al, bh ^ bl)
    return (ll ^ _mul4i(hh, nu)) | ((m ^ ll) << 4)


def _find_nu() -> int:
    """Smallest nu making y^2 + y + nu irreducible over GF(2^4)."""
    for nu in range(1, 16):
        if all(_mul4i(y, y) ^ y ^ nu for y in range(16)):
            return nu
    raise AssertionError("no irreducible quadratic (unreachable)")


NU = _find_nu()


def _derive_matrices():
    """phi: AES poly basis -> tower basis (8x8 over GF(2)), and the
    output map = AES affine matrix composed with phi^-1."""
    from ..aes import _gf_mul  # AES-field multiply (mod 0x11B)

    # Root of the AES modulus inside the tower field.
    def aes_modulus_tower(t: int) -> int:
        acc = 0
        for e in (8, 4, 3, 1, 0):
            p = 1
            for _ in range(e):
                p = _mul8i(p, t, NU)
            acc ^= p
        return acc

    root = next(t for t in range(2, 256)
                if aes_modulus_tower(t) == 0)

    # phi matrix columns: phi(X^i) = root^i in tower rep.
    cols = []
    p = 1
    for _ in range(8):
        cols.append(p)
        p = _mul8i(p, root, NU)
    phi = np.zeros((8, 8), np.uint8)
    for (j, val) in enumerate(cols):
        for i in range(8):
            phi[i, j] = (val >> i) & 1

    # Invert phi over GF(2) (Gauss-Jordan).
    m = np.concatenate([phi.copy(), np.eye(8, dtype=np.uint8)], axis=1)
    for col in range(8):
        pivot = next(r for r in range(col, 8) if m[r, col])
        m[[col, pivot]] = m[[pivot, col]]
        for r in range(8):
            if r != col and m[r, col]:
                m[r] ^= m[col]
    phi_inv = m[:, 8:]

    # AES S-box affine matrix: out_i = sum_j in_{(j+i) mod 8 ...};
    # rows of the standard affine: bit i = b_i ^ b_{(i+4)%8} ^
    # b_{(i+5)%8} ^ b_{(i+6)%8} ^ b_{(i+7)%8}.
    affine = np.zeros((8, 8), np.uint8)
    for i in range(8):
        for off in (0, 4, 5, 6, 7):
            affine[i, (i + off) % 8] ^= 1
    out_map = (affine @ phi_inv) % 2
    # Sanity: phi is a field isomorphism (spot-check products).
    for (a, b) in ((0x57, 0x83), (0x02, 0x80), (0xFF, 0x1B)):
        ta = _apply_int(phi, a)
        tb = _apply_int(phi, b)
        assert _apply_int(phi_inv, _mul8i(ta, tb, NU)) == _gf_mul(a, b)
    return (phi.astype(np.uint8), out_map.astype(np.uint8))


def _apply_int(matrix: np.ndarray, val: int) -> int:
    out = 0
    for i in range(8):
        bit = 0
        for j in range(8):
            if matrix[i, j]:
                bit ^= (val >> j) & 1
        out |= bit << i
    return out


(PHI, OUT_MAP) = _derive_matrices()


# -- the circuit (representation-agnostic: ^ and & on planes) --------


def _apply_matrix(matrix: np.ndarray, planes: list) -> list:
    out = []
    for i in range(8):
        acc = None
        for j in range(8):
            if matrix[i, j]:
                acc = planes[j] if acc is None else acc ^ planes[j]
        out.append(acc)
    return out


def _mul2(a: list, b: list) -> list:
    q = (a[0] ^ a[1]) & (b[0] ^ b[1])
    p = a[0] & b[0]
    t = a[1] & b[1]
    return [p ^ t, q ^ p]


def _sq2(a: list) -> list:
    return [a[0] ^ a[1], a[1]]


def _mulw(a: list) -> list:
    return [a[1], a[0] ^ a[1]]


def _mul4(a: list, b: list) -> list:
    (al, ah) = (a[:2], a[2:])
    (bl, bh) = (b[:2], b[2:])
    hh = _mul2(ah, bh)
    ll = _mul2(al, bl)
    m = _mul2([ah[0] ^ al[0], ah[1] ^ al[1]],
              [bh[0] ^ bl[0], bh[1] ^ bl[1]])
    lo = _mulw(hh)
    return [ll[0] ^ lo[0], ll[1] ^ lo[1], m[0] ^ ll[0], m[1] ^ ll[1]]


def _sq4(a: list) -> list:
    (al, ah) = (a[:2], a[2:])
    hs = _sq2(ah)
    ls = _sq2(al)
    lo = _mulw(hs)
    return [ls[0] ^ lo[0], ls[1] ^ lo[1], hs[0], hs[1]]


def _scale4(a: list, const: int) -> list:
    """Multiply by a GF(2^4) constant via its bit-matrix (precomputed
    per constant; used only for NU)."""
    matrix = _SCALE4_MATRICES[const]
    out = []
    for i in range(4):
        acc = None
        for j in range(4):
            if matrix[i, j]:
                acc = a[j] if acc is None else acc ^ a[j]
        out.append(acc)
    return out


def _scale4_matrix(const: int) -> np.ndarray:
    matrix = np.zeros((4, 4), np.uint8)
    for j in range(4):
        val = _mul4i(1 << j, const)
        for i in range(4):
            matrix[i, j] = (val >> i) & 1
    return matrix


_SCALE4_MATRICES = {NU: _scale4_matrix(NU)}


def _inv4(a: list) -> list:
    """GF(2^4) inversion via the GF(2^2) norm (delta^-1 = delta^2)."""
    (al, ah) = (a[:2], a[2:])
    delta = _mulw(_sq2(ah))
    prod = _mul2(ah, al)
    lsq = _sq2(al)
    delta = [delta[0] ^ prod[0] ^ lsq[0], delta[1] ^ prod[1] ^ lsq[1]]
    dinv = _sq2(delta)
    out_h = _mul2(ah, dinv)
    out_l = _mul2([ah[0] ^ al[0], ah[1] ^ al[1]], dinv)
    return out_l + out_h


def _inv8(a: list) -> list:
    """GF(2^8) inversion (0 -> 0) via the GF(2^4) norm."""
    (al, ah) = (a[:4], a[4:])
    delta = _scale4(_sq4(ah), NU)
    prod = _mul4(ah, al)
    lsq = _sq4(al)
    delta = [delta[i] ^ prod[i] ^ lsq[i] for i in range(4)]
    dinv = _inv4(delta)
    out_h = _mul4(ah, dinv)
    out_l = _mul4([ah[i] ^ al[i] for i in range(4)], dinv)
    return out_l + out_h


def sbox_planes_tower(planes: list, one) -> list:
    """The AES S-box on 8 bit-planes: basis change in, tower-field
    inversion, affine-fused basis change out, 0x63 constant (`one` is
    1 for 0/1 byte planes, all-ones for packed uint32 planes)."""
    t = _apply_matrix(PHI, planes)
    inv = _inv8(t)
    out = _apply_matrix(OUT_MAP, inv)
    for i in range(8):
        if (0x63 >> i) & 1:
            out[i] = out[i] ^ one
    return out
