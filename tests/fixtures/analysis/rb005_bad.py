"""Known-bad: a deadline-less scheduler loop (RB005) — nothing bounds
the drain if an epoch wedges."""


class EpochScheduler:
    def __init__(self):
        self.pending = []

    def step(self) -> bool:
        return bool(self.pending)

    def run_until_drained(self) -> None:
        while self.step():
            pass
