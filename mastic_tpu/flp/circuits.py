"""The five validity circuits used by the Mastic instantiations
(draft-irtf-cfrg-vdaf-13 §7.4; consumed at reference mastic.py:567-614).

Measured parameter ground truth (SURVEY.md §2.4):
  Count               F64   MEAS_LEN 1, PROOF_LEN 5,  verifier 4, no jr
  Sum(max=7)          F64   MEAS_LEN 6, PROOF_LEN 16, verifier 3, no jr
  SumVec(3,1,1)       F128  MEAS_LEN 3, PROOF_LEN 9,  verifier 4, jr
  Histogram(4,2)      F128  MEAS_LEN 4, PROOF_LEN 11, verifier 6, jr
  MultihotCountVec(4,2,2) F128 MEAS_LEN 6, PROOF_LEN 11, verifier 6, jr
"""

from typing import Generic

from ..field import F
from .flp import Gadget, Mul, ParallelSum, PolyEval, Valid


class Count(Valid[int, int, F]):
    """f(x) = x^2 - x: valid iff the measurement is 0 or 1."""

    JOINT_RAND_LEN = 0
    MEAS_LEN = 1
    OUTPUT_LEN = 1
    EVAL_OUTPUT_LEN = 1

    def __init__(self, field: type[F]):
        self.field = field
        self.GADGETS: list[Gadget[F]] = [Mul()]
        self.GADGET_CALLS = [1]

    def eval(self, meas: list[F], joint_rand: list[F],
             num_shares: int) -> list[F]:
        self.check_valid_eval(meas, joint_rand)
        squared = self.GADGETS[0].eval(self.field, [meas[0], meas[0]])
        return [squared - meas[0]]

    def encode(self, measurement: int) -> list[F]:
        if measurement not in range(2):
            raise ValueError("measurement out of range")
        return [self.field(measurement)]

    def truncate(self, meas: list[F]) -> list[F]:
        if len(meas) != 1:
            raise ValueError("incorrect measurement length")
        return meas

    def decode(self, output: list[F],
               _num_measurements: int) -> int:
        return output[0].int()

    def test_vec_set_type_param(self, test_vec: dict) -> list[str]:
        return []


class Sum(Valid[int, int, F]):
    """Dual bit-decomposition range check: meas encodes `m` and
    `m + offset` in `bits` bits each; both must be boolean and decode
    consistently, proving 0 <= m <= max_measurement."""

    EVAL_OUTPUT_LEN: int
    JOINT_RAND_LEN = 0
    OUTPUT_LEN = 1

    def __init__(self, field: type[F], max_measurement: int):
        self.field = field
        self.max_measurement = max_measurement
        self.bits = max_measurement.bit_length()
        self.offset = self.field(2 ** self.bits - 1 - max_measurement)
        self.MEAS_LEN = 2 * self.bits
        self.EVAL_OUTPUT_LEN = 2 * self.bits + 1
        self.GADGETS: list[Gadget[F]] = [PolyEval([0, -1, 1])]
        self.GADGET_CALLS = [2 * self.bits]

    def eval(self, meas: list[F], joint_rand: list[F],
             num_shares: int) -> list[F]:
        self.check_valid_eval(meas, joint_rand)
        shares_inv = self.field(num_shares).inv()
        out = []
        for b in meas:
            out.append(self.GADGETS[0].eval(self.field, [b]))
        range_check = self.offset * shares_inv + \
            self.field.decode_from_bit_vector(meas[:self.bits]) - \
            self.field.decode_from_bit_vector(meas[self.bits:])
        out.append(range_check)
        return out

    def encode(self, measurement: int) -> list[F]:
        if measurement not in range(self.max_measurement + 1):
            raise ValueError("measurement out of range")
        return self.field.encode_into_bit_vector(measurement, self.bits) + \
            self.field.encode_into_bit_vector(
                measurement + self.offset.int(), self.bits)

    def truncate(self, meas: list[F]) -> list[F]:
        return [self.field.decode_from_bit_vector(meas[:self.bits])]

    def decode(self, output: list[F],
               _num_measurements: int) -> int:
        return output[0].int()

    def test_vec_set_type_param(self, test_vec: dict) -> list[str]:
        test_vec["max_measurement"] = self.max_measurement
        return ["max_measurement"]


class _ParallelSumRangeChecks(Generic[F]):
    """Shared helper: random-linear-combination bit checks evaluated as
    a ParallelSum of Mul gadget calls over fixed-size chunks
    (vdaf-13 §7.4.3)."""

    field: type[F]
    GADGETS: list[Gadget[F]]

    def parallel_sum_range_checks(self, meas: list[F],
                                  joint_rand: list[F],
                                  chunk_length: int,
                                  num_shares: int) -> F:
        field = self.field
        shares_inv = field(num_shares).inv()
        out = field(0)
        for (chunk_index, r) in enumerate(joint_rand):
            inputs: list[F] = []
            r_power = r
            for j in range(chunk_length):
                index = chunk_index * chunk_length + j
                meas_elem = meas[index] if index < len(meas) else field(0)
                inputs.append(r_power * meas_elem)
                inputs.append(meas_elem - shares_inv)
                r_power = r_power * r
            out += self.GADGETS[0].eval(field, inputs)
        return out


class SumVec(_ParallelSumRangeChecks[F], Valid[list[int], list[int], F]):
    """Vector of `length` sums, each in `bits` bits."""

    EVAL_OUTPUT_LEN = 1

    def __init__(self, field: type[F], length: int, bits: int,
                 chunk_length: int):
        self.field = field
        self.length = length
        self.bits = bits
        self.chunk_length = chunk_length
        self.MEAS_LEN = length * bits
        self.OUTPUT_LEN = length
        self.GADGET_CALLS = [
            (length * bits + chunk_length - 1) // chunk_length]
        self.JOINT_RAND_LEN = self.GADGET_CALLS[0]
        self.GADGETS: list[Gadget[F]] = [
            ParallelSum(Mul(), chunk_length)]

    def eval(self, meas: list[F], joint_rand: list[F],
             num_shares: int) -> list[F]:
        self.check_valid_eval(meas, joint_rand)
        return [self.parallel_sum_range_checks(
            meas, joint_rand, self.chunk_length, num_shares)]

    def encode(self, measurement: list) -> list[F]:
        if len(measurement) != self.length:
            raise ValueError("incorrect measurement length")
        encoded = []
        for val in measurement:
            if val not in range(2 ** self.bits):
                raise ValueError("measurement entry out of range")
            encoded += self.field.encode_into_bit_vector(val, self.bits)
        return encoded

    def truncate(self, meas: list[F]) -> list[F]:
        return [
            self.field.decode_from_bit_vector(
                meas[i * self.bits:(i + 1) * self.bits])
            for i in range(self.length)
        ]

    def decode(self, output: list[F],
               _num_measurements: int) -> list[int]:
        return [x.int() for x in output]

    def test_vec_set_type_param(self, test_vec: dict) -> list[str]:
        test_vec["length"] = self.length
        test_vec["bits"] = self.bits
        test_vec["chunk_length"] = self.chunk_length
        return ["length", "bits", "chunk_length"]


class Histogram(_ParallelSumRangeChecks[F], Valid[int, list[int], F]):
    """One-hot vector of `length` buckets."""

    EVAL_OUTPUT_LEN = 2

    def __init__(self, field: type[F], length: int, chunk_length: int):
        self.field = field
        self.length = length
        self.chunk_length = chunk_length
        self.MEAS_LEN = length
        self.OUTPUT_LEN = length
        self.GADGET_CALLS = [(length + chunk_length - 1) // chunk_length]
        self.JOINT_RAND_LEN = self.GADGET_CALLS[0]
        self.GADGETS: list[Gadget[F]] = [
            ParallelSum(Mul(), chunk_length)]

    def eval(self, meas: list[F], joint_rand: list[F],
             num_shares: int) -> list[F]:
        self.check_valid_eval(meas, joint_rand)
        range_check = self.parallel_sum_range_checks(
            meas, joint_rand, self.chunk_length, num_shares)
        shares_inv = self.field(num_shares).inv()
        sum_check = -shares_inv
        for b in meas:
            sum_check += b
        return [range_check, sum_check]

    def encode(self, measurement: int) -> list[F]:
        if measurement not in range(self.length):
            raise ValueError("measurement out of range")
        encoded = self.field.zeros(self.length)
        encoded[measurement] = self.field(1)
        return encoded

    def truncate(self, meas: list[F]) -> list[F]:
        return meas

    def decode(self, output: list[F],
               _num_measurements: int) -> list[int]:
        return [x.int() for x in output]

    def test_vec_set_type_param(self, test_vec: dict) -> list[str]:
        test_vec["length"] = self.length
        test_vec["chunk_length"] = self.chunk_length
        return ["length", "chunk_length"]


class MultihotCountVec(_ParallelSumRangeChecks[F],
                       Valid[list[bool], list[int], F]):
    """Boolean vector with at most `max_weight` ones; the claimed weight
    is carried in an offset bit encoding and cross-checked against the
    actual weight."""

    EVAL_OUTPUT_LEN = 2

    def __init__(self, field: type[F], length: int, max_weight: int,
                 chunk_length: int):
        self.field = field
        self.length = length
        self.max_weight = max_weight
        self.chunk_length = chunk_length
        self.bits_for_weight = max_weight.bit_length()
        self.offset = self.field(
            2 ** self.bits_for_weight - 1 - max_weight)
        self.MEAS_LEN = length + self.bits_for_weight
        self.OUTPUT_LEN = length
        self.GADGET_CALLS = [
            (self.MEAS_LEN + chunk_length - 1) // chunk_length]
        self.JOINT_RAND_LEN = self.GADGET_CALLS[0]
        self.GADGETS: list[Gadget[F]] = [
            ParallelSum(Mul(), chunk_length)]

    def eval(self, meas: list[F], joint_rand: list[F],
             num_shares: int) -> list[F]:
        self.check_valid_eval(meas, joint_rand)
        range_check = self.parallel_sum_range_checks(
            meas, joint_rand, self.chunk_length, num_shares)
        shares_inv = self.field(num_shares).inv()
        count_vec = meas[:self.length]
        weight = self.field(0)
        for b in count_vec:
            weight += b
        weight_reported = \
            self.field.decode_from_bit_vector(meas[self.length:])
        weight_check = self.offset * shares_inv + weight - weight_reported
        return [range_check, weight_check]

    def encode(self, measurement: list) -> list[F]:
        if len(measurement) != self.length:
            raise ValueError("incorrect measurement length")
        count_vec = [self.field(int(x)) for x in measurement]
        weight = sum(int(x) for x in measurement)
        if weight > self.max_weight:
            raise ValueError("measurement weight too large")
        encoded_weight = self.field.encode_into_bit_vector(
            weight + self.offset.int(), self.bits_for_weight)
        return count_vec + encoded_weight

    def truncate(self, meas: list[F]) -> list[F]:
        return meas[:self.length]

    def decode(self, output: list[F],
               _num_measurements: int) -> list[int]:
        return [x.int() for x in output]

    def test_vec_set_type_param(self, test_vec: dict) -> list[str]:
        test_vec["length"] = self.length
        test_vec["max_weight"] = self.max_weight
        test_vec["chunk_length"] = self.chunk_length
        return ["length", "max_weight", "chunk_length"]
