"""Network-admission layer for the upload front (ISSUE 11): per-IP
token buckets, a connection ceiling, and the body-size gate — the
defenses that must fire BEFORE a request costs the service a decode.

The collector service already defends itself per tenant (quotas,
quarantine, shed policies); this layer defends the *door*: a single
hostile address cannot monopolize the listener's threads or bandwidth,
and every refusal here is reason-coded so it composes with the
service's shed accounting (`CollectorService.shed_external`) instead
of vanishing at the HTTP layer.

Memory is bounded by construction: the per-IP bucket table holds at
most `max_tracked_ips` entries, LRU-evicted (a hostile address stream
recycles bucket slots, never grows the table), and evictions are
counted.  All state mutates under one lock — the HTTP server runs a
thread per connection, so the controller is the one place their
admission decisions serialize.

Levers (env forms in USAGE.md "Network front"): `MASTIC_NET_MAX_BODY`,
`MASTIC_NET_MAX_CONNS`, `MASTIC_NET_RATE`, `MASTIC_NET_BURST`,
`MASTIC_NET_TRUST_FORWARDED`, `MASTIC_NET_MAX_TRACKED_IPS`,
`MASTIC_NET_IO_TIMEOUT`, `MASTIC_NET_IDLE_TIMEOUT`.
"""

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass

from ..drivers.session import _env_float, _env_int

# Reason codes the admission layer sheds with (they land in
# ServiceCounters.shed_reasons next to the service's own policies).
REASON_RATE_LIMITED = "rate-limited"
REASON_CONNS_EXHAUSTED = "connections-exhausted"
REASON_BODY_TOO_LARGE = "body-too-large"
REASON_INCOMPLETE_BODY = "incomplete-body"
REASON_IDLE_TIMEOUT = "idle-timeout"


def _env_bool(name: str, default: bool) -> bool:
    import os

    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    return raw.strip() not in ("0", "false", "no")


@dataclass
class NetConfig:
    """Upload-front levers.  `rate`/`burst` are per client address:
    sustained uploads/s and bucket depth (rate 0 disables the bucket
    — admission is then bounded only by connections and the service's
    own quotas).  `trust_forwarded` honors X-Forwarded-For as the
    client address — ONLY for deployments behind a trusted proxy (and
    for the load generator, which simulates 10^5 client addresses
    through loopback)."""

    max_body: int = 1 << 20        # bytes; PUT bodies past it -> 413
    max_connections: int = 64      # concurrent requests being served
    rate: float = 0.0              # per-IP uploads/s (0 = unlimited)
    burst: float = 32.0            # per-IP bucket depth
    trust_forwarded: bool = False  # X-Forwarded-For as client addr
    max_tracked_ips: int = 4096    # bucket-table bound (LRU evicted)
    io_timeout: float = 30.0       # per-socket read/write deadline
    idle_timeout: float = 30.0     # whole-request-body deadline: a
    #                                client trickling bytes under the
    #                                per-read io_timeout can no longer
    #                                hold a connection slot forever —
    #                                past this budget it sheds
    #                                reason-coded `idle-timeout`

    def __post_init__(self):
        if self.max_body < 1:
            raise ValueError("max_body must be >= 1")
        if self.max_connections < 1:
            raise ValueError("max_connections must be >= 1")
        if self.max_tracked_ips < 1:
            raise ValueError("max_tracked_ips must be >= 1")
        if self.rate < 0 or self.burst <= 0:
            raise ValueError("rate must be >= 0 and burst > 0")
        if self.idle_timeout <= 0:
            raise ValueError("idle_timeout must be > 0")

    @classmethod
    def from_env(cls) -> "NetConfig":
        return cls(
            max_body=_env_int("MASTIC_NET_MAX_BODY", 1 << 20),
            max_connections=_env_int("MASTIC_NET_MAX_CONNS", 64),
            rate=_env_float("MASTIC_NET_RATE", 0.0),
            burst=_env_float("MASTIC_NET_BURST", 32.0),
            trust_forwarded=_env_bool("MASTIC_NET_TRUST_FORWARDED",
                                      False),
            max_tracked_ips=_env_int("MASTIC_NET_MAX_TRACKED_IPS",
                                     4096),
            io_timeout=_env_float("MASTIC_NET_IO_TIMEOUT", 30.0),
            idle_timeout=_env_float("MASTIC_NET_IDLE_TIMEOUT", 30.0),
        )


class AdmissionController:
    """The door's shared state: one instance per upload front, called
    from every handler thread.  `clock` is injectable so the bucket
    math is unit-testable without sleeping."""

    def __init__(self, config: NetConfig, clock=time.monotonic):
        # Attr named `cfg`, not `config`: the CC001 pass matches
        # shared state by attribute name, and `config` aliases
        # jax.config writes in the drivers' main paths.
        self.cfg = config
        self._clock = clock
        self._mu = threading.Lock()
        # ip -> [tokens, last refill time]; ordered for LRU eviction.
        self._buckets: OrderedDict = OrderedDict()
        self.evictions = 0
        self._active = 0

    # -- connection ceiling ----------------------------------------

    def try_acquire_connection(self) -> bool:
        """One request wants serving; False past the ceiling (the
        caller answers 503 + Retry-After, counted)."""
        with self._mu:
            if self._active >= self.cfg.max_connections:
                return False
            self._active += 1
            return True

    def release_connection(self) -> None:
        with self._mu:
            self._active = max(0, self._active - 1)

    def active_connections(self) -> int:
        with self._mu:
            return self._active

    # -- per-IP token bucket ---------------------------------------

    def admit(self, ip: str) -> tuple:
        """Spend one token for `ip`.  Returns (admitted, retry_after
        seconds — 0.0 when admitted).  Bucket table is LRU-bounded;
        an evicted address starts over with a full bucket (generous
        to the reborn, bounded for everyone)."""
        cfg = self.cfg
        if cfg.rate <= 0:
            return (True, 0.0)
        now = self._clock()
        with self._mu:
            slot = self._buckets.get(ip)
            if slot is None:
                if len(self._buckets) >= cfg.max_tracked_ips:
                    self._buckets.popitem(last=False)
                    self.evictions += 1
                slot = [cfg.burst, now]
                self._buckets[ip] = slot
            else:
                self._buckets.move_to_end(ip)
            (tokens, last) = slot
            tokens = min(cfg.burst, tokens + (now - last) * cfg.rate)
            if tokens >= 1.0:
                slot[0] = tokens - 1.0
                slot[1] = now
                return (True, 0.0)
            slot[0] = tokens
            slot[1] = now
            return (False, (1.0 - tokens) / cfg.rate)

    def tracked_ips(self) -> int:
        with self._mu:
            return len(self._buckets)
