"""Differential tests: batched JAX VIDPF vs the scalar oracle.

The scalar layer is conformance-locked against the reference vectors,
so byte-equality here extends that lock to the batched backend.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow

from mastic_tpu.backend import BatchedVidpf, LevelSchedule
from mastic_tpu.backend.vidpf_jax import pack_path_bits
from mastic_tpu.common import pack_bits
from mastic_tpu.field import Field64, Field128
from mastic_tpu.vidpf import Vidpf

CTX = b"backend differential test"


def _rand_u8(rng, shape):
    return rng.integers(0, 256, shape, dtype=np.uint8)


def _setup(field, bits, value_len, num_reports, seed):
    rng = np.random.default_rng(seed)
    scalar = Vidpf(field, bits, value_len)
    batched = BatchedVidpf(field, bits, value_len)
    alphas = rng.integers(0, 2, (num_reports, bits)).astype(bool)
    betas_scalar = [
        [field(int(x)) for x in rng.integers(0, 1000, value_len)]
        for _ in range(num_reports)
    ]
    nonces = _rand_u8(rng, (num_reports, 16))
    rand = _rand_u8(rng, (num_reports, 32))
    return (scalar, batched, alphas, betas_scalar, nonces, rand)


def _batched_gen(batched, alphas, betas_scalar, nonces, rand):
    betas = np.stack([
        np.stack([batched.spec.int_to_limbs(x.int()) for x in beta])
        for beta in betas_scalar
    ])
    gen = jax.jit(lambda a, b, n, r: batched.gen(a, b, CTX, n, r))
    return gen(jnp.asarray(alphas), jnp.asarray(betas),
               jnp.asarray(nonces), jnp.asarray(rand))


@pytest.mark.parametrize("field,bits,value_len",
                         [(Field64, 4, 2), (Field128, 3, 3)])
def test_gen_matches_scalar(field, bits, value_len):
    (scalar, batched, alphas, betas_scalar, nonces, rand) = _setup(
        field, bits, value_len, num_reports=3, seed=7)
    (cws, keys, ok) = _batched_gen(batched, alphas, betas_scalar, nonces,
                                   rand)
    assert bool(np.all(ok))

    for r in range(alphas.shape[0]):
        alpha = tuple(bool(b) for b in alphas[r])
        (cws_ref, keys_ref) = scalar.gen(
            alpha, betas_scalar[r], CTX, nonces[r].tobytes(),
            rand[r].tobytes())
        assert np.asarray(keys[r, 0]).tobytes() == keys_ref[0]
        assert np.asarray(keys[r, 1]).tobytes() == keys_ref[1]
        got = batched.cws_to_host(cws, r)
        for (d, (g, e)) in enumerate(zip(got, cws_ref)):
            assert g[0] == e[0], f"seed cw, report {r} level {d}"
            assert g[1] == e[1], f"ctrl cw, report {r} level {d}"
            assert [x.int() for x in g[2]] == [x.int() for x in e[2]], \
                f"payload cw, report {r} level {d}"
            assert g[3] == e[3], f"proof cw, report {r} level {d}"


@pytest.mark.parametrize("field,bits,value_len,level",
                         [(Field64, 4, 2, 2), (Field64, 4, 2, 3),
                          (Field128, 3, 3, 1)])
def test_eval_matches_scalar(field, bits, value_len, level):
    (scalar, batched, alphas, betas_scalar, nonces, rand) = _setup(
        field, bits, value_len, num_reports=2, seed=11)
    (cws, keys, _) = _batched_gen(batched, alphas, betas_scalar, nonces,
                                  rand)

    # A prefix set mixing on-path and off-path nodes, deliberately not
    # in sorted order (the out gather must follow the caller's order).
    all_prefixes = scalar.prefixes_for_level(level)
    prefixes = list(all_prefixes[::-1][:3])
    sched = LevelSchedule(prefixes, level, bits)

    for agg_id in range(2):
        eval_fn = jax.jit(lambda c, k, n, a=agg_id: batched.eval_full(
            a, c, k, sched, CTX, n))
        (levels, out_w, ok) = eval_fn(cws, keys[:, agg_id],
                                      jnp.asarray(nonces))
        assert bool(np.all(ok))

        for r in range(alphas.shape[0]):
            cws_ref = batched.cws_to_host(cws, r)
            key = np.asarray(keys[r, agg_id]).tobytes()
            (out_ref, tree_ref) = scalar.eval_level_synchronous(
                agg_id, cws_ref, key, level, prefixes, CTX,
                nonces[r].tobytes())
            # Per-prefix output shares (incl. aggregator-1 negation).
            got_out = batched.w_to_host(out_w[r])
            for (p, (g, e)) in enumerate(zip(got_out, out_ref)):
                assert [x.int() for x in g] == [x.int() for x in e], \
                    f"out share agg {agg_id} report {r} prefix {p}"
            # Every materialized node: seed, ctrl, payload, proof.
            for (d, nodes_ref) in enumerate(tree_ref.levels):
                paths = sorted(nodes_ref)
                st = levels[d]
                for (j, path) in enumerate(paths):
                    node = nodes_ref[path]
                    assert np.asarray(
                        st.seed[r, j]).tobytes() == node.seed
                    assert bool(st.ctrl[r, j]) == node.ctrl
                    got_w = batched.w_to_host(st.w[r, j])
                    assert [x.int() for x in got_w] == \
                        [x.int() for x in node.w]
                    assert np.asarray(
                        st.proof[r, j]).tobytes() == node.proof


def test_beta_share_matches_scalar():
    (field, bits, value_len) = (Field64, 3, 4)
    (scalar, batched, alphas, betas_scalar, nonces, rand) = _setup(
        field, bits, value_len, num_reports=2, seed=13)
    (cws, keys, _) = _batched_gen(batched, alphas, betas_scalar, nonces,
                                  rand)
    for agg_id in range(2):
        beta_fn = jax.jit(lambda c, k, n, a=agg_id:
                          batched.get_beta_share(a, c, k, CTX, n))
        (share, ok) = beta_fn(cws, keys[:, agg_id], jnp.asarray(nonces))
        assert bool(np.all(ok))
        for r in range(alphas.shape[0]):
            cws_ref = batched.cws_to_host(cws, r)
            key = np.asarray(keys[r, agg_id]).tobytes()
            expect = scalar.get_beta_share(agg_id, cws_ref, key, CTX,
                                           nonces[r].tobytes())
            got = batched.w_to_host(share[r])
            assert [x.int() for x in got] == [x.int() for x in expect]


def test_pack_path_bits_matches_host():
    rng = np.random.default_rng(3)
    for length in (1, 5, 8, 13, 16):
        bits = rng.integers(0, 2, (4, length)).astype(bool)
        got = np.asarray(pack_path_bits(jnp.asarray(bits)))
        for r in range(4):
            assert got[r].tobytes() == pack_bits(list(bits[r]))


def test_level_core_plane_path_matches_byte_path():
    """The bitsliced plane-domain level core (R >= 32) against the
    byte path on identical inputs, including correction selects and
    the rejection mask."""
    import jax.numpy as jnp
    import numpy as np

    from mastic_tpu.backend.vidpf_jax import BatchedVidpf, EvalState
    from mastic_tpu.field import Field64

    vid = BatchedVidpf(Field64, 8, 2)
    rng = np.random.default_rng(9)
    (r, n) = (64, 3)
    nonces = jnp.asarray(rng.integers(0, 256, (r, 16), np.uint8))
    (ext_rk, conv_rk) = vid.roundkeys(b"plane test", nonces)
    parents = EvalState(
        seed=jnp.asarray(rng.integers(0, 256, (r, n, 16), np.uint8)),
        ctrl=jnp.asarray(rng.integers(0, 2, (r, n)).astype(bool)),
        w=jnp.zeros((r, n, 2, 4), jnp.uint32),
        proof=jnp.zeros((r, n, 32), jnp.uint8))
    cw = (jnp.asarray(rng.integers(0, 256, (r, 16), np.uint8)),
          jnp.asarray(rng.integers(0, 2, (r, 2)).astype(bool)),
          jnp.asarray(rng.integers(0, 1 << 16, (r, 2, 4),
                                   dtype=np.uint32)),
          jnp.asarray(rng.integers(0, 256, (r, 32), np.uint8)))

    (ps, pt, pw, pok) = vid._level_core_planes(ext_rk, conv_rk,
                                               parents, cw)
    # Byte path: slice per-report batches below the plane threshold.
    for lo in (0, 32):
        sub = EvalState(seed=parents.seed[lo:lo + 16],
                        ctrl=parents.ctrl[lo:lo + 16],
                        w=parents.w[lo:lo + 16],
                        proof=parents.proof[lo:lo + 16])
        sub_cw = tuple(x[lo:lo + 16] for x in cw)
        (bs, bt, bw, bok) = vid.level_core(ext_rk[lo:lo + 16],
                                           conv_rk[lo:lo + 16],
                                           sub, sub_cw)
        s = slice(lo, lo + 16)
        assert (np.asarray(ps[s]) == np.asarray(bs)).all()
        assert (np.asarray(pt[s]) == np.asarray(bt)).all()
        assert (np.asarray(pw[s]) == np.asarray(bw)).all()
        assert (np.asarray(pok[s]) == np.asarray(bok)).all()
