"""Differential tests: batched Keccak/TurboSHAKE128 vs scalar reference."""

import numpy as np

from mastic_tpu.keccak import turbo_shake128
from mastic_tpu.ops.keccak_jax import turbo_shake128 as ts_jax


def test_turbo_shake128_matches_scalar():
    rng = np.random.default_rng(0)
    # Lengths straddling the 168-byte rate boundary, both domains used
    # by the VDAF XOFs, single- and multi-block squeezes.
    cases = [
        (0, 1, 16), (1, 2, 32), (42, 1, 32), (167, 1, 168),
        (168, 2, 169), (169, 1, 16), (336, 2, 32), (901, 1, 345),
    ]
    for (msg_len, domain, out_len) in cases:
        batch = rng.integers(0, 256, size=(3, msg_len), dtype=np.uint8)
        got = np.asarray(ts_jax(batch, domain, out_len))
        for b in range(batch.shape[0]):
            want = turbo_shake128(bytes(batch[b]), domain, out_len)
            assert bytes(got[b]) == want, (msg_len, domain, out_len, b)


def test_turbo_shake128_nd_batch():
    rng = np.random.default_rng(1)
    batch = rng.integers(0, 256, size=(2, 3, 50), dtype=np.uint8)
    got = np.asarray(ts_jax(batch, 1, 32))
    assert got.shape == (2, 3, 32)
    for i in range(2):
        for j in range(3):
            assert bytes(got[i, j]) == turbo_shake128(bytes(batch[i, j]), 1, 32)


import pytest  # noqa: E402  (module tail: only the pallas test below)


@pytest.mark.slow
@pytest.mark.parametrize("flat", [5, 600])
def test_keccak_pallas_call_plumbing(flat):
    """The pallas_call plumbing (lane-major transpose, padding, grid —
    incl. a batch whose lane-padded size is not a _BLOCK_B multiple)
    is bit-exact vs the scan path for a single round in interpret
    mode.  The round math itself is the scan path's _keccak_round,
    shared by construction; a full 12-round unrolled kernel takes
    minutes of interpret compile on the CPU fabric, so one round
    suffices here."""
    pytest.importorskip("jax.experimental.pallas")
    import jax.numpy as jnp

    from mastic_tpu.ops.keccak_jax import keccak_p1600
    from mastic_tpu.ops.keccak_pallas import keccak_p1600_pallas

    rng = np.random.default_rng(3)
    lo = jnp.asarray(rng.integers(0, 1 << 32, (flat, 25),
                                  dtype=np.uint32))
    hi = jnp.asarray(rng.integers(0, 1 << 32, (flat, 25),
                                  dtype=np.uint32))
    (alo, ahi) = keccak_p1600(lo, hi, 1)
    (blo, bhi) = keccak_p1600_pallas(lo, hi, 1, interpret=True)
    np.testing.assert_array_equal(np.asarray(alo), np.asarray(blo))
    np.testing.assert_array_equal(np.asarray(ahi), np.asarray(bhi))


@pytest.mark.slow
def test_keccak_pallas_chained_rounds_match_scan():
    """All 12 rounds through the pallas boundary, one single-round
    kernel per round (round_range pins each round's constant), must
    equal the 12-round scan path.  This validates the multi-round
    state handoff and the ROUND_CONSTANTS start offset that the
    single kernel's unrolled form bakes in — without the >1 h
    interpret compile of that form (VERDICT r4 ask #5)."""
    pytest.importorskip("jax.experimental.pallas")
    import jax.numpy as jnp

    from mastic_tpu.ops.keccak_jax import keccak_p1600
    from mastic_tpu.ops.keccak_pallas import keccak_p1600_pallas

    rng = np.random.default_rng(5)
    lo = jnp.asarray(rng.integers(0, 1 << 32, (7, 25), dtype=np.uint32))
    hi = jnp.asarray(rng.integers(0, 1 << 32, (7, 25), dtype=np.uint32))
    (want_lo, want_hi) = keccak_p1600(lo, hi, 12)
    (got_lo, got_hi) = (lo, hi)
    for r in range(12, 24):
        (got_lo, got_hi) = keccak_p1600_pallas(
            got_lo, got_hi, interpret=True, round_range=(r, r + 1))
    np.testing.assert_array_equal(np.asarray(want_lo),
                                  np.asarray(got_lo))
    np.testing.assert_array_equal(np.asarray(want_hi),
                                  np.asarray(got_hi))
