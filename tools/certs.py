#!/usr/bin/env python3
"""Mint the mutual-TLS credential set for a party deployment
(ISSUE 14; USAGE.md "Transport security").

One self-signed CA plus one leaf certificate per party (leader,
helper, collector), each with its party name as CN and DNS SAN — the
name `net.transport.TlsConfig` pins at handshake time on BOTH ends
(server verifies the dialing client's cert name, client verifies the
listener's), so a credential minted for one role cannot impersonate
another even inside the same CA.

Everything shells out to the `openssl` CLI (the only X.509 tool in
this image — there is no `cryptography` wheel); private keys are
written by openssl straight to disk with 0600 permissions and never
pass through this process's memory, so there is no key material for
the SF004 egress rule to even see.  EC P-256 keys keep minting fast
enough to run per-test.

CLI:

    python tools/certs.py --out DIR [--days N] [--parties a,b,c]
                          [--expired NAME] [--ca-name CN]

writes DIR/ca.pem, DIR/ca.key and DIR/<party>.pem/<party>.key per
party.  `--expired NAME` additionally mints <NAME>-expired.pem (same
key, validity already over) for the negative-path test matrix.
"""

import argparse
import os
import pathlib
import subprocess
import sys
import tempfile

DEFAULT_PARTIES = ("leader", "helper", "collector")
CURVE = "prime256v1"


def _run(cmd: list) -> None:
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"openssl failed ({' '.join(cmd[:3])}...): "
            f"{proc.stderr.strip()[-500:]}")


def _genkey(path: pathlib.Path) -> None:
    _run(["openssl", "ecparam", "-name", CURVE, "-genkey", "-noout",
          "-out", str(path)])
    os.chmod(path, 0o600)


def mint_ca(out: pathlib.Path, ca_name: str = "mastic-ca",
            days: int = 365) -> None:
    """Self-signed CA keypair at out/ca.{key,pem}."""
    out.mkdir(parents=True, exist_ok=True)
    _genkey(out / "ca.key")
    _run(["openssl", "req", "-x509", "-new", "-key",
          str(out / "ca.key"), "-subj", f"/CN={ca_name}", "-days",
          str(days), "-sha256", "-out", str(out / "ca.pem")])


def mint_party(out: pathlib.Path, name: str, days: int = 365,
               suffix: str = "") -> None:
    """One leaf cert for `name`, signed by out/ca.*, SAN DNS:name.
    `days` may be negative: the validity window is already over (the
    expired-cert refusal fixture).  `suffix` renames the output pair
    (<name><suffix>.pem) without changing the certified name."""
    stem = f"{name}{suffix}"
    key = out / f"{stem}.key"
    _genkey(key)
    with tempfile.TemporaryDirectory() as tmp:
        csr = pathlib.Path(tmp) / "leaf.csr"
        ext = pathlib.Path(tmp) / "leaf.ext"
        ext.write_text(f"subjectAltName=DNS:{name}\n")
        _run(["openssl", "req", "-new", "-key", str(key), "-subj",
              f"/CN={name}", "-out", str(csr)])
        _run(["openssl", "x509", "-req", "-in", str(csr), "-CA",
              str(out / "ca.pem"), "-CAkey", str(out / "ca.key"),
              "-CAcreateserial", "-days", str(days), "-sha256",
              "-extfile", str(ext), "-out", str(out / f"{stem}.pem")])


def mint_party_set(out, parties: tuple = DEFAULT_PARTIES,
                   days: int = 365) -> pathlib.Path:
    """CA + one leaf per party; returns the directory path.  The
    one-call form the chaos drill and the test fixtures use."""
    out = pathlib.Path(out)
    mint_ca(out, days=days)
    for name in parties:
        mint_party(out, name, days=days)
    return out


def main() -> int:
    parser = argparse.ArgumentParser(
        description="mint the mTLS CA + per-party certs "
                    "(USAGE.md 'Transport security')")
    parser.add_argument("--out", required=True,
                        help="output directory for ca.* and the "
                             "per-party pairs")
    parser.add_argument("--days", type=int, default=365)
    parser.add_argument("--parties", type=str,
                        default=",".join(DEFAULT_PARTIES),
                        help="comma-separated party names "
                             "(default leader,helper,collector)")
    parser.add_argument("--expired", type=str, default=None,
                        help="additionally mint NAME-expired.pem "
                             "(validity already over) for refusal "
                             "testing")
    parser.add_argument("--ca-name", type=str, default="mastic-ca")
    args = parser.parse_args()

    out = pathlib.Path(args.out)
    parties = tuple(p.strip() for p in args.parties.split(",")
                    if p.strip())
    mint_ca(out, ca_name=args.ca_name, days=args.days)
    for name in parties:
        mint_party(out, name, days=args.days)
    if args.expired:
        mint_party(out, args.expired, days=-1, suffix="-expired")
    print(f"certs: CA + {len(parties)} part"
          f"{'ies' if len(parties) != 1 else 'y'}"
          + (f" + {args.expired}-expired" if args.expired else "")
          + f" -> {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
