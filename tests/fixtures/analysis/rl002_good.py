"""RL002 clean: every non-exceptional path closes the socket."""
import socket


def probe(host, port, want):
    sock = socket.create_connection((host, port))
    if not want:
        sock.close()
        return None
    sock.close()
    return True
