"""Known-good twin of sf004_key_bad: only the PATHS of credential
files cross (the tools/party.py CLI stance) — the key bytes never
enter this process at all, so there is nothing to leak."""


def ship_credential_paths(sock, cert_path: str, key_path: str):
    del key_path   # stays local: the ssl context reads it from disk
    sock.sendall(cert_path.encode())
