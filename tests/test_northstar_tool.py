"""tools/northstar.py CLI safety (ADVICE r5 regressions): argument
validation at parse time and the resume shard-parameter binding."""

import importlib.util
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NORTHSTAR = os.path.join(REPO, "tools", "northstar.py")


def _load_tool():
    spec = importlib.util.spec_from_file_location("northstar_tool",
                                                  NORTHSTAR)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_checkpoint_every_rejected_at_parse_time():
    """--checkpoint-every < 1 must die in argument parsing (exit 2,
    before any JAX import or sharding work), not as a
    ZeroDivisionError after the first completed level."""
    for bad in ("0", "-3"):
        proc = subprocess.run(
            [sys.executable, NORTHSTAR, "--checkpoint-every", bad],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 2, proc.stderr
        assert "--checkpoint-every" in proc.stderr
        assert "ZeroDivision" not in proc.stderr


def test_checkpoint_header_roundtrip():
    tool = _load_tool()
    vk = bytes(range(32))
    params = {"inst": "count", "reports": 100, "bits": 16, "seed": 7,
              "planted": 3, "max_weight": 7, "tail_weight": 1}
    blob = b"run state bytes"
    raw = tool.write_checkpoint_bytes(vk, params, blob)
    (vk2, params2, blob2) = tool.read_checkpoint_bytes(raw)
    assert (vk2, params2, blob2) == (vk, params, blob)
    assert tool.verify_shard_params(params2, params) == []


def test_checkpoint_header_mismatch_detected():
    """A resume with different shard parameters must be detectable
    immediately — each differing key named (the old format silently
    continued carried state over mismatched reports and only failed
    at the end of the full remaining wall time)."""
    tool = _load_tool()
    saved = {"inst": "count", "reports": 100, "bits": 16, "seed": 7,
             "planted": 3, "max_weight": 7, "tail_weight": 1}
    current = dict(saved, seed=8, planted=2)
    assert tool.verify_shard_params(saved, current) == \
        ["planted", "seed"]


def test_checkpoint_old_format_refused():
    """A pre-header checkpoint (vk + blob only) must fail with a
    descriptive error, not be misread as carried state."""
    tool = _load_tool()
    vk = bytes(range(32))
    raw = len(vk).to_bytes(2, "little") + vk + b"\x00" * 64
    with pytest.raises(ValueError, match="header"):
        tool.read_checkpoint_bytes(raw)


def test_resume_param_mismatch_exits_before_rounds(tmp_path):
    """End to end through the CLI: write a checkpoint at one --seed,
    resume at another — the process must refuse at startup (exit 2,
    naming the parameter), never reaching the aggregation rounds."""
    tool = _load_tool()
    ck = tmp_path / "run.ck"
    params = {"inst": "count", "reports": 64, "bits": 4, "seed": 1,
              "planted": 2, "max_weight": 7, "tail_weight": 1}
    ck.write_bytes(tool.write_checkpoint_bytes(
        bytes(range(32)), params, b""))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, NORTHSTAR, "--reports", "64", "--bits", "4",
         "--planted", "2", "--seed", "2", "--checkpoint", str(ck),
         "--resume"],
        capture_output=True, text=True, timeout=570, env=env)
    assert proc.returncode == 2, (proc.stdout, proc.stderr)
    assert "seed" in proc.stderr and "--resume refused" in proc.stderr
