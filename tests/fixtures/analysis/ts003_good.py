"""Known-good: numpy only on host constants and shapes (TS003)."""

import jax
import jax.numpy as jnp
import numpy as np


def scaled(x: jax.Array) -> jax.Array:
    weights = np.arange(4, dtype=np.uint32)
    n = int(np.prod(x.shape))
    return x * jnp.asarray(weights) * n
