"""Wire-codec round trips and canonicality checks."""

import pytest

from mastic_tpu import MasticCount, MasticHistogram
from mastic_tpu.common import gen_rand


def test_public_share_round_trip():
    for mastic in (MasticCount(7), MasticHistogram(3, 4, 2)):
        vidpf = mastic.vidpf
        alpha = vidpf.test_index_from_int(5, vidpf.BITS)
        beta = [vidpf.field(i + 1) for i in range(vidpf.VALUE_LEN)]
        (cw, _keys) = vidpf.gen(alpha, beta, b"ctx", gen_rand(16),
                                gen_rand(vidpf.RAND_SIZE))
        encoded = vidpf.encode_public_share(cw)
        decoded = vidpf.decode_public_share(encoded)
        assert vidpf.encode_public_share(decoded) == encoded
        for (got, want) in zip(decoded, cw):
            assert got[0] == want[0]
            assert list(got[1]) == list(want[1])
            assert got[2] == want[2]
            assert got[3] == want[3]

        with pytest.raises(ValueError):
            vidpf.decode_public_share(encoded + b"\x00")


def test_agg_param_round_trip_and_canonicality():
    mastic = MasticCount(4)
    agg_param = (1, tuple(mastic.vidpf.test_index_from_int(v, 2)
                          for v in range(3)), True)
    encoded = mastic.encode_agg_param(agg_param)
    assert mastic.decode_agg_param(encoded) == agg_param

    # Nonzero padding bits in a prefix chunk must be rejected: the
    # encoding is injective on the wire (decode o encode = id).
    tampered = bytearray(encoded)
    tampered[6] |= 0x01  # low bit of the 2-bit prefix byte is padding
    with pytest.raises(ValueError):
        mastic.decode_agg_param(bytes(tampered))


def test_agg_param_level_zero():
    mastic = MasticCount(4)
    agg_param = (0, ((False,), (True,)), True)
    encoded = mastic.encode_agg_param(agg_param)
    assert mastic.decode_agg_param(encoded) == agg_param


# -- negative-path sweep: every decoder refuses malformed input ------
#
# Truncated, oversized, and bit-flipped inputs must raise ValueError /
# EOFError with a message naming the channel — never a raw
# struct.error or a numpy reshape traceback (ISSUE 3 satellite).

def _decoders(mastic):
    """(channel name, decoder over bytes, one honest encoding)."""
    from mastic_tpu import wire
    from mastic_tpu.common import gen_rand
    from mastic_tpu.testvec_codec import (encode_agg_share,
                                          encode_input_share,
                                          encode_prep_share)

    ctx = b"negative path"
    bits = mastic.vidpf.BITS
    alpha = tuple(bool(i & 1) for i in range(bits))
    weight = 1   # valid for Count (bool) and Histogram (bucket < 4)
    nonce = gen_rand(mastic.NONCE_SIZE)
    (ps, shares) = mastic.shard(ctx, (alpha, weight), nonce,
                                gen_rand(mastic.RAND_SIZE))
    level = bits - 1
    agg_param = (level, (alpha,), True)
    verify_key = gen_rand(mastic.VERIFY_KEY_SIZE)
    prep_states = []
    prep_shares = []
    for agg_id in range(2):
        (state, share) = mastic.prep_init(verify_key, ctx, agg_id,
                                          agg_param, nonce, ps,
                                          shares[agg_id])
        prep_states.append(state)
        prep_shares.append(share)
    prep_msg = mastic.prep_shares_to_prep(ctx, agg_param, prep_shares)
    out = mastic.prep_next(ctx, prep_states[0], prep_msg)
    agg = mastic.agg_update(agg_param, mastic.agg_init(agg_param), out)

    return [
        ("report",
         lambda b: wire.decode_report(mastic, 0, b),
         wire.encode_report(mastic, 0, nonce, ps, shares[0])),
        ("input share",
         lambda b: wire.decode_input_share(mastic, 1, b),
         encode_input_share(mastic, shares[1])),
        ("prep share",
         lambda b: wire.decode_prep_share(mastic, agg_param, b),
         encode_prep_share(mastic, prep_shares[0])),
        ("prep message",
         lambda b: wire.decode_prep_msg(mastic, agg_param, b),
         prep_msg or b""),
        ("aggregate share",
         lambda b: wire.decode_agg_share(mastic, agg_param, b),
         encode_agg_share(mastic, agg)),
        ("public share",
         lambda b: mastic.vidpf.decode_public_share(b),
         mastic.vidpf.encode_public_share(ps)),
    ]


@pytest.mark.parametrize("mastic", [MasticCount(2),
                                    MasticHistogram(2, 4, 2)],
                         ids=["Count", "Histogram-jointrand"])
def test_decoders_reject_malformed(mastic):
    for (name, decode, honest) in _decoders(mastic):
        decode(honest)  # sanity: the honest encoding decodes
        # Truncated and oversized inputs are always refused.
        for bad in (honest[:-1], honest + b"\x00", b""):
            if bad == honest:
                continue  # Count's prep message is legally empty
            with pytest.raises((ValueError, EOFError)):
                decode(bad)
        # Bit-flips either decode (the flip lands in free bytes) or
        # refuse with ValueError/EOFError — never a struct.error or
        # numpy traceback.  Sweep a byte in each region of the blob.
        for pos in {0, len(honest) // 3, len(honest) // 2,
                    2 * len(honest) // 3, len(honest) - 1}:
            if pos < 0 or pos >= len(honest):
                continue  # the empty prep message has no bytes to flip
            flipped = (honest[:pos]
                       + bytes([honest[pos] ^ 0x80])
                       + honest[pos + 1:])
            try:
                decode(flipped)
            except (ValueError, EOFError):
                pass  # refusal is fine; any other exception fails


def test_decoders_name_the_channel():
    from mastic_tpu import wire

    mastic = MasticCount(2)
    agg_param = (1, ((False, True),), True)
    cases = [
        ("report", lambda: wire.decode_report(mastic, 0, b"\x00" * 7)),
        ("input share",
         lambda: wire.decode_input_share(mastic, 0, b"\x00" * 7)),
        ("prep share",
         lambda: wire.decode_prep_share(mastic, agg_param,
                                        b"\x00" * 7)),
        ("prep message",
         lambda: wire.decode_prep_msg(mastic, agg_param, b"\x00" * 7)),
        ("aggregate share",
         lambda: wire.decode_agg_share(mastic, agg_param,
                                       b"\x00" * 7)),
    ]
    for (name, call) in cases:
        with pytest.raises(ValueError, match=name.split()[0]):
            call()
    # Out-of-range field elements are named too, not raw tracebacks.
    size = wire.agg_share_size(mastic, agg_param)
    with pytest.raises(ValueError, match="aggregate share"):
        wire.decode_agg_share(mastic, agg_param, b"\xff" * size)


def test_unframe_rejects_truncation():
    from mastic_tpu import wire

    framed = wire.frame(b"payload")
    assert wire.unframe(framed) == (b"payload", b"")
    with pytest.raises(ValueError, match="frame"):
        wire.unframe(framed[:3])        # inside the header
    with pytest.raises(ValueError, match="frame"):
        wire.unframe(framed[:-2])       # inside the payload
