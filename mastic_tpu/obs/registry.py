"""Metrics registry (ISSUE 7 tentpole, part 2): named counters,
gauges and histograms with label sets, exported as Prometheus text
and as a JSON snapshot.

This replaces the pattern where every layer kept its own counter
fields (`ServiceCounters`, `RoundMetrics`) with no export path: the
dataclasses stay as the snapshot/serialization ledger, but their
increments now mirror into the one process-wide registry
(`ServiceCounters.inc`, `obs/devtime.observe_round`), so the
`/metrics` endpoint and a `bench.py` run read the same series.

Cardinality is bounded by construction: each metric accepts at most
`max_label_sets` distinct label-value tuples (default 64); past the
cap, new label sets collapse into one reserved
``{"overflow": "true"}`` child and `mastic_obs_label_overflow_total`
counts the collapses — a hostile tenant name stream degrades one
series, never memory.

Every series a shipped code path registers is DECLARED up front in
`DECLARED` below (name -> kind, help, label names); `tools/lint.py`
check 9 enforces that each declared name appears in USAGE.md's
metric table, so the documentation cannot drift from the registry.
Ad-hoc metrics (tests) may be created without declaring.
"""

import json
import threading
from bisect import bisect_left
from typing import Optional, Sequence

DEFAULT_MAX_LABEL_SETS = 64

# Default histogram buckets, in milliseconds: the phase times range
# from sub-ms host folds to multi-minute cold compiles.
DEFAULT_BUCKETS_MS = (1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, 10000.0,
                      30000.0, 60000.0, 120000.0, 300000.0)

_OVERFLOW_LABELS = ("overflow",)
_OVERFLOW_VALUES = ("true",)

# name -> (kind, help, label names).  The shipped series; lint check 9
# keeps each name documented in USAGE.md.
DECLARED = {
    "mastic_reports_admitted_total":
        ("counter", "reports admitted by the collector service",
         ("tenant",)),
    "mastic_reports_quarantined_total":
        ("counter", "reports refused at the door, by reason",
         ("tenant", "reason")),
    "mastic_reports_shed_total":
        ("counter", "reports dropped by backpressure, by reason",
         ("tenant", "reason")),
    "mastic_pages_sealed_total":
        ("counter", "buffer pages sealed behind a digest",
         ("tenant",)),
    "mastic_pages_corrupt_total":
        ("counter", "sealed pages whose digest check failed",
         ("tenant",)),
    "mastic_epochs_total":
        ("counter", "epoch outcomes (completed/truncated/failed/"
         "refused)", ("tenant", "outcome")),
    "mastic_deadline_misses_total":
        ("counter", "epoch deadline expiries", ("tenant",)),
    "mastic_rounds_total":
        ("counter", "aggregation rounds completed", ("tenant",)),
    "mastic_reports_accepted_total":
        ("counter", "per-round accepted reports, summed",
         ("tenant",)),
    "mastic_reports_rejected_total":
        ("counter", "per-round rejected reports, by first failing "
         "check", ("tenant", "check")),
    "mastic_session_retries_total":
        ("counter", "session-layer retries (with_retries)",
         ("tenant",)),
    "mastic_session_timeouts_total":
        ("counter", "session-layer deadline expiries", ("tenant",)),
    "mastic_session_reconnects_total":
        ("counter", "party links redialed and resumed mid-session "
         "(reconnect-and-replay; ReliableChannel)", ("tenant",)),
    "mastic_frames_replayed_total":
        ("counter", "session frames redelivered after a reconnect "
         "(deduped by sequence number on the receiver)", ("tenant",)),
    "mastic_tls_refusals_total":
        ("counter", "mTLS handshakes refused, by reason code and "
         "side (tls-wrong-ca / tls-expired-cert / "
         "tls-hostname-mismatch / tls-plaintext / ...)",
         ("reason", "side")),
    "mastic_faults_injected_total":
        ("counter", "MASTIC_FAULTS rules fired",
         ("action", "step")),
    "mastic_buffered_reports":
        ("gauge", "reports admitted but not yet finished",
         ("tenant",)),
    "mastic_pending_epochs":
        ("gauge", "epochs queued behind the active one", ("tenant",)),
    "mastic_round_wall_ms":
        ("histogram", "wall time of one aggregation round",
         ("tenant",)),
    "mastic_chunk_phase_ms":
        ("histogram", "per-chunk phase wall time (upload/compile/"
         "dispatch/compute_wait/download/host)", ("phase",)),
    "mastic_device_time_ms_total":
        ("counter", "device-time attribution: inline compile wait vs "
         "execute wait, milliseconds", ("kind",)),
    "mastic_sched_overhead_ms_total":
        ("counter", "scheduler overhead on top of raw rounds, "
         "milliseconds", ("tenant",)),
    "mastic_trace_spans_total":
        ("counter", "spans finished by the tracer", ()),
    "mastic_trace_spans_dropped_total":
        ("counter", "spans evicted from the tracer ring", ()),
    "mastic_obs_label_overflow_total":
        ("counter", "label sets collapsed by the cardinality cap",
         ("metric",)),
    "mastic_artifact_loads_total":
        ("counter", "AOT artifact-store load attempts, by gate "
         "outcome (hit/miss/probe_fail/version_skew/corrupt)",
         ("outcome",)),
    "mastic_scheduler_occupancy":
        ("gauge", "staged tenant rounds in flight at the end of the "
         "last scheduler quantum (0 = serial round-robin)", ()),
    "mastic_sched_overlap_efficiency":
        ("gauge", "structural overlap of the last drained scheduler "
         "window: fraction of staged round time hidden behind other "
         "tenants' work (pipeline.overlap_efficiency semantics)", ()),
    "mastic_ingest_queue_depth":
        ("gauge", "uploads waiting in the concurrent ingest front's "
         "bounded queue", ()),
    "mastic_net_http_requests_total":
        ("counter", "upload-front HTTP requests by response code "
         "(mastic_tpu/net/ingest.py)", ("code",)),
    "mastic_net_admission_latency_ms":
        ("histogram", "upload-front request latency: accept to "
         "verdict written, per PUT", ()),
    "mastic_net_active_connections":
        ("gauge", "upload-front requests currently being served "
         "(bounded by MASTIC_NET_MAX_CONNS)", ()),
    "mastic_wal_appends_total":
        ("counter", "admission-WAL records appended and made "
         "durable, by tenant and record kind (report/epoch_cut; "
         "mastic_tpu/drivers/wal.py)", ("tenant", "kind")),
    "mastic_wal_fsync_ms":
        ("histogram", "per-ack durability wait: append start to "
         "fsync-confirmed, milliseconds (group commit batches "
         "these)", ()),
    "mastic_wal_recovered_records_total":
        ("counter", "WAL records handled at recovery, by outcome "
         "(replayed/covered/deduped/torn_tail/corrupt/epoch_cut/"
         "rejected)", ("outcome",)),
    "mastic_wal_segment_bytes":
        ("gauge", "bytes in the WAL's current open segment (resets "
         "on rotation at MASTIC_WAL_SEGMENT_BYTES)", ()),
}


class _Metric:
    """One named metric family: children keyed by label-value
    tuples.  Value shape depends on kind: counters/gauges hold a
    float; histograms hold [bucket counts..., +inf count, sum]."""

    __slots__ = ("name", "kind", "help", "label_names", "buckets",
                 "children", "overflowed")

    def __init__(self, name: str, kind: str, help_text: str,
                 label_names: tuple, buckets: Optional[tuple]):
        self.name = name
        self.kind = kind
        self.help = help_text
        self.label_names = label_names
        self.buckets = buckets
        self.children: dict = {}
        self.overflowed = 0


class _Handle:
    """A (metric, label values) pair the call sites hold; operations
    lock the registry so any thread may record."""

    __slots__ = ("_reg", "_metric", "_values")

    def __init__(self, reg: "MetricsRegistry", metric: _Metric,
                 values: tuple):
        self._reg = reg
        self._metric = metric
        self._values = values

    def inc(self, n: float = 1.0) -> None:
        self._reg._add(self._metric, self._values, n)

    def set(self, value: float) -> None:
        if self._metric.kind != "gauge":
            raise ValueError(
                f"{self._metric.name} is a {self._metric.kind}; only "
                f"gauges support set()")
        self._reg._set(self._metric, self._values, value)

    def set_total(self, value: float) -> None:
        """Publish an externally-accumulated monotone total (the
        ServiceCounters bridge after a snapshot restore): counters
        stay increment-only for call sites, but a resumed ledger must
        re-export its persisted totals."""
        self._reg._set(self._metric, self._values, value)

    def observe(self, value: float) -> None:
        if self._metric.kind != "histogram":
            raise ValueError(
                f"{self._metric.name} is a {self._metric.kind}; only "
                f"histograms support observe()")
        self._reg._observe(self._metric, self._values, value)

    def value(self):
        return self._reg._value(self._metric, self._values)


class MetricsRegistry:
    """The process-wide metric store (singleton via `get_registry`;
    tests build private instances)."""

    def __init__(self, max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        self._lock = threading.Lock()
        self._metrics: dict = {}
        self.max_label_sets = max_label_sets

    # -- creation --------------------------------------------------

    def _get_metric(self, name: str, kind: str, help_text: str,
                    labels: Sequence[str],
                    buckets: Optional[tuple] = None) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                declared = DECLARED.get(name)
                if declared is not None:
                    (kind, help_text, labels) = declared
                m = _Metric(name, kind, help_text or "",
                            tuple(labels),
                            (tuple(buckets or DEFAULT_BUCKETS_MS)
                             if kind == "histogram" else None))
                self._metrics[name] = m
            if m.kind != kind:
                raise ValueError(
                    f"metric {name} already registered as {m.kind}, "
                    f"requested {kind}")
            return m

    def _handle(self, m: _Metric, label_values: dict) -> _Handle:
        extra = set(label_values) - set(m.label_names)
        if extra:
            raise ValueError(
                f"metric {m.name} has labels {m.label_names}; "
                f"unexpected {sorted(extra)}")
        values = tuple(str(label_values.get(ln, ""))
                       for ln in m.label_names)
        return _Handle(self, m, values)

    def counter(self, name: str, help_text: str = "",
                **labels) -> _Handle:
        return self._handle(
            self._get_metric(name, "counter", help_text,
                             tuple(labels)), labels)

    def gauge(self, name: str, help_text: str = "",
              **labels) -> _Handle:
        return self._handle(
            self._get_metric(name, "gauge", help_text,
                             tuple(labels)), labels)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Optional[Sequence[float]] = None,
                  **labels) -> _Handle:
        return self._handle(
            self._get_metric(name, "histogram", help_text,
                             tuple(labels),
                             tuple(buckets) if buckets else None),
            labels)

    # -- the cardinality cap ---------------------------------------

    def _child(self, m: _Metric, values: tuple):
        """The child slot for a label-value tuple, collapsing to the
        overflow child past the cap."""
        child = m.children.get(values)
        if child is not None:
            return values
        if len(m.children) >= self.max_label_sets:
            m.overflowed += 1
            over_name = "mastic_obs_label_overflow_total"
            if m.name != over_name:
                over = self._metrics.get(over_name)
                if over is None:
                    (kind, help_text, labels) = DECLARED[over_name]
                    over = _Metric(over_name, kind, help_text,
                                   labels, None)
                    self._metrics[over_name] = over
                slot = over.children.setdefault((m.name,), [0.0])
                slot[0] += 1
            return _OVERFLOW_VALUES
        if m.kind == "histogram":
            m.children[values] = [0] * (len(m.buckets) + 1) + [0.0]
        else:
            m.children[values] = [0.0]
        return values

    def _ensure_overflow_child(self, m: _Metric) -> None:
        if _OVERFLOW_VALUES not in m.children:
            if m.kind == "histogram":
                m.children[_OVERFLOW_VALUES] = \
                    [0] * (len(m.buckets) + 1) + [0.0]
            else:
                m.children[_OVERFLOW_VALUES] = [0.0]

    # -- recording -------------------------------------------------

    def _add(self, m: _Metric, values: tuple, n: float) -> None:
        with self._lock:
            key = self._child(m, values)
            if key is _OVERFLOW_VALUES:
                self._ensure_overflow_child(m)
            m.children[key][-1] += n

    def _set(self, m: _Metric, values: tuple, value: float) -> None:
        with self._lock:
            key = self._child(m, values)
            if key is _OVERFLOW_VALUES:
                self._ensure_overflow_child(m)
            m.children[key][-1] = value

    def _observe(self, m: _Metric, values: tuple,
                 value: float) -> None:
        with self._lock:
            key = self._child(m, values)
            if key is _OVERFLOW_VALUES:
                self._ensure_overflow_child(m)
            child = m.children[key]
            idx = bisect_left(m.buckets, value)
            child[idx] += 1
            child[-1] += value

    def _value(self, m: _Metric, values: tuple):
        with self._lock:
            child = m.children.get(values)
            if child is None:
                return None
            if m.kind == "histogram":
                return {"count": sum(child[:-1]), "sum": child[-1]}
            return child[-1]

    # -- export ----------------------------------------------------

    def prometheus_text(self) -> str:
        """The Prometheus text exposition format (v0.0.4): HELP/TYPE
        headers, one sample line per child; histograms expand to
        cumulative _bucket{le=...} plus _sum/_count."""
        out: list = []
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                out.append(f"# HELP {name} {m.help}")
                out.append(f"# TYPE {name} {m.kind}")
                label_names = (m.label_names
                               if _OVERFLOW_VALUES not in m.children
                               else m.label_names or _OVERFLOW_LABELS)
                for values in sorted(m.children):
                    if values == _OVERFLOW_VALUES \
                            and m.label_names != _OVERFLOW_LABELS:
                        pairs = 'overflow="true"'
                    else:
                        pairs = ",".join(
                            f'{ln}="{_escape(v)}"'
                            for (ln, v) in zip(label_names, values))
                    child = m.children[values]
                    if m.kind == "histogram":
                        cum = 0
                        for (le, cnt) in zip(m.buckets, child):
                            cum += cnt
                            lbl = (pairs + "," if pairs else "") \
                                + f'le="{_fmt(le)}"'
                            out.append(
                                f"{name}_bucket{{{lbl}}} {cum}")
                        cum += child[len(m.buckets)]
                        lbl = (pairs + "," if pairs else "") \
                            + 'le="+Inf"'
                        out.append(f"{name}_bucket{{{lbl}}} {cum}")
                        brace = f"{{{pairs}}}" if pairs else ""
                        out.append(
                            f"{name}_sum{brace} {_fmt(child[-1])}")
                        out.append(f"{name}_count{brace} {cum}")
                    else:
                        brace = f"{{{pairs}}}" if pairs else ""
                        out.append(
                            f"{name}{brace} {_fmt(child[-1])}")
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        """JSON-able snapshot for /varz: name -> {kind, help,
        series: [{labels, value | {count,sum}}]}."""
        out: dict = {}
        with self._lock:
            for (name, m) in sorted(self._metrics.items()):
                series = []
                for (values, child) in sorted(m.children.items()):
                    if values == _OVERFLOW_VALUES \
                            and m.label_names != _OVERFLOW_LABELS:
                        labels = {"overflow": "true"}
                    else:
                        labels = dict(zip(m.label_names, values))
                    if m.kind == "histogram":
                        val = {"count": sum(child[:-1]),
                               "sum": round(child[-1], 3)}
                    else:
                        val = child[-1]
                    series.append({"labels": labels, "value": val})
                out[name] = {"kind": m.kind, "help": m.help,
                             "series": series,
                             "overflowed": m.overflowed}
        return out

    def metric_names(self) -> list:
        with self._lock:
            return sorted(self._metrics)


def _escape(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"") \
        .replace("\n", r"\n")


def _fmt(x: float) -> str:
    if isinstance(x, float) and x == int(x) and abs(x) < 1e15:
        return str(int(x))
    return repr(x)


def declared_metric_names() -> list:
    """Every shipped series name (lint check 9's source of truth)."""
    return sorted(DECLARED)


def snapshot_json(registry: Optional[MetricsRegistry] = None) -> str:
    reg = registry if registry is not None else get_registry()
    return json.dumps(reg.snapshot(), sort_keys=True)


# -- the process-wide singleton ---------------------------------------

_registry: Optional[MetricsRegistry] = None
_registry_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = MetricsRegistry()
    return _registry


def configure(max_label_sets: int = DEFAULT_MAX_LABEL_SETS
              ) -> MetricsRegistry:
    """Rebuild the singleton (tests)."""
    global _registry
    with _registry_lock:
        _registry = MetricsRegistry(max_label_sets=max_label_sets)
    return _registry
