"""Observability: per-round metrics from the heavy-hitters driver,
locked against an independent recount of SURVEY.md §3.2's op model
and the wire size formulas."""

import pytest

pytestmark = pytest.mark.slow


from mastic_tpu import wire
from mastic_tpu.backend.incremental import needed_paths
from mastic_tpu.backend.schedule import LevelSchedule
from mastic_tpu.common import gen_rand
from mastic_tpu.drivers.heavy_hitters import (
    HeavyHittersRun, get_reports_from_measurements)
from mastic_tpu.mastic import MasticCount

CTX = b"metrics test"
THRESHOLDS = {"default": 2}


def _measurements():
    return [((bool(v >> 2 & 1), bool(v >> 1 & 1), bool(v & 1)), 1)
            for v in [0, 0, 0, 5, 5, 3]]


def _convert_blocks(m):
    payload = m.vidpf.VALUE_LEN * m.field.ENCODED_SIZE
    return 1 + (payload + 15) // 16


@pytest.mark.parametrize("incremental", [True, False],
                         ids=["incremental", "from-root"])
def test_op_model_and_bytes(incremental) -> None:
    m = MasticCount(3)
    reports = get_reports_from_measurements(m, CTX, _measurements())
    # Tamper one report's VIDPF key: rejected via the eval-proof check.
    (nonce, ps, shares) = reports[0]
    (key, proof, seed, part) = shares[0]
    shares = [(bytes([key[0] ^ 1]) + key[1:], proof, seed, part),
              shares[1]]
    reports[0] = (nonce, ps, shares)

    run = HeavyHittersRun(m, CTX, THRESHOLDS, reports,
                          verify_key=gen_rand(m.VERIFY_KEY_SIZE),
                          incremental=incremental)
    while run.step():
        pass
    assert len(run.metrics) == len(run.prev_agg_params)

    num = len(reports)
    for (metrics, agg_param) in zip(run.metrics, run.prev_agg_params):
        (level, prefixes, do_wc) = agg_param
        assert metrics.level == level
        assert metrics.frontier_width == len(prefixes)
        assert metrics.reports_total == num
        # Verdict counters partition the batch.
        assert (metrics.accepted + metrics.rejected_eval_proof
                + metrics.rejected_weight_check
                + metrics.rejected_joint_rand
                + metrics.rejected_fallback) == num
        assert metrics.xof_fallbacks == 0
        # The tampered report fails the eval-proof check every round.
        assert metrics.rejected_eval_proof == 1
        assert metrics.rejected_weight_check == 0

        # Structural op counts vs an independent recount.
        if incremental:
            nodes = len(needed_paths(prefixes, level)[level])
        else:
            nodes = LevelSchedule(prefixes, level, 3).total_nodes
        assert metrics.node_evals == 2 * num * nodes
        assert metrics.aes_extend_blocks == metrics.node_evals
        assert metrics.aes_convert_blocks == \
            metrics.node_evals * _convert_blocks(m)
        assert metrics.keccak_node_proofs == metrics.node_evals

        # Channel bytes from the conformance-locked size formulas.
        # Upload is paid once, on the round the reports enter
        # (weight-check round), and its size must match what the
        # wire-encoded report actually serializes to.
        if do_wc:
            from mastic_tpu import testvec_codec
            from mastic_tpu.metrics import upload_bytes
            (nonce0, ps0, shares0) = reports[1]
            encoded = len(testvec_codec.encode_public_share(m, ps0)) \
                + len(testvec_codec.encode_input_share(m, shares0[0])) \
                + len(testvec_codec.encode_input_share(m, shares0[1]))
            assert upload_bytes(m) == encoded
            assert metrics.bytes_upload == num * encoded
        else:
            assert metrics.bytes_upload == 0
        assert metrics.bytes_prep_shares == \
            2 * num * wire.prep_share_size(m, agg_param)
        assert metrics.bytes_agg_shares == \
            2 * wire.agg_share_size(m, agg_param)
        assert metrics.bytes_prep_msgs == 0  # Count: no joint rand

    # The incremental engine's total tree work is the from-root
    # engine's LAST round alone, give or take the depth-0 rows —
    # O(sum of frontiers) vs O(sum of whole-tree re-walks).
    if incremental:
        total = sum(mx.node_evals for mx in run.metrics)
        frontier_total = sum(
            2 * num * len(needed_paths(p, lv)[lv])
            for (lv, p, _wc) in run.prev_agg_params)
        assert total == frontier_total


def test_metrics_as_dict() -> None:
    from mastic_tpu.metrics import RoundMetrics

    metrics = RoundMetrics(level=0, frontier_width=2, padded_width=4,
                           reports_total=3)
    d = metrics.as_dict()
    assert d["level"] == 0 and d["reports_total"] == 3
    assert "node_evals" in d and "bytes_prep_shares" in d
    # Session fault-tolerance counters ship in the same record.
    for key in ("timeouts", "retries", "quarantined", "respawns"):
        assert d[key] == 0


def test_fault_counters_populated_by_injected_round() -> None:
    """An injected-fault round lands its timeouts / retries /
    quarantines in the RoundMetrics counters — degradation is
    observable, not silent (ISSUE 3).  The respawn counter is
    exercised by tests/test_faults.py's kill-and-resume tests."""
    from mastic_tpu.drivers.parties import ProcessCollector
    from mastic_tpu.drivers.session import SessionConfig

    m = MasticCount(2)
    ctx = b"fault metrics"
    reports = []
    for alpha in ((False, True), (True, False), (True, True)):
        nonce = gen_rand(m.NONCE_SIZE)
        (ps, shares) = m.shard(ctx, (alpha, 1), nonce,
                               gen_rand(m.RAND_SIZE))
        reports.append((nonce, ps, shares))
    cfg = SessionConfig(connect_timeout=30.0, exchange_timeout=300.0,
                        ack_timeout=15.0, round_deadline=600.0,
                        shutdown_timeout=5.0, retries=2, backoff=0.1)
    # Two faults: the leader's copy of report 1 is truncated
    # (quarantine), and the leader's first upload ack is dropped
    # (timeout + retry).
    coll = ProcessCollector(
        m, {"class": "MasticCount", "args": [2]}, ctx,
        gen_rand(m.VERIFY_KEY_SIZE), config=cfg,
        faults_spec=("truncate:party=collector:step=upload_report:nth=2;"
                     "drop:party=leader:step=upload_ack"))
    metrics_out: list = []
    try:
        coll.upload(reports)
        (result, accept, _shares) = coll.round(
            (0, ((False,), (True,)), True), metrics_out=metrics_out)
    finally:
        coll.close()

    assert list(accept) == [True, False, True]
    assert result == [1, 1]     # the quarantined report never counts
    (mx,) = metrics_out
    assert mx.reports_total == 3 and mx.accepted == 2
    assert mx.quarantined == 1
    assert mx.retries >= 1
    assert mx.timeouts >= 1
    assert mx.respawns == 0
    assert mx.extra["quarantine"] == {"1": "malformed"}
    assert mx.extra["process_separated"] is True
    d = mx.as_dict()
    assert d["quarantined"] == 1 and d["retries"] >= 1
