"""Attribute-based metrics mode: a single aggregation at the last
level with hashed attributes as the index space.

Functionally equivalent to the reference
(/root/reference/poc/examples.py:172-260; spec mode
draft-mouris-cfrg-mastic.md:1574-1611): alpha = H(attribute) truncated
to BITS, one weight-checked aggregation at level BITS-1 with the
candidate prefixes being the collector's attributes of interest.
"""

import hashlib
from typing import Optional, Sequence

from ..common import gen_rand
from ..mastic import Mastic
from ..backend.mastic_jax import BatchedMastic
from .heavy_hitters import run_round


def hash_attribute(mastic: Mastic, attribute: str) -> tuple:
    """SHA3-256 the attribute and keep the first BITS bits (the
    reference truncates the same way for BITS=8; collision resistance
    governs how small BITS may be in practice)."""
    bits = mastic.vidpf.BITS
    digest = hashlib.sha3_256(attribute.encode()).digest()
    value = int.from_bytes(digest[:(bits + 7) // 8], "big")
    value >>= (8 - bits % 8) % 8
    return mastic.vidpf.test_index_from_int(value, bits)


def aggregate_by_attribute(mastic: Mastic, ctx: bytes,
                           attributes: Sequence[str], reports: list,
                           verify_key: Optional[bytes] = None,
                           metrics_out: Optional[list] = None,
                           chunk_size: Optional[int] = None) -> list:
    """Aggregate `reports` grouped by the collector's attributes of
    interest.  Returns [(attribute, aggregate)] pairs; appends a
    RoundMetrics record to `metrics_out` (observability, SURVEY §5).

    With `chunk_size`, reports stream through the single aggregation
    round in fixed-size blocks (the device never holds the whole
    batch; full chunks share one compiled program, the tail runs at
    its natural size), bit-identical to the unchunked result."""
    if verify_key is None:
        verify_key = gen_rand(mastic.VERIFY_KEY_SIZE)
    bm = BatchedMastic(mastic)
    level = mastic.vidpf.BITS - 1
    prefixes = tuple(hash_attribute(mastic, a) for a in attributes)
    if len(set(prefixes)) != len(prefixes):
        raise ValueError("attribute hash collision; increase BITS")
    agg_param = (level, prefixes, True)
    assert mastic.is_valid(agg_param, [])
    if chunk_size is not None and chunk_size < 1:
        raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
    if chunk_size is None:
        batch = bm.marshal_reports(reports)
        result = run_round(bm, verify_key, ctx, agg_param, batch,
                           reports, metrics_out=metrics_out)
    else:
        result = _run_round_chunked(bm, verify_key, ctx, agg_param,
                                    reports, chunk_size, metrics_out)
    return list(zip(attributes, result))


def _run_round_chunked(bm: BatchedMastic, verify_key: bytes,
                       ctx: bytes, agg_param, reports: list,
                       chunk_size: int,
                       metrics_out: Optional[list]) -> list:
    """One from-root aggregation round streamed chunk by chunk
    (heavy_hitters.run_round semantics, accumulated aggregates)."""
    import numpy as np

    from ..common import vec_add
    from ..backend.schedule import LevelSchedule
    from .heavy_hitters import _round_fn, _vk_array, finalize_round

    (level, prefixes, do_weight_check) = agg_param
    num = len(reports)
    rows = len(prefixes) * (1 + bm.m.flp.OUTPUT_LEN)
    agg_shares = [[bm.m.field(0)] * rows for _ in range(2)]
    accept_all = np.zeros(num, bool)
    ok_all = np.ones(num, bool)
    eval_ok = np.zeros(num, bool)
    wc_ok: Optional[np.ndarray] = None
    jr_ok: Optional[np.ndarray] = None

    for lo in range(0, num, chunk_size):
        chunk = reports[lo:lo + chunk_size]
        hi = lo + len(chunk)
        batch = bm.marshal_reports(chunk)
        (agg0, agg1, accept, ok, checks) = _round_fn(
            bm, ctx, agg_param)(_vk_array(verify_key), batch)
        ok_all[lo:hi] = np.asarray(ok)
        accept_all[lo:hi] = np.asarray(accept)
        eval_ok[lo:hi] = np.asarray(checks["eval_proof"])
        if "weight_check" in checks:
            if wc_ok is None:
                wc_ok = np.zeros(num, bool)
            wc_ok[lo:hi] = np.asarray(checks["weight_check"])
        if "joint_rand" in checks:
            if jr_ok is None:
                jr_ok = np.zeros(num, bool)
            jr_ok[lo:hi] = np.asarray(checks["joint_rand"])
        for (a, arr) in ((0, agg0), (1, agg1)):
            agg_shares[a] = vec_add(agg_shares[a],
                                    bm.agg_share_to_host(arr))

    sched = LevelSchedule(prefixes, level, bm.m.vidpf.BITS)
    checks = {"eval_proof": eval_ok}
    if wc_ok is not None:
        checks["weight_check"] = wc_ok
    if jr_ok is not None:
        checks["joint_rand"] = jr_ok
    return finalize_round(
        bm, verify_key, ctx, agg_param, reports, ok_all, accept_all,
        checks, agg_shares, padded_width=sched.total_nodes,
        nodes_evaluated=sched.total_nodes, metrics_out=metrics_out,
        extra={"chunk_size": chunk_size})
