"""Verifiable Incremental Distributed Point Function (VIDPF) of [MST24].

Functionally equivalent to the reference implementation
(/root/reference/poc/vidpf.py) — same wire formats, same XOF usages,
byte-exact against /root/reference/test_vec/mastic/ — but organized
*level-synchronously*: instead of a lazily materialized pointer tree,
evaluation proceeds one tree level at a time over a dense, sorted grid
of nodes.  This is the natural shape for the batched TPU backend
(mastic_tpu/backend/), where the same per-level step runs vmapped over
(reports x nodes); the scalar code here is its differential-testing
oracle.

Verifiability hooks (all three are recomputed here exactly as in the
reference, vidpf.py:327, mastic.py:258-306):
  * per-node proofs (TurboSHAKE over the corrected seed),
  * payload sums (each node's payload equals the sum of its children's),
  * the counter (first payload element) at the root.
"""

from typing import Generic, Sequence, TypeAlias

from .common import pack_bits, pack_bits_le, to_le_bytes, unpack_bits_le, \
    vec_add, vec_neg, vec_sub, xor
from .dst import USAGE_CONVERT, USAGE_EXTEND, USAGE_NODE_PROOF, dst
from .field import F
from .xof import XofFixedKeyAes128, XofTurboShake128

PROOF_SIZE: int = 32

# A bit-path into the binary prefix tree; () is the root.
Path: TypeAlias = tuple[bool, ...]

CorrectionWord: TypeAlias = tuple[
    bytes,       # seed correction
    list[bool],  # control-bit corrections (left, right)
    list,        # payload correction
    bytes,       # node-proof correction
]


def encode_path(path: Path) -> bytes:
    """Big-endian bit packing (reference PrefixTreeIndex.encode,
    vidpf.py:32-39)."""
    return pack_bits(list(path))


class EvalNode(Generic[F]):
    """Per-node evaluation state of one aggregator: corrected seed,
    control bit, payload and node proof (reference PrefixTreeEntry,
    vidpf.py:60-81)."""

    __slots__ = ("seed", "ctrl", "w", "proof")

    def __init__(self, seed: bytes, ctrl: bool, w: list[F], proof: bytes):
        self.seed = seed
        self.ctrl = ctrl
        self.w = w
        self.proof = proof


class PrefixTree(Generic[F]):
    """The level-synchronous evaluation grid for one (report, aggregator)
    pair: `nodes[d]` maps each materialized depth-(d+1) path to its
    EvalNode.  Within a level, iteration order is lexicographic, which
    reproduces the reference's breadth-first traversal order
    (mastic.py:258-287) — see `Vidpf.tree_schedule`."""

    def __init__(self) -> None:
        self.levels: list[dict[Path, EvalNode[F]]] = []


def tree_schedule(prefixes: Sequence[Path], level: int) \
        -> list[list[Path]]:
    """The dense node grid implied by a candidate-prefix set: for each
    depth d+1 in 1..level+1, the sorted list of both children of every
    path node `p[:d]`.

    Sorting lexicographically per level reproduces the reference's BFS
    materialization order exactly: children are enqueued left-then-right
    in parents' visit order, so each level of the queue is in
    lexicographic order.  The schedule depends only on the (public)
    prefix set, never on secret data — on TPU it is precomputed on the
    host and applied as a static gather/permutation.
    """
    schedule = []
    for depth in range(level + 1):
        parents = sorted(set(p[:depth] for p in prefixes))
        children = []
        for parent in parents:
            children.append(parent + (False,))
            children.append(parent + (True,))
        schedule.append(children)
    return schedule


class Vidpf(Generic[F]):
    """VIDPF with field `field`, input length `bits` and payload length
    `value_len` (reference Vidpf, vidpf.py:84-101)."""

    KEY_SIZE = XofFixedKeyAes128.SEED_SIZE
    NONCE_SIZE = XofFixedKeyAes128.SEED_SIZE
    RAND_SIZE = 2 * XofFixedKeyAes128.SEED_SIZE

    def __init__(self, field: type[F], bits: int, value_len: int):
        self.field = field
        self.BITS = bits
        self.VALUE_LEN = value_len

    # -- key generation (client side; reference vidpf.py:103-211) --

    def gen(self,
            alpha: Path,
            beta: list[F],
            ctx: bytes,
            nonce: bytes,
            rand: bytes,
            ) -> tuple[list[CorrectionWord], list[bytes]]:
        """Produce the public share (one correction word per level) and
        the two aggregator keys."""
        if len(alpha) != self.BITS:
            raise ValueError("alpha out of range")
        if len(beta) != self.VALUE_LEN:
            raise ValueError("incorrect beta length")
        if len(nonce) != self.NONCE_SIZE:
            raise ValueError("incorrect nonce size")
        if len(rand) != self.RAND_SIZE:
            raise ValueError("randomness has incorrect length")

        keys = [rand[:self.KEY_SIZE], rand[self.KEY_SIZE:]]
        seed = [keys[0], keys[1]]
        ctrl = [False, True]
        correction_words: list[CorrectionWord] = []
        for i in range(self.BITS):
            bit = alpha[i]
            keep = int(bit)
            lose = 1 - keep

            # Extend both parties' seeds into left/right children.
            (s0, t0) = self.extend(seed[0], ctx, nonce)
            (s1, t1) = self.extend(seed[1], ctx, nonce)

            # Seed/ctrl corrections: arranged so that after correction,
            # on-path children differ (ctrl shares of 1) while off-path
            # children collide (ctrl shares of 0).
            #
            # Timing note on the suppressions below: gen() is client
            # code running over the client's OWN (alpha, beta) — no
            # other party observes its timing — and the deployed
            # batched twin replaces every secret-dependent choice with
            # a lane select (backend/vidpf_jax.py gen).
            # mastic-allow: SF002 — client-side keygen, see note above
            seed_cw = xor(s0[lose], s1[lose])
            ctrl_cw = [
                t0[0] ^ t1[0] ^ (not bit),
                t0[1] ^ t1[1] ^ bit,
            ]

            # mastic-allow: SF001, SF002 — client-side keygen (above)
            s0k = xor(s0[keep], seed_cw) if ctrl[0] else s0[keep]
            # mastic-allow: SF002 — client-side keygen (above)
            t0k = t0[keep] ^ (ctrl[0] and ctrl_cw[keep])
            # mastic-allow: SF001, SF002 — client-side keygen (above)
            s1k = xor(s1[keep], seed_cw) if ctrl[1] else s1[keep]
            # mastic-allow: SF002 — client-side keygen (above)
            t1k = t1[keep] ^ (ctrl[1] and ctrl_cw[keep])

            # Convert the kept child seeds into payloads + next seeds.
            (seed0, w0) = self.convert(s0k, ctx, nonce)
            (seed1, w1) = self.convert(s1k, ctx, nonce)
            seed = [seed0, seed1]
            ctrl = [t0k, t1k]

            # Payload correction: make the on-path payload shares sum
            # to beta.
            w_cw = vec_add(vec_sub(beta, w0), w1)
            # mastic-allow: SF001 — client-side keygen (above)
            if ctrl[1]:
                w_cw = vec_neg(w_cw)

            # Node-proof correction: on path, exactly one party
            # corrects, aligning the two proofs.
            idx = alpha[:i + 1]
            proof_cw = xor(
                self.node_proof(seed[0], ctx, idx),
                self.node_proof(seed[1], ctx, idx),
            )

            correction_words.append((seed_cw, ctrl_cw, w_cw, proof_cw))

        return (correction_words, keys)

    # -- evaluation (aggregator side) ------------------------------

    def eval_level_synchronous(self,
                               agg_id: int,
                               correction_words: list[CorrectionWord],
                               key: bytes,
                               level: int,
                               prefixes: Sequence[Path],
                               ctx: bytes,
                               nonce: bytes,
                               ) -> tuple[list[list[F]], PrefixTree[F]]:
        """Evaluate the prefix tree one level at a time over the dense
        node grid of `tree_schedule`.

        Equivalent to the reference's per-prefix lazy walk
        (eval_with_siblings, vidpf.py:213-261) but with each level's
        nodes computed in one pass — the shape the TPU backend runs
        vmapped.  Returns the per-prefix payload shares (negated for
        aggregator 1) and the populated tree.
        """
        if agg_id not in range(2):
            raise ValueError("invalid aggregator ID")
        if len(correction_words) != self.BITS:
            raise ValueError("correction words have incorrect length")
        if level not in range(self.BITS):
            raise ValueError("level too deep")
        for prefix in prefixes:
            if len(prefix) != level + 1:
                raise ValueError("prefix with incorrect length")
        if len(set(prefixes)) != len(prefixes):
            raise ValueError("candidate prefixes are non-unique")

        root = EvalNode(key, bool(agg_id), self.field.zeros(self.VALUE_LEN),
                        b"")
        tree: PrefixTree[F] = PrefixTree()
        schedule = tree_schedule(prefixes, level)
        prev: dict[Path, EvalNode[F]] = {(): root}
        for (depth, paths) in enumerate(schedule):
            nodes: dict[Path, EvalNode[F]] = {}
            for path in paths:
                parent = prev[path[:-1]]
                nodes[path] = self.eval_next(
                    parent, correction_words[depth], ctx, nonce, path)
            tree.levels.append(nodes)
            prev = nodes

        out_share = []
        for prefix in prefixes:
            w = tree.levels[level][prefix].w
            out_share.append(list(w) if agg_id == 0 else vec_neg(w))
        return (out_share, tree)

    def get_beta_share(self,
                       agg_id: int,
                       correction_words: list[CorrectionWord],
                       key: bytes,
                       ctx: bytes,
                       nonce: bytes,
                       ) -> list[F]:
        """Each party's share of beta: the sum of the two depth-1
        payloads (reference vidpf.py:263-279)."""
        root = EvalNode(key, bool(agg_id), self.field.zeros(self.VALUE_LEN),
                        b"")
        left = self.eval_next(root, correction_words[0], ctx, nonce,
                              (False,))
        right = self.eval_next(root, correction_words[0], ctx, nonce,
                               (True,))
        beta_share = vec_add(left.w, right.w)
        if agg_id == 1:
            beta_share = vec_neg(beta_share)
        return beta_share

    def eval_next(self,
                  node: EvalNode[F],
                  correction_word: CorrectionWord,
                  ctx: bytes,
                  nonce: bytes,
                  path: Path,
                  ) -> EvalNode[F]:
        """Extend `node`, select/correct the child on `path`'s last bit,
        convert to a payload + next seed, and attach the corrected node
        proof (reference vidpf.py:281-325).

        Scalar reference note: branches on secret control bits below are
        replaced by lane-wise selects in the TPU backend, which is
        constant-time by construction.
        """
        (seed_cw, ctrl_cw, w_cw, proof_cw) = correction_word
        keep = int(path[-1])

        (s, t) = self.extend(node.seed, ctx, nonce)
        # mastic-allow: SF001 — scalar differential oracle; the
        # deployed path is the backend's lane select (docstring note)
        if node.ctrl:
            s[keep] = xor(s[keep], seed_cw)
            t[keep] ^= ctrl_cw[keep]

        (next_seed, w) = self.convert(s[keep], ctx, nonce)
        next_ctrl = t[keep]
        # mastic-allow: SF001 — scalar oracle, see docstring note
        if next_ctrl:
            w = vec_add(w, w_cw)

        proof = self.node_proof(next_seed, ctx, path)
        # mastic-allow: SF001 — scalar oracle, see docstring note
        if next_ctrl:
            proof = xor(proof, proof_cw)

        return EvalNode(next_seed, next_ctrl, w, proof)

    def verify(self, proof0: bytes, proof1: bytes) -> bool:
        return proof0 == proof1

    # -- the two PRGs and the node hash (reference vidpf.py:330-380) --

    def extend(self,
               seed: bytes,
               ctx: bytes,
               nonce: bytes,
               ) -> tuple[list[bytes], list[bool]]:
        """Extend a seed into (left seed, right seed) plus control bits.
        The control bits are the LSBs of the child seeds, which are then
        zeroed (127-bit seeds; saves one AES block per node)."""
        xof = XofFixedKeyAes128(seed, dst(ctx, USAGE_EXTEND), nonce)
        s = [
            bytearray(xof.next(self.KEY_SIZE)),
            bytearray(xof.next(self.KEY_SIZE)),
        ]
        t = [bool(s[0][0] & 1), bool(s[1][0] & 1)]
        s[0][0] &= 0xFE
        s[1][0] &= 0xFE
        return ([bytes(s[0]), bytes(s[1])], t)

    def convert(self,
                seed: bytes,
                ctx: bytes,
                nonce: bytes,
                ) -> tuple[bytes, list[F]]:
        """Convert a selected child seed into the next-level seed and a
        payload vector."""
        xof = XofFixedKeyAes128(seed, dst(ctx, USAGE_CONVERT), nonce)
        next_seed = xof.next(XofFixedKeyAes128.SEED_SIZE)
        payload = xof.next_vec(self.field, self.VALUE_LEN)
        return (next_seed, payload)

    def node_proof(self,
                   seed: bytes,
                   ctx: bytes,
                   path: Path) -> bytes:
        """TurboSHAKE proof binding (seed, BITS, level, path)."""
        binder = \
            to_le_bytes(self.BITS, 2) + \
            to_le_bytes(len(path) - 1, 2) + \
            encode_path(path)
        xof = XofTurboShake128(seed, dst(ctx, USAGE_NODE_PROOF), binder)
        return xof.next(PROOF_SIZE)

    # -- public-share wire format (reference vidpf.py:382-394) -----

    def encode_public_share(self,
                            correction_words: list[CorrectionWord]) -> bytes:
        (seeds, ctrl, payloads, proofs) = zip(*correction_words)
        encoded = bytes()
        encoded += pack_bits_le([bit for pair in ctrl for bit in pair])
        for seed in seeds:
            encoded += seed
        for payload in payloads:
            encoded += self.field.encode_vec(payload)
        for proof in proofs:
            encoded += proof
        return encoded

    def decode_public_share(self, encoded: bytes) -> list[CorrectionWord]:
        """Inverse of encode_public_share (needed by the wire layer; the
        reference never decodes, test vectors only encode)."""
        b = self.BITS
        elem = self.field.ENCODED_SIZE
        ctrl_len = (2 * b + 7) // 8
        expect = ctrl_len + b * (self.KEY_SIZE + self.VALUE_LEN * elem
                                 + PROOF_SIZE)
        if len(encoded) != expect:
            raise ValueError("malformed public share")
        ctrl_bits = unpack_bits_le(encoded[:ctrl_len], 2 * b)
        off = ctrl_len
        seeds = [encoded[off + i * self.KEY_SIZE:
                         off + (i + 1) * self.KEY_SIZE] for i in range(b)]
        off += b * self.KEY_SIZE
        payloads = []
        for i in range(b):
            payloads.append(self.field.decode_vec(
                encoded[off:off + self.VALUE_LEN * elem]))
            off += self.VALUE_LEN * elem
        proofs = [encoded[off + i * PROOF_SIZE:
                          off + (i + 1) * PROOF_SIZE] for i in range(b)]
        return [
            (seeds[i], [ctrl_bits[2 * i], ctrl_bits[2 * i + 1]],
             payloads[i], proofs[i])
            for i in range(b)
        ]

    def is_prefix(self, x: Path, y: Path, level: int) -> bool:
        """True iff `x` is the level-`level` prefix of `y`."""
        return x == y[:level + 1]

    # -- test helpers (reference vidpf.py:409-427) -----------------

    def test_index_from_int(self, value: int, length: int) -> Path:
        assert length <= self.BITS
        return tuple(
            (value >> (length - 1 - i)) & 1 != 0 for i in range(length))

    def prefixes_for_level(self, level: int) -> tuple[Path, ...]:
        """Every (level+1)-bit prefix, in lexicographic order.

        Deliberate divergence from the reference helper
        (vidpf.py:424-427), which enumerates only range(2**level) —
        the half of the prefixes whose leading bit is 0.  Tests here
        exercise on-path nodes for arbitrary alphas, so the full
        2**(level+1) enumeration is required.
        """
        return tuple(self.test_index_from_int(v, level + 1)
                     for v in range(2 ** (level + 1)))
