"""SF004 bad fixture: key-derived bytes cross the wire raw — the
taint flows through the helper's return (interprocedural)."""


def mix(key):
    return key + b"pad"


def push(sock, key):
    sock.sendall(mix(key))
