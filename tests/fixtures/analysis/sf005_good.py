"""SF005 good fixture: a fixed backoff schedule."""
import time


def backoff(key):
    del key
    time.sleep(0.25)
