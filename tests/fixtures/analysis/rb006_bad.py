"""Known-bad: publish-by-rename without durability (RB006) — the
rename lands atomically but nothing forced the tmp file's bytes to
disk first, so a crash can leave an empty or torn file under the
final name."""

import json
import os


def publish_snapshot(path, state):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f)            # buffered, never fsynced
    os.replace(tmp, path)


def rotate_log(path):
    os.rename(path, path + ".1")       # same hazard, rename spelling
