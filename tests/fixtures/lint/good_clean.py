"""Known-good lint fixture: parses, no unused imports, no prints."""

import os


def path_exists(path: str) -> bool:
    return os.path.exists(path)
