"""EV003 clean: the only wait under the lock carries a timeout."""
import queue
import threading

MU = threading.Lock()


def drain(sock, q):
    sock.setblocking(False)
    with MU:
        return q.get(timeout=0.05)
