"""Mesh-sharded production rounds (ISSUE 5): the pipelined chunked
executor over a report-axis device mesh must be bit-identical to the
serial single-device path — aggregates, accept masks, rejection
counters, quarantine-union (fallback) masks and checkpoint state
arrays — across 1/2/3-chunk stores including the padded tail and
UNEVEN shards (chunk_size not a multiple of the mesh), with
`("serial", "mesh")` gone as a degrade reason and steady-state rounds
compiling zero inline on the mesh.

Fast tier: envelope/padding/key units plus the per-device allocation
parity (`make multichip` runs these and tools/multichip.py — the real
8-device pipelined proof run).  The full mesh={1,2,8} x chunk-layout
matrix, growth-under-mesh, attribute-metrics and checkpoint-resume
compositions are slow tier (each is a pair of full collection runs).
"""

import numpy as np
import pytest

import jax

from mastic_tpu.backend.mastic_jax import BatchedMastic
from mastic_tpu.common import gen_rand
from mastic_tpu.drivers.chunked import (HostReportStore,
                                        _carry_to_device, _pad_rows,
                                        memory_envelope)
from mastic_tpu.drivers.heavy_hitters import (
    HeavyHittersRun, get_reports_from_measurements)
from mastic_tpu.mastic import MasticCount, MasticHistogram
from mastic_tpu.parallel import make_mesh, place_reports

CTX = b"mesh pipeline test"


def _reports(m):
    """10 reports over a 3-bit tree, one tampered (eval-proof reject
    at report 6): hitters {0, 6, 7} at threshold 2 with a steady
    one-child-per-parent frontier from level 1 — the AOT predictor's
    fixed point, so the zero-inline-compile claim is assertable."""
    meas = [(m.vidpf.test_index_from_int(v, 3), True)
            for v in (0, 0, 0, 7, 7, 7, 3, 1, 6, 6)]
    reports = get_reports_from_measurements(m, CTX, meas)
    (nonce, ps, shares) = reports[6]
    (key, proof, seed, part) = shares[0]
    reports[6] = (nonce, ps, [
        (bytes([key[0] ^ 1]) + key[1:], proof, seed, part), shares[1]])
    return reports


def _run_all(run):
    while run.step():
        pass
    return run


def _assert_bit_identical(ser, mesh_run):
    assert ser.result() == mesh_run.result()
    assert len(ser.metrics) == len(mesh_run.metrics)
    for (a, b) in zip(ser.metrics, mesh_run.metrics):
        assert (a.accepted, a.rejected_eval_proof,
                a.rejected_weight_check, a.rejected_joint_rand,
                a.rejected_fallback, a.xof_fallbacks,
                a.node_evals) == \
            (b.accepted, b.rejected_eval_proof,
             b.rejected_weight_check, b.rejected_joint_rand,
             b.rejected_fallback, b.xof_fallbacks, b.node_evals)
    # Quarantine-union (scalar-fallback) masks agree lane for lane.
    assert np.array_equal(ser.runner.fallback, mesh_run.runner.fallback)
    # Checkpoint state arrays (every chunk's both carries) bit-equal.
    (sa, sb) = (ser.runner.state_arrays(),
                mesh_run.runner.state_arrays())
    assert sorted(sa) == sorted(sb)
    for k in sa:
        assert np.array_equal(sa[k], sb[k]), f"state array {k}"


# -- fast tier: units + per-device allocation parity -----------------


def test_envelope_per_shard_fields():
    """Per-shard residency = device term / report shards, priced at
    the padded device rows (uneven chunks pad up to the shard
    multiple)."""
    m = MasticCount(3)
    bm = BatchedMastic(m)
    base = memory_envelope(bm, 8, 8, 16)
    env = memory_envelope(bm, 8, 8, 16, n_device_shards=4)
    assert base["report_shards"] == 1
    assert base["device_bytes_per_chunk_per_shard"] == \
        base["device_bytes_per_chunk"]
    assert env["report_shards"] == 4
    assert env["device_rows_per_chunk"] == 8
    assert env["rows_per_shard"] == 2
    assert env["device_bytes_per_chunk_per_shard"] == \
        env["device_bytes_per_chunk"] // 4
    assert env["device_bytes_per_chunk_pipelined_per_shard"] == \
        env["device_bytes_per_chunk_pipelined"] // 4
    assert env["max_chunk_size_at_width_sharded"] == \
        4 * env["max_chunk_size_at_width"]
    # Uneven: chunk 6 over 4 shards pads to 8 device rows, and the
    # per-shard price covers the padded rows (2 each), not 6/4.
    uneven = memory_envelope(bm, 6, 8, 16, n_device_shards=4)
    assert uneven["device_rows_per_chunk"] == 8
    assert uneven["rows_per_shard"] == 2
    assert uneven["device_bytes_per_chunk_per_shard"] == \
        env["device_bytes_per_chunk_per_shard"]


def test_pad_rows_rule_and_device_chunk():
    """Device-tile padding repeats row 0 (the host_slice rule), and
    the live mask excludes every padded lane — dead lanes compute the
    same garbage serial and meshed, so trimmed carries stay
    bit-identical."""
    a = np.arange(6).reshape(3, 2)
    padded = _pad_rows(a, 5)
    assert padded.shape == (5, 2)
    assert np.array_equal(padded[3], a[0])
    assert np.array_equal(padded[4], a[0])
    assert _pad_rows(a, 3) is a  # no-op when nothing to pad

    m = MasticCount(3)
    bm = BatchedMastic(m)
    reports = _reports(m)[:5]
    store = HostReportStore.from_batch(bm.marshal_reports(reports), 4)
    # Tail chunk: 1 live row, chunk_size 4, device rows 8 (mesh of 8).
    (batch, live) = store.device_chunk(1, rows=8)
    assert batch.nonces.shape[0] == 8
    assert live.tolist() == [True] + [False] * 7
    row0 = np.asarray(batch.nonces[0])
    for lane in range(1, 8):
        assert np.array_equal(np.asarray(batch.nonces[lane]), row0)


def test_program_keys_carry_mesh_shape():
    """The AOT ProgramCache keys include the mesh's report-axis size
    (and the padded device rows), so serial and sharded programs can
    never collide — the invalidation-free growth argument extended
    one axis up."""
    m = MasticCount(3)
    bm = BatchedMastic(m)
    reports = _reports(m)
    store = HostReportStore.from_batch(bm.marshal_reports(reports), 4)
    mesh = make_mesh(8, nodes_axis=1)
    run = HeavyHittersRun(m, CTX, {"default": 2}, reports,
                          verify_key=gen_rand(m.VERIFY_KEY_SIZE),
                          store=store, mesh=mesh)
    runner = run.runner
    assert runner.mesh is mesh
    assert runner._report_shards() == 8
    assert runner._device_rows() == 8  # chunk 4 padded to the multiple
    plan = runner._plan(((False,), (True,)), 0)
    assert runner._eval_key(8, plan)[:3] == ("eval", 8, 8)
    assert runner._agg_key(8, 4)[:3] == ("agg", 8, 8)
    # Serial twin: shards=0 in the key, device rows = chunk size.
    ser = HeavyHittersRun(m, CTX, {"default": 2}, reports,
                          verify_key=gen_rand(m.VERIFY_KEY_SIZE),
                          chunk_size=4)
    assert ser.runner._eval_key(4, plan)[:3] == ("eval", 4, 0)
    assert ser.runner._device_rows() == 4


def test_envelope_per_shard_parity_real_allocations():
    """test_memory_envelope_guard-style parity, one axis up: the
    analytic per-shard price equals what ONE device actually holds
    when a chunk's state is placed exactly as the pipelined stage
    phase places it (joint-rand family, padded tail chunk)."""
    m = MasticHistogram(4, 4, 2)
    bm = BatchedMastic(m)
    meas = [(m.vidpf.test_index_from_int(v % 16, 4), v % 4)
            for v in range(6)]
    reports = get_reports_from_measurements(m, CTX, meas)
    store = HostReportStore.from_batch(bm.marshal_reports(reports), 4)
    mesh = make_mesh(2, nodes_axis=1)
    run = HeavyHittersRun(m, CTX, {"default": 1}, reports,
                          verify_key=gen_rand(m.VERIFY_KEY_SIZE),
                          store=store, mesh=mesh)
    runner = run.runner
    env = memory_envelope(bm, 4, runner.width, 6, n_device_shards=2)
    assert env["device_rows_per_chunk"] == runner._device_rows() == 4

    for chunk in range(store.num_chunks):
        cs = runner.chunks[chunk]
        (batch, _live) = store.device_chunk(chunk, rows=4)
        dev_c0 = _carry_to_device(cs.carries[0], 4)
        dev_c1 = _carry_to_device(cs.carries[1], 4)
        ext_rk = jax.numpy.asarray(_pad_rows(cs.ext_rk, 4))
        conv_rk = jax.numpy.asarray(_pad_rows(cs.conv_rk, 4))
        placed = place_reports(
            mesh, (batch, dev_c0, dev_c1, ext_rk, conv_rk))
        dev0 = sum(x.addressable_shards[0].data.nbytes
                   for x in jax.tree_util.tree_leaves(placed))
        assert dev0 == env["device_bytes_per_chunk_per_shard"], \
            f"chunk {chunk}"


# -- slow tier: full bit-identity matrix -----------------------------


@pytest.mark.slow
@pytest.mark.parametrize("mesh_n,chunk_size,num_chunks", [
    (1, 4, 3),    # 1-device mesh == serial layout, collective-free
    (2, 5, 2),    # even shards, no tail padding
    (2, 4, 3),    # padded tail chunk (2 live of 4)
    (8, 4, 3),    # UNEVEN: chunk 4 pads to 8 device rows per chunk
    (8, 12, 1),   # single chunk (serial fallback named, still sharded)
], ids=["mesh1-3chunk", "mesh2-2chunk", "mesh2-3chunk-tail",
        "mesh8-uneven", "mesh8-1chunk"])
def test_mesh_pipelined_matches_serial(monkeypatch, mesh_n,
                                       chunk_size, num_chunks):
    monkeypatch.setenv("MASTIC_PIPELINE", "1")
    m = MasticCount(3)
    reports = _reports(m)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    thresholds = {"default": 2}

    ser = _run_all(HeavyHittersRun(m, CTX, thresholds, reports,
                                   verify_key=vk,
                                   chunk_size=chunk_size))
    mesh = make_mesh(mesh_n, nodes_axis=1)
    meshed = _run_all(HeavyHittersRun(m, CTX, thresholds, reports,
                                      verify_key=vk,
                                      chunk_size=chunk_size,
                                      mesh=mesh))
    assert meshed.runner.store.num_chunks == num_chunks
    _assert_bit_identical(ser, meshed)

    pipes = [mx.extra["pipeline"] for mx in meshed.metrics]
    if num_chunks > 1:
        # The tentpole: mesh rounds PIPELINE — ("serial", "mesh") is
        # gone as a degrade reason.
        assert all(p["mode"] == "pipelined" for p in pipes)
        assert all(p["fallback"] is None for p in pipes)
    else:
        assert all(p["fallback"] == "single-chunk" for p in pipes)
    # Steady-state rounds after the first pay zero inline compile on
    # the mesh (sharded AOT warm predicted them).
    for p in pipes[1:]:
        assert p["compile_inline_ms"] == 0.0
        assert p["aot"]["predicted"]
    for mx in meshed.metrics:
        blk = mx.extra["mesh"]
        assert blk["report_shards"] == mesh_n
        assert blk["device_rows_per_chunk"] % mesh_n == 0
        if mesh_n > 1:
            assert blk["psum_bytes_per_round"] > 0
    # Per-shard rate honesty on every chunk record (live AND padded).
    for rec in meshed.metrics[-1].extra["chunks"]:
        assert rec["node_evals_per_sec_per_shard"] == pytest.approx(
            rec["node_evals_per_sec"] / mesh_n, rel=0.01)
        assert rec["node_evals_per_sec_padded_per_shard"] == \
            pytest.approx(rec["node_evals_per_sec_padded"] / mesh_n,
                          rel=0.01)


@pytest.mark.slow
def test_grow_under_mesh(monkeypatch):
    """Width growth under a mesh: the grown carries re-place with the
    same report sharding and the shape+mesh-keyed programs recompile
    for the new width — bit-identical to the serial grown run (the
    satellite regression for heavy_hitters/_grow threading)."""
    monkeypatch.setenv("MASTIC_PIPELINE", "1")
    m = MasticCount(5)
    meas = [(m.vidpf.test_index_from_int(v * 4, 5), True)
            for v in range(8)]
    reports = get_reports_from_measurements(m, CTX, meas)
    vk = gen_rand(m.VERIFY_KEY_SIZE)

    ser = _run_all(HeavyHittersRun(m, CTX, {"default": 1}, reports,
                                   verify_key=vk, chunk_size=4))
    mesh = make_mesh(2, nodes_axis=1)
    meshed = _run_all(HeavyHittersRun(m, CTX, {"default": 1}, reports,
                                      verify_key=vk, chunk_size=4,
                                      mesh=mesh))
    assert ser.runner.width == meshed.runner.width == 16
    _assert_bit_identical(ser, meshed)
    # Every compiled eval program key carries the mesh shape next to
    # the width it closed over.
    eval_keys = [k for k in meshed.runner.programs._programs
                 if k[0] == "eval"]
    assert eval_keys and all(k[2] == 2 for k in eval_keys)
    assert {k[3] for k in eval_keys} >= {8, 16}


@pytest.mark.slow
def test_checkpoint_resume_under_mesh(monkeypatch):
    """Kill after level 0, restore WITH the mesh, finish: identical to
    the uninterrupted serial run (from_bytes threads the mesh into the
    restored chunked runner)."""
    monkeypatch.setenv("MASTIC_PIPELINE", "1")
    m = MasticCount(3)
    reports = _reports(m)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    thresholds = {"default": 2}

    ref = _run_all(HeavyHittersRun(m, CTX, thresholds, reports,
                                   verify_key=vk, chunk_size=4))
    mesh = make_mesh(8, nodes_axis=1)
    victim = HeavyHittersRun(m, CTX, thresholds, reports,
                             verify_key=vk, chunk_size=4, mesh=mesh)
    victim.step()
    blob = victim.to_bytes()
    del victim

    resumed = HeavyHittersRun.from_bytes(m, CTX, thresholds, reports,
                                         vk, blob, mesh=mesh)
    assert resumed.level == 1
    assert resumed.runner.mesh is mesh
    _run_all(resumed)
    assert resumed.result() == ref.result()
    (sa, sb) = (ref.runner.state_arrays(),
                resumed.runner.state_arrays())
    for k in sa:
        assert np.array_equal(sa[k], sb[k]), k


@pytest.mark.slow
def test_attribute_round_mesh(monkeypatch):
    """aggregate_by_attribute over a mesh, uneven chunk (5 reports,
    chunk 3, 2 shards): padded+masked lanes never reach the psum —
    result identical to the whole-batch single-device round."""
    from mastic_tpu.drivers.attribute_metrics import (
        aggregate_by_attribute, hash_attribute)

    monkeypatch.setenv("MASTIC_PIPELINE", "1")
    m = MasticCount(8)
    attrs = ["checkout", "landing"]
    meas = [(hash_attribute(m, "checkout"), True)] * 3 + \
        [(hash_attribute(m, "landing"), True)] * 2
    reports = get_reports_from_measurements(m, CTX, meas)
    vk = gen_rand(m.VERIFY_KEY_SIZE)

    whole = aggregate_by_attribute(m, CTX, attrs, reports,
                                   verify_key=vk)
    out_m: list = []
    mesh = make_mesh(2, nodes_axis=1)
    meshed = aggregate_by_attribute(m, CTX, attrs, reports,
                                    verify_key=vk, chunk_size=3,
                                    mesh=mesh, metrics_out=out_m)
    assert whole == meshed == [("checkout", 3), ("landing", 2)]
    blk = out_m[0].extra["mesh"]
    assert blk["report_shards"] == 2
    assert blk["psum_bytes_per_round"] > 0
    assert out_m[0].extra["pipeline"]["mode"] == "pipelined"
