"""Known-bad: index_map arity != grid rank (PL002)."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def call(kernel):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((16, 256), jnp.uint32),
        grid=(2, 2),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (0, i))],
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
    )
