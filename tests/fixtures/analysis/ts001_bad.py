"""Known-bad: Python branch on a traced value (TS001)."""

import jax
import jax.numpy as jnp


def relu_sum(x: jax.Array) -> jax.Array:
    total = jnp.sum(x)
    if total > 0:
        return total
    return -total


def drain(x: jax.Array) -> jax.Array:
    while jnp.any(x > 0):
        x = x - 1
    return x
