"""Test configuration.

Sharding/mesh tests run on a virtual 8-device CPU mesh; the real-TPU
benchmark path is exercised separately by bench.py.  All env vars must
be set before `import jax` (jax snapshots them into config defaults at
import time), hence the ordering below.
"""

import os
import sys

# Force CPU: the ambient environment pins jax to the real TPU tunnel
# (its sitecustomize overrides the jax_platforms *config*, so the env
# var alone is not enough — see the config.update below), and tests
# must not depend on the tunnel — it blocks for minutes when down.
# The virtual 8-device CPU mesh is the test fabric for all sharding
# paths.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = \
        (xla_flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent compilation cache.  The CPU backend in this jax build does
# not serialize executables (the cache stays empty under pytest), but
# the same config is what bench.py relies on for the real TPU chip,
# where first compiles are the dominant startup cost.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/mastic_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

import jax  # noqa: E402  (after the env setup above, by design)

jax.config.update("jax_platforms", "cpu")
# This jax build does not pick the cache dir up from the env var, so
# set the config explicitly (CPU cache needs the min-size/-time floors
# dropped, done via the env vars above).
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
