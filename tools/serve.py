"""Collector-service driver: boot a long-lived multi-tenant service
(`mastic_tpu/drivers/service.py`), stream synthetic uploads through
it, and drain epochs — the serving twin of the offline
`tools/northstar.py` batch run.

Three modes:

* default — build the demo tenants (a heavy-hitters Count collection
  and an attribute-metrics collection at a different bit-width),
  admit `--reports` seeded uploads per tenant per epoch, run
  `--epochs` epochs each through the scheduler, and print one JSON
  line with the per-tenant results and the full service metrics.
  With `--snapshot PATH` the service state is written (atomic
  rename) after admission and after every scheduler round, so a
  `kill -9` at any point loses at most the round in flight;
  `--resume` restores from the snapshot instead of re-admitting —
  the kill-and-resume test drives exactly this pair.

* ``--smoke`` — the `make serve-smoke` gate: two tenants plus
  overload/deadline scratch tenants, a malformed-upload burst
  (quarantined, tenant-attributed), sustained overload against a
  tiny quota (bounded memory, sheds counted under both policies), an
  epoch-deadline miss (degrades to the truncated frontier, marked),
  and a mid-epoch crash drill (snapshot, discard the live service,
  resume, bit-identical result).  Any violated expectation exits
  non-zero with the reason; the JSON line carries ``"ok": true``
  otherwise.

* ``--soak SECONDS`` — the unattended chip-session cell: loop
  admit -> epoch -> drain under one deadline, reporting epochs
  completed, rounds, and counter totals (a service that leaks,
  wedges, or sheds silently fails loudly here).

`MASTIC_FAULTS` (party ``collector``) is honored end to end — the
service arms its injector from the environment, so e.g.
``kill:party=collector:step=epoch_round:nth=2`` exercises a real
process death mid-epoch against the snapshot/resume pair.

Observability (ISSUE 7): ``--status-port N`` starts the live status
surface (`mastic_tpu/obs/statusz.py`) on 127.0.0.1:N — ``/metrics``
(Prometheus), ``/statusz`` (human text: per-tenant occupancy, queue
depths, shed/quarantine totals, last-round timelines) and ``/varz``
(JSON snapshot).  Port 0 binds an ephemeral port (printed in the JSON
line as ``status_port``).  The scheduler stays single-threaded: it
publishes an immutable snapshot after every quantum and the server
thread only reads published snapshots (snapshot-under-lock).  With
``--smoke --status-port`` the smoke gate additionally self-fetches
all three endpoints and asserts the expected per-tenant series are
present — the `make obs-smoke` cell.  `MASTIC_TRACE_FILE=path` gets
a JSONL span trace of every epoch/round/chunk (USAGE.md
"Observability").
"""

import argparse
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_reports(m, ctx, rng, values, bits):
    """Seeded client uploads: shard each value with rng-derived
    nonce/rand so two processes with one --seed build byte-identical
    reports (the unfaulted / faulted+resumed comparison needs it)."""
    reports = []
    for v in values:
        alpha = m.vidpf.test_index_from_int(v, bits)
        nonce = bytes(rng.integers(0, 256, m.NONCE_SIZE,
                                   dtype="uint8"))
        rand = bytes(rng.integers(0, 256, m.RAND_SIZE, dtype="uint8"))
        (ps, shares) = m.shard(ctx, (alpha, True), nonce, rand)
        reports.append((nonce, ps, shares))
    return reports


def strip_wall(records):
    """Epoch records minus wall-clock stamps (the bit-identity
    comparison target: everything except timing — compile accounting
    is timing too: a resumed run recomputes fewer rounds)."""
    out = []
    for rec in records:
        rec = dict(rec)
        for key in ("wall_s", "compile_ms", "inline_compiles"):
            rec.pop(key, None)
        out.append(rec)
    return out


def admit_all(svc, tenant, m, reports, expect=None):
    from mastic_tpu.drivers.service import encode_upload

    outcomes = []
    for r in reports:
        outcomes.append(svc.submit(tenant, encode_upload(m, r)))
    if expect is not None:
        bad = [o for o in outcomes if o[0] != expect]
        if bad:
            fail(f"admission to {tenant}: expected {expect}, "
                 f"got {bad[:3]}")
    return outcomes


def fail(msg: str) -> None:
    print(f"serve: FAIL: {msg}", file=sys.stderr, flush=True)
    sys.exit(1)


def drain(svc, snapshot_path=None, deadline=None, status=None) -> None:
    from mastic_tpu.drivers.session import Deadline

    if deadline is None:
        # The drain itself is deadline-bounded (the scheduler's
        # per-epoch deadlines bound each epoch; this bounds the loop).
        deadline = Deadline(3600.0)
    while svc.step():
        # Snapshots are quiescent points: with the overlapped
        # executor armed, writing one mid-window would force-drain
        # the in-flight rounds every quantum — snapshot only when
        # nothing is staged (serial mode: every quantum, as before).
        if snapshot_path and svc.inflight_rounds() == 0:
            write_snapshot(svc, snapshot_path)
        publish_status(status, svc)
        if deadline.expired():
            fail("drain deadline expired with epochs still queued")
    publish_status(status, svc)


def start_status(port):
    """The --status-port surface, or None when the flag is absent.
    Port 0 binds an ephemeral port (server.port has the real one)."""
    if port is None:
        return None
    from mastic_tpu.obs.statusz import StatusServer

    return StatusServer(port=port).start()


def publish_status(status, svc) -> None:
    """One scheduler quantum's snapshot to the status server — the
    single-threaded scheduler's only contact with the server thread
    (snapshot-under-lock; the server never touches `svc`)."""
    if status is not None:
        status.publish(svc.metrics())


def check_status_endpoints(status) -> None:
    """Self-fetch /metrics, /statusz and /varz over real HTTP and
    assert the series the acceptance criteria name are present (the
    `make obs-smoke` gate's teeth)."""
    import urllib.request

    def get(path: str) -> bytes:
        url = f"http://127.0.0.1:{status.port}{path}"
        with urllib.request.urlopen(url, timeout=10) as resp:
            if resp.status != 200:
                fail(f"GET {path} -> {resp.status}")
            return resp.read()

    metrics = get("/metrics").decode()
    for needle in (
            'mastic_reports_admitted_total{tenant="count"}',
            'mastic_reports_quarantined_total{tenant="count"',
            'mastic_reports_shed_total{tenant="flood"',
            'mastic_rounds_total{tenant="count"}',
            'mastic_session_retries_total{tenant="count"}',
            "mastic_chunk_phase_ms_bucket",
            "mastic_epochs_total{",
            "mastic_round_wall_ms_bucket"):
        if needle not in metrics:
            fail(f"/metrics missing expected series {needle!r}")
    statusz = get("/statusz").decode()
    for needle in ("tenant count", "occupancy:", "counters:"):
        if needle not in statusz:
            fail(f"/statusz missing {needle!r}")
    varz = json.loads(get("/varz"))
    for key in ("metrics", "trace", "service"):
        if key not in varz:
            fail(f"/varz missing {key!r}")
    if "count" not in varz["service"].get("tenants", {}):
        fail("/varz service snapshot has no tenants")


def run_upload_window(args, svc, status, wal=None):
    """The HTTP-ingest window (ISSUE 11, `mastic_tpu/net/ingest.py`):
    serve the DAP-shaped upload endpoint for `--upload-window`
    seconds — or until a client POSTs the admin drain control — then
    cut every tenant's buffered pages into epochs and fall through to
    the normal drain.

    Plane separation: handler threads only admit (`submit()` is the
    r15 thread-safe seam) and ENQUEUE — epoch cuts and snapshots
    execute here, on this thread, which owns the whole scheduler
    plane (the CC001 pass holds the tree to exactly this split).
    Durability (ISSUE 18): with `--snapshot` a WAL sits under
    admission — each handler's 2xx waits only for its record's
    (group-committed) fsync, not a full snapshot, so a client holding
    an ack can never lose that report to a kill -9; an un-acked
    upload is the client's to retry (the DAP upload contract).  The
    snapshot-before-ack ticket loop this replaces survives only as
    the compaction trigger: this thread snapshots PERIODICALLY
    (`--snapshot-every`) and truncates the WAL segments the snapshot
    covers — `tools/loadgen.py --smoke`'s mid-upload crash drill and
    `--wal-drill` drive the kill/--resume pair."""
    from mastic_tpu.drivers.session import Deadline
    from mastic_tpu.net.ingest import UploadFront

    front = UploadFront(
        svc, port=args.upload_port, admin=True,
        injector=svc.injector,
        persist=(wal.append_report if wal is not None
                 else None)).start()
    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"upload_port": front.port}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, args.port_file)
        fsync_dir(os.path.dirname(args.port_file))

    def compact() -> None:
        # Covered-seq FIRST: anything appended while to_bytes runs
        # is not provably in the snapshot, so it stays replayable.
        seq = wal.tail_seq()
        digest = write_snapshot(svc, args.snapshot)
        wal.mark_covered(seq, digest)

    def cut_epoch(tenant: str) -> None:
        if wal is not None:
            # Log the cut before executing it: a crash between the
            # two replays the same cut over the same reports.
            wal.append_epoch_cut(tenant)
        svc.begin_epoch(tenant)

    next_compact = time.monotonic() + args.snapshot_every
    deadline = Deadline(args.upload_window)
    while not deadline.expired():
        drain_now = front.drain_requested.wait(0.02)
        for tenant in front.pop_epoch_requests():
            cut_epoch(tenant)
        if wal is not None and time.monotonic() >= next_compact:
            compact()
            next_compact = time.monotonic() + args.snapshot_every
        publish_status(status, svc)
        if drain_now:
            break
    front.stop()
    for tenant in front.pop_epoch_requests():
        cut_epoch(tenant)
    for name in list(svc.tenants):
        cut_epoch(name)
    if wal is not None:
        compact()
    elif args.snapshot:
        write_snapshot(svc, args.snapshot)
    return front.port


def fsync_dir(path: str) -> None:
    from mastic_tpu.drivers import wal as wal_mod

    wal_mod.fsync_dir(path or ".")


def write_snapshot(svc, path: str) -> str:
    """Crash-safe snapshot write — the full tmp → fsync(file) →
    os.replace → fsync(dir) sequence (RB006's required idiom: rename
    alone can land with the bytes still in the page cache).  Returns
    the SHA-256 hexdigest of the snapshot bytes: the WAL's covered
    marker records it, and recovery re-verifies it before trusting
    the marker over replay."""
    data = svc.to_bytes()
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        # mastic-allow: SF004 — the snapshot is the durable
        # crash-resume medium and MUST carry the tenant key bindings
        # (the resumed process re-derives nothing); the trust
        # boundary is filesystem permissions on the operator's
        # --snapshot path, not the codec layer
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path))
    return hashlib.sha256(data).hexdigest()


def main() -> None:
    parser = argparse.ArgumentParser(
        description="long-lived collector service driver "
                    "(USAGE.md 'Collector service')")
    parser.add_argument("--bits", type=int, default=2,
                        help="tree depth of the heavy-hitters tenant")
    parser.add_argument("--reports", type=int, default=6,
                        help="uploads per tenant per epoch")
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--page-size", type=int, default=4)
    parser.add_argument("--chunk-size", type=int, default=None)
    parser.add_argument("--mesh", type=int, default=0)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--snapshot", type=str, default=None)
    parser.add_argument("--resume", action="store_true")
    parser.add_argument("--smoke", action="store_true",
                        help="the serve-smoke robustness gate")
    parser.add_argument("--overlap-drill", action="store_true",
                        help="the overlapped-epoch drill: concurrent "
                             "submit burst against the ingest front, "
                             "then a kill-9 + --resume pair with the "
                             "overlapped executor armed (part of "
                             "`make serve-smoke`)")
    parser.add_argument("--soak", type=float, default=0.0,
                        help="unattended soak for SECONDS "
                             "(chip-session cell)")
    parser.add_argument("--chaos-drill", type=int, default=None,
                        metavar="SEED",
                        help="seeded network-chaos campaign (ISSUE "
                             "14): full two-party collections over "
                             "TCP+mTLS standalone parties "
                             "(tools/party.py) under a randomized "
                             "conn_drop/partition/tls_handshake/"
                             "slow_loris schedule — bit-identity vs "
                             "the loopback path, every injected "
                             "fault recovered and attributed "
                             "(USAGE.md 'Transport security')")
    parser.add_argument("--chaos-seeds", type=int, default=3,
                        help="distinct chaos schedules to run, "
                             "seeds SEED..SEED+N-1 (default 3)")
    parser.add_argument("--wal", type=str, default=None,
                        help="directory of the durable admission WAL "
                             "(ISSUE 18; default <snapshot>.wal — "
                             "armed whenever --snapshot and "
                             "--upload-port are both set; USAGE.md "
                             "'Durability')")
    parser.add_argument("--snapshot-every", type=float, default=5.0,
                        help="seconds between periodic compaction "
                             "snapshots while the upload window is "
                             "open (the WAL subsumed per-ack "
                             "snapshots)")
    parser.add_argument("--wal-drill", type=int, default=None,
                        metavar="SEED",
                        help="the disk-fault leg of the seeded chaos "
                             "campaign (ISSUE 18): kill -9 at every "
                             "WAL checkpoint plus randomized kill/"
                             "torn-tail/ENOSPC schedules over the "
                             "HTTP ingest path — each must recover "
                             "bit-identical with zero lost acked "
                             "reports and zero duplicates (`make "
                             "wal-smoke`)")
    parser.add_argument("--wal-seeds", type=int, default=3,
                        help="randomized WAL fault schedules to run, "
                             "seeds SEED..SEED+N-1 (default 3)")
    parser.add_argument("--status-port", type=int, default=None,
                        help="serve /metrics, /statusz and /varz on "
                             "127.0.0.1:PORT (0 = ephemeral; USAGE.md "
                             "'Observability')")
    parser.add_argument("--upload-port", type=int, default=None,
                        help="serve the DAP-shaped HTTP upload "
                             "endpoint (PUT /v1/tenants/{id}/reports) "
                             "on 127.0.0.1:PORT for --upload-window "
                             "seconds before cutting epochs and "
                             "draining (0 = ephemeral; USAGE.md "
                             "'Network front')")
    parser.add_argument("--upload-window", type=float, default=30.0,
                        help="seconds the upload endpoint accepts "
                             "reports (a client POST to "
                             "/v1/admin/drain closes it early)")
    parser.add_argument("--port-file", type=str, default=None,
                        help="write the bound upload port as JSON to "
                             "this path (atomic rename) — how a "
                             "driver finds an ephemeral --upload-port "
                             "0")
    parser.add_argument("--overlap", type=int, default=None,
                        help="keep up to K tenants' rounds in flight "
                             "(overlapped epoch executor; sets "
                             "MASTIC_SERVICE_OVERLAP — <2 = the "
                             "serial round-robin scheduler)")
    parser.add_argument("--ingest-threads", type=int, default=None,
                        help="decode-validate admissions on this "
                             "many worker threads behind a bounded "
                             "queue (concurrent ingest front; sets "
                             "MASTIC_SERVICE_INGEST_THREADS — 0 = "
                             "in-process admission)")
    parser.add_argument("--artifact-dir", type=str, default=None,
                        help="AOT artifact store (tools/bake.py) — "
                             "preloaded at startup and on tenant "
                             "admission so rounds never trace "
                             "(USAGE.md 'AOT artifacts'; equivalent "
                             "to MASTIC_ARTIFACT_DIR)")
    parser.add_argument("--out", type=str, default=None)
    args = parser.parse_args()

    if args.resume and not args.snapshot:
        parser.error("--resume needs --snapshot PATH")
    # argv-time environment pinning (tools/envpin.py): these writes
    # happen strictly before any thread or the jax import exists.
    from tools import envpin

    if args.artifact_dir:
        # The env lever is the one seam every runner reads
        # (drivers/artifacts.store_from_env); the flag just sets it.
        envpin.pin("MASTIC_ARTIFACT_DIR", args.artifact_dir)
    if args.overlap is not None:
        envpin.pin("MASTIC_SERVICE_OVERLAP", str(args.overlap))
    if args.ingest_threads is not None:
        envpin.pin("MASTIC_SERVICE_INGEST_THREADS",
                   str(args.ingest_threads))
    if args.mesh:
        envpin.force_host_devices(args.mesh)

    import numpy as np
    import jax

    requested = os.environ.get("JAX_PLATFORMS", "").strip()
    if requested and "axon" not in requested.split(","):
        jax.config.update("jax_platforms", requested)

    mesh = None
    if args.mesh:
        from mastic_tpu.parallel import make_mesh
        mesh = make_mesh(args.mesh, nodes_axis=1)

    if args.smoke:
        run_smoke(args, mesh, status=start_status(args.status_port))
        return
    if args.overlap_drill:
        run_overlap_drill(args)
        return
    if args.chaos_drill is not None:
        run_chaos_drill(args)
        return
    if args.wal_drill is not None:
        run_wal_drill(args)
        return

    from mastic_tpu.drivers.service import (CollectorService,
                                            ServiceConfig, TenantSpec)
    from mastic_tpu.mastic import MasticCount

    t_start = time.time()
    bits = args.bits
    m_count = MasticCount(bits)
    m_attr = MasticCount(8)
    rng = np.random.default_rng(args.seed)
    # Deterministic keys: the resumed process must rebuild the same
    # tenant bindings the snapshot header carries.
    vk_count = bytes(rng.integers(0, 256, m_count.VERIFY_KEY_SIZE,
                                  dtype="uint8"))
    vk_attr = bytes(rng.integers(0, 256, m_attr.VERIFY_KEY_SIZE,
                                 dtype="uint8"))
    threshold = max(2, int(args.reports * 0.4))
    tenants = [
        TenantSpec(name="count",
                   spec={"class": "MasticCount", "args": [bits]},
                   ctx=b"serve count", verify_key=vk_count,
                   thresholds={"default": threshold},
                   chunk_size=args.chunk_size),
        TenantSpec(name="attrs",
                   spec={"class": "MasticCount", "args": [8]},
                   ctx=b"serve attrs", verify_key=vk_attr,
                   mode="attribute_metrics",
                   attributes=["checkout.html", "landing.html"],
                   chunk_size=args.chunk_size),
    ]
    config = ServiceConfig.from_env()
    config.page_size = args.page_size

    snap_sha256 = None
    if args.resume:
        with open(args.snapshot, "rb") as f:
            snap_bytes = f.read()
        snap_sha256 = hashlib.sha256(snap_bytes).hexdigest()
        svc = CollectorService.from_bytes(snap_bytes, config=config,
                                          mesh=mesh)
    else:
        svc = CollectorService(tenants, config=config, mesh=mesh)

    # The durable admission log (ISSUE 18): armed whenever the HTTP
    # ingest plane and a snapshot path are both configured.  On
    # --resume, recovery replays every record the restored snapshot
    # does not cover (verified by digest) BEFORE the window reopens.
    wal = None
    wal_recovery = None
    if args.upload_port is not None and args.snapshot:
        from mastic_tpu.drivers.wal import AdmissionWal

        wal = AdmissionWal(args.wal or (args.snapshot + ".wal"),
                           injector=svc.injector,
                           fresh=not args.resume)
        if args.resume:
            wal_recovery = wal.recover(svc,
                                       snapshot_sha256=snap_sha256)
        else:
            # Seed the compaction baseline: the snapshot file exists
            # from boot, so a crash at ANY later point resumes from
            # snapshot + WAL replay, never from nothing.
            wal.mark_covered(wal.tail_seq(),
                             write_snapshot(svc, args.snapshot))
    status = start_status(args.status_port)
    publish_status(status, svc)

    hot = args.reports // 2
    count_values = [0] * hot + [2 ** bits - 1] * (args.reports - hot)
    from mastic_tpu.drivers.attribute_metrics import hash_attribute
    attr_alpha = hash_attribute(m_attr, "checkout.html")
    attr_int = int("".join("1" if b else "0" for b in attr_alpha), 2)
    attr_values = [attr_int] * max(1, args.reports - 2) \
        + [0] * min(2, args.reports)

    if args.soak:
        run_soak(args, svc, m_count, count_values, rng, t_start,
                 status=status)
        return

    upload_port = None
    if args.upload_port is not None:
        # HTTP ingest replaces the synthetic admission loop entirely
        # (on --resume too: the reopened window is where a client
        # retries the uploads the crashed process never acked).
        upload_port = run_upload_window(args, svc, status, wal=wal)
    elif not args.resume:
        for _ in range(args.epochs):
            reports = build_reports(m_count, b"serve count", rng,
                                    count_values, bits)
            admit_all(svc, "count", m_count, reports)
            svc.begin_epoch("count")
            reports = build_reports(m_attr, b"serve attrs", rng,
                                    attr_values, 8)
            admit_all(svc, "attrs", m_attr, reports)
            svc.begin_epoch("attrs")
        if args.snapshot:
            write_snapshot(svc, args.snapshot)
    drain(svc, snapshot_path=args.snapshot, status=status)
    if args.snapshot:
        digest = write_snapshot(svc, args.snapshot)
        if wal is not None:
            wal.mark_covered(wal.tail_seq(), digest)
            wal.close()

    metrics = svc.metrics()
    out = {
        "mode": "resume" if args.resume else "serve",
        "upload_port": upload_port,
        "platform": jax.devices()[0].platform,
        "bits": bits, "reports": args.reports,
        "epochs": args.epochs,
        "mesh_devices": args.mesh or 1,
        "status_port": status.port if status is not None else None,
        "artifact_dir": args.artifact_dir,
        "wall_seconds": round(time.time() - t_start, 1),
        "results": {name: strip_wall(t["epochs"])
                    for (name, t) in metrics["tenants"].items()},
        "metrics": metrics,
        "ok": True,
    }
    if wal is not None:
        out["wal"] = wal.stats()
        if wal_recovery is not None:
            out["wal"]["recovery"] = wal_recovery
            out["wal"]["replayed_records"] = \
                wal_recovery["replayed"]
            out["wal"]["recovery_wall_ms"] = \
                wal_recovery["recovery_wall_ms"]
    line = json.dumps(out)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if os.environ.get("MASTIC_HARD_EXIT"):
        # Drill children (--wal-drill spawns ~a dozen of these): the
        # work is done and durably on disk — skip the interpreter's
        # atexit teardown, where jaxlib's clear_backends segfaults
        # flakily on CPU and would be misread as a lost-ack failure.
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)


def run_overlap_drill(args) -> None:
    """The overlapped-epoch drill (`make serve-smoke`, ISSUE 10):

    1. concurrent submit burst — 4 client threads stream uploads into
       2 tenants through a live ingest front (3 workers, a 16-deep
       bounded queue): every submission must be accounted exactly
       once (admitted + shed == submitted, the buffered pages hold
       exactly the admitted blobs — zero lost, zero duplicated), and
       the burst must never block on the scheduler;
    2. kill-9 + --resume — the default two-tenant serve scenario runs
       as child processes with `--overlap 2 --ingest-threads 2`: a
       clean run, a run hard-killed mid-drain by the injector at the
       scheduler's epoch_round checkpoint, and a `--resume` from the
       killed run's snapshot, whose results must equal the clean
       run's bit for bit.
    """
    import subprocess
    import tempfile
    import threading

    import numpy as np

    from mastic_tpu.drivers import faults
    from mastic_tpu.drivers.service import (QUEUED, SHED,
                                            CollectorService,
                                            ServiceConfig, TenantSpec,
                                            encode_upload)
    from mastic_tpu.mastic import MasticCount

    t_start = time.time()
    bits = 2
    m = MasticCount(bits)
    rng = np.random.default_rng(args.seed)
    vk = bytes(rng.integers(0, 256, m.VERIFY_KEY_SIZE, dtype="uint8"))
    specs = [
        TenantSpec(name=f"t{i}",
                   spec={"class": "MasticCount", "args": [bits]},
                   ctx=b"drill", verify_key=vk,
                   thresholds={"default": 2}, max_buffered=64)
        for i in range(2)
    ]
    cfg = ServiceConfig(page_size=4, max_buffered=64,
                        shed_policy="reject-newest",
                        overlap=2, ingest_threads=3, ingest_queue=16,
                        epoch_deadline=600.0)
    svc = CollectorService(specs, config=cfg)
    per_thread = 10
    blobs = [encode_upload(m, r)
             for r in build_reports(m, b"drill", rng,
                                    [0] * per_thread, bits)]
    outcomes: list = []
    mu = threading.Lock()

    def feed(tenant: str) -> None:
        got = [svc.submit(tenant, b) for b in blobs]
        with mu:
            outcomes.extend(got)

    threads = [threading.Thread(target=feed, args=(f"t{i % 2}",))
               for i in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    svc.flush_ingest()
    queued = sum(1 for o in outcomes if o[0] == QUEUED)
    shed_at_queue = sum(1 for o in outcomes
                        if o == (SHED, "ingest-queue-full"))
    if queued + shed_at_queue != 4 * per_thread:
        fail(f"burst outcomes unaccounted: {queued} queued + "
             f"{shed_at_queue} queue-shed != {4 * per_thread}")
    mx = svc.metrics()["tenants"]
    landed = 0
    queue_shed_counted = 0
    for name in ("t0", "t1"):
        c = mx[name]["counters"]
        qshed = c["shed_reasons"].get("ingest-queue-full", 0)
        queue_shed_counted += qshed
        landed += c["admitted"] + c["quarantined"] \
            + (c["shed"] - qshed)
        if c["admitted"] != mx[name]["buffered_reports"]:
            fail(f"{name}: admitted {c['admitted']} != buffered "
                 f"{mx[name]['buffered_reports']} (lost/dup pages)")
    if queue_shed_counted != shed_at_queue:
        fail(f"queue-full sheds miscounted: counters say "
             f"{queue_shed_counted}, callers saw {shed_at_queue}")
    if landed + shed_at_queue != 4 * per_thread:
        fail(f"burst accounting: {landed} landed + {shed_at_queue} "
             f"queue-shed != {4 * per_thread}")
    svc.stop_ingest()

    # 2. kill-9 + --resume with overlap + ingest armed, as children.
    tmp = tempfile.mkdtemp(prefix="mastic_overlap_drill_")
    me = os.path.abspath(__file__)

    def run_child(extra, fault=None, expect_rc=0):
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        env.pop("MASTIC_FAULTS", None)
        if fault is not None:
            env["MASTIC_FAULTS"] = fault
        # All three children run the parser-default seed: the drill
        # needs them deterministic relative to EACH OTHER, nothing
        # else (and argv stays free of anything seed-derived).
        cmd = [sys.executable, me, "--reports", "4",
               "--overlap", "2", "--ingest-threads", "2"] + extra
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=1800, env=env)
        if proc.returncode != expect_rc:
            fail(f"drill child {extra} rc={proc.returncode} "
                 f"(wanted {expect_rc}): {proc.stderr[-1500:]}")
        return proc

    clean = run_child(["--snapshot", os.path.join(tmp, "clean.snap")])
    clean_out = json.loads(clean.stdout.strip().splitlines()[-1])
    snap = os.path.join(tmp, "killed.snap")
    run_child(["--snapshot", snap],
              fault="kill:party=collector:step=epoch_round:nth=2",
              expect_rc=faults.KILL_EXIT_CODE)
    if not os.path.exists(snap):
        fail("killed child left no snapshot")
    resumed = run_child(["--snapshot", snap, "--resume"])
    resumed_out = json.loads(resumed.stdout.strip().splitlines()[-1])
    if resumed_out["results"] != clean_out["results"]:
        fail(f"overlap kill-9 resume diverged: "
             f"{resumed_out['results']} != {clean_out['results']}")

    out = {
        "mode": "overlap-drill",
        "burst_submitted": 4 * per_thread,
        "burst_admitted": landed,
        "burst_queue_shed": shed_at_queue,
        "kill9_resume_bit_identical": True,
        "wall_seconds": round(time.time() - t_start, 1),
        "ok": True,
    }
    line = json.dumps(out)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


def run_chaos_drill(args) -> None:
    """The seeded network-chaos campaign (`--chaos-drill SEED`):

    1. loopback baseline — the spawn-path AggregationSession walks a
       full heavy-hitters collection; its per-round results, accept
       masks and raw share bytes are the bit-identity target;
    2. TCP+mTLS pair — two standalone `tools/party.py serve`
       processes on distinct listen addresses (certs minted by
       tools/certs.py), collector in connect mode; must reproduce
       the loopback collection byte for byte;
    3. chaos runs — for each of `--chaos-seeds` seeds, a fresh party
       pair runs the same collection under a seeded random schedule
       of conn_drop / partition / slow_loris / tls_handshake-delay
       faults.  Every run must be bit-identical, every injected rule
       must have fired, every recovery must be attributed
       (RoundMetrics.reconnects / replayed_frames and the
       mastic_session_reconnects_total / mastic_frames_replayed_total
       series nonzero), and zero uploads lost or duplicated
       (quarantine empty, accept masks identical).
    """
    import random as random_mod
    import subprocess
    import tempfile

    import numpy as np

    from mastic_tpu.drivers.parties import AggregationSession
    from mastic_tpu.drivers.session import SessionConfig
    from mastic_tpu.net.transport import TlsConfig
    from mastic_tpu.obs.registry import get_registry
    from tools import certs as certs_mod

    t_start = time.time()
    bits = 2
    from mastic_tpu.mastic import MasticCount

    m = MasticCount(bits)
    spec = {"class": "MasticCount", "args": [bits]}
    ctx = b"chaos drill"
    rng = np.random.default_rng(args.seed)
    vk = bytes(rng.integers(0, 256, m.VERIFY_KEY_SIZE, dtype="uint8"))
    reports = build_reports(m, ctx, rng, [0, 0, 3, 3], bits)
    thresholds = {"default": 2}
    cfg = SessionConfig(connect_timeout=30.0, exchange_timeout=240.0,
                        ack_timeout=60.0, round_deadline=600.0,
                        shutdown_timeout=5.0, retries=2, backoff=0.2)

    tmp = tempfile.mkdtemp(prefix="mastic_chaos_")
    certdir = certs_mod.mint_party_set(os.path.join(tmp, "certs"))
    tls = TlsConfig(str(certdir / "collector.pem"),
                    str(certdir / "collector.key"),
                    str(certdir / "ca.pem"))

    def walk(sess):
        """Full threshold-pruned heavy-hitters collection; returns
        (hitters, per-round records, metrics records)."""
        from mastic_tpu.drivers.heavy_hitters import get_threshold

        rounds = []
        metrics: list = []
        try:
            sess.upload(reports)
            prefixes = [(False,), (True,)]
            for level in range(bits):
                param = (level, tuple(prefixes), level == 0)
                (result, accept, shares) = sess.round(
                    param, metrics_out=metrics)
                rounds.append((list(result),
                               [bool(x) for x in accept], shares))
                survivors = [p for (p, c) in zip(prefixes, result)
                             if c >= get_threshold(thresholds, p)]
                prefixes = (survivors if level == bits - 1 else
                            [p + (b,) for p in survivors
                             for b in (False, True)])
        finally:
            sess.close()
        return (sorted(prefixes), rounds, metrics)

    def spawn_pair(tag):
        """Two standalone mTLS parties on distinct listen
        addresses; returns (procs, connect map)."""
        pdir = os.path.join(tmp, tag)
        os.makedirs(pdir, exist_ok=True)
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        env.pop("MASTIC_FAULTS", None)
        procs = []
        for (name, extra) in (("leader",
                               ["--peer-listen", "127.0.0.1:0"]),
                              ("helper", [])):
            procs.append(subprocess.Popen(
                [sys.executable,
                 os.path.join(os.path.dirname(
                     os.path.abspath(__file__)), "party.py"),
                 "serve", "--listen", "127.0.0.1:0",
                 "--tls-cert", str(certdir / f"{name}.pem"),
                 "--tls-key", str(certdir / f"{name}.key"),
                 "--tls-ca", str(certdir / "ca.pem"),
                 "--port-file", os.path.join(pdir, f"{name}.ports")]
                + extra,
                env=env, stdout=sys.stderr, stderr=sys.stderr))

        def ports(name):
            path = os.path.join(pdir, f"{name}.ports")
            give_up = time.monotonic() + 120.0
            while time.monotonic() < give_up:
                try:
                    with open(path) as f:
                        return json.load(f)
                except (FileNotFoundError, ValueError):
                    time.sleep(0.1)
            fail(f"party {name} never published its ports ({tag})")

        (lp, hp) = (ports("leader"), ports("helper"))
        connect = {"leader": ("127.0.0.1", lp["listen"]),
                   "helper": ("127.0.0.1", hp["listen"]),
                   "leader_peer": ("127.0.0.1", lp["peer_listen"])}
        return (procs, connect)

    def reap(procs):
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def chaos_schedule(seed):
        """A seeded random fault schedule, all rules addressed to the
        collector (whose injector we can audit after the run): at
        least one hard drop (so reconnect-and-replay provably runs),
        a guaranteed-firing tls_handshake delay, and a random tail of
        partitions / extra drops / stalled writers."""
        r = random_mod.Random(seed)
        rules = [
            f"conn_drop:party=collector:step=upload"
            f":nth={r.randint(1, 2)}",
            f"delay:party=collector:step=tls_handshake:nth=1"
            f":delay={r.uniform(0.1, 0.3):.2f}",
        ]
        extras = r.randint(1, 2)
        for _ in range(extras):
            pick = r.choice(("partition", "conn_drop", "slow_loris"))
            if pick == "partition":
                rules.append(
                    f"partition:party=collector:step=agg_param"
                    f":nth={r.randint(1, 4)}"
                    f":delay={r.uniform(0.3, 0.8):.2f}")
            elif pick == "conn_drop":
                rules.append(
                    f"conn_drop:party=collector:step=agg_param"
                    f":nth={r.randint(1, 4)}")
            else:
                rules.append(
                    f"slow_loris:party=collector:step=upload"
                    f":nth={r.randint(1, 2)}"
                    f":delay={r.uniform(0.2, 0.5):.2f}")
        # Distinct (step, nth) per rule — two rules on one occurrence
        # would leave the later one unfired and the audit ambiguous.
        seen = set()
        out = []
        for rule in rules:
            key = tuple(sorted(
                kv for kv in rule.split(":")
                if kv.startswith(("step=", "nth="))))
            if key in seen:
                continue
            seen.add(key)
            out.append(rule)
        return ";".join(out)

    # 1. loopback baseline (the spawn path).
    base = walk(AggregationSession(m, spec, ctx, vk, config=cfg))
    print(f"chaos: loopback baseline hitters={base[0]}",
          file=sys.stderr, flush=True)

    # 2. undisturbed TCP+mTLS pair on distinct listen addresses.
    (procs, connect) = spawn_pair("undisturbed")
    try:
        tcp = walk(AggregationSession(m, spec, ctx, vk, config=cfg,
                                      connect=connect, tls=tls))
    finally:
        reap(procs)
    if tcp[:2] != base[:2]:
        fail(f"TCP+mTLS pair diverged from loopback: {tcp[:2]} != "
             f"{base[:2]}")
    print("chaos: TCP+mTLS pair bit-identical to loopback",
          file=sys.stderr, flush=True)

    # 3. the seeded chaos campaign.
    seeds = list(range(args.chaos_drill,
                       args.chaos_drill + args.chaos_seeds))
    runs = []
    for seed in seeds:
        spec_str = chaos_schedule(seed)
        drops = sum(1 for r in spec_str.split(";")
                    if r.startswith(("conn_drop", "partition")))
        (procs, connect) = spawn_pair(f"seed{seed}")
        sess = AggregationSession(m, spec, ctx, vk, config=cfg,
                                  faults_spec=spec_str,
                                  connect=connect, tls=tls)
        try:
            chaos = walk(sess)
            rel = sess.coll.reliability_counters()
            unfired = [f"{r.action}:{r.step}:nth={r.nth}"
                       for r in sess.coll.injector.rules
                       if not r.fired]
            quarantined = dict(sess.coll.quarantine)
        finally:
            reap(procs)
        if chaos[:2] != base[:2]:
            fail(f"seed {seed}: chaos run diverged: {chaos[:2]} != "
                 f"{base[:2]}")
        if unfired:
            fail(f"seed {seed}: injected rules never fired: "
                 f"{unfired} (schedule {spec_str})")
        if rel["reconnects"] < drops:
            fail(f"seed {seed}: {drops} drops/partitions injected "
                 f"but only {rel['reconnects']} reconnects counted")
        if rel["replayed_frames"] < 1:
            fail(f"seed {seed}: no frames replayed despite "
                 f"{drops} drops — recovery path not exercised")
        if quarantined:
            fail(f"seed {seed}: uploads quarantined under chaos: "
                 f"{quarantined}")
        last = chaos[2][-1]
        if last.reconnects < drops or last.replayed_frames < 1:
            fail(f"seed {seed}: RoundMetrics missing recovery "
                 f"attribution: reconnects={last.reconnects} "
                 f"replayed_frames={last.replayed_frames}")
        print(f"chaos: seed {seed} ok — schedule [{spec_str}] "
              f"reconnects={rel['reconnects']} "
              f"replayed={rel['replayed_frames']}",
              file=sys.stderr, flush=True)
        runs.append({"seed": seed, "schedule": spec_str,
                     "reconnects": rel["reconnects"],
                     "replayed_frames": rel["replayed_frames"]})

    reg = get_registry()
    if not reg.counter("mastic_session_reconnects_total",
                       tenant="").value():
        fail("mastic_session_reconnects_total never incremented")
    if not reg.counter("mastic_frames_replayed_total",
                       tenant="").value():
        fail("mastic_frames_replayed_total never incremented")

    out = {
        "mode": "chaos-drill",
        "seeds": seeds,
        "tcp_mtls_bit_identical": True,
        "runs": runs,
        "hitters": [[bool(b) for b in p] for p in base[0]],
        "wall_seconds": round(time.time() - t_start, 1),
        "ok": True,
    }
    line = json.dumps(out)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


def run_wal_drill(args) -> None:
    """The disk-fault leg of the seeded chaos campaign (ISSUE 18,
    `make wal-smoke`): drive the HTTP ingest path with the WAL armed
    and (a) kill -9 at EVERY WAL checkpoint — `wal_append` (before
    the record's write), `wal_fsync` (written, not yet durable),
    `wal_ack` (durable, not yet acked) — then (b) `--wal-seeds`
    randomized schedules drawn from the disk-fault vocabulary
    (kill-at-checkpoint, short_write torn tail, ENOSPC brownout).
    Every schedule must end bit-identical to the undisturbed run
    with EXACTLY the clean run's reports admitted: zero acked-but-
    lost, zero duplicates.  Recovery must attribute itself (replayed
    / torn_tail counts and wall time in the resumed child's JSON)."""
    import random
    import shutil
    import subprocess
    import tempfile
    from http.client import HTTPConnection

    import numpy as np

    from mastic_tpu.drivers import faults
    from mastic_tpu.drivers.service import encode_upload
    from mastic_tpu.mastic import MasticCount
    from mastic_tpu.net.ingest import MEDIA_TYPE

    t_start = time.time()
    serve_py = os.path.abspath(__file__)
    bits = 2
    m = MasticCount(bits)
    rng = np.random.default_rng(args.wal_drill)
    blobs = []
    for value in [0, 0, 0, 3, 3, 3]:
        alpha = m.vidpf.test_index_from_int(value, bits)
        nonce = bytes(rng.integers(0, 256, m.NONCE_SIZE,
                                   dtype="uint8"))
        rand = bytes(rng.integers(0, 256, m.RAND_SIZE,
                                  dtype="uint8"))
        (ps, shares) = m.shard(b"serve count", (alpha, True), nonce,
                               rand)
        blobs.append(encode_upload(m, (nonce, ps, shares)))
    tmp = tempfile.mkdtemp(prefix="mastic-wal-drill-")

    def spawn(tag, fault=None, resume=False, snap_tag=None):
        pf = os.path.join(tmp, f"{tag}.port")
        snap = os.path.join(tmp, f"{snap_tag or tag}.snap")
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONFAULTHANDLER": "1",
               "MASTIC_HARD_EXIT": "1"}
        env.pop("MASTIC_FAULTS", None)
        env.pop("MASTIC_NET_SHAPE", None)
        # The campaign spawns ~a dozen collector children that all
        # lower the same tiny programs — share one persistent compile
        # cache so only the first child pays the XLA lowering.  A
        # child running under a fault (it may die by kill-9) gets a
        # throwaway COPY of the warm cache instead: jax's cache
        # writes are not atomic, so a kill mid-write plants a torn
        # entry that heap-corrupts the next reader.
        shared_cache = os.path.join(tmp, "jaxcache")
        if fault is None:
            cache = shared_cache
        else:
            cache = os.path.join(tmp, f"jaxcache-{tag}")
            if os.path.isdir(shared_cache) \
                    and not os.path.isdir(cache):
                shutil.copytree(shared_cache, cache)
        os.makedirs(cache, exist_ok=True)
        env["JAX_COMPILATION_CACHE_DIR"] = cache
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                       "0.5")
        if fault is not None:
            env["MASTIC_FAULTS"] = fault
        cmd = [sys.executable, serve_py, "--reports", "6", "--bits",
               str(bits), "--page-size", "2", "--upload-port", "0",
               "--upload-window", "120", "--port-file", pf,
               "--snapshot", snap]
        if resume:
            cmd.append("--resume")
        proc = subprocess.Popen(cmd, env=env, text=True,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE)
        return (proc, pf, snap)

    def wait_port(path, deadline_s=120.0):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            try:
                with open(path) as f:
                    return json.load(f)["upload_port"]
            except (OSError, ValueError, KeyError):
                time.sleep(0.05)
        fail(f"wal drill: no port file at {path}")

    def put_one(port, blob):
        """One PUT; returns (status_code, retry_after) — status None
        when the collector died mid-request."""
        try:
            conn = HTTPConnection("127.0.0.1", port, timeout=30)
            conn.request("PUT", "/v1/tenants/count/reports",
                         body=blob,
                         headers={"Content-Type": MEDIA_TYPE})
            resp = conn.getresponse()
            resp.read()
            retry_after = resp.getheader("Retry-After")
            conn.close()
            return (resp.status, retry_after)
        except OSError:
            return (None, None)

    def put_all(port, send, brownouts=None):
        """PUT each (index, blob); 503s honor Retry-After and retry
        in place (the brownout contract); a dead socket stops the
        loop — the tail is the client's to retry after resume."""
        acked = []
        for (i, blob) in send:
            while True:
                (code, retry_after) = put_one(port, blob)
                if code == 503:
                    if brownouts is not None:
                        brownouts.append(i)
                        if retry_after is None:
                            fail(f"wal drill: 503 without "
                                 f"Retry-After on upload {i}")
                    time.sleep(min(float(retry_after or 1), 2.0))
                    continue
                break
            if code in (201, 202):
                acked.append(i)
            elif code is None:
                break
            else:
                fail(f"wal drill: upload {i} got {code}")
        return acked

    def cut_and_drain(port):
        conn = HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/v1/tenants/count/epoch",
                     headers={"Content-Length": "0"})
        conn.getresponse().read()
        conn.request("POST", "/v1/admin/drain",
                     headers={"Content-Length": "0"})
        conn.getresponse().read()
        conn.close()

    def finish(proc, tag, expect_rc=0):
        (out, err) = proc.communicate(timeout=1500)
        if proc.returncode != expect_rc:
            fail(f"wal drill {tag}: rc={proc.returncode} (wanted "
             f"{expect_rc}): {err[-1500:]}")
        if expect_rc != 0:
            return {}
        return json.loads(out.strip().splitlines()[-1])

    def admitted_total(result):
        return result["metrics"]["tenants"]["count"]["counters"][
            "admitted"]

    def run_schedule(tag, fault, lethal):
        """One campaign entry: run under `fault`; if `lethal`, the
        child must die with the kill exit code and a resumed child
        finishes the collection.  Returns the final run's JSON plus
        the acked bookkeeping."""
        (proc, pf, snap) = spawn(tag, fault=fault)
        port = wait_port(pf)
        brownouts = []
        acked = put_all(port, list(enumerate(blobs)),
                        brownouts=brownouts)
        if not lethal:
            if len(acked) != 6:
                proc.kill()
                fail(f"wal drill {tag}: acked {acked}, wanted all 6")
            cut_and_drain(port)
            return (finish(proc, tag), acked, brownouts, None)
        finish(proc, tag, expect_rc=faults.KILL_EXIT_CODE)
        if os.environ.get("MASTIC_WAL_DRILL_KEEP"):
            pre = os.path.join(tmp, f"{tag}.pre-resume")
            os.makedirs(pre, exist_ok=True)
            shutil.copy(os.path.join(tmp, f"{tag}.snap"), pre)
            shutil.copytree(os.path.join(tmp, f"{tag}.snap.wal"),
                            os.path.join(pre, f"{tag}.snap.wal"),
                            dirs_exist_ok=True)
        (proc, pf2, _s) = spawn(f"{tag}-resumed", resume=True,
                                snap_tag=tag)
        port = wait_port(pf2)
        retries = [(i, blobs[i]) for i in range(6) if i not in acked]
        re_acked = put_all(port, retries)
        if len(re_acked) != len(retries):
            proc.kill()
            fail(f"wal drill {tag}: retries {re_acked} of "
                 f"{[i for (i, _b) in retries]}")
        cut_and_drain(port)
        return (finish(proc, f"{tag}-resumed"), acked + re_acked,
                brownouts, None)

    # Undisturbed baseline.
    (clean, _acked, _b, _r) = run_schedule("clean", None, False)
    clean_admitted = admitted_total(clean)

    runs = []
    # (a) kill -9 at every WAL checkpoint, deterministically.
    for step in ("wal_append", "wal_fsync", "wal_ack"):
        tag = f"kill-{step}"
        fault = f"kill:party=collector:step={step}:nth=4"
        (result, acked, _b, _r) = run_schedule(tag, fault, True)
        if result["results"]["count"] != clean["results"]["count"]:
            print(json.dumps(result), file=sys.stderr, flush=True)
            fail(f"wal drill {tag}: results diverge\n"
                 f"  clean: {clean['results']['count']}\n"
                 f"  {tag}: {result['results']['count']}")
        if admitted_total(result) != clean_admitted:
            fail(f"wal drill {tag}: {admitted_total(result)} "
                 f"admitted, wanted {clean_admitted} (lost or "
                 f"duplicated)")
        wal_info = result.get("wal") or {}
        if "recovery_wall_ms" not in wal_info:
            fail(f"wal drill {tag}: resumed child did not stamp "
                 f"recovery attribution: {wal_info}")
        runs.append({"schedule": fault,
                     "replayed": wal_info.get("replayed_records"),
                     "recovery_wall_ms":
                         wal_info.get("recovery_wall_ms")})

    # (b) seeded randomized disk-fault schedules.
    seeds = list(range(args.wal_drill,
                       args.wal_drill + args.wal_seeds))
    for seed in seeds:
        r = random.Random(seed)
        kind = r.choice(["kill", "kill", "short_write", "enospc"])
        nth = r.randint(2, 5)
        if kind == "kill":
            step = r.choice(["wal_append", "wal_fsync", "wal_ack"])
            fault = f"kill:party=collector:step={step}:nth={nth}"
            lethal = True
        elif kind == "short_write":
            cut = r.randint(1, 24)
            fault = (f"short_write:party=collector:step=wal_append"
                     f":nth={nth}:cut={cut}")
            lethal = True
        else:
            fault = f"enospc:party=collector:step=wal_append:nth={nth}"
            lethal = False
        (result, acked, brownouts, _r2) = run_schedule(
            f"seed-{seed}", fault, lethal)
        if result["results"]["count"] != clean["results"]["count"]:
            fail(f"wal drill seed {seed}: results diverge under "
                 f"[{fault}]\n"
                 f"  clean: {clean['results']['count']}\n"
                 f"  seed-{seed}: {result['results']['count']}")
        if admitted_total(result) != clean_admitted:
            fail(f"wal drill seed {seed}: "
                 f"{admitted_total(result)} admitted, wanted "
                 f"{clean_admitted} under [{fault}] (lost or "
                 f"duplicated)")
        rec = {"seed": seed, "schedule": fault}
        if kind == "enospc":
            if not brownouts:
                fail(f"wal drill seed {seed}: injected ENOSPC but "
                     f"no 503 brownout was observed")
            shed = result["metrics"]["tenants"]["count"][
                "counters"]["shed_reasons"]
            if not shed.get("wal-full"):
                fail(f"wal drill seed {seed}: brownout not "
                     f"attributed as wal-full: {shed}")
            rec["brownouts"] = len(brownouts)
        if kind == "short_write":
            torn = (result.get("wal") or {}).get(
                "recovery", {}).get("torn_tail", 0)
            if not torn:
                fail(f"wal drill seed {seed}: injected torn tail "
                     f"was not counted at recovery: "
                     f"{result.get('wal')}")
            rec["torn_tail"] = torn
        if lethal:
            rec["recovery_wall_ms"] = (result.get("wal") or {}).get(
                "recovery_wall_ms")
        runs.append(rec)
        print(f"wal drill: seed {seed} ok — [{fault}]",
              file=sys.stderr, flush=True)

    shutil.rmtree(tmp, ignore_errors=True)
    out = {
        "mode": "wal-drill",
        "seeds": seeds,
        "checkpoints": ["wal_append", "wal_fsync", "wal_ack"],
        "admitted": clean_admitted,
        "bit_identical": True,
        "runs": runs,
        "wall_seconds": round(time.time() - t_start, 1),
        "ok": True,
    }
    line = json.dumps(out)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


def run_soak(args, svc, m_count, count_values, rng, t_start,
             status=None) -> None:
    """Unattended soak: admit -> epoch -> drain in a loop under one
    deadline; every epoch's output is checked against the expected
    hitters, so a service that degrades mid-soak fails the cell."""
    import jax

    from mastic_tpu.drivers.service import encode_upload
    from mastic_tpu.drivers.session import Deadline

    bits = args.bits
    expected = sorted([[False] * bits, [True] * bits])
    deadline = Deadline(args.soak)
    epochs = 0
    while not deadline.expired():
        reports = build_reports(m_count, b"serve count", rng,
                                count_values, bits)
        for r in reports:
            svc.submit("count", encode_upload(m_count, r))
        svc.begin_epoch("count")
        drain(svc, snapshot_path=args.snapshot, deadline=deadline,
              status=status)
        recs = svc.metrics()["tenants"]["count"]["epochs"]
        if recs and not recs[-1]["truncated"]:
            epochs += 1
            got = sorted(recs[-1]["result"])
            if got != expected:
                fail(f"soak epoch {epochs}: hitters {got} != "
                     f"{expected}")
    counters = svc.metrics()["tenants"]["count"]["counters"]
    out = {
        "mode": "soak",
        "platform": jax.devices()[0].platform,
        "soak_seconds": args.soak,
        "epochs_completed": epochs,
        "rounds": counters["rounds"],
        "wall_seconds": round(time.time() - t_start, 1),
        "counters": counters,
        "ok": epochs >= 1,
    }
    print(json.dumps(out), flush=True)
    if not out["ok"]:
        sys.exit(1)


def run_smoke(args, mesh, status=None) -> None:
    """The serve-smoke gate: one process, every defensive behavior
    demonstrated and asserted (module docstring lists them).  With a
    status server attached (`--status-port`), the three observability
    endpoints are self-fetched over real HTTP mid-run and their
    expected per-tenant series asserted (the obs-smoke gate)."""
    import numpy as np
    import jax

    from mastic_tpu.drivers.service import (ADMITTED, QUARANTINED,
                                            SHED, CollectorService,
                                            ServiceConfig, TenantSpec,
                                            encode_upload)
    from mastic_tpu.mastic import MasticCount

    t_start = time.time()
    rng = np.random.default_rng(args.seed)
    bits = 2
    m = MasticCount(bits)
    m_attr = MasticCount(8)
    vk = bytes(rng.integers(0, 256, m.VERIFY_KEY_SIZE, dtype="uint8"))
    vk_attr = bytes(rng.integers(0, 256, m_attr.VERIFY_KEY_SIZE,
                                 dtype="uint8"))

    def specs():
        return [
            TenantSpec(name="count",
                       spec={"class": "MasticCount", "args": [bits]},
                       ctx=b"smoke count", verify_key=vk,
                       thresholds={"default": 2},
                       chunk_size=args.chunk_size),
            TenantSpec(name="attrs",
                       spec={"class": "MasticCount", "args": [8]},
                       ctx=b"smoke attrs", verify_key=vk_attr,
                       mode="attribute_metrics",
                       attributes=["checkout.html", "landing.html"],
                       chunk_size=args.chunk_size),
            # Overload scratch tenant: tiny quota, never scheduled.
            TenantSpec(name="flood",
                       spec={"class": "MasticCount", "args": [bits]},
                       ctx=b"smoke flood", verify_key=vk,
                       thresholds={"default": 2}, max_buffered=5),
            # Deadline tenant: an already-expired epoch budget, so
            # its epoch degrades to the truncated frontier.
            TenantSpec(name="slow",
                       spec={"class": "MasticCount", "args": [bits]},
                       ctx=b"smoke slow", verify_key=vk,
                       thresholds={"default": 2}, epoch_deadline=0.0),
        ]

    config = ServiceConfig(page_size=3, max_buffered=64,
                           max_pending_epochs=2,
                           shed_policy="reject-newest",
                           quarantine_limit=16,
                           epoch_deadline=600.0)
    svc = CollectorService(specs(), config=config, mesh=mesh)

    # 1. malformed-upload burst: reason-coded quarantine, tenant-
    # attributed; the other tenants are untouched.
    for blob in (b"", b"\x07garbage", b"\xff" * 40):
        (outcome, detail) = svc.submit("count", blob)
        if outcome != QUARANTINED:
            fail(f"malformed blob admitted: {(outcome, detail)}")
    qm = svc.metrics()["tenants"]
    if qm["count"]["counters"]["quarantined"] != 3 \
            or qm["count"]["suspended"] \
            or qm["attrs"]["counters"]["quarantined"] != 0:
        fail(f"quarantine counters wrong: {qm['count']['counters']}")

    # 2. sustained overload against the flood tenant's quota of 5:
    # admission stays bounded, sheds are counted, memory is pages
    # not uploads.
    flood_reports = build_reports(m, b"smoke flood", rng,
                                  [0] * 12, bits)
    outcomes = admit_all(svc, "flood", m, flood_reports)
    admitted = sum(1 for o in outcomes if o[0] == ADMITTED)
    shed = sum(1 for o in outcomes if o[0] == SHED)
    fm = svc.metrics()["tenants"]["flood"]
    if admitted != 5 or shed != 7 \
            or fm["buffered_reports"] != 5 \
            or fm["counters"]["shed_reasons"].get("reject-newest") != 7:
        fail(f"reject-newest overload wrong: admitted={admitted} "
             f"shed={shed} {fm['counters']}")

    # 2b. oldest-epoch-first on a scratch service: the oldest queued
    # epoch is dropped to admit fresh load.  (Fresh spec: the flood
    # tenant above carries its own tighter max_buffered override.)
    svc_old = CollectorService(
        [TenantSpec(name="flood",
                    spec={"class": "MasticCount", "args": [bits]},
                    ctx=b"smoke flood", verify_key=vk,
                    thresholds={"default": 2}, max_buffered=6)],
        config=ServiceConfig(page_size=3,
                             max_pending_epochs=2,
                             shed_policy="oldest-epoch-first",
                             epoch_deadline=600.0))
    admit_all(svc_old, "flood", m,
              build_reports(m, b"smoke flood", rng, [0] * 6, bits),
              expect=ADMITTED)
    first_epoch = svc_old.begin_epoch("flood")
    outcomes = admit_all(svc_old, "flood", m,
                         build_reports(m, b"smoke flood", rng,
                                       [1] * 3, bits),
                         expect=ADMITTED)   # room made by the drop
    om = svc_old.metrics()["tenants"]["flood"]
    if first_epoch != 0 or om["pending_epochs"] != 0 \
            or om["counters"]["shed_reasons"] \
            .get("oldest-epoch-first") != 6:
        fail(f"oldest-epoch-first wrong: {om}")

    # 3. real multi-tenant work, admission continuing mid-flight.
    count_values = [0, 0, 0, 3, 3]
    count_reports = build_reports(m, b"smoke count", rng,
                                  count_values, bits)
    admit_all(svc, "count", m, count_reports, expect=ADMITTED)
    svc.begin_epoch("count")
    from mastic_tpu.drivers.attribute_metrics import hash_attribute
    alpha = hash_attribute(m_attr, "checkout.html")
    attr_int = int("".join("1" if b else "0" for b in alpha), 2)
    attr_reports = build_reports(m_attr, b"smoke attrs", rng,
                                 [attr_int, attr_int, 0], 8)
    admit_all(svc, "attrs", m_attr, attr_reports, expect=ADMITTED)
    svc.begin_epoch("attrs")
    # deadline tenant: its expired budget must degrade, not hang.
    admit_all(svc, "slow", m,
              build_reports(m, b"smoke slow", rng, [0, 0, 3], bits),
              expect=ADMITTED)
    svc.begin_epoch("slow")

    steps = 0
    while svc.step():
        steps += 1
        publish_status(status, svc)
        if steps == 1:
            # admission while rounds are in flight: lands in the
            # open page, joins the NEXT epoch.
            admit_all(svc, "count", m,
                      build_reports(m, b"smoke count", rng,
                                    count_values, bits),
                      expect=ADMITTED)
        if steps > 200:
            fail("drain did not converge")
    publish_status(status, svc)
    if status is not None:
        # The obs-smoke teeth: fetch all three endpoints over HTTP
        # during the live process and assert the acceptance series.
        check_status_endpoints(status)

    mx = svc.metrics()["tenants"]
    count_rec = mx["count"]["epochs"][0]
    expected_hitters = sorted([[False] * bits, [True] * bits])
    if count_rec["truncated"] \
            or sorted(count_rec["result"]) != expected_hitters:
        fail(f"count epoch wrong: {count_rec}")
    attr_rec = mx["attrs"]["epochs"][0]
    if attr_rec["truncated"] or attr_rec["result"][0][1] != [2] \
            and attr_rec["result"][0][1] != 2:
        fail(f"attrs epoch wrong: {attr_rec}")
    slow_rec = mx["slow"]["epochs"][0]
    if not slow_rec["truncated"] \
            or mx["slow"]["counters"]["deadline_misses"] != 1:
        fail(f"deadline miss not degraded: {slow_rec}")

    # 4. crash drill: second count epoch, snapshot mid-epoch, discard
    # the live service, resume, drain — result bit-identical to the
    # first epoch's (same reports are NOT required; same VALUES are,
    # so compare against epoch 0's result).
    svc.begin_epoch("count")   # the mid-flight admissions from step 1
    svc.step()                 # one round into the epoch
    blob = svc.to_bytes()
    del svc
    svc2 = CollectorService.from_bytes(blob, config=config, mesh=mesh)
    drain(svc2)
    mx2 = svc2.metrics()["tenants"]
    resumed_rec = mx2["count"]["epochs"][1]
    if resumed_rec["truncated"] \
            or sorted(resumed_rec["result"]) != expected_hitters:
        fail(f"resumed epoch wrong: {resumed_rec}")
    if not mx2["count"]["counters"]["resumes"]:
        fail("resume not counted")

    out = {
        "mode": "smoke",
        "platform": jax.devices()[0].platform,
        "wall_seconds": round(time.time() - t_start, 1),
        "tenants": {name: t["counters"]
                    for (name, t) in mx2.items()},
        "scheduler_rounds": steps,
        "status_port": status.port if status is not None else None,
        "ok": True,
    }
    line = json.dumps(out)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
