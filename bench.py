"""Benchmark: steady-state VIDPF evaluation throughput on one chip.

Prints ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
   "configs": {...}}

The headline metric is the BASELINE.json north star — VIDPF node
evaluations per second per chip at 256-bit tree depth, where one node
evaluation is the full extend + correct + convert + node-proof
pipeline of /root/reference/poc/vidpf.py:281-325 (2 fixed-key-AES
blocks + 2 AES convert blocks + 1 TurboSHAKE-128 hash per node,
reference op model in BASELINE.md / PERF.md).  The reference publishes
no timing numbers, so vs_baseline compares against this repo's own
scalar CPU reference layer (the same byte-exact math the reference's
Python PoC runs), measured in-process.

Shapes mimic the heavy-hitters steady state: a pruned frontier of
constant width marching down a 256-level tree; each timed step is one
tree level over (reports x frontier) with a traced node binder so a
single compiled program serves every level.

`configs` carries the BASELINE.json per-config entries:
  incremental_round      full steady-state incremental round (tree
                         step + binder hashing + eval proof + masked
                         aggregation; backend/incremental.py) at the
                         headline shape — rounds/s and evals/s
  prep_round_p50_ms      p50 single-round latency of the same program
                         (includes host dispatch + tunnel RTT)
  histogram_f128_b64     MasticHistogram(16, 4) @ BITS=64 — Field128
                         limb kernels + device FLP weight check
  sumvec1024_f128_b128   MasticSumVec(1024, 1, 32) @ BITS=128 —
                         huge-payload convert; reported as payload
                         bytes/s next to evals/s

Fail-open design: every phase (import / device / scalar baseline /
tiny sanity / compile / warmup / measure / each config) stamps
progress to stderr and updates a shared partial-result record; the
watchdog prints the best measurement completed so far instead of a
bare zero, with the failing phase named in "error".

Tunnel resilience (the remote-TPU link can be down at snapshot time):
  * device attach is probed in a SUBPROCESS with a hard per-attempt
    timeout and retries before the main process commits to jax.devices()
    (an in-process attach hang is unrecoverable — it ignores signals);
  * every successful full run persists its result to
    BENCH_LAST_GOOD.json (value, configs, git rev, timestamp); when
    attach fails, that record is emitted with "cached": true and its
    provenance, so a flaky tunnel degrades the round's number to
    "last verified" instead of erasing it;
  * the cached record is pre-seeded into the fail-open PARTIAL *before*
    attach, so even a watchdog firing mid-attach emits it.
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import threading
import time

_T0 = time.time()

# Partial-result record, updated as phases complete; the watchdog and
# any exception handler print it so a hang/crash still yields data.
PARTIAL = {
    "metric": "vidpf_node_evals_per_sec_per_chip_256bit",
    "value": 0.0,
    "unit": "evals/s",
    "vs_baseline": 0.0,
    "phase": "start",
}


def stamp(phase: str, **info) -> None:
    """Progress line on stderr + phase update for the fail-open JSON."""
    PARTIAL["phase"] = phase
    extra = " ".join(f"{k}={v}" for (k, v) in info.items())
    print(f"[bench {time.time() - _T0:7.1f}s] {phase} {extra}".rstrip(),
          file=sys.stderr, flush=True)


def emit(error: str | None = None) -> None:
    out = dict(PARTIAL)
    phase = out.pop("phase")
    if error is not None:
        out["error"] = f"{error} (last phase: {phase})"
    print(json.dumps(out), flush=True)


_LAST_GOOD_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_LAST_GOOD.json")


def load_last_good() -> dict | None:
    try:
        with open(_LAST_GOOD_PATH) as fh:
            rec = json.load(fh)
        if (isinstance(rec, dict)
                and isinstance(rec.get("value"), (int, float))
                and rec["value"] > 0):
            return rec
        return None
    except Exception:
        # A corrupt cache (e.g. a partial write cut off by the
        # watchdog's os._exit) must never stop a fresh measurement.
        return None


def save_last_good() -> None:
    """Persist the just-measured full result with provenance.

    Headline fields are always fresh here (only called after a fresh
    on-chip measurement).  Configs are persisted only when they are
    real measurements: an errored or empty config phase falls back to
    the previous record's configs, carrying THEIR provenance forward —
    never re-stamped under this run's revision."""
    rec = {k: v for (k, v) in PARTIAL.items()
           if k not in ("phase", "cached", "cached_provenance",
                        "configs", "configs_provenance")}
    configs = PARTIAL.get("configs")
    clean = ({k: v for (k, v) in configs.items() if k != "error"}
             if isinstance(configs, dict) else {})
    if clean:
        rec["configs"] = clean
        prov = PARTIAL.get("configs_provenance")
        if prov:  # configs were seeded from an older run, keep its rev
            rec["configs_provenance"] = prov
    else:
        old = load_last_good()
        old_configs = (old or {}).get("configs")
        old_clean = ({k: v for (k, v) in old_configs.items()
                      if k != "error"}
                     if isinstance(old_configs, dict) else {})
        if old_clean:
            rec["configs"] = old_clean
            rec["configs_provenance"] = old.get("configs_provenance") \
                or {"git_rev": old.get("git_rev", "unknown"),
                    "timestamp": old.get("timestamp", "unknown")}
    rec["timestamp"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    try:
        rev = subprocess.run(
            ["git", "-C", os.path.dirname(_LAST_GOOD_PATH), "rev-parse",
             "HEAD"], capture_output=True, text=True, timeout=10)
        rec["git_rev"] = rev.stdout.strip() or "unknown"
    except Exception:
        rec["git_rev"] = "unknown"
    tmp = _LAST_GOOD_PATH + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(rec, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, _LAST_GOOD_PATH)


def seed_from_cache(bits: int, reports: int) -> dict | None:
    """Pre-seed the fail-open record from the last verified full run,
    clearly marked as cached with its provenance.  A record measured
    at a different shape is not comparable (different tree depth /
    tile) and is left unused rather than emitted under this run's
    metric name."""
    last = load_last_good()
    if last is None:
        return None
    if last.get("bits") != bits or last.get("reports") != reports:
        return None
    PARTIAL["value"] = last["value"]
    PARTIAL["vs_baseline"] = last.get("vs_baseline", 0.0)
    if isinstance(last.get("configs"), dict):
        PARTIAL["configs"] = last["configs"]
        # Configs keep the revision they were measured at (may be
        # older than the headline's if a headline-only run re-saved).
        PARTIAL["configs_provenance"] = last.get("configs_provenance") \
            or {"git_rev": last.get("git_rev", "unknown"),
                "timestamp": last.get("timestamp", "unknown")}
    PARTIAL["cached"] = True
    PARTIAL["cached_provenance"] = {
        "git_rev": last.get("git_rev", "unknown"),
        "timestamp": last.get("timestamp", "unknown"),
        "reports": last.get("reports"),
        "frontier": last.get("frontier"),
    }
    return last


def probe_attach(timeout: float = 60.0, retries: int = 3) -> bool:
    """Probe jax.devices() in a subprocess with a hard timeout.

    An in-process attach to a dead tunnel blocks forever in C++ and
    ignores signals, so the main process must never be the first to
    try.  A successful probe also warms the tunnel, making the real
    attach fast."""
    code = "import jax; d = jax.devices(); print(d[0].platform)"
    for attempt in range(1, retries + 1):
        stamp("attach-probe", attempt=f"{attempt}/{retries}",
              timeout_s=int(timeout))
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                text=True, timeout=timeout)
        except subprocess.TimeoutExpired:
            stamp("attach-probe-timeout", attempt=attempt)
            continue
        platform = proc.stdout.strip()
        if proc.returncode == 0 and platform not in ("", "cpu"):
            stamp("attach-probe-ok", platform=platform)
            return True
        # rc 0 + platform "cpu" = jax fell back to the host backend
        # (fast-failing tunnel): that is NOT the chip — treating it as
        # one would record a bogus CPU rate over the real last-good.
        stamp("attach-probe-failed", rc=proc.returncode,
              platform=platform or "?",
              err=proc.stderr.strip().splitlines()[-1][:120]
              if proc.stderr.strip() else "")
    return False


def _watchdog(seconds: float):
    """Emit the partial result and hard-exit if any phase hangs (the
    remote-TPU tunnel can block indefinitely on attach)."""

    def fire():
        emit(error=f"watchdog timeout after {seconds:.0f}s")
        os._exit(2)

    timer = threading.Timer(seconds, fire)
    timer.daemon = True
    timer.start()
    return timer


def scalar_rate(bits: int = 256, level: int = 3) -> float:
    """Node evals/sec of the scalar byte-exact reference layer."""
    from mastic_tpu.field import Field64
    from mastic_tpu.vidpf import Vidpf

    vidpf = Vidpf(Field64, bits, 2)
    alpha = tuple(bool(i % 2) for i in range(bits))
    beta = [Field64(1), Field64(1)]
    nonce = bytes(16)
    rand = bytes(range(32))
    (cws, keys) = vidpf.gen(alpha, beta, b"bench", nonce, rand)
    prefixes = tuple(
        tuple(bool((v >> (level - i)) & 1) for i in range(level + 1))
        for v in range(2 ** (level + 1)))
    t0 = time.perf_counter()
    (_, tree) = vidpf.eval_level_synchronous(
        0, cws, keys[0], level, prefixes, b"bench", nonce)
    dt = time.perf_counter() - t0
    nodes = sum(len(lvl) for lvl in tree.levels)
    return nodes / dt


class SteadyState:
    """The compiled one-level step at a given (reports, frontier)."""

    def __init__(self, bm, reports: int, frontier: int, bits: int):
        import numpy as np
        import jax
        import jax.numpy as jnp

        from mastic_tpu.backend.vidpf_jax import EvalState

        vid = bm.vidpf
        ctx = b"bench"
        rng = np.random.default_rng(0)
        nonces = jnp.asarray(rng.integers(0, 256, (reports, 16),
                                          dtype=np.uint8))
        (ext_rk, conv_rk) = jax.jit(
            lambda n: vid.roundkeys(ctx, n))(nonces)
        jax.block_until_ready(ext_rk)

        self.cw = (
            jnp.asarray(rng.integers(0, 256, (reports, 16), np.uint8)),
            jnp.asarray(rng.integers(0, 2, (reports, 2)).astype(bool)),
            jnp.asarray(rng.integers(
                0, 1 << 16,
                (reports, vid.VALUE_LEN, bm.spec.num_limbs),
                dtype=np.uint32)),
            jnp.asarray(rng.integers(0, 256, (reports, 32), np.uint8)),
        )
        # Binder is traced data so one compile serves every level (at
        # depth >= 248 of a 256-bit tree the path encoding is 32 B).
        self.binder = jnp.asarray(rng.integers(
            0, 256, (2 * frontier, 36), dtype=np.uint8))
        keep = np.arange(0, 2 * frontier, 2)

        def step(seed, ctrl, binder):
            parents = EvalState(
                seed=seed, ctrl=ctrl,
                w=jnp.zeros((reports, frontier, vid.VALUE_LEN,
                             bm.spec.num_limbs), jnp.uint32),
                proof=jnp.zeros((reports, frontier, 32), jnp.uint8))
            (child, ok) = vid.eval_step(ext_rk, conv_rk, parents,
                                        self.cw, ctx, binder)
            # Prune back to the frontier width (threshold survivors).
            return (child.seed[:, keep], child.ctrl[:, keep],
                    child.proof, ok)

        self.seed = jnp.asarray(rng.integers(
            0, 256, (reports, frontier, 16), dtype=np.uint8))
        self.ctrl = jnp.asarray(rng.integers(
            0, 2, (reports, frontier)).astype(bool))
        self.step = jax.jit(step)
        self.jax = jax
        self.evals_per_step = reports * 2 * frontier

    def compile(self) -> float:
        t0 = time.perf_counter()
        compiled = self.step.lower(self.seed, self.ctrl,
                                   self.binder).compile()
        dt = time.perf_counter() - t0
        self.step = compiled
        # XLA's compiled cost analysis: logical bytes accessed per
        # step — the roofline-position number PERF.md §3 tracks (the
        # megakernel's acceptance metric is this value dropping >= 3x
        # vs the scan path on the same platform).  Fail-open: some
        # backends return nothing.
        self.cost_bytes = None
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            val = ca.get("bytes accessed")
            if val is not None:
                self.cost_bytes = float(val)
        except Exception:
            pass
        return dt

    def run(self, steps: int) -> float:
        (seed, ctrl) = (self.seed, self.ctrl)
        t0 = time.perf_counter()
        for _ in range(steps):
            (seed, ctrl, _proof, _ok) = self.step(seed, ctrl, self.binder)
        self.jax.block_until_ready(seed)
        dt = time.perf_counter() - t0
        return self.evals_per_step * steps / dt


def _synth_batch(bm, num_reports: int, rng):
    """A synthetic ReportBatch with random bytes/limbs: the compute
    cost of a round is input-independent (constant-time lane selects),
    so throughput measured on garbage reports equals throughput on
    real ones — only `accept` differs, and aggregation is masked
    either way."""
    import jax.numpy as jnp
    import numpy as np

    from mastic_tpu.backend.mastic_jax import ReportBatch
    from mastic_tpu.backend.vidpf_jax import BatchedCorrectionWords

    m = bm.m
    bits = m.vidpf.BITS
    vl = m.vidpf.VALUE_LEN
    n = bm.spec.num_limbs

    def u8(*shape):
        return jnp.asarray(rng.integers(0, 256, shape, np.uint8))

    def limbs(*shape):
        return jnp.asarray(rng.integers(0, 1 << 16, shape,
                                        dtype=np.uint32))

    use_jr = m.flp.JOINT_RAND_LEN > 0
    return ReportBatch(
        nonces=u8(num_reports, 16),
        cws=BatchedCorrectionWords(
            seed=u8(num_reports, bits, 16),
            ctrl=jnp.asarray(rng.integers(0, 2, (num_reports, bits, 2))
                             .astype(bool)),
            w=limbs(num_reports, bits, vl, n),
            proof=u8(num_reports, bits, 32)),
        keys=u8(num_reports, 2, 16),
        leader_proofs=limbs(num_reports, m.flp.PROOF_LEN, n),
        helper_seeds=u8(num_reports, 32),
        leader_seeds=u8(num_reports, 32) if use_jr else None,
        peer_parts=tuple(u8(num_reports, 32) if use_jr else None
                         for _ in range(2)))


def bench_full_round(bm, num_reports: int, agg_param, steps: int,
                     latency_samples: int = 11):
    """Compile one full from-root round (both preps + checks + FLP on
    weight-check rounds + masked aggregation), then measure chained
    steady-state throughput and single-round p50 latency."""
    import time as _time

    import jax
    import numpy as np

    rng = np.random.default_rng(7)
    batch = _synth_batch(bm, num_reports, rng)
    vk = bytes(range(32))
    fn = jax.jit(lambda b: bm.round_device(vk, b"bench", agg_param, b))
    t0 = _time.perf_counter()
    compiled = fn.lower(batch).compile()
    compile_s = _time.perf_counter() - t0
    out = compiled(batch)
    jax.block_until_ready(out)

    # Chained throughput: feed a rotated nonce array back in so each
    # round depends on the last (defeats dispatch pipelining).
    t0 = _time.perf_counter()
    b = batch
    for _ in range(steps):
        (agg0, _agg1, _accept, _ok) = compiled(b)
        b = b._replace(nonces=b.nonces.at[0, 0].set(
            agg0[0, 0].astype("uint8")))
    jax.block_until_ready(b.nonces)
    per_round = (_time.perf_counter() - t0) / steps

    lat = []
    for _ in range(latency_samples):
        t0 = _time.perf_counter()
        out = compiled(batch)
        jax.block_until_ready(out)
        lat.append(_time.perf_counter() - t0)
    p50_ms = sorted(lat)[len(lat) // 2] * 1e3
    return (per_round, p50_ms, compile_s)


def bench_incremental_round(bm, num_reports: int, frontier: int,
                            bits: int, steps: int, mesh=None):
    """Steady-state *incremental* round at a deep level: tree step for
    both aggregators + binder hashing over the carried ancestor tree +
    eval proof + masked aggregation (backend/incremental.py).  Carry
    contents are random — cost is input-independent.

    With `mesh`, carries / batch / round keys place report-sharded and
    the masked aggregate's psum is the only cross-chip collective —
    the returned dict then carries the per-shard rate and the psum
    bytes per round next to the aggregate rate."""
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mastic_tpu.backend.incremental import (Carry,
                                                IncrementalMastic,
                                                RoundPlan,
                                                needed_paths,
                                                round_inputs)

    level = bits - 56  # deep steady state; any level compiles the same
    width = max(4, frontier)
    half = width // 2
    num_parents = frontier // 2
    # Parents: distinct level-bit paths; candidates: both children.
    parents = [
        tuple(bool((i >> b) & 1) for b in range(level))
        for i in range(num_parents)
    ]
    prefixes = tuple(p + (c,) for p in parents for c in (False, True))
    carried = needed_paths(parents, level - 1)
    plan = RoundPlan(prefixes, level, bits, width, carried)
    rnd = round_inputs(plan)

    engine = IncrementalMastic(bm, width)
    rng = np.random.default_rng(8)
    spec = bm.spec
    vl = bm.m.vidpf.VALUE_LEN

    def carry():
        return Carry(
            w=jnp.asarray(rng.integers(
                0, 1 << 16, (num_reports, bits, width, vl,
                             spec.num_limbs), dtype=np.uint32)),
            proof=jnp.asarray(rng.integers(
                0, 256, (num_reports, bits, width, 32), np.uint8)),
            seed=jnp.asarray(rng.integers(
                0, 256, (num_reports, width, 16), np.uint8)),
            ctrl=jnp.asarray(rng.integers(
                0, 2, (num_reports, width)).astype(bool)))

    batch = _synth_batch(bm, num_reports, rng)
    vk = bytes(range(32))
    (ext_rk, conv_rk) = jax.jit(
        lambda nn: bm.vidpf.roundkeys(b"bench", nn))(batch.nonces)

    cws = batch.cws
    jit_kwargs = {}
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from mastic_tpu.parallel import place_replicated, place_reports

        (ext_rk, conv_rk, cws) = place_reports(
            mesh, (ext_rk, conv_rk, cws))
        rnd = place_replicated(mesh, rnd)
        rep = NamedSharding(mesh, P("reports"))
        repl = NamedSharding(mesh, P())
        # Carries report-sharded in and out; aggregates replicated —
        # the psum over the sharded report axis is the round's only
        # collective (PERF.md §8's cost model).
        jit_kwargs["out_shardings"] = (rep, rep, repl, repl)

    def place(c):
        if mesh is None:
            return c
        from mastic_tpu.parallel import place_reports
        return place_reports(mesh, c)

    def both(c0, c1, r):
        (c0, p0, out0, ok0) = engine.agg_round(
            0, vk, b"bench", c0, r, ext_rk, conv_rk, cws)
        (c1, p1, out1, ok1) = engine.agg_round(
            1, vk, b"bench", c1, r, ext_rk, conv_rk, cws)
        accept = jnp.all(p0 == p1, axis=-1)
        return (c0, c1, bm.aggregate(out0, accept),
                bm.aggregate(out1, accept))

    fn = jax.jit(both, donate_argnums=(0, 1), **jit_kwargs)
    t0 = _time.perf_counter()
    compiled = fn.lower(place(carry()), place(carry()), rnd).compile()
    compile_s = _time.perf_counter() - t0
    (c0, c1) = (place(carry()), place(carry()))
    (c0, c1, a0, a1) = compiled(c0, c1, rnd)
    jax.block_until_ready(a0)

    t0 = _time.perf_counter()
    for _ in range(steps):
        (c0, c1, a0, a1) = compiled(c0, c1, rnd)
    jax.block_until_ready(a0)
    per_round = (_time.perf_counter() - t0) / steps
    evals = num_reports * 2 * num_parents * 2  # both aggregators
    collective_bytes = (a0.nbytes + a1.nbytes if mesh is not None
                        else 0)
    return (per_round, evals / per_round, compile_s,
            collective_bytes)


def _bench_mesh(args):
    """The --mesh lever resolved to a Mesh (None when off).  `mesh_n`
    is resolved after the jax import in main ("all" -> device count).
    """
    n = getattr(args, "mesh_n", 1)
    if n <= 1:
        return None
    from mastic_tpu.parallel import make_mesh

    return make_mesh(n, nodes_axis=1)


def bench_chunked_round(args) -> dict:
    """The chunked PRODUCTION round on the pipelined executor
    (drivers/pipeline.py, `MASTIC_PIPELINE`): a small planted
    heavy-hitters run streamed through fixed-size chunks, measuring
    the per-phase timeline (upload / dispatch / compute-wait /
    download / host / compile) and the overlap efficiency — the
    numbers ISSUE 4 moves; the headline eval_step bench cannot see
    them because it never leaves the device."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from mastic_tpu import MasticCount
    from mastic_tpu.backend.mastic_jax import BatchedMastic
    from mastic_tpu.common import gen_rand
    from mastic_tpu.drivers.chunked import HostReportStore
    from mastic_tpu.drivers.heavy_hitters import HeavyHittersRun

    (bits, R, C) = (32, args.chunked_reports, args.chunked_reports // 4)
    m = MasticCount(bits)
    bm = BatchedMastic(m)
    rng = np.random.default_rng(5)
    # Three planted paths, no uniform tail: the frontier stays <= 6
    # wide for the whole depth, so the run is round-loop-bound (the
    # thing being measured), not node-eval-bound.
    paths = rng.integers(0, 2, (3, bits)).astype(bool)
    alphas = paths[rng.integers(0, 3, R)]
    beta = np.stack([bm.spec.int_to_limbs(el.int())
                     for el in [m.field(1)] + m.flp.encode(1)])
    betas = np.broadcast_to(beta, (R,) + beta.shape)
    shard_fn = jax.jit(
        lambda a, b, n, r: bm.shard_device(b"bench", a, b, n, r))
    (batch, ok) = shard_fn(
        jnp.asarray(alphas), jnp.asarray(betas),
        jnp.asarray(rng.integers(0, 256, (R, 16), dtype=np.uint8)),
        jnp.asarray(rng.integers(0, 256, (R, m.RAND_SIZE),
                                 dtype=np.uint8)))
    assert bool(np.all(np.asarray(ok)))
    store = HostReportStore.from_batch(batch, C)
    mesh = _bench_mesh(args)
    run = HeavyHittersRun(m, b"bench", {"default": R // 6}, None,
                          verify_key=gen_rand(m.VERIFY_KEY_SIZE),
                          store=store, mesh=mesh)
    # Same span schema as tools/serve.py epochs and tools/northstar.py
    # (one "collection" parent, "round"/"chunk.*" children), so a
    # bench trace and a live-service trace diff directly.
    from mastic_tpu.obs import trace as obs_trace
    tracer = obs_trace.get_tracer()
    coll_span = tracer.start_detached_span(
        "collection", tool="bench", reports=R, bits=bits)
    t0 = time.perf_counter()
    with tracer.use_parent(coll_span):
        while run.step():
            pass
    wall = time.perf_counter() - t0
    tracer.end_span(coll_span)

    pipes = [mx.extra["pipeline"] for mx in run.metrics]
    effs = sorted(p["overlap_efficiency"] for p in pipes)
    rounds = sorted(p["round_wall_ms"] for p in pipes)
    phases: dict = {}
    for mx in run.metrics:
        for rec in mx.extra["chunks"]:
            for (k, v) in rec["phases"].items():
                phases[k] = phases.get(k, 0.0) + v
    evals = sum(mx.node_evals for mx in run.metrics)
    shards = mesh.shape["reports"] if mesh is not None else 1
    mesh_block = None
    if mesh is not None:
        rounds_m = [mx.extra["mesh"] for mx in run.metrics
                    if "mesh" in mx.extra]
        skews = sorted(mr["shard_wait_skew_ms_max"] for mr in rounds_m)
        mesh_block = {
            "report_shards": shards,
            "device_rows_per_chunk":
                rounds_m[-1]["device_rows_per_chunk"],
            "psum_bytes_per_round_last":
                rounds_m[-1]["psum_bytes_per_round"],
            "psum_bytes_total": sum(mr["psum_bytes_per_round"]
                                    for mr in rounds_m),
            "shard_wait_skew_ms_p50": skews[len(skews) // 2],
            "shard_wait_skew_ms_max": skews[-1],
        }
    return {
        "instance": f"MasticCount({bits})",
        "reports": R, "chunk_size": C, "levels": len(run.metrics),
        "mesh_devices": shards,
        "mesh": mesh_block,
        "node_evals_per_sec_per_shard": round(evals / wall / shards, 1),
        "pipeline": pipes[-1]["mode"],
        "fallbacks": sorted({p["fallback"] for p in pipes
                             if p["fallback"]}),
        "wall_seconds": round(wall, 2),
        "round_ms_p50": round(rounds[len(rounds) // 2], 2),
        "node_evals_per_sec": round(evals / wall, 1),
        "overlap_efficiency_p50": effs[len(effs) // 2],
        "overlap_efficiency_max": effs[-1],
        "phase_ms": {k: round(v, 1) for (k, v) in sorted(
            phases.items())},
        "compile_inline_ms_total": round(
            sum(p["compile_inline_ms"] for p in pipes), 1),
        "aot_inline_compiles":
            run.runner.programs.stats["inline_compiles"],
        "aot_warm_compiles":
            run.runner.programs.stats["warm_compiles"],
    }


def bench_parties_wan(args) -> dict:
    """The `--parties-wan` config (ISSUE 11): the process-separated
    leader/helper session over the SHAPED network link
    (`MASTIC_NET_SHAPE`, mastic_tpu/net/transport.py), extending
    BASELINE's communication-only byte counts into a measured
    communication-vs-computation crossover.

    Method: one unshaped session is the compute baseline, then one
    session per bandwidth/RTT cell of the ladder.  Every session
    uploads the same seeded batch, pays one warm round (the parties'
    per-round trace/compile — identical across cells), then measures
    `--wan-rounds` rounds; the per-cell communication cost is the
    wall delta against the unshaped baseline, so the (large, equal)
    host/device work cancels.  Bit-identity across every cell is
    ASSERTED — a shaped link may slow the round, never change the
    aggregate.  The crossover stamp is the bandwidth at which the
    round's wire bytes take as long as the unshaped round computes:
    below it the session is communication-bound (the draft's
    deployment question, measured)."""
    import numpy as np

    from mastic_tpu.drivers.parties import AggregationSession
    from mastic_tpu.drivers.session import SessionConfig
    from mastic_tpu.mastic import MasticCount
    from mastic_tpu.metrics import RoundMetrics, count_round_bytes
    from mastic_tpu.net.transport import parse_shape

    bits = args.wan_bits
    n = args.wan_reports
    m = MasticCount(bits)
    spec = {"class": "MasticCount", "args": [bits]}
    ctx = b"bench parties wan"
    vk = bytes(range(m.VERIFY_KEY_SIZE))
    rng = np.random.default_rng(0)
    reports = []
    for i in range(n):
        value = 0 if i % 2 == 0 else (1 << bits) - 1
        alpha = m.vidpf.test_index_from_int(value, bits)
        nonce = bytes(rng.integers(0, 256, m.NONCE_SIZE,
                                   dtype="uint8"))
        rand = bytes(rng.integers(0, 256, m.RAND_SIZE,
                                  dtype="uint8"))
        (ps, shares) = m.shard(ctx, (alpha, True), nonce, rand)
        reports.append((nonce, ps, shares))
    param = (0, ((False,), (True,)), True)

    # The wire cost model (metrics.count_round_bytes — BASELINE's
    # communication-only numbers): per-round exchange bytes vs the
    # once-per-collection upload.
    model = RoundMetrics(level=0, frontier_width=2, padded_width=2,
                         reports_total=n)
    count_round_bytes(model, m, param, n)
    round_bytes = (model.bytes_prep_shares + model.bytes_prep_msgs
                   + model.bytes_agg_shares)
    upload_bytes_model = model.bytes_upload

    cfg = SessionConfig(connect_timeout=30.0, exchange_timeout=600.0,
                        ack_timeout=120.0, round_deadline=1200.0,
                        shutdown_timeout=5.0, retries=0, backoff=0.2)
    shapes = [None] + [s.strip() for s in args.wan_shapes.split(",")
                       if s.strip()]
    cells = []
    baseline = None
    reference = None
    for shape_text in shapes:
        if shape_text:
            os.environ["MASTIC_NET_SHAPE"] = shape_text
        else:
            os.environ.pop("MASTIC_NET_SHAPE", None)
        stamp("wan-cell", shape=shape_text or "unshaped")
        sess = AggregationSession(m, spec, ctx, vk, config=cfg)
        try:
            t0 = time.perf_counter()
            sess.upload(reports)
            upload_s = time.perf_counter() - t0
            upload_wire = sess.coll.wire_bytes()["sent"]
            sess.round(param)           # warm round (compile-bearing)
            walls = []
            for _ in range(max(1, args.wan_rounds)):
                t0 = time.perf_counter()
                (result, accept, shares) = sess.round(param)
                walls.append(time.perf_counter() - t0)
            wire_meas = sess.coll.wire_bytes()
        finally:
            sess.close()
        outcome = (result, [bool(x) for x in accept], shares)
        if reference is None:
            reference = outcome
        elif outcome != reference:
            raise RuntimeError(
                f"parties-wan: shaped link {shape_text!r} changed "
                f"the aggregate — bit-identity violated")
        cell = {
            "shape": shape_text or "unshaped",
            "upload_s": round(upload_s, 3),
            "round_wall_s": round(min(walls), 3),
            "round_walls_s": [round(w, 3) for w in walls],
            "collector_wire_bytes": wire_meas,
        }
        shape = parse_shape(shape_text)
        if shape is None:
            baseline = cell
        else:
            delta = min(walls) - baseline["round_wall_s"]
            cell["bandwidth_bytes_per_s"] = shape.bandwidth
            cell["rtt_s"] = shape.rtt
            # The upload leg is the CLEAN communication measurement
            # (no compute in it): measured wall vs the pipe model
            # over the collector's measured upload bytes validates
            # that the shaped link actually delivers its shape.
            cell["upload_model_s"] = round(
                (upload_wire / shape.bandwidth
                 if shape.bandwidth > 0 else 0.0) + shape.rtt, 3)
            cell["comm_delta_s"] = round(delta, 3)
            # Model: round bytes through the pipe + ~6 sequential
            # shaped sends on the critical path (agg params, prep
            # share, resolution, two agg shares), rtt/2 each.
            cell["comm_model_s"] = round(
                (round_bytes / shape.bandwidth
                 if shape.bandwidth > 0 else 0.0)
                + 6 * shape.rtt / 2, 3)
            cell["comm_fraction_of_round"] = round(
                max(0.0, delta) / max(1e-9, min(walls)), 3)
        cells.append(cell)

    compute_s = baseline["round_wall_s"]
    crossover = round_bytes / compute_s if compute_s > 0 else 0.0
    # The measured bracket around the crossover: the slowest shaped
    # cell still compute-bound and the fastest already comm-bound.
    above = [c for c in cells if c.get("comm_delta_s") is not None
             and c["comm_delta_s"] < compute_s]
    below = [c for c in cells if c.get("comm_delta_s") is not None
             and c["comm_delta_s"] >= compute_s]
    return {
        "bits": bits,
        "reports": n,
        "rounds_measured": max(1, args.wan_rounds),
        "round_bytes_model": round_bytes,
        "upload_bytes_model": upload_bytes_model,
        "compute_round_s": compute_s,
        "crossover_bandwidth_bytes_per_s": round(crossover, 1),
        "crossover_measured_bracket_bytes_per_s": [
            min((c["bandwidth_bytes_per_s"] for c in above),
                default=None),
            max((c["bandwidth_bytes_per_s"] for c in below),
                default=None),
        ],
        "cells": cells,
        "note": ("compute_round_s includes the parties' per-round "
                 "re-trace on this fabric; it cancels in every "
                 "comm_delta_s (equal work both sides of the delta) "
                 "but makes the crossover an upper bound"),
    }


def bench_service_overlap(args) -> dict:
    """The `--service-overlap` config (ISSUE 10): aggregate
    multi-tenant reports/s through the LIVE collector service —
    round-robin baseline (the r11 scheduler, in-process admission)
    vs the overlapped epoch executor + concurrent ingest front — with
    a freshly-baked AOT artifact store armed so steady-state epochs
    are trace-free in BOTH modes (fair fight: the r14 cold-start win
    is not conflated into the overlap number).

    Asserted, not just stamped: per-tenant epoch records bit-identical
    across the two modes, and zero inline compiles in every measured
    epoch (via the per-record compile accounting, which sums the
    timeline compile fields).  On a single-core fabric the wall clock
    is work-conserving — host work and XLA compute timeshare one core
    — so the speedup stamp is accompanied by the core count; the
    chip_session `serve-overlap` cell is where the device-overlap
    claim gets its hardware number (PERF.md §12)."""
    import tempfile
    import numpy as np

    from mastic_tpu.backend.mastic_jax import BatchedMastic
    from mastic_tpu.drivers import artifacts
    from mastic_tpu.drivers.heavy_hitters import \
        get_reports_from_measurements
    from mastic_tpu.drivers.service import (CollectorService,
                                            ServiceConfig, TenantSpec,
                                            encode_upload)
    from mastic_tpu.drivers.session import Deadline
    from mastic_tpu.mastic import MasticCount
    from mastic_tpu.obs.registry import get_registry

    bits = args.service_bits
    tenants_n = args.service_tenants
    reports_n = args.service_reports
    epochs_n = args.service_epochs
    hitters = 2
    ctx = b"bench service overlap"
    m = MasticCount(bits)
    vk = bytes(range(m.VERIFY_KEY_SIZE))

    # Bake the round-program family for exactly this config (rows =
    # the resident runner's report count), then arm the store.
    stamp("service-overlap-bake", bits=bits, rows=reports_n)
    store_dir = tempfile.mkdtemp(prefix="mastic_svc_overlap_")
    store = artifacts.default_store(store_dir)
    baker = artifacts.make_baker(BatchedMastic(m), ctx, width=8)
    bake_stats = artifacts.bake_trajectory(
        baker, store, reports_n,
        artifacts.trajectory(bits,
                             artifacts.planted_paths(bits, hitters)))
    os.environ["MASTIC_ARTIFACT_DIR"] = store_dir
    stamp("service-overlap-baked", **bake_stats)

    paths = artifacts.planted_paths(bits, hitters)
    meas = [(tuple(paths[i % hitters]), True)
            for i in range(reports_n)]
    reports = get_reports_from_measurements(m, ctx, meas)
    blobs = [encode_upload(m, r) for r in reports]
    expected_hitters = sorted("".join("1" if b else "0" for b in p)
                              for p in paths)

    def tenant_specs():
        return [
            TenantSpec(name=f"t{i}",
                       spec={"class": "MasticCount", "args": [bits]},
                       ctx=ctx, verify_key=vk,
                       thresholds={"default": 1})
            for i in range(tenants_n)
        ]

    def run_mode(overlapped: bool) -> dict:
        cfg = ServiceConfig(
            page_size=64, max_buffered=10 * reports_n * epochs_n,
            max_pending_epochs=epochs_n + 2, epoch_deadline=3600.0,
            overlap=(args.service_overlap_k if overlapped else 0),
            ingest_threads=(2 if overlapped else 0),
            ingest_queue=4 * reports_n)
        svc = CollectorService(tenant_specs(), config=cfg)
        deadline = Deadline(3600.0)

        def admit_epoch():
            for i in range(tenants_n):
                name = f"t{i}"
                for b in blobs:
                    svc.submit(name, b)
                svc.begin_epoch(name)

        # Warmup epoch: pays the once-per-process artifact loads +
        # probe rounds; excluded from the measured window.
        admit_epoch()
        while svc.step():
            if deadline.expired():
                raise RuntimeError("service-overlap warmup wedged")
        t0 = time.perf_counter()
        for _ in range(epochs_n):
            admit_epoch()
        while svc.step():
            if deadline.expired():
                raise RuntimeError("service-overlap drain wedged")
        wall = time.perf_counter() - t0
        svc.stop_ingest()
        mx = svc.metrics()["tenants"]
        records = {}
        inline = 0
        compile_ms = 0.0
        for (name, t) in mx.items():
            measured = t["epochs"][1:]
            for rec in measured:
                inline += rec.get("inline_compiles", 0)
                compile_ms += rec.get("compile_ms", 0.0)
                if sorted(rec["result"]) != [
                        [c == "1" for c in h]
                        for h in expected_hitters]:
                    raise RuntimeError(
                        f"service-overlap epoch wrong: {rec}")
            records[name] = [
                {k: v for (k, v) in rec.items()
                 if k not in ("wall_s", "compile_ms",
                              "inline_compiles")}
                for rec in measured
            ]
        eff = get_registry().gauge(
            "mastic_sched_overlap_efficiency").value()
        return {
            "wall_s": round(wall, 3),
            "reports_per_sec": round(
                tenants_n * reports_n * epochs_n / wall, 1),
            "records": records,
            "inline_compiles": inline,
            "compile_ms": round(compile_ms, 2),
            "overlap_efficiency": eff,
        }

    stamp("service-overlap-baseline")
    base = run_mode(False)
    stamp("service-overlap-overlapped",
          k=args.service_overlap_k)
    over = run_mode(True)

    bit_identical = over["records"] == base["records"]
    problems = []
    if not bit_identical:
        problems.append("per-tenant records diverge between modes")
    if base["inline_compiles"] or over["inline_compiles"]:
        problems.append(
            f"steady-state inline compiles nonzero: "
            f"baseline={base['inline_compiles']} "
            f"overlap={over['inline_compiles']}")
    if base["compile_ms"] or over["compile_ms"]:
        problems.append(
            f"steady-state timeline compile field nonzero: "
            f"baseline={base['compile_ms']}ms "
            f"overlap={over['compile_ms']}ms")
    if problems:
        raise RuntimeError("; ".join(problems))
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:
        cores = os.cpu_count() or 1
    rec = {
        "tenants": tenants_n,
        "bits": bits,
        "reports_per_epoch": reports_n,
        "epochs_measured": epochs_n,
        "overlap_k": args.service_overlap_k,
        "ingest_threads": 2,
        "store_entries": store.entry_count(),
        "baseline_reports_per_sec": base["reports_per_sec"],
        "overlap_reports_per_sec": over["reports_per_sec"],
        "speedup": round(over["reports_per_sec"]
                         / base["reports_per_sec"], 3),
        "bit_identical": bit_identical,
        "inline_compiles_measured": (base["inline_compiles"]
                                     + over["inline_compiles"]),
        "overlap_efficiency": over["overlap_efficiency"],
        "cores": cores,
    }
    if cores <= 1:
        # Physics stamp: one core timeshares host work and XLA
        # compute, so wall is work-conserving and the speedup here is
        # an overhead measurement, not the device-overlap claim —
        # that number comes from the serve-overlap chip cell.
        rec["note"] = ("single-core fabric: wall clock is "
                       "work-conserving; device-overlap speedup "
                       "requires the chip cell (PERF.md §12)")
    return rec


def run_cold_start_child(args) -> dict:
    """Fresh-process time-to-first-round of the PRODUCTION chunked
    incremental round (the runner path the AOT artifact store
    serves): build a deterministic planted-path collection, run it to
    completion, and report per-round compile fields + artifact stats
    + results — the payload both `bench.py --cold-start` and
    `tools/bake.py --smoke` compare across traced vs warm-store
    children.  Client-side report sharding is measured separately and
    excluded from the cold-start number (it is client work, not
    collector work)."""
    import jax

    from mastic_tpu.drivers import artifacts as artifacts_mod
    from mastic_tpu.drivers.heavy_hitters import (
        HeavyHittersRun, get_reports_from_measurements)
    from mastic_tpu.mastic import MasticCount

    bits = args.bits
    k = args.cold_start_hitters
    reports_n = args.chunked_reports
    ctx = args.cold_start_ctx.encode()
    m = MasticCount(bits)
    paths = artifacts_mod.planted_paths(bits, k)
    meas = [(tuple(paths[i % k]), True) for i in range(reports_n)]
    t_shard0 = time.time()
    reports = get_reports_from_measurements(m, ctx, meas)
    shard_s = time.time() - t_shard0
    stamp("cold-start-run", reports=reports_n, bits=bits,
          store=os.environ.get("MASTIC_ARTIFACT_DIR", ""))
    run = HeavyHittersRun(m, ctx, {"default": 1}, reports,
                          verify_key=bytes(range(m.VERIFY_KEY_SIZE)),
                          chunk_size=args.cold_start_chunk)
    more = run.step()   # the first round: the cold-start target
    t_first = time.time()
    while more:
        more = run.step()
    t_done = time.time()
    stats = run.runner.programs.stats
    round_compile = [
        round(sum(rec["phases"].get("compile_ms", 0.0)
                  for rec in mx.extra.get("chunks", ())), 3)
        for mx in run.metrics
    ]
    counters = [
        {"level": mx.level, "accepted": mx.accepted,
         "rejected_eval_proof": mx.rejected_eval_proof,
         "rejected_weight_check": mx.rejected_weight_check,
         "rejected_joint_rand": mx.rejected_joint_rand,
         "xof_fallbacks": mx.xof_fallbacks}
        for mx in run.metrics
    ]
    return {
        "mode": "cold-start-child",
        "platform": jax.devices()[0].platform,
        "bits": bits, "reports": reports_n,
        "chunk_size": args.cold_start_chunk, "hitters": k,
        "artifact_store": os.environ.get("MASTIC_ARTIFACT_DIR")
        or None,
        # Process start -> first completed round, client sharding
        # excluded: imports + backend init + runner construction
        # (incl. preload/compile) + round 0.
        "time_to_first_round_s": round(
            t_first - _T0 - shard_s, 2),
        "shard_seconds": round(shard_s, 2),
        "wall_s": round(t_done - _T0, 2),
        "levels": len(run.metrics),
        "inline_compiles": stats["inline_compiles"],
        "warm_compiles": stats["warm_compiles"],
        "artifact_hits": stats["artifact_hits"],
        "artifact_load_ms": round(stats["artifact_load_ms"], 1),
        "round_compile_ms": round_compile,
        "results": ["".join("1" if b else "0" for b in p)
                    for p in run.result()],
        "counters": counters,
    }


def run_cold_start_parent(args, timer) -> None:
    """`--cold-start`: the headline measurement of ISSUE 9 — fresh-
    subprocess time-to-first-round, traced vs warm artifact store,
    on the same fabric.  Bakes the store first (tools/bake.py, the
    same planted-path trajectory the children run) unless
    --artifact-dir already holds a manifest; stamps everything into
    one JSON line so the claim is reproducible from bench JSON
    alone."""
    import tempfile

    root = os.path.dirname(os.path.abspath(__file__))
    store = args.artifact_dir or os.path.join(
        tempfile.mkdtemp(prefix="mastic_cold_"), "store")

    def run_child(env_store: str | None) -> dict:
        env = dict(os.environ)
        env.pop("MASTIC_ARTIFACT_DIR", None)
        if env_store is not None:
            env["MASTIC_ARTIFACT_DIR"] = env_store
        cmd = [sys.executable, os.path.join(root, "bench.py"),
               "--cold-start-child",
               "--bits", str(args.cold_start_bits),
               "--chunked-reports", str(args.cold_start_reports),
               "--cold-start-chunk", str(args.cold_start_chunk),
               "--cold-start-hitters", str(args.cold_start_hitters),
               "--cold-start-ctx", args.cold_start_ctx]
        if args.cpu:
            cmd.append("--cpu")
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=3600, env=env)
        if proc.returncode != 0:
            raise RuntimeError(
                f"cold-start child (store={env_store}) failed "
                f"rc={proc.returncode}: {proc.stderr[-2000:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    bake_s = 0.0
    bake_entries = None
    if not os.path.exists(os.path.join(store, "manifest.json")):
        stamp("cold-start-bake", out=store)
        t0 = time.time()
        cmd = [sys.executable, os.path.join(root, "tools", "bake.py"),
               "--out", store, "--bits", str(args.cold_start_bits),
               "--rows", str(args.cold_start_chunk),
               "--hitters", str(args.cold_start_hitters),
               "--ctx", args.cold_start_ctx]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=7200, env=dict(os.environ))
        if proc.returncode != 0:
            timer.cancel()
            emit(error=f"cold-start bake failed: "
                 f"{proc.stderr[-1000:]}")
            sys.exit(2)
        bake_rec = json.loads(proc.stdout.strip().splitlines()[-1])
        bake_s = round(time.time() - t0, 1)
        bake_entries = bake_rec["entries"]
        stamp("cold-start-bake-done", entries=bake_entries,
              seconds=bake_s)

    stamp("cold-start-traced-child")
    traced = run_child(None)
    stamp("cold-start-warm-child", store=store)
    warm = run_child(store)
    (t_cold, t_warm) = (traced["time_to_first_round_s"],
                        warm["time_to_first_round_s"])
    PARTIAL["metric"] = "cold_start_time_to_first_round_seconds"
    PARTIAL["value"] = t_warm
    PARTIAL["unit"] = "s"
    PARTIAL["platform"] = warm["platform"]
    for key in ("cached", "cached_provenance", "configs",
                "configs_provenance", "vs_baseline"):
        PARTIAL.pop(key, None)
    PARTIAL["configs"] = {"incremental_round": {
        "instance": f"MasticCount({args.cold_start_bits})",
        "reports": args.cold_start_reports,
        "chunk_size": args.cold_start_chunk,
        "hitters": args.cold_start_hitters,
        # The attribution the r9..r13 bench JSON lacked: cold_start
        # is a FRESH PROCESS's time to its first completed round
        # (in-process `compile_seconds` elsewhere in this file can
        # read warm when the persistent XLA cache is armed on chip).
        "cold_start_seconds": t_cold,
        "warm_store_seconds": t_warm,
        "warm_over_cold": round(t_warm / t_cold, 3) if t_cold else None,
        "bake_seconds": bake_s,
        "store": store,
        "store_entries": bake_entries,
        "warm_inline_compiles": warm["inline_compiles"],
        "warm_artifact_hits": warm["artifact_hits"],
        "warm_round_compile_ms": warm["round_compile_ms"],
        "bit_identical": (warm["results"] == traced["results"]
                          and warm["counters"] == traced["counters"]),
    }}
    timer.cancel()
    stamp("done", cold=t_cold, warm=t_warm)
    emit()


def run_configs(args) -> dict:
    """The BASELINE.json per-config benches; each fails open into the
    shared record."""
    from mastic_tpu import MasticCount, MasticHistogram, MasticSumVec
    from mastic_tpu.backend.mastic_jax import BatchedMastic

    configs = PARTIAL.setdefault("configs", {})

    # 1. Full steady-state incremental round at the headline shape,
    # mesh-sharded over the report axis when --mesh asks for it (the
    # per-shard rate + psum bytes are the 8-chip scaling stamps).
    stamp("config-incremental-round", mesh=getattr(args, "mesh_n", 1))
    mesh = _bench_mesh(args)
    bm = BatchedMastic(MasticCount(args.bits))
    reports = args.reports // 2
    if mesh is not None:
        n = mesh.shape["reports"]
        reports = -(-reports // n) * n  # resident tile shards evenly
    (per_round, evals_s, compile_s, coll_bytes) = \
        bench_incremental_round(bm, reports, args.frontier, args.bits,
                                args.steps, mesh=mesh)
    configs["incremental_round"] = {
        "instance": f"MasticCount({args.bits})",
        "reports": reports, "frontier": args.frontier,
        "mesh_devices": (mesh.shape["reports"]
                         if mesh is not None else 1),
        "round_ms": round(per_round * 1e3, 2),
        "node_evals_per_sec": round(evals_s, 1),
        "node_evals_per_sec_per_shard": round(
            evals_s / (mesh.shape["reports"] if mesh is not None
                       else 1), 1),
        "collective_bytes_per_round": coll_bytes,
        "compile_seconds": round(compile_s, 1),
    }
    stamp("config-incremental-done", evals_s=f"{evals_s:.0f}")

    # 2. Histogram Field128 @ BITS=64: full round incl. device FLP.
    stamp("config-histogram-f128")
    bmh = BatchedMastic(MasticHistogram(64, 16, 4))
    agg_param = (0, ((False,), (True,)), True)
    (per_round, p50_ms, compile_s) = bench_full_round(
        bmh, 2048, agg_param, max(4, args.steps // 4))
    configs["histogram_f128_b64"] = {
        "instance": "MasticHistogram(bits=64, length=16, chunk=4)",
        "reports": 2048, "round": "level 0 + FLP weight check",
        "round_ms": round(per_round * 1e3, 2),
        "reports_per_sec": round(2048 / per_round, 1),
        "prep_round_p50_ms": round(p50_ms, 2),
        "compile_seconds": round(compile_s, 1),
    }
    stamp("config-histogram-done",
          rps=f"{2048 / per_round:.0f}")

    # 2b. Pipelined chunked production round: phase timeline +
    # overlap efficiency (drivers/pipeline.py).
    stamp("config-chunked-round",
          pipeline=os.environ.get("MASTIC_PIPELINE", "1"))
    configs["chunked_round"] = bench_chunked_round(args)
    stamp("config-chunked-round-done",
          eff=configs["chunked_round"]["overlap_efficiency_p50"])

    # 3. SumVec(1024) Field128 @ BITS=128: huge-payload convert.
    stamp("config-sumvec-f128")
    bmv = BatchedMastic(MasticSumVec(128, 1024, 1, 32))
    sv = SteadyState(bmv, 128, 8, 128)
    sv_compile = sv.compile()
    sv.run(1)
    rate = sv.run(max(4, args.steps // 4))
    payload = bmv.m.vidpf.VALUE_LEN * bmv.m.field.ENCODED_SIZE
    configs["sumvec1024_f128_b128"] = {
        "instance": "MasticSumVec(bits=128, length=1024, chunk=32)",
        "reports": 128, "frontier": 8,
        "node_evals_per_sec": round(rate, 1),
        "payload_bytes_per_sec": round(rate * payload, 1),
        "compile_seconds": round(sv_compile, 1),
    }
    stamp("config-sumvec-done", rate=f"{rate:.0f}")
    return configs


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--reports", type=int, default=4096)
    parser.add_argument("--frontier", type=int, default=64)
    parser.add_argument("--steps", type=int, default=16)
    parser.add_argument("--bits", type=int, default=256)
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (local sanity)")
    parser.add_argument("--headline-only", action="store_true",
                        help="skip the per-config benches")
    parser.add_argument("--keccak-unroll", type=int, default=None,
                        help="Keccak round-scan unroll factor "
                        "(sets MASTIC_KECCAK_UNROLL; default 1 unless "
                        "the env var is already set; 1 = cheapest "
                        "compile)")
    parser.add_argument("--aes-pallas", action="store_true",
                        help="route the bitsliced AES through the "
                        "Pallas fused-VMEM kernel (MASTIC_AES_PALLAS)")
    parser.add_argument("--keccak-pallas", action="store_true",
                        help="route the Keccak permutation through "
                        "the Pallas fused-VMEM kernel "
                        "(MASTIC_KECCAK_PALLAS)")
    parser.add_argument("--level-pallas", action="store_true",
                        help="route the whole level step (extend -> "
                        "correct -> convert -> node proof) through "
                        "the fused-VMEM Pallas megakernel "
                        "(MASTIC_LEVEL_PALLAS) — the HBM-roofline "
                        "lever, PERF.md §3")
    parser.add_argument("--pipeline", choices=("on", "off"),
                        default=None,
                        help="set the MASTIC_PIPELINE lever for the "
                        "chunked-round config (drivers/pipeline.py: "
                        "double-buffered chunk streaming + "
                        "ahead-of-time bucket compile; default on)")
    parser.add_argument("--chunked-round-only", action="store_true",
                        help="run ONLY the chunked pipelined round "
                        "bench (per-phase timeline + "
                        "overlap_efficiency) — the MASTIC_PIPELINE "
                        "on/off comparison cell of "
                        "tools/chip_session.sh")
    parser.add_argument("--chunked-reports", type=int, default=1024,
                        help="report count for the chunked-round "
                        "config (4 chunks)")
    parser.add_argument("--service-overlap", action="store_true",
                        help="run ONLY the multi-tenant collector-"
                        "service bench: aggregate reports/s, "
                        "round-robin baseline vs the overlapped "
                        "epoch executor + concurrent ingest front, "
                        "bit-identity and zero-steady-state-compile "
                        "asserted (ISSUE 10; PERF.md §12)")
    parser.add_argument("--service-tenants", type=int, default=3)
    parser.add_argument("--service-reports", type=int, default=96,
                        help="reports per tenant per epoch for "
                        "--service-overlap")
    parser.add_argument("--service-epochs", type=int, default=3,
                        help="measured epochs per tenant (one warmup "
                        "epoch runs first, excluded)")
    parser.add_argument("--service-bits", type=int, default=6)
    parser.add_argument("--service-overlap-k", type=int, default=2,
                        help="in-flight tenant rounds for the "
                        "overlapped mode (MASTIC_SERVICE_OVERLAP)")
    parser.add_argument("--parties-wan", action="store_true",
                        help="run ONLY the network-separated "
                        "leader/helper session over the shaped link "
                        "ladder (MASTIC_NET_SHAPE): per-cell round "
                        "wall + comm delta, bit-identity asserted, "
                        "communication-vs-computation crossover "
                        "stamped (ISSUE 11; PERF.md §13)")
    parser.add_argument("--wan-bits", type=int, default=4)
    parser.add_argument("--wan-reports", type=int, default=256)
    parser.add_argument("--wan-rounds", type=int, default=2,
                        help="measured rounds per --parties-wan cell "
                        "(one warm round runs first, excluded)")
    parser.add_argument("--wan-shapes", type=str,
                        default="bw=1m:rtt=10ms,bw=128k:rtt=20ms,"
                                "bw=32k:rtt=40ms,bw=8k:rtt=80ms",
                        help="comma-separated MASTIC_NET_SHAPE cells "
                        "for --parties-wan (bw in bytes/s)")
    parser.add_argument("--cold-start", action="store_true",
                        help="measure fresh-process time-to-first-"
                        "round, traced vs warm AOT artifact store "
                        "(bakes via tools/bake.py unless "
                        "--artifact-dir holds a manifest) — the "
                        "ISSUE 9 headline; emits one JSON line")
    parser.add_argument("--cold-start-child", action="store_true",
                        help=argparse.SUPPRESS)  # internal: one
    # fresh-process collection run, JSON on stdout (parent + bake
    # --smoke drive it)
    parser.add_argument("--artifact-dir", type=str, default=None,
                        help="AOT artifact store for --cold-start "
                        "(reused when it has a manifest, baked "
                        "otherwise)")
    parser.add_argument("--cold-start-bits", type=int, default=8)
    parser.add_argument("--cold-start-reports", type=int, default=64)
    parser.add_argument("--cold-start-chunk", type=int, default=16)
    parser.add_argument("--cold-start-hitters", type=int, default=2)
    parser.add_argument("--cold-start-ctx", type=str,
                        default="bench cold-start")
    parser.add_argument("--mesh", type=str, default="1",
                        help="shard the report axis of the "
                        "incremental_round and chunked_round configs "
                        "over this many devices ('all' = every "
                        "attached device; 1 = off).  On CPU a numeric "
                        "value forces that many virtual host devices "
                        "(xla_force_host_platform_device_count)")
    parser.add_argument("--watchdog", type=float, default=1500.0)
    parser.add_argument("--attach-timeout", type=float, default=60.0)
    parser.add_argument("--attach-retries", type=int, default=3)
    args = parser.parse_args()

    timer = _watchdog(args.watchdog)
    # The unroll lever must be in the environment before any
    # mastic_tpu.ops import (ops/keccak_jax.py reads it at import).
    # An explicit --keccak-unroll wins over an inherited env var; the
    # env var wins over the flag's default.
    if args.keccak_unroll is not None:
        os.environ["MASTIC_KECCAK_UNROLL"] = str(args.keccak_unroll)
    else:
        # unroll=1 was the best rate observed in the r5 chip lever
        # matrix (42.2M vs 37.5M warm at unroll=4 — single warm
        # measurements, so suggestive) and compiles quickest.
        os.environ.setdefault("MASTIC_KECCAK_UNROLL", "1")
    if args.keccak_pallas:
        os.environ["MASTIC_KECCAK_PALLAS"] = "1"
    if args.aes_pallas:
        os.environ["MASTIC_AES_PALLAS"] = "1"
    if args.level_pallas:
        os.environ["MASTIC_LEVEL_PALLAS"] = "1"
    if args.pipeline is not None:
        os.environ["MASTIC_PIPELINE"] = \
            "1" if args.pipeline == "on" else "0"

    if args.cold_start:
        # Pure subprocess orchestration: bake + two fresh children —
        # this process never imports jax (the children's cold start
        # must not inherit a warm runtime).
        run_cold_start_parent(args, timer)
        return

    if args.parties_wan:
        # Pure subprocess orchestration too: the parties are the
        # processes that touch jax; the parent only shards reports
        # (scalar layer) and drives the session.  Its own metric,
        # never BENCH_LAST_GOOD.
        PARTIAL["metric"] = "parties_wan_crossover_bandwidth"
        for key in ("cached", "cached_provenance", "configs",
                    "configs_provenance", "vs_baseline"):
            PARTIAL.pop(key, None)
        PARTIAL["platform"] = (os.environ.get("JAX_PLATFORMS", "")
                               or "ambient")
        stamp("parties-wan", shapes=args.wan_shapes,
              reports=args.wan_reports)
        rec = bench_parties_wan(args)
        PARTIAL["value"] = rec["crossover_bandwidth_bytes_per_s"]
        PARTIAL["unit"] = "bytes/s"
        PARTIAL["configs"] = {"parties_wan": rec}
        timer.cancel()
        stamp("done",
              crossover=rec["crossover_bandwidth_bytes_per_s"],
              compute_s=rec["compute_round_s"])
        emit()
        return

    # Pre-seed the fail-open record from the last verified run BEFORE
    # anything that can hang, so every exit path has a nonzero number
    # when one has ever been measured.
    cached = seed_from_cache(args.bits, args.reports)
    if cached is not None:
        stamp("cache-seeded", value=cached["value"],
              rev=cached.get("git_rev", "?")[:12])

    # A numeric --mesh > 1 must pin the virtual host device count
    # BEFORE the jax import (jax snapshots XLA_FLAGS then); on a chip
    # platform the flag only affects the unused host backend, so it is
    # always safe to set.  "all" resolves after the import.
    if args.mesh not in ("all",) and int(args.mesh) > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{int(args.mesh)}").strip()

    stamp("import-jax")
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    requested = os.environ.get("JAX_PLATFORMS", "").strip()
    if requested and "axon" not in requested.split(","):
        jax.config.update("jax_platforms", requested)

    if args.cold_start_child:
        # One fresh-process collection run; no attach probe (the
        # caller bounds the subprocess), no persistent compile cache
        # (a warm cache would fake the traced cold start).
        rec = run_cold_start_child(args)
        timer.cancel()
        print(json.dumps(rec), flush=True)
        return

    stamp("scalar-baseline")
    base = scalar_rate(bits=args.bits)
    PARTIAL["scalar_evals_per_sec"] = round(base, 1)
    if cached is not None and base > 0:
        PARTIAL["vs_baseline"] = round(PARTIAL["value"] / base, 1)

    # Subprocess probe first: a dead tunnel hangs the first in-process
    # jax.devices() beyond any recoverable point (r1 and r3 both lost
    # their number to exactly that).  Only the tunnel backend needs
    # probing — when JAX_PLATFORMS steers away from it (the config
    # override above), there is nothing to hang on, and the probe
    # child could not see that override anyway (the ambient
    # sitecustomize re-pins the child to the tunnel at config level).
    tunnel_expected = not requested or "axon" in requested.split(",")
    if not args.cpu and tunnel_expected:
        if not probe_attach(args.attach_timeout, args.attach_retries):
            timer.cancel()
            PARTIAL["platform"] = "unattached"
            emit(error="device attach probe failed "
                 f"({args.attach_retries}x{args.attach_timeout:.0f}s; "
                 "tunnel down)")
            # Nonzero so wrappers gating on exit status see that no
            # fresh measurement happened (the JSON line still carries
            # the cached number + provenance when one exists).
            sys.exit(3)
    stamp("device-attach")
    devices = jax.devices()
    stamp("device-up", devices=devices)
    on_chip = devices[0].platform != "cpu"
    # Resolve the --mesh lever now that the device set is known.
    args.mesh_n = (len(devices) if args.mesh == "all"
                   else int(args.mesh))
    if args.mesh_n > len(devices):
        timer.cancel()
        emit(error=f"--mesh {args.mesh_n} exceeds the "
             f"{len(devices)} attached device(s)")
        sys.exit(2)
    if args.mesh_n > 1:
        PARTIAL["mesh_devices"] = args.mesh_n
    # Stamped into every emit from here on, so a CPU-sim rate can
    # never be mistaken for a chip rate in a round artifact.
    PARTIAL["platform"] = devices[0].platform
    # Persistent compile cache, keyed by host so a cache built on a
    # different machine type is never reused (XLA rejects mismatched
    # machine types with noisy warnings and, historically, SIGILL).
    # Platform-gated since r9: on the CPU fabric, RELOADING cached
    # executables segfaults or loads silently wrong programs
    # (reproduced at the pre-pipeline HEAD; PERF.md §7), so only chip
    # runs get the cache unless MASTIC_COMPILE_CACHE=1 forces it
    # (=0 forces it off).
    cache_lever = os.environ.get("MASTIC_COMPILE_CACHE", "")
    cache_armed = (cache_lever == "1"
                   or (cache_lever != "0" and on_chip))
    if cache_armed:
        cache = f"/tmp/mastic_tpu_jax_cache_{socket.gethostname()}"
        jax.config.update("jax_compilation_cache_dir", cache)
    # Attribution honesty (ISSUE 9 satellite): with the persistent
    # cache armed, every in-process `compile_seconds` below can read
    # warm — the fresh-process cold start lives in `--cold-start`'s
    # `cold_start_seconds`, never here.
    PARTIAL["compile_cache_armed"] = cache_armed

    if args.service_overlap:
        # Multi-tenant serving throughput cell: round-robin baseline
        # vs overlapped executor + ingest front (ISSUE 10).  Its own
        # metric; never touches BENCH_LAST_GOOD.
        PARTIAL["metric"] = "service_overlap_reports_per_sec"
        for key in ("cached", "cached_provenance", "configs",
                    "configs_provenance", "vs_baseline"):
            PARTIAL.pop(key, None)
        stamp("service-overlap", tenants=args.service_tenants,
              reports=args.service_reports, k=args.service_overlap_k)
        rec = bench_service_overlap(args)
        PARTIAL["value"] = rec["overlap_reports_per_sec"]
        PARTIAL["unit"] = "reports/s"
        PARTIAL["speedup_vs_round_robin"] = rec["speedup"]
        PARTIAL["configs"] = {"service_overlap": rec}
        timer.cancel()
        stamp("done", rps=rec["overlap_reports_per_sec"],
              speedup=rec["speedup"])
        emit()
        return

    if args.chunked_round_only:
        # The MASTIC_PIPELINE on/off comparison cell: one JSON line
        # with the chunked production round's phase timeline and
        # overlap efficiency.  Never touches BENCH_LAST_GOOD (it is a
        # different metric than the headline).
        PARTIAL["metric"] = "chunked_round_node_evals_per_sec"
        PARTIAL["pipeline"] = \
            os.environ.get("MASTIC_PIPELINE", "1") != "0"
        for key in ("cached", "cached_provenance", "configs",
                    "configs_provenance", "vs_baseline"):
            PARTIAL.pop(key, None)
        stamp("chunked-round", reports=args.chunked_reports,
              pipeline=PARTIAL["pipeline"])
        rec = bench_chunked_round(args)
        PARTIAL["value"] = rec["node_evals_per_sec"]
        PARTIAL["overlap_efficiency"] = rec["overlap_efficiency_p50"]
        PARTIAL["configs"] = {"chunked_round": rec}
        timer.cancel()
        stamp("done", rate=f"{rec['node_evals_per_sec']:.0f}",
              eff=rec["overlap_efficiency_p50"])
        emit()
        return

    from mastic_tpu import MasticCount
    from mastic_tpu.backend.mastic_jax import BatchedMastic
    bm = BatchedMastic(MasticCount(args.bits))

    # Tiny-shape sanity: proves chip + kernels work before the big
    # compile; its rate is the fail-open fallback value.
    stamp("tiny-sanity-compile", reports=64, frontier=8)
    tiny = SteadyState(bm, 64, 8, args.bits)
    tiny_compile = tiny.compile()
    tiny_rate = tiny.run(4)
    PARTIAL["tiny_rate_evals_per_sec"] = round(tiny_rate, 1)
    if cached is None:
        # Without a last-good record the tiny rate is the best
        # fallback; with one, the cached full-shape number stays (a
        # 64x8 tile underfills the chip and would read as a regression).
        PARTIAL["value"] = round(tiny_rate, 1)
        PARTIAL["vs_baseline"] = round(tiny_rate / base, 1)
        PARTIAL["note"] = "tiny-shape (64x8) fallback rate"
    stamp("tiny-sanity-done", rate=f"{tiny_rate:.0f}",
          compile_s=f"{tiny_compile:.1f}")

    stamp("full-compile", reports=args.reports, frontier=args.frontier)
    full = SteadyState(bm, args.reports, args.frontier, args.bits)
    compile_s = full.compile()
    stamp("warmup", compile_s=f"{compile_s:.1f}")
    full.run(2)
    stamp("measure")
    rate = full.run(args.steps)

    PARTIAL.pop("note", None)
    # A fresh full measurement supersedes any cached pre-seed.  Under
    # --headline-only, cached configs stay with their own
    # configs_provenance: a verified older per-config record beats
    # discarding it, but it keeps the revision it was measured at.
    PARTIAL.pop("cached", None)
    PARTIAL.pop("cached_provenance", None)
    if not args.headline_only:
        PARTIAL.pop("configs", None)
        PARTIAL.pop("configs_provenance", None)
    PARTIAL["value"] = round(rate, 1)
    PARTIAL["vs_baseline"] = round(rate / base, 1)
    PARTIAL["compile_seconds"] = round(compile_s, 1)
    PARTIAL["reports"] = args.reports
    PARTIAL["frontier"] = args.frontier
    PARTIAL["bits"] = args.bits
    PARTIAL["keccak_unroll"] = int(
        os.environ.get("MASTIC_KECCAK_UNROLL", "1"))
    PARTIAL["keccak_pallas"] = \
        os.environ.get("MASTIC_KECCAK_PALLAS", "0") == "1"
    PARTIAL["aes_pallas"] = \
        os.environ.get("MASTIC_AES_PALLAS", "0") == "1"
    PARTIAL["level_pallas"] = \
        os.environ.get("MASTIC_LEVEL_PALLAS", "0") == "1"
    if full.cost_bytes:
        # Logical bytes accessed per step / per eval (PERF.md §3: the
        # scan path measured 8.29 GB/step = 15.8 KB/eval on a v5e;
        # the megakernel acceptance target is < 5.3 KB/eval).
        PARTIAL["cost_bytes_per_step"] = round(full.cost_bytes, 1)
        PARTIAL["cost_bytes_per_eval"] = round(
            full.cost_bytes / full.evals_per_step, 1)

    if not args.headline_only:
        try:
            run_configs(args)
        except Exception as exc:  # fail open per config
            PARTIAL.setdefault("configs", {})["error"] = \
                f"{type(exc).__name__}: {exc}"
    if not args.cpu and on_chip:
        save_last_good()
        stamp("last-good-saved", path=_LAST_GOOD_PATH)
    timer.cancel()
    stamp("done", rate=f"{rate:.0f}")
    emit()


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # fail open: report what we had
        emit(error=f"{type(exc).__name__}: {exc}")
        raise
