"""Fixture tests for the 8 tools/lint.py checks (fast tier).

Checks 1-4 and 6 run against known-good / known-bad snippets under
tests/fixtures/lint/; the repo-global checks (5, 7, 8) are asserted
clean on the shipped tree and exercised known-bad by pointing the
module lists at fixtures.
"""

import pathlib

from tools import lint

FIXTURES = pathlib.Path(__file__).parent / "fixtures" / "lint"


def rel(name: str) -> str:
    return str((FIXTURES / name).relative_to(lint.REPO))


# -- check 1: syntax --------------------------------------------------

def test_syntax_error_flagged():
    problems = lint.check_file(FIXTURES / "bad_syntax.py")
    assert len(problems) == 1 and "syntax error" in problems[0]


def test_clean_fixture_passes():
    assert lint.check_file(FIXTURES / "good_clean.py") == []


# -- check 2: unused imports -----------------------------------------

def test_unused_import_flagged():
    problems = lint.check_file(FIXTURES / "bad_unused_import.py")
    assert any("unused import 'os'" in p for p in problems)
    assert not any("'sys'" in p for p in problems)


# -- check 3: annotations in the ANNOTATED layer ----------------------

def test_missing_annotations_flagged(monkeypatch):
    monkeypatch.setattr(lint, "ANNOTATED", [rel("bad_annotations.py")])
    problems = lint.check_file(FIXTURES / "bad_annotations.py")
    assert any("missing annotations: ['value', 'other']" in p
               for p in problems)
    assert any("missing return annotation" in p for p in problems)


def test_annotations_not_required_outside_layer():
    # Same file, not in ANNOTATED: the annotation standard is scoped.
    assert lint.check_file(FIXTURES / "bad_annotations.py") == []


# -- check 4: no print() in library code ------------------------------

def test_print_flagged(monkeypatch):
    # Fixtures live under tests/ (a PRINT_OK prefix), so narrow the
    # allowlist to exercise the check itself.
    monkeypatch.setattr(lint, "PRINT_OK", ())
    problems = lint.check_file(FIXTURES / "bad_print.py")
    assert any("print() to stdout" in p for p in problems)


def test_print_allowed_in_tools(monkeypatch):
    monkeypatch.setattr(lint, "PRINT_OK", ("tests/",))
    assert lint.check_file(FIXTURES / "bad_print.py") == []


# -- check 5: annotations resolve at runtime --------------------------

def test_annotation_resolution_clean_on_repo():
    assert lint.check_annotations_resolve() == []


def test_unresolvable_annotation_flagged(monkeypatch):
    monkeypatch.setattr(lint, "ANNOTATED",
                        [rel("bad_annot_resolve.py")])
    problems = lint.check_annotations_resolve()
    assert any("does not resolve" in p for p in problems)


# -- check 6: call signatures -----------------------------------------

def test_call_arity_mismatch_flagged():
    problems = lint.check_call_signatures(
        [FIXTURES / "bad_call_arity.py"])
    assert any("takes 2 positional arg(s), call passes 3" in p
               for p in problems)


def test_call_arity_good_twin_passes():
    assert lint.check_call_signatures(
        [FIXTURES / "good_call_arity.py"]) == []


# -- check 7: env lever coverage --------------------------------------

def test_env_levers_clean_on_repo():
    assert lint.check_env_levers() == []


# -- check 8: ANNOTATED <-> mypy.ini strict sync ----------------------

def test_mypy_sync_clean_on_repo():
    assert lint.check_mypy_sync() == []


def test_mypy_sync_flags_missing_annotated(monkeypatch):
    trimmed = [p for p in lint.ANNOTATED
               if p != "mastic_tpu/wire.py"]
    monkeypatch.setattr(lint, "ANNOTATED", trimmed)
    problems = lint.check_mypy_sync()
    assert any("mastic_tpu.wire" in p and "missing from" in p
               for p in problems)


def test_mypy_sync_flags_relaxed_annotated(monkeypatch):
    # backend/ modules are ignore_errors in mypy.ini: listing one in
    # ANNOTATED must be reported as the reverse drift.
    monkeypatch.setattr(
        lint, "ANNOTATED",
        lint.ANNOTATED + ["mastic_tpu/backend/schedule.py"])
    problems = lint.check_mypy_sync()
    assert any("mastic_tpu.backend.schedule" in p
               and "relaxed in mypy.ini" in p for p in problems)


# -- check 10: analyzer rule table <-> USAGE.md -----------------------

def test_rule_table_docs_clean_on_repo():
    assert lint.check_rule_table_docs() == []


def test_rule_table_docs_flags_undocumented_rule(monkeypatch):
    import tools.analysis as analysis

    padded = dict(analysis._RULE_TABLE)
    padded["ZZ999"] = "a rule the docs have never heard of"
    monkeypatch.setattr(analysis, "_RULE_TABLE", padded)
    problems = lint.check_rule_table_docs()
    assert any("ZZ999" in p and "missing" in p for p in problems)


def test_rule_table_docs_flags_stale_row(monkeypatch):
    import tools.analysis as analysis

    trimmed = {k: v for (k, v) in analysis._RULE_TABLE.items()
               if k != "CC001"}
    monkeypatch.setattr(analysis, "_RULE_TABLE", trimmed)
    problems = lint.check_rule_table_docs()
    assert any("CC001" in p and "stale" in p for p in problems)


# -- check 11: refusal/shed reason codes <-> USAGE.md -----------------

def test_reason_docs_clean_on_repo():
    assert lint.check_reason_docs() == []


def test_reason_vocabulary_is_collected():
    """The AST collection sees both flavors of reason source: string
    literals at the shed sinks and the TLS_* constants."""
    reasons = lint._counted_reasons()
    assert "tenant-quarantined" in reasons
    assert "rate-limited" in reasons          # Name arg via REASON_*
    assert "tls-handshake-failed" in reasons  # TLS_* constant
    assert "shed" not in reasons              # no hyphen, not a code


def test_reason_docs_flags_undocumented_reason(monkeypatch):
    real = lint._counted_reasons()
    padded = dict(real)
    padded["never-documented"] = "mastic_tpu/fake.py"
    monkeypatch.setattr(lint, "_counted_reasons", lambda: padded)
    problems = lint.check_reason_docs()
    assert any("never-documented" in p and "no row" in p
               for p in problems)


def test_reason_docs_flags_stale_row(monkeypatch):
    real = lint._counted_reasons()
    trimmed = {k: v for (k, v) in real.items()
               if k != "rate-limited"}
    monkeypatch.setattr(lint, "_counted_reasons", lambda: trimmed)
    problems = lint.check_reason_docs()
    assert any("rate-limited" in p and "stale" in p
               for p in problems)


# -- the gate itself --------------------------------------------------

def test_repo_lint_is_clean():
    assert lint.main() == 0
