"""Known-good twin of rb001_tls_bad: the socket carries a deadline
before the handshake runs (the net/transport.py TcpListener
pattern), so a stalled dialer costs the budget, never the thread."""


class Listener:
    def accept_tls(self, ctx, handshake_timeout: float):
        self.sock.settimeout(handshake_timeout)
        (conn, _addr) = self.sock.accept()
        conn.settimeout(handshake_timeout)
        tls = ctx.wrap_socket(conn, server_side=True,
                              do_handshake_on_connect=False)
        tls.settimeout(handshake_timeout)
        tls.do_handshake()
        return tls
