#!/usr/bin/env python3
"""Standalone network aggregator party (ISSUE 14): the deployment
shape where leader and helper are long-lived processes on their own
hosts, reachable only over authenticated TCP.

    python tools/party.py serve --listen 127.0.0.1:0 \
        [--peer-listen 127.0.0.1:0] \
        --tls-cert certs/leader.pem --tls-key certs/leader.key \
        --tls-ca certs/ca.pem [--port-file ports.json] [--once]

The process binds its listener(s), publishes the bound ports
(`--port-file`, atomic rename — how a driver finds `--listen host:0`),
and serves collector sessions forever (or one, with `--once`):

* every inbound connection is authenticated by the mutual-TLS gate
  (`net.transport.TcpListener`): CA pinning, client-cert requirement,
  peer-name check ("collector" on the main listener, "helper" on the
  leader's peer listener).  Plaintext, wrong-CA, expired or misnamed
  dialers are refused reason-coded before a single session byte;
* the session config — which binds the VERIFY KEY — arrives as the
  first framed message on the established mTLS channel (the network
  twin of the spawn path's private-stdin handoff; never argv, never
  the environment);
* channels are reliable (`drivers/session.ReliableChannel`): frames
  are sequence-numbered, acked and replay-buffered, so a dropped
  connection or healed partition redials and resumes exactly-once —
  the collector's chaos drill (`tools/serve.py --chaos-drill`) drives
  precisely this path;
* a collector that abandons its session and opens a new one (respawn)
  hands over cleanly: the accept-side resume handshake surfaces the
  fresh session (`SessionRestart`) and the serve loop resets party
  state without dropping the new connection.

TLS flags fall back to the `MASTIC_NET_TLS_CERT` / `_KEY` / `_CA`
levers; with neither, the listener speaks plaintext (tests only — a
real deployment arms TLS, USAGE.md "Transport security").
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def parse_hostport(text: str) -> tuple:
    (host, _, port) = text.rpartition(":")
    if not host or not port.lstrip("-").isdigit():
        raise ValueError(f"--listen wants host:port, got {text!r}")
    return (host, int(port))


def _write_port_file(path: str, ports: dict) -> None:
    # fsync-then-rename (RB006): a reader polling for this file must
    # never observe a torn JSON body under the final name.
    from mastic_tpu.drivers.wal import fsync_dir

    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ports, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(os.path.abspath(path)))


def serve(args) -> int:
    # The ambient sitecustomize force-overrides jax's platform config
    # (same dance as drivers/parties.party_main): the caller's
    # JAX_PLATFORMS must stay authoritative for a network party too.
    import jax

    requested = os.environ.get("JAX_PLATFORMS", "").strip()
    if requested and "axon" not in requested.split(","):
        jax.config.update("jax_platforms", requested)
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                     "/tmp/mastic_tpu_jax_cache"))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      0.0)

    from mastic_tpu.drivers import faults as faults_mod
    from mastic_tpu.drivers import parties as parties_mod
    from mastic_tpu.drivers import session as session_mod
    from mastic_tpu.drivers.session import (SessionConfig,
                                            SessionError,
                                            reliable_accept,
                                            reliable_connect)
    from mastic_tpu.net.transport import (SessionRestart, TcpListener,
                                          TlsConfig, shape_from_env)
    from mastic_tpu.obs import trace as obs_trace

    if args.tls_cert or args.tls_key or args.tls_ca:
        if not (args.tls_cert and args.tls_key and args.tls_ca):
            print("party: --tls-cert/--tls-key/--tls-ca must all be "
                  "given (or none)", file=sys.stderr)
            return 2
        tls = TlsConfig(args.tls_cert, args.tls_key, args.tls_ca)
    else:
        tls = TlsConfig.from_env()

    config = SessionConfig.from_env()
    shaper = shape_from_env()
    (host, port) = parse_hostport(args.listen)
    listener = TcpListener(
        host, port,
        tls=tls.expecting("collector") if tls else None)
    peer_listener = None
    # The listeners live in a try/finally from the instant they are
    # bound: a failed peer-listener bind, a port-file write error or
    # a crash out of the serve loop must not strand the bound fds
    # (RL001/RL002).
    try:
        if args.peer_listen:
            (ph, pp) = parse_hostport(args.peer_listen)
            peer_listener = TcpListener(
                ph, pp, tls=tls.expecting("helper") if tls else None)
        if args.port_file:
            _write_port_file(args.port_file, {
                "listen": listener.port,
                "peer_listen": (peer_listener.port
                                if peer_listener else None)})
        print(f"party: listening on {host}:{listener.port}"
              + (f" (peer {ph}:{peer_listener.port})"
                 if peer_listener else "")
              + (" [mTLS]" if tls else " [plaintext]"),
              file=sys.stderr, flush=True)

        restart = None
        sessions = 0
        while True:
            peer = None
            coll = None
            try:
                coll = reliable_accept(listener, "collector", config,
                                       restart=restart)
                restart = None
                raw_cfg = coll.recv_msg(
                    "config", timeout=config.connect_timeout)
                cfg = json.loads(raw_cfg)
                agg_id = cfg["agg_id"]
                me = "leader" if agg_id == 0 else "helper"
                injector = (
                    faults_mod.FaultInjector(
                        faults_mod.parse_faults(cfg["faults"]), me)
                    if cfg.get("faults")
                    else faults_mod.injector_from_env(me))
                # Arm the already-built channel with this session's
                # injector (the config that names the faults rides
                # the very channel they apply to).
                coll.tp.injector = injector

                def trace(what: str, _me=me) -> None:
                    obs_trace.event("party_step", party=_me,
                                    step=what)

                def checkpoint(step: str, _inj=injector) -> None:
                    if _inj is not None:
                        _inj.checkpoint(step)

                checkpoint("spawn")
                mastic = parties_mod.instantiate(cfg["mastic"])
                party = parties_mod.AggregatorParty(
                    mastic, agg_id, bytes.fromhex(cfg["verify_key"]),
                    bytes.fromhex(cfg["ctx"]))
                coll.send_msg(bytes([agg_id]), "hello")
                trace("engine up (network session)")
                if agg_id == 0:
                    if peer_listener is None:
                        raise SessionError(
                            "collector", "config",
                            session_mod.KIND_PROTOCOL,
                            "leader config but no --peer-listen "
                            "listener to accept the helper on")
                    peer = reliable_accept(peer_listener, "helper",
                                           config,
                                           injector=injector,
                                           shaper=shaper)
                else:
                    (peer_host, peer_port) = cfg["peer"]
                    peer = reliable_connect(
                        peer_host, int(peer_port), "leader", config,
                        tls=tls, injector=injector, shaper=shaper)
                trace("peer channel up")
                parties_mod._command_loop(party, coll, peer, config,
                                          injector, trace,
                                          checkpoint)
                sessions += 1
                print(f"party: session {sessions} complete",
                      file=sys.stderr, flush=True)
            except SessionRestart as sr:
                restart = sr
                print("party: collector opened a new session; "
                      "resetting", file=sys.stderr, flush=True)
                continue
            except SessionError as err:
                # A dead collector or an exhausted redial budget
                # ends the session attributed; the server survives
                # to take the next one.
                print(f"party: session error: {err}",
                      file=sys.stderr, flush=True)
                if args.once:
                    return 1
            finally:
                for chan in (peer, coll):
                    if chan is not None:
                        chan.close()
            if args.once and restart is None:
                break
        return 0
    finally:
        listener.close()
        if peer_listener is not None:
            peer_listener.close()


def main() -> int:
    parser = argparse.ArgumentParser(
        description="standalone network aggregator party "
                    "(USAGE.md 'Transport security')")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("serve", help="bind the listeners and serve "
                                      "collector sessions")
    sp.add_argument("--listen", required=True,
                    help="host:port for collector sessions (port 0 "
                         "= ephemeral; see --port-file)")
    sp.add_argument("--peer-listen", default=None,
                    help="host:port for the helper's prep-exchange "
                         "link (leader role only)")
    sp.add_argument("--tls-cert", default=None)
    sp.add_argument("--tls-key", default=None)
    sp.add_argument("--tls-ca", default=None,
                    help="pinned CA bundle; with cert/key, arms "
                         "mutual TLS (else MASTIC_NET_TLS_* env, "
                         "else plaintext)")
    sp.add_argument("--port-file", default=None,
                    help="write the bound ports as JSON (atomic "
                         "rename)")
    sp.add_argument("--once", action="store_true",
                    help="serve exactly one session then exit")
    args = parser.parse_args()
    if args.cmd == "serve":
        return serve(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
