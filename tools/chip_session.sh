#!/bin/bash
# The first-chip-session checklist (VERDICT r4 ask #1), runnable as one
# command so even a short tunnel window captures everything, in value
# order:
#   1. full default bench  -> headline + per-config numbers,
#      BENCH_LAST_GOOD.json persisted with provenance
#   2. Keccak unroll lever matrix on the headline shape
#   3. Pallas fused-Keccak kernel on the headline shape (first-ever
#      hardware execution of the 12-round form)
# Each step has its own timeout; a hang or crash in one step must not
# cost the rest of the window (run() tolerates per-step failure), but
# a scaffolding failure — bad cwd, unwritable log, broken git — must
# abort loudly instead of producing a silent partial session log, so
# the script runs under -euo pipefail with an exit trap that names
# the matrix entry that was executing.
set -euo pipefail
cd "$(dirname "$0")/.."
LOG="${1:-/tmp/chip_session.log}"
exec >>"$LOG" 2>&1

CURRENT="(setup)"
on_exit() {
    local rc=$?
    if [ "$rc" -ne 0 ]; then
        echo "=== chip session ABORTED (exit=$rc) at matrix entry:" \
             "$CURRENT ==="
    fi
}
trap on_exit EXIT

echo "=== chip session $(date -u +%FT%TZ) rev=$(git rev-parse --short HEAD) ==="

run() {
    local name="$1"; shift
    CURRENT="$name: $*"
    echo "--- $name: $* ---"
    local rc=0
    timeout 2400 "$@" || rc=$?
    echo "--- $name: exit=$rc ---"
}

# 1. The one number the framework exists for (writes BENCH_LAST_GOOD).
run full python bench.py

# 2. Lever matrix: unroll x pallas on the headline shape (headline-only
# keeps each cell ~minutes; the full run above already owns last-good,
# and headline-only cells never overwrite its configs).  The default
# is unroll=1 since r5, so the matrix probes the non-default cells.
for unroll in 4 8; do
    run "unroll-$unroll" python bench.py --headline-only \
        --keccak-unroll "$unroll"
done
run pallas python bench.py --headline-only --keccak-pallas
run aes-pallas python bench.py --headline-only --aes-pallas

# 3b. The fused level-step megakernel (ops/level_pallas.py): first
# hardware execution of the whole extend->correct->convert->proof
# pipeline in VMEM — the HBM-roofline lever (PERF.md §3).  The JSON
# line carries cost_bytes_per_eval, the acceptance metric (< 5.3 KB
# vs the scan path's measured 15.8 KB).
run level-pallas python bench.py --headline-only --level-pallas

# 4. Pipelined chunk-streaming executor (drivers/pipeline.py): the
# chunked PRODUCTION round with MASTIC_PIPELINE on vs off, so the
# overlap + ahead-of-time-compile gain is measured unattended the
# moment the tunnel returns.  The JSON lines carry the per-phase
# timeline and overlap_efficiency (never touch BENCH_LAST_GOOD).
run pipeline-on python bench.py --chunked-round-only --pipeline on
run pipeline-off python bench.py --chunked-round-only --pipeline off

# 5. Mesh-sharded production round (r10, drivers/chunked.py +
# parallel/mesh.py): the chunked pipelined round at --mesh 1 vs every
# attached chip, so the next tunnel window measures multi-chip
# scaling (per-shard rate, psum bytes, shard skew) unattended.  The
# r10 bit-identity proof itself runs in CI (make multichip); these
# cells are the HARDWARE rate measurement.
run mesh-1 python bench.py --chunked-round-only --mesh 1
run mesh-all python bench.py --chunked-round-only --mesh all

# 6. Unattended collector-service soak (drivers/service.py +
# tools/serve.py): continuous admit -> epoch -> drain on the chip
# for two minutes, every epoch's hitters checked — a service that
# wedges, leaks, or degrades mid-soak fails this cell, and the JSON
# line records epochs/rounds completed plus the full counter ledger
# (scheduler-overhead numbers for PERF.md).
run serve-soak python tools/serve.py --soak 120 --bits 4 --reports 32

# 6d. Overlapped multi-tenant epoch execution on the chip (ISSUE 10):
# the round-robin-vs-overlap throughput comparison where it actually
# means something — host-side stage/collect work hiding behind real
# device dispatch.  The JSON line stamps baseline_reports_per_sec /
# overlap_reports_per_sec / speedup with bit-identity and the
# zero-steady-state-compile assertion (PERF.md §12); the soak twin
# runs the live service with the overlapped executor + ingest front
# armed for two minutes.
run serve-overlap python bench.py --service-overlap
run serve-overlap-soak python tools/serve.py --soak 120 --bits 4 \
    --reports 32 --overlap 2 --ingest-threads 2

# 6e. The network front on the chip host (ISSUE 11): the serve-load
# cell drives the DAP-shaped upload endpoint with 10^6 simulated
# clients (zipf mix, bursts, adversarial fraction) and stamps
# p50/p95/p99 admission latency + reports/s + the shed ledger — the
# first end-to-end SLO cell; parties-wan runs the network-separated
# leader/helper over the shaped-link ladder and stamps the
# communication-vs-computation crossover with chip-speed compute
# (PERF.md §13 tracks both).
run serve-load python tools/loadgen.py --clients 1000000 \
    --duration 30 --rate 600 --workers 8 --slo-p99-ms 250
run parties-wan python bench.py --parties-wan

# 6f. Survivable multi-host parties on the chip host (ISSUE 14):
# parties-tcp runs the seeded chaos campaign — standalone TCP+mTLS
# party processes (tools/party.py), reconnect-and-replay under
# injected conn_drop/partition/tls_handshake/slow_loris, bit-identity
# vs the loopback path — with chip-speed party compute; chaos-soak
# widens it to eight seeds for an unattended soak of the recovery
# machinery (every run's JSON line stamps reconnects/replayed_frames).
run parties-tcp python tools/serve.py --chaos-drill 7 --chaos-seeds 3
run chaos-soak python tools/serve.py --chaos-drill 100 \
    --chaos-seeds 8

# 6g. Durable admission on the chip host (ISSUE 18): the WAL drill's
# disk-fault campaign — kill-9 at every WAL checkpoint plus eight
# seeded kill/short_write/enospc schedules — with chip-speed epoch
# compute; every resumed run stamps replayed-record counts and
# recovery wall time, and must end bit-identical with exactly the
# clean run's admissions (USAGE.md "Durability", PERF.md §14).
run wal-soak python tools/serve.py --wal-drill 100 --wal-seeds 8

# 6c. On-chip AOT bake + trace-free load cycle (ISSUE 9,
# drivers/artifacts.py): bake the cold-start family on the chip,
# then bench.py --cold-start reuses the store (MASTIC_ARTIFACT_DIR
# under the hood) and measures fresh-process time-to-first-round,
# traced vs warm — the cold_start_seconds / warm_store_seconds pair
# PERF.md §11 tracks on real silicon.
run artifacts-bake python tools/bake.py \
    --out /tmp/mastic_aot_chip --bits 8 --rows 16 --hitters 2 \
    --ctx "bench cold-start"
run artifacts-cold python bench.py --cold-start \
    --artifact-dir /tmp/mastic_aot_chip

# 6b. The live status surface on the chip (ISSUE 7): the smoke
# scenario with --status-port armed self-curls /metrics, /statusz
# and /varz mid-run and asserts the per-tenant series, so the
# observability endpoints are proven against real chip rounds (the
# chunk-phase histograms carry hardware numbers here, not CPU ones).
run serve-status python tools/serve.py --smoke --status-port 8321

# Every on-chip run persists itself to BENCH_LAST_GOOD; end on the
# default configuration so the cached record reflects the default
# levers, not whichever matrix cell happened to run last.
run default-final python bench.py --headline-only

echo "=== chip session complete $(date -u +%FT%TZ) ==="
