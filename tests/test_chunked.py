"""Chunked at-scale execution: batched client shard bit-exact vs the
scalar client, and the report-chunked incremental runner bit-identical
to the unchunked one (same aggregates, same verdicts, same
checkpoints)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mastic_tpu.backend.mastic_jax import BatchedMastic
from mastic_tpu.common import gen_rand
from mastic_tpu.drivers.heavy_hitters import (
    HeavyHittersRun, get_reports_from_measurements)
from mastic_tpu.mastic import MasticCount, MasticHistogram

pytestmark = pytest.mark.slow

CTX = b"chunk test"


def _shard_inputs(m, bm, measurements, seed=7):
    rng = np.random.default_rng(seed)
    num = len(measurements)
    nonces = rng.integers(0, 256, (num, 16), dtype=np.uint8)
    rand = rng.integers(0, 256, (num, m.RAND_SIZE), dtype=np.uint8)
    (alphas, betas) = bm.encode_measurements(measurements)
    return (nonces, rand, alphas, betas)


@pytest.mark.parametrize("inst,weight", [
    (MasticCount(4), True),
    (MasticHistogram(4, 4, 2), 2),   # joint-rand family
], ids=["count", "histogram-jr"])
def test_shard_device_matches_scalar(inst, weight) -> None:
    m = inst
    bm = BatchedMastic(m)
    meas = [(m.vidpf.test_index_from_int(v % 16, 4), weight)
            for v in (0, 3, 9, 9, 15)]
    (nonces, rand, alphas, betas) = _shard_inputs(m, bm, meas)

    (batch, ok) = jax.jit(
        lambda a, b, n, r: bm.shard_device(CTX, a, b, n, r))(
        jnp.asarray(alphas), jnp.asarray(betas),
        jnp.asarray(nonces), jnp.asarray(rand))
    assert bool(np.all(np.asarray(ok)))

    for r in range(len(meas)):
        (cws, shares) = m.shard(CTX, meas[r], bytes(nonces[r]),
                                bytes(rand[r]))
        got_cws = bm.vidpf.cws_to_host(batch.cws, r)
        for (got, want) in zip(got_cws, cws):
            assert got[0] == want[0]            # seed cw
            assert got[1] == list(want[1])      # ctrl cw
            assert [x.int() for x in got[2]] == \
                [x.int() for x in want[2]]      # payload cw
            assert got[3] == want[3]            # proof cw
        assert np.asarray(batch.keys[r, 0]).tobytes() == shares[0][0]
        assert np.asarray(batch.keys[r, 1]).tobytes() == shares[1][0]
        got_proof = [bm.spec.limbs_to_int(np.asarray(
            batch.leader_proofs[r, j]))
            for j in range(m.flp.PROOF_LEN)]
        assert got_proof == [x.int() for x in shares[0][1]]
        assert np.asarray(batch.helper_seeds[r]).tobytes() == \
            shares[1][2]
        if m.flp.JOINT_RAND_LEN > 0:
            assert np.asarray(batch.leader_seeds[r]).tobytes() == \
                shares[0][2]
            assert np.asarray(
                batch.peer_parts[0][r]).tobytes() == shares[0][3]
            assert np.asarray(
                batch.peer_parts[1][r]).tobytes() == shares[1][3]


def _tampered_reports(m):
    meas = [((bool(v >> 2 & 1), bool(v >> 1 & 1), bool(v & 1)), True)
            for v in [0, 0, 0, 5, 5, 5, 3, 1, 6, 6]]
    reports = get_reports_from_measurements(m, CTX, meas)
    # Report 4: VIDPF key tamper -> fails the eval-proof check.
    (nonce, ps, shares) = reports[4]
    (key, proof, seed, part) = shares[0]
    reports[4] = (nonce, ps, [
        (bytes([key[0] ^ 1]) + key[1:], proof, seed, part), shares[1]])
    # Report 7: FLP proof-share tamper -> passes the eval proof,
    # fails the weight check (attribution must survive chunking).
    (nonce, ps, shares) = reports[7]
    (key, proof, seed, part) = shares[0]
    bad_proof = [proof[0] + m.field(1)] + proof[1:]
    reports[7] = (nonce, ps, [(key, bad_proof, seed, part), shares[1]])
    return reports


def test_chunked_matches_unchunked() -> None:
    m = MasticCount(3)
    reports = _tampered_reports(m)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    thresholds = {"default": 2}

    runs = [
        HeavyHittersRun(m, CTX, thresholds, reports, verify_key=vk),
        HeavyHittersRun(m, CTX, thresholds, reports, verify_key=vk,
                        chunk_size=4),   # 10 reports -> 4+4+2 (pad)
    ]
    while True:
        more = [run.step() for run in runs]
        assert more[0] == more[1]
        for (m0, m1) in zip(runs[0].metrics, runs[1].metrics):
            assert m0.accepted == m1.accepted
            assert m0.rejected_eval_proof == m1.rejected_eval_proof
            assert m0.rejected_weight_check == m1.rejected_weight_check
            assert m0.rejected_joint_rand == m1.rejected_joint_rand
            assert m0.node_evals == m1.node_evals
        if not more[0]:
            break
    # Level 0 attributes one reject to each check, in both runners.
    assert runs[0].metrics[0].rejected_eval_proof == 1
    assert runs[0].metrics[0].rejected_weight_check == 1
    assert runs[0].result() == runs[1].result()
    assert runs[1].result()  # nonempty: the honest hitters survive

    # Per-chunk metrics and memory accounting are present.
    extra = runs[1].metrics[-1].extra
    assert len(extra["chunks"]) == 3
    assert sum(c["reports"] for c in extra["chunks"]) == len(reports)
    mem = extra["memory"]
    assert mem["num_chunks"] == 3 and mem["chunk_size"] == 4
    assert mem["device_bytes_per_chunk"] < mem["host_bytes_total"]


def test_chunked_checkpoint_roundtrip() -> None:
    m = MasticCount(3)
    reports = _tampered_reports(m)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    thresholds = {"default": 2}

    ref = HeavyHittersRun(m, CTX, thresholds, reports, verify_key=vk,
                          chunk_size=4)
    ref.step()
    ref.step()
    blob = ref.to_bytes()
    resumed = HeavyHittersRun.from_bytes(m, CTX, thresholds, reports,
                                         vk, blob)
    assert resumed.level == ref.level
    assert resumed.prefixes == ref.prefixes
    while True:
        (a, b) = (ref.step(), resumed.step())
        assert a == b
        if not a:
            break
    assert ref.result() == resumed.result()


def test_checkpoint_runner_kind_mismatch_refused() -> None:
    """Restoring a resident checkpoint with a store (or a chunked one
    with neither store nor reports) must fail descriptively, not with
    a KeyError on missing carry arrays (ADVICE r4)."""
    from mastic_tpu.drivers.chunked import HostReportStore

    m = MasticCount(3)
    reports = _tampered_reports(m)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    thresholds = {"default": 2}

    resident = HeavyHittersRun(m, CTX, thresholds, reports,
                               verify_key=vk)
    resident.step()
    resident_blob = resident.to_bytes()
    chunked = HeavyHittersRun(m, CTX, thresholds, reports,
                              verify_key=vk, chunk_size=4)
    chunked.step()
    chunked_blob = chunked.to_bytes()

    bm = BatchedMastic(m)
    store = HostReportStore.from_batch(bm.marshal_reports(reports), 4)
    with pytest.raises(ValueError, match="resident"):
        HeavyHittersRun.from_bytes(m, CTX, thresholds, None, vk,
                                   resident_blob, store=store)
    with pytest.raises(ValueError, match="report store"):
        HeavyHittersRun.from_bytes(m, CTX, thresholds, None, vk,
                                   chunked_blob)


def test_chunked_width_growth_matches_resident() -> None:
    """A frontier that outgrows the initial padded width: 8 distinct
    3-bit prefixes in a 5-bit tree with threshold 1 force _grow at
    level 3 (8 ancestors > width 8 / 2), and level 4 then runs on the
    grown carries.  Both runners cross the growth boundary and must
    stay bit-identical (VERDICT r4 weak #1: the growth path had never
    executed)."""
    m = MasticCount(5)
    meas = [(m.vidpf.test_index_from_int(v * 4, 5), True)
            for v in range(8)]
    reports = get_reports_from_measurements(m, CTX, meas)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    thresholds = {"default": 1}

    runs = [
        HeavyHittersRun(m, CTX, thresholds, reports, verify_key=vk),
        HeavyHittersRun(m, CTX, thresholds, reports, verify_key=vk,
                        chunk_size=4),
    ]
    assert all(run.runner.width == 8 for run in runs)
    while True:
        more = [run.step() for run in runs]
        assert more[0] == more[1]
        if not more[0]:
            break
    # Both runners actually grew (the point of the test), at the same
    # level, and agree on everything downstream of the boundary.
    assert all(run.runner.width == 16 for run in runs)
    for (m0, m1) in zip(runs[0].metrics, runs[1].metrics):
        assert (m0.accepted, m0.padded_width, m0.node_evals) == \
            (m1.accepted, m1.padded_width, m1.node_evals)
    assert runs[0].metrics[3].padded_width == 16  # grew entering L3
    assert sorted(runs[0].result()) == sorted(runs[1].result()) == \
        sorted(m.vidpf.test_index_from_int(v * 4, 5) for v in range(8))


def test_memory_envelope_guard(monkeypatch) -> None:
    """The feasibility guard refuses shapes outside the device/host
    budget with an actionable message, and the analytic envelope
    matches the measured accounting byte-for-byte."""
    from mastic_tpu.drivers.chunked import (HostReportStore,
                                            memory_envelope)

    m = MasticHistogram(4, 4, 2)     # joint-rand family: widest rows
    bm = BatchedMastic(m)
    meas = [(m.vidpf.test_index_from_int(v % 16, 4), v % 4)
            for v in range(6)]
    (nonces, rand, alphas, betas) = _shard_inputs(m, bm, meas, seed=3)
    (batch, ok) = jax.jit(
        lambda a, b, n, r: bm.shard_device(CTX, a, b, n, r))(
        jnp.asarray(alphas), jnp.asarray(betas),
        jnp.asarray(nonces), jnp.asarray(rand))
    assert bool(np.all(np.asarray(ok)))
    # chunk_size 4 does NOT divide 6 reports: the parity below must
    # hold through the padded tail chunk (carries/round keys allocate
    # padded rows, the store exact rows).
    store = HostReportStore.from_batch(batch, chunk_size=4)
    vk = gen_rand(m.VERIFY_KEY_SIZE)

    run = HeavyHittersRun(m, CTX, {"default": 1}, None, verify_key=vk,
                          store=store)
    env = memory_envelope(bm, 4, run.runner.width, 6)
    mem = run.runner.memory_accounting()
    assert env["device_bytes_per_chunk"] == mem["device_bytes_per_chunk"]
    assert env["host_bytes_total"] == mem["host_bytes_total"]
    # The pipelined-residency term is exactly two chunks in flight.
    assert env["device_bytes_per_chunk_pipelined"] == \
        2 * mem["device_bytes_per_chunk"]

    # A budget below even one report's footprint: the width itself is
    # infeasible and the message must say so (not "shrink to 0").
    monkeypatch.setenv("MASTIC_DEVICE_BUDGET_BYTES", "1000")
    with pytest.raises(ValueError, match="width itself is infeasible"):
        HeavyHittersRun(m, CTX, {"default": 1}, None,
                        verify_key=vk, store=store)
    # A budget that fits one report but not the chunk: actionable
    # largest-feasible-chunk message.
    per = env["device_bytes_per_chunk"] // 4
    monkeypatch.setenv("MASTIC_DEVICE_BUDGET_BYTES", str(per * 2))
    with pytest.raises(ValueError, match="feasible chunk_size"):
        HeavyHittersRun(m, CTX, {"default": 1}, None,
                        verify_key=vk, store=store)
    monkeypatch.delenv("MASTIC_DEVICE_BUDGET_BYTES")
    monkeypatch.setenv("MASTIC_HOST_BUDGET_BYTES", "1000")
    with pytest.raises(ValueError, match="hosts"):
        HeavyHittersRun(m, CTX, {"default": 1}, None,
                        verify_key=vk, store=store)
    monkeypatch.delenv("MASTIC_HOST_BUDGET_BYTES")

    # Per-round binder-peak gate (the term a 20k x 256 resident run
    # OOMed on in r5): construction passes — the envelope cannot know
    # the live buckets up front — but the round refuses at the actual
    # buckets with the level named and everything before it
    # checkpointable.  Applies to both runners; exercised here on the
    # resident one (its whole batch is the "chunk").
    run2 = HeavyHittersRun(m, CTX, {"default": 1}, None,
                           verify_key=vk, batch=batch)
    resident = run2.runner.memory_accounting()["device_bytes_total"]
    monkeypatch.setenv("MASTIC_DEVICE_BUDGET_BYTES",
                       str(resident + 1))
    with pytest.raises(ValueError, match="binder buckets"):
        run2.step()


def test_round_peak_per_bucket_model(monkeypatch) -> None:
    """check_round_peak prices the proof staging at the onehot bucket
    and the payload staging at the payload bucket, SUMMED — not
    max(onehot, payload) applied to both (ADVICE r5: the shared cap
    overstated the peak whenever the two pow2 buckets diverge, which
    is the common case — payload rows trail onehot rows — and
    refused runs that actually fit the budget)."""
    from mastic_tpu.drivers.chunked import (_binder_staging_bytes,
                                            check_round_peak)

    m = MasticCount(8)
    bm = BatchedMastic(m)
    limb_bytes = m.vidpf.VALUE_LEN * bm.spec.num_limbs * 4
    (onehot_cap, payload_cap, rows, resident) = (64, 16, 100, 1 << 20)

    per_row = _binder_staging_bytes(bm, onehot_cap, payload_cap)
    assert per_row == 4 * (onehot_cap * 32 + payload_cap * limb_bytes)
    old_model = 4 * max(onehot_cap, payload_cap) * (32 + limb_bytes)
    assert per_row < old_model  # diverging buckets: model tightened

    # A budget between the tightened peak and the old overstated one:
    # the old model refused this shape; the per-bucket model admits it.
    peak = resident + per_row * rows
    monkeypatch.setenv(
        "MASTIC_DEVICE_BUDGET_BYTES",
        str((resident + old_model * rows + peak) // 2))
    check_round_peak(bm, onehot_cap, payload_cap, rows, resident, 3)

    # Still a real gate: a budget below the tightened peak refuses,
    # naming both buckets and the level.
    monkeypatch.setenv("MASTIC_DEVICE_BUDGET_BYTES", str(peak - 1))
    with pytest.raises(ValueError) as err:
        check_round_peak(bm, onehot_cap, payload_cap, rows, resident, 3)
    assert "64 (onehot)" in str(err.value)
    assert "16 (payload)" in str(err.value)
    assert "level 3" in str(err.value)


def test_shard_device_feeds_chunked_run() -> None:
    """The at-scale path end to end: device-sharded reports (no scalar
    client at all) -> HostReportStore -> chunked heavy hitters."""
    from mastic_tpu.drivers.chunked import HostReportStore

    m = MasticCount(3)
    bm = BatchedMastic(m)
    meas = [((bool(v >> 2 & 1), bool(v >> 1 & 1), bool(v & 1)), True)
            for v in [0, 0, 0, 5, 5, 5, 3, 6]]
    (nonces, rand, alphas, betas) = _shard_inputs(m, bm, meas, seed=11)
    (batch, ok) = jax.jit(
        lambda a, b, n, r: bm.shard_device(CTX, a, b, n, r))(
        jnp.asarray(alphas), jnp.asarray(betas),
        jnp.asarray(nonces), jnp.asarray(rand))
    assert bool(np.all(np.asarray(ok)))

    store = HostReportStore.from_batch(batch, chunk_size=4)
    vk = gen_rand(m.VERIFY_KEY_SIZE)
    run = HeavyHittersRun(m, CTX, {"default": 3}, None, verify_key=vk,
                          store=store)
    while run.step():
        pass
    expected = [
        m.vidpf.test_index_from_int(v, 3) for v in (0, 5)]
    assert sorted(run.result()) == sorted(expected)
