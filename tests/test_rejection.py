"""The XOF rejection-sampling fallback (vdaf-13 §6.2).

The batched sampler is exact only when no sampled element falls
outside the field; lanes where one does (probability ~2^-32 per
element for Field64) are flagged via the `ok` mask and must be
recomputed through the scalar layer, whose sampler implements the true
rejection loop (reference consumption
/root/reference/poc/vidpf.py:352-364).

A real rejection needs ~2^32 trials to find, so these tests force the
mask instead: `sample_vec` is monkeypatched to flag chosen report
lanes, and the drivers must produce output identical to the unpatched
run over the same reports (the device values of a flagged lane are
still valid here, and the scalar fallback recomputes exactly those
values — so agreement proves the splice is wired end-to-end).  The
mask predicate itself is unit-tested against crafted out-of-range
bytes below.
"""

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import mastic_tpu.backend.mastic_jax as mastic_jax
import mastic_tpu.backend.vidpf_jax as vidpf_jax
import mastic_tpu.backend.xof_jax as xof_jax
from mastic_tpu import MasticCount, MasticSum
from mastic_tpu.drivers import (aggregate_by_attribute,
                                compute_heavy_hitters,
                                get_reports_from_measurements,
                                hash_attribute)
from mastic_tpu.field import Field64, Field128
from mastic_tpu.ops.field_jax import spec_for


def _force_reject(monkeypatch, lanes):
    """Patch sample_vec so the chosen report lanes always read as
    rejected (the leading batch axis is the report axis at every call
    site in the aggregation path)."""
    real = xof_jax.sample_vec
    lanes = jnp.asarray(lanes)

    def fake(spec, stream, length, offset=0):
        (limbs, ok) = real(spec, stream, length, offset)
        bad = jnp.zeros((ok.shape[0],), bool).at[lanes].set(True)
        return (limbs, ok & ~bad.reshape((-1,) + (1,) * (ok.ndim - 1)))

    for mod in (vidpf_jax, mastic_jax):
        monkeypatch.setattr(mod, "sample_vec", fake)


def test_heavy_hitters_with_forced_rejections(monkeypatch):
    bits = 4
    mastic = MasticCount(bits)
    ctx = b"rejection hh"
    values = [0b1001, 0b0000, 0b0000, 0b1001, 0b1100, 0b0011]
    measurements = [
        (mastic.vidpf.test_index_from_int(v, bits), 1) for v in values
    ]
    reports = get_reports_from_measurements(mastic, ctx, measurements)
    verify_key = bytes(range(32))
    thresholds = {"default": 2}

    want = compute_heavy_hitters(mastic, ctx, thresholds, reports,
                                 verify_key=verify_key)
    assert want  # non-trivial example

    _force_reject(monkeypatch, [0, 3])
    for incremental in (True, False):
        got = compute_heavy_hitters(mastic, ctx, thresholds, reports,
                                    verify_key=verify_key,
                                    incremental=incremental)
        assert got == want


def test_attribute_metrics_with_forced_rejection(monkeypatch):
    mastic = MasticSum(8, 3)
    ctx = b"rejection attrs"
    votes = [("Greece", 1), ("United States", 2), ("Greece", 3),
             ("India", 1)]
    reports = get_reports_from_measurements(
        mastic, ctx,
        [(hash_attribute(mastic, a), v) for (a, v) in votes])
    verify_key = bytes(range(32))
    attributes = ["Greece", "Mexico", "United States"]

    want = aggregate_by_attribute(mastic, ctx, attributes, reports,
                                  verify_key=verify_key)
    _force_reject(monkeypatch, [2])
    got = aggregate_by_attribute(mastic, ctx, attributes, reports,
                                 verify_key=verify_key)
    assert got == want == [("Greece", 4), ("Mexico", 0),
                           ("United States", 2)]


def test_fallback_requires_host_reports(monkeypatch):
    from mastic_tpu.backend.mastic_jax import BatchedMastic
    from mastic_tpu.drivers.heavy_hitters import run_round

    mastic = MasticCount(2)
    ctx = b"rejection guard"
    measurements = [(mastic.vidpf.test_index_from_int(0b10, 2), 1)]
    reports = get_reports_from_measurements(mastic, ctx, measurements)
    bm = BatchedMastic(mastic)
    batch = bm.marshal_reports(reports)
    _force_reject(monkeypatch, [0])
    with pytest.raises(ValueError, match="scalar fallback"):
        run_round(bm, bytes(32), ctx, (0, ((False,), (True,)), True),
                  batch)


@pytest.mark.parametrize("field", [Field64, Field128])
def test_limb_mask_flags_out_of_range_bytes(field):
    """The device in-range predicate matches `value < p` exactly at
    the boundary (scalar rejection predicate: mastic_tpu/xof.py)."""
    spec = spec_for(field)
    size = field.ENCODED_SIZE
    cases = [
        (field.MODULUS - 1, True),
        (field.MODULUS, False),
        ((1 << (8 * size)) - 1, False),
        (0, True),
    ]
    data = jnp.asarray(np.stack([
        np.frombuffer(v.to_bytes(size, "little"), np.uint8)
        for (v, _) in cases
    ]))
    (limbs, ok) = spec.limbs_from_le_bytes(data)
    assert list(np.asarray(ok)) == [want for (_, want) in cases]
    assert spec.limbs_to_int(np.asarray(limbs)[0]) == field.MODULUS - 1


def test_sample_vec_mask_reduces_over_elements():
    """sample_vec's per-lane mask is the AND over that lane's sampled
    elements."""
    spec = spec_for(Field64)
    good = (1).to_bytes(8, "little")
    bad = ((1 << 64) - 1).to_bytes(8, "little")
    stream = jnp.asarray(np.stack([
        np.frombuffer(good + good, np.uint8),
        np.frombuffer(good + bad, np.uint8),
    ]))
    (_limbs, ok) = xof_jax.sample_vec(spec, stream, 2)
    assert list(np.asarray(ok)) == [True, False]
