"""Known-bad: print() to stdout in library code (lint check 4)."""


def chatty() -> None:
    print("stdout pollution")
