"""The network front (ISSUE 11, ROADMAP item 1): the collector as a
real network service.

The Mastic draft is designed to ride DAP-style HTTPS upload/aggregate
flows between genuinely separate parties; everything below this
package runs in one process or over loopback pipes spawned by one
parent.  This package is the missing edge, in three legs:

* `net/ingest.py` — a threaded HTTP upload endpoint framed DAP-style
  (versioned ``PUT /v1/tenants/{id}/reports`` carrying the dual-view
  report blob, content-length/media-type gates, structured JSON error
  bodies with the r8 reason codes) feeding the bounded-queue
  `CollectorService.submit()` seam;

* `net/admission.py` — the per-IP token-bucket + connection-limit
  admission layer in front of it, composing with the service's
  quota/shed machinery so every rejection lands in
  `ServiceCounters.shed_reasons` and the obs registry, never silent;

* `net/transport.py` — a `Transport` abstraction under the r8
  `Channel` (the existing socket path plus a `ShapedTransport`
  injecting configurable bandwidth/RTT/jitter), so the leader and
  helper run as network-separated parties over a link with
  bandwidth-delay realism (`MASTIC_NET_SHAPE`);

* `net/loadgen.py` — a closed-loop open/closed hybrid load generator
  simulating 10^5-10^6 clients (zipf tenant/client mix, Poisson
  arrivals with bursts, a configurable malformed fraction) that
  drives the upload endpoint and stamps p50/p95/p99 admission
  latency, reports/s and shed/quarantine accounting
  (`tools/loadgen.py`; the `serve-load` bench cell).

Import submodules explicitly (``from mastic_tpu.net import ingest``):
`ingest` pulls in the driver stack, while `transport`/`admission`
stay stdlib-light so `drivers/parties.py` can import shaping without
a cycle.  USAGE.md "Network front" has the endpoint spec, the
`MASTIC_NET_*` lever table and loadgen recipes; PERF.md §13 has the
measured SLO and communication-vs-computation crossover.
"""

__all__ = ["admission", "ingest", "loadgen", "transport"]
