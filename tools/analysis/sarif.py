"""SARIF 2.1.0 emitter for the analyzer (ISSUE 8 satellite).

One run object: the tool driver lists every rule in `_RULE_TABLE`
(stable index order), unsuppressed findings become `results` at level
"error", and inline `mastic-allow`ed findings are emitted too —
marked with an `inSource` suppression carrying the written
justification — so the SARIF artifact is the complete risk register,
not just the gate's view.  The structure follows the OASIS SARIF
2.1.0 schema (the subset GitHub code scanning ingests);
tests/test_analysis_tool.py validates the invariants.
"""

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def to_sarif(rule_table: dict, findings: list, suppressed: list,
             reasons: dict = None) -> dict:
    """The SARIF log dict.  `reasons` maps (rel, line, rule) of a
    suppressed finding to the allow's justification text."""
    rule_ids = sorted(rule_table)
    index = {rid: i for (i, rid) in enumerate(rule_ids)}
    rules = [{
        "id": rid,
        "shortDescription": {"text": rule_table[rid]},
        "defaultConfiguration": {"level": "error"},
    } for rid in rule_ids]

    def result(f, sup_reason=None):
        out = {
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": "error",
            "message": {"text": f.msg},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.rel,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {"startLine": max(1, f.line)},
                },
            }],
        }
        if sup_reason is not None:
            out["suppressions"] = [{
                "kind": "inSource",
                "justification": sup_reason,
            }]
        return out

    # One combined (path, line, rule) order over findings AND
    # suppressions: runs over identical trees serialize identically,
    # so CI artifact diffs show real drift, not emission order.
    tagged = [(f, None) for f in findings]
    tagged += [(f, (reasons or {}).get((f.rel, f.line, f.rule), ""))
               for f in suppressed]
    tagged.sort(key=lambda t: t[0].key())
    results = [result(f, sup_reason=r) for (f, r) in tagged]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "mastic-analysis",
                    "informationUri":
                        "USAGE.md#static-analysis",
                    "rules": rules,
                },
            },
            "originalUriBaseIds": {
                "SRCROOT": {"uri": "file:./"},
            },
            "results": results,
        }],
    }
