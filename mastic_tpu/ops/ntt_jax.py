"""Batched number-theoretic transforms over the 16-bit-limb Montgomery
representation (ops/field_jax.py).

The FLP's polynomial algebra (wire interpolation, gadget-polynomial
evaluation over the call domain — reference semantics:
/root/reference/poc/mastic.py:250-256 via vdaf_poc.flp_bbcggi19) only
ever needs transforms of a *static, small* power-of-two size p (the
gadget wire domain, p = next_pow2(calls+1); p <= 64 for every shipped
instantiation).  So each transform is an unrolled iterative radix-2
butterfly network with host-precomputed Montgomery-domain twiddles —
log2(p) stages of vectorized add/sub/mul over (..., p, limbs) arrays,
compiled once per (field, size).

Both Field64 (2-adicity 32) and Field128 (2-adicity 66) admit every
size used here.
"""

import numpy as np
import jax
import jax.numpy as jnp

from .field_jax import FieldSpec


def _bit_reverse_perm(size: int) -> np.ndarray:
    bits = size.bit_length() - 1
    out = np.zeros(size, np.int32)
    for i in range(size):
        out[i] = int(f"{i:0{bits}b}"[::-1], 2) if bits else 0
    return out


class NttPlan:
    """One compiled-shape transform: out[j] = sum_k x[k] omega^(jk),
    with omega the canonical generator of the order-`size` subgroup
    (forward) or its inverse with the 1/size factor folded in
    (inverse) — matching the scalar poly_eval_domain / poly_interp
    (mastic_tpu/field.py:164-199)."""

    def __init__(self, spec: FieldSpec, size: int, inverse: bool):
        assert size & (size - 1) == 0 and size >= 1
        self.spec = spec
        self.size = size
        self.inverse = inverse
        mod = spec.modulus
        gen = pow(7, (mod - 1) // spec.gen_order, mod)
        omega = pow(gen, spec.gen_order // size, mod)
        if inverse:
            omega = pow(omega, mod - 2, mod)
        self.perm = _bit_reverse_perm(size)
        # Stage s (m = 2^s halves): twiddles omega^(j * size / (2m)).
        self.stage_twiddles = []
        m = 1
        while m < size:
            step = size // (2 * m)
            tw = np.stack([
                spec.to_mont_host(pow(omega, j * step, mod))
                for j in range(m)
            ])
            self.stage_twiddles.append(tw)
            m *= 2
        self.size_inv = spec.to_mont_host(
            pow(size, mod - 2, mod)) if inverse else None

    def __call__(self, x: jax.Array) -> jax.Array:
        """Transform (..., size, n) Montgomery limbs along axis -2."""
        spec = self.spec
        assert x.shape[-2] == self.size
        x = x[..., self.perm, :]
        m = 1
        for tw in self.stage_twiddles:
            shape = x.shape[:-2] + (self.size // (2 * m), 2 * m,
                                    x.shape[-1])
            x = x.reshape(shape)
            even = x[..., :m, :]
            odd = spec.mul(x[..., m:, :], jnp.asarray(tw))
            x = jnp.concatenate(
                [spec.add(even, odd), spec.sub(even, odd)], axis=-2)
            x = x.reshape(x.shape[:-3] + (-1, x.shape[-1]))
            m *= 2
        if self.size_inv is not None:
            x = spec.mul(x, jnp.asarray(self.size_inv))
        return x


_PLANS: dict[tuple[int, int, bool], NttPlan] = {}


def ntt_plan(spec: FieldSpec, size: int, inverse: bool) -> NttPlan:
    key = (spec.modulus, size, inverse)
    plan = _PLANS.get(key)
    if plan is None:
        plan = NttPlan(spec, size, inverse)
        _PLANS[key] = plan
    return plan


def poly_eval_mont(spec: FieldSpec, coeffs: jax.Array,
                   t: jax.Array) -> jax.Array:
    """Horner evaluation: coeffs (..., L, n) low-to-high Montgomery,
    t (..., n) Montgomery -> (..., n).  The chain runs under lax.scan
    so the (mul, add) body compiles once per call site."""
    length = coeffs.shape[-2]
    if length == 1:
        return coeffs[..., 0, :]
    t_b = jnp.broadcast_to(t, coeffs.shape[:-2] + t.shape[-1:])

    def body(acc, c):
        return (spec.add(spec.mul(acc, t_b), c), None)

    rest = jnp.moveaxis(coeffs[..., :length - 1, :], -2, 0)
    (acc, _) = jax.lax.scan(body, coeffs[..., length - 1, :],
                            rest, reverse=True)
    return acc


def pow_static(spec: FieldSpec, t: jax.Array, exponent: int) -> jax.Array:
    """t^exponent for a static exponent (square-and-multiply)."""
    assert exponent >= 1
    acc = None
    base = t
    e = exponent
    while e:
        if e & 1:
            acc = base if acc is None else spec.mul(acc, base)
        e >>= 1
        if e:
            base = spec.mul(base, base)
    return acc


def power_chain(spec: FieldSpec, t: jax.Array, count: int) -> jax.Array:
    """[t^1, t^2, ..., t^count] stacked on a new axis -2 (lax.scan so
    the multiply body compiles once)."""
    if count == 1:
        return t[..., None, :]

    def body(acc, _):
        nxt = spec.mul(acc, t)
        return (nxt, nxt)

    (_, rest) = jax.lax.scan(body, t, None, length=count - 1)
    # scan stacks on axis 0; move it next to the limb axis.
    rest = jnp.moveaxis(rest, 0, -2)
    return jnp.concatenate([t[..., None, :], rest], axis=-2)
