"""Pass 8 — path-sensitive resource lifetime (RL001-RL005, ISSUE 17).

Scope: the session/network plane that ROADMAP item 1's event-loop
ingest rewrite lands on — all of mastic_tpu/net/, the session and
party drivers, and the serving/load tools.  Built on the CFG engine
(`cfg.py`): every function body is lowered to basic blocks with
explicit raise edges out of every call, and an "open/closed" fact per
locally-acquired resource is pushed along all paths to fixpoint.

Acquisition sites tracked (bound to a plain local name): socket
constructors (`socket.socket`, `socket.create_connection`,
`<ctx>.wrap_socket`, `<listener>.accept()` — first element of a
tuple unpack), `open(...)`, `subprocess.Popen`, `selectors.*Selector`,
and the repo's own transport constructors (`TcpListener`,
`TcpTransport`, `ShapedTransport`, `tcp_dial`).

A resource stops being the function's problem when it is closed
(settled, for a Popen: wait/communicate/terminate/kill), returned or
yielded, stored into an attribute/container, passed to another
callable (the callee is assumed to take ownership — the documented
intraprocedural blind spot), owned by a `with`, or narrowed away by a
None-guard (`if sock is not None: sock.close()` prunes the None path).
`<ctx>.wrap_socket(sock)` transfers ownership on success and — per
the ssl contract — leaves the plain socket the caller's problem when
the handshake raises.

  RL001  leak on an exception path: an open resource reaches the
         uncaught-exception exit (raise edges out of every call).
  RL002  leak on an early return / fall-through: an open resource
         reaches the normal exit.
  RL003  use after close (call on / argument-pass of a resource that
         is closed on every path reaching the use).
  RL004  double close without an intervening reopen or a path on
         which the first close did not happen.
  RL005  Popen with no wait/terminate/kill/communicate on some path
         (zombie process) — both exit kinds map here for processes.
"""

import ast

from . import cfg
from .core import Finding, call_name

PASS_NAME = "lifetime"

RULES = {
    "RL001": "resource leaked on an exception path",
    "RL002": "resource leaked on an early-return/fall-through path",
    "RL003": "resource used after close",
    "RL004": "resource closed twice without an intervening reopen",
    "RL005": "Popen never waited/terminated on some path (zombie)",
}

SCOPE_PREFIXES = ("mastic_tpu/net/",)
EXTRA_FILES = ("mastic_tpu/drivers/session.py",
               "mastic_tpu/drivers/parties.py",
               "tools/party.py", "tools/serve.py", "tools/loadgen.py")

# klass -> the method names that settle the resource.
_CLOSERS = {
    "socket": {"close"},
    "file": {"close"},
    "selector": {"close"},
    "transport": {"close"},
    "popen": {"wait", "communicate", "terminate", "kill"},
}

_TRANSPORT_CTORS = {"TcpListener", "TcpTransport", "ShapedTransport",
                    "tcp_dial"}


def in_scope(rel: str) -> bool:
    return rel.startswith(SCOPE_PREFIXES) or rel in EXTRA_FILES


def _acquisition(call: ast.Call):
    """Resource klass acquired by `call`, or None."""
    name = call_name(call)
    if not name:
        return None
    tail = name.rsplit(".", 1)[-1]
    if name == "open":
        return "file"
    if name in ("socket.socket", "socket.create_connection") \
            or tail == "create_connection":
        return "socket"
    if tail in ("wrap_socket", "accept"):
        return "socket"
    if tail == "Popen":
        return "popen"
    if tail.endswith("Selector"):
        return "selector"
    if tail in _TRANSPORT_CTORS:
        return "transport"
    return None


# -- fact plumbing ----------------------------------------------------
#
# A fact is ("open"|"closed", name, acquisition line, klass).

def _facts_for(facts, name):
    return frozenset(f for f in facts if f[1] == name)


def _drop(facts, names):
    if not names:
        return facts
    return frozenset(f for f in facts if f[1] not in names)


def _escaping_names(element) -> set:
    """Names the element hands to someone else: call arguments
    (nested), return/yield values, assignment RHS reads.  Receiver
    chains (the .func of a Call) do not escape — `sock.recv(n)` uses
    sock, it does not give it away."""
    if isinstance(element, ast.expr):
        return set()                     # branch tests never escape
    receiver = set()
    for node in ast.walk(element):
        if isinstance(node, ast.Call):
            for sub in ast.walk(node.func):
                receiver.add(id(sub))
    out = set()
    for node in ast.walk(element):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and id(node) not in receiver:
            out.add(node.id)
    return out


def _bound_names(target) -> set:
    out = set()
    for node in ast.walk(target):
        if isinstance(node, ast.Name):
            out.add(node.id)
    return out


def _direct_calls(element):
    """(call, receiver name, attr) for every `name.method(...)` call
    in the element (direct receiver only — `a.b.method()` is not a
    use of `a` for RL003/RL004 purposes)."""
    out = []
    for node in ast.walk(element) if element is not None else ():
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name):
            out.append((node, node.func.value.id, node.func.attr))
    return out


def _none_guard(expr):
    """(name, kill_edge) for a None/truthiness narrowing test, else
    None.  kill_edge is the edge kind on which the name is known
    None/falsy (and its facts die)."""
    if isinstance(expr, ast.Name):
        return (expr.id, cfg.FALSE)
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Not) \
            and isinstance(expr.operand, ast.Name):
        return (expr.operand.id, cfg.TRUE)
    if isinstance(expr, ast.Compare) \
            and isinstance(expr.left, ast.Name) \
            and len(expr.ops) == 1 \
            and len(expr.comparators) == 1 \
            and isinstance(expr.comparators[0], ast.Constant) \
            and expr.comparators[0].value is None:
        if isinstance(expr.ops[0], ast.Is):
            return (expr.left.id, cfg.TRUE)
        if isinstance(expr.ops[0], ast.IsNot):
            return (expr.left.id, cfg.FALSE)
    return None


class _FuncAnalysis:
    def __init__(self, info, func):
        self.info = info
        self.func = func
        self.graph = cfg.build(func)
        self.findings = []
        self._reported = set()

    # -- transfer -----------------------------------------------------

    def transfer(self, block, facts):
        el = block.elem
        if el is None:
            return {cfg.FLOW: facts}
        if isinstance(el, ast.expr):
            guard = _none_guard(el)
            if guard is not None:
                (name, kill_edge) = guard
                return {cfg.FLOW: facts,
                        kill_edge: _drop(facts, {name})}
            return {cfg.FLOW: facts}
        if isinstance(el, tuple):
            if el[0] == "for":
                node = el[1]
                out = _drop(facts, _bound_names(node.target))
                out = _drop(out, _escaping_names(node.iter))
                return {cfg.FLOW: out, cfg.EXC: facts}
            if el[0] == "with":
                return self._with_item(el[1], facts)
        return self._stmt_transfer(el, facts)

    def _with_item(self, item, facts):
        expr = item.context_expr
        out = facts
        if item.optional_vars is not None:
            out = _drop(out, _bound_names(item.optional_vars))
        if isinstance(expr, ast.Name):
            # `with sock:` — __exit__ closes it on every path out.
            out = _drop(out, {expr.id})
        elif isinstance(expr, ast.Call):
            # Acquisition inside a with-item is owned by the with; any
            # tracked name passed in escapes to the context manager.
            out = _drop(out, _escaping_names(ast.Expr(value=expr)))
        return {cfg.FLOW: out, cfg.EXC: facts}

    def _stmt_transfer(self, st, facts):
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            return self._assign(st, facts)
        if isinstance(st, ast.Return):
            out = _drop(facts, _escaping_names(st))
            return {cfg.FLOW: out, cfg.EXC: out}
        if isinstance(st, ast.Delete):
            names = set()
            for t in st.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
            return {cfg.FLOW: _drop(facts, names)}
        if isinstance(st, ast.Expr):
            return self._expr_stmt(st, facts)
        if isinstance(st, ast.Raise):
            out = _drop(facts, _escaping_names(st))
            return {cfg.FLOW: out, cfg.EXC: out}
        # assert, import, global, pass, ...
        return {cfg.FLOW: facts}

    def _close_effects(self, st, facts):
        """Move open facts to closed for every direct `name.closer()`
        call in the statement (wherever it appears — `rc = p.wait()`
        settles p just as `p.wait()` does).  Returns (facts, names
        closed here)."""
        closed_here = set()
        for (_call, recv, attr) in _direct_calls(st):
            for f in _facts_for(facts, recv):
                if f[0] == "open" and attr in _CLOSERS.get(f[3], ()):
                    closed_here.add(recv)
        out = facts
        for name in closed_here:
            moved = {("closed", f[1], f[2], f[3])
                     for f in _facts_for(facts, name)}
            out = _drop(out, {name}) | moved
        return (out, closed_here)

    def _assign(self, st, facts):
        value = st.value
        (facts, closed_here) = self._close_effects(st, facts)
        targets = (st.targets if isinstance(st, ast.Assign)
                   else [st.target])
        bound = set()
        for t in targets:
            bound |= _bound_names(t)
        klass = _acquisition(value) if isinstance(value, ast.Call) \
            else None
        # The name the new resource binds to: a single plain Name, or
        # the first element of a tuple unpack for `.accept()`.
        gen_name = None
        if klass is not None and len(targets) == 1:
            t = targets[0]
            if isinstance(t, ast.Name):
                gen_name = t.id
            elif isinstance(t, (ast.Tuple, ast.List)) and t.elts \
                    and isinstance(t.elts[0], ast.Name) \
                    and call_name(value).rsplit(".", 1)[-1] == "accept":
                gen_name = t.elts[0].id
        escapes = _escaping_names(st) - closed_here
        out = _drop(facts, bound)
        out = _drop(out, escapes)
        if gen_name is not None:
            out = _drop(out, {gen_name}) \
                | {("open", gen_name, st.lineno, klass)}
        # If the statement raises, the binding did not happen and —
        # for wrap_socket, per the ssl contract — the plain socket
        # remains the caller's to close.
        if klass is not None \
                and call_name(value).rsplit(".", 1)[-1] == "wrap_socket":
            exc_out = facts
        else:
            exc_out = _drop(facts, escapes)
        return {cfg.FLOW: out, cfg.EXC: exc_out}

    def _expr_stmt(self, st, facts):
        (out, closed_here) = self._close_effects(st, facts)
        out = _drop(out, _escaping_names(st) - closed_here)
        # Kills commit on the raise edge too: a failing close still
        # counts as cleanup (fd released by the attempt).
        return {cfg.FLOW: out, cfg.EXC: out}

    # -- reporting ----------------------------------------------------

    def _report(self, rule, line, msg):
        key = (rule, line, msg)
        if key not in self._reported:
            self._reported.add(key)
            self.findings.append(Finding(rule, self.info.rel, line, msg))

    def run(self):
        ins = cfg.solve(self.graph, self.transfer)
        self._report_leaks(ins)
        self._report_stale_uses(ins)
        return self.findings

    def _report_leaks(self, ins):
        fname = self.func.name
        for (state, name, line, klass) in sorted(
                ins[self.graph.raise_exit.idx]):
            if state != "open":
                continue
            if klass == "popen":
                self._report("RL005", line,
                             f"Popen '{name}' in {fname}() is never "
                             f"waited/terminated on an exception path "
                             f"— settle it in a finally or store it")
            else:
                self._report("RL001", line,
                             f"{klass} '{name}' in {fname}() leaks on "
                             f"an exception path — close it in a "
                             f"finally, own it with `with`, or "
                             f"store/return it before calls that can "
                             f"raise")
        for (state, name, line, klass) in sorted(
                ins[self.graph.exit.idx]):
            if state != "open":
                continue
            if klass == "popen":
                self._report("RL005", line,
                             f"Popen '{name}' in {fname}() is never "
                             f"waited/terminated on some path (zombie "
                             f"process) — wait/terminate before every "
                             f"return")
            else:
                self._report("RL002", line,
                             f"{klass} '{name}' in {fname}() leaks on "
                             f"an early-return/fall-through path — "
                             f"every non-exceptional path must close, "
                             f"store or return it")

    def _report_stale_uses(self, ins):
        for block in self.graph.blocks:
            el = block.elem
            if el is None or isinstance(el, tuple) \
                    or not isinstance(el, (ast.stmt, ast.expr)):
                continue
            facts = ins[block.idx]
            if not facts:
                continue
            closed = {}
            still_open = set()
            for f in facts:
                if f[0] == "closed":
                    closed[f[1]] = f
                else:
                    still_open.add(f[1])
            if not closed:
                continue
            for (call, recv, attr) in _direct_calls(el):
                f = closed.get(recv)
                if f is None or recv in still_open:
                    continue
                if attr in _CLOSERS.get(f[3], ()):
                    if f[3] != "popen":   # second wait() is harmless
                        self._report(
                            "RL004", call.lineno,
                            f"{f[3]} '{recv}' closed twice (already "
                            f"closed on every path reaching this "
                            f"close) — drop one, or guard with a "
                            f"None-out (`x.close(); x = None`)")
                else:
                    self._report(
                        "RL003", call.lineno,
                        f"{f[3]} '{recv}' used after close (closed on "
                        f"every path reaching this call)")
            # Argument-passing a definitely-closed resource.
            for node in ast.walk(el):
                if not isinstance(node, ast.Call):
                    continue
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name) \
                            and arg.id in closed \
                            and arg.id not in still_open:
                        f = closed[arg.id]
                        self._report(
                            "RL003", node.lineno,
                            f"{f[3]} '{arg.id}' passed along after "
                            f"close (closed on every path reaching "
                            f"this call)")


def check(info) -> list:
    findings = []
    for node in ast.walk(info.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings += _FuncAnalysis(info, node).run()
    return findings
