"""Host-side precomputation of the prefix-tree evaluation grid.

The candidate-prefix set and level are *public* protocol data (part of
the aggregation parameter), identical for every report in a batch — so
the tree shape, gather indices, node-proof binders and check ordering
are all computed once on the host with plain Python and baked into the
compiled program as static data.  Only seeds/payloads/proofs (secret,
per-report) live on device.

The grid reproduces the reference's breadth-first materialization
order (/root/reference/poc/mastic.py:258-287): at each depth, children
are generated left-then-right from lexicographically sorted parents,
which keeps every level lexicographically sorted (see
mastic_tpu.vidpf.tree_schedule, the scalar twin of this module).
"""

from typing import Sequence

import numpy as np

from ..common import to_le_bytes
from ..vidpf import Path, encode_path


class LevelSchedule:
    """The dense node grid for evaluating `prefixes` at `level`.

    Attributes (per depth d in 0..level, node arrays hold the children
    at depth d+1 in lexicographic order):

      num_children[d]   2 * number of distinct d-bit parent paths
      parent_index[d]   for d>0: position of each depth-d parent in the
                        depth d-1 child array (None at d=0: the root)
      node_binder[d]    static node-proof binder bytes per child,
                        uint8 (num_children[d], 4 + ceil((d+1)/8))
      internal_index[d] for d<level: positions in child array d of the
                        nodes whose children are materialized at d+1 —
                        the payload-check participants, in BFS order
      out_index         position of each requested prefix (caller's
                        order) in the child array at depth `level`
    """

    def __init__(self, prefixes: Sequence[Path], level: int, bits: int):
        if any(len(p) != level + 1 for p in prefixes):
            raise ValueError("prefix with incorrect length")
        if len(set(prefixes)) != len(prefixes):
            raise ValueError("candidate prefixes are non-unique")
        self.level = level
        self.bits = bits
        self.prefixes = tuple(prefixes)

        parents: list[list[Path]] = [
            sorted(set(p[:d] for p in prefixes)) for d in range(level + 1)
        ]
        children: list[list[Path]] = [
            [par + (b,) for par in parents[d] for b in (False, True)]
            for d in range(level + 1)
        ]
        child_pos: list[dict[Path, int]] = [
            {path: i for (i, path) in enumerate(lvl)} for lvl in children
        ]

        self.num_children = [len(lvl) for lvl in children]
        self.parent_index: list[np.ndarray | None] = [None]
        for d in range(1, level + 1):
            self.parent_index.append(np.array(
                [child_pos[d - 1][par] for par in parents[d]], np.int32))

        self.node_binder = []
        for d in range(level + 1):
            binder = np.stack([
                np.frombuffer(
                    to_le_bytes(bits, 2) + to_le_bytes(d, 2)
                    + encode_path(path), np.uint8)
                for path in children[d]
            ])
            self.node_binder.append(binder)

        self.internal_index: list[np.ndarray] = []
        for d in range(level):
            self.internal_index.append(np.array(
                [child_pos[d][par] for par in parents[d + 1]], np.int32))

        self.out_index = np.array(
            [child_pos[level][p] for p in self.prefixes], np.int32)

    @property
    def total_nodes(self) -> int:
        """Total materialized nodes = onehot-binder length in proofs."""
        return sum(self.num_children)
