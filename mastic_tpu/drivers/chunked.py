"""Report-chunked incremental heavy hitters: the at-scale execution
model (PERF.md §4's production plan).

The incremental engine's cross-round carry is O(BITS x width) per
report — far beyond HBM at the north-star shape (1M reports x 256
bits).  The protocol is embarrassingly parallel across reports
(reference loop /root/reference/poc/examples.py:49-71 is per-report;
aggregation is a plain sum, mastic.py:384-397), so the production
model streams fixed-size report chunks through each round:

* the full report batch and every chunk's cross-round carry live in
  HOST memory; the device holds exactly one chunk's state at a time
  (the steady-state tile bench.py measures);
* all chunks share one compiled round program (the last chunk is
  padded with dead lanes, masked out of acceptance and aggregation);
* each chunk's aggregate share is accumulated on the host, so the
  collector-facing results are bit-identical to the unchunked runner
  (tests/test_chunked.py locks this).

Memory accounting (`memory_accounting()`) reports the per-chunk device
footprint vs the total host footprint — the numbers that justify the
design at shapes where the unchunked carry cannot exist on one chip.
"""

import os
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..common import vec_add
from ..metrics import (RoundMetrics, attribute_rejections,
                       count_round_bytes, count_round_ops)
from ..backend.mastic_jax import BatchedMastic, ReportBatch

# Memory budgets the feasibility guard enforces (PERF.md §4 derives
# the envelope at the north-star shape).  The device default is a
# conservative single-chip HBM allowance (16 GiB parts, XLA scratch
# headroom); <= 0 disables a budget.
DEVICE_BUDGET_DEFAULT = 12 << 30

# Double buffering: the pipelined executor (drivers/pipeline.py)
# keeps exactly one extra chunk's resident state in flight.
PIPELINE_CHUNKS_IN_FLIGHT = 2


def _device_budget() -> int:
    return int(os.environ.get("MASTIC_DEVICE_BUDGET_BYTES",
                              DEVICE_BUDGET_DEFAULT))


def _host_budget() -> int:
    env = os.environ.get("MASTIC_HOST_BUDGET_BYTES")
    if env is not None:
        return int(env)
    try:
        total = (os.sysconf("SC_PAGE_SIZE")
                 * os.sysconf("SC_PHYS_PAGES"))
    except (ValueError, OSError):
        return 0
    # A cgroup limit below physical RAM is where the OOM kill actually
    # lands — honor it (v2 then v1; "max" / absent means unlimited).
    for path in ("/sys/fs/cgroup/memory.max",
                 "/sys/fs/cgroup/memory/memory.limit_in_bytes"):
        try:
            with open(path) as f:
                text = f.read().strip()
            if text.isdigit():
                total = min(total, int(text))
        # best-effort cgroup probe: an absent / unreadable limit file
        # simply means no cgroup cap applies
        except OSError:  # mastic-allow: RB002 — absence means no limit
            pass
    return int(total * 0.9)


def per_report_bytes(bm: BatchedMastic, width: int) -> dict:
    """Analytic per-report footprint of the chunked execution model
    (the arrays init_carry / roundkeys / HostReportStore actually
    allocate; tests/test_chunked.py pins these against the real
    allocations).  All three scale linearly in reports, so the
    envelope below is exact, not an estimate."""
    vid = bm.vidpf
    spec = bm.spec
    bits = vid.BITS
    limb_bytes = vid.VALUE_LEN * spec.num_limbs * 4
    # Carry (backend/incremental.py Carry, both aggregators): the
    # w/proof planes carry the whole BITS x width capacity; seed/ctrl
    # only the newest depth.
    carry = 2 * (bits * width * (limb_bytes + 32) + width * (16 + 1))
    # Fixed-key AES schedules (vidpf_jax.roundkeys): 2 x (11, 16).
    roundkeys = 2 * 11 * 16
    # Report store row (HostReportStore.from_batch).
    store = (16                              # nonce
             + bits * (16 + 2 + limb_bytes + 32)   # correction words
             + 2 * 16                        # VIDPF keys
             + bm.m.flp.PROOF_LEN * spec.num_limbs * 4
             + 32)                           # helper seed
    if bm.m.flp.JOINT_RAND_LEN > 0:
        store += 32 + 2 * 32                 # leader seed + peer parts
    # Worst-case binder staging: every carried depth at full width
    # (real runs prune far below; the per-round gate uses the actual
    # buckets).
    cap = 1
    while cap < bits * width:
        cap *= 2
    return {"carry": carry, "roundkeys": roundkeys, "store": store,
            "binder_peak": _binder_staging_bytes(bm, cap, cap)}


def _binder_staging_bytes(bm: BatchedMastic, onehot_cap: int,
                          payload_cap: int) -> int:
    """Per-report bytes of transient eval-proof binder staging — the
    one cost model shared by the planning envelope (worst-case
    buckets) and the per-round gate (actual buckets).  An r5
    20k × 256 device-resident run OOMed on exactly this term: two
    4.92 GiB buffers at bucket 2048 on top of 5.25 GB of carries.

    The onehot check stages a 32-byte proof row per slot of ITS pow2
    bucket and the payload check a limb row per slot of ITS bucket —
    the two buckets diverge whenever the payload row count (internal
    ancestors) trails the onehot row count (all current children), so
    each term is priced at its own bucket and summed (ADVICE r5: a
    shared max() cap overstated the peak and refused runs that fit).
    ×2 aggregators, ×2 for the gather + hash staging copies XLA
    materializes side by side."""
    limb_bytes = bm.vidpf.VALUE_LEN * bm.spec.num_limbs * 4
    return 4 * (onehot_cap * 32 + payload_cap * limb_bytes)


def memory_envelope(bm: BatchedMastic, chunk_size: int, width: int,
                    num_reports: int,
                    n_device_shards: int = 1) -> dict:
    """The (chunk_size, width) feasibility envelope: what one chunk
    costs the device and what the whole run costs the host, plus the
    largest chunk size that fits the device budget at this width.
    PERF.md §4 walks the arithmetic at the 1M x 256 north star.

    With `n_device_shards` > 1 the chunk's report axis is mesh-sharded
    and every device-resident term divides by the shard count: the
    `*_per_shard` fields price ONE chip's residency (the numbers the
    per-device budget actually bounds; tests/test_mesh_pipeline.py
    locks them against real per-device allocations).  Device rows pad
    up to the shard multiple first (uneven chunks shard by padding +
    masking, not by uneven placement — jax refuses the latter)."""
    per = per_report_bytes(bm, width)
    per_chunk = per["carry"] + per["roundkeys"] + per["store"]
    shards = max(1, n_device_shards)
    dev_rows = -(-chunk_size // shards) * shards
    rows_per_shard = dev_rows // shards
    # Worst-case round peak: resident state + binder staging with
    # every carried depth at full width.  Informational for planning
    # (real runs prune far below it) — the gating that protects a run
    # is per-round at the ACTUAL bucket, check_round_peak below.
    per_peak = per_chunk + per["binder_peak"]
    device_budget = _device_budget()
    host_budget = _host_budget()
    # Carries and round keys are allocated per padded chunk row (the
    # tail chunk is padded to chunk_size); only the store keeps exactly
    # num_reports rows.
    padded_rows = -(-num_reports // chunk_size) * chunk_size
    host_total = (padded_rows * (per["carry"] + per["roundkeys"])
                  + num_reports * per["store"])
    return {
        "bits": bm.vidpf.BITS, "width": width,
        "chunk_size": chunk_size, "num_reports": num_reports,
        "per_report_bytes": per,
        "device_bytes_per_chunk": chunk_size * per_chunk,
        "device_peak_bytes_per_chunk": chunk_size * per_peak,
        # Pipelined streaming keeps TWO chunks' resident state in
        # flight (chunk i+1 uploads while chunk i computes/downloads;
        # drivers/pipeline.py) — the binder staging is paid once, only
        # the chunk in its compute phase holds it.  The executor
        # degrades to serial when this doubled footprint would exceed
        # the budget (round_peak_bytes below, at the ACTUAL buckets).
        "pipeline_chunks_in_flight": PIPELINE_CHUNKS_IN_FLIGHT,
        "device_bytes_per_chunk_pipelined":
            PIPELINE_CHUNKS_IN_FLIGHT * chunk_size * per_chunk,
        "device_peak_bytes_per_chunk_pipelined":
            PIPELINE_CHUNKS_IN_FLIGHT * chunk_size * per_chunk
            + chunk_size * per["binder_peak"],
        "max_pipelined_chunk_size_at_width": (
            device_budget // (PIPELINE_CHUNKS_IN_FLIGHT * per_chunk)
            if device_budget > 0 else 0),
        # Per-shard residency: what ONE chip of the report-axis mesh
        # holds.  The padded device rows divide evenly by design, so
        # these are exact, not estimates.
        "report_shards": shards,
        "device_rows_per_chunk": dev_rows,
        "rows_per_shard": rows_per_shard,
        "device_bytes_per_chunk_per_shard": rows_per_shard * per_chunk,
        "device_peak_bytes_per_chunk_per_shard":
            rows_per_shard * per_peak,
        "device_bytes_per_chunk_pipelined_per_shard":
            PIPELINE_CHUNKS_IN_FLIGHT * rows_per_shard * per_chunk,
        "device_peak_bytes_per_chunk_pipelined_per_shard":
            PIPELINE_CHUNKS_IN_FLIGHT * rows_per_shard * per_chunk
            + rows_per_shard * per["binder_peak"],
        "max_chunk_size_at_width_sharded": (
            shards * (device_budget // per_chunk)
            if device_budget > 0 else 0),
        "max_pipelined_chunk_size_at_width_sharded": (
            shards * (device_budget
                      // (PIPELINE_CHUNKS_IN_FLIGHT * per_chunk))
            if device_budget > 0 else 0),
        "host_bytes_total": host_total,
        "device_budget_bytes": device_budget,
        "host_budget_bytes": host_budget,
        "max_chunk_size_at_width": (device_budget // per_chunk
                                    if device_budget > 0 else 0),
        "min_hosts": (-(-host_total // host_budget)
                      if host_budget > 0 else 1),
    }


def check_envelope(bm: BatchedMastic, chunk_size: int, width: int,
                   num_reports: int,
                   n_device_shards: int = 1) -> dict:
    """Refuse shapes outside the envelope with an actionable message
    (the guard VERDICT r4 asked for): the device check bounds one
    chunk's live state — per chip when the chunk's report axis is
    mesh-sharded over `n_device_shards` devices; the host check bounds
    the carry store and names the multi-host answer when one host
    cannot hold it."""
    env = memory_envelope(bm, chunk_size, width, num_reports,
                          n_device_shards)
    per_chip = env["device_bytes_per_chunk_per_shard"]
    max_chunk = env["max_chunk_size_at_width_sharded"]
    if env["device_budget_bytes"] > 0 \
            and per_chip > env["device_budget_bytes"]:
        chip = (f" across {n_device_shards} chips"
                if n_device_shards > 1 else "")
        if max_chunk == 0:
            raise ValueError(
                f"width {width} at {bm.vidpf.BITS} bits needs "
                f"{per_chip / 2**30:.1f} GiB per chip{chip} even for a "
                f"single-report chunk (budget "
                f"{env['device_budget_bytes'] / 2**30:.1f} GiB) — the "
                f"width itself is infeasible at this budget; raise "
                f"MASTIC_DEVICE_BUDGET_BYTES or shard the chunk over "
                f"more devices")
        raise ValueError(
            f"chunk of {chunk_size} reports needs "
            f"{per_chip / 2**30:.1f} GiB per chip{chip} "
            f"at width {width} (budget "
            f"{env['device_budget_bytes'] / 2**30:.1f} GiB); the largest "
            f"feasible chunk_size at this width is "
            f"{max_chunk} — shrink the chunk, or "
            f"raise MASTIC_DEVICE_BUDGET_BYTES if the chip has more HBM")
    if env["host_budget_bytes"] > 0 \
            and env["host_bytes_total"] > env["host_budget_bytes"]:
        raise ValueError(
            f"{num_reports} reports need "
            f"{env['host_bytes_total'] / 2**30:.1f} GiB of host memory "
            f"at width {width} (budget "
            f"{env['host_budget_bytes'] / 2**30:.1f} GiB); split the "
            f"report store across >= {env['min_hosts']} hosts, each "
            f"running its own chunked runner over its shard (carries, "
            f"round keys and store are all per-report; only the "
            f"per-round aggregate shares cross hosts), or raise "
            f"MASTIC_HOST_BUDGET_BYTES")
    return env


def round_peak_bytes(bm: BatchedMastic, onehot_cap: int,
                     payload_cap: int, chunk_rows: int,
                     resident_bytes: int, n_device_shards: int = 1,
                     chunks_in_flight: int = 1) -> int:
    """Per-chip peak of one round at the ACTUAL binder buckets:
    `chunks_in_flight` copies of the resident chunk state (the
    pipelined executor keeps two) plus ONE chunk's binder staging
    (only the chunk in its compute phase holds the staging buffers).
    The single cost model behind check_round_peak (serial, raising)
    and the pipeline executor's degrade-to-serial decision
    (non-raising, drivers/chunked.ChunkedIncrementalRunner)."""
    staging = _binder_staging_bytes(bm, onehot_cap,
                                    payload_cap) * chunk_rows
    return -(-(chunks_in_flight * resident_bytes + staging)
             // n_device_shards)


def check_round_peak(bm: BatchedMastic, onehot_cap: int,
                     payload_cap: int, chunk_rows: int,
                     resident_bytes: int, level: int,
                     n_device_shards: int = 1) -> None:
    """Per-round device-memory gate at the ACTUAL binder buckets.

    The construction-time envelope bounds resident state; the binder
    staging buffers scale with the pow2 buckets of the LIVE carried
    rows, which grow with depth and cannot be known up front without
    assuming the worst case (which would refuse prunable runs the
    hardware handles fine).  So both runners call this before each
    round with the plan's real buckets — proof staging priced at the
    onehot bucket, payload staging at the (usually smaller) payload
    bucket: a run that would OOM the chip mid-depth instead stops at
    the offending level with the remedy, and everything up to that
    level is checkpointable.  (r5: a 20k × 256 device-resident run
    died exactly this way, two 4.92 GiB staging buffers at bucket
    2048 surfacing as a remote-compile OOM.)
    """
    budget = _device_budget()
    if budget <= 0:
        return
    per_row = _binder_staging_bytes(bm, onehot_cap, payload_cap)
    staging = per_row * chunk_rows
    peak = round_peak_bytes(bm, onehot_cap, payload_cap, chunk_rows,
                            resident_bytes, n_device_shards)
    if peak > budget:
        # Largest TOTAL chunk size (across all its device shards)
        # whose peak fits: (resident_scaled + per_row*rows)/shards
        # <= budget, with resident scaling with rows too — bound it
        # conservatively by keeping resident's per-row share.
        per_row_resident = resident_bytes // max(1, chunk_rows)
        max_rows = max(0, (budget * n_device_shards)
                       // (per_row + per_row_resident))
        raise ValueError(
            f"level {level}: binder buckets {onehot_cap} (onehot) / "
            f"{payload_cap} (payload) need "
            f"{staging / 2**30:.1f} GiB of staging on top of "
            f"{resident_bytes / 2**30:.1f} GiB resident "
            f"({peak / 2**30:.1f} GiB peak per chip vs budget "
            f"{budget / 2**30:.1f} GiB) — checkpoint and resume with "
            f"a total chunk of <= {max_rows} reports (across its "
            f"{n_device_shards} device shard(s)), shard over more "
            f"devices, or raise MASTIC_DEVICE_BUDGET_BYTES")


class HostReportStore:
    """A report batch resident in host memory, sliced into fixed-size
    device chunks (the upload database of a real aggregator; the
    checkpoint note at SURVEY.md §5 scopes report persistence to the
    caller — this class is that caller-side store)."""

    def __init__(self, arrays: dict, num_reports: int, chunk_size: int):
        self.arrays = arrays
        self.num_reports = num_reports
        self.chunk_size = chunk_size
        self.num_chunks = -(-num_reports // chunk_size)
        self.use_jr = arrays.get("leader_seeds") is not None

    @classmethod
    def from_batch(cls, batch: ReportBatch,
                   chunk_size: int) -> "HostReportStore":
        """Adopt a marshalled batch (device arrays land back on host)."""
        arrays = {
            "nonces": np.asarray(batch.nonces),
            "cws_seed": np.asarray(batch.cws.seed),
            "cws_ctrl": np.asarray(batch.cws.ctrl),
            "cws_w": np.asarray(batch.cws.w),
            "cws_proof": np.asarray(batch.cws.proof),
            "keys": np.asarray(batch.keys),
            "leader_proofs": np.asarray(batch.leader_proofs),
            "helper_seeds": np.asarray(batch.helper_seeds),
            "leader_seeds": (None if batch.leader_seeds is None
                             else np.asarray(batch.leader_seeds)),
            "peer_parts": tuple(
                None if p is None else np.asarray(p)
                for p in batch.peer_parts),
        }
        return cls(arrays, int(batch.nonces.shape[0]), chunk_size)

    def chunk_bounds(self, i: int) -> tuple[int, int]:
        lo = i * self.chunk_size
        return (lo, min(lo + self.chunk_size, self.num_reports))

    def host_slice(self, x: np.ndarray, i: int) -> np.ndarray:
        """Chunk i of a per-report host array, padded to chunk_size
        with dead lanes (row 0 repeated) — the single definition of
        the padding rule (device_chunk and the runner's key-schedule
        setup must pad identically)."""
        (lo, hi) = self.chunk_bounds(i)
        sl = x[lo:hi]
        pad = self.chunk_size - (hi - lo)
        if pad:
            sl = np.concatenate([sl, np.repeat(sl[:1], pad, axis=0)],
                                axis=0)
        return sl

    def device_chunk(self, i: int,
                     rows: Optional[int] = None
                     ) -> tuple[ReportBatch, np.ndarray]:
        """Chunk i as device arrays, padded to `rows` (default
        chunk_size) with dead lanes (row 0 repeated).  A mesh-sharded
        round passes rows = the next shard multiple of chunk_size so
        the padded tile places evenly across the report axis; the live
        mask excludes every padded lane either way.  Returns
        (batch, live mask)."""
        from ..backend.vidpf_jax import BatchedCorrectionWords

        if rows is None:
            rows = self.chunk_size
        (lo, hi) = self.chunk_bounds(i)

        def take(x):
            return None if x is None \
                else jnp.asarray(_pad_rows(self.host_slice(x, i), rows))

        a = self.arrays
        batch = ReportBatch(
            nonces=take(a["nonces"]),
            cws=BatchedCorrectionWords(
                seed=take(a["cws_seed"]), ctrl=take(a["cws_ctrl"]),
                w=take(a["cws_w"]), proof=take(a["cws_proof"])),
            keys=take(a["keys"]),
            leader_proofs=take(a["leader_proofs"]),
            helper_seeds=take(a["helper_seeds"]),
            leader_seeds=take(a["leader_seeds"]),
            peer_parts=tuple(take(p) for p in a["peer_parts"]))
        live = np.zeros(rows, bool)
        live[:hi - lo] = True
        return (batch, live)

    def host_bytes(self) -> int:
        total = 0
        for v in self.arrays.values():
            if isinstance(v, tuple):
                total += sum(x.nbytes for x in v if x is not None)
            elif v is not None:
                total += v.nbytes
        return total


def _pad_rows(x: np.ndarray, rows: int) -> np.ndarray:
    """Pad a per-report host array's leading axis to `rows` dead lanes
    (first row repeated — the same rule as HostReportStore.host_slice,
    so serial and mesh-padded tiles compute identical dead-lane data
    and the downloaded carries stay bit-identical after trimming)."""
    pad = rows - x.shape[0]
    if pad <= 0:
        return x
    return np.concatenate([x, np.repeat(x[:1], pad, axis=0)], axis=0)


class _ChunkState(NamedTuple):
    """One chunk's host-resident cross-round state: both aggregators'
    carries plus the per-report AES round keys (kept so rounds > 0
    skip the key-schedule recompute)."""
    carries: list   # [Carry-of-numpy x 2]
    ext_rk: np.ndarray
    conv_rk: np.ndarray


def _carry_to_host(carry):
    from ..backend.incremental import Carry

    return Carry(w=np.asarray(carry.w), proof=np.asarray(carry.proof),
                 seed=np.asarray(carry.seed),
                 ctrl=np.asarray(carry.ctrl))


def _carry_to_device(carry, rows: Optional[int] = None):
    from ..backend.incremental import Carry

    def up(x):
        return jnp.asarray(x if rows is None else _pad_rows(x, rows))

    return Carry(w=up(carry.w), proof=up(carry.proof),
                 seed=up(carry.seed), ctrl=up(carry.ctrl))


def _carry_trim(carry, rows: int):
    """Drop mesh-padding lanes from a downloaded host carry (inverse
    of _carry_to_device's pad; a no-op when nothing was padded)."""
    from ..backend.incremental import Carry

    if carry.w.shape[0] <= rows:
        return carry
    return Carry(w=carry.w[:rows], proof=carry.proof[:rows],
                 seed=carry.seed[:rows], ctrl=carry.ctrl[:rows])


def _carry_bytes(carry) -> int:
    # .nbytes is metadata on both np and jax arrays — never forces a
    # device->host transfer (np.asarray on a device carry would).
    return sum(x.nbytes for x in carry)


from .heavy_hitters import RoundPrograms


class ChunkedIncrementalRunner(RoundPrograms):
    """Drives backend/incremental.py chunk by chunk.

    External contract matches _IncrementalRunner (round(),
    width/fallback/layouts, checkpoint arrays), so
    HeavyHittersRun can swap it in when a chunk size is given; the
    jitted round programs are shared via RoundPrograms.
    """

    def __init__(self, bm: BatchedMastic, verify_key: bytes, ctx: bytes,
                 store: HostReportStore, reports: Optional[list] = None,
                 width: int = 8, n_device_shards: int = 1,
                 mesh=None):
        from ..backend.incremental import IncrementalMastic

        self.bm = bm
        self.verify_key = verify_key
        self.ctx = ctx
        self.store = store
        self.reports = reports
        self.num_reports = store.num_reports
        self.fallback = np.zeros(self.num_reports, bool)
        self.width = max(4, width)
        # A mesh given at construction shards every chunk's report
        # axis from round 0 (parallel/mesh.shard_incremental_runner
        # attaching one later is equivalent — the chunked runner's
        # cross-round state lives on the host, so there is nothing to
        # re-place).
        self.mesh = mesh
        if mesh is not None:
            n_device_shards = mesh.shape["reports"]
        self.n_device_shards = max(1, n_device_shards)
        check_envelope(bm, store.chunk_size, self.width,
                       self.num_reports, self.n_device_shards)
        self.engine = IncrementalMastic(bm, self.width)
        self._init_programs()
        # Warm artifact store (drivers/artifacts.py): preload the
        # first round's programs before anything compiles, so a
        # baked store makes construction + round 0 trace-free (the
        # key-schedule program below included); deeper levels
        # prefetch in the predictor's overlapped warm slot.
        self._preload_first_round(self._device_rows(),
                                  store.chunk_size)
        self.chunks = [self._init_chunk(i)
                       for i in range(store.num_chunks)]
        self.layouts: list = []  # per-depth creation layouts

    def _init_chunk(self, i: int) -> _ChunkState:
        """Initial carries and AES round keys for chunk i — built from
        cheap host slices (only the nonces cross to the device for the
        key schedules; uploading the whole chunk batch here would
        stream the full O(BITS) report store through the device,
        exactly the startup cost the chunked design avoids)."""
        nonces = self.store.host_slice(self.store.arrays["nonces"], i)
        keys = self.store.host_slice(self.store.arrays["keys"], i)
        nonce_dev = jnp.asarray(nonces)
        (rk_prog, _rk_wait) = self._rk_program(self.store.chunk_size,
                                               (nonce_dev,))
        (ext_rk, conv_rk) = rk_prog(nonce_dev)
        carries = [
            self.engine.init_carry(self.store.chunk_size, keys[:, a],
                                   a, host=True)
            for a in range(2)
        ]
        return _ChunkState(carries=carries,
                           ext_rk=np.asarray(ext_rk),
                           conv_rk=np.asarray(conv_rk))

    def _grow(self, width: int) -> None:
        from ..backend.incremental import Carry, IncrementalMastic

        n = (self.mesh.shape["reports"] if self.mesh is not None
             else self.n_device_shards)
        check_envelope(self.bm, self.store.chunk_size, width,
                       self.num_reports, n)
        pad = width - self.width
        for cs in self.chunks:
            for a in range(2):
                c = cs.carries[a]
                cs.carries[a] = Carry(
                    w=np.pad(c.w, ((0, 0), (0, 0), (0, pad),
                                   (0, 0), (0, 0))),
                    proof=np.pad(c.proof,
                                 ((0, 0), (0, 0), (0, pad), (0, 0))),
                    seed=np.pad(c.seed, ((0, 0), (0, pad), (0, 0))),
                    ctrl=np.pad(c.ctrl, ((0, 0), (0, pad))),
                )
        self.width = width
        self.engine = IncrementalMastic(self.bm, width)
        # AOT programs key on their shapes (the grown width maps to
        # fresh keys); only the jitted closures capture the engine.
        self._eval_fn = None
        self._combine_fn = None

    # -- one round over every chunk --------------------------------

    def _report_shards(self) -> int:
        """Report-axis device count this runner's chunks spread over
        (mesh wins over the construction-time hint; 1 = single chip).
        """
        return (self.mesh.shape["reports"] if self.mesh is not None
                else self.n_device_shards)

    def _device_rows(self) -> int:
        """Rows of one chunk's DEVICE tile: chunk_size padded up to
        the mesh's shard multiple (jax refuses uneven placement, so
        an uneven tail shards by padding + masking — the dead lanes
        are excluded from acceptance and aggregation exactly like the
        tail chunk's existing chunk_size padding)."""
        n = (self.mesh.shape["reports"] if self.mesh is not None
             else 1)
        return -(-self.store.chunk_size // n) * n

    def _resident_dev_bytes(self) -> int:
        """One device tile's resident bytes at the padded row count
        (the measured per-chunk accounting scaled from chunk_size to
        the mesh-padded rows)."""
        acct = self.memory_accounting()["device_bytes_per_chunk"]
        return acct * self._device_rows() // self.store.chunk_size

    def _pipeline_mode(self, plan) -> tuple:
        """(mode, fallback_reason): whether this round runs the
        double-buffered executor or degrades to serial — and why, so
        the fallback is named in metrics, never silent.  Mesh-sharded
        rounds pipeline like single-chip ones (the r10 tentpole); the
        budget term prices the PER-SHARD doubled footprint."""
        from .pipeline import pipeline_enabled

        if not pipeline_enabled():
            return ("serial", "lever-off")
        if self.store.num_chunks < 2:
            return ("serial", "single-chunk")
        budget = _device_budget()
        if budget > 0:
            peak = round_peak_bytes(
                self.bm, len(plan.onehot_idx),
                len(plan.payload_parent), self._device_rows(),
                self._resident_dev_bytes(),
                self._report_shards(),
                chunks_in_flight=PIPELINE_CHUNKS_IN_FLIGHT)
            if peak > budget:
                return ("serial", "device-budget")
        return ("pipelined", None)

    def round(self, agg_param,
              metrics_out: Optional[list] = None) -> list:
        """One round over every chunk on the pipelined executor
        (drivers/pipeline.py): chunk i+1's batch and carries upload
        and its whole eval -> weight-check -> mask-combine ->
        aggregate chain dispatches while chunk i computes and its
        result carries download — one blocking host sync per chunk,
        issued after the next chunk's work is in flight.  The
        accept/ok/weight-check masks combine ON DEVICE (exactly the
        serial boolean algebra, so aggregates are bit-identical),
        and the per-chunk phase timeline lands in
        `RoundMetrics.extra`.  Degrades to serial (same stage/collect
        bodies, no overlap) when the doubled in-flight footprint
        exceeds the device budget — the fallback is named in
        metrics."""
        from ..backend.incremental import round_inputs
        from .heavy_hitters import _vk_array, splice_rejected
        from .pipeline import overlap_efficiency, run_chunks

        (level, prefixes, do_weight_check) = agg_param
        plan = self._plan(prefixes, level)
        shards = self._report_shards()
        dev_rows = self._device_rows()
        check_round_peak(
            self.bm,
            len(plan.onehot_idx), len(plan.payload_parent),
            dev_rows, self._resident_dev_bytes(), level, shards)
        (mode, fb_reason) = self._pipeline_mode(plan)
        rnd = round_inputs(plan)
        vk_arr = _vk_array(self.verify_key)
        ones = jnp.ones(dev_rows, bool)
        if self.mesh is not None:
            # Small per-round inputs replicate across the mesh, the
            # per-report ones mask shards — pinned explicitly so the
            # warm-compiled sharded programs see exactly these
            # shardings at dispatch (heavy_hitters.RoundPrograms).
            from ..parallel.mesh import place_replicated, place_reports
            (rnd, vk_arr) = place_replicated(self.mesh, (rnd, vk_arr))
            ones = place_reports(self.mesh, ones)
        rows = len(prefixes) * (1 + self.bm.m.flp.OUTPUT_LEN)
        chunk_size = self.store.chunk_size

        agg_shares = [[self.bm.m.field(0)] * rows for _ in range(2)]
        accept_all = np.zeros(self.num_reports, bool)
        # Per-check masks across chunks, so rejection attribution
        # matches the resident runner's (first-failing-check order).
        eval_ok_all = np.zeros(self.num_reports, bool)
        wc_ok_all = (np.zeros(self.num_reports, bool)
                     if do_weight_check else None)
        jr_ok_all: Optional[np.ndarray] = None
        warm_args: list = [None]
        warm_spent: list = [0.0]
        psum_bytes: list = [0]
        shard_skews: dict = {}

        def stage(i: int):
            """Upload chunk i and dispatch its full device chain —
            returns futures only, no blocking sync."""
            cs = self.chunks[i]
            t0 = time.perf_counter()
            (batch, live) = self.store.device_chunk(i, rows=dev_rows)
            (lo, hi) = self.store.chunk_bounds(i)
            # The aggregation validity mask, known at stage time: live
            # (non-padding) lanes whose device carry was intact BEFORE
            # this round.  This round's ok / wc_ok fold in on device,
            # reproducing the serial path's fallback-then-mask order.
            valid = live.copy()
            valid[:hi - lo] &= ~self.fallback[lo:hi]
            dev_c0 = _carry_to_device(cs.carries[0], dev_rows)
            dev_c1 = _carry_to_device(cs.carries[1], dev_rows)
            ext_rk = jnp.asarray(_pad_rows(cs.ext_rk, dev_rows))
            conv_rk = jnp.asarray(_pad_rows(cs.conv_rk, dev_rows))
            valid_dev = jnp.asarray(valid)
            if self.mesh is not None:
                # Chunk upload lands report-sharded across the mesh;
                # aggregation below is the only cross-chip collective.
                from ..parallel.mesh import place_reports
                (batch, dev_c0, dev_c1, ext_rk, conv_rk, valid_dev) = \
                    place_reports(self.mesh,
                                  (batch, dev_c0, dev_c1, ext_rk,
                                   conv_rk, valid_dev))
            t_up = time.perf_counter()
            args = (vk_arr, dev_c0, dev_c1, rnd, ext_rk, conv_rk,
                    batch.cws)
            (eval_prog, compile_s) = self._eval_program(
                dev_rows, plan, args)
            t_d0 = time.perf_counter()
            (c0, c1, out0, out1, accept_ev, ok) = eval_prog(*args)
            wc_checks = {}
            wc_compile_s = 0.0
            (wc_accept, wc_okdev, jr) = (ones, ones, ones)
            if do_weight_check:
                wcargs = (vk_arr, batch, c0.w[:, 0, :2],
                          c1.w[:, 0, :2])
                (wc_prog, wc_compile_s) = self._wc_program(
                    dev_rows, level, wcargs)
                (wc_checks, wc_okdev) = wc_prog(*wcargs)
                wc_accept = wc_checks["weight_check"]
                jr = wc_checks.get("joint_rand", ones)
            cargs = (out0, out1, accept_ev, ok, valid_dev,
                     wc_accept, wc_okdev, jr)
            (agg_prog, agg_compile_s) = self._agg_program(
                dev_rows, cargs)
            (accept_dev, agg0, agg1) = agg_prog(*cargs)
            t_d1 = time.perf_counter()
            if warm_args[0] is None:
                warm_args[0] = args  # shape template for _warm_next
            compile_ms = (compile_s + agg_compile_s
                          + wc_compile_s) * 1e3
            phases = {
                "upload_ms": round((t_up - t0) * 1e3, 3),
                "compile_ms": round(compile_ms, 3),
                "dispatch_ms": round(
                    (t_d1 - t_d0 - compile_s - agg_compile_s
                     - wc_compile_s) * 1e3, 3),
            }
            handle = (c0, c1, accept_ev, ok, wc_checks, wc_okdev,
                      accept_dev, agg0, agg1)
            return (handle, phases)

        def collect(i: int, handle) -> dict:
            """Chunk i's single blocking sync, downloads, host fold."""
            (c0, c1, accept_ev, ok, wc_checks, wc_okdev,
             accept_dev, agg0, agg1) = handle
            cs = self.chunks[i]
            (lo, hi) = self.store.chunk_bounds(i)
            t0 = time.perf_counter()
            if self.mesh is not None and shards > 1:
                # Per-shard completion skew, measured inside the
                # chunk's one sync window: block the report-sharded
                # accept mask shard by shard (device order) before the
                # global sync — the straggler shard shows up as the
                # max-min spread.  Observability only; the arithmetic
                # never depends on it.
                waits = []
                for sh in accept_dev.addressable_shards:
                    sh.data.block_until_ready()
                    waits.append((time.perf_counter() - t0) * 1e3)
                shard_skews[i] = round(max(waits) - min(waits), 3)
                # One psum per aggregator's replicated aggregate.
                psum_bytes[0] += agg0.nbytes + agg1.nbytes
            jax.block_until_ready(
                (c0, c1, accept_ev, ok, wc_checks, wc_okdev,
                 accept_dev, agg0, agg1))
            t_wait = time.perf_counter()
            cs.carries[0] = _carry_trim(_carry_to_host(c0), chunk_size)
            cs.carries[1] = _carry_trim(_carry_to_host(c1), chunk_size)
            ok_np = np.asarray(ok)
            accept_ev_np = np.asarray(accept_ev)
            accept_np = np.asarray(accept_dev)
            agg_np = [np.asarray(agg0), np.asarray(agg1)]
            wc_np = {k: np.asarray(v) for (k, v) in wc_checks.items()}
            wc_ok_np = (np.asarray(wc_okdev) if do_weight_check
                        else None)
            t_down = time.perf_counter()
            self.fallback[lo:hi] |= ~ok_np[:hi - lo]
            eval_ok_all[lo:hi] = accept_ev_np[:hi - lo]
            if do_weight_check:
                self.fallback[lo:hi] |= ~wc_ok_np[:hi - lo]
                wc_ok_all[lo:hi] = wc_np["weight_check"][:hi - lo]
                if "joint_rand" in wc_np:
                    nonlocal jr_ok_all
                    if jr_ok_all is None:
                        jr_ok_all = np.zeros(self.num_reports, bool)
                    jr_ok_all[lo:hi] = wc_np["joint_rand"][:hi - lo]
            for a in range(2):
                agg_shares[a] = vec_add(
                    agg_shares[a],
                    self.bm.agg_share_to_host(agg_np[a][:rows]))
            accept_all[lo:hi] = accept_np[:hi - lo]
            t_host = time.perf_counter()
            return {
                "compute_wait_ms": round((t_wait - t0) * 1e3, 3),
                "download_ms": round((t_down - t_wait) * 1e3, 3),
                "host_ms": round((t_host - t_down) * 1e3, 3),
            }

        def warm_predicted() -> None:
            # Every chunk's device work is dispatched and the host is
            # about to idle in the final blocking sync: compile the
            # predicted next level's programs while the device
            # computes through them (see pipeline.ProgramCache for
            # why this is synchronous, not a compiler thread).
            warm_spent[0] = self._warm_next(plan, warm_args[0],
                                            dev_rows)

        from .pipeline import paused_gc
        with paused_gc():
            # GC paused for the chunk loop: its traces (first-call
            # jits, inline lowers) segfault this jaxlib if a
            # collection fires mid-trace (pipeline.paused_gc).
            (timeline, wall_ms) = run_chunks(
                self.store.num_chunks, stage, collect,
                pipelined=(mode == "pipelined"),
                before_last_collect=warm_predicted)

        evals_per_report = 2 * plan.parent_count * 2  # both parties
        for rec in timeline:
            (lo, hi) = self.store.chunk_bounds(rec["chunk"])
            span_s = max(rec["collect_end_ms"]
                         - rec["stage_start_ms"], 1e-3) / 1e3
            rec["reports"] = hi - lo
            rec["wall_ms"] = round(span_s * 1e3, 2)
            # Live-report rate (comparable across full and partial
            # chunks) AND the padded device-work rate — the tail chunk
            # computes dev_rows padded lanes but only hi-lo of them
            # are reports, so a single padded-rate stamp would
            # overstate tail throughput (r9's honesty fix, extended
            # to the mesh's shard-multiple padding).
            rec["node_evals_per_sec"] = round(
                (hi - lo) * evals_per_report / span_s, 1)
            rec["node_evals_per_sec_padded"] = round(
                dev_rows * evals_per_report / span_s, 1)
            if self.mesh is not None:
                # Per-shard twins of both stamps: each chip computes
                # dev_rows/shards lanes of the chunk, so the per-shard
                # rate is the number the single-chip roofline compares
                # against (PERF.md §8).
                rec["node_evals_per_sec_per_shard"] = round(
                    rec["node_evals_per_sec"] / shards, 1)
                rec["node_evals_per_sec_padded_per_shard"] = round(
                    rec["node_evals_per_sec_padded"] / shards, 1)
                if rec["chunk"] in shard_skews:
                    rec["shard_wait_skew_ms"] = \
                        shard_skews[rec["chunk"]]
        chunk_stats = timeline

        assert level == len(self.layouts)
        self.layouts.append(plan.layout_new)

        metrics = RoundMetrics(level=level,
                               frontier_width=len(prefixes),
                               padded_width=self.width,
                               reports_total=self.num_reports)
        attribute_rejections(metrics, eval_ok_all, wc_ok_all,
                             jr_ok_all, device_ok=~self.fallback)
        count_round_ops(metrics, self.bm.m, self.num_reports,
                        2 * plan.parent_count,
                        include_key_setup=(level == 0))
        count_round_bytes(metrics, self.bm.m, agg_param,
                          self.num_reports)
        metrics.extra["chunks"] = chunk_stats
        metrics.extra["memory"] = self.memory_accounting()
        compile_inline_ms = sum(rec["phases"].get("compile_ms", 0.0)
                                for rec in timeline)
        metrics.extra["pipeline"] = {
            "mode": mode,
            "fallback": fb_reason,
            "round_wall_ms": round(wall_ms, 2),
            "overlap_efficiency": overlap_efficiency(timeline,
                                                     wall_ms),
            "compile_inline_ms": round(compile_inline_ms, 2),
            "warm_ms": round(warm_spent[0] * 1e3, 2),
            # One blocking sync per chunk (the executor contract) —
            # stamped here too so the pipeline block carries the same
            # key set as the resident producer (obs/schema.py).
            "host_syncs": sum(rec["host_syncs"] for rec in timeline),
            "aot": self._aot_summary(dev_rows, plan,
                                     compile_inline_ms),
        }
        metrics.extra["artifacts"] = self._artifacts_block()
        if self.mesh is not None:
            # Collective overhead made observable (not inferred): one
            # psum of each aggregator's O(frontier) aggregate share
            # per chunk is the round's ONLY cross-chip traffic.
            skews = sorted(shard_skews.values())
            metrics.extra["mesh"] = {
                "report_shards": shards,
                "device_rows_per_chunk": dev_rows,
                "rows_per_shard": dev_rows // shards,
                "psum_bytes_per_round": psum_bytes[0],
                "shard_wait_skew_ms_p50":
                    (skews[len(skews) // 2] if skews else 0.0),
                "shard_wait_skew_ms_max":
                    (skews[-1] if skews else 0.0),
            }

        splice_rejected(self.bm.m, self.verify_key, self.ctx, agg_param,
                        self.reports, ~self.fallback, accept_all,
                        agg_shares)
        metrics.accepted = int(accept_all.sum())
        metrics.xof_fallbacks = int(self.fallback.sum())
        metrics.rejected_fallback = int(
            (self.fallback & ~accept_all).sum())
        if metrics_out is not None:
            metrics_out.append(metrics)
        num = int(accept_all.sum())
        return self.bm.m.unshard(agg_param, agg_shares, num)

    def memory_accounting(self) -> dict:
        """Device-vs-host footprint: the chunked design's reason to
        exist.  Device holds one chunk (2 carries + batch tile); host
        holds every chunk's carry plus the report store."""
        carry = 2 * _carry_bytes(self.chunks[0].carries[0])
        rk = (self.chunks[0].ext_rk.nbytes
              + self.chunks[0].conv_rk.nbytes)
        store = self.store
        tile = 0
        for v in store.arrays.values():
            if isinstance(v, tuple):
                tile += sum(x[:1].nbytes * store.chunk_size
                            for x in v if x is not None)
            elif v is not None:
                tile += v[:1].nbytes * store.chunk_size
        host = (sum(2 * _carry_bytes(cs.carries[0]) + cs.ext_rk.nbytes
                    + cs.conv_rk.nbytes for cs in self.chunks)
                + store.host_bytes())
        return {
            "chunk_size": store.chunk_size,
            "num_chunks": store.num_chunks,
            "device_bytes_per_chunk": carry + rk + tile,
            "device_carry_bytes": carry,
            "host_bytes_total": host,
        }

    # -- checkpoint hooks (HeavyHittersRun.to_bytes/from_bytes) ----

    def state_arrays(self) -> dict:
        from ..backend.incremental import carry_to_arrays

        data: dict = {"chunk_size": np.int64(self.store.chunk_size)}
        for (i, cs) in enumerate(self.chunks):
            data.update(carry_to_arrays(cs.carries[0], f"k{i}_c0_"))
            data.update(carry_to_arrays(cs.carries[1], f"k{i}_c1_"))
        return data

    def load_state(self, arrays, num_chunks: int) -> None:
        from ..backend.incremental import carry_from_arrays

        for i in range(num_chunks):
            self.chunks[i].carries[0] = _carry_to_host(
                carry_from_arrays(arrays, f"k{i}_c0_"))
            self.chunks[i].carries[1] = _carry_to_host(
                carry_from_arrays(arrays, f"k{i}_c1_"))
