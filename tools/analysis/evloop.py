"""Pass 9 — event-loop readiness (EV001-EV003, ISSUE 17).

Scope: the same session/network plane as the lifetime pass — the
code ROADMAP item 1 rewrites onto a selector event loop.  A function
runs in a *non-blocking context* when it must not stall the loop:

  * it makes a socket non-blocking (`x.setblocking(False)`), or
  * it is registered as a selector callback (the data/3rd argument
    of a `.register(...)` call that resolves to a known function), or
  * the call graph reaches it ONLY from such functions — a helper
    called both from a non-blocking context and from ordinary
    blocking code is left alone (its blocking caller proves the call
    may legitimately wait).

Seed discovery and reachability ride the whole-program model
(`callgraph.Program`, strong edges only — a multi-candidate name
dispatch must not drag half the program into the loop's context);
the lock facts reuse the same model the concurrency pass consumes.

  EV001  blocking call in a non-blocking context: recv / recv_into /
         accept / do_handshake / sleep / thread join / bare
         queue-style `.get()` with no timeout.  A `timeout=` keyword
         exempts the call; readiness ops (recv/recv_into/accept) are
         exempt inside a selector callback or a function that drives
         `.select()` itself — there the loop has already proven the
         fd ready.
  EV002  send loop without writability registration: a `while` loop
         in a non-blocking context that calls `.send`/`.sendall`
         with no `.register`/`.modify`/`.select` inside the loop —
         a slow reader turns the loop body into a spin or a stall.
  EV003  blocking call while holding a lock in a non-blocking
         context (reported INSTEAD of EV001 for that call): the
         loop stalls AND every thread needing the lock queues
         behind it.

Known blind spots (documented in USAGE.md): callbacks passed through
containers or partial(), `setblocking` reached via helpers, and
fileobj readiness checked by hand with `select.select` on lists.
"""

import ast

from .core import Finding, dotted
from .callgraph import _Scope

PASS_NAME = "evloop"
WHOLE_PROGRAM = True

RULES = {
    "EV001": "blocking call in a non-blocking (event-loop) context",
    "EV002": "send loop without writability registration",
    "EV003": "blocking call under a held lock in a non-blocking "
             "context",
}

SCOPE_PREFIXES = ("mastic_tpu/net/",)
EXTRA_FILES = ("mastic_tpu/drivers/session.py",
               "mastic_tpu/drivers/parties.py",
               "tools/party.py", "tools/serve.py", "tools/loadgen.py")

_BLOCKING_ATTRS = {"recv", "recv_into", "accept", "do_handshake",
                   "sleep", "join", "get"}
_BLOCKING_NAMES = {"sleep"}
_READINESS_OPS = {"recv", "recv_into", "accept"}
_LOOP_DRIVER_OPS = {"register", "modify", "select"}


def in_scope(rel: str) -> bool:
    return rel.startswith(SCOPE_PREFIXES) or rel in EXTRA_FILES


def check(info) -> list:
    """Per-file entry point kept for interface symmetry; the real
    work happens in check_program (the driver calls it once with the
    run's Program)."""
    return []


# -- non-blocking context discovery -----------------------------------

def _sets_nonblocking(fn) -> bool:
    for node in _Scope.iter(fn.node):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "setblocking" \
                and len(node.args) == 1 \
                and isinstance(node.args[0], ast.Constant) \
                and not node.args[0].value:
            return True
    return False


def _resolve_value(program, fn, expr):
    """The FuncNode a callback-valued expression names, or None."""
    if isinstance(expr, ast.Name):
        nested = program.functions.get(
            f"{fn.qual}.<locals>.{expr.id}")
        if nested is not None:
            return nested
        hit = program.names.get((fn.module, expr.id))
        if hit and hit[0] == "func":
            return program.functions.get(hit[1])
        return None
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self" and fn.cls is not None:
        return program._method_in(fn.cls, expr.attr)
    return None


def _callback_seeds(program) -> set:
    """Functions registered as selector callbacks: the data/3rd
    argument of any `.register(...)` call that resolves."""
    out = set()
    for fn in program.functions.values():
        for (call, _targets) in fn.callees:
            f = call.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr == "register"):
                continue
            cand = call.args[2] if len(call.args) >= 3 else None
            for kw in call.keywords:
                if kw.arg == "data":
                    cand = kw.value
            target = _resolve_value(program, fn, cand) \
                if cand is not None else None
            if target is not None:
                out.add(target.qual)
    return out


def _blocking_reach(program, seeds: set) -> set:
    """Functions reachable from a blocking-OK entry (module bodies,
    API entry points, thread roots) WITHOUT passing through a seed —
    these may legitimately wait, so the pass leaves them alone."""
    stack = []
    for fn in program.functions.values():
        if (fn.is_module or not fn.callers) and fn.qual not in seeds:
            stack.append(fn.qual)
    for roots in program.thread_roots.values():
        for t in roots:
            if t.qual not in seeds:
                stack.append(t.qual)
    seen: set = set()
    while stack:
        q = stack.pop()
        if q in seen or q in seeds:
            continue
        seen.add(q)
        fn = program.functions.get(q)
        if fn is None:
            continue
        for (call, targets) in fn.callees:
            if id(call) in fn.weak_calls:
                continue
            for t in targets:
                stack.append(t.qual)
    return seen


def nonblocking_contexts(program) -> set:
    """Quals of every function the pass holds to the no-blocking
    contract: the seeds, plus everything only they (strongly) reach."""
    seeds = _callback_seeds(program)
    for fn in program.functions.values():
        if not fn.is_module and _sets_nonblocking(fn):
            seeds.add(fn.qual)
    if not seeds:
        return set()
    seed_fns = [program.functions[q] for q in seeds
                if q in program.functions]
    reach = program._reach(seed_fns, strong_only=True)
    return (reach - _blocking_reach(program, seeds)) | seeds


# -- the rules --------------------------------------------------------

def _is_blocking(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout":
            return False
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in _BLOCKING_NAMES
    if not isinstance(f, ast.Attribute):
        return False
    attr = f.attr
    if attr not in _BLOCKING_ATTRS:
        return False
    # "sep".join(...) is string formatting, not thread join.
    if attr == "join" and isinstance(f.value, ast.Constant):
        return False
    # `d.get(key)` is a dict lookup; a bare `.get()` is queue-style
    # and blocks until an item arrives.
    if attr == "get" and call.args:
        return False
    return True


def _drives_select(fn) -> bool:
    for node in _Scope.iter(fn.node):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "select":
            return True
    return False


def _lock_name(lid) -> str:
    return f"{lid[1]}.{lid[2]}"


def _check_blocking_calls(program, nb, callbacks, findings) -> None:
    for qual in sorted(nb):
        fn = program.functions.get(qual)
        if fn is None or fn.is_module:
            continue
        readiness_ok = qual in callbacks or _drives_select(fn)
        for (call, _targets) in fn.callees:
            if not _is_blocking(call):
                continue
            attr = (call.func.attr
                    if isinstance(call.func, ast.Attribute)
                    else call.func.id)
            if attr in _READINESS_OPS and readiness_ok:
                continue
            held = program.locks_held_at(fn, call)
            name = dotted(call.func) or attr
            if held:
                findings.append(Finding(
                    "EV003", fn.rel, call.lineno,
                    f"blocking call '{name}' under "
                    f"{_lock_name(sorted(held)[0])} in non-blocking "
                    f"context {fn.name}() — the event loop stalls "
                    f"and every lock waiter queues behind it; "
                    f"release the lock or use a timeout"))
            else:
                findings.append(Finding(
                    "EV001", fn.rel, call.lineno,
                    f"blocking call '{name}' in non-blocking "
                    f"context {fn.name}() — use a timeout, defer to "
                    f"the selector, or restructure so readiness is "
                    f"proven first"))


def _check_send_loops(program, nb, findings) -> None:
    for qual in sorted(nb):
        fn = program.functions.get(qual)
        if fn is None or fn.is_module:
            continue
        for node in _Scope.iter(fn.node):
            if not isinstance(node, ast.While):
                continue
            sends = []
            driven = False
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute):
                    if sub.func.attr in ("send", "sendall"):
                        sends.append(sub)
                    elif sub.func.attr in _LOOP_DRIVER_OPS:
                        driven = True
            if sends and not driven:
                findings.append(Finding(
                    "EV002", fn.rel, sends[0].lineno,
                    f"send loop in non-blocking context {fn.name}() "
                    f"has no writability registration — register "
                    f"EVENT_WRITE (or select) inside the loop so a "
                    f"slow reader cannot wedge the event loop"))


def check_program(program, force_scope: bool = False) -> list:
    findings: list = []
    callbacks = _callback_seeds(program)
    nb = nonblocking_contexts(program)
    if nb:
        _check_blocking_calls(program, nb, callbacks, findings)
        _check_send_loops(program, nb, findings)
    if not force_scope:
        findings = [f for f in findings if in_scope(f.rel)]
    seen = set()
    out = []
    for f in findings:
        if f.key() in seen:
            continue
        seen.add(f.key())
        out.append(f)
    return out
