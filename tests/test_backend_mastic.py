"""Differential tests: batched Mastic prep vs the scalar protocol.

Runs the full one-round aggregation (shard on the scalar layer, prep
on the batched backend, checks + aggregation + unshard) and requires
byte-equality with the scalar path at every boundary.
"""

import numpy as np
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow

from mastic_tpu import (MasticCount, MasticHistogram,
                        MasticMultihotCountVec, MasticSum, MasticSumVec)
from mastic_tpu.backend.mastic_jax import BatchedMastic

CTX = b"batched mastic test"
VERIFY_KEY = bytes(range(32))


def _limbs(spec, vec):
    return np.stack([spec.int_to_limbs(x.int()) for x in vec])


def _run_round(mastic, measurements, agg_param, seed=0):
    rng = np.random.default_rng(seed)
    bm = BatchedMastic(mastic)
    spec = bm.spec
    (level, prefixes, do_weight_check) = agg_param

    reports = []
    for meas in measurements:
        nonce = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
        rand = rng.integers(0, 256, mastic.RAND_SIZE,
                            dtype=np.uint8).tobytes()
        (public_share, input_shares) = mastic.shard(CTX, meas, nonce,
                                                    rand)
        reports.append((nonce, public_share, input_shares))

    # Host -> device marshalling.
    nonces = jnp.asarray(np.stack(
        [np.frombuffer(n, np.uint8) for (n, _, _) in reports]))
    cws = bm.vidpf.cws_from_host([ps for (_, ps, _) in reports])
    keys = [
        jnp.asarray(np.stack([np.frombuffer(sh[agg_id][0], np.uint8)
                              for (_, _, sh) in reports]))
        for agg_id in range(2)
    ]
    leader_proofs = jnp.asarray(np.stack(
        [_limbs(spec, sh[0][1]) for (_, _, sh) in reports]))
    helper_seeds = jnp.asarray(np.stack(
        [np.frombuffer(sh[1][2], np.uint8) for (_, _, sh) in reports]))
    if mastic.flp.JOINT_RAND_LEN > 0:
        leader_seeds = jnp.asarray(np.stack(
            [np.frombuffer(sh[0][2], np.uint8) for (_, _, sh) in reports]))
        peer_parts = [
            jnp.asarray(np.stack(
                [np.frombuffer(sh[agg_id][3], np.uint8)
                 for (_, _, sh) in reports]))
            for agg_id in range(2)
        ]
        seeds = [leader_seeds, helper_seeds]
    else:
        peer_parts = [None, None]
        seeds = [None, helper_seeds]

    import jax

    def prep0(n, c, k, p, s, jr):
        return bm.prep(0, VERIFY_KEY, CTX, agg_param, n, c, k,
                       proof_shares=p, seeds=s, peer_jr_parts=jr)

    def prep1(n, c, k, s, jr):
        return bm.prep(1, VERIFY_KEY, CTX, agg_param, n, c, k,
                       seeds=s, peer_jr_parts=jr)

    preps = [
        jax.jit(prep0)(nonces, cws, keys[0], leader_proofs, seeds[0],
                       peer_parts[0]),
        jax.jit(prep1)(nonces, cws, keys[1], seeds[1], peer_parts[1]),
    ]
    assert bool(np.all(np.asarray(preps[0].ok)))
    assert bool(np.all(np.asarray(preps[1].ok)))

    # Scalar oracle: the full protocol per report.
    for (r, (nonce, public_share, input_shares)) in enumerate(reports):
        states = []
        shares = []
        for agg_id in range(2):
            (state, share) = mastic.prep_init(
                VERIFY_KEY, CTX, agg_id, agg_param, nonce, public_share,
                input_shares[agg_id])
            states.append(state)
            shares.append(share)
        (eval_proof_ref, verifier_ref, jr_part_ref) = shares[0]
        p = preps[0]
        assert np.asarray(p.eval_proof[r]).tobytes() == eval_proof_ref
        assert np.asarray(
            preps[1].eval_proof[r]).tobytes() == shares[1][0]
        if jr_part_ref is not None:
            assert np.asarray(
                p.joint_rand_part[r]).tobytes() == jr_part_ref
            assert np.asarray(
                preps[1].joint_rand_part[r]).tobytes() == shares[1][2]
        if do_weight_check:
            for agg_id in range(2):
                got_v = np.asarray(preps[agg_id].verifier[r])
                assert [bm.spec.limbs_to_int(got_v[i])
                        for i in range(got_v.shape[0])] == \
                    [x.int() for x in shares[agg_id][1]], \
                    f"verifier share {agg_id} {r}"
        prep_msg = mastic.prep_shares_to_prep(CTX, agg_param, shares)
        for agg_id in range(2):
            out_ref = mastic.prep_next(CTX, states[agg_id], prep_msg)
            got = np.asarray(preps[agg_id].out_share[r])
            assert [bm.spec.limbs_to_int(got[i])
                    for i in range(got.shape[0])] == \
                [x.int() for x in out_ref], f"out share {agg_id} {r}"

    # Device accept (eval-proof equality + FLP decide + joint-rand
    # confirmation) + aggregate + unshard.
    accept = np.asarray(
        jax.jit(lambda a, b: bm.accept_mask(a, b, do_weight_check))(
            preps[0], preps[1]))
    assert accept.all()
    agg_shares = [
        bm.agg_share_to_host(
            bm.aggregate(p.out_share, jnp.asarray(accept)))
        for p in preps
    ]
    return mastic.unshard(agg_param, agg_shares, len(measurements))


def _path(mastic, value):
    return mastic.vidpf.test_index_from_int(value, mastic.vidpf.BITS)


def _all_prefixes(mastic, level):
    return tuple(mastic.vidpf.test_index_from_int(v, level + 1)
                 for v in range(2 ** (level + 1)))


def test_count():
    mastic = MasticCount(2)
    measurements = [(_path(mastic, 0b10), 1), (_path(mastic, 0b11), 1),
                    (_path(mastic, 0b10), 0)]
    prefixes = _all_prefixes(mastic, 1)
    result = _run_round(mastic, measurements, (1, prefixes, True))
    assert result == [0, 0, 1, 1]


def test_count_no_weight_check():
    mastic = MasticCount(3)
    measurements = [(_path(mastic, 0b101), 1), (_path(mastic, 0b100), 1)]
    prefixes = _all_prefixes(mastic, 2)
    result = _run_round(mastic, measurements, (2, prefixes, False))
    assert result == [0, 0, 0, 0, 1, 1, 0, 0]


def test_sum():
    mastic = MasticSum(2, 7)
    measurements = [(_path(mastic, 0b00), 3), (_path(mastic, 0b01), 5),
                    (_path(mastic, 0b00), 7)]
    prefixes = ((False,), (True,))
    result = _run_round(mastic, measurements, (0, prefixes, True))
    assert result == [15, 0]


def test_sum_vec():
    mastic = MasticSumVec(2, 2, 2, 1)
    measurements = [(_path(mastic, 0b10), [1, 2]),
                    (_path(mastic, 0b10), [3, 1])]
    prefixes = _all_prefixes(mastic, 1)
    result = _run_round(mastic, measurements, (1, prefixes, True))
    assert result == [[0, 0], [0, 0], [4, 3], [0, 0]]


def test_histogram():
    mastic = MasticHistogram(2, 3, 2)
    measurements = [(_path(mastic, 0b01), 0), (_path(mastic, 0b01), 2)]
    prefixes = ((False, True),)
    result = _run_round(mastic, measurements, (1, prefixes, True))
    assert result == [[1, 0, 1]]


def test_multihot():
    mastic = MasticMultihotCountVec(2, 3, 2, 2)
    measurements = [(_path(mastic, 0b11), [True, False, True]),
                    (_path(mastic, 0b11), [False, False, True])]
    prefixes = _all_prefixes(mastic, 0)
    result = _run_round(mastic, measurements, (0, prefixes, True))
    assert result == [[0, 0, 0], [1, 0, 2]]
