"""Application drivers / modes of operation (reference layer L4,
SURVEY.md §1): weighted heavy hitters, attribute-based metrics, and
the communication-cost report, all running on the batched TPU backend
with the host orchestrating the multi-round collector loop."""

from .heavy_hitters import (HeavyHittersRun, compute_heavy_hitters,
                            get_threshold,
                            get_reports_from_measurements, run_round)
from .attribute_metrics import (AttributeMetricsRun,
                                aggregate_by_attribute,
                                hash_attribute)
from .communication import communication_report
from .service import (CollectionRun, CollectorService, ServiceConfig,
                      TenantSpec, encode_upload)

__all__ = [
    "HeavyHittersRun", "compute_heavy_hitters", "get_threshold",
    "get_reports_from_measurements", "run_round",
    "AttributeMetricsRun", "aggregate_by_attribute", "hash_attribute",
    "communication_report",
    "CollectionRun", "CollectorService", "ServiceConfig",
    "TenantSpec", "encode_upload",
]
