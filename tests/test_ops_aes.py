"""Differential tests: batched bitsliced AES vs scalar reference."""

import numpy as np

from mastic_tpu.aes import Aes128
from mastic_tpu.ops.aes_jax import aes128_encrypt, aes128_key_schedule


def test_fips197_known_answer():
    key = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
    pt = bytes.fromhex("00112233445566778899aabbccddeeff")
    rk = aes128_key_schedule(np.frombuffer(key, np.uint8))
    ct = aes128_encrypt(rk, np.frombuffer(pt, np.uint8))
    assert bytes(np.asarray(ct)) == \
        bytes.fromhex("69c4e0d86a7b0430d8cdb78070b4c55a")


def test_batched_matches_scalar():
    rng = np.random.default_rng(2)
    keys = rng.integers(0, 256, size=(4, 16), dtype=np.uint8)
    blocks = rng.integers(0, 256, size=(4, 3, 16), dtype=np.uint8)
    rk = aes128_key_schedule(keys)           # (4, 11, 16)
    got = np.asarray(aes128_encrypt(rk[:, None], blocks))
    for b in range(4):
        cipher = Aes128(bytes(keys[b]))
        for n in range(3):
            assert bytes(got[b, n]) == cipher.encrypt_block(bytes(blocks[b, n]))


def test_key_schedule_matches_scalar():
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 256, size=(2, 16), dtype=np.uint8)
    rk = np.asarray(aes128_key_schedule(keys))
    for b in range(2):
        want = Aes128(bytes(keys[b])).round_keys
        for r in range(11):
            assert bytes(rk[b, r]) == want[r]


def test_bitsliced_matches_byte_path():
    """The batch-bitsliced circuit (32 blocks per uint32 word) against
    the byte-plane path, including report-axis padding (R % 32 != 0)
    and the Davies-Meyer construction in fixed_key_blocks."""
    from mastic_tpu.ops.aes_jax import (aes128_encrypt_bitsliced,
                                        bitslice_keys, bitslice_pack,
                                        bitslice_unpack)

    rng = np.random.default_rng(4)
    keys = rng.integers(0, 256, size=(32, 16), dtype=np.uint8)
    blocks = rng.integers(0, 256, size=(32, 3, 16), dtype=np.uint8)
    rk = aes128_key_schedule(keys)
    want = np.asarray(aes128_encrypt(rk[:, None], blocks))
    got = np.asarray(bitslice_unpack(aes128_encrypt_bitsliced(
        bitslice_keys(rk), bitslice_pack(blocks))))
    assert (got == want).all()


def test_bitslice_pack_roundtrip():
    from mastic_tpu.ops.aes_jax import bitslice_pack, bitslice_unpack

    rng = np.random.default_rng(5)
    x = rng.integers(0, 256, size=(64, 2, 16), dtype=np.uint8)
    assert (np.asarray(bitslice_unpack(bitslice_pack(x))) == x).all()


def test_fixed_key_blocks_bitslice_switch():
    """fixed_key_blocks takes the bitsliced path for R >= 32 (with
    padding when R % 32 != 0) and must agree with the byte path."""
    import jax.numpy as jnp

    from mastic_tpu.backend import xof_jax

    rng = np.random.default_rng(6)
    for (r, shape, m) in [(33, (5,), 2), (32, (), 1), (40, (2,), 3)]:
        keys = jnp.asarray(rng.integers(0, 256, (r, 16), np.uint8))
        rk = aes128_key_schedule(keys)
        seeds = jnp.asarray(
            rng.integers(0, 256, (r,) + shape + (16,), np.uint8))
        got = np.asarray(xof_jax.fixed_key_blocks(rk, seeds, m))
        x = seeds[..., None, :] ^ jnp.asarray(xof_jax._block_indices(m))
        (lo, hi) = (x[..., :8], x[..., 8:])
        sigma = jnp.concatenate([hi, hi ^ lo], axis=-1)
        extra = sigma.ndim - rk.ndim + 1
        rkb = rk.reshape(rk.shape[:-2] + (1,) * extra + rk.shape[-2:])
        want = np.asarray(
            (aes128_encrypt(rkb, sigma) ^ sigma).reshape(
                sigma.shape[:-2] + (m * 16,)))
        assert (got == want).all(), (r, shape, m)


def test_aes_pallas_chained_stages_match_scan():
    """All 11 AES stages (whiten, 9 full rounds, final round) through
    the pallas boundary, one single-stage kernel per stage, must equal
    the scan-path bitsliced encrypt — pinning each stage's round key
    and the final round's missing MixColumns without the interpret
    compile of the fully unrolled kernel (same strategy as the Keccak
    chained test).  Covers key broadcast over a middle block dim and a
    packed-word axis narrower than the 128-lane tile."""
    import pytest

    pytest.importorskip("jax.experimental.pallas")
    import jax.numpy as jnp

    from mastic_tpu.ops.aes_jax import (aes128_encrypt_bitsliced,
                                        bitslice_keys, bitslice_pack)
    from mastic_tpu.ops.aes_pallas import aes128_encrypt_bitsliced_pallas

    rng = np.random.default_rng(7)
    r = 64   # 2 packed words < one 128-lane tile (exercises padding)
    keys = jnp.asarray(rng.integers(0, 256, (r, 16), np.uint8))
    kp = bitslice_keys(aes128_key_schedule(keys))
    blocks = jnp.asarray(
        rng.integers(0, 256, (r, 3, 16), np.uint8))  # middle dim M=3
    planes = bitslice_pack(blocks)

    want = np.asarray(aes128_encrypt_bitsliced(kp, planes))
    got = planes
    for stage in range(11):
        got = aes128_encrypt_bitsliced_pallas(
            kp, got, interpret=True, stage_range=(stage, stage + 1))
    np.testing.assert_array_equal(want, np.asarray(got))


def test_aes_pallas_lane_grid(monkeypatch):
    """The lane-axis grid dimension: with the lane block shrunk to one
    packed word, a 2-word batch runs as two lane grid steps and every
    (block, lane) index-map combination must land on the right tile."""
    import pytest

    pytest.importorskip("jax.experimental.pallas")
    import jax.numpy as jnp

    from mastic_tpu.ops import aes_pallas
    from mastic_tpu.ops.aes_jax import (aes128_encrypt_bitsliced,
                                        bitslice_keys, bitslice_pack)

    monkeypatch.setattr(aes_pallas, "_LANE", 1)
    monkeypatch.setattr(aes_pallas, "_CALL_CACHE", {})
    rng = np.random.default_rng(8)
    r = 64   # 2 packed words -> grid (M, 2)
    keys = jnp.asarray(rng.integers(0, 256, (r, 16), np.uint8))
    kp = bitslice_keys(aes128_key_schedule(keys))
    blocks = jnp.asarray(rng.integers(0, 256, (r, 2, 16), np.uint8))
    planes = bitslice_pack(blocks)

    want = np.asarray(aes128_encrypt_bitsliced(kp, planes))
    got = planes
    for stage in range(11):
        got = aes_pallas.aes128_encrypt_bitsliced_pallas(
            kp, got, interpret=True, stage_range=(stage, stage + 1))
    np.testing.assert_array_equal(want, np.asarray(got))
