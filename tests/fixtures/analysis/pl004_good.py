"""Known-good: sublane block dims 8-aligned or 1 (PL004)."""

from jax.experimental import pallas as pl

_ROWS = 16


def specs():
    return (pl.BlockSpec((_ROWS, 128), lambda i: (0, i)),
            pl.BlockSpec((1, 128), lambda i: (0, i)))
