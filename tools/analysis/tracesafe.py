"""Pass 1 — trace-safety over the jitted/Pallas layer.

Scope: mastic_tpu/ops/, mastic_tpu/backend/, mastic_tpu/flp/flp_jax.py
(the modules whose function bodies run under jax.jit / lax control flow
/ pallas_call, where a Python-level branch or cast on a traced array is
either a silent trace-time freeze or a ConcretizationTypeError on the
first jit).

Rules:
  TS001  Python `if` / `while` / ternary / `assert` whose condition
         involves a traced-array value (lane data must use jnp.where /
         lax.select / lax.cond; shape/dtype predicates are static and
         not flagged).
  TS002  int() / bool() / float() / .item() / .tolist() applied to a
         traced-array value (forces concretization).
  TS003  numpy (`np.*`) called on a traced-array value (silently
         escapes the trace; `jnp` / `lax` is required on traced data).
  TS004  trace-time environment probe inside a function body
         (jax.default_backend(), os.environ reads): the value freezes
         into the compiled program at trace time, which is a staleness
         hazard unless deliberate — suppress with the justification.

Array-ness is inferred per function (to a fixpoint, so loop-carried
values are seen): parameters annotated `jax.Array`/`jnp.ndarray`, all
parameters of kernel/scan-style bodies (pallas `*_ref`/`refs` params;
functions named kernel/body/step/cond), results of jnp./jax./lax.
calls, and anything computed from those.  Nested functions inherit the
enclosing function's traced set (closures over traced values are how
pallas kernels and scan bodies are written here).  `.shape`/`.ndim`/
`.dtype`/`.size` reads and `is None` tests escape the taint —
branching on static shape data is exactly what trace-time Python is
for.  The inference is conservative: a value is only traced if the
analyzer can see it flow from a traced source, so host-side numpy
precomputation never trips the rules.
"""

import ast

from .core import (Finding, call_name, for_target_taints, root_name,
                   target_names)

PASS_NAME = "tracesafe"

RULES = {
    "TS001": "Python branch on a traced-array value",
    "TS002": "host cast (int/bool/float/.item) on a traced-array value",
    "TS003": "numpy call on a traced-array value (jnp/lax required)",
    "TS004": "trace-time environment probe inside a function body",
}

SCOPE_PREFIXES = ("mastic_tpu/ops/", "mastic_tpu/backend/")
SCOPE_FILES = ("mastic_tpu/flp/flp_jax.py",)

# Attributes whose value is static Python data even on a tracer.
_ESCAPE_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize",
                 "nbytes", "weak_type", "sharding"}
# Builtins that never return traced values.
_HOST_SAFE = {"len", "isinstance", "hasattr", "getattr", "callable",
              "type", "id", "repr", "str", "print", "range",
              "enumerate", "sorted", "abs", "format", "zip"}
# jax.* helpers that return host (non-traced) objects.
_JAX_HOST = {"jax.ShapeDtypeStruct", "jax.default_backend",
             "jax.devices", "jax.device_count",
             "jax.local_device_count", "jax.make_mesh"}
_TRACED_ROOTS = ("jnp", "lax", "pl", "pltpu")
_KERNEL_FN_NAMES = {"kernel", "body", "step", "cond"}
_CAST_FNS = {"int", "bool", "float", "complex"}
_ITEM_ATTRS = {"item", "tolist"}
_ENV_PROBES = {"jax.default_backend", "os.environ.get", "os.getenv"}


def in_scope(rel: str) -> bool:
    return rel.startswith(SCOPE_PREFIXES) or rel in SCOPE_FILES


def iter_scope(fn):
    """All nodes of `fn`'s own body, not descending into nested
    function definitions (they are analyzed separately, with this
    scope's traced set inherited)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _annotation_is_array(node) -> bool:
    if node is None:
        return False
    text = ast.unparse(node)
    return ("jax.Array" in text or "jnp.ndarray" in text
            or "ArrayLike" in text)


def _is_none_test(node: ast.Compare) -> bool:
    return (len(node.ops) == 1
            and isinstance(node.ops[0], (ast.Is, ast.IsNot)))


class _FnAnalysis:
    """Traced-value inference + sink reporting for one function."""

    def __init__(self, fn, info, findings, inherited=()):
        self.fn = fn
        self.info = info
        self.findings = findings
        self.traced: set = set(inherited)
        self._seed_params()

    def _seed_params(self):
        args = self.fn.args
        all_args = args.posonlyargs + args.args + args.kwonlyargs
        # In scan/while bodies every param is a traced carry/slice; in
        # other functions only the pallas ref params are traced (a
        # static `meta` param next to a `refs` param stays host data).
        scan_body = self.fn.name in _KERNEL_FN_NAMES
        for a in all_args:
            if _annotation_is_array(a.annotation):
                self.traced.add(a.arg)
            elif a.arg.endswith("_ref") or a.arg == "refs":
                self.traced.add(a.arg)
            elif scan_body and a.arg not in ("self", "cls"):
                self.traced.add(a.arg)
        if args.vararg is not None and (
                scan_body or args.vararg.arg == "refs"
                or args.vararg.arg.endswith("_refs")):
            self.traced.add(args.vararg.arg)

    # -- expression taint ------------------------------------------

    def is_traced(self, node) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.traced
        if isinstance(node, ast.Attribute):
            if node.attr in _ESCAPE_ATTRS:
                return False
            return self.is_traced(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_traced(node.value)
        if isinstance(node, ast.Call):
            return self._call_traced(node)
        if isinstance(node, ast.BinOp):
            return self.is_traced(node.left) or self.is_traced(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_traced(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_traced(v) for v in node.values)
        if isinstance(node, ast.Compare):
            if _is_none_test(node):
                return False
            return (self.is_traced(node.left)
                    or any(self.is_traced(c) for c in node.comparators))
        if isinstance(node, ast.IfExp):
            return self.is_traced(node.body) or self.is_traced(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.is_traced(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.is_traced(node.value)
        if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            return (self.is_traced(node.elt)
                    or any(self.is_traced(g.iter)
                           for g in node.generators))
        if isinstance(node, ast.DictComp):
            return (self.is_traced(node.value)
                    or any(self.is_traced(g.iter)
                           for g in node.generators))
        return False

    def _call_traced(self, node: ast.Call) -> bool:
        name = call_name(node)
        root = root_name(node.func)
        if isinstance(node.func, ast.Name) and name in _HOST_SAFE:
            return False
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _ESCAPE_ATTRS | _ITEM_ATTRS:
            return False
        if name in _JAX_HOST:
            return False
        if root in ("np", "numpy"):
            return False      # numpy results are host constants
        if root in _TRACED_ROOTS or name.startswith("jax."):
            return True
        return (any(self.is_traced(a) for a in node.args)
                or any(self.is_traced(k.value) for k in node.keywords))

    # -- propagation to fixpoint -----------------------------------

    def _taint_target(self, target):
        self.traced.update(target_names(target))

    def propagate(self):
        for _ in range(10):
            before = len(self.traced)
            for node in iter_scope(self.fn):
                if isinstance(node, ast.Assign):
                    if self.is_traced(node.value):
                        for t in node.targets:
                            self._taint_target(t)
                elif isinstance(node, ast.AugAssign):
                    if self.is_traced(node.value) \
                            or self.is_traced(node.target):
                        self._taint_target(node.target)
                elif isinstance(node, (ast.AnnAssign, ast.NamedExpr)):
                    if node.value is not None \
                            and self.is_traced(node.value):
                        self._taint_target(node.target)
                elif isinstance(node, ast.For):
                    self.traced.update(for_target_taints(
                        node.target, node.iter, self.is_traced))
                elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                                       ast.SetComp, ast.DictComp)):
                    for g in node.generators:
                        self.traced.update(for_target_taints(
                            g.target, g.iter, self.is_traced))
            if len(self.traced) == before:
                break

    # -- sinks ------------------------------------------------------

    def _flag(self, rule, node, msg):
        self.findings.append(
            Finding(rule, self.info.rel, node.lineno, msg))

    def report(self):
        for node in iter_scope(self.fn):
            if isinstance(node, (ast.If, ast.While)) \
                    and self.is_traced(node.test):
                self._flag("TS001", node,
                           "Python branch on traced value "
                           f"'{ast.unparse(node.test)[:60]}' — use "
                           "jnp.where / lax.cond")
            elif isinstance(node, ast.IfExp) \
                    and self.is_traced(node.test):
                self._flag("TS001", node,
                           "ternary on traced value "
                           f"'{ast.unparse(node.test)[:60]}'")
            elif isinstance(node, ast.Assert) \
                    and self.is_traced(node.test):
                self._flag("TS001", node, "assert on traced value")
            elif isinstance(node, ast.Call):
                self._report_call(node)

    def _report_call(self, node: ast.Call):
        name = call_name(node)
        root = root_name(node.func)
        if isinstance(node.func, ast.Name) and name in _CAST_FNS \
                and any(self.is_traced(a) for a in node.args):
            self._flag("TS002", node,
                       f"{name}() on a traced value forces "
                       "concretization")
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in _ITEM_ATTRS \
                and self.is_traced(node.func.value):
            self._flag("TS002", node,
                       f".{node.func.attr}() on a traced value")
        elif root in ("np", "numpy") \
                and (any(self.is_traced(a) for a in node.args)
                     or any(self.is_traced(k.value)
                            for k in node.keywords)):
            self._flag("TS003", node,
                       f"numpy call {name}() on a traced value — "
                       "use the jnp/lax equivalent")
        elif name in _ENV_PROBES:
            self._flag("TS004", node,
                       f"{name}() inside a function body is frozen "
                       "into the trace at trace time")


def _analyze(fn, info, findings, inherited=()):
    fa = _FnAnalysis(fn, info, findings, inherited)
    fa.propagate()
    fa.report()
    for node in iter_scope(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _analyze(node, info, findings, set(fa.traced))


def check(info) -> list:
    findings: list = []
    for node in info.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _analyze(node, info, findings)
        elif isinstance(node, ast.ClassDef):
            for member in node.body:
                if isinstance(member, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    _analyze(member, info, findings)
    seen = set()
    out = []
    for f in findings:
        if f.key() in seen:
            continue
        seen.add(f.key())
        out.append(f)
    return out
