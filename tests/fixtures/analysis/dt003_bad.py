"""Known-bad: literal/shift out of range for the dtype (DT003)."""

import jax.numpy as jnp


def oversized_mask():
    x = jnp.zeros((4,), jnp.uint8)
    return x & 0x1FF


def oversized_shift():
    x = jnp.zeros((4,), jnp.uint32)
    return x >> 32
