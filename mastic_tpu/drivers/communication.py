"""Communication-cost report (the reference's overhead example,
/root/reference/poc/examples.py:263-364, rebuilt on this framework's
codecs).

Reports this framework's *measured* wire sizes by encoding real
reports for the same configs the reference benchmarks, plus the
protocol-shape facts the spec itself states (1 prep round vs
Poplar1's 2; O(num_measurements x BITS) inter-aggregator traffic,
draft-mouris-cfrg-mastic.md:166-168, :1619-1623).  The Poplar1 /
Prio3 implementations themselves are out of the framework's scope
(SURVEY.md §2.2), and their byte counts are not archived in
BASELINE.md, so no numbers are invented for them here.
"""

from .. import testvec_codec as codec
from ..common import gen_rand
from ..mastic import Mastic, MasticCount, MasticHistogram, MasticSum


def report_sizes(mastic: Mastic, measurement) -> dict:
    """Encode one report and measure each wire message."""
    ctx = b"sizes"
    nonce = gen_rand(mastic.NONCE_SIZE)
    rand = gen_rand(mastic.RAND_SIZE)
    (public_share, input_shares) = mastic.shard(ctx, measurement, nonce,
                                                rand)
    public = len(codec.encode_public_share(mastic, public_share))
    leader = len(codec.encode_input_share(mastic, input_shares[0]))
    helper = len(codec.encode_input_share(mastic, input_shares[1]))
    return {
        "public_share": public,
        "leader_share": leader,
        "helper_share": helper,
        "upload": public + leader + helper,
    }


def communication_report(print_fn=print) -> dict:
    """Mastic upload sizes for the reference's comparison configs."""
    out = {}
    alpha256 = (False,) * 256

    out["MasticCount(256)"] = report_sizes(MasticCount(256),
                                           (alpha256, 1))
    out["MasticSum(256, max=255)"] = report_sizes(
        MasticSum(256, 255), (alpha256, 17))
    out["MasticHistogram(32, 100, 10)"] = report_sizes(
        MasticHistogram(32, 100, 10), ((False,) * 32, 3))
    out["prep_rounds"] = {"mastic": 1, "poplar1_spec": 2}

    for (name, sizes) in out.items():
        print_fn(f"{name}: {sizes}")
    return out
