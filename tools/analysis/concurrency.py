"""Pass 7 — thread/lock discipline over the service plane (ISSUE 8).

Scope: mastic_tpu/obs/ + mastic_tpu/drivers/ + tools/serve.py — the
layer that grew a second thread in r12 (the `--status-port` server)
next to the single-threaded scheduler, with shared mutable state
(registry, tracer ring, published status snapshots) whose safety the
code comments only *promise*.  This pass consumes the whole-program
model (`callgraph.Program`): thread-rooted reachability says which
functions run on which thread, the lock model says which statements
run under which lock (including locks inherited from every call
site), and the rules check the promises:

  CC001  unlocked cross-thread mutation: a write to state reachable
         from more than one thread root (an instance attribute of a
         class whose methods span thread roots, or a module global
         read by another thread) performed while holding no lock.
         Constructors are exempt (the object is unpublished);
         publish-before-start handoffs carry an allow naming the
         happens-before edge.

  CC002  lock acquisition order inversion: lock B acquired (directly
         or via a callee) while holding A somewhere, and A acquired
         while holding B somewhere else — the classic ABBA deadlock
         shape, flagged at both acquisition sites.

  CC003  publishing a mutable object instead of a snapshot across
         the lock boundary: a `with <lock>:` region that returns (or
         binds-then-returns) a container-valued attribute without
         copying it — the caller ends up sharing the very object the
         lock guards, so the guard protects nothing after the
         return.  `dict(...)/list(...)/.copy()/sorted(...)` wrappers
         are the sanctioned snapshot forms.

  CC004  blocking while holding a lock: a sleep / socket op / join /
         wait / file open inside a lock region (directly or in a
         function that inherits the lock from every call site) —
         every other thread needing the lock stalls behind I/O.

Known blind spots (shared with the call-graph model, USAGE.md):
dynamic dispatch past the resolution cap, getattr, callables passed
as values, and locks threaded through parameters.  Intentional
exceptions are suppressed inline with a justified
`# mastic-allow: CC00x — reason`, same as every other pass.
"""

import ast

from .core import Finding, dotted
from .callgraph import ClassNode, _Scope

PASS_NAME = "concurrency"
WHOLE_PROGRAM = True

RULES = {
    "CC001": "unlocked mutation of state shared across thread roots",
    "CC002": "lock acquisition order inversion (ABBA deadlock shape)",
    "CC003": "lock-guarded mutable attribute published without a "
             "snapshot copy",
    "CC004": "blocking call while holding a lock",
}

SCOPE_PREFIXES = ("mastic_tpu/obs/", "mastic_tpu/drivers/")
EXTRA_FILES = ("tools/serve.py",)

_CTOR_EXEMPT = ("__init__", "__post_init__")

_MUTATING_METHODS = {"append", "extend", "add", "update", "insert",
                     "remove", "discard", "pop", "popleft", "clear",
                     "setdefault", "appendleft"}

_COPY_CALLS = {"dict", "list", "tuple", "set", "frozenset", "sorted",
               "copy", "deepcopy", "bytes"}

_BLOCKING_ATTRS = {"sleep", "accept", "recv", "recv_into", "sendall",
                   "sendto", "connect", "create_connection",
                   "makefile", "join", "wait", "communicate",
                   "urlopen", "serve_forever", "readline", "read"}
_BLOCKING_NAMES = {"sleep", "open", "create_connection", "urlopen"}


def in_scope(rel: str) -> bool:
    return rel.startswith(SCOPE_PREFIXES) or rel in EXTRA_FILES


def check(info) -> list:
    """Per-file entry point kept for interface symmetry; the real
    work happens in check_program (the driver calls it once with the
    run's Program)."""
    return []


def check_program(program, force_scope: bool = False) -> list:
    findings: list = []
    _check_cc001(program, findings)
    _check_cc002(program, findings)
    _check_cc003(program, findings)
    _check_cc004(program, findings)
    if not force_scope:
        findings = [f for f in findings if in_scope(f.rel)]
    seen = set()
    out = []
    for f in findings:
        if f.key() in seen:
            continue
        seen.add(f.key())
        out.append(f)
    return out


# -- CC001: shared-state mutation without the lock --------------------

class _Access:
    __slots__ = ("fn", "node", "attr", "cls", "is_write", "locked")

    def __init__(self, fn, node, attr, cls, is_write, locked):
        self.fn = fn
        self.node = node
        self.attr = attr
        self.cls = cls          # ClassNode | str (external) | None
        self.is_write = is_write
        self.locked = locked


def _attr_accesses(program, fn):
    """Attribute reads/writes of one function scope, with best-effort
    receiver classes.  Method accesses (the .func of a Call) are
    calls, not state reads."""
    write_targets = set()
    call_funcs = set()
    out = []
    for node in _Scope.iter(fn.node):
        if isinstance(node, ast.Call):
            call_funcs.add(id(node.func))
            f = node.func
            if isinstance(f, ast.Attribute) \
                    and f.attr in _MUTATING_METHODS \
                    and isinstance(f.value, ast.Attribute):
                out.append(_mk_access(program, fn, f.value,
                                      is_write=True))
        elif isinstance(node, (ast.Assign, ast.AugAssign,
                               ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Attribute):
                        write_targets.add(id(sub))
                        out.append(_mk_access(program, fn, sub,
                                              is_write=True))
                        break   # the outermost attribute is the write
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    out.append(_mk_access(program, fn, t,
                                          is_write=True))
    for node in _Scope.iter(fn.node):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Load) \
                and id(node) not in call_funcs:
            out.append(_mk_access(program, fn, node, is_write=False))
    return [a for a in out if a is not None]


def _mk_access(program, fn, attr_node, is_write):
    base = attr_node.value
    cls = None
    if isinstance(base, ast.Name) and base.id in ("self", "cls"):
        cls = fn.cls
    else:
        cls = program.receiver_class(fn, base)
    # Accessing a method name is a bound-method read, not state.
    if isinstance(cls, ClassNode) and attr_node.attr in cls.methods:
        return None
    locked = bool(program.locks_held_at(fn, attr_node))
    return _Access(fn, attr_node, attr_node.attr, cls, is_write,
                   locked)


def _compatible(a: _Access, b: _Access) -> bool:
    """Two accesses may touch the same state: same known class, or at
    least one receiver unresolved (the conservative match the
    statusz `owner` handoff needs)."""
    if isinstance(a.cls, ClassNode) and isinstance(b.cls, ClassNode):
        return a.cls.qual == b.cls.qual
    return True


def _check_cc001(program, findings) -> None:
    by_attr: dict = {}
    for fn in program.functions.values():
        if fn.is_module:
            continue
        groups = program.root_groups(fn)
        if not groups:
            continue
        for acc in _attr_accesses(program, fn):
            by_attr.setdefault(acc.attr, []).append((acc, groups))
    for (attr, entries) in by_attr.items():
        all_groups = set()
        for (_acc, groups) in entries:
            all_groups |= groups
        if len(all_groups) < 2:
            continue
        for (acc, groups) in entries:
            if not acc.is_write or acc.locked:
                continue
            if acc.fn.name in _CTOR_EXEMPT:
                continue
            # Cross-thread only if some COMPATIBLE access runs under
            # a root group this write's function does not.
            foreign = [o for (o, og) in entries
                       if o is not acc and _compatible(acc, o)
                       and (og - groups)]
            if not foreign:
                continue
            other = foreign[0]
            findings.append(Finding(
                "CC001", acc.fn.rel, acc.node.lineno,
                f"unlocked write to '{attr}' shared across thread "
                f"roots (also touched by {other.fn.qual}, reachable "
                f"from {sorted(program.root_groups(other.fn))[0]}) — "
                f"hold the owning lock, or allow naming the "
                f"happens-before edge"))
    _check_cc001_globals(program, findings)


def _check_cc001_globals(program, findings) -> None:
    """Module globals written via `global` off one root and read from
    another, unlocked."""
    decls: dict = {}   # (module, name) -> [(fn, node, locked, groups)]
    reads: dict = {}
    for fn in program.functions.values():
        if fn.is_module:
            continue
        groups = program.root_groups(fn)
        if not groups:
            continue
        globals_here = set()
        for node in _Scope.iter(fn.node):
            if isinstance(node, ast.Global):
                globals_here.update(node.names)
        for node in _Scope.iter(fn.node):
            if not isinstance(node, ast.Name):
                continue
            key = (fn.module, node.id)
            if isinstance(node.ctx, ast.Store) \
                    and node.id in globals_here:
                locked = bool(program.locks_held_at(fn, node))
                decls.setdefault(key, []).append(
                    (fn, node, locked, groups))
            elif isinstance(node.ctx, ast.Load):
                reads.setdefault(key, set()).update(groups)
    for (key, writes) in decls.items():
        for (fn, node, locked, groups) in writes:
            if locked:
                continue
            if reads.get(key, set()) - groups:
                findings.append(Finding(
                    "CC001", fn.rel, node.lineno,
                    f"unlocked write to module global '{key[1]}' "
                    f"read from another thread root — guard it with "
                    f"the module's lock"))


# -- CC002: lock order inversions -------------------------------------

def _acquire_closure(program) -> dict:
    """qual -> locks a call to this function may acquire (direct
    with-regions plus callees', to a fixpoint)."""
    direct = {}
    for fn in program.functions.values():
        direct[fn.qual] = {lid for (lid, _r)
                           in program.with_regions(fn)}
    closure = {q: set(s) for (q, s) in direct.items()}
    for _ in range(10):
        changed = False
        for fn in program.functions.values():
            acc = closure[fn.qual]
            before = len(acc)
            for (_call, targets) in fn.callees:
                for t in targets:
                    acc |= closure.get(t.qual, set())
            if len(acc) != before:
                changed = True
        if not changed:
            break
    return closure


def _check_cc002(program, findings) -> None:
    closure = _acquire_closure(program)
    pairs: dict = {}   # (outer, inner) -> (fn, node)
    for fn in program.functions.values():
        regions = program.with_regions(fn)
        for (lid, region) in regions:
            held = set(program.entry_locks.get(fn.qual, frozenset()))
            for (outer_lid, outer) in regions:
                if outer is region:
                    continue
                if outer.lineno <= region.lineno <= getattr(
                        outer, "end_lineno", outer.lineno):
                    held.add(outer_lid)
            for outer_lid in held:
                if outer_lid != lid:
                    pairs.setdefault((outer_lid, lid), (fn, region))
        for (call, targets) in fn.callees:
            held = program.locks_held_at(fn, call)
            if not held:
                continue
            acquired = set()
            for t in targets:
                acquired |= closure.get(t.qual, set())
            for outer_lid in held:
                for inner in acquired - held:
                    pairs.setdefault((outer_lid, inner), (fn, call))
    for ((a, b), (fn, node)) in pairs.items():
        if (b, a) in pairs:
            findings.append(Finding(
                "CC002", fn.rel, node.lineno,
                f"lock order inversion: {_lock_name(b)} acquired "
                f"while holding {_lock_name(a)}, and the reverse "
                f"order exists elsewhere — pick one global order"))


def _lock_name(lid) -> str:
    return f"{lid[1]}.{lid[2]}"


# -- CC003: publishing the guarded object -----------------------------

def _is_copy_wrapped(expr) -> bool:
    if isinstance(expr, ast.Call):
        name = dotted(expr.func).rsplit(".", 1)[-1]
        return name in _COPY_CALLS
    return False


def _mutable_attr_of(program, fn, expr):
    """(class, attr) when `expr` loads a container-valued instance
    attribute of a known class."""
    if not isinstance(expr, ast.Attribute):
        return None
    base = expr.value
    cls = (fn.cls if isinstance(base, ast.Name)
           and base.id in ("self", "cls")
           else program.receiver_class(fn, base))
    if isinstance(cls, ClassNode) and expr.attr in cls.mutable_attrs:
        return (cls, expr.attr)
    return None


def _check_cc003(program, findings) -> None:
    for fn in program.functions.values():
        if fn.is_module:
            continue
        regions = program.with_regions(fn)
        if not regions:
            continue
        escaped: dict = {}   # local name -> (attr, bind node)
        for (_lid, region) in regions:
            for node in ast.walk(region):
                if isinstance(node, ast.Return) \
                        and node.value is not None:
                    hit = _mutable_attr_of(program, fn, node.value)
                    if hit is not None:
                        findings.append(Finding(
                            "CC003", fn.rel, node.lineno,
                            f"returns lock-guarded mutable "
                            f"'{hit[1]}' by reference — the caller "
                            f"shares the object the lock guards; "
                            f"return a snapshot copy "
                            f"(dict()/list()/.copy())"))
                elif isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and not _is_copy_wrapped(node.value):
                    hit = _mutable_attr_of(program, fn, node.value)
                    if hit is not None:
                        escaped[node.targets[0].id] = \
                            (hit[1], node)
        if not escaped:
            continue
        for node in _Scope.iter(fn.node):
            if not (isinstance(node, ast.Return)
                    and node.value is not None):
                continue
            if _is_copy_wrapped(node.value) and isinstance(
                    node.value, ast.Call):
                continue
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id in escaped:
                    (attr, bind) = escaped[sub.id]
                    findings.append(Finding(
                        "CC003", fn.rel, bind.lineno,
                        f"lock-guarded mutable '{attr}' bound to "
                        f"'{sub.id}' under the lock and returned — "
                        f"the caller shares the guarded object; "
                        f"bind a snapshot copy instead"))
                    break


# -- CC004: blocking under a lock -------------------------------------

def _is_blocking(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in _BLOCKING_NAMES
    if isinstance(f, ast.Attribute):
        if f.attr not in _BLOCKING_ATTRS:
            return False
        # "sep".join(...) is string formatting, not thread join.
        if f.attr == "join" and isinstance(f.value, ast.Constant):
            return False
        return True
    return False


def _check_cc004(program, findings) -> None:
    for fn in program.functions.values():
        if fn.is_module:
            continue
        entry = program.entry_locks.get(fn.qual, frozenset())
        regions = program.with_regions(fn)
        if not regions and not entry:
            continue
        for (call, _targets) in fn.callees:
            if not _is_blocking(call):
                continue
            held = program.locks_held_at(fn, call)
            if held:
                findings.append(Finding(
                    "CC004", fn.rel, call.lineno,
                    f"blocking call "
                    f"'{dotted(call.func) or 'open'}' while holding "
                    f"{_lock_name(sorted(held)[0])} — every thread "
                    f"needing the lock stalls behind the I/O; move "
                    f"the blocking work outside the lock region"))
