"""The two VDAF XOFs (draft-irtf-cfrg-vdaf-13 §6.2).

* `XofTurboShake128` — TurboSHAKE128 with domain byte 1 over the message
  `le16(len(dst)) || dst || seed || binder`.  Used for node proofs and
  every Mastic seed/vector derivation (reference mastic.py:70,
  vidpf.py:377).

* `XofFixedKeyAes128` — one TurboSHAKE-derived fixed AES key per
  (dst, binder), then a correlation-robust Davies-Meyer-style hash of
  `seed XOR le128(block_index)` per output block.  Used for the VIDPF
  extend/convert PRGs (reference vidpf.py:339, :361); the fixed key is
  shared across the whole prefix tree of one report, which is what makes
  the batched TPU kernel amortize so well.

Byte-exactness of both constructions is locked by replaying
/root/reference/test_vec/mastic/*.json end-to-end.
"""

from .aes import Aes128
from .common import concat, from_le_bytes, to_le_bytes, xor
from .field import F
from .keccak import TurboShake128Stream, turbo_shake128


class Xof:
    """Streaming XOF interface (next / next_vec / one-shot helpers)."""

    SEED_SIZE: int

    def next(self, length: int) -> bytes:
        raise NotImplementedError()

    def next_vec(self, field: type[F], length: int) -> list[F]:
        """Rejection-sample `length` field elements from the stream."""
        vec: list[F] = []
        while len(vec) < length:
            val = from_le_bytes(self.next(field.ENCODED_SIZE))
            # mastic-allow: SF001 — rejection sampling: the branch
            # leaks only the rejection count, which is independent of
            # the accepted outputs (standard VDAF XOF behavior; the
            # batched twin returns the in-range mask instead,
            # backend/xof_jax.sample_vec)
            if val < field.MODULUS:
                vec.append(field(val))
        return vec

    @classmethod
    def expand_into_vec(cls, field: type[F], seed: bytes, dst: bytes,
                        binder: bytes, length: int) -> list[F]:
        return cls(seed, dst, binder).next_vec(field, length)

    @classmethod
    def derive_seed(cls, seed: bytes, dst: bytes, binder: bytes) -> bytes:
        return cls(seed, dst, binder).next(cls.SEED_SIZE)


class XofTurboShake128(Xof):
    SEED_SIZE = 32

    def __init__(self, seed: bytes, dst: bytes, binder: bytes):
        """Variable seed lengths are supported (the VIDPF node proof
        feeds 16-byte seeds, the Mastic checks empty ones); the seed is
        length-prefixed to keep the encoding injective."""
        if len(dst) >= 2 ** 16:
            raise ValueError("dst too long")
        if len(seed) >= 2 ** 8:
            raise ValueError("seed too long")
        self.stream = TurboShake128Stream(
            to_le_bytes(len(dst), 2) + dst
            + to_le_bytes(len(seed), 1) + seed + binder, domain=1)

    def next(self, length: int) -> bytes:
        return self.stream.read(length)


class XofFixedKeyAes128(Xof):
    SEED_SIZE = 16

    def __init__(self, seed: bytes, dst: bytes, binder: bytes):
        if len(seed) != self.SEED_SIZE:
            raise ValueError("incorrect seed size")
        if len(dst) >= 2 ** 16:
            raise ValueError("dst too long")
        self.length_consumed = 0
        fixed_key = turbo_shake128(
            to_le_bytes(len(dst), 2) + dst + binder, domain=2, length=16)
        self.cipher = Aes128(fixed_key)
        self.seed = seed

    def _hash_block(self, block: bytes) -> bytes:
        """The tweakable correlation-robust hash of [GKWWY20]:
        pi(x) = CIPH(sigma(x)) XOR sigma(x), sigma(lo || hi) =
        hi || (hi XOR lo)."""
        (lo, hi) = (block[:8], block[8:])
        sigma_block = concat([hi, xor(hi, lo)])
        return xor(self.cipher.encrypt_block(sigma_block), sigma_block)

    def next(self, length: int) -> bytes:
        offset = self.length_consumed % 16
        new_length = self.length_consumed + length
        block_range = range(self.length_consumed // 16,
                            (new_length + 15) // 16)
        self.length_consumed = new_length
        hashed_blocks = [
            self._hash_block(xor(self.seed, to_le_bytes(i, 16)))
            for i in block_range
        ]
        return concat(hashed_blocks)[offset:offset + length]
