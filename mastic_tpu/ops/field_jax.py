"""Batched Field64/Field128 arithmetic in JAX: 16-bit limbs, Montgomery
multiplication.

TPUs have no 64-bit integer lanes and no widening multiply, so field
elements are vectors of 16-bit limbs held in uint32 (a 16x16 product
fits in 32 bits with room for column accumulation).  Multiplication is
schoolbook + Montgomery REDC with R = 2^(16*n); elements on device live
in the Montgomery domain, and conversion happens only at the byte
boundaries (XOF output -> field, field -> wire encoding), which is
where the scalar reference (mastic_tpu.field) defines byte-exact
behavior.

Layout: shape (..., n) uint32, little-endian limb order, n = 4 for
Field64 and n = 8 for Field128.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..field import Field, Field64, Field128

_U32 = jnp.uint32
_MASK16 = 0xFFFF


class FieldSpec:
    """Constants for one prime field, precomputed on the host with
    Python bignums."""

    def __init__(self, field: type[Field], gen_order: int):
        self.field = field
        self.modulus = field.MODULUS
        self.encoded_size = field.ENCODED_SIZE
        self.num_limbs = field.ENCODED_SIZE // 2
        self.gen_order = gen_order
        n = self.num_limbs
        self.R = pow(2, 16 * n, self.modulus)
        self.R2 = (self.R * self.R) % self.modulus
        self.R_INV = pow(self.R, -1, self.modulus)
        # -p^-1 mod 2^16, the REDC quotient constant.
        self.P_PRIME = (-pow(self.modulus, -1, 1 << 16)) & _MASK16
        self.P = self.int_to_limbs(self.modulus)
        self.R2_LIMBS = self.int_to_limbs(self.R2)
        self.ONE_MONT = self.int_to_limbs(self.R % self.modulus)
        # One-hot (n, n, 2n+1) tensors scattering partial product (i, j)
        # into column i+j (low halves) / i+j+1 (high halves): the
        # schoolbook column sum becomes one einsum, which traces O(1)
        # ops and lets XLA tile it instead of compiling n^2 scatters.
        self.COL_LO = np.zeros((n, n, 2 * n + 1), np.uint32)
        self.COL_HI = np.zeros((n, n, 2 * n + 1), np.uint32)
        for i in range(n):
            for j in range(n):
                self.COL_LO[i, j, i + j] = 1
                self.COL_HI[i, j, i + j + 1] = 1

    # -- host-side converters (Python bignum; for constants & tests) --

    def int_to_limbs(self, value: int) -> np.ndarray:
        return np.array([(value >> (16 * i)) & _MASK16
                         for i in range(self.num_limbs)], np.uint32)

    def limbs_to_int(self, limbs) -> int:
        limbs = np.asarray(limbs)
        return sum(int(limbs[..., i]) << (16 * i)
                   for i in range(self.num_limbs))

    def to_mont_host(self, value: int) -> np.ndarray:
        return self.int_to_limbs((value * self.R) % self.modulus)

    def from_mont_host(self, limbs) -> int:
        return (self.limbs_to_int(limbs) * self.R_INV) % self.modulus

    def vec_to_mont_host(self, values) -> np.ndarray:
        """List of ints (or scalar Field elements) -> (len, n) mont limbs."""
        out = np.zeros((len(values), self.num_limbs), np.uint32)
        for (i, v) in enumerate(values):
            out[i] = self.to_mont_host(v.int() if hasattr(v, "int") else v)
        return out

    def mont_to_field_host(self, limbs) -> list:
        """(..., n) mont limbs -> flat list of scalar Field elements."""
        arr = np.asarray(limbs).reshape(-1, self.num_limbs)
        return [self.field(self.from_mont_host(row)) for row in arr]

    # -- device ops ------------------------------------------------

    def _propagate(self, cols: jax.Array, num_out: int) -> jax.Array:
        """Carry-propagate column sums into `num_out` 16-bit limbs.
        Column values must be < 2^32 at all times (guaranteed by the
        callers' accumulation bounds)."""
        limbs = []
        carry = jnp.zeros(cols.shape[:-1], _U32)
        for i in range(num_out):
            v = (cols[..., i] if i < cols.shape[-1]
                 else jnp.zeros(cols.shape[:-1], _U32)) + carry
            limbs.append(v & _MASK16)
            carry = v >> 16
        return jnp.stack(limbs, axis=-1)

    def _sub_limbs(self, a: jax.Array, b: np.ndarray | jax.Array):
        """a - b limbwise with borrow chain; returns (diff, borrow)."""
        n = a.shape[-1]
        diff = []
        borrow = jnp.zeros(a.shape[:-1], _U32)
        for i in range(n):
            # mastic-allow: TS002 — the else arm runs only for the
            # host-side 1-D np.ndarray constants (modulus limbs);
            # every jax.Array operand here is >= 2-D and takes the
            # first arm, so no tracer reaches the int()
            bi = b[..., i] if hasattr(b, "shape") and b.ndim > 1 \
                else _U32(int(b[i]))
            need = bi + borrow
            ai = a[..., i]
            borrow = (ai < need).astype(_U32)
            diff.append((ai + (borrow << 16) - need) & _MASK16)
        return (jnp.stack(diff, axis=-1), borrow)

    def _cond_sub_p(self, limbs: jax.Array) -> jax.Array:
        """One conditional subtract of p (constant-time select)."""
        p_ext = np.zeros(limbs.shape[-1], np.uint32)
        p_ext[:self.num_limbs] = self.P
        (diff, borrow) = self._sub_limbs(limbs, p_ext)
        keep = (borrow == 1)[..., None]
        return jnp.where(keep, limbs, diff)[..., :self.num_limbs]

    def add(self, a: jax.Array, b: jax.Array) -> jax.Array:
        s = self._propagate(a + b, self.num_limbs + 1)
        return self._cond_sub_p(s)

    def sub(self, a: jax.Array, b: jax.Array) -> jax.Array:
        (diff, borrow) = self._sub_limbs(a, b)
        plus_p = self._propagate(diff + jnp.asarray(self.P), self.num_limbs)
        return jnp.where((borrow == 1)[..., None], plus_p, diff)

    def neg(self, a: jax.Array) -> jax.Array:
        return self.sub(jnp.zeros_like(a), a)

    def mul(self, a: jax.Array, b: jax.Array) -> jax.Array:
        """Montgomery product: mont(x)*mont(y) -> mont(x*y)."""
        n = self.num_limbs
        # Schoolbook product into 2n+1 columns via one einsum per half
        # (column sums stay < 2n * 2^16 < 2^32).
        prods = a[..., :, None] * b[..., None, :]
        cols = jnp.einsum("...ij,ijk->...k", prods & _MASK16,
                          jnp.asarray(self.COL_LO)) + \
            jnp.einsum("...ij,ijk->...k", prods >> 16,
                       jnp.asarray(self.COL_HI))
        # REDC: clear the low n limbs one at a time, deferring all
        # carry propagation except the single carry out of the limb
        # being cleared (the quotient digit m only needs t[i] exact
        # mod 2^16, and every contribution to column i has landed by
        # iteration i).  Columns stay < 2^22, far from uint32 overflow.
        # The chain runs under lax.scan so its body compiles once per
        # call site — XLA-CPU compile time of the unrolled form
        # dominated the whole test suite.
        p_arr = jnp.asarray(self.P)

        def clear_limb(t, i):
            digit = jax.lax.dynamic_index_in_dim(t, i, axis=-1,
                                                 keepdims=False)
            m = (digit * _U32(self.P_PRIME)) & _MASK16
            mp = m[..., None] * p_arr
            window = jax.lax.dynamic_slice_in_dim(t, i, n + 1, axis=-1)
            window = window.at[..., :n].add(mp & _MASK16)
            window = window.at[..., 1:].add(mp >> 16)
            # Forward the cleared limb's carry one column.
            window = window.at[..., 1].add(window[..., 0] >> 16)
            return (jax.lax.dynamic_update_slice_in_dim(
                t, window, i, axis=-1), None)

        (t, _) = jax.lax.scan(clear_limb, cols,
                              jnp.arange(n, dtype=jnp.int32))
        out = self._propagate(t[..., n:], n + 1)
        return self._cond_sub_p(out)

    def to_mont(self, plain: jax.Array) -> jax.Array:
        return self.mul(plain, jnp.asarray(self.R2_LIMBS))

    def from_mont(self, mont: jax.Array) -> jax.Array:
        one = np.zeros(self.num_limbs, np.uint32)
        one[0] = 1
        return self.mul(mont, jnp.asarray(one))

    # -- byte boundaries -------------------------------------------

    def limbs_from_le_bytes(self, data: jax.Array):
        """uint8 (..., ENCODED_SIZE) -> (plain limbs, in_range mask).
        The mask is the XOF rejection-sampling predicate value < p
        (scalar reference: Xof.next_vec, mastic_tpu/xof.py:33-40)."""
        pairs = data.reshape(data.shape[:-1] + (self.num_limbs, 2))
        limbs = pairs[..., 0].astype(_U32) | (pairs[..., 1].astype(_U32) << 8)
        (_, borrow) = self._sub_limbs(limbs, self.P)
        return (limbs, borrow == 1)

    def mont_to_le_bytes(self, mont: jax.Array) -> jax.Array:
        return self.plain_to_le_bytes(self.from_mont(mont))

    def plain_to_le_bytes(self, plain: jax.Array) -> jax.Array:
        """Canonical little-endian wire encoding of plain-domain limbs
        (byte-exact vs the scalar field.encode_vec)."""
        lo = (plain & 0xFF).astype(jnp.uint8)
        hi = (plain >> 8).astype(jnp.uint8)
        return jnp.stack([lo, hi], axis=-1).reshape(
            plain.shape[:-1] + (self.encoded_size,))


def field_sum(spec: FieldSpec, x: jax.Array, axis: int) -> jax.Array:
    """Exact modular sum along `axis` by pairwise tree reduction
    (log2(n) full-width adds; used for share aggregation, reference
    mastic.py:384-397)."""
    x = jnp.moveaxis(x, axis, 0)
    n = x.shape[0]
    if n == 0:
        raise ValueError("empty field sum")
    while n > 1:
        half = n // 2
        rest = x[2 * half:]
        x = spec.add(x[:half], x[half:2 * half])
        if rest.shape[0]:
            x = jnp.concatenate([x, rest], axis=0)
        n = x.shape[0]
    return x[0]


FIELD64 = FieldSpec(Field64, Field64.GEN_ORDER)
FIELD128 = FieldSpec(Field128, Field128.GEN_ORDER)


def spec_for(field: type[Field]) -> FieldSpec:
    if field is Field64:
        return FIELD64
    if field is Field128:
        return FIELD128
    raise ValueError(f"no batched spec for {field}")
