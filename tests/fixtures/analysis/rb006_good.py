"""Known-good: the tmp-write → fsync(file) → replace → fsync(dir)
idiom (RB006) — the bytes are durable before the name points at
them, and the directory entry itself is durable after."""

import json
import os


def fsync_dir(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def publish_snapshot(path, state):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(os.path.abspath(path)))
