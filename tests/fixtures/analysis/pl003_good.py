"""Known-good: one out spec per out shape (PL003)."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def call(kernel):
    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((8, 128), jnp.uint32),
                   jax.ShapeDtypeStruct((8, 128), jnp.uint32)),
        grid=(2,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (0, i))],
        out_specs=(pl.BlockSpec((8, 128), lambda i: (0, i)),
                   pl.BlockSpec((8, 128), lambda i: (0, i))),
    )
