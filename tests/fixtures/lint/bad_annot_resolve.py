"""Known-bad: annotation naming an undefined type (lint check 5)."""


def exposed(value: "NoSuchType") -> int:
    return 0
