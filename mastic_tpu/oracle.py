"""Functional model of Mastic: what the protocol computes, with no
crypto.  Differential-testing oracle for the drivers (the reference
ships the same kind of model at talks/func.py).
"""

from typing import Any, Callable, Sequence


def prefix_weights(measurements: Sequence[tuple],
                   prefixes: Sequence[tuple],
                   zero: Callable[[], Any],
                   add: Callable[[Any, Any], Any]) -> dict:
    """Total weight per candidate prefix: sum of beta over measurements
    whose alpha has that prefix.  `zero`/`add` abstract the weight
    monoid (ints, vectors, ...)."""
    out = {p: zero() for p in prefixes}
    for (alpha, beta) in measurements:
        for p in prefixes:
            if tuple(alpha[:len(p)]) == tuple(p):
                out[p] = add(out[p], beta)
    return out


def weighted_heavy_hitters(measurements: Sequence[tuple], threshold: int,
                           bit_len: int) -> list:
    """The level-by-level refinement loop over exact weights."""
    if bit_len < 1:
        raise ValueError("bit_len must be >= 1")
    prefixes = [(False,), (True,)]
    for level in range(bit_len):
        weights = prefix_weights(measurements, prefixes,
                                 zero=lambda: 0, add=lambda a, b: a + b)
        survivors = [p for p in prefixes if weights[p] >= threshold]
        if level == bit_len - 1:
            return sorted(survivors)
        prefixes = [p + (bit,) for p in survivors
                    for bit in (False, True)]
    raise AssertionError("unreachable")
