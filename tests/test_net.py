"""The network front (ISSUE 11, `mastic_tpu/net/`): DAP framing
golden vectors, admission (token bucket / connection ceiling / body
gates), network fault checkpoints, the shaped transport, the load
generator, and the concurrent-upload page-multiset stress.

Fast tier: everything here runs without a single XLA compile — the
upload door is pure admission (decode + page append), which is the
point.  Slow tier: the shaped leader/helper session proven
bit-identical to the in-process path (run explicitly by
`make net-smoke`), and the kill-9 mid-upload resume drill
(`tools/loadgen.py --smoke` runs the same drill in CI).
"""

import json
import socket
import threading
import time
from http.client import HTTPConnection

import numpy as np
import pytest

from mastic_tpu.drivers import faults
from mastic_tpu.drivers.service import (CollectorService,
                                        ServiceConfig, TenantSpec)
from mastic_tpu.drivers.session import Channel
from mastic_tpu.mastic import MasticCount
from mastic_tpu.net import loadgen as loadgen_mod
from mastic_tpu.net import transport as transport_mod
from mastic_tpu.net.admission import AdmissionController, NetConfig
from mastic_tpu.net.ingest import MEDIA_TYPE, UploadFront
from mastic_tpu.obs.registry import configure as configure_registry

CTX = b"net test"
BITS = 2


def make_service(**over) -> tuple:
    m = MasticCount(BITS)
    vk = bytes(range(m.VERIFY_KEY_SIZE))
    spec = TenantSpec(name="count",
                      spec={"class": "MasticCount", "args": [BITS]},
                      ctx=CTX, verify_key=vk,
                      thresholds={"default": 1})
    defaults = dict(page_size=4, max_buffered=64,
                    epoch_deadline=600.0)
    defaults.update(over)
    svc = CollectorService([spec], config=ServiceConfig(**defaults))
    return (svc, m)


def put(port: int, path: str, body: bytes, ctype: str = MEDIA_TYPE,
        headers: dict = None, timeout: float = 10.0) -> tuple:
    """One PUT on a fresh connection -> (status, parsed json body,
    headers dict)."""
    conn = HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        h = {"Content-Type": ctype}
        if headers:
            h.update(headers)
        conn.request("PUT", path, body=body, headers=h)
        resp = conn.getresponse()
        data = resp.read()
        return (resp.status, json.loads(data), dict(resp.getheaders()))
    finally:
        conn.close()


@pytest.fixture
def front_svc():
    """A service + upload front pair on an ephemeral port, registry
    isolated per test."""
    configure_registry()
    (svc, m) = make_service()
    front = UploadFront(svc, config=NetConfig(max_body=4096,
                                              trust_forwarded=True),
                        admin=True).start()
    yield (front, svc, m)
    front.stop()
    svc.stop_ingest()


def blobs_for(m, count: int, replay: int = 1) -> list:
    return loadgen_mod.build_blob_pool(m, CTX, count, BITS,
                                       replay=replay)


# -- DAP framing golden vectors ---------------------------------------

def test_golden_happy_path(front_svc):
    (front, svc, m) = front_svc
    blob = blobs_for(m, 1)[0]
    (code, body, headers) = put(front.port,
                                "/v1/tenants/count/reports", blob)
    assert (code, body) == (201, {"status": "admitted"})
    assert headers["Content-Type"] == "application/json"
    assert svc.metrics()["tenants"]["count"]["counters"][
        "admitted"] == 1


def test_golden_malformed_blob_quarantines(front_svc):
    (front, svc, m) = front_svc
    for (blob, reason) in ((b"", "malformed"),
                           (b"\x07garbage", "malformed")):
        (code, body, _h) = put(front.port,
                               "/v1/tenants/count/reports", blob)
        assert (code, body) == (400, {"error": "quarantined",
                                      "reason": reason})
    c = svc.metrics()["tenants"]["count"]["counters"]
    assert c["quarantined"] == 2 and c["admitted"] == 0
    assert c["quarantine_reasons"] == {"malformed": 2}


def test_golden_unknown_tenant_and_route(front_svc):
    (front, _svc, m) = front_svc
    blob = blobs_for(m, 1)[0]
    (code, body, _h) = put(front.port, "/v1/tenants/nope/reports",
                           blob)
    assert (code, body) == (404, {"error": "unknown-tenant"})
    (code, body, _h) = put(front.port, "/v1/not/a/route", blob)
    assert (code, body) == (404, {"error": "unknown-route"})


def test_golden_wrong_media_type(front_svc):
    (front, _svc, m) = front_svc
    blob = blobs_for(m, 1)[0]
    (code, body, headers) = put(front.port,
                                "/v1/tenants/count/reports", blob,
                                ctype="application/json")
    assert code == 415
    assert body == {"error": "unsupported-media-type",
                    "expect": MEDIA_TYPE}
    # The unread body poisons keep-alive framing: refuse-and-close.
    assert headers.get("Connection") == "close"


def test_golden_oversized_body(front_svc):
    (front, svc, _m) = front_svc
    (code, body, _h) = put(front.port, "/v1/tenants/count/reports",
                           b"x" * 5000)
    assert code == 413
    assert body == {"error": "body-too-large", "limit_bytes": 4096}
    c = svc.metrics()["tenants"]["count"]["counters"]
    assert c["shed_reasons"] == {"body-too-large": 1}


def test_golden_quota_429_with_retry_after():
    """Queue-full: past the tenant quota every upload sheds 429 with
    a Retry-After header and the reject-newest reason coded."""
    configure_registry()
    (svc, m) = make_service(max_buffered=2)
    front = UploadFront(svc, config=NetConfig()).start()
    try:
        blobs = blobs_for(m, 4)
        codes = []
        for blob in blobs:
            (code, body, headers) = put(
                front.port, "/v1/tenants/count/reports", blob)
            codes.append(code)
            if code == 429:
                assert body == {"error": "shed",
                                "reason": "reject-newest"}
                assert int(headers["Retry-After"]) >= 1
        assert codes == [201, 201, 429, 429]
        c = svc.metrics()["tenants"]["count"]["counters"]
        assert c["shed_reasons"] == {"reject-newest": 2}
    finally:
        front.stop()


def test_golden_queued_202_with_ingest_front():
    configure_registry()
    (svc, m) = make_service(ingest_threads=1, ingest_queue=8)
    front = UploadFront(svc, config=NetConfig()).start()
    try:
        (code, body, _h) = put(front.port,
                               "/v1/tenants/count/reports",
                               blobs_for(m, 1)[0])
        assert (code, body) == (202, {"status": "queued"})
        svc.flush_ingest()
        assert svc.metrics()["tenants"]["count"]["counters"][
            "admitted"] == 1
    finally:
        front.stop()
        svc.stop_ingest()


def test_incomplete_body_rejected_attributed(front_svc):
    """A client promising more bytes than it sends: the read comes up
    short, the request 400s with `incomplete-body`, and the drop is
    reason-coded — never admitted, never silent."""
    (front, svc, m) = front_svc
    blob = blobs_for(m, 1)[0]
    sock = socket.create_connection(("127.0.0.1", front.port),
                                    timeout=10)
    try:
        head = (f"PUT /v1/tenants/count/reports HTTP/1.1\r\n"
                f"Host: t\r\nContent-Type: {MEDIA_TYPE}\r\n"
                f"Content-Length: {len(blob) + 64}\r\n\r\n").encode()
        sock.sendall(head + blob)       # 64 bytes short
        sock.shutdown(socket.SHUT_WR)
        # Read to EOF: the response spans several segments (wbufsize
        # 0 writes status/headers/body separately) and the server
        # closes the connection after an unconsumed body.
        chunks = []
        while True:
            data = sock.recv(4096)
            if not data:
                break
            chunks.append(data)
        resp = b"".join(chunks).decode()
    finally:
        sock.close()
    assert " 400 " in resp.splitlines()[0]
    assert "incomplete-body" in resp
    c = svc.metrics()["tenants"]["count"]["counters"]
    assert c["shed_reasons"] == {"incomplete-body": 1}
    assert c["admitted"] == 0


def test_healthz_and_admin_controls(front_svc):
    (front, svc, m) = front_svc
    conn = HTTPConnection("127.0.0.1", front.port, timeout=10)
    try:
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        assert (resp.status, json.loads(resp.read())) \
            == (200, {"status": "ok"})
        # Admission, then an epoch-cut REQUEST: the handler only
        # queues; the embedding thread executes.
        put(front.port, "/v1/tenants/count/reports",
            blobs_for(m, 1)[0])
        conn.request("POST", "/v1/tenants/count/epoch",
                     headers={"Content-Length": "0"})
        resp = conn.getresponse()
        assert (resp.status, json.loads(resp.read())) \
            == (202, {"status": "epoch-requested"})
        assert front.pop_epoch_requests() == ["count"]
        assert front.pop_epoch_requests() == []
        conn.request("POST", "/v1/admin/drain",
                     headers={"Content-Length": "0"})
        resp = conn.getresponse()
        assert resp.status == 202
        resp.read()
        assert front.drain_requested.is_set()
    finally:
        conn.close()


def test_admin_controls_hidden_without_admin():
    configure_registry()
    (svc, _m) = make_service()
    front = UploadFront(svc, config=NetConfig(), admin=False).start()
    try:
        conn = HTTPConnection("127.0.0.1", front.port, timeout=10)
        conn.request("POST", "/v1/tenants/count/epoch",
                     headers={"Content-Length": "0"})
        resp = conn.getresponse()
        assert (resp.status, json.loads(resp.read())) \
            == (404, {"error": "unknown-route"})
        conn.close()
    finally:
        front.stop()


# -- admission layer --------------------------------------------------

def test_token_bucket_depletes_and_refills():
    clock = [0.0]
    c = AdmissionController(NetConfig(rate=50.0, burst=5.0),
                            clock=lambda: clock[0])
    verdicts = []
    for _ in range(8):
        verdicts.append(c.admit("a")[0])
    assert verdicts == [True] * 5 + [False] * 3
    (_ok, retry_after) = c.admit("a")
    assert retry_after > 0
    clock[0] += 1.0   # 50 tokens refill, capped at burst 5
    assert [c.admit("a")[0] for _ in range(6)] \
        == [True] * 5 + [False]
    # An unrelated address has its own bucket.
    assert c.admit("b")[0] is True


def test_bucket_table_is_lru_bounded():
    clock = [0.0]
    c = AdmissionController(
        NetConfig(rate=1.0, burst=1.0, max_tracked_ips=8),
        clock=lambda: clock[0])
    for i in range(50):
        c.admit(f"10.0.0.{i}")
    assert c.tracked_ips() == 8
    assert c.evictions == 42


def test_connection_ceiling():
    c = AdmissionController(NetConfig(max_connections=2))
    assert c.try_acquire_connection()
    assert c.try_acquire_connection()
    assert not c.try_acquire_connection()
    c.release_connection()
    assert c.try_acquire_connection()


def test_per_ip_rate_limit_over_http():
    configure_registry()
    (svc, m) = make_service()
    front = UploadFront(
        svc, config=NetConfig(rate=0.001, burst=2.0,
                              trust_forwarded=True)).start()
    try:
        blob = blobs_for(m, 1)[0]
        codes = [put(front.port, "/v1/tenants/count/reports", blob,
                     headers={"X-Forwarded-For": "10.1.2.3"})[0]
                 for _ in range(4)]
        assert codes == [201, 201, 429, 429]
        # A different simulated client is untouched.
        assert put(front.port, "/v1/tenants/count/reports", blob,
                   headers={"X-Forwarded-For": "10.9.9.9"})[0] == 201
        c = svc.metrics()["tenants"]["count"]["counters"]
        assert c["shed_reasons"] == {"rate-limited": 2}
    finally:
        front.stop()


def test_connections_exhausted_503(front_svc):
    (front, svc, m) = front_svc
    ceiling = front.cfg.max_connections
    for _ in range(ceiling):
        assert front.controller.try_acquire_connection()
    try:
        (code, body, headers) = put(front.port,
                                    "/v1/tenants/count/reports",
                                    blobs_for(m, 1)[0])
        assert code == 503
        assert body == {"error": "shed",
                        "reason": "connections-exhausted"}
        assert int(headers["Retry-After"]) >= 1
    finally:
        for _ in range(ceiling):
            front.controller.release_connection()
    c = svc.metrics()["tenants"]["count"]["counters"]
    assert c["shed_reasons"] == {"connections-exhausted": 1}


# -- network fault checkpoints ----------------------------------------

def test_truncated_upload_body_never_admitted():
    """The ISSUE 11 fast fault gate: a body truncated in flight
    (http_body content seam) is rejected with an attributed reason —
    never admitted, never silent."""
    configure_registry()
    (svc, m) = make_service()
    inj = faults.FaultInjector(
        faults.parse_faults(
            "truncate:party=collector:step=http_body:cut=40"),
        "collector")
    front = UploadFront(svc, config=NetConfig(),
                        injector=inj).start()
    try:
        blob = blobs_for(m, 1)[0]
        (code, body, _h) = put(front.port,
                               "/v1/tenants/count/reports", blob)
        assert (code, body) == (400, {"error": "quarantined",
                                      "reason": "malformed"})
        c = svc.metrics()["tenants"]["count"]["counters"]
        assert c["admitted"] == 0 and c["quarantined"] == 1
        # The rule fired once; the next (unfaulted) upload admits.
        assert put(front.port, "/v1/tenants/count/reports",
                   blob)[0] == 201
    finally:
        front.stop()


def test_corrupted_upload_body_never_admitted():
    configure_registry()
    (svc, m) = make_service()
    inj = faults.FaultInjector(
        faults.parse_faults(
            "corrupt:party=collector:step=http_body:offset=6"),
        "collector")
    front = UploadFront(svc, config=NetConfig(),
                        injector=inj).start()
    try:
        (code, body, _h) = put(front.port,
                               "/v1/tenants/count/reports",
                               blobs_for(m, 1)[0])
        assert code == 400 and body["error"] == "quarantined"
        assert svc.metrics()["tenants"]["count"]["counters"][
            "admitted"] == 0
    finally:
        front.stop()


def test_http_accept_checkpoint_fires():
    configure_registry()
    (svc, m) = make_service()
    inj = faults.FaultInjector(
        faults.parse_faults(
            "delay:party=collector:step=http_accept:delay=0.05"),
        "collector")
    front = UploadFront(svc, config=NetConfig(),
                        injector=inj).start()
    try:
        t0 = time.perf_counter()
        (code, _b, _h) = put(front.port,
                             "/v1/tenants/count/reports",
                             blobs_for(m, 1)[0])
        assert code == 201
        assert time.perf_counter() - t0 >= 0.05
        assert inj.rules[0].fired
    finally:
        front.stop()


# -- the shaped transport ---------------------------------------------

def test_parse_shape():
    sh = transport_mod.parse_shape("bw=1m:rtt=20ms:jitter=2ms:seed=7")
    assert (sh.bandwidth, sh.rtt, sh.jitter, sh.seed) \
        == (1e6, 0.02, 0.002, 7)
    assert transport_mod.parse_shape("bw=64k").bandwidth == 64e3
    assert transport_mod.parse_shape("rtt=1.5s").rtt == 1.5
    assert transport_mod.parse_shape("") is None
    assert transport_mod.parse_shape(None) is None
    for bad in ("speed=1", "bw=fast", "rtt=xms", "bw"):
        with pytest.raises(ValueError):
            transport_mod.parse_shape(bad)


def test_shaped_channel_roundtrip_and_accounting():
    (a, b) = socket.socketpair()
    shape = transport_mod.LinkShape(bandwidth=1e6, rtt=0.004,
                                    jitter=0.001, seed=3)
    tx = Channel(a, "peer", timeout=5.0,
                 transport=transport_mod.ShapedTransport(a, shape))
    rx = Channel(b, "peer", timeout=5.0)
    try:
        payload = bytes(range(256)) * 8
        t0 = time.perf_counter()
        tx.send_msg(payload, "s")
        got = rx.recv_msg("s")
        elapsed = time.perf_counter() - t0
        assert got == payload
        # rtt/2 at minimum was slept; bytes counted on both ends.
        assert elapsed >= 0.002
        assert tx.transport.slept_s > 0
        assert tx.sent_bytes == len(payload) + 4
        assert rx.recv_bytes == len(payload) + 4
    finally:
        tx.close()
        rx.close()


def test_shaped_jitter_is_deterministic_per_seed():
    shape = transport_mod.LinkShape(jitter=0.01, seed=11)

    def sleeps(s):
        (a, b) = socket.socketpair()
        tr = transport_mod.ShapedTransport(a, s)
        out = []
        for _ in range(5):
            before = tr.slept_s
            tr.send(b"x")
            out.append(round(tr.slept_s - before, 6))
        a.close()
        b.close()
        return out

    assert sleeps(shape) == sleeps(
        transport_mod.LinkShape(jitter=0.01, seed=11))
    assert sleeps(shape) != sleeps(
        transport_mod.LinkShape(jitter=0.01, seed=12))


def test_net_send_checkpoint_fires():
    (a, b) = socket.socketpair()
    inj = faults.FaultInjector(
        faults.parse_faults(
            "delay:party=leader:step=net_send:delay=0.01"),
        "leader")
    tr = transport_mod.ShapedTransport(
        a, transport_mod.LinkShape(), injector=inj)
    try:
        tr.send(b"frame")
        assert inj.rules[0].fired
    finally:
        a.close()
        b.close()


def test_for_socket_plain_is_none():
    (a, b) = socket.socketpair()
    assert transport_mod.for_socket(a, None) is None
    a.close()
    b.close()


# -- concurrent-upload stress (the r15 page-multiset check) -----------

def test_concurrent_uploads_zero_lost_zero_duplicated():
    """4 client threads stream DISTINCT blobs over HTTP; every 201
    must land exactly once — the buffered pages' blob multiset equals
    the acked multiset exactly."""
    configure_registry()
    (svc, m) = make_service(max_buffered=512, ingest_threads=2,
                            ingest_queue=64)
    front = UploadFront(svc, config=NetConfig()).start()
    acked: list = [None] * 4
    try:
        pools = [blobs_for(m, 16, replay=10 + i) for i in range(4)]

        def feed(wid: int) -> None:
            got = []
            conn = HTTPConnection("127.0.0.1", front.port,
                                  timeout=30)
            for blob in pools[wid]:
                conn.request("PUT", "/v1/tenants/count/reports",
                             body=blob,
                             headers={"Content-Type": MEDIA_TYPE})
                resp = conn.getresponse()
                resp.read()
                if resp.status in (201, 202):
                    got.append(blob)
            conn.close()
            acked[wid] = got

        threads = [threading.Thread(target=feed, args=(i,))
                   for i in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        svc.flush_ingest()
    finally:
        front.stop()
        svc.stop_ingest()
    sent = [b for got in acked for b in got]
    assert len(sent) == 64
    buffered = loadgen_mod.buffered_blobs(svc, "count")
    assert loadgen_mod.decode_pool_multiset(buffered) \
        == loadgen_mod.decode_pool_multiset(sent)
    assert svc.metrics()["tenants"]["count"]["counters"][
        "admitted"] == 64


# -- observability ----------------------------------------------------

def test_net_metrics_and_span(front_svc):
    from mastic_tpu.obs import trace as trace_mod
    from mastic_tpu.obs.registry import get_registry
    from mastic_tpu.obs.trace import get_tracer

    trace_mod.configure()   # fresh ring: the tracer is process-wide
    (front, svc, m) = front_svc
    put(front.port, "/v1/tenants/count/reports", blobs_for(m, 1)[0])
    put(front.port, "/v1/tenants/count/reports", b"garbage")
    reg = get_registry()
    assert reg.counter("mastic_net_http_requests_total",
                       code="201").value() == 1
    assert reg.counter("mastic_net_http_requests_total",
                       code="400").value() == 1
    hist = reg.histogram("mastic_net_admission_latency_ms").value()
    assert hist["count"] == 2
    assert reg.gauge("mastic_net_active_connections").value() == 0
    spans = [sp for sp in get_tracer().spans()
             if sp.name == "net.request"]
    assert len(spans) == 2
    assert sorted(sp.attrs["code"] for sp in spans) == [201, 400]
    assert all(sp.duration_ms is not None for sp in spans)


def test_record_span_single_call_form():
    from mastic_tpu.obs.trace import Tracer

    tracer = Tracer()
    sp = tracer.record_span("net.request", duration_ms=12.5,
                            method="PUT", code=201)
    assert sp.duration_ms == 12.5
    assert sp.attrs == {"method": "PUT", "code": 201}
    assert sp in tracer.spans()


def test_shed_external_lands_in_ledger():
    configure_registry()
    (svc, _m) = make_service()
    svc.shed_external("count", "rate-limited", n=3)
    c = svc.metrics()["tenants"]["count"]["counters"]
    assert c["shed"] == 3
    assert c["shed_reasons"] == {"rate-limited": 3}


# -- load generator units ---------------------------------------------

def test_schedule_deterministic_and_burst_shaped():
    profile = loadgen_mod.LoadProfile(clients=100_000, duration_s=4.0,
                                      rate=200.0, burst_factor=4.0,
                                      malformed_frac=0.1, replay=5)
    ev1 = loadgen_mod.build_schedule(profile, ["count"])
    ev2 = loadgen_mod.build_schedule(profile, ["count"])
    assert [(e.t, e.client, e.malformed) for e in ev1] \
        == [(e.t, e.client, e.malformed) for e in ev2]
    # Bursts densify the burst windows vs the steady stretches.
    in_burst = sum(1 for e in ev1
                   if (e.t % profile.burst_every_s)
                   < profile.burst_len_s)
    frac = in_burst / len(ev1)
    window_frac = profile.burst_len_s / profile.burst_every_s
    assert frac > 1.5 * window_frac
    bad = sum(1 for e in ev1 if e.malformed)
    assert 0.04 < bad / len(ev1) < 0.2
    assert all(0 <= e.client < profile.clients for e in ev1)


def test_zipf_mix_and_client_ips():
    profile = loadgen_mod.LoadProfile(clients=1000, duration_s=3.0,
                                      rate=300.0, zipf_s=1.3,
                                      replay=2)
    events = loadgen_mod.build_schedule(profile, ["a", "b"])
    clients = [e.client for e in events]
    counts = {}
    for c in clients:
        counts[c] = counts.get(c, 0) + 1
    top = max(counts.values())
    assert top > 3 * (len(clients) / len(counts))   # skewed head
    assert loadgen_mod.client_ip(0x01020304) == "10.2.3.4"
    assert {e.tenant for e in events} == {"a", "b"}


def test_malform_variants_decode_fail():
    from mastic_tpu.drivers.service import decode_upload

    m = MasticCount(BITS)
    blob = blobs_for(m, 1)[0]
    rng = np.random.default_rng(0)
    for _ in range(8):
        bad = loadgen_mod.malform(blob, rng)
        with pytest.raises((ValueError, EOFError)):
            decode_upload(m, bad)


def test_loadgen_small_run_accounting():
    """A small end-to-end LoadGenerator run: every offered event is
    answered, codes are the admission taxonomy, counters agree."""
    configure_registry()
    (svc, m) = make_service(max_buffered=10_000)
    front = UploadFront(svc,
                        config=NetConfig(max_connections=64,
                                         trust_forwarded=True)
                        ).start()
    try:
        pools = {"count": {
            "valid": blobs_for(m, 8),
            "malformed": [loadgen_mod.malform(
                blobs_for(m, 2)[0], np.random.default_rng(1))],
        }}
        profile = loadgen_mod.LoadProfile(
            clients=10_000, duration_s=1.0, rate=120.0,
            malformed_frac=0.1, workers=4, replay=3)
        gen = loadgen_mod.LoadGenerator("127.0.0.1", front.port,
                                        profile, pools)
        rec = gen.run()
    finally:
        front.stop()
    assert rec["transport_errors"] == 0
    assert rec["answered"] == rec["offered"] == len(gen.events)
    assert set(rec["codes"]) <= {"201", "400"}
    c = svc.metrics()["tenants"]["count"]["counters"]
    assert c["admitted"] == rec["codes"].get("201", 0)
    assert c["quarantined"] == rec["codes"].get("400", 0)
    assert rec["latency_ms"]["p99"] is not None


# -- the shaped leader/helper session (slow; `make net-smoke` runs
#    the bit-identity acceptance test by explicit node id) ------------

def _session_reports(m):
    rng = np.random.default_rng(0)
    reports = []
    for value in (0, 0, 3, 3):
        alpha = m.vidpf.test_index_from_int(value, BITS)
        nonce = bytes(rng.integers(0, 256, m.NONCE_SIZE,
                                   dtype="uint8"))
        rand = bytes(rng.integers(0, 256, m.RAND_SIZE,
                                  dtype="uint8"))
        (ps, shares) = m.shard(CTX, (alpha, True), nonce, rand)
        reports.append((nonce, ps, shares))
    return reports


def _session_walk(m, reports, vk, thresholds):
    """A full heavy-hitters collection through the process-separated
    AggregationSession: per-level rounds, threshold pruning, child
    expansion — returns (hitters, per-round (result, accept, shares)
    records)."""
    from mastic_tpu.drivers.heavy_hitters import get_threshold
    from mastic_tpu.drivers.parties import AggregationSession
    from mastic_tpu.drivers.session import SessionConfig

    cfg = SessionConfig(connect_timeout=30.0, exchange_timeout=300.0,
                        ack_timeout=60.0, round_deadline=600.0,
                        shutdown_timeout=5.0, retries=0, backoff=0.2)
    spec = {"class": "MasticCount", "args": [BITS]}
    sess = AggregationSession(m, spec, CTX, vk, config=cfg)
    rounds = []
    try:
        sess.upload(reports)
        prefixes = [(False,), (True,)]
        for level in range(BITS):
            param = (level, tuple(prefixes), level == 0)
            (result, accept, shares) = sess.round(param)
            rounds.append((list(result), [bool(x) for x in accept],
                           shares))
            survivors = [p for (p, c) in zip(prefixes, result)
                         if c >= get_threshold(thresholds, p)]
            if level == BITS - 1:
                prefixes = survivors
            else:
                prefixes = [p + (b,) for p in survivors
                            for b in (False, True)]
    finally:
        sess.close()
    return (prefixes, rounds)


@pytest.mark.slow
def test_shaped_parties_bit_identical_to_in_process(monkeypatch):
    """The net-smoke acceptance test: leader and helper complete a
    full collection over the SHAPED network link (bandwidth + RTT +
    jitter), and the result is bit-identical to both the unshaped
    session and the in-process driver."""
    from mastic_tpu.drivers.heavy_hitters import compute_heavy_hitters

    m = MasticCount(BITS)
    vk = bytes(range(m.VERIFY_KEY_SIZE))
    thresholds = {"default": 2}
    reports = _session_reports(m)

    expected = sorted(compute_heavy_hitters(m, CTX, thresholds,
                                            reports, verify_key=vk))

    monkeypatch.delenv("MASTIC_NET_SHAPE", raising=False)
    (plain_hitters, plain_rounds) = _session_walk(m, reports, vk,
                                                  thresholds)
    monkeypatch.setenv("MASTIC_NET_SHAPE",
                       "bw=256k:rtt=10ms:jitter=1ms:seed=4")
    (wan_hitters, wan_rounds) = _session_walk(m, reports, vk,
                                              thresholds)

    assert sorted(plain_hitters) == expected
    assert sorted(wan_hitters) == expected
    # Bit-identity over the shaped link: every round's result vector,
    # accept mask AND raw aggregate-share bytes match the loopback
    # session's exactly.
    assert wan_rounds == plain_rounds


@pytest.mark.slow
def test_upload_kill9_resume_drill():
    """The mid-upload kill-9 + serve.py --resume drill (the same
    scenario `tools/loadgen.py --smoke` gates in CI): at-least-once
    client retry + snapshot-before-ack = exactly-once admission,
    results bit-identical to a clean run."""
    import argparse
    import tempfile

    from tools.loadgen import run_upload_drill

    args = argparse.Namespace(replay=0)
    tmp = tempfile.mkdtemp(prefix="mastic_net_drill_test_")
    rec = run_upload_drill(args, tmp)
    assert rec["bit_identical"] is True
    assert rec["admitted_total"] == 6


# -- transport security: mTLS + reconnect-and-replay (ISSUE 14) -------
#
# Fast tier: certs are minted once per module (openssl CLI, EC P-256,
# ~a second), every case is socket-level — no XLA compile anywhere.
# The full two-party TCP+mTLS collection and the seeded chaos
# campaign run in `make chaos-smoke` (tools/serve.py --chaos-drill).

from mastic_tpu.drivers.session import (SessionConfig, SessionError,
                                        reliable_accept,
                                        reliable_connect)
from mastic_tpu.net.transport import TcpListener, TlsConfig

RCFG = SessionConfig(connect_timeout=5.0, exchange_timeout=5.0,
                     ack_timeout=5.0, round_deadline=30.0,
                     shutdown_timeout=2.0, retries=2, backoff=0.05)


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    """One CA + per-party certs, plus the negative-matrix material:
    a second CA with its own 'collector' cert (wrong CA) and an
    expired collector cert under the pinned CA."""
    from tools import certs as certs_mod

    good = tmp_path_factory.mktemp("certs")
    certs_mod.mint_party_set(good)
    certs_mod.mint_party(good, "collector", days=-1,
                         suffix="-expired")
    rogue = tmp_path_factory.mktemp("rogue_certs")
    certs_mod.mint_ca(rogue, ca_name="rogue-ca")
    certs_mod.mint_party(rogue, "collector")
    return (good, rogue)


def _tls(d, name: str) -> TlsConfig:
    return TlsConfig(str(d / f"{name}.pem"), str(d / f"{name}.key"),
                     str(d / "ca.pem"))


def _accept_outcome(listener) -> tuple:
    """Run one accept on a thread; returns (thread, result dict) —
    result carries either 'sock' or the refusal's kind/reason."""
    result: dict = {}

    def run():
        try:
            result["sock"] = listener.accept("collector", 5.0)
        except SessionError as err:
            result["kind"] = err.kind
            result["reason"] = getattr(err, "reason", None)
            result["detail"] = err.detail

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return (t, result)


def test_mtls_session_roundtrip(certs):
    """The positive path: mutually-authenticated reliable channels
    carry framed messages both ways, and the per-party name pinning
    holds (collector cert accepted by a listener expecting
    'collector')."""
    (good, _rogue) = certs
    lst = TcpListener("127.0.0.1", 0,
                      tls=_tls(good, "leader").expecting("collector"))
    got = {}

    def server():
        ch = reliable_accept(lst, "collector", RCFG)
        got["msg"] = ch.recv_msg("m")
        ch.send_msg(b"pong", "m")
        got["chan"] = ch

    t = threading.Thread(target=server, daemon=True)
    t.start()
    ch = reliable_connect("127.0.0.1", lst.port, "leader", RCFG,
                          tls=_tls(good, "collector"))
    try:
        ch.send_msg(b"ping over mTLS", "m")
        assert ch.recv_msg("m") == b"pong"
        t.join(timeout=5)
        assert got["msg"] == b"ping over mTLS"
    finally:
        ch.close()
        got["chan"].close()
        lst.close()


def test_mtls_negative_matrix(certs):
    """Every bad credential class is refused with its reason code and
    zero admitted frames: wrong CA, expired cert, plaintext client,
    truncated handshake — and the refusals land in the listener's
    ledger + the registry series."""
    from mastic_tpu.obs.registry import get_registry

    configure_registry()
    (good, rogue) = certs
    lst = TcpListener("127.0.0.1", 0,
                      tls=_tls(good, "leader").expecting("collector"))
    try:
        import ssl as ssl_mod

        from mastic_tpu.net.transport import tcp_dial as dial_fn

        def dial_await_verdict(tls):
            """Dial, then READ: TLS 1.3 lets the dialer 'finish'
            before the listener verifies its cert, so the refusal
            arrives as an alert on the first read — waiting for it
            makes the server-side outcome deterministic."""
            try:
                s = dial_fn("127.0.0.1", lst.port, "leader", 5.0,
                            tls=tls)
            except SessionError:
                return
            try:
                s.settimeout(5)
                s.recv(1)
            except (ssl_mod.SSLError, OSError):
                pass
            finally:
                s.close()

        # wrong CA: the dialer presents a collector cert signed by
        # the ROGUE CA (it still pins the good CA for the server, so
        # the refusal is the server's verdict on the client cert)
        (t, res) = _accept_outcome(lst)
        dial_await_verdict(TlsConfig(str(rogue / "collector.pem"),
                                     str(rogue / "collector.key"),
                                     str(good / "ca.pem")))
        t.join(timeout=5)
        assert (res["kind"], res["reason"]) == ("tls",
                                                "tls-wrong-ca"), res

        # expired collector cert under the pinned CA
        (t, res) = _accept_outcome(lst)
        dial_await_verdict(
            TlsConfig(str(good / "collector-expired.pem"),
                      str(good / "collector-expired.key"),
                      str(good / "ca.pem")))
        t.join(timeout=5)
        assert res["reason"] == "tls-expired-cert", res

        # plaintext client against the TLS listener
        (t, res) = _accept_outcome(lst)
        raw = socket.create_connection(("127.0.0.1", lst.port),
                                       timeout=5)
        raw.sendall(b"\x02plaintext session frame")
        t.join(timeout=5)
        raw.close()
        assert res["reason"] == "tls-plaintext", res

        # truncated handshake: a TLS record header, then EOF
        (t, res) = _accept_outcome(lst)
        raw = socket.create_connection(("127.0.0.1", lst.port),
                                       timeout=5)
        raw.sendall(b"\x16\x03\x01\x00\x80")
        raw.close()
        t.join(timeout=5)
        assert res["reason"] == "tls-truncated-handshake", res

        assert lst.refusals == {"tls-wrong-ca": 1,
                                "tls-expired-cert": 1,
                                "tls-plaintext": 1,
                                "tls-truncated-handshake": 1}
        reg = get_registry()
        for reason in lst.refusals:
            assert reg.counter("mastic_tls_refusals_total",
                               reason=reason,
                               side="server").value() == 1
    finally:
        lst.close()


def test_mtls_hostname_mismatch_refused(certs):
    """CA-valid credential, wrong NAME: the dialer expects 'helper'
    but the listener presents the leader cert — refused client-side
    with the hostname reason; the listener sees the alert."""
    (good, _rogue) = certs
    lst = TcpListener("127.0.0.1", 0,
                      tls=_tls(good, "leader").expecting("collector"))
    try:
        (t, res) = _accept_outcome(lst)
        with pytest.raises(SessionError) as ei:
            reliable_connect("127.0.0.1", lst.port, "helper", RCFG,
                             tls=_tls(good, "collector"))
        assert ei.value.kind == "tls"
        assert getattr(ei.value, "reason", None) \
            == "tls-hostname-mismatch"
        t.join(timeout=5)
        assert res.get("reason") == "tls-peer-refused", res
    finally:
        lst.close()


def test_reliable_reconnect_and_replay_exactly_once():
    """A connection killed between (and inside) exchanges redials and
    resumes from the last acked frame: every payload arrives exactly
    once, reconnects/replayed_frames are attributed."""
    lst = TcpListener("127.0.0.1", 0)
    got = {}

    def server():
        ch = reliable_accept(lst, "collector", RCFG)
        got["msgs"] = [ch.recv_msg("s") for _ in range(3)]
        ch.send_msg(b"done", "s")
        got["chan"] = ch

    t = threading.Thread(target=server, daemon=True)
    t.start()
    ch = reliable_connect("127.0.0.1", lst.port, "leader", RCFG)
    try:
        ch.send_msg(b"one", "s")
        ch.tp.kill_socket()          # drop between frames
        ch.send_msg(b"two", "s")
        ch.tp.kill_socket()          # and again
        ch.send_msg(b"three", "s")
        assert ch.recv_msg("s") == b"done"
        t.join(timeout=5)
        assert got["msgs"] == [b"one", b"two", b"three"]
        assert ch.reconnects == 2
        assert ch.replayed_frames >= 1
    finally:
        ch.close()
        got["chan"].close()
        lst.close()


def test_injected_conn_drop_recovers_and_traces():
    """The on_net fault seam: an injected conn_drop fires AFTER the
    frame enters the replay buffer, so recovery runs reconnect-and-
    replay; the trace carries a `session_reconnect` event (distinct
    from `session_retry`) with the replay attribution, and the
    registry counts the reconnect."""
    from mastic_tpu.obs import trace as trace_mod
    from mastic_tpu.obs.registry import get_registry

    configure_registry()
    tracer = trace_mod.configure()
    inj = faults.FaultInjector(
        faults.parse_faults("conn_drop:party=collector:step=upload"),
        "collector")
    lst = TcpListener("127.0.0.1", 0)
    got = {}

    def server():
        ch = reliable_accept(lst, "collector", RCFG)
        got["msg"] = ch.recv_msg("upload")
        got["chan"] = ch

    t = threading.Thread(target=server, daemon=True)
    t.start()
    ch = reliable_connect("127.0.0.1", lst.port, "leader", RCFG)
    ch.tp.injector = inj
    try:
        ch.send_msg(b"report body", "upload")
        t.join(timeout=5)
        assert got["msg"] == b"report body"
        assert inj.rules[0].fired
        assert ch.reconnects == 1 and ch.replayed_frames >= 1
        events = [ev for sp in tracer.spans() for ev in [sp]
                  if sp.name == "session_reconnect"]
        assert events, [sp.name for sp in tracer.spans()]
        attrs = events[-1].attrs
        assert attrs["frames_replayed"] >= 1
        assert attrs["redials"] == 1
        assert not [sp for sp in tracer.spans()
                    if sp.name == "session_retry"]
        # Both ends of the link count their own recovery (the server
        # thread re-accepted), so the process-wide series sees >= 1.
        assert get_registry().counter(
            "mastic_session_reconnects_total",
            tenant="").value() >= 1
        assert get_registry().counter(
            "mastic_frames_replayed_total", tenant="").value() >= 1
    finally:
        ch.close()
        got["chan"].close()
        lst.close()
        trace_mod.configure()


def test_injected_partition_heals_within_deadline():
    """A partition (both directions down for delay seconds) heals:
    the redial ladder backs off through the partition window and the
    exchange completes, attributed as a reconnect."""
    inj = faults.FaultInjector(
        faults.parse_faults(
            "partition:party=collector:step=agg_param:delay=0.3"),
        "collector")
    lst = TcpListener("127.0.0.1", 0)
    got = {}

    def server():
        ch = reliable_accept(lst, "collector", RCFG)
        got["msg"] = ch.recv_msg("agg_param")
        got["chan"] = ch

    t = threading.Thread(target=server, daemon=True)
    t.start()
    ch = reliable_connect("127.0.0.1", lst.port, "leader", RCFG)
    ch.tp.injector = inj
    try:
        t0 = time.monotonic()
        ch.send_msg(b"round command", "agg_param")
        t.join(timeout=10)
        assert got["msg"] == b"round command"
        assert time.monotonic() - t0 >= 0.3   # waited out the cut
        assert ch.reconnects == 1
    finally:
        ch.close()
        got["chan"].close()
        lst.close()


def test_recv_timeout_does_not_redial():
    """A slow peer is slow, not gone: a recv timeout surfaces as an
    attributed SessionError without burning a reconnect."""
    lst = TcpListener("127.0.0.1", 0)
    srv = {}

    def server():
        srv["chan"] = reliable_accept(lst, "collector", RCFG)

    t = threading.Thread(target=server, daemon=True)
    t.start()
    ch = reliable_connect("127.0.0.1", lst.port, "leader", RCFG)
    t.join(timeout=5)
    try:
        with pytest.raises(SessionError) as ei:
            ch.recv_msg("agg_share", timeout=0.2)
        assert ei.value.kind == "timeout"
        assert ch.reconnects == 0
    finally:
        ch.close()
        srv["chan"].close()
        lst.close()


def test_idle_timeout_sheds_slow_loris():
    """ISSUE 14 satellite: a slow-loris client (bytes trickling under
    the per-read io_timeout) is shed at the whole-body idle budget
    with reason `idle-timeout` — the connection slot comes back, the
    ledger and the 408 are explicit."""
    configure_registry()
    (svc, m) = make_service()
    front = UploadFront(
        svc, config=NetConfig(idle_timeout=0.3, io_timeout=5.0)
    ).start()
    try:
        blob = blobs_for(m, 1)[0]
        sock = socket.create_connection(("127.0.0.1", front.port),
                                        timeout=10)
        try:
            head = (f"PUT /v1/tenants/count/reports HTTP/1.1\r\n"
                    f"Host: t\r\nContent-Type: {MEDIA_TYPE}\r\n"
                    f"Content-Length: {len(blob) + 64}\r\n\r\n"
                    ).encode()
            sock.sendall(head + blob[:8])   # then stall, holding on
            t0 = time.monotonic()
            chunks = []
            while True:
                data = sock.recv(4096)
                if not data:
                    break
                chunks.append(data)
            resp = b"".join(chunks).decode()
        finally:
            sock.close()
        waited = time.monotonic() - t0
        assert " 408 " in resp.splitlines()[0], resp
        assert "idle-timeout" in resp
        assert 0.2 <= waited < 5.0   # the budget, not io_timeout
        c = svc.metrics()["tenants"]["count"]["counters"]
        assert c["shed_reasons"] == {"idle-timeout": 1}
        assert c["admitted"] == 0
        # The slot is free again: a well-behaved upload admits.
        assert put(front.port, "/v1/tenants/count/reports",
                   blob)[0] == 201
    finally:
        front.stop()


def test_tls_config_env_parsing(monkeypatch, certs):
    """Partial MASTIC_NET_TLS_* is an error (silent plaintext when
    the operator meant TLS would be the worst outcome); a full set
    parses; an empty set means unarmed."""
    (good, _rogue) = certs
    for var in ("MASTIC_NET_TLS_CERT", "MASTIC_NET_TLS_KEY",
                "MASTIC_NET_TLS_CA", "MASTIC_NET_TLS_NAME"):
        monkeypatch.delenv(var, raising=False)
    assert TlsConfig.from_env() is None
    monkeypatch.setenv("MASTIC_NET_TLS_CERT",
                       str(good / "leader.pem"))
    with pytest.raises(ValueError):
        TlsConfig.from_env()
    monkeypatch.setenv("MASTIC_NET_TLS_KEY",
                       str(good / "leader.key"))
    monkeypatch.setenv("MASTIC_NET_TLS_CA", str(good / "ca.pem"))
    tls = TlsConfig.from_env()
    assert tls.ca_file == str(good / "ca.pem")
    assert tls.peer_name is None
    assert tls.expecting("collector").peer_name == "collector"
