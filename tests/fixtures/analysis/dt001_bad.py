"""Known-bad: u8/u32 mixed in one op without astype (DT001)."""

import jax.numpy as jnp


def mix():
    bytes_ = jnp.zeros((4,), jnp.uint8)
    words = jnp.zeros((4,), jnp.uint32)
    return bytes_ + words
