"""OB001 good fixture: diagnostics route through the telemetry
layer, where they can be scraped, asserted on, and attributed."""


def observed_round(level: int, trace, registry) -> int:
    trace.event("round_start", level=level)
    result = level * 2
    registry.counter("mastic_rounds_total", tenant="t").inc()
    return result
