"""RL003: recv on a socket that is closed on every path reaching
the call."""
import socket


def reuse(host, port):
    sock = socket.create_connection((host, port))
    sock.close()
    return sock.recv(16)
