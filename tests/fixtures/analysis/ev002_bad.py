"""EV002: a send loop with no writability registration — a slow
reader turns it into a spin (non-blocking) or a stall (blocking)."""


def flush(sock, payload):
    sock.setblocking(False)
    while payload:
        sent = sock.send(payload)
        payload = payload[sent:]
