"""RL004: second close on a socket already closed on every path."""
import socket


def shutdown(host, port):
    sock = socket.create_connection((host, port))
    sock.close()
    sock.close()
