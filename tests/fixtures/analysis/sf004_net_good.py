"""Known-good twin of sf004_net_bad: the error body is built from
fixed strings and public reason names only (the net/ingest.py error
contract); the key never reaches the response."""
import json


def error_body(reason: str) -> str:
    return json.dumps({"error": "quarantined", "reason": reason})


def respond(wfile, key):
    del key   # authenticates the tenant upstream; never echoed
    wfile.write(error_body("malformed"))
