"""SF005 bad fixture: the backoff pause depends on key bytes."""
import time


def backoff(key):
    time.sleep(0.1 * key[0])
