"""Batched AES-128 (encrypt-only) in JAX, bitsliced per byte.

TPUs have no AES instructions and data-dependent table lookups are both
slow (gathers) and timing-leaky, so SubBytes is computed as a boolean
circuit over the 8 bit-planes of each byte: GF(2^8) inversion by the
addition chain x^254 (4 multiplies + 8 squarings on bit-planes)
followed by the affine map.  This is constant-time by construction —
the TPU-native reading of the reference's side-channel notes
(/root/reference/poc/vidpf.py:116-119).

The circuit functions are generic over the array type (anything with
&, ^): at import they are run on numpy over all 256 byte values and
asserted equal to the generated S-box table of the scalar reference
(mastic_tpu.aes.SBOX), so the JAX path and the scalar path cannot
drift.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..aes import SBOX, _gf_mul
from .sbox_tower import sbox_planes_tower

_U8 = jnp.uint8

# Route the bitsliced encrypt through the Pallas fused-VMEM kernel
# (ops/aes_pallas.py).  Off by default: bit-exact by the chained
# interpret suite, but unmeasured on real hardware.
USE_PALLAS = os.environ.get("MASTIC_AES_PALLAS", "0") == "1"


def _planes(x):
    """Split bytes into 8 bit-planes (LSB first), values 0/1."""
    return [(x >> i) & 1 for i in range(8)]


def _unplanes(planes):
    out = planes[0]
    for i in range(1, 8):
        out = out ^ (planes[i] << i)
    return out


def _gf_mul_planes(a, b):
    """Schoolbook GF(2^8) multiply on bit-planes, reduced mod 0x11B."""
    t: list = [None] * 15
    for i in range(8):
        for j in range(8):
            p = a[i] & b[j]
            k = i + j
            t[k] = p if t[k] is None else t[k] ^ p
    # x^8 == x^4 + x^3 + x + 1: fold degrees 14..8 downward so cascades
    # into still-unprocessed degrees are picked up.
    for k in range(14, 7, -1):
        c = t[k]
        t[k - 4] = t[k - 4] ^ c
        t[k - 5] = t[k - 5] ^ c
        t[k - 7] = t[k - 7] ^ c
        t[k - 8] = t[k - 8] ^ c
    return t[:8]


def _gf_square_planes(a):
    """Squaring is linear: sum a_i x^(2i), then fold."""
    zero = a[0] ^ a[0]
    t = [zero] * 15
    for i in range(8):
        t[2 * i] = a[i]
    for k in range(14, 7, -1):
        c = t[k]
        t[k - 4] = t[k - 4] ^ c
        t[k - 5] = t[k - 5] ^ c
        t[k - 7] = t[k - 7] ^ c
        t[k - 8] = t[k - 8] ^ c
    return t[:8]


def _gf_inv_planes(x):
    """x^254 = x^-1 (and 0 -> 0) via an addition chain."""
    x2 = _gf_square_planes(x)
    x3 = _gf_mul_planes(x2, x)
    x6 = _gf_square_planes(x3)
    x12 = _gf_square_planes(x6)
    x15 = _gf_mul_planes(x12, x3)
    x30 = _gf_square_planes(x15)
    x60 = _gf_square_planes(x30)
    x120 = _gf_square_planes(x60)
    x240 = _gf_square_planes(x120)
    x252 = _gf_mul_planes(x240, x12)
    return _gf_mul_planes(x252, x2)


def _sbox_planes(x, one=1):
    """The affine-constant term `one` is 1 for 0/1-valued byte planes
    and all-ones for bit-packed uint32 planes (the circuit itself is
    representation-agnostic: only &, ^ between planes)."""
    inv = _gf_inv_planes(x)
    out = []
    for i in range(8):
        bit = inv[i] ^ inv[(i + 4) % 8] ^ inv[(i + 5) % 8] \
            ^ inv[(i + 6) % 8] ^ inv[(i + 7) % 8]
        if (0x63 >> i) & 1:
            bit = bit ^ one
        out.append(bit)
    return out


def sub_bytes(x):
    """Apply the AES S-box elementwise to a uint8 array (tower-field
    circuit, ops/sbox_tower.py — ~4x fewer gates than the x^254
    chain above, which is kept as independent documentation of the
    inversion)."""
    return _unplanes(sbox_planes_tower(_planes(x), 1))


# Lock BOTH circuits against the table at import (numpy path).
for _circuit in (
        lambda p: _sbox_planes(p),
        lambda p: sbox_planes_tower(p, 1),
):
    _check = _unplanes(_circuit(_planes(np.arange(256, dtype=np.uint8))))
    assert bytes(_check) == SBOX, "S-box circuit diverges from table"
del _check, _circuit


def _xtime(v):
    # mastic-allow: DT002 — the uint8 truncation IS the GF(2^8)
    # reduction: bit 8 of (v << 1) is exactly what the 0x1B term
    # folds back in, so dropping it is the field multiply by x
    return ((v << 1) ^ ((v >> 7) * _U8(0x1B))).astype(_U8)


# ShiftRows: byte i of the new state comes from byte (i + 4*(i%4)) % 16
# (column-major state; scalar reference mastic_tpu/aes.py:97).
_SHIFT_ROWS = tuple((i + 4 * (i % 4)) % 16 for i in range(16))

_RCON = []
_r = 1
for _ in range(10):
    _RCON.append(_r)
    _r = _gf_mul(_r, 2)


def aes128_key_schedule(keys: jax.Array) -> jax.Array:
    """Batched key expansion: (..., 16) uint8 -> (..., 11, 16).

    The 10 expansion rounds run under lax.scan — each round contains a
    full bitsliced S-box circuit, and unrolling all of them dominated
    XLA compile time."""
    words = keys.reshape(keys.shape[:-1] + (4, 4))

    def body(words, rcon):
        s = sub_bytes(words[..., 3, :])
        temp = jnp.stack([s[..., 1] ^ rcon, s[..., 2], s[..., 3],
                          s[..., 0]], axis=-1)
        w0 = words[..., 0, :] ^ temp
        w1 = words[..., 1, :] ^ w0
        w2 = words[..., 2, :] ^ w1
        w3 = words[..., 3, :] ^ w2
        new = jnp.stack([w0, w1, w2, w3], axis=-2)
        return (new, new)

    (_, rounds) = jax.lax.scan(body, words,
                               jnp.asarray(_RCON, dtype=_U8))
    rounds = jnp.moveaxis(rounds, 0, -3)  # (..., 10, 4, 4)
    all_rounds = jnp.concatenate([words[..., None, :, :], rounds],
                                 axis=-3)
    return all_rounds.reshape(keys.shape[:-1] + (11, 16))


def _sub_shift(state: jax.Array) -> jax.Array:
    return sub_bytes(state)[..., _SHIFT_ROWS]


def _mix_columns(state: jax.Array) -> jax.Array:
    cols = state.reshape(state.shape[:-1] + (4, 4))
    rot1 = jnp.roll(cols, -1, axis=-1)
    mixed = _xtime(cols) ^ _xtime(rot1) ^ rot1 \
        ^ jnp.roll(cols, -2, axis=-1) ^ jnp.roll(cols, -3, axis=-1)
    return mixed.reshape(state.shape)


def aes128_encrypt(round_keys: jax.Array, blocks: jax.Array) -> jax.Array:
    """Batched ECB encrypt: round_keys (..., 11, 16) and blocks
    (..., 16) uint8, with broadcasting between the batch shapes.
    Middle rounds run under lax.scan (one S-box circuit compiled, not
    nine)."""
    state = blocks ^ round_keys[..., 0, :]
    mid = jnp.moveaxis(round_keys[..., 1:10, :], -2, 0)
    mid = jnp.broadcast_to(mid, (9,) + state.shape)

    def body(state, rk):
        return (_mix_columns(_sub_shift(state)) ^ rk, None)

    (state, _) = jax.lax.scan(body, state, mid)
    return _sub_shift(state) ^ round_keys[..., 10, :]


# -- batch-bitsliced path ---------------------------------------------
#
# The byte path above stores one 0/1 plane value per array element, so
# every VPU lane carries a single data bit (uint8 elementwise ops run
# in 32-bit lanes on TPU).  For large batches the state is instead
# bit-transposed along the batch axis: bit j of the uint32 word at
# packed index w is batch element 32*w + j, and each of the 128
# (byte, bit) state positions becomes a dense word vector.  The
# boolean circuit is unchanged — its arrays are 32x smaller, which is
# the difference between the VPU spending lanes on padding and
# spending them on data.  Constant-time discipline is preserved (same
# gates, no lookups).

_U32 = jnp.uint32
# numpy scalar on purpose: a jnp constant at module scope would
# initialize the JAX backend at import time (see _RC_LO note in
# ops/keccak_jax.py) — and with the remote-TPU tunnel down that hangs
# every fresh process that merely imports this module.
_ONES32 = np.uint32(0xFFFFFFFF)
_SHIFT_ROWS_ARR = np.asarray(_SHIFT_ROWS)


def bitslice_pack(x: jax.Array) -> jax.Array:
    """uint8 (M, ..., K) with M % 32 == 0 -> planes (8, K, ..., M//32)
    uint32, where bit j of word w is element 32*w + j of the leading
    axis."""
    m = x.shape[0]
    assert m % 32 == 0
    rest = x.shape[1:-1]
    xr = x.reshape((m // 32, 32) + rest + x.shape[-1:]).astype(_U32)
    shifts = jnp.arange(32, dtype=_U32).reshape(
        (1, 32) + (1,) * (len(rest) + 1))
    planes = []
    for b in range(8):
        bits = (xr >> b) & _U32(1)
        planes.append(jnp.sum(bits << shifts, axis=1, dtype=_U32))
    p = jnp.stack(planes)          # (8, W, ..., K)
    p = jnp.moveaxis(p, -1, 1)     # (8, K, W, ...)
    return jnp.moveaxis(p, 2, -1)  # (8, K, ..., W)


def bitslice_unpack(planes: jax.Array) -> jax.Array:
    """Inverse of bitslice_pack: (8, K, ..., W) -> (32*W, ..., K)."""
    p = jnp.moveaxis(planes, -1, 2)  # (8, K, W, ...)
    p = jnp.moveaxis(p, 1, -1)       # (8, W, ..., K)
    shifts = jnp.arange(32, dtype=_U32).reshape(
        (1, 32) + (1,) * (p.ndim - 2))
    acc = None
    for b in range(8):
        bits = ((p[b][:, None] >> shifts) & _U32(1)) << b
        acc = bits if acc is None else acc | bits
    out = acc.astype(_U8)            # (W, 32, ..., K)
    return out.reshape((-1,) + out.shape[2:])


def bitslice_keys(round_keys: jax.Array) -> jax.Array:
    """Key schedules (R, 11, 16) uint8 -> key planes (11, 8, 16, R//32)
    uint32 (R % 32 == 0)."""
    return jnp.moveaxis(bitslice_pack(round_keys), 2, 0)


def pack_mask(bits: jax.Array) -> jax.Array:
    """Pack a bool array (M, ...) along its leading axis:
    -> (..., M//32) uint32 select-mask words (bit j of word w = element
    32*w + j), for plane-domain lane selects (x ^ (planes & mask))."""
    m = bits.shape[0]
    assert m % 32 == 0
    xr = bits.reshape((m // 32, 32) + bits.shape[1:]).astype(_U32)
    shifts = jnp.arange(32, dtype=_U32).reshape(
        (1, 32) + (1,) * (bits.ndim - 1))
    words = jnp.sum(xr << shifts, axis=1, dtype=_U32)  # (W, ...)
    return jnp.moveaxis(words, 0, -1)


def unpack_mask(words: jax.Array, m: int) -> jax.Array:
    """Inverse of pack_mask: (..., W) uint32 -> (m, ...) bool."""
    shifts = jnp.arange(32, dtype=_U32).reshape(
        (1,) * (words.ndim - 1) + (1, 32))
    bits = (words[..., None] >> shifts) & _U32(1)   # (..., W, 32)
    bits = bits.reshape(words.shape[:-1] + (-1,))   # (..., 32W)
    return jnp.moveaxis(bits, -1, 0)[:m].astype(bool)


def block_index_planes(num_blocks: int) -> np.ndarray:
    """le128(i) for i < num_blocks as plane masks: (num_blocks, 8, 16)
    uint32, each entry 0 or 0xFFFFFFFF (XOR-constant in plane form)."""
    out = np.zeros((num_blocks, 8, 16), np.uint32)
    for i in range(num_blocks):
        le = i.to_bytes(16, "little")
        for b in range(8):
            for k in range(16):
                if (le[k] >> b) & 1:
                    out[i, b, k] = 0xFFFFFFFF
    return out


def _xtime_planes(v: jax.Array) -> jax.Array:
    """xtime on a (8, ...) plane stack: shift planes up one, fold the
    top plane into the 0x1B taps (bits 1, 3, 4; bit 0 is the rolled-in
    top plane itself)."""
    out = jnp.roll(v, 1, axis=0)
    hi = v[7]
    out = out.at[1].set(out[1] ^ hi)
    out = out.at[3].set(out[3] ^ hi)
    return out.at[4].set(out[4] ^ hi)


def _mix_columns_planes(s: jax.Array) -> jax.Array:
    c = s.reshape((8, 4, 4) + s.shape[2:])  # (planes, col, row, ...)
    rot1 = jnp.roll(c, -1, axis=2)
    mixed = _xtime_planes(c) ^ _xtime_planes(rot1) ^ rot1 \
        ^ jnp.roll(c, -2, axis=2) ^ jnp.roll(c, -3, axis=2)
    return mixed.reshape(s.shape)


def _sub_shift_planes(s: jax.Array) -> jax.Array:
    sb = jnp.stack(sbox_planes_tower([s[b] for b in range(8)],
                                     _ONES32))
    return sb[:, _SHIFT_ROWS_ARR]


def aes128_encrypt_bitsliced(key_planes: jax.Array,
                             planes: jax.Array) -> jax.Array:
    """Bitsliced ECB encrypt.

    key_planes: (11, 8, 16, W) from bitslice_keys — one schedule per
    packed batch element.  planes: (8, 16, ..., W) state planes whose
    middle dims broadcast against the keys (many blocks per batch
    element, e.g. every tree node of a report)."""
    if USE_PALLAS:
        from .aes_pallas import aes128_encrypt_bitsliced_pallas
        return aes128_encrypt_bitsliced_pallas(key_planes, planes)
    extra = planes.ndim - 3
    kp = key_planes.reshape(
        (11, 8, 16) + (1,) * extra + key_planes.shape[-1:])

    def body(state, rk):
        return (_mix_columns_planes(_sub_shift_planes(state)) ^ rk, None)

    state = planes ^ kp[0]
    (state, _) = jax.lax.scan(body, state, kp[1:10])
    return _sub_shift_planes(state) ^ kp[10]
