"""Known-good: uploads route through the mesh placement helpers
(RB003) — no bare device_put anywhere."""


def upload_chunk(mesh, batch, carry, vk_arr):
    from mastic_tpu.parallel.mesh import place_replicated, place_reports

    (dev_batch, dev_carry) = place_reports(mesh, (batch, carry))
    vk_dev = place_replicated(mesh, vk_arr)
    return (dev_batch, dev_carry, vk_dev)
