"""Level-synchronous batched execution backend (JAX / XLA).

The scalar layer (mastic_tpu.vidpf / .mastic) is the byte-exact oracle;
this package runs the same protocol math as dense arrays over a
(reports x nodes) grid:

  xof_jax     batched XofTurboShake128 / XofFixedKeyAes128
  schedule    host-precomputed prefix-tree node grids (public data only)
  vidpf_jax   batched VIDPF gen / eval / beta shares
  mastic_jax  batched Mastic prep (checks, binders, eval proof)

Everything secret-dependent is computed with lane-wise selects
(jnp.where), never control flow — the TPU-native reading of the
reference's constant-time notes (/root/reference/poc/vidpf.py:116-119,
:300-312).
"""

from .schedule import LevelSchedule
from .vidpf_jax import BatchedVidpf

__all__ = ["LevelSchedule", "BatchedVidpf"]
