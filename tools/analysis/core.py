"""Shared machinery for the static analyzer passes.

One FileInfo per source file (AST + module-constant environment +
suppression table), a Finding record, and the suppression semantics:

    x = risky_thing()  ``mastic-allow: <RULE-ID> — why this is fine``

as a trailing comment, or — for long / multi-line statements — as a
comment-only line directly above the statement (IDs may be a comma
list).  The examples here spell the marker without a real rule ID so
this docstring is not itself parsed as a suppression.

A suppression must name the rule ID(s) it silences and carry a written
justification after the IDs (AL001 flags bare ones); a suppression
that silences nothing is itself a finding (AL002), so stale allows
cannot accumulate.
"""

import ast
import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parent.parent.parent

_ALLOW_RE = re.compile(
    r"#\s*mastic-allow:\s*"
    r"(?P<ids>[A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)"
    r"(?P<rest>.*)$")


class Finding:
    """One analyzer finding; sorts by location.  `sup_reason` is
    filled for suppressed findings (the allow's justification — the
    SARIF emitter exports it)."""

    __slots__ = ("rule", "rel", "line", "msg", "sup_reason")

    def __init__(self, rule: str, rel: str, line: int, msg: str):
        self.rule = rule
        self.rel = rel
        self.line = line
        self.msg = msg
        self.sup_reason = None

    def key(self):
        return (self.rel, self.line, self.rule)

    def text(self) -> str:
        return f"{self.rel}:{self.line}: {self.rule}: {self.msg}"

    def as_json(self) -> dict:
        return {"rule": self.rule, "file": self.rel, "line": self.line,
                "message": self.msg}


class Suppression:
    __slots__ = ("line", "ids", "reason", "comment_only", "used")

    def __init__(self, line: int, ids: tuple, reason: str,
                 comment_only: bool):
        self.line = line
        self.ids = ids
        self.reason = reason
        self.comment_only = comment_only
        self.used = False


def _fold(node: ast.AST, env: dict):
    """Best-effort constant folding of int expressions: literals,
    names bound (once) to folded ints, and +,-,*,// of those."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.BinOp):
        left = _fold(node.left, env)
        right = _fold(node.right, env)
        if left is None or right is None:
            return None
        try:
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.FloorDiv) and right != 0:
                return left // right
            if isinstance(node.op, ast.LShift):
                return left << right
        except (ValueError, OverflowError):
            return None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        val = _fold(node.operand, env)
        return None if val is None else -val
    return None


class FileInfo:
    """Parsed source + the per-file tables every pass shares."""

    def __init__(self, path: pathlib.Path, rel: str, src: str,
                 tree: ast.Module):
        self.path = path
        self.rel = rel
        self.src = src
        self.tree = tree
        self.lines = src.splitlines()
        self.consts = self._module_consts()
        self.suppressions = self._parse_suppressions()
        self.stmt_start = self._statement_starts()

    def _module_consts(self) -> dict:
        """Module-level `NAME = <int expr>` bindings, skipping names
        assigned more than once (they are not constants)."""
        counts: dict = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            counts[n.id] = counts.get(n.id, 0) + 1
        env: dict = {}
        for node in self.tree.body:
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and counts.get(node.targets[0].id) == 1:
                val = _fold(node.value, env)
                if val is not None:
                    env[node.targets[0].id] = val
        return env

    def fold(self, node: ast.AST, local_env: dict = None):
        env = self.consts
        if local_env:
            env = dict(env)
            env.update(local_env)
        return _fold(node, env)

    def _parse_suppressions(self) -> list:
        out = []
        for (i, line) in enumerate(self.lines, start=1):
            m = _ALLOW_RE.search(line)
            if m is None:
                continue
            ids = tuple(s.strip() for s in m.group("ids").split(","))
            reason = m.group("rest").lstrip(" -–—:·")
            comment_only = line.lstrip().startswith("#")
            out.append(Suppression(i, ids, reason.strip(), comment_only))
        return out

    def _statement_starts(self) -> dict:
        """Line -> start line of the smallest statement covering it
        (continuation lines of a multi-line statement map to its first
        line), so a comment-only allow above a statement covers every
        finding inside it."""
        start: dict = {}

        def visit(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt) and hasattr(child, "lineno"):
                    end = getattr(child, "end_lineno", child.lineno)
                    for ln in range(child.lineno, end + 1):
                        start[ln] = child.lineno
                visit(child)   # nested stmts overwrite with tighter spans

        visit(self.tree)
        return start

    def suppression_for(self, finding: Finding):
        """The suppression covering `finding`, or None: same line, or a
        comment-only allow on the line above the enclosing statement."""
        stmt = self.stmt_start.get(finding.line, finding.line)
        for sup in self.suppressions:
            if finding.rule not in sup.ids:
                continue
            if sup.line == finding.line:
                return sup
            if sup.comment_only and sup.line == stmt - 1:
                return sup
            # A block of consecutive comment-only allow lines above the
            # statement (continuation comments in between are fine).
            if sup.comment_only and sup.line < stmt:
                gap = self.lines[sup.line:stmt - 1]
                if all(ln.lstrip().startswith("#") for ln in gap):
                    return sup
        return None


def load_file(path: pathlib.Path):
    """FileInfo for `path`, or a Finding for unparsable source."""
    rel = str(path.relative_to(REPO))
    try:
        src = path.read_text()
        tree = ast.parse(src, filename=rel)
    except SyntaxError as err:
        return Finding("XX000", rel, err.lineno or 0,
                       f"syntax error: {err.msg}")
    return FileInfo(path, rel, src, tree)


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target, best effort ('' if dynamic)."""
    return dotted(node.func)


def dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def root_name(node: ast.AST) -> str:
    """Leftmost name of an attribute/subscript/call chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        node = node.value if not isinstance(node, ast.Call) else node.func
    return node.id if isinstance(node, ast.Name) else ""


def target_names(target: ast.AST) -> list:
    """Plain names bound by an assignment target.  Attribute/Subscript
    stores (obj.x = v, obj[i] = v) bind no *name* — tainting their
    base object would e.g. mark `self` secret because one field is."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, ast.Starred):
        return target_names(target.value)
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for e in target.elts:
            out += target_names(e)
        return out
    return []


def for_target_taints(target, iter_node, is_tainted) -> list:
    """Names a `for target in iter:` loop taints, given a predicate
    over expressions.  A literal sequence of same-length literal
    tuples is unpacked positionally, so `for (i, x) in ((0, a), ...)`
    taints only the positions whose values are tainted."""
    if isinstance(target, (ast.Tuple, ast.List)) \
            and isinstance(iter_node, (ast.Tuple, ast.List)) \
            and iter_node.elts \
            and all(isinstance(e, (ast.Tuple, ast.List))
                    and len(e.elts) == len(target.elts)
                    for e in iter_node.elts):
        out = []
        for (pos, sub) in enumerate(target.elts):
            if any(is_tainted(e.elts[pos]) for e in iter_node.elts):
                out += target_names(sub)
        return out
    if is_tainted(iter_node):
        return target_names(target)
    return []
