"""Attribute-based metrics mode: a single aggregation at the last
level with hashed attributes as the index space.

Functionally equivalent to the reference
(/root/reference/poc/examples.py:172-260; spec mode
draft-mouris-cfrg-mastic.md:1574-1611): alpha = H(attribute) truncated
to BITS, one weight-checked aggregation at level BITS-1 with the
candidate prefixes being the collector's attributes of interest.
"""

import hashlib
import json
import time
from typing import Optional, Sequence

from ..common import gen_rand
from ..mastic import Mastic
from ..obs import devtime, trace as obs_trace
from ..backend.mastic_jax import BatchedMastic


def hash_attribute(mastic: Mastic, attribute: str) -> tuple:
    """SHA3-256 the attribute and keep the first BITS bits (the
    reference truncates the same way for BITS=8; collision resistance
    governs how small BITS may be in practice)."""
    bits = mastic.vidpf.BITS
    digest = hashlib.sha3_256(attribute.encode()).digest()
    value = int.from_bytes(digest[:(bits + 7) // 8], "big")
    value >>= (8 - bits % 8) % 8
    return mastic.vidpf.test_index_from_int(value, bits)


def aggregate_by_attribute(mastic: Mastic, ctx: bytes,
                           attributes: Sequence[str], reports: list,
                           verify_key: Optional[bytes] = None,
                           metrics_out: Optional[list] = None,
                           chunk_size: Optional[int] = None,
                           mesh=None) -> list:
    """Aggregate `reports` grouped by the collector's attributes of
    interest.  Returns [(attribute, aggregate)] pairs; appends a
    RoundMetrics record to `metrics_out` (observability, SURVEY §5).

    With `chunk_size`, reports stream through the single aggregation
    round in fixed-size blocks (the device never holds the whole
    batch; full chunks share one compiled program, the tail runs at
    its natural size), bit-identical to the unchunked result.

    With `mesh`, each chunk's report axis shards across the mesh's
    "reports" devices (padded to the shard multiple and masked when
    uneven — same rule as the chunked heavy-hitters runner) and the
    masked aggregation's psum is the round's only cross-chip
    collective; bit-identical to the single-device result either way.

    Internally one `AttributeMetricsRun` — the same scheduler-facing
    round loop the collector service drives (drivers/service.py), so
    the offline call and the service epoch execute the identical
    code path.
    """
    run = AttributeMetricsRun(mastic, ctx, attributes, reports,
                              verify_key=verify_key,
                              chunk_size=chunk_size, mesh=mesh)
    while run.step():
        pass
    if metrics_out is not None:
        metrics_out.extend(run.metrics)
    return run.result()


class AttributeMetricsRun:
    """The attribute-metrics mode behind the scheduler-facing
    `CollectionRun` interface (drivers/service.py): a single
    weight-checked aggregation round at the last level, exposed as a
    one-step run so the epoch scheduler multiplexes it exactly like
    the multi-round heavy-hitters loop.

    Checkpoint contract: `to_bytes()` before the round records only
    that nothing ran (a resumed epoch re-runs the round — it is one
    deterministic dispatch over the replayed reports, so the rerun is
    bit-identical); after the round it records the final result, so a
    resumed finished epoch replays without touching the device.
    """

    def __init__(self, mastic: Mastic, ctx: bytes,
                 attributes: Sequence[str], reports: list,
                 verify_key: Optional[bytes] = None,
                 chunk_size: Optional[int] = None, mesh=None):
        if verify_key is None:
            verify_key = gen_rand(mastic.VERIFY_KEY_SIZE)
        if chunk_size is not None and chunk_size < 1:
            raise ValueError(
                f"chunk_size must be >= 1, got {chunk_size}")
        prefixes = tuple(hash_attribute(mastic, a) for a in attributes)
        if len(set(prefixes)) != len(prefixes):
            raise ValueError("attribute hash collision; increase BITS")
        self.mastic = mastic
        self.ctx = ctx
        self.attributes = list(attributes)
        self.reports = reports
        self.verify_key = verify_key
        self.chunk_size = chunk_size
        self.mesh = mesh
        self.prefixes = prefixes
        self.metrics: list = []
        self.obs_tenant = ""  # telemetry label (set by the service)
        self.done = False
        self._result: Optional[list] = None

    def step(self) -> bool:
        """Run the single aggregation round.  Returns False (no more
        rounds) — matching the step() contract of HeavyHittersRun.
        The round runs inside a "round" trace span and feeds the same
        registry series HeavyHittersRun.step does (obs/devtime), so
        the two run kinds are diffable in one trace.

        ISSUE 10: `step()` is the `step_begin` / `step_finish` pair
        run back to back; the overlapped epoch executor splits them so
        this round's device work computes while another tenant
        stages."""
        handle = self.step_begin()
        if handle is None:
            return False
        return self.step_finish(handle)

    def step_begin(self):
        """Dispatch the single round without blocking (resident path;
        the round program rides the AOT artifact tier via
        heavy_hitters.root_round_program) or run it outright (chunked
        / mesh path — ``atomic`` in the handle).  None when the run
        already finished (a resumed completed epoch)."""
        if self.done:
            return None
        from .heavy_hitters import run_round_stage

        m = self.mastic
        bm = BatchedMastic(m)
        level = m.vidpf.BITS - 1
        agg_param = (level, self.prefixes, True)
        assert m.is_valid(agg_param, [])
        chunk_size = self.chunk_size
        if chunk_size is None and self.mesh is not None:
            # The mesh path needs the padded+masked chunk machinery
            # for uneven report counts — stream as one chunk.
            chunk_size = len(self.reports)
        profile_dir = devtime.take_profile_dir()
        prof = None
        if profile_dir:
            import jax

            prof = jax.profiler.trace(profile_dir)
        tracer = obs_trace.get_tracer()
        span = tracer.start_detached_span(
            "round", tenant=self.obs_tenant, round=0,
            level=level, frontier_width=len(self.prefixes),
            reports=len(self.reports), profiled=bool(profile_dir))
        handle = {"bm": bm, "agg_param": agg_param, "span": span,
                  "prof": prof, "t0": time.perf_counter(),
                  "atomic": True, "rh": None, "result": None}
        if prof is not None:
            prof.__enter__()
        try:
            with tracer.use_parent(span):
                if chunk_size is None:
                    batch = bm.marshal_reports(self.reports)
                    handle["rh"] = run_round_stage(
                        bm, self.verify_key, self.ctx, agg_param,
                        batch)
                    handle["atomic"] = False
                else:
                    handle["result"] = _run_round_chunked(
                        bm, self.verify_key, self.ctx, agg_param,
                        self.reports, chunk_size, self.metrics,
                        mesh=self.mesh)
        except BaseException as exc:
            self._step_cleanup(handle, error=exc)
            raise
        return handle

    def step_finish(self, handle) -> bool:
        """Collect the staged round (the blocking sync lives here for
        a split handle), stamp metrics, finalize the result.  Always
        returns False — there is exactly one round."""
        from .heavy_hitters import run_round_collect

        tracer = obs_trace.get_tracer()
        try:
            if not handle["atomic"]:
                with tracer.use_parent(handle["span"]):
                    handle["result"] = run_round_collect(
                        handle["bm"], self.verify_key, self.ctx,
                        handle["agg_param"], handle["rh"],
                        reports=self.reports,
                        metrics_out=self.metrics)
        except BaseException as exc:
            self._step_cleanup(handle, error=exc)
            raise
        self._step_cleanup(handle)
        if self.metrics:
            self.metrics[-1].extra["round_wall_ms"] = round(
                (time.perf_counter() - handle["t0"]) * 1e3, 2)
            self.metrics[-1].validate_extra()
            devtime.observe_round(self.metrics[-1],
                                  tenant=self.obs_tenant)
        self._result = list(zip(self.attributes, handle["result"]))
        self.done = True
        return False

    def _step_cleanup(self, handle, error=None) -> None:
        prof = handle.pop("prof", None)
        if prof is not None:
            prof.__exit__(None, None, None)
        span = handle.pop("span", None)
        if span is not None:
            if error is not None:
                span.set_default("error", type(error).__name__)
            obs_trace.get_tracer().end_span(span)

    def result(self) -> list:
        return self._result

    def frontier(self) -> list:
        """Truncated output: the full result once the one round ran,
        nothing before (no partial claims exist for a single-round
        mode)."""
        return list(self._result) if self.done else []

    def rounds_completed(self) -> int:
        return 1 if self.done else 0

    # -- checkpoint / resume (service snapshot hooks) --------------

    def to_bytes(self) -> bytes:
        return json.dumps({
            "done": self.done,
            "result": (None if self._result is None
                       else [[a, v] for (a, v) in self._result]),
        }).encode()

    @classmethod
    def from_bytes(cls, mastic: Mastic, ctx: bytes,
                   attributes: Sequence[str], reports: list,
                   verify_key: bytes, data: bytes,
                   chunk_size: Optional[int] = None,
                   mesh=None) -> "AttributeMetricsRun":
        run = cls(mastic, ctx, attributes, reports,
                  verify_key=verify_key, chunk_size=chunk_size,
                  mesh=mesh)
        state = json.loads(data)
        if state["done"]:
            run.done = True
            run._result = [(a, v) for (a, v) in state["result"]]
        return run


def _round_fn_masked(bm: BatchedMastic, ctx: bytes, agg_param, mesh):
    """The mesh twin of heavy_hitters._round_fn: a from-root round
    program over a shard-padded batch with an explicit validity mask
    (padded duplicate lanes must not reach the aggregate — the mask
    folds into the aggregation the way the chunked runner's `valid`
    does).  Jitted once per (ctx, agg_param, mesh shape); outputs pin
    the aggregates replicated (the psum) and the verdict masks
    report-sharded."""
    import jax

    from jax.sharding import NamedSharding, PartitionSpec as P

    cache = getattr(bm, "_round_masked_cache", None)
    if cache is None:
        cache = {}
        bm._round_masked_cache = cache
    key = (ctx, agg_param, mesh.shape["reports"])
    fn = cache.get(key)
    if fn is None:
        (_level, _prefixes, do_weight_check) = agg_param

        def body(vk, batch, valid):
            (p0, p1) = bm.prep_both(vk, ctx, agg_param, batch)
            checks = bm.accept_checks(p0, p1, do_weight_check)
            accept = checks["eval_proof"]
            for (name, mask) in checks.items():
                if name != "eval_proof":
                    accept = accept & mask
            ok = p0.ok & p1.ok
            agg0 = bm.aggregate(p0.out_share, accept & ok & valid)
            agg1 = bm.aggregate(p1.out_share, accept & ok & valid)
            return (agg0, agg1, accept, ok, checks)

        repl = NamedSharding(mesh, P())
        rep = NamedSharding(mesh, P("reports"))
        fn = jax.jit(body,
                     out_shardings=(repl, repl, rep, rep, rep))
        cache[key] = fn
    return fn


def _run_round_chunked(bm: BatchedMastic, verify_key: bytes,
                       ctx: bytes, agg_param, reports: list,
                       chunk_size: int,
                       metrics_out: Optional[list],
                       mesh=None) -> list:
    """One from-root aggregation round streamed chunk by chunk
    (heavy_hitters.run_round semantics, accumulated aggregates), on
    the pipelined executor: chunk i+1's scalar reports marshal (the
    host-heavy step) and its round dispatches while chunk i's device
    round computes and downloads — one blocking sync per chunk, the
    per-chunk phase timeline in `RoundMetrics.extra["pipeline"]`.
    Bit-identical to the serial loop (same programs, same fold
    order); `MASTIC_PIPELINE=0` restores strict serial execution."""
    import time

    import jax
    import numpy as np

    from ..common import vec_add
    from ..backend.schedule import LevelSchedule
    from .heavy_hitters import (_artifacts_delta, _vk_array,
                                finalize_round, root_program_cache,
                                root_round_program)
    from .pipeline import (overlap_efficiency, paused_gc,
                           pipeline_enabled, run_chunks)

    (level, prefixes, do_weight_check) = agg_param
    num = len(reports)
    rows = len(prefixes) * (1 + bm.m.flp.OUTPUT_LEN)
    agg_shares = [[bm.m.field(0)] * rows for _ in range(2)]
    accept_all = np.zeros(num, bool)
    ok_all = np.ones(num, bool)
    eval_ok = np.zeros(num, bool)
    wc_ok: Optional[np.ndarray] = None
    jr_ok: Optional[np.ndarray] = None
    bounds = [(lo, min(lo + chunk_size, num))
              for lo in range(0, num, chunk_size)]
    vk_arr = _vk_array(verify_key)
    shards = mesh.shape["reports"] if mesh is not None else 1
    if mesh is not None:
        from ..parallel.mesh import place_replicated, place_reports
        vk_arr = place_replicated(mesh, vk_arr)
    # The chunk programs ride the AOT cache/artifact tier
    # (heavy_hitters.root_round_program, ISSUE 10): full chunks share
    # one key, the ragged tail another — with a baked store neither
    # traces.
    prog_cache = root_program_cache(bm)
    stats_mark = dict(prog_cache.stats)
    psum_bytes: list = [0]
    shard_skews: list = []

    def stage(i: int):
        (lo, hi) = bounds[i]
        t0 = time.perf_counter()
        if mesh is not None:
            # Pad the chunk's report list to the shard multiple (first
            # report repeated) and mask: jax refuses uneven placement,
            # and the masked aggregate excludes the duplicate lanes —
            # bit-identical to the unpadded single-device sum.
            rows = -(-(hi - lo) // shards) * shards
            chunk = list(reports[lo:hi])
            chunk += [reports[lo]] * (rows - len(chunk))
            batch = bm.marshal_reports(chunk)
            valid = np.zeros(rows, bool)
            valid[:hi - lo] = True
            (batch, valid_dev) = place_reports(
                mesh, (batch, jax.numpy.asarray(valid)))
            t_up = time.perf_counter()
            args = (vk_arr, batch, valid_dev)
        else:
            batch = bm.marshal_reports(reports[lo:hi])
            t_up = time.perf_counter()
            args = (vk_arr, batch)
        before_inline = prog_cache.stats["inline_compiles"]
        (prog, wait_s) = root_round_program(bm, ctx, agg_param, args,
                                            mesh=mesh)
        # The compile field carries INLINE XLA waits only — artifact
        # loads are attributed in extra["artifacts"].load_ms, so a
        # warm-store round keeps the zero-compile claim measurable.
        compiled_inline = \
            prog_cache.stats["inline_compiles"] > before_inline
        out = prog(*args)
        t_d = time.perf_counter()
        phases = {
            "upload_ms": round((t_up - t0) * 1e3, 3),
            "compile_ms": round(wait_s * 1e3, 3) if compiled_inline
            else 0.0,
            "dispatch_ms": round((t_d - t_up - wait_s) * 1e3, 3),
        }
        return (out, phases)

    def collect(i: int, handle) -> dict:
        nonlocal wc_ok, jr_ok
        (agg0, agg1, accept, ok, checks) = handle
        (lo, hi) = bounds[i]
        t0 = time.perf_counter()
        if mesh is not None and shards > 1:
            waits = []
            for sh in accept.addressable_shards:
                sh.data.block_until_ready()
                waits.append((time.perf_counter() - t0) * 1e3)
            shard_skews.append(round(max(waits) - min(waits), 3))
            psum_bytes[0] += agg0.nbytes + agg1.nbytes
        jax.block_until_ready((agg0, agg1, accept, ok, checks))
        t_wait = time.perf_counter()
        ok_all[lo:hi] = np.asarray(ok)[:hi - lo]
        accept_all[lo:hi] = np.asarray(accept)[:hi - lo]
        eval_ok[lo:hi] = np.asarray(checks["eval_proof"])[:hi - lo]
        if "weight_check" in checks:
            if wc_ok is None:
                wc_ok = np.zeros(num, bool)
            wc_ok[lo:hi] = np.asarray(checks["weight_check"])[:hi - lo]
        if "joint_rand" in checks:
            if jr_ok is None:
                jr_ok = np.zeros(num, bool)
            jr_ok[lo:hi] = np.asarray(checks["joint_rand"])[:hi - lo]
        t_down = time.perf_counter()
        for (a, arr) in ((0, agg0), (1, agg1)):
            agg_shares[a] = vec_add(agg_shares[a],
                                    bm.agg_share_to_host(arr))
        t_host = time.perf_counter()
        return {
            "compute_wait_ms": round((t_wait - t0) * 1e3, 3),
            "download_ms": round((t_down - t_wait) * 1e3, 3),
            "host_ms": round((t_host - t_down) * 1e3, 3),
        }

    pipelined = pipeline_enabled() and len(bounds) > 1
    with paused_gc():
        # GC paused for the chunk loop's traces (pipeline.paused_gc).
        (timeline, wall_ms) = run_chunks(len(bounds), stage, collect,
                                         pipelined=pipelined)
    for rec in timeline:
        (lo, hi) = bounds[rec["chunk"]]
        rec["reports"] = hi - lo
        # The unified chunk schema (obs/schema.py): every producer
        # stamps wall_ms — serial and pipelined rounds alike (the
        # key-set inconsistency ISSUE 7 closes).
        rec["wall_ms"] = round(
            max(rec["collect_end_ms"] - rec["stage_start_ms"], 0.0),
            2)

    sched = LevelSchedule(prefixes, level, bm.m.vidpf.BITS)
    checks = {"eval_proof": eval_ok}
    if wc_ok is not None:
        checks["weight_check"] = wc_ok
    if jr_ok is not None:
        checks["joint_rand"] = jr_ok
    extra = {"chunk_size": chunk_size,
             "chunks": timeline,
             "artifacts": _artifacts_delta(prog_cache, stats_mark),
             "pipeline": {
                 "mode": "pipelined" if pipelined else "serial",
                 "fallback": (None if pipelined else
                              ("single-chunk" if len(bounds) < 2
                               else "lever-off")),
                 "round_wall_ms": round(wall_ms, 2),
                 "overlap_efficiency": overlap_efficiency(
                     timeline, wall_ms),
                 "host_syncs": sum(rec["host_syncs"]
                                   for rec in timeline),
             }}
    if mesh is not None:
        skews = sorted(shard_skews)
        extra["mesh"] = {
            "report_shards": shards,
            "psum_bytes_per_round": psum_bytes[0],
            "shard_wait_skew_ms_p50":
                (skews[len(skews) // 2] if skews else 0.0),
            "shard_wait_skew_ms_max": (skews[-1] if skews else 0.0),
        }
    return finalize_round(
        bm, verify_key, ctx, agg_param, reports, ok_all, accept_all,
        checks, agg_shares, padded_width=sched.total_nodes,
        nodes_evaluated=sched.total_nodes, metrics_out=metrics_out,
        extra=extra)
