"""RL001: the configure step between acquisition and return can
raise, and nothing closes the socket on that path."""
import socket


def dial(host, port):
    sock = socket.create_connection((host, port))
    sock.settimeout(5.0)
    return sock
