#!/usr/bin/env python3
"""Minimal dependency-free lint gate (pyflakes is not in this image).

Checks, over mastic_tpu/, tests/, tools/ and the repo-root scripts:

1. every file parses (syntax);
2. unused imports (name imported but never referenced);
3. public functions/methods in the scalar protocol layer carry full
   type annotations (the local stand-in for the reference's strict
   mypy gate, /root/reference/.github/workflows/test.yml:36-44 —
   mypy.ini is shipped for environments that have mypy);
4. no `print(` in library code (drivers return data; observability is
   the metrics dict);
5. every annotation in the ANNOTATED layer resolves at runtime
   (typing.get_type_hints over each public function, class and
   method — undefined or misspelled type names fail here even
   without mypy; mypy itself remains uninstallable in this image).

Exit status 0 iff clean.  Run via `make lint` / `make ci`.
"""

import ast
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

# Scalar-layer modules held to the annotation standard (the batched
# JAX layer's shapes/dtypes are documented in docstrings instead).
ANNOTATED = [
    "mastic_tpu/common.py", "mastic_tpu/dst.py", "mastic_tpu/field.py",
    "mastic_tpu/xof.py", "mastic_tpu/aes.py", "mastic_tpu/keccak.py",
    "mastic_tpu/vidpf.py", "mastic_tpu/mastic.py", "mastic_tpu/vdaf.py",
    "mastic_tpu/oracle.py", "mastic_tpu/flp/flp.py",
    "mastic_tpu/flp/circuits.py", "mastic_tpu/testvec_codec.py",
]

PRINT_OK = ("tools/", "bench.py", "gen_test_vec.py", "tests/",
            "__graft_entry__.py", "demo")


class ImportTracker(ast.NodeVisitor):
    def __init__(self):
        self.imported: dict = {}
        self.used: set = set()

    def visit_Import(self, node):
        for alias in node.names:
            name = (alias.asname or alias.name).split(".")[0]
            self.imported.setdefault(name, node.lineno)

    def visit_ImportFrom(self, node):
        for alias in node.names:
            if alias.name == "*":
                continue
            name = alias.asname or alias.name
            self.imported.setdefault(name, node.lineno)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


def check_file(path: pathlib.Path) -> list:
    rel = str(path.relative_to(REPO))
    problems = []
    try:
        tree = ast.parse(path.read_text(), filename=rel)
    except SyntaxError as err:
        return [f"{rel}:{err.lineno}: syntax error: {err.msg}"]

    tracker = ImportTracker()
    tracker.visit(tree)
    # Names used only inside docstring type references don't count;
    # __all__ re-exports do.
    exported = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id == "__all__":
                    if isinstance(node.value, (ast.List, ast.Tuple)):
                        for elt in node.value.elts:
                            if isinstance(elt, ast.Constant):
                                exported.add(elt.value)
    if not rel.endswith("__init__.py"):
        for (name, lineno) in sorted(tracker.imported.items(),
                                     key=lambda kv: kv[1]):
            if name not in tracker.used and name not in exported:
                problems.append(f"{rel}:{lineno}: unused import "
                                f"'{name}'")

    if rel in ANNOTATED:
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name.startswith("_"):
                continue
            args = node.args
            all_args = args.posonlyargs + args.args + args.kwonlyargs
            missing = [a.arg for a in all_args
                       if a.annotation is None
                       and a.arg not in ("self", "cls")]
            if missing:
                problems.append(
                    f"{rel}:{node.lineno}: public function "
                    f"'{node.name}' missing annotations: {missing}")
            if node.returns is None and node.name != "__init__":
                problems.append(
                    f"{rel}:{node.lineno}: public function "
                    f"'{node.name}' missing return annotation")

    if not any(rel.startswith(ok) or ok in rel for ok in PRINT_OK):
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                    and not _prints_to_stderr(node)):
                problems.append(f"{rel}:{node.lineno}: print() to "
                                "stdout in library code")
    return problems


def _prints_to_stderr(node: ast.Call) -> bool:
    """Diagnostics on stderr are fine; stdout pollution is the smell."""
    for kw in node.keywords:
        if kw.arg == "file" and isinstance(kw.value, ast.Attribute) \
                and kw.value.attr == "stderr":
            return True
    return False


def check_annotations_resolve() -> list:
    """Check 5: every annotation in the ANNOTATED layer resolves at
    runtime.  get_type_hints evaluates the annotation expressions
    against the module globals, so a typo'd or un-imported type name
    raises here — the executable subset of mypy's name resolution."""
    import importlib
    import inspect
    import typing

    problems = []
    sys.path.insert(0, str(REPO))
    for rel in ANNOTATED:
        mod_name = rel[:-3].replace("/", ".")
        try:
            mod = importlib.import_module(mod_name)
        except Exception as exc:
            problems.append(f"{rel}: module does not import: "
                            f"{type(exc).__name__}: {exc}")
            continue
        def unwrap(member):
            """classmethod/staticmethod descriptors and properties
            hide their function from inspect.isfunction — unwrap, or
            their annotations would silently escape the check."""
            if isinstance(member, (classmethod, staticmethod)):
                return member.__func__
            if isinstance(member, property):
                return member.fget
            return member

        targets = []
        for (name, obj) in vars(mod).items():
            if getattr(obj, "__module__", None) != mod_name:
                continue
            if inspect.isfunction(obj):
                targets.append((name, obj))
            elif inspect.isclass(obj):
                targets.append((name, obj))
                for (mname, member) in vars(obj).items():
                    member = unwrap(member)
                    if inspect.isfunction(member):
                        targets.append((f"{name}.{mname}", member))
        for (tname, target) in targets:
            try:
                typing.get_type_hints(target)
            except Exception as exc:
                problems.append(
                    f"{rel}: annotation on '{tname}' does not "
                    f"resolve: {type(exc).__name__}: {exc}")
    return problems


def main() -> int:
    roots = [REPO / "mastic_tpu", REPO / "tests", REPO / "tools"]
    files = [REPO / "bench.py", REPO / "__graft_entry__.py"]
    for root in roots:
        files += sorted(root.rglob("*.py"))
    problems = []
    for path in files:
        problems += check_file(path)
    problems += check_annotations_resolve()
    for problem in problems:
        print(problem)
    print(f"lint: {len(files)} files, {len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
