"""Whole-round AOT artifact store: trace-free cold start (ROADMAP
item 4, the compiler-first refactor).

Steady-state rounds pay zero inline compile since r9, but every
*process* still pays the full trace+XLA bill before its first round
(`BENCH_LAST_GOOD.json`: 100.8 s on the incremental round) — exactly
the cold start the r11 collector service eats on restart or tenant
admission.  This module lowers the round-program family ahead of time
to serialized artifacts a fresh process loads in seconds:

* **what is stored** — every `ProgramCache` entry kind ("eval" /
  "agg" / "wc" / "rk" over rows × width × pow2 buckets × mesh shape),
  as two forms per entry: the `jax.export` StableHLO module (the
  portable, inspectable, versioned artifact) and the native compiled
  executable (`jax.experimental.serialize_executable` — the form that
  actually skips XLA).  Measured on this fabric: deserializing the
  StableHLO still pays ~95% of the inline XLA compile, while the
  native executable loads in ~1.5 s against a ~21 s compile — so the
  native form is the load path and the StableHLO rides along for
  portability (a version-skewed store can be recompiled from it
  offline without the original Python);

* **how loads are gated** — three gates, in order: (a) the manifest's
  SHA-256 digest of the blob file (a corrupted store is detected
  before any byte is unpickled — reason ``corrupt``), (b) the
  key/runtime match (the artifact key embeds the jax version +
  backend it was compiled under; a skewed runtime refuses with reason
  ``version-skew`` instead of loading an ABI-incompatible
  executable), and (c) a **bit-identity probe round** on first use:
  deterministic inputs are regenerated from the artifact's input
  signature and the loaded executable's output digest must equal the
  digest the freshly-traced reference produced at bake time.  PERF.md
  §7 proved the XLA persistent-cache *reload* can be silently wrong
  on this fabric (a reloaded round program that rejected every
  report) — the probe is the non-negotiable soundness gate, not an
  optimization.  Any gate failure falls back to inline tracing with
  the attributed reason in `mastic_artifact_loads_total{outcome=...}`;

* **who loads** — `drivers/pipeline.ProgramCache` grows an artifact
  tier below the in-process tier (`store=`): a cache miss consults
  the store before compiling, and the predictor's `warm` prefetches
  from disk before falling back to XLA.  Runners preload their
  shape family at construction (`ProgramCache.preload`), the
  collector service preloads every tenant's family at startup and on
  tenant admission (`CollectorService`), and `tools/bake.py`
  enumerates the pow2 bucket × growth-path × mesh-shape family for a
  config and writes the store offline.

The blob payload is a pickle (the executable serialization jax ships
is pickle-based); the digest gate runs BEFORE any unpickling, so the
trust boundary is filesystem permissions on the store directory —
the same boundary as the service snapshot.  The store is a local
directory, `MASTIC_ARTIFACT_DIR` / `--artifact-dir` select it.
"""

import hashlib
import json
import os
import pickle
import time
from typing import Callable, Optional, Sequence

import numpy as np

from ..obs import trace as obs_trace
from ..obs.registry import get_registry
from . import wal as wal_mod

ARTIFACT_VERSION = 1
MANIFEST_NAME = "manifest.json"

# Load outcomes (the mastic_artifact_loads_total label values).
HIT = "hit"
MISS = "miss"
PROBE_FAIL = "probe_fail"
VERSION_SKEW = "version_skew"
CORRUPT = "corrupt"

_PROBE_SEED = 0x6D617374  # "mast"; shared by bake and load sides

_runtime_tag: Optional[str] = None


def runtime_tag() -> str:
    """The runtime a compiled executable is only valid under:
    ``jax-<version>-<backend>``.  Part of every program-cache and
    artifact key, so a program compiled under a different jax build
    or backend can never be served — in process or from disk."""
    global _runtime_tag
    if _runtime_tag is None:
        import jax

        _runtime_tag = f"jax-{jax.__version__}-{jax.default_backend()}"
    return _runtime_tag


def check_key_runtime(key: tuple) -> None:
    """Refuse a program-cache key stamped for a different runtime.
    An in-process cache trivially matches; the gate exists for
    restored / cross-process key material, where serving a stale
    executable would be the PERF.md §7 failure mode with no probe in
    front of it."""
    tag = runtime_tag()
    for el in key:
        if isinstance(el, str) and el.startswith("jax-") and el != tag:
            raise RuntimeError(
                f"program key {key!r} was compiled under {el}, this "
                f"process runs {tag} — refusing to serve it (rebake "
                f"the artifact store for this runtime)")


def family_id(bm, ctx: bytes) -> str:
    """Digest binding a program family to the VDAF instantiation and
    collection context that are BAKED into the traced programs (the
    verify key is traced data; everything here is compile-time
    constant): algorithm ID, tree depth, payload/proof geometry,
    field, and the ctx bytes the domain-separation tags close over."""
    m = bm.m
    desc = [int(m.ID), int(m.vidpf.BITS), int(m.vidpf.VALUE_LEN),
            int(bm.spec.num_limbs), m.field.__name__,
            int(m.flp.PROOF_LEN), int(m.flp.OUTPUT_LEN),
            int(m.flp.JOINT_RAND_LEN), ctx.hex()]
    return hashlib.sha256(json.dumps(desc).encode()).hexdigest()[:16]


def _canon_key(key: Sequence) -> list:
    out = []
    for el in key:
        if isinstance(el, (bool, np.bool_)):
            out.append(bool(el))
        elif isinstance(el, (int, np.integer)):
            out.append(int(el))
        elif isinstance(el, str):
            out.append(el)
        else:
            raise TypeError(f"artifact key element {el!r} is not "
                            f"int/str")
    return out


def key_name(key: Sequence) -> str:
    """Content-addressed entry name for a program key."""
    canon = json.dumps(_canon_key(key))
    return hashlib.sha256(canon.encode()).hexdigest()[:24]


# -- deterministic probe inputs ---------------------------------------

def _gen_like(aval, rng: np.random.Generator) -> np.ndarray:
    """A deterministic array for one input aval.  Values only need to
    be deterministic, not meaningful: the probe compares the loaded
    executable's outputs against the freshly-traced reference's on
    the SAME inputs, and every op in the round programs is
    deterministic integer/boolean math (gather clamping included)."""
    dt = np.dtype(aval.dtype)
    if dt == np.bool_:
        return rng.integers(0, 2, aval.shape).astype(bool)
    if dt.kind in ("u", "i"):
        # Small positives: valid for index arrays (gathers stay in
        # range for any realistic dim) and exercise real carries in
        # the limb arithmetic.
        return rng.integers(0, 8, aval.shape).astype(dt)
    return rng.random(aval.shape).astype(dt)


def probe_inputs(executable, seed: int = _PROBE_SEED):
    """Regenerate the deterministic probe inputs for an executable
    from its own input signature, placed with its own input
    shardings (mesh executables need their inputs committed to the
    right devices before the call)."""
    import jax

    (arg_avals, kw_avals) = executable.in_avals
    rng = np.random.default_rng(seed)
    flat_avals = jax.tree_util.tree_leaves((arg_avals, kw_avals))
    flat = [_gen_like(a, rng) for a in flat_avals]
    (shardings, kw_sh) = executable.input_shardings
    # Shardings are pytree leaves, so a plain flatten pairs one
    # sharding per flattened input array.
    flat_sh = jax.tree_util.tree_leaves((shardings, kw_sh))
    if len(flat_sh) == len(flat):
        # placement comes from the loaded executable's own input
        # shardings, so mesh programs probe with mesh-correct inputs
        flat = [jax.device_put(x, s)  # mastic-allow: RB003 — the
                # sharding IS the executable's recorded input
                # placement, not a report upload path
                for (x, s) in zip(flat, flat_sh)]
    treedef = jax.tree_util.tree_structure((arg_avals, kw_avals))
    return jax.tree_util.tree_unflatten(treedef, flat)


def probe_digest(executable, seed: int = _PROBE_SEED) -> str:
    """SHA-256 over the executable's outputs on the deterministic
    probe inputs — computed identically at bake time (on the freshly
    traced program) and at load time (on the deserialized one); the
    two must be bit-equal or the reload is unsound."""
    import jax

    (args, kwargs) = probe_inputs(executable, seed)
    out = executable(*args, **kwargs)
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(out):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


# -- jax.export (the portable StableHLO form) -------------------------

_export_registered = False


def _register_export_types() -> None:
    """jax.export needs every custom pytree namedtuple registered
    once per process before an Exported can be serialized."""
    global _export_registered
    if _export_registered:
        return
    import jax.export as jax_export

    from ..backend.incremental import Carry, IncrementalRound
    from ..backend.mastic_jax import BatchedPrep, ReportBatch
    from ..backend.vidpf_jax import BatchedCorrectionWords, EvalState

    for t in (Carry, IncrementalRound, BatchedCorrectionWords,
              EvalState, ReportBatch, BatchedPrep):
        try:
            jax_export.register_namedtuple_serialization(
                t, serialized_name=f"mastic_tpu.{t.__name__}")
        except ValueError:  # mastic-allow: RB002 — already registered
            # by an earlier store in this process; idempotent by design
            pass
    _export_registered = True


def export_stablehlo(jit_fn, structs) -> Optional[bytes]:
    """The `jax.export` serialized StableHLO module for a jitted
    function at an abstract signature — the portable artifact form.
    Returns None when export is impossible (e.g. donation the
    exporter refuses): the native executable is the load path either
    way, so a missing StableHLO degrades portability, not function."""
    import zlib

    import jax.export as jax_export

    _register_export_types()
    try:
        exported = jax_export.export(jit_fn)(*structs)
        return zlib.compress(exported.serialize())
    except Exception:
        return None


# -- the store --------------------------------------------------------

class ArtifactStore:
    """A directory of digest-sealed compiled round programs.

    Layout: ``manifest.json`` plus one blob file per entry under
    ``blobs/`` (native executable pickle) and optionally ``hlo/``
    (compressed `jax.export` StableHLO).  Loaded-and-probed
    executables are memoized in memory, so per-epoch runner
    construction after a service preload is free.  Single-threaded by
    design, like the scheduler that owns it (drivers/service.py):
    bake tools, runners and the collector service all touch the
    store from the one scheduler/driver thread — the status-server
    thread never does."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self._loaded: dict = {}     # name -> probed compiled
        self._failed: dict = {}     # name -> outcome (negative memo)
        self.manifest = self._read_manifest()

    def _read_manifest(self) -> dict:
        try:
            with open(os.path.join(self.path, MANIFEST_NAME)) as fh:
                man = json.load(fh)
        except (OSError, ValueError):
            return {"version": ARTIFACT_VERSION,
                    "runtime": runtime_tag(), "entries": {}}
        if not isinstance(man.get("entries"), dict):
            man["entries"] = {}
        return man

    def _write_manifest(self) -> None:
        # Crash-safe manifest (ISSUE 18 / RB006): tmp → fsync(file) →
        # atomic rename → fsync(dir), so a power cut never leaves a
        # half-written manifest NOR a rename whose bytes are still in
        # the page cache.
        os.makedirs(self.path, exist_ok=True)
        tmp = os.path.join(self.path, MANIFEST_NAME + ".tmp")
        with open(tmp, "w") as fh:
            json.dump(self.manifest, fh, indent=1, sort_keys=True)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, os.path.join(self.path, MANIFEST_NAME))
        wal_mod.fsync_dir(self.path)

    def keys(self) -> list:
        """Every manifest entry's program key, as tuples."""
        return [tuple(e["key"])
                for e in self.manifest["entries"].values()]

    def has(self, key) -> bool:
        return key_name(key) in self.manifest["entries"]

    def entry_count(self) -> int:
        return len(self.manifest["entries"])

    def store_bytes(self) -> int:
        return sum(int(e.get("bytes", 0))
                   for e in self.manifest["entries"].values())

    # -- save (bake side) ------------------------------------------

    def save(self, key, compiled,
             stablehlo: Optional[bytes] = None) -> dict:
        """Seal one freshly-compiled executable into the store: the
        native serialized form behind a SHA-256 digest, the probe
        output digest of THIS (traced, never pickled) executable as
        the load-time bit-identity reference, and optionally the
        `jax.export` StableHLO module."""
        from jax.experimental import serialize_executable as se

        donated = tuple(getattr(compiled, "donate_argnums", ()) or ())
        if donated:
            raise ValueError(
                f"refusing to seal an executable with donated "
                f"arguments {donated}: input-output aliasing "
                f"DOUBLE-FREES its buffers when the executable is "
                f"deserialized on this fabric (heap corruption, "
                f"allocator-state dependent, invisible to the output "
                f"probe — PERF.md §11).  Bake via "
                f"artifacts.make_baker, which lowers donation-free")
        payload = pickle.dumps(se.serialize(compiled))
        digest = hashlib.sha256(payload).hexdigest()
        probe = probe_digest(compiled)
        name = key_name(key)
        try:
            devices = len(compiled.input_shardings[0][0].device_set)
        except Exception:
            devices = 1
        entry = {
            "key": _canon_key(key),
            "blob": f"blobs/{name}.pkl",
            "sha256": digest,
            "probe_digest": probe,
            "probe_seed": _PROBE_SEED,
            "devices": devices,
            "bytes": len(payload),
            "stablehlo": (f"hlo/{name}.stablehlo.zz"
                          if stablehlo else None),
        }
        os.makedirs(os.path.join(self.path, "blobs"), exist_ok=True)
        # The blob must be durable BEFORE the manifest names it
        # (ISSUE 18): a manifest entry pointing at unsynced bytes
        # would fail its sha256 gate on the next load after a crash.
        blob_path = os.path.join(self.path, entry["blob"])
        with open(blob_path, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        wal_mod.fsync_dir(os.path.dirname(blob_path))
        if stablehlo:
            os.makedirs(os.path.join(self.path, "hlo"), exist_ok=True)
            with open(os.path.join(self.path, entry["stablehlo"]),
                      "wb") as f:
                f.write(stablehlo)
                f.flush()
                os.fsync(f.fileno())
        self.manifest["version"] = ARTIFACT_VERSION
        self.manifest["runtime"] = runtime_tag()
        self.manifest["entries"][name] = entry
        self._write_manifest()
        # The saved executable IS the freshly-traced one: memoize it
        # so a run in the baking process serves the traced object,
        # never a reload of it.
        self._loaded[name] = compiled
        return entry

    # -- load (serve side) -----------------------------------------

    def _gated_load(self, name: str, entry: dict):
        """(compiled | None, outcome) through the three gates; no
        memoization, no counting — `load` owns those."""
        import jax
        from jax.experimental import serialize_executable as se

        if self.manifest.get("version") != ARTIFACT_VERSION \
                or self.manifest.get("runtime") != runtime_tag():
            return (None, VERSION_SKEW)
        if int(entry.get("devices", 1)) > len(jax.devices()):
            return (None, VERSION_SKEW)
        try:
            with open(os.path.join(self.path, entry["blob"]),
                      "rb") as f:
                payload = f.read()
        except OSError:
            return (None, CORRUPT)
        # Gate (a): digest BEFORE any unpickling.
        if hashlib.sha256(payload).hexdigest() != entry["sha256"]:
            return (None, CORRUPT)
        try:
            loaded = se.deserialize_and_load(*pickle.loads(payload))
        except Exception:
            return (None, CORRUPT)
        # Gate (c): the bit-identity probe round — the loaded
        # executable must reproduce the traced reference's outputs on
        # the deterministic probe inputs (PERF.md §7: a reload can be
        # silently wrong; this is the soundness gate).
        try:
            dig = probe_digest(loaded,
                               int(entry.get("probe_seed",
                                             _PROBE_SEED)))
        except Exception:
            return (None, PROBE_FAIL)
        if dig != entry["probe_digest"]:
            return (None, PROBE_FAIL)
        return (loaded, HIT)

    def load(self, key):
        """The gated load: returns the probed executable or None (the
        caller compiles inline).  Every call lands one observation in
        `mastic_artifact_loads_total{outcome=...}` and one
        ``artifact.load`` span with the store path + key attrs."""
        name = key_name(key)
        tracer = obs_trace.get_tracer()
        # the key's family component is a SHA-256 digest of the
        # public instantiation record + protocol ctx (wire-public);
        # no key or seed material reaches the span
        with tracer.span(  # mastic-allow: SF003 — key carries only
                # a digest of public instantiation+ctx, no secrets
                "artifact.load", store=self.path,
                key="/".join(str(k) for k in key)) as span:
            if name in self._loaded:
                outcome = HIT
                prog = self._loaded[name]
            elif name in self._failed:
                outcome = self._failed[name]
                prog = None
            else:
                entry = self.manifest["entries"].get(name)
                if entry is None:
                    (prog, outcome) = (None, MISS)
                else:
                    (prog, outcome) = self._gated_load(name, entry)
                    if prog is not None:
                        self._loaded[name] = prog
                    elif outcome != MISS:
                        self._failed[name] = outcome
            span.set(outcome=outcome)
        get_registry().counter("mastic_artifact_loads_total",
                               outcome=outcome).inc()
        return prog

    def preload(self, match: Optional[Callable] = None) -> dict:
        """Load (and probe) every manifest entry whose key passes
        `match` — service startup / tenant admission / runner
        construction call this so round paths never pay the disk
        latency inline.  Returns outcome counts."""
        counts: dict = {}
        for key in self.keys():
            if match is not None and not match(key):
                continue
            outcome = (HIT if self.load(key) is not None
                       else self._failed.get(key_name(key), MISS))
            counts[outcome] = counts.get(outcome, 0) + 1
        return counts


# -- process-wide store registry --------------------------------------

_stores: dict = {}


def default_store(path: str) -> ArtifactStore:
    """One shared ArtifactStore per path: the in-memory loaded-
    executable memo must be process-wide, or every epoch's fresh
    runner would re-pay the disk load + probe.  Same single-thread
    ownership contract as the store itself."""
    path = os.path.abspath(path)
    store = _stores.get(path)
    if store is None:
        store = ArtifactStore(path)
        _stores[path] = store
    return store


def store_from_env() -> Optional[ArtifactStore]:
    """The `MASTIC_ARTIFACT_DIR` lever, read per call (a long-lived
    process can be pointed at a store without restarting)."""
    path = os.environ.get("MASTIC_ARTIFACT_DIR", "").strip()
    return default_store(path) if path else None


# -- family enumeration (bake side) -----------------------------------

def planted_paths(bits: int, k: int) -> list:
    """Deterministic planted hitter paths: path i carries i's binary
    digits little-endian, so k paths diverge at the root and the
    per-depth ancestor counts (which set every pow2 bucket) are a
    pure function of (bits, k).  `bench.py --cold-start` and
    `tools/bake.py` share this, so a bake reproduces the measured
    run's frontier trajectory exactly."""
    return [tuple(bool((i >> d) & 1) for d in range(bits))
            for i in range(k)]


def trajectory(bits: int, paths: list):
    """Yield (level, prefixes) of a planted-path heavy-hitters run at
    threshold 1: survivors at each level are exactly the ancestors of
    the planted paths (every report's alpha is a planted path, so any
    ancestor has a full count and any other child has zero) — the
    same rule `HeavyHittersRun.step` applies."""
    prefixes = [(False,), (True,)]
    for level in range(bits):
        yield (level, tuple(prefixes))
        survivors = [p for p in prefixes
                     if any(tuple(path[:level + 1]) == p
                            for path in paths)]
        if level < bits - 1:
            prefixes = [p + (b,) for p in survivors
                        for b in (False, True)]


def growth_trajectory(bits: int, max_frontier: int):
    """Yield (level, prefixes) of the threshold-prunes-nothing phase:
    every candidate survives, the frontier doubles per level until
    `max_frontier` — the early levels of any run, and the width-growth
    path (`_grow`) the predictor deliberately leaves to inline
    compilation unless baked here."""
    prefixes = [(False,), (True,)]
    for level in range(bits):
        if len(prefixes) > max_frontier:
            return
        yield (level, tuple(prefixes))
        if level < bits - 1:
            prefixes = [p + (b,) for p in prefixes
                        for b in (False, True)]


def make_baker(bm, ctx: bytes, width: int = 8, mesh=None):
    """A lowering-only RoundPrograms host: the same jitted closures
    and cache keys the runners use (one definition — a baked program
    IS the runner's program), with no reports attached."""
    from ..backend.incremental import IncrementalMastic
    from .heavy_hitters import RoundPrograms

    class _Baker(RoundPrograms):
        # Baked executables must NOT donate: input-output aliasing
        # double-frees on deserialization (heap corruption on this
        # jaxlib CPU — found by the artifacts-smoke gate, PERF.md
        # §11).  ArtifactStore.save enforces this structurally.
        _donate_carries = False

        def __init__(self):
            self.bm = bm
            self.verify_key = bytes(bm.m.VERIFY_KEY_SIZE)
            self.ctx = ctx
            self.mesh = mesh
            self.width = max(4, width)
            self.engine = IncrementalMastic(bm, self.width)
            self.layouts: list = []
            self._init_programs()

        def _grow(self, new_width: int) -> None:
            self.width = new_width
            self.engine = IncrementalMastic(self.bm, new_width)
            self._eval_fn = None
            self._combine_fn = None

    return _Baker()


def bake_attribute_round(baker, store: ArtifactStore, rows: int,
                         attributes: Sequence[str],
                         with_stablehlo: bool = True) -> dict:
    """Seal the attribute-metrics round program (ISSUE 10 satellite:
    the from-root round rides the same artifact tier as
    eval/agg/wc/rk).  The program bakes per (attribute set, rows,
    mesh shape): the hashed prefixes are compile-time constants of
    the traced round, so the key carries their digest
    (`heavy_hitters.root_program_key`) and the serving config must
    bake the exact attribute list it collects — a mismatch is a cache
    miss that compiles inline, attributed, never a wrong program."""
    import jax.numpy as jnp

    from .attribute_metrics import _round_fn_masked, hash_attribute
    from .heavy_hitters import _round_fn, root_program_key
    from .pipeline import paused_gc

    (bm, ctx, mesh) = (baker.bm, baker.ctx, baker.mesh)
    m = bm.m
    prefixes = tuple(hash_attribute(m, a) for a in attributes)
    if len(set(prefixes)) != len(prefixes):
        raise ValueError("attribute hash collision; increase BITS")
    agg_param = (m.vidpf.BITS - 1, prefixes, True)
    (rep, repl) = baker._mesh_sh()
    vk = baker._sds((m.VERIFY_KEY_SIZE,), jnp.uint8, repl)
    batch = baker._batch_structs(rows)
    if mesh is not None:
        shards = mesh.shape["reports"]
        fn = _round_fn_masked(bm, ctx, agg_param, mesh)
        structs = (vk, batch, baker._sds((rows,), jnp.bool_, rep))
    else:
        shards = 0
        fn = _round_fn(bm, ctx, agg_param)
        structs = (vk, batch)
    key = root_program_key(bm, ctx, agg_param, rows, shards)
    stats = {"compiled": 0, "skipped": 0, "seconds": 0.0}
    if store.has(key):
        stats["skipped"] = 1
        return stats
    t0 = time.perf_counter()
    with paused_gc():
        compiled = fn.lower(*structs).compile()
    hlo = (export_stablehlo(fn, structs) if with_stablehlo else None)
    store.save(key, compiled, stablehlo=hlo)
    stats["compiled"] = 1
    stats["seconds"] = time.perf_counter() - t0
    return stats


def bake_trajectory(baker, store: ArtifactStore, rows: int,
                    levels, with_stablehlo: bool = True) -> dict:
    """Walk one frontier trajectory, compiling and sealing every
    program key the runners would need: the eval + agg pair per
    level's shape bucket, the weight-check program at level 0, and
    the AES round-key schedule once.  Keys already in the store (or
    compiled earlier this walk) are skipped, so overlapping
    trajectories cost nothing extra."""
    from .pipeline import paused_gc

    stats = {"compiled": 0, "skipped": 0, "seconds": 0.0}

    def seal(key, jit_fn, structs) -> None:
        if store.has(key):
            stats["skipped"] += 1
            return
        t0 = time.perf_counter()
        with paused_gc():
            compiled = jit_fn.lower(*structs).compile()
        hlo = (export_stablehlo(jit_fn, structs) if with_stablehlo
               else None)
        store.save(key, compiled, stablehlo=hlo)
        stats["compiled"] += 1
        stats["seconds"] += time.perf_counter() - t0

    rk_key = baker._rk_key(rows)
    seal(rk_key, baker._rk_jit(), baker._rk_structs(rows))
    out_len = 1 + baker.bm.m.flp.OUTPUT_LEN
    bits = baker.bm.m.vidpf.BITS
    for (level, prefixes) in levels:
        plan = baker._plan(prefixes, level)
        assert level == len(baker.layouts)
        baker.layouts.append(plan.layout_new)
        seal(baker._eval_key(rows, plan), baker._eval_jit(),
             baker._eval_structs(rows, plan))
        out_cols = len(plan.out_idx) * out_len
        seal(baker._agg_key(rows, out_cols), baker._combine_jit(),
             baker._agg_structs(rows, out_cols))
        if level == 0:
            seal(baker._wc_key(rows, 0), baker._wc_fn(0),
                 baker._wc_structs(rows))
        # The runtime predictor warms BOTH its candidate shapes per
        # round (steady one-child-per-parent + all-survive growth);
        # a candidate absent from the store falls back to an XLA
        # compile in the warm slot — measured at ~16 s per round on
        # the CPU fabric, dominating the warm cold start.  Bake the
        # candidate family too, so every runtime warm is a load.
        from .pipeline import predicted_next_plans

        for nplan in predicted_next_plans(plan.prefixes, level, bits,
                                          baker.width,
                                          list(baker.layouts)):
            seal(baker._eval_key(rows, nplan), baker._eval_jit(),
                 baker._eval_structs(rows, nplan))
            ncols = len(nplan.out_idx) * out_len
            seal(baker._agg_key(rows, ncols), baker._combine_jit(),
                 baker._agg_structs(rows, ncols))
        del plan  # plans hold per-level index arrays; keep bake lean
    return stats
