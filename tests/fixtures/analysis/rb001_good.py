"""Known-good: every blocking read is deadline-armed (RB001)."""

import socket


def serve(server: socket.socket) -> bytes:
    server.settimeout(30.0)
    (conn, _addr) = server.accept()
    conn.settimeout(30.0)
    return conn.recv(4)


def dial(port: int) -> socket.socket:
    return socket.create_connection(("127.0.0.1", port), timeout=30.0)
