"""Known-good: widths unified explicitly before the op (DT001)."""

import jax.numpy as jnp


def mix():
    bytes_ = jnp.zeros((4,), jnp.uint8)
    words = jnp.zeros((4,), jnp.uint32)
    return bytes_.astype(jnp.uint32) + words
