"""Known-bad: BlockSpec rank mismatch (PL001)."""

from jax.experimental import pallas as pl


def spec():
    return pl.BlockSpec((8, 128), lambda i: (0, 0, i))
