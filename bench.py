"""Benchmark: steady-state VIDPF evaluation throughput on one chip.

Prints ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The metric is the BASELINE.json north star — VIDPF node evaluations
per second per chip at 256-bit tree depth, where one node evaluation
is the full extend + correct + convert + node-proof pipeline of
/root/reference/poc/vidpf.py:281-325 (2 fixed-key-AES blocks + 2 AES
convert blocks + 1 TurboSHAKE-128 hash per node, reference op model in
BASELINE.md).  The reference publishes no timing numbers, so
vs_baseline compares against this repo's own scalar CPU reference
layer (the same byte-exact math the reference's Python PoC runs),
measured in-process.

Shapes mimic the heavy-hitters steady state: a pruned frontier of
constant width marching down a 256-level tree; each timed step is one
tree level over (reports x frontier) with a traced node binder so a
single compiled program serves every level.

Fail-open design: every phase (import / device / scalar baseline /
tiny sanity / compile / warmup / measure) stamps progress to stderr
and updates a shared partial-result record; the watchdog prints the
best measurement completed so far (tiny-shape rate if the full shape
never finished, scalar baseline if the chip never came up) instead of
a bare zero, with the failing phase named in "error".
"""

import argparse
import json
import os
import socket
import sys
import threading
import time

_T0 = time.time()

# Partial-result record, updated as phases complete; the watchdog and
# any exception handler print it so a hang/crash still yields data.
PARTIAL = {
    "metric": "vidpf_node_evals_per_sec_per_chip_256bit",
    "value": 0.0,
    "unit": "evals/s",
    "vs_baseline": 0.0,
    "phase": "start",
}


def stamp(phase: str, **info) -> None:
    """Progress line on stderr + phase update for the fail-open JSON."""
    PARTIAL["phase"] = phase
    extra = " ".join(f"{k}={v}" for (k, v) in info.items())
    print(f"[bench {time.time() - _T0:7.1f}s] {phase} {extra}".rstrip(),
          file=sys.stderr, flush=True)


def emit(error: str | None = None) -> None:
    out = dict(PARTIAL)
    phase = out.pop("phase")
    if error is not None:
        out["error"] = f"{error} (last phase: {phase})"
    print(json.dumps(out), flush=True)


def _watchdog(seconds: float):
    """Emit the partial result and hard-exit if any phase hangs (the
    remote-TPU tunnel can block indefinitely on attach)."""

    def fire():
        emit(error=f"watchdog timeout after {seconds:.0f}s")
        os._exit(2)

    timer = threading.Timer(seconds, fire)
    timer.daemon = True
    timer.start()
    return timer


def scalar_rate(bits: int = 256, level: int = 3) -> float:
    """Node evals/sec of the scalar byte-exact reference layer."""
    from mastic_tpu.field import Field64
    from mastic_tpu.vidpf import Vidpf

    vidpf = Vidpf(Field64, bits, 2)
    alpha = tuple(bool(i % 2) for i in range(bits))
    beta = [Field64(1), Field64(1)]
    nonce = bytes(16)
    rand = bytes(range(32))
    (cws, keys) = vidpf.gen(alpha, beta, b"bench", nonce, rand)
    prefixes = tuple(
        tuple(bool((v >> (level - i)) & 1) for i in range(level + 1))
        for v in range(2 ** (level + 1)))
    t0 = time.perf_counter()
    (_, tree) = vidpf.eval_level_synchronous(
        0, cws, keys[0], level, prefixes, b"bench", nonce)
    dt = time.perf_counter() - t0
    nodes = sum(len(lvl) for lvl in tree.levels)
    return nodes / dt


class SteadyState:
    """The compiled one-level step at a given (reports, frontier)."""

    def __init__(self, bm, reports: int, frontier: int, bits: int):
        import numpy as np
        import jax
        import jax.numpy as jnp

        from mastic_tpu.backend.vidpf_jax import EvalState

        vid = bm.vidpf
        ctx = b"bench"
        rng = np.random.default_rng(0)
        nonces = jnp.asarray(rng.integers(0, 256, (reports, 16),
                                          dtype=np.uint8))
        (ext_rk, conv_rk) = jax.jit(
            lambda n: vid.roundkeys(ctx, n))(nonces)
        jax.block_until_ready(ext_rk)

        self.cw = (
            jnp.asarray(rng.integers(0, 256, (reports, 16), np.uint8)),
            jnp.asarray(rng.integers(0, 2, (reports, 2)).astype(bool)),
            jnp.asarray(rng.integers(0, 1 << 16, (reports, 2, 4),
                                     dtype=np.uint32)),
            jnp.asarray(rng.integers(0, 256, (reports, 32), np.uint8)),
        )
        # Binder is traced data so one compile serves every level (at
        # depth >= 248 of a 256-bit tree the path encoding is 32 B).
        self.binder = jnp.asarray(rng.integers(
            0, 256, (2 * frontier, 36), dtype=np.uint8))
        keep = np.arange(0, 2 * frontier, 2)

        def step(seed, ctrl, binder):
            parents = EvalState(
                seed=seed, ctrl=ctrl,
                w=jnp.zeros((reports, frontier, vid.VALUE_LEN,
                             bm.spec.num_limbs), jnp.uint32),
                proof=jnp.zeros((reports, frontier, 32), jnp.uint8))
            (child, ok) = vid.eval_step(ext_rk, conv_rk, parents,
                                        self.cw, ctx, binder)
            # Prune back to the frontier width (threshold survivors).
            return (child.seed[:, keep], child.ctrl[:, keep],
                    child.proof, ok)

        self.seed = jnp.asarray(rng.integers(
            0, 256, (reports, frontier, 16), dtype=np.uint8))
        self.ctrl = jnp.asarray(rng.integers(
            0, 2, (reports, frontier)).astype(bool))
        self.step = jax.jit(step)
        self.jax = jax
        self.evals_per_step = reports * 2 * frontier

    def compile(self) -> float:
        t0 = time.perf_counter()
        compiled = self.step.lower(self.seed, self.ctrl,
                                   self.binder).compile()
        dt = time.perf_counter() - t0
        self.step = compiled
        return dt

    def run(self, steps: int) -> float:
        (seed, ctrl) = (self.seed, self.ctrl)
        t0 = time.perf_counter()
        for _ in range(steps):
            (seed, ctrl, _proof, _ok) = self.step(seed, ctrl, self.binder)
        self.jax.block_until_ready(seed)
        dt = time.perf_counter() - t0
        return self.evals_per_step * steps / dt


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--reports", type=int, default=4096)
    parser.add_argument("--frontier", type=int, default=64)
    parser.add_argument("--steps", type=int, default=16)
    parser.add_argument("--bits", type=int, default=256)
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (local sanity)")
    parser.add_argument("--watchdog", type=float, default=900.0)
    args = parser.parse_args()

    timer = _watchdog(args.watchdog)
    stamp("import-jax")
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    requested = os.environ.get("JAX_PLATFORMS", "").strip()
    if requested and "axon" not in requested.split(","):
        jax.config.update("jax_platforms", requested)
    # Persistent compile cache, keyed by host so a cache built on a
    # different machine type is never reused (XLA rejects mismatched
    # machine types with noisy warnings and, historically, SIGILL).
    cache = f"/tmp/mastic_tpu_jax_cache_{socket.gethostname()}"
    jax.config.update("jax_compilation_cache_dir", cache)

    stamp("scalar-baseline")
    base = scalar_rate(bits=args.bits)
    PARTIAL["scalar_evals_per_sec"] = round(base, 1)
    stamp("device-attach")
    devices = jax.devices()
    stamp("device-up", devices=devices)

    from mastic_tpu import MasticCount
    from mastic_tpu.backend.mastic_jax import BatchedMastic
    bm = BatchedMastic(MasticCount(args.bits))

    # Tiny-shape sanity: proves chip + kernels work before the big
    # compile; its rate is the fail-open fallback value.
    stamp("tiny-sanity-compile", reports=64, frontier=8)
    tiny = SteadyState(bm, 64, 8, args.bits)
    tiny_compile = tiny.compile()
    tiny_rate = tiny.run(4)
    PARTIAL["value"] = round(tiny_rate, 1)
    PARTIAL["vs_baseline"] = round(tiny_rate / base, 1)
    PARTIAL["note"] = "tiny-shape (64x8) fallback rate"
    stamp("tiny-sanity-done", rate=f"{tiny_rate:.0f}",
          compile_s=f"{tiny_compile:.1f}")

    stamp("full-compile", reports=args.reports, frontier=args.frontier)
    full = SteadyState(bm, args.reports, args.frontier, args.bits)
    compile_s = full.compile()
    stamp("warmup", compile_s=f"{compile_s:.1f}")
    full.run(2)
    stamp("measure")
    rate = full.run(args.steps)
    timer.cancel()

    PARTIAL.pop("note", None)
    PARTIAL["value"] = round(rate, 1)
    PARTIAL["vs_baseline"] = round(rate / base, 1)
    PARTIAL["compile_seconds"] = round(compile_s, 1)
    PARTIAL["reports"] = args.reports
    PARTIAL["frontier"] = args.frontier
    stamp("done", rate=f"{rate:.0f}")
    emit()


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # fail open: report what we had
        emit(error=f"{type(exc).__name__}: {exc}")
        raise
