"""Pipelined chunk-streaming execution (the `MASTIC_PIPELINE` lever).

The chunked production path (PERF.md §4-5) streams fixed-size report
chunks through one compiled round program.  Serially, each chunk pays
the full upload -> compute -> download -> host chain with blocking
`np.asarray` walls between every step, so the device idles during
host work and the host idles during device work — BENCH_r05's
`incremental_round` measured the production round at 211k evals/s on
a chip whose kernel runs at 43.4M evals/s, with 100.8 s of inline
XLA compile sitting on the critical path.  This module attacks both
gaps:

* **double-buffered chunk streaming** (`run_chunks`): chunk i+1's
  batch and carries upload and its round dispatches while chunk i
  computes and downloads, leaning on JAX async dispatch — the
  accept/ok/weight-check masks stay device arrays until one blocking
  sync per chunk, issued only after the next chunk's work is already
  in flight.  The per-chunk phase timeline (upload / dispatch /
  compute-wait / download / host) is recorded so overlap efficiency
  is a measured number in `RoundMetrics.extra`, not a claim;

* **ahead-of-time bucket compilation** (`ProgramCache` +
  `predicted_next_plans`): the round programs specialize on the
  power-of-two binder buckets and padded width of the live frontier
  (`backend/incremental.RoundPlan`), all host-predictable from the
  frontier trajectory — the predicted next `(bucket, width)`
  programs compile while the current round's dispatched device work
  is still executing (async dispatch keeps the device busy through
  the compile), moving the compile stalls off the critical path.
  This composes with the persistent `jax_compilation_cache_dir`
  (which only helps the *second* process): warming makes the *first*
  process's later rounds compile-free too.  (See ProgramCache for
  why warming is synchronous-overlapped rather than a compiler
  thread: concurrent tracing is unsound on this jax.)

Memory honesty lives in `drivers/chunked.py`: two chunks in flight
double the resident chunk state, so `memory_envelope` reports the
pipelined footprint and the runner degrades to serial (naming the
fallback in metrics) when the doubled footprint would exceed
`MASTIC_DEVICE_BUDGET_BYTES`.
"""

import gc
import os
import time
from contextlib import contextmanager
from typing import Callable, Optional, Sequence

import jax

from ..obs import trace as obs_trace


@contextmanager
def paused_gc():
    """Generational GC paused around a trace/compile window.

    A collection firing MID-TRACE segfaults this jax/jaxlib build —
    observed repeatedly via faulthandler ("Garbage-collecting" inside
    pjit tracing / abstract eval), single-threaded, with no
    persistent cache involved; the trigger is tracing while earlier
    runs' jit graphs sit collectable.  Deferring collection past the
    trace is semantically free: the next allocation after re-enabling
    collects outside the danger window.  Nested uses are fine (inner
    exit leaves GC disabled until the outer exit)."""
    if not gc.isenabled():
        yield
        return
    gc.disable()
    try:
        yield
    finally:
        gc.enable()


def pipeline_enabled() -> bool:
    """The `MASTIC_PIPELINE` lever, read per round (not at import) so
    a long-lived process can be steered without restarting.  Default
    on: the pipelined path is bit-identical to serial (locked by
    tests/test_pipeline.py) and strictly reduces wall clock."""
    return os.environ.get("MASTIC_PIPELINE", "1").lower() \
        not in ("0", "off", "false", "")


# -- double-buffered executor -----------------------------------------

def run_chunks(num_chunks: int, stage: Callable, collect: Callable,
               pipelined: bool,
               before_last_collect: Optional[Callable] = None) -> tuple:
    """Drive `stage`/`collect` over `num_chunks` chunks.

    `stage(i) -> (handle, phases)` uploads chunk i's inputs and
    dispatches its device work WITHOUT blocking on results (JAX async
    dispatch returns futures); `collect(i, handle) -> phases` issues
    the chunk's single blocking sync, downloads its results and folds
    them into host state.  `phases` are dicts of phase-name -> ms.

    Pipelined mode keeps two chunks in flight: chunk i+1 stages while
    chunk i's results are still being computed/collected.  Serial
    mode collects each chunk before staging the next (the shape of
    the pre-pipeline loop — the comparison baseline and the memory
    fallback).

    `before_last_collect` runs after every chunk's work is dispatched
    and before the final blocking collect — the point where the
    device is maximally busy and the host is about to idle.  The
    runners hang the ahead-of-time compile of the predicted next
    round's programs here, so XLA work overlaps in-flight device
    execution instead of sitting between a round's dispatch and its
    results.

    Returns (timeline, wall_ms): per-chunk records with absolute
    stage/collect timestamps (ms since round start) and the merged
    phase dict, plus the loop's total wall clock.  Timestamps let
    tests assert real overlap structurally: pipelined execution has
    timeline[i+1]["stage_start_ms"] < timeline[i]["collect_start_ms"].
    """
    timeline: list = [None] * num_chunks
    t0 = time.perf_counter()

    def now_ms() -> float:
        return (time.perf_counter() - t0) * 1e3

    tracer = obs_trace.get_tracer()

    def do_stage(i: int):
        start = now_ms()
        # The chunk spans nest under the caller's "round" span, so a
        # trace reconstructs round -> chunk (ISSUE 7).
        with tracer.span("chunk.stage", chunk=i):
            (handle, phases) = stage(i)
        timeline[i] = {
            "chunk": i,
            "stage_start_ms": round(start, 3),
            "stage_end_ms": round(now_ms(), 3),
            "phases": dict(phases),
            "host_syncs": 0,
        }
        return handle

    def do_collect(i: int, handle) -> None:
        if i == num_chunks - 1 and before_last_collect is not None:
            before_last_collect()
        rec = timeline[i]
        rec["collect_start_ms"] = round(now_ms(), 3)
        with tracer.span("chunk.collect", chunk=i):
            rec["phases"].update(collect(i, handle))
        rec["collect_end_ms"] = round(now_ms(), 3)
        # collect() blocks exactly once (jax.block_until_ready on the
        # chunk's full output tree); everything after is ready-data
        # copies.  Recorded so the one-sync contract is testable.
        rec["host_syncs"] = 1

    if pipelined and num_chunks > 1:
        in_flight = do_stage(0)
        for i in range(num_chunks):
            staged_next = (do_stage(i + 1) if i + 1 < num_chunks
                           else None)
            do_collect(i, in_flight)
            in_flight = staged_next
    else:
        for i in range(num_chunks):
            do_collect(i, do_stage(i))
    return (timeline, now_ms())


def overlap_efficiency(timeline: Sequence[dict],
                       wall_ms: float) -> float:
    """Fraction of the chunks' total phase time hidden by overlap:
    1 - wall / sum(phases).  0.0 when nothing overlapped (serial, or
    a single chunk); approaches the ideal (n-1)/n stacking as upload
    and download fully hide under compute."""
    busy = sum(sum(rec["phases"].values()) for rec in timeline)
    if wall_ms <= 0.0 or busy <= wall_ms:
        return 0.0
    return round(1.0 - wall_ms / busy, 4)


# -- shape-keyed compiled-program cache + background warming ----------

def to_struct(x) -> jax.ShapeDtypeStruct:
    """Array -> abstract shape/dtype (the lowering signature).

    Mesh-placed arrays keep their NamedSharding: a warm compile for a
    mesh-sharded round must lower with the same input shardings the
    real call will pass, or the cached executable would be rejected
    (or silently recompiled) at dispatch.  Single-device arrays stay
    sharding-free — pinning their SingleDeviceSharding would
    needlessly specialize the program to one device ordinal."""
    from jax.sharding import NamedSharding

    sharding = getattr(x, "sharding", None)
    if isinstance(sharding, NamedSharding):
        return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                    sharding=sharding)
    return jax.ShapeDtypeStruct(x.shape, x.dtype)


class ProgramCache:
    """Compiled round programs keyed by the shapes they actually
    close over (chunk rows, padded width, the pow2 binder/out
    buckets) plus the runtime tag (jax version + backend) and program
    family (instantiation + ctx digest) — NOT cleared on width
    growth: a grown runner simply compiles (or has pre-warmed) the
    new width's keys while the old entries become unreachable.  A key
    stamped for a different runtime is REFUSED (`artifacts.
    check_key_runtime`): an in-process cache can never serve a
    program compiled under a different jax build or backend.

    `get` is the inline path: returns the compiled program plus the
    seconds the caller had to WAIT for it — zero exactly when a warm
    already landed it, the full compile when cold (the timeline's
    compile field, so the zero-inline-compile claim is measured, not
    asserted).  `warm` compiles SYNCHRONOUSLY on the caller's thread:
    the runners invoke it only at points where every in-flight
    chunk's device work is already dispatched and the host is about
    to idle in a blocking sync (run_chunks' `before_last_collect`
    hook), so the XLA work overlaps device execution.  A separate
    compiler thread is deliberately NOT used: jax tracing is not
    thread-safe on this fabric (0.4.x) — a background thread lowering
    while the main thread traced produced both hard crashes
    (segfault/std::terminate) and, worse, silently WRONG jaxprs
    (observed: a round program that rejected every report).  The
    synchronous form keeps the same measured win — dispatch is async,
    so the device computes through the compile — with none of the
    failure modes, and it composes with the persistent
    `jax_compilation_cache_dir` across processes.

    `store` plugs in the AOT artifact tier (`drivers/artifacts.py`,
    ROADMAP item 4): below the in-process dict, a cache miss consults
    the digest-sealed, probe-verified on-disk store before paying
    XLA — `get` loads inline (the wait is the disk+probe latency,
    ~1.5 s vs ~21 s compile on this fabric), `warm` prefetches from
    disk in the same overlapped slot it would have compiled in, and
    `preload` walks the store up front so first rounds hit the
    in-process tier directly.  Artifact loads are never counted as
    inline compiles — the `artifact_hits` / `artifact_load_ms` stats
    attribute them separately.
    """

    def __init__(self, store=None):
        self._programs: dict = {}
        self.store = store
        self.stats = {"inline_compiles": 0, "warm_compiles": 0,
                      "warm_errors": 0, "artifact_hits": 0,
                      "artifact_load_ms": 0.0}

    def _check_runtime(self, key) -> None:
        from .artifacts import check_key_runtime

        check_key_runtime(key)

    def _from_store(self, key):
        """Artifact-tier lookup: gated load (digest / runtime / probe
        — see artifacts.ArtifactStore.load), memoized into the
        in-process tier on success."""
        if self.store is None:
            return None
        t0 = time.perf_counter()
        prog = self.store.load(key)
        if prog is None:
            return None
        self._programs[key] = prog
        self.stats["artifact_hits"] += 1
        self.stats["artifact_load_ms"] += \
            (time.perf_counter() - t0) * 1e3
        return prog

    def get(self, key, build: Callable) -> tuple:
        """(compiled, wait_seconds); `build()` returns a Lowered."""
        self._check_runtime(key)
        prog = self._programs.get(key)
        if prog is not None:
            return (prog, 0.0)
        t0 = time.perf_counter()
        prog = self._from_store(key)
        if prog is not None:
            return (prog, time.perf_counter() - t0)
        with paused_gc():
            compiled = build().compile()
        self._programs[key] = compiled
        self.stats["inline_compiles"] += 1
        return (compiled, time.perf_counter() - t0)

    def warm(self, key, build: Callable) -> float:
        """Land `key` now if absent — from the artifact store when it
        has the key (the predictor prefetches from disk before
        compiling), else by compiling; returns the seconds spent.
        Errors are counted, never raised: a mispredicted or
        unbuildable warm must not take down the round that scheduled
        it — the real round compiles inline instead."""
        self._check_runtime(key)
        if key in self._programs:
            return 0.0
        t0 = time.perf_counter()
        if self._from_store(key) is not None:
            return time.perf_counter() - t0
        try:
            with paused_gc():
                self._programs[key] = build().compile()
            self.stats["warm_compiles"] += 1
        except Exception:
            self.stats["warm_errors"] += 1
        return time.perf_counter() - t0

    def preload(self, match: Callable) -> int:
        """Pull every store entry whose key passes `match` into the
        in-process tier (runner construction calls this with its
        shape family, so the first round's `get` is a pure dict
        hit and the timeline's compile field stays zero)."""
        if self.store is None:
            return 0
        n = 0
        for key in self.store.keys():
            if key in self._programs or not match(key):
                continue
            if self._from_store(key) is not None:
                n += 1
        return n

    def entries(self) -> dict:
        """The compiled programs by key (bake-from-run export)."""
        return dict(self._programs)

    def contains(self, key) -> bool:
        return key in self._programs


# -- frontier-trajectory bucket prediction ----------------------------

def plan_shape_key(plan) -> tuple:
    """The shapes a RoundPlan's traced inputs specialize the compiled
    round program on: padded width plus the pow2 onehot / payload /
    out buckets.  (`level` et al. are traced scalars — free.)"""
    return (plan.width, len(plan.onehot_idx),
            len(plan.payload_parent), len(plan.out_idx))


def _candidate_survivor_sets(prefixes: Sequence) -> list:
    """The two frontier trajectories worth warming for, derived from
    the current prefix set:

    * steady state — the threshold keeps ~one child per parent, the
      heavy-hitters fixed point (frontier width constant; which child
      survives does not matter for SHAPES: per-depth ancestor counts,
      and therefore every bucket, are identity-independent);
    * growth — every prefix survives (the early levels of a run, and
      any level where the threshold prunes nothing).

    Anything else (mass extinction, partial prune straddling a pow2
    boundary) mispredicts and pays its compile inline — correctness
    is untouched, only the stall location moves."""
    groups: dict = {}
    for p in prefixes:
        groups.setdefault(p[:-1], []).append(p)
    steady = tuple(children[0] for children in groups.values())
    return [tuple(prefixes), steady]


def predicted_next_plans(prefixes: Sequence, level: int, bits: int,
                         width: int, layouts_next: list) -> list:
    """Predicted RoundPlans for level+1, deduplicated by shape key.
    `layouts_next` must already include the current round's new
    layout (the depth the in-flight round is creating).  Candidates
    that would force a width growth are skipped — the grow round
    recompiles inline by design (at most log2(max_width) times per
    run)."""
    from ..backend.incremental import RoundPlan

    if level + 1 >= bits:
        return []
    plans = []
    seen = set()
    for survivors in _candidate_survivor_sets(list(prefixes)):
        nxt = tuple(p + (b,) for p in survivors
                    for b in (False, True))
        try:
            plan = RoundPlan(nxt, level + 1, bits, width, layouts_next)
        # a candidate that does not fit the padded width is not an
        # error — the grow round compiles inline by design, and the
        # miss is observable as aot.predicted=False in the metrics
        except ValueError:  # mastic-allow: RB002 — infeasible
            # prediction candidate skipped; recorded via aot stats
            continue
        key = plan_shape_key(plan)
        if key not in seen:
            seen.add(key)
            plans.append(plan)
    return plans
