"""Known-bad (ISSUE 11, network-front flavor): an HTTP error body
that echoes key-derived detail back to the client (SF004) — the
exact leak the upload front's fixed-string error bodies exist to
rule out."""
import json


def error_body(key):
    return json.dumps({"error": "rejected", "detail": key.hex()})


def respond(wfile, key):
    wfile.write(error_body(key))
