"""Device-time attribution (ISSUE 7 tentpole, part 3).

The pipelined executor already measures a per-chunk phase timeline
(upload / compile / dispatch / compute-wait / download / host,
`drivers/pipeline.run_chunks`), but the numbers were buried in
`extra["chunks"]` and vanished unless a caller printed them.  This
module turns them into registry observations:

* `observe_round(metrics, tenant=...)` — called by the drivers'
  step() after each round: every chunk phase lands in the
  `mastic_chunk_phase_ms{phase=...}` histogram, the round wall in
  `mastic_round_wall_ms{tenant=...}`, and the compile-vs-execute
  split in `mastic_device_time_ms_total{kind=compile|execute}` —
  the datum that drives the AOT work (PAPERS.md "Automatic Full
  Compilation ... to Cloud TPUs": knowing how much of a round is
  compile is what justifies compiling ahead);

* `MASTIC_JAX_PROFILE=dir` — an opt-in, one-shot lever: the FIRST
  round stepped after import runs under `jax.profiler.trace(dir)`
  (open with TensorBoard/xprof).  One round, not the whole run:
  profiler overhead and trace size make an always-on capture useless,
  and one steady-state round is exactly the datum ROADMAP item 3's
  chip measurement needs.  `take_profile_dir()` consumes the lever;
  HeavyHittersRun.step / AttributeMetricsRun.step call it when no
  explicit profile_dir was set.
"""

import os
import threading
from typing import Optional

from .registry import get_registry

# Phases whose wall time is attributed to XLA compile rather than
# device execution (ProgramCache.get wait + warm time).
_COMPILE_PHASES = ("compile_ms",)
_EXECUTE_PHASES = ("dispatch_ms", "compute_wait_ms")

_profile_lock = threading.Lock()
_profile_consumed = False


def take_profile_dir() -> Optional[str]:
    """The MASTIC_JAX_PROFILE directory, once: the first caller gets
    it (and brackets its round in jax.profiler.trace), every later
    call gets None.  Re-arm by restarting the process — the lever is
    deliberately one-shot per process."""
    global _profile_consumed
    path = os.environ.get("MASTIC_JAX_PROFILE")
    if not path:
        return None
    with _profile_lock:
        if _profile_consumed:
            return None
        _profile_consumed = True
    return path


def reset_profile_lever() -> None:
    """Tests only: re-arm the one-shot."""
    global _profile_consumed
    with _profile_lock:
        _profile_consumed = False


def observe_round(metrics, tenant: str = "") -> None:
    """Feed one RoundMetrics record into the registry: chunk-phase
    histograms, round wall, compile-vs-execute attribution, and the
    per-check accept/reject counters.  Cheap (a few dict walks), and
    tolerant of records stamped by any producer — missing blocks
    simply contribute nothing."""
    reg = get_registry()
    extra = metrics.extra
    wall = extra.get("round_wall_ms")
    if wall is None:
        pipeline = extra.get("pipeline") or {}
        wall = pipeline.get("round_wall_ms")
    if wall is not None:
        reg.histogram("mastic_round_wall_ms",
                      tenant=tenant).observe(float(wall))

    compile_ms = 0.0
    execute_ms = 0.0
    for rec in extra.get("chunks") or ():
        for (phase, ms) in rec.get("phases", {}).items():
            reg.histogram("mastic_chunk_phase_ms",
                          phase=phase[:-3] if phase.endswith("_ms")
                          else phase).observe(float(ms))
            if phase in _COMPILE_PHASES:
                compile_ms += float(ms)
            elif phase in _EXECUTE_PHASES:
                execute_ms += float(ms)
    pipeline = extra.get("pipeline") or {}
    phases = pipeline.get("phases")
    if phases:
        # The resident runner has one phase record per round instead
        # of per chunk; it feeds the same histograms.
        for (phase, ms) in phases.items():
            reg.histogram("mastic_chunk_phase_ms",
                          phase=phase[:-3] if phase.endswith("_ms")
                          else phase).observe(float(ms))
            if phase in _COMPILE_PHASES:
                compile_ms += float(ms)
            elif phase in _EXECUTE_PHASES:
                execute_ms += float(ms)
    if compile_ms:
        reg.counter("mastic_device_time_ms_total",
                    kind="compile").inc(compile_ms)
    if execute_ms:
        reg.counter("mastic_device_time_ms_total",
                    kind="execute").inc(execute_ms)

    reg.counter("mastic_rounds_total", tenant=tenant).inc()
    reg.counter("mastic_reports_accepted_total",
                tenant=tenant).inc(metrics.accepted)
    for (check, n) in (
            ("eval_proof", metrics.rejected_eval_proof),
            ("weight_check", metrics.rejected_weight_check),
            ("joint_rand", metrics.rejected_joint_rand),
            ("fallback", metrics.rejected_fallback)):
        if n:
            reg.counter("mastic_reports_rejected_total",
                        tenant=tenant, check=check).inc(n)
