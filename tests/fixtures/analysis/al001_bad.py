"""Known-bad: suppression without a written justification (AL001)."""


def leaky(seed: bytes) -> bytes:
    # mastic-allow: SF001
    if seed[0] & 1:
        return seed[1:]
    return seed
