"""Known-bad: branch on secret-derived data (SF001)."""


def leaky(seed: bytes) -> bytes:
    if seed[0] & 1:
        return seed[1:]
    return seed
