"""Batched JAX/XLA kernels for the Mastic hot path.

Every kernel here is a pure, shape-static function over arrays with an
arbitrary leading batch shape, differential-tested bit-for-bit against
the scalar CPU reference modules in mastic_tpu/ (keccak, aes, field).
Secret-dependent control flow never appears: all selects are lane-wise
`jnp.where`, which is the TPU-native reading of the reference's
constant-time implementation notes (/root/reference/poc/vidpf.py:116-119,
:301-312).
"""
