"""Deterministic fault injection for aggregation sessions
(`MASTIC_FAULTS` lever; USAGE.md "Fault model & injection").

The session layer's claims — bounded-time failure, party-attributed
errors, no fault ever yielding a silently wrong aggregate — are only
as good as the faults they were tested against.  This harness injects
the faults real transports produce, deterministically, at two seams:

* **outbound frames** (`FaultInjector.on_send`, called by
  `session.Channel.send_msg` on the fully framed bytes): drop,
  delay, truncate, corrupt, duplicate, hang — transport-level
  mutations, so e.g. `truncate` leaves the receiver waiting on a
  frame whose header promises more bytes than ever arrive;
* **protocol checkpoints** (`FaultInjector.checkpoint`, called by the
  party main loop and the collector between steps): kill (hard
  process exit), hang, delay — crash-at-step faults.

A fault spec is one or more `;`-separated rules:

    <action>:party=<leader|helper|collector>:step=<step>[:nth=N]
            [:delay=SECONDS][:cut=BYTES][:xor=BYTE][:offset=BYTES]

e.g. ``kill:party=helper:step=round_start`` or
``corrupt:party=leader:step=prep_share:offset=4:xor=1``.  `nth` is the
1-based occurrence of the (party, step) event the rule fires on
(default 1); each rule fires exactly once, so injection is
deterministic and replayable.  Step names are the wire labels of
drivers/parties.py (hello, leader_port, upload, upload_report,
upload_ack, agg_param, prep_share, resolution, agg_share, shutdown)
plus the process checkpoints (spawn, reports_loaded, round_start,
prep_done, resolve_done, confirm_done) and the collector service's
ingest/scheduler checkpoints (drivers/service.py, party=collector:
admit, page_flush, epoch_start, epoch_round, snapshot — page_flush
additionally honors truncate/corrupt as a content mutation of the
sealed page's stored bytes, modeling storage corruption the page
digest must catch).

ISSUE 11 extends the matrix to the network edge: the HTTP upload
front (mastic_tpu/net/ingest.py, party=collector) fires checkpoint
``http_accept`` per request (kill/hang/delay) and the `on_blob`
content seam ``http_body`` over each received upload body
(truncate/corrupt model an upload mangled in flight — which must be
rejected with an attributed reason, never admitted), and the shaped
party links (net/transport.py, party = the sending process) fire
checkpoint ``net_send`` per outbound frame, so the whole action
matrix reaches the wide-area transport too.

ISSUE 14 reaches the reliable TCP/mTLS transport: the new actions
``conn_drop`` (drop the connection now), ``partition`` (drop it and
refuse redial for ``delay`` seconds, both directions) and
``slow_loris`` (stall the writer mid-frame for ``delay`` seconds)
fire at the per-frame ``on_net`` seam inside
`net.transport.TcpTransport` — after the frame enters the replay
buffer, so recovery exercises reconnect-and-replay, never silent
loss — and the ``tls_handshake`` checkpoint fires in the dial/accept
paths (kill/hang/delay a handshake).  `tools/serve.py --chaos-drill`
composes a seeded random schedule out of exactly this vocabulary.

ISSUE 18 reaches durable storage: the admission WAL
(drivers/wal.py, party=collector) fires the ``on_disk`` content seam
at ``wal_append`` (per record write) and ``wal_fsync`` (per fsync),
where the disk actions live — ``short_write`` (the record lands
`cut` bytes short and the process dies before fsync: a torn tail
recovery must truncate-and-count), ``enospc`` and ``fsync_error``
(raise, flipping ingest to the reason-coded `wal-full`/`wal-degraded`
brownout), plus ``corrupt`` as a post-checksum bit-flip — and the
plain ``wal_ack`` checkpoint fires after fsync and before the ack
(kill there leaves a durable-but-unacked record the client will
retry, which recovery's digest dedup must ack idempotently).

Each process parses `MASTIC_FAULTS` itself and keeps only the rules
addressed to its own party name, so one env var arms the whole
session (the collector passes it through to the party processes).
"""

import os
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..obs import trace as obs_trace
from ..obs.registry import get_registry

ACTIONS = ("drop", "delay", "truncate", "corrupt", "duplicate",
           "hang", "kill",
           # ISSUE 14 network-fault actions (the reliable-transport
           # seam, FaultInjector.on_net): a dropped connection, a
           # partition lasting `delay` seconds both directions, and
           # a writer that stalls mid-frame for `delay` seconds.
           "conn_drop", "partition", "slow_loris",
           # ISSUE 18 disk-fault actions (the WAL seam,
           # FaultInjector.on_disk): a write that lands `cut` bytes
           # short and dies before fsync (torn tail), a full disk,
           # and an fsync that errors.
           "short_write", "enospc", "fsync_error")
PARTIES = ("leader", "helper", "collector")

# The actions only the reliable-transport seam implements (a plain
# channel cannot recover from them; the TcpTransport reconnects).
NET_ACTIONS = ("conn_drop", "partition", "slow_loris")

# The actions only the durable-storage seam implements
# (FaultInjector.on_disk — the WAL append/fsync path).
DISK_ACTIONS = ("short_write", "enospc", "fsync_error")

# `hang` sleeps this long — far past any configured deadline, short
# enough that an orphaned hung process eventually dies on its own.
HANG_SECONDS = 3600.0

# Exit code a killed party dies with (distinct from 1 = structured
# session error, so the collector can tell "injected kill" from
# "party hit an error" in test assertions).
KILL_EXIT_CODE = 17


@dataclass
class FaultRule:
    action: str
    party: str
    step: str
    nth: int = 1
    delay: float = 5.0     # seconds, for delay
    cut: int = 1           # trailing bytes removed, for truncate
    xor: int = 0x01        # byte mask, for corrupt
    offset: int = 4        # frame offset for corrupt (4 = first
    #                        payload byte; 0..3 hits the length header)
    fired: bool = field(default=False, repr=False)


def parse_faults(text: Optional[str]) -> list:
    """Parse a `;`-separated MASTIC_FAULTS spec into FaultRules.
    Unknown actions/parties/keys are errors: a typo'd fault spec that
    silently injects nothing would make the whole matrix vacuous."""
    rules = []
    for chunk in (text or "").split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        parts = chunk.split(":")
        action = parts[0].strip()
        if action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r} (must be one of "
                f"{', '.join(ACTIONS)})")
        kwargs: dict = {}
        for kv in parts[1:]:
            if "=" not in kv:
                raise ValueError(f"malformed fault field {kv!r} "
                                 f"(want key=value)")
            (key, val) = kv.split("=", 1)
            key = key.strip()
            val = val.strip()
            if key == "party":
                if val not in PARTIES:
                    raise ValueError(
                        f"unknown fault party {val!r} (must be one "
                        f"of {', '.join(PARTIES)})")
                kwargs["party"] = val
            elif key == "step":
                kwargs["step"] = val
            elif key in ("nth", "cut", "offset"):
                kwargs[key] = int(val)
            elif key == "delay":
                kwargs[key] = float(val)
            elif key == "xor":
                kwargs[key] = int(val, 0) & 0xFF
            else:
                raise ValueError(f"unknown fault field {key!r}")
        if "party" not in kwargs or "step" not in kwargs:
            raise ValueError(
                f"fault rule {chunk!r} needs party= and step=")
        rules.append(FaultRule(action=action, **kwargs))
    return rules


class FaultInjector:
    """Applies the rules addressed to one party.  Counting is per
    (rule), matched against this party's (step) events in order, so a
    spec replays identically run to run.

    The occurrence counters are lock-guarded (ISSUE 10): the
    collector's ingest front fires the ``admit`` / ``page_flush``
    checkpoints from its worker threads while the scheduler thread
    fires the epoch checkpoints, and an unlocked read-modify-write of
    the per-step count would let two concurrent events claim the same
    nth (a rule firing twice, or never)."""

    def __init__(self, rules: list, party: str):
        self.party = party
        self.rules = [r for r in rules if r.party == party]
        self._event_counts: dict = {}
        self._mu = threading.Lock()

    def _match(self, step: str) -> Optional[FaultRule]:
        """One event of (party, step) happened; the rule whose nth it
        is fires.  Events are counted per step regardless of whether
        any rule fires, so several rules can target different
        occurrences of the same step.  A firing rule lands in the
        trace and the registry BEFORE its action runs, so even a
        `kill` is visible in the JSONL trace (ISSUE 7: an injected
        fault must be findable in the telemetry, not inferred)."""
        with self._mu:
            n = self._event_counts.get(step, 0) + 1
            self._event_counts[step] = n
            fired = None
            for rule in self.rules:
                if rule.step == step and not rule.fired \
                        and rule.nth == n:
                    rule.fired = True
                    fired = rule
                    break
        if fired is not None:
            obs_trace.event("fault_injected", action=fired.action,
                            party=fired.party, step=step, nth=n)
            get_registry().counter(
                "mastic_faults_injected_total",
                action=fired.action, step=step).inc()
        return fired

    # -- outbound frames (Channel.send_msg) ------------------------

    def on_send(self, step: str, frame: bytes) -> list:
        """Transform one outbound frame (header + payload) into the
        list of byte strings actually written."""
        rule = self._match(step)
        if rule is None:
            return [frame]
        if rule.action == "drop":
            return []
        if rule.action == "duplicate":
            return [frame, frame]
        if rule.action == "truncate":
            return [frame[:max(0, len(frame) - rule.cut)]]
        if rule.action == "corrupt":
            off = min(rule.offset, len(frame) - 1)
            mutated = bytearray(frame)
            mutated[off] ^= (rule.xor or 0x01)
            return [bytes(mutated)]
        if rule.action == "delay":
            time.sleep(rule.delay)
            return [frame]
        if rule.action == "hang":
            time.sleep(HANG_SECONDS)
            return [frame]
        if rule.action == "kill":
            os._exit(KILL_EXIT_CODE)
        raise AssertionError(f"unhandled fault action {rule.action}")

    # -- protocol checkpoints --------------------------------------

    def checkpoint(self, step: str) -> None:
        """Crash-at-step seam: kill/hang/delay fire here; the frame
        mutations are meaningless between messages and ignored."""
        rule = self._match(step)
        if rule is None:
            return
        if rule.action == "kill":
            os._exit(KILL_EXIT_CODE)
        elif rule.action == "hang":
            time.sleep(HANG_SECONDS)
        elif rule.action == "delay":
            time.sleep(rule.delay)

    def on_net(self, step: str) -> Optional[FaultRule]:
        """The reliable-transport seam (ISSUE 14): fired by
        `net.transport.TcpTransport` once per outbound session frame,
        AFTER the frame enters the replay buffer and BEFORE the
        write — so a fired `conn_drop` forces the frame through the
        reconnect-and-replay path, which is the point.  kill/hang/
        delay behave as at any checkpoint; the NET_ACTIONS return the
        rule for the transport to enact (it owns the socket); the
        frame-mutation actions are meaningless below the seq/ack
        framing and ignored here."""
        rule = self._match(step)
        if rule is None:
            return None
        if rule.action == "kill":
            os._exit(KILL_EXIT_CODE)
        if rule.action == "hang":
            time.sleep(HANG_SECONDS)
            return None
        if rule.action == "delay":
            time.sleep(rule.delay)
            return None
        if rule.action in NET_ACTIONS:
            return rule
        return None

    def on_blob(self, step: str, blob: bytes) -> bytes:
        """Combined checkpoint + content seam for a blob-producing
        step (the service's `page_flush`): ONE (party, step) event,
        so a rule's `nth` counts seals, not internal hook calls.
        kill/hang/delay fire as process faults; truncate/corrupt
        mutate the produced bytes (modeling storage corruption —
        applied AFTER the caller's digest, which must catch it)."""
        rule = self._match(step)
        if rule is None:
            return blob
        if rule.action == "kill":
            os._exit(KILL_EXIT_CODE)
        if rule.action == "hang":
            time.sleep(HANG_SECONDS)
            return blob
        if rule.action == "delay":
            time.sleep(rule.delay)
            return blob
        if rule.action == "truncate":
            return blob[:max(0, len(blob) - rule.cut)]
        if rule.action == "corrupt":
            off = min(rule.offset, len(blob) - 1)
            mutated = bytearray(blob)
            mutated[off] ^= (rule.xor or 0x01)
            return bytes(mutated)
        raise ValueError(
            f"fault action {rule.action!r} does not apply to "
            f"step {step!r}")

    def on_disk(self, step: str, data: bytes) -> tuple:
        """Durable-storage seam (ISSUE 18): fired by the admission
        WAL once per write (`wal_append`, `data` = the encoded
        record) and once per fsync (`wal_fsync`, `data` empty).
        Returns ``(bytes_to_write, after)`` where `after` is
        ``"kill"`` when the process must die immediately after the
        (possibly shortened) bytes reach the OS — the short-write/
        torn-tail fault, which recovery must truncate-and-count.
        ``enospc``/``fsync_error`` raise the matching OSError so the
        WAL's reason-coded brownout path runs; ``corrupt`` flips a
        byte AFTER the record's CRC was computed (recovery must
        detect, attribute, and skip — never admit garbage);
        kill/hang/delay behave as at any checkpoint."""
        import errno

        rule = self._match(step)
        if rule is None:
            return (data, None)
        if rule.action == "kill":
            os._exit(KILL_EXIT_CODE)
        if rule.action == "hang":
            time.sleep(HANG_SECONDS)
            return (data, None)
        if rule.action == "delay":
            time.sleep(rule.delay)
            return (data, None)
        if rule.action == "short_write":
            return (data[:max(0, len(data) - rule.cut)], "kill")
        if rule.action == "enospc":
            raise OSError(errno.ENOSPC, "injected ENOSPC")
        if rule.action == "fsync_error":
            raise OSError(errno.EIO, "injected fsync failure")
        if rule.action == "corrupt":
            off = min(rule.offset, len(data) - 1)
            mutated = bytearray(data)
            mutated[off] ^= (rule.xor or 0x01)
            return (bytes(mutated), None)
        raise ValueError(
            f"fault action {rule.action!r} does not apply to "
            f"step {step!r}")

    def split_report_blob(self, step: str, blob: bytes) -> bytes:
        """Content-level mutation of ONE report blob inside the upload
        body (quarantine-path testing): truncate/corrupt apply to the
        bare blob, not a frame — so `offset` counts from byte 0."""
        rule = self._match(step)
        if rule is None:
            return blob
        if rule.action == "truncate":
            return blob[:max(0, len(blob) - rule.cut)]
        if rule.action == "corrupt":
            off = min(rule.offset, len(blob) - 1)
            mutated = bytearray(blob)
            mutated[off] ^= (rule.xor or 0x01)
            return bytes(mutated)
        raise ValueError(
            f"fault action {rule.action!r} does not apply to "
            f"step {step!r} (use truncate or corrupt)")


def injector_from_env(party: str) -> Optional[FaultInjector]:
    """The process's injector, or None when MASTIC_FAULTS is unset /
    names no rule for this party (the common, zero-overhead case)."""
    spec = os.environ.get("MASTIC_FAULTS")
    if not spec:
        return None
    inj = FaultInjector(parse_faults(spec), party)
    return inj if inj.rules else None


def frame_of(payload: bytes) -> bytes:
    """The framed form of a payload (for tests asserting what a fault
    does to the wire bytes)."""
    return struct.pack("<I", len(payload)) + payload
